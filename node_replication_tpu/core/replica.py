"""Replica runtime: flat-combining batching + lock-step replay.

The TPU re-design of `nr/src/replica.rs`. What changes and why
(SURVEY.md §7):

- The reference elects a combiner thread with a CAS lock
  (`nr/src/replica.rs:508-540`) because threads race; replay here is a
  lock-step device computation, so combiner *election* is meaningless. What
  survives is the *batching* contract: per-thread `Context` rings are
  drained whole, in thread order, into one append batch per replica
  (`Replica::combine`, `nr/src/replica.rs:543-595`).
- `data: CachePadded<RwLock<D>>` (`nr/src/replica.rs:108-114`) becomes a
  vmapped pytree with a leading replica axis — functional state needs no
  reader/writer lock (SURVEY.md §7 "RwLock → unnecessary on-device"). A
  native C++ distributed RwLock still backs the CPU engine
  (`node_replication_tpu/native/`).
- `execute_mut` = stage → combine → collect response
  (`nr/src/replica.rs:345-356`); `execute` (read) waits until this replica's
  ltail passes the completed tail, helping replay while it waits, then
  dispatches locally (`nr/src/replica.rs:404-410`, `483-497`).
- "Append must help GC when the log is full" (`nr/src/log.rs:364-387`)
  becomes: run replay windows until `log_space` fits the batch.
- The reference's spin-diagnostic `WARN_THRESHOLD` warnings
  (`nr/src/log.rs:43`) become a host-side watchdog: after `WARN_ROUNDS`
  replay rounds without progress, a structured warning fires and the
  CNR-style GC starvation callback (`cnr/src/log.rs:135-142`) is invoked
  with the most dormant replica.

`NodeReplicated` is the stateful convenience wrapper (per-op API parity with
the reference examples, `nr/examples/hashmap.rs:55-105`); the jit-hot batch
path is `core/step.py`.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import statistics
import threading

from node_replication_tpu.analysis.locks import make_rlock
import time
from collections import deque
from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from node_replication_tpu.core.log import (
    LogSpec,
    WARN_ROUNDS,
    gather_window,
    log_append,
    log_catchup_all,
    log_exec_all,
    log_init,
    log_space,
    ring_slice,
)
from node_replication_tpu.fault.inject import fault_hook
from node_replication_tpu.obs.metrics import COUNT_BUCKETS, get_registry
from node_replication_tpu.ops.context import MAX_PENDING_OPS, Context
from node_replication_tpu.ops.encoding import (
    Dispatch,
    apply_read,
    encode_ops,
)
from node_replication_tpu.utils.trace import get_tracer, span

logger = logging.getLogger("node_replication_tpu")

# Max logical threads per replica (`nr/src/replica.rs:56`).
MAX_THREADS_PER_REPLICA = 256

# Default static replay window per device round (jit-compiled once).
DEFAULT_EXEC_WINDOW = 256

# Fused-tier winner selection (`engine='auto'` + a fused-capable
# dispatch): per tier, the first WARMUP eligible rounds absorb compile
# cost off the books, the next SAMPLES rounds are timed (fenced by the
# round's own host readback), and the tier with the lower median
# commits. Both calibration tiers run REAL rounds — results are
# bit-identical either way, only their speed differs.
FUSED_CAL_WARMUP = 1
FUSED_CAL_SAMPLES = 2

# Reserved context key for `execute_mut_batch` response sinks: real
# thread ids are allocated from 0 upward by `register`, so -1 can never
# collide, and `combine`'s thread-order drain (`range(threads)`) never
# visits it.
BATCH_TID = -1


class _BatchSink:
    """Response sink for caller-assembled batches (`execute_mut_batch`).

    Duck-types the response half of `ops.context.Context`
    (`enqueue_resps`) so `_exec_round`'s delivery loop needs no special
    case, but skips the 32-slot pending ring entirely — a serve batch
    is already assembled and can be any size up to the log's appendable
    capacity. Guarded by the wrapper's combiner lock like every other
    context structure.
    """

    __slots__ = ("_resps", "_inflight")

    def __init__(self) -> None:
        self._resps: list = []
        self._inflight = 0

    def expect(self, n: int) -> None:
        self._inflight += n

    def enqueue_resps(self, resps) -> None:
        self._inflight -= len(resps)
        self._resps.extend(resps)

    def take(self) -> list:
        out = self._resps
        self._resps = []
        return out

    def reset(self) -> None:
        """Discard delivered responses and the expectation count (the
        failed-batch cleanup path: stale replies must never prefix the
        next batch's)."""
        self._resps = []
        self._inflight = 0


class _PendingRound:
    """One combiner round between `begin` and `finish` — the split
    round protocol behind `begin_mut_batch`/`finish_mut_batch` (and
    the serve pipeline's assembly/completion overlap,
    `serve/frontend.py`).

    After `begin` the batch is APPENDED: the ops are in the in-memory
    log (and the WAL, when one is attached), so a failure from here on
    is post-append (`maybe_executed` semantics). What `begin` defers
    is only this replica's replay-to-target (chain tier) or the
    response readback (fused tier — the kernel is already launched and
    running on the device); `finish` completes it. `done` marks a
    round that `begin` ran eagerly end-to-end (serial callers,
    calibration rounds, empty batches) so `finish` only collects.
    `log_idx` is the CNR per-log variant's mapped log (None for NR).
    """

    __slots__ = ("rid", "tids", "n", "pos0", "target", "batch",
                 "log_idx", "fused_resps", "done", "t_chain", "pad",
                 "fkey", "tier")

    def __init__(self, rid: int, tids: list[int], n: int, pos0: int,
                 batch: bool = False, log_idx: int | None = None):
        self.rid = rid
        self.tids = tids
        self.n = n
        self.pos0 = pos0
        self.target = pos0 + n
        self.batch = batch
        self.log_idx = log_idx
        #: device array of the fused launch awaiting readback
        self.fused_resps = None
        self.done = False
        self.t_chain: float | None = None
        self.pad = 0
        #: calibration fence-mask key at begin (chain samples note it)
        self.fkey: tuple = ()
        #: engine tier of a deferred fused launch (readback delivery)
        self.tier: str | None = None


class ReplicaToken(NamedTuple):
    """Registration handle (`ReplicaToken`, `nr/src/replica.rs:27-30`).

    The reference makes it `!Send` to pin it to a thread; here it is just an
    index pair the caller must not share across logical threads.
    """

    rid: int
    tid: int


class LogTooSmallError(RuntimeError):
    """A single batch exceeds the log's appendable capacity."""


class ReplicaFencedError(RuntimeError):
    """The operation targets a fenced (quarantined) replica.

    A fenced replica's replay is frozen and its cursor is excluded from
    GC (`fault/health.py`), so waiting on its progress would hang
    forever; appends, reads, and single-replica syncs against it fail
    fast instead. Repair (`fault/repair.py`) unfences and readmits.
    """

    def __init__(self, rid: int):
        super().__init__(
            f"replica {rid} is fenced (quarantined); repair and "
            f"unfence it before routing operations to it"
        )
        self.rid = rid


# Locked methods emit trace events and update instruments; the tracer
# and instrument handles come from module-level get_* accessors the
# analyzer cannot type through, so the nesting is declared:
# nrcheck: lock-order NodeReplicated._lock -> Tracer._lock — locked methods emit trace events
# nrcheck: lock-order MultiLogReplicated._lock -> Tracer._lock — CNR locked methods emit trace events
# nrcheck: lock-order NodeReplicated._lock -> Counter._lock — locked methods bump counters
# nrcheck: lock-order MultiLogReplicated._lock -> Counter._lock — CNR locked methods bump counters
# nrcheck: lock-order NodeReplicated._lock -> Histogram._lock — locked methods observe durations
# nrcheck: lock-order MultiLogReplicated._lock -> Histogram._lock — CNR locked methods observe durations
# nrcheck: lock-order NodeReplicated._lock -> WriteAheadLog._lock — the combiner round journals the batch into the attached WAL
# nrcheck: lock-order MultiLogReplicated._lock -> WriteAheadLog._lock — same journaling through the CNR wrapper
def _locked(fn):
    """Run a method under the instance's combiner lock (`self._lock`).

    The reference elects a combiner with a CAS lock
    (`nr/src/replica.rs:508-540`); threads that lose the race spin or
    enqueue. Here the wrappers' shared mutable host state (`log`,
    `states`, contexts, in-flight queues, counters) is guarded by one
    reentrant combiner lock: each public entry point is one critical
    section, so concurrent logical threads can call `execute_mut` /
    `execute` / `combine` from real OS threads and observe consistent
    cursors. Reentrant because combine -> _exec_round -> gc_callback ->
    sync_log chains re-enter on the same thread. The nrlint
    `lock-discipline` rule understands this decorator as a whole-method
    `with self._lock` region.

    Lock-wait accounting (host-budget input, ROADMAP item 2): when
    metrics are on, a contended acquisition is timed into
    `nr.lock.wait_s` — the combiner-lock analogue of the reference's
    lost-CAS spin. Disabled = one `enabled` branch; the uncontended
    fast path adds one `acquire(blocking=False)` either way, which an
    RLock satisfies reentrantly.
    """
    reg = get_registry()
    m_wait = reg.histogram("nr.lock.wait_s")

    @functools.wraps(fn)
    def inner(self, *args, **kwargs):
        lock = self._lock
        if not reg.enabled:
            with lock:
                return fn(self, *args, **kwargs)
        if not lock.acquire(blocking=False):
            t0 = time.monotonic()
            lock.acquire()
            m_wait.observe(time.monotonic() - t0)
        try:
            return fn(self, *args, **kwargs)
        finally:
            lock.release()

    return inner


def replicate_state(state, n_replicas: int):
    """Stack one replica state into an [R, ...] lock-step fleet."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(
            x[None], (n_replicas,) + x.shape
        ).copy(),
        state,
    )


def states_equal(states) -> bool:
    """All replicas of an [R, ...] state pytree are bit-identical (the
    `replicas_are_equal` convergence idiom, `nr/tests/stack.rs:434-489`).
    Shared by every runner/wrapper so the check can't drift."""
    return all(
        jax.tree.leaves(
            jax.tree.map(
                lambda a: bool(np.all(np.asarray(a) == np.asarray(a)[0:1])),
                states,
            )
        )
    )


class _FusedTier:
    """Fused-pallas-tier plumbing shared by `NodeReplicated` and
    `MultiLogReplicated` (`core/cnr.py`): lazy spec-bound engine
    construction, the calibration sampler, and the winner-selection
    state machine. Hosts expect the attributes initialized by their
    constructors (`_fused_mode`, `_fused_choice`, `_fused_verdicts`,
    `_fused_samples`, `_fused`, `_fused_spec`) and provide
    `_fused_log_spec()` — the `LogSpec` the engine is built against (a
    CNR derives one per-log spec for all its logs). All methods run
    under the host's combiner lock.

    On a mesh (`NodeReplicated(mesh=)`) the tier is the MESH-FUSED
    composition (`parallel/collectives.py:MeshFusedEngine`): the same
    one-launch round wrapped in shard_map with the cursor lattice
    joined over ICI, competing against the shmap/gspmd chain instead
    of the single-device one. Calibration is mesh-aware by
    construction: the verdict is measured at the live (R, capacity,
    devices) point and reset on `grow_fleet` AND on mesh re-placement
    (`_place_on_mesh`)."""

    def _fused_log_spec(self) -> LogSpec:
        return self.spec

    def _fused_fence_key(self) -> tuple:
        """Calibration key for the CURRENT quarantine mask: the sorted
        fenced rids (empty when none). Chain and fused timings are
        only comparable under the same mask — the fenced kernel
        variant is a DIFFERENT program — so samples and verdicts are
        keyed on it: a quarantine mid-serve recalibrates instead of
        routing rounds through a tier whose fenced variant was never
        timed."""
        f = getattr(self, "_fenced", None)
        if f is None:
            return ()
        return tuple(int(r) for r in np.where(f)[0])

    def _init_fused_tier(self, engine: str, dispatch, mesh, reg,
                         prefix: str, debug: bool = False,
                         mesh_fused: bool = False) -> None:
        """Initialize the tier state + counters and resolve the mode —
        the one constructor block both wrappers share. `engine='pallas'`
        FORCES the tier (validated loudly here: the model must carry a
        `fused_factory`, and checkify `debug` has no fused twin; on a
        mesh the host must support the mesh-fused composition —
        `mesh_fused=True`, NodeReplicated only); `engine='auto'` with a
        fused-capable dispatch arms the measured calibration on TPU
        (NR_TPU_FUSED_CAL=1 is the CPU-test hook — in interpret mode
        the fused tier cannot honestly win); anything else leaves the
        tier off."""
        self._fused = None
        self._fused_spec = None
        self._fused_mode = "off"
        self._fused_choice: bool | None = False
        # auto-mode verdicts, keyed by the fence mask (_fused_fence_key)
        self._fused_verdicts: dict[tuple, bool] = {}
        self._fused_mesh = mesh if mesh_fused else None
        self._fused_tier_name = (
            "mesh_fused" if self._fused_mesh is not None
            else "pallas_fused"
        )
        # calibration samples are keyed by (WINDOW, fence mask): chain
        # and fused timings are only comparable at the same padded
        # batch size AND the same quarantine mask, and the per-key
        # warmup absorbs each program's jit compile — a verdict
        # commits at the first key that fills both sides (see
        # _note_fused_sample)
        self._fused_samples: dict[str, dict[tuple, list]] = {
            "pallas_fused": {}, "chain": {},
        }
        self._fused_rounds = 0
        self.last_round_tier: str | None = None
        self._tier_by_rid: dict[int, str] = {}
        self._pos_by_rid: dict[int, int] = {}
        self._m_engine_fused = reg.counter(
            f"{prefix}.exec.engine.pallas_fused"
        )
        self._m_fused_fallback = reg.counter(
            f"{prefix}.exec.engine.fused_fallback"
        )
        if engine == "pallas":
            if dispatch.fused_factory is None:
                raise ValueError(
                    f"engine='pallas' but {dispatch.name} has no "
                    f"fused_factory (no fused kernel for this model)"
                )
            if mesh is not None and not mesh_fused:
                raise ValueError(
                    "engine='pallas' does not take mesh= here (the "
                    "mesh-fused composition is NodeReplicated-only; "
                    "the CNR per-log tier runs un-meshed — see README "
                    "'Engines')"
                )
            if debug:
                raise ValueError(
                    "engine='pallas' has no checkify twin; use "
                    "debug=False (the fused round replays inside the "
                    "kernel, outside the checks' reach)"
                )
            # build eagerly so an unsupported config fails loudly at
            # construction (the explicit ask), not mid-traffic
            spec = self._fused_log_spec()
            self._fused = self._build_fused_engine(spec)
            self._fused_spec = spec
            self._fused_mode = "forced"
            self._fused_choice = True
        elif (
            engine == "auto"
            and dispatch.fused_factory is not None
            and (mesh is None or mesh_fused)
            and not debug
            and (jax.default_backend() == "tpu"
                 or os.environ.get("NR_TPU_FUSED_CAL") == "1")
        ):
            self._fused_mode = "auto"
            self._fused_choice = None  # calibration pending

    def _build_fused_engine(self, spec: LogSpec):
        """The tier's engine for `spec`: the dispatch's own fused
        engine un-meshed, the shard_map-wrapped MeshFusedEngine on a
        mesh. Both raise ValueError for unsupported configs."""
        if self._fused_mesh is not None:
            from node_replication_tpu.parallel.collectives import (
                MeshFusedEngine,
            )

            return MeshFusedEngine(self.dispatch, spec,
                                   self._fused_mesh)
        return self.dispatch.fused_factory(spec)

    def _fused_engine(self):
        """Lazily (re)build the tier's fused engine for the CURRENT
        spec (fleet growth rebinds it). A factory rejection after a
        shape change degrades the tier to off with a warning rather
        than killing live traffic."""
        if self._fused_mode == "off":
            return None
        spec = self._fused_log_spec()
        if self._fused is None or self._fused_spec != spec:
            try:
                self._fused = self._build_fused_engine(spec)
                self._fused_spec = spec
            except ValueError as e:
                logger.warning(
                    "fused engine rejected spec after fleet change "
                    "(%s); falling back to the ordinary chain", e
                )
                self._fused_mode = "off"
                self._fused_choice = False
                return None
        return self._fused

    def _fused_calibrating(self, fkey: tuple | None = None) -> bool:
        """Auto mode with no committed verdict for the CURRENT fence
        mask — rounds are timed (and `defer` is ignored) while this
        holds. A fenced mask whose engine has NO fenced variant
        commits `chain` immediately: there is nothing to measure —
        `_try_fused_round` would fall back unconditionally — and
        without the short-circuit the fused side of the (pad, fkey)
        key could never fill, leaving the wrapper 'calibrating' (defer
        forced off, the serve pipeline's overlap dead) for the whole
        quarantine. Callers on the round hot path pass the
        already-computed `fkey` (the key derivation is an O(R) host
        scan under the combiner lock — compute it once per round)."""
        if self._fused_mode != "auto":
            return False
        if fkey is None:
            fkey = self._fused_fence_key()
        if self._fused_verdicts.get(fkey) is not None:
            return False
        if fkey:
            eng = self._fused_engine()
            if eng is None or not eng.supports_fenced:
                self._fused_verdicts[fkey] = False
                # every verdict commit leaves a trace record — an
                # operator reading the calibrations section must be
                # able to tell "measured chain win" from "nothing to
                # measure under this mask"
                get_tracer().emit(
                    "fused-calibration", window=0, fenced=list(fkey),
                    tier=self._fused_tier_name,
                    devices=getattr(eng, "devices", 1),
                    fused_s=0.0, chain_s=0.0, winner="chain",
                    reason="no-fenced-variant",
                )
                return False
        return True

    def _fused_tier_wanted(self, pad: int,
                           fkey: tuple | None = None):
        """The engine to route a `pad`-window round through, or None
        for the ordinary chain. During auto calibration the chain goes
        first AT EACH (window, fence-mask) key (its programs are the
        already-compiled steady state), then the fused tier collects
        that key's own samples — mixing keys would compare
        incomparable rounds. `fkey` as in `_fused_calibrating`."""
        if self._fused_mode == "off" or self._fused_choice is False:
            return None
        if self._fused_mode == "auto":
            if fkey is None:
                fkey = self._fused_fence_key()
            verdict = self._fused_verdicts.get(fkey)
            if verdict is False:
                return None
            if verdict is None:
                need = FUSED_CAL_WARMUP + FUSED_CAL_SAMPLES
                chain = self._fused_samples["chain"].get(
                    (pad, fkey), ()
                )
                if len(chain) < need:
                    return None
        return self._fused_engine()

    def _note_fused_sample(self, tier: str, pad: int, dt: float,
                           fkey: tuple = ()) -> None:
        need = FUSED_CAL_WARMUP + FUSED_CAL_SAMPLES
        key = (pad, tuple(fkey))
        samples = self._fused_samples[tier].setdefault(key, [])
        if len(samples) < need:
            samples.append(dt)
        # the verdict commits at the FIRST key whose chain and fused
        # sides are both full: same-window same-mask samples only, and
        # each side's warmup absorbed that program's compile
        chain = self._fused_samples["chain"].get(key, ())
        fused = self._fused_samples["pallas_fused"].get(key, ())
        if len(chain) < need or len(fused) < need:
            return
        med_c = statistics.median(chain[FUSED_CAL_WARMUP:])
        med_f = statistics.median(fused[FUSED_CAL_WARMUP:])
        verdict = med_f <= med_c
        self._fused_verdicts[tuple(fkey)] = verdict
        get_tracer().emit(
            "fused-calibration", window=pad, fenced=list(fkey),
            tier=self._fused_tier_name,
            devices=getattr(self._fused, "devices", 1),
            fused_s=med_f, chain_s=med_c,
            winner=(
                self._fused_tier_name if verdict else "chain"
            ),
        )

    def _reset_fused_calibration(self) -> None:
        """Fleet-shape change (or mesh re-placement) under
        engine='auto': the committed verdicts were measured at the OLD
        (R, capacity, devices) point — drop them and recalibrate at
        the new one."""
        if self._fused_mode == "auto":
            self._fused_verdicts = {}
            self._fused_samples = {"pallas_fused": {}, "chain": {}}

    def round_tier(self, rid: int) -> str | None:
        """The engine tier that served replica `rid`'s most recent
        combiner round — per-rid, so concurrent serve workers cannot
        misattribute each other's rounds (`last_round_tier` is the
        wrapper-wide convenience for single-driver callers). For a CNR
        batch spanning several logs this is the LAST sub-batch's
        tier."""
        return self._tier_by_rid.get(rid)

    def round_pos(self, rid: int) -> int | None:
        """The log position replica `rid`'s most recent combiner round
        appended at (`pos0`) — the per-record trace join key the serve
        layer stamps onto its `serve-batch` ack event, so a record's
        submit→ack hop is joinable with the append/ship/apply hops
        downstream (`obs/` fleet tracing). Same per-rid discipline as
        `round_tier`."""
        return self._pos_by_rid.get(rid)

    def _fused_tier_state(self) -> str:
        """Human-readable fused-tier state for stats()/snapshot() —
        the verdict for the CURRENT fence mask (auto mode verdicts are
        per-mask, see `_fused_fence_key`)."""
        if self._fused_mode == "off":
            return "off"
        if self._fused_mode == "forced":
            return "forced"
        verdict = self._fused_verdicts.get(self._fused_fence_key())
        if verdict is None:
            return "calibrating"
        return (
            f"auto:{self._fused_tier_name}" if verdict
            else "auto:chain"
        )


class NodeReplicated(_FusedTier):
    """N replicas of one `Dispatch` data structure behind a shared log.

    Mirrors the user-facing surface of `Replica` + `Log` wiring from the
    reference examples: `register`, `execute_mut`, `execute`, `sync`,
    `verify`, plus batched `enqueue_mut`/`flush` (the flat-combining fast
    path made explicit).
    """

    def __init__(
        self,
        dispatch: Dispatch,
        n_replicas: int = 1,
        log_entries: int | None = None,
        gc_slack: int | None = None,
        exec_window: int = DEFAULT_EXEC_WINDOW,
        gc_callback: Callable[[int, int], None] | None = None,
        debug: bool | None = None,
        engine: str = "auto",
        mesh=None,
        collectives: str = "auto",
    ):
        kw = {}
        if log_entries is not None:
            kw["capacity"] = log_entries
        if gc_slack is not None:
            kw["gc_slack"] = gc_slack
        self.spec = LogSpec(
            n_replicas=n_replicas, arg_width=dispatch.arg_width, **kw
        )
        self.dispatch = dispatch
        self.exec_window = int(exec_window)
        self.gc_callback = gc_callback
        # `debug` compiles device-side cursor invariants into the append
        # and replay programs (checkify — utils/checks.py): invalid
        # ltails and window-overrunning appends raise instead of
        # clamping. Off (default) the compiled programs are unchanged;
        # None defers to the NR_TPU_DEBUG env var.
        if debug is None:
            from node_replication_tpu.utils.checks import debug_default

            debug = debug_default()
        self.debug = bool(debug)

        self.log = log_init(self.spec)
        self.states = replicate_state(dispatch.init_state(), n_replicas)

        # Combiner lock (see `_locked`): guards log/states/cursor and
        # context bookkeeping against concurrent OS-thread callers.
        self._lock = make_rlock("NodeReplicated._lock")
        self._contexts: dict[tuple[int, int], Context] = {}
        self._threads_per_replica = [0] * n_replicas
        # Appended-but-unanswered ops per replica: deque[(logical_pos, tid)].
        self._inflight: list[deque] = [deque() for _ in range(n_replicas)]
        # Split-round registry (`begin_mut_batch`): at most ONE
        # begun-but-unfinished round per replica — the pipeline-depth-1
        # invariant that keeps future ordering, `maybe_executed`
        # attribution, and WAL group-commit per-round.
        self._pending_batch: dict[int, "_PendingRound"] = {}
        # Quarantine mask (`fault/health.py`): None until the first
        # `fence_replica` so the no-fault hot path stays byte-identical
        # (the compiled programs never see a mask argument); a bool[R]
        # numpy array while any replica is fenced.
        self._fenced: np.ndarray | None = None
        # Write-ahead log (`durable/wal.py`): None (the default) costs
        # one branch per append/exec round, the obs/metrics discipline.
        # While attached, every combiner append is mirrored into it
        # and GC-head progress drives segment reclamation.
        self._wal = None
        self._exec_rounds = 0
        # Rounds short-circuited because every replica was already at the
        # tail (empty combine() help, read-sync polling) — the device
        # sort+merge those rounds used to pay is skipped (ADVICE r5).
        self._idle_rounds = 0

        # Metric handles are created once here; each hot-path update is
        # one branch when the registry is disabled (obs/metrics.py).
        reg = get_registry()
        self._m_rounds = reg.counter("nr.exec.rounds")
        self._m_idle = reg.counter("nr.exec.idle_rounds")
        self._m_batch = reg.histogram("nr.combine.batch_size",
                                      buckets=COUNT_BUCKETS)
        self._m_stalls = reg.counter("nr.watchdog.stalls")
        self._m_lag = reg.histogram("nr.replica.lag",
                                    buckets=COUNT_BUCKETS)

        # Replay engine for every cursor catch-up loop (sync, read-sync,
        # combine-replay, recovery): 'combined' routes through
        # `log_catchup_all` — for plan/merge models the union-window
        # plan, sound because this wrapper's fleet is always ON the
        # shared replay trajectory (states are folds of the log from
        # common init; the reference's catch-up-at-hot-loop-speed
        # contract, `nr/src/log.rs:473-524`) — 'scan' forces the
        # generic vmapped scan, 'auto' (default) picks combined when
        # the model provides a combined form. Off-trajectory hand-built
        # states must not use 'combined' (see log_catchup_all's
        # `on_trajectory`).
        if engine not in ("auto", "combined", "scan", "pallas"):
            raise ValueError(f"unknown engine {engine!r}")
        if (dispatch.window_plan is None) != (
            dispatch.window_merge is None
        ):
            raise ValueError(
                f"{dispatch.name}: window_plan and window_merge come "
                f"as a pair (got only one)"
            )
        has_any_combined = (
            dispatch.window_apply is not None
            or dispatch.window_plan is not None
        )
        if engine == "combined" and not has_any_combined:
            raise ValueError(
                f"engine='combined' but {dispatch.name} has no "
                f"window_apply or window_plan"
            )
        # 'auto' resolves to the combined engine only when a combined
        # tier will actually run: window_apply, or a plan/merge pair
        # that opted into the union contract (window_canonical). A
        # lock-step-only plan/merge model would otherwise fall through
        # to the scan inside log_catchup_all every round while
        # stats()/metrics reported 'combined'.
        auto_combined = (
            dispatch.window_apply is not None
            or (dispatch.window_plan is not None
                and dispatch.window_canonical)
        )
        # engine='pallas' forces the FUSED tier for combiner rounds;
        # the catch-up loops below it still need a divergent-cursor
        # engine, resolved exactly as 'auto' would
        use_combined = (
            auto_combined if engine in ("auto", "pallas")
            else engine == "combined"
        )
        self.engine = "combined" if use_combined else "scan"
        # engine='combined' is the caller EXPLICITLY asserting the
        # union-tier contract; 'auto' defers to the model's own
        # `window_canonical` opt-in (ADVICE r5: presence of a
        # plan/merge pair only claims the lock-step contract)
        self._union = True if engine == "combined" else None
        # per-round engine usage (host truth for the wrapper; core/log.py
        # counts per-trace selections of the inner tiers)
        self._m_engine = reg.counter(f"nr.exec.engine.{self.engine}")

        # ---- mesh placement (parallel/): shard the replica axis -----
        # `mesh` puts the fleet across devices: states (and ltails)
        # shard over the mesh's 'replica' axis, the log's ring arrays
        # and scalar cursors replicate (`parallel/mesh.py:place` — the
        # NamedSharding(mesh, P('replica')) batch-dim pattern). Accepts
        # a jax Mesh, a device count (first N devices), or a
        # ReplicaStrategy. `collectives` picks the cross-device exec
        # tier: 'shmap' = the explicit-collective shard_map exec
        # (`parallel/collectives.py:make_shmap_exec`, pmax/pmin lattice
        # over ICI), 'gspmd' = the annotation path (the exact
        # single-device programs, GSPMD inserts the collectives from
        # the placed inputs), 'auto' = shmap for scan-engine fleets,
        # gspmd when the combined engine (whose union-plan economics
        # GSPMD preserves) or debug checks are in play. Both tiers are
        # differentially pinned bit-identical to the un-meshed wrapper
        # (tests/test_mesh_fleet.py). mesh=None is byte-identical to
        # the pre-mesh wrapper: no placement, no extra branches traced.
        if collectives not in ("auto", "shmap", "gspmd"):
            raise ValueError(f"unknown collectives tier {collectives!r}")
        self.mesh = None
        self._mesh_shards = 0
        self._mesh_tier = None
        self._ring_rounds = 0
        if mesh is not None:
            from node_replication_tpu.parallel.mesh import (
                ReplicaStrategy,
                announce_placement,
                replica_mesh,
            )

            if isinstance(mesh, int):
                mesh = replica_mesh(mesh)
            elif isinstance(mesh, ReplicaStrategy):
                mesh = replica_mesh(strategy=mesh)
            if "replica" not in mesh.axis_names:
                raise ValueError(
                    f"mesh {mesh.axis_names} has no 'replica' axis"
                )
            shards = mesh.shape["replica"]
            if n_replicas % shards:
                raise ValueError(
                    f"R={n_replicas} replicas cannot shard over "
                    f"{shards} mesh shards"
                )
            if collectives == "auto":
                tier = (
                    "gspmd"
                    if (self.engine == "combined" or self.debug)
                    else "shmap"
                )
            else:
                tier = collectives
            if tier == "shmap" and self.debug:
                raise ValueError(
                    "collectives='shmap' has no checkify twin; use "
                    "the gspmd tier (or debug=False) on a mesh"
                )
            self.mesh = mesh
            self._mesh_shards = shards
            self._mesh_tier = tier
            self._m_mesh_round = reg.counter(f"nr.exec.mesh.{tier}")
            self._m_mesh_sync_bytes = reg.counter("mesh.sync_bytes")
            self._m_mesh_dur = reg.histogram("mesh.round.duration_s")
            self._m_ring = reg.counter("nr.exec.engine.ring")
            # mesh-fused rounds (the shard_map-wrapped one-launch tier)
            # count separately from the shmap/gspmd chain rounds
            self._m_mesh_fused_round = reg.counter(
                "nr.exec.mesh.mesh_fused"
            )
            announce_placement(mesh, n_replicas, "NodeReplicated", tier)

        # ---- fused pallas combiner-round tier (ops/pallas_replay) ----
        # One kernel launch per combiner round: append + replay +
        # response gather fused into a single program, replacing the
        # append-jit → exec-jit chain (and its per-round host syncs)
        # when the round is lock-step eligible. On a mesh the tier is
        # the MESH-FUSED composition (`parallel/collectives.py:
        # MeshFusedEngine`): one shard_map-wrapped launch per device
        # with the cursor lattice joined over ICI, replacing the
        # shmap/gspmd chain for eligible rounds. Mode resolution +
        # winner-selection calibration: `_FusedTier` (shared with the
        # CNR twin; initialized AFTER mesh normalization so the tier
        # binds the real Mesh object). The tier never changes results —
        # it is differentially pinned bit-identical to the scan engine
        # (tests/test_pallas_fused.py, tests/test_mesh_fleet.py) —
        # only the launch count.
        self._init_fused_tier(engine, dispatch, self.mesh, reg, "nr",
                              debug=self.debug, mesh_fused=True)
        if self.mesh is not None:
            self._place_on_mesh()
        self._build_jits()

    @_locked
    def _place_on_mesh(self) -> None:
        """(Re)apply the canonical mesh shardings to log + states —
        after construction and after every fleet-shape change
        (`grow_fleet`, `recover`, `restore`) whose fresh arrays would
        otherwise land on the default device. No-op un-meshed."""
        if self.mesh is None:
            return
        from node_replication_tpu.parallel.mesh import place

        self.log, self.states = place(self.log, self.states, self.mesh)
        # re-placement is a new (R, capacity, devices) point: an
        # auto-mode winner verdict measured before it no longer applies
        self._reset_fused_calibration()

    def replica_device(self, rid: int):
        """The device hosting replica `rid`'s state shard (None when
        un-meshed) — the serve layer's worker-per-replica→device map.
        NamedSharding(P('replica')) splits the replica axis into
        contiguous blocks in mesh device order."""
        if self.mesh is None:
            return None
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        shard = rid // (self.n_replicas // self._mesh_shards)
        return self.mesh.devices.reshape(self._mesh_shards, -1)[shard][0]

    @_locked
    def _shmap_fn(self, window: int, fenced: bool):
        """Build-once cache of the explicit-collective exec programs
        (`parallel/collectives.py:make_shmap_exec`), keyed (window,
        fenced) like jit's own static cache."""
        fn = self._shmap_cache.get((window, fenced))
        if fn is None:
            from node_replication_tpu.parallel.collectives import (
                make_shmap_exec,
            )

            fn = make_shmap_exec(self.dispatch, self.spec, self.mesh,
                                 window, fenced=fenced)
            self._shmap_cache[(window, fenced)] = fn
        return fn

    def _shmap_exec_entry(self, log, states, window):
        return self._shmap_fn(window, False)(log, states)

    def _shmap_exec_fenced_entry(self, log, states, fenced, window):
        return self._shmap_fn(window, True)(log, states, fenced)

    @_locked
    def _build_jits(self) -> None:
        """(Re)build the compiled append/exec/read entry points against the
        CURRENT `self.spec` — called from `__init__` and `grow_fleet`
        (growing changes `n_replicas`, so the partials must rebind)."""
        # mesh program caches are spec-bound too
        self._shmap_cache: dict = {}
        self._ring_fn = None
        self._ring_gather = None
        # the fused engine is spec-bound (R, capacity): rebuild lazily
        # after any fleet-shape change — and an auto-mode verdict
        # measured at the old shape no longer applies (recalibrate)
        self._fused = None
        self._reset_fused_calibration()
        dispatch = self.dispatch
        exec_fn = (
            partial(log_catchup_all, union=self._union)
            if self.engine == "combined" else log_exec_all
        )
        def _exec_fenced(log, states, fenced, window):
            return exec_fn(self.spec, dispatch, log, states,
                           window=window, fenced=fenced)

        if self.debug:
            from node_replication_tpu.utils.checks import checked

            self._exec_jit = jax.jit(
                checked(partial(exec_fn, self.spec, dispatch)),
                static_argnames=("window",),
            )
            self._exec_fenced_jit = jax.jit(
                checked(_exec_fenced), static_argnames=("window",),
            )
            self._append_jit = jax.jit(
                checked(partial(log_append, self.spec))
            )
        else:
            self._exec_jit = jax.jit(
                partial(exec_fn, self.spec, dispatch),
                static_argnames=("window",),
                donate_argnums=(0, 1),
            )
            # Fenced twin of the exec program (compiled only if a
            # replica is ever fenced — jit compilation is lazy, so the
            # fault-free path never pays for it).
            self._exec_fenced_jit = jax.jit(
                _exec_fenced, static_argnames=("window",),
                donate_argnums=(0, 1),
            )
            self._append_jit = jax.jit(
                partial(log_append, self.spec), donate_argnums=(0,)
            )

        if self.mesh is not None and self._mesh_tier == "shmap":
            # the explicit-collective tier REPLACES the exec programs
            # (append + read jits stay: appends are replicated writes,
            # reads a one-replica gather — GSPMD handles both)
            self._exec_jit = self._shmap_exec_entry
            self._exec_fenced_jit = self._shmap_exec_fenced_entry

        def _read_one(states, rid, opcode, args):
            state = jax.tree.map(lambda a: a[rid], states)
            return apply_read(dispatch, state, opcode, args)

        self._read_jit = jax.jit(_read_one)

    # ------------------------------------------------------------------ API

    @property
    def n_replicas(self) -> int:
        return self.spec.n_replicas

    @_locked
    def ltail(self, rid: int) -> int:
        """Replica `rid`'s applied cursor (host int). Locked: an
        unlocked read races the exec round's buffer donation (the old
        `log` arrays are DELETED once donated) — the bounded-staleness
        read path (`serve/frontend.py`, `repl/`) polls this."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        return int(np.asarray(self.log.ltails)[rid])

    @_locked
    def register(self, rid: int = 0) -> ReplicaToken:
        """Register a logical thread on replica `rid`
        (`Replica::register`, `nr/src/replica.rs:279-298`)."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        tid = self._threads_per_replica[rid]
        if tid >= MAX_THREADS_PER_REPLICA:
            raise RuntimeError(
                f"replica {rid} already has {MAX_THREADS_PER_REPLICA} threads"
            )
        self._threads_per_replica[rid] = tid + 1
        self._contexts[(rid, tid)] = Context()
        return ReplicaToken(rid, tid)

    @_locked
    def grow_fleet(self, k: int = 1, donor: int | None = None,
                   catch_up: bool = True) -> list[int]:
        """Dynamic replica registration: add `k` replicas to a LIVE
        instance and return their new rids.

        The reference registers replicas against a live log at any time —
        `Log::register` CASes a fresh id (`nr/src/log.rs:272-292`) and
        `Replica::new` calls it at construction
        (`nr/src/replica.rs:184-232`); the newcomer starts from `Default`
        at position 0, which is only sound before the ring wraps. Here
        the newcomer instead CLONES the most caught-up replica's state —
        a consistent snapshot at exactly `ltails[donor]` (induction: a
        replica's state is the fold of `[0, ltails[r])`) — inherits that
        cursor, and catches up through the same combined/scan exec loop
        every replica uses (`log_catchup_all`), so a join is valid at ANY
        point in the log's lifetime, wraps included. Existing tokens stay
        valid (rids are stable); register threads on the new rids to use
        them. GC is never held back: the newcomer's ltail equals the
        donor's, which is >= min(ltails), so `head = min(ltails)` is
        unchanged (with the default most-caught-up donor it is in fact
        the max, but the invariant only needs >= min).
        """
        if k < 1:
            raise ValueError("grow_fleet needs k >= 1")
        R = self.n_replicas
        if self.mesh is not None and (R + k) % self._mesh_shards:
            # validated BEFORE any state mutates: an indivisible fleet
            # cannot keep the P('replica') placement balanced
            raise ValueError(
                f"grown fleet of {R + k} replicas cannot shard over "
                f"{self._mesh_shards} mesh shards (grow in multiples "
                f"of the shard count)"
            )
        ltails = np.asarray(self.log.ltails)
        if donor is None:
            # never clone from a fenced (possibly corrupt) replica
            masked = (
                ltails if self._fenced is None
                else np.where(self._fenced, -1, ltails)
            )
            donor = int(np.argmax(masked))
        elif not 0 <= donor < R:
            raise ValueError(f"donor replica {donor} out of range")
        elif self._is_fenced(donor):
            raise ReplicaFencedError(donor)
        donor_ltail = int(ltails[donor])

        self.spec = dataclasses.replace(
            self.spec, n_replicas=R + k
        )
        # states: stack k bit-copies of the donor's snapshot onto the
        # replica axis; cursors: the newcomers start at the donor's ltail
        self.states = jax.tree.map(
            lambda x: jnp.concatenate(
                [x] + [x[donor][None]] * k, axis=0
            ),
            self.states,
        )
        self.log = self.log._replace(
            ltails=jnp.concatenate(
                [self.log.ltails,
                 jnp.full((k,), donor_ltail, jnp.int64)]
            )
        )
        self._threads_per_replica.extend([0] * k)
        self._inflight.extend(deque() for _ in range(k))
        if self._fenced is not None:
            self._fenced = np.concatenate(
                [self._fenced, np.zeros(k, bool)]
            )
        self._place_on_mesh()
        self._build_jits()
        new_rids = list(range(R, R + k))
        get_tracer().emit(
            "grow_fleet", k=k, donor=donor, donor_ltail=donor_ltail,
            n_replicas=R + k,
        )
        if catch_up:
            for rid in new_rids:
                self.sync(rid)
        return new_rids

    # ------------------------------------------------- fencing (fault/)

    def _is_fenced(self, rid: int) -> bool:
        f = self._fenced
        return f is not None and bool(f[rid])

    @property
    def fenced_rids(self) -> list[int]:
        """Currently fenced (quarantined) replicas."""
        f = self._fenced
        return [] if f is None else [int(r) for r in np.where(f)[0]]

    @_locked
    def fence_replica(self, rid: int) -> None:
        """Fence `rid` out of the fleet (the QUARANTINED half of the
        lifecycle machine, `fault/health.py`): its replay freezes at
        its current ltail, and the GC reduction `head = min(ltails)`
        skips it (`core/log.py:_gc_head`) so one dead replica cannot
        stall log GC. Its in-flight responses are dropped (crash
        semantics, like `recover`): a fenced replica's replay never
        advances, so they are undeliverable. Idempotent."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        if self._fenced is None:
            self._fenced = np.zeros(self.n_replicas, bool)
        if self._fenced[rid]:
            return
        self._fenced[rid] = True
        self._inflight[rid] = deque()
        sink = self._contexts.get((rid, BATCH_TID))
        if sink is not None:
            sink.reset()
        # crash semantics for a begun-but-unfinished split round too:
        # its delivery state is gone with the sink, and a repaired
        # replica must be able to begin fresh rounds
        stale = self._pending_batch.pop(rid, None)
        if stale is not None:
            stale.done = True
            stale.fused_resps = None
        get_tracer().emit(
            "fault-fence", rid=rid,
            ltail=int(np.asarray(self.log.ltails)[rid]),
        )

    @_locked
    def unfence_replica(self, rid: int) -> None:
        """Readmit `rid` to replay and GC accounting. The caller must
        have re-seated its state/cursor first (`clone_replica_from` —
        a fenced cursor may have fallen behind the GC head, where the
        log no longer holds its entries). Idempotent."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        if self._fenced is None or not self._fenced[rid]:
            return
        self._fenced[rid] = False
        if not self._fenced.any():
            self._fenced = None  # restore the no-mask hot path
        get_tracer().emit("fault-unfence", rid=rid)

    @_locked
    def clone_replica_from(self, rid: int,
                           donor: int | None = None) -> tuple[int, int]:
        """Overwrite replica `rid`'s state and cursor with a bit-copy
        of a healthy donor's — the `grow_fleet` donor-copy invariant
        applied IN PLACE (a replica's state is the fold of
        `[0, ltails[r])` from common init, so the copy is a consistent
        snapshot at exactly the donor's ltail). The first half of
        repair-by-replay (`fault/repair.py`); the second half is the
        ordinary catch-up loop after `unfence_replica`. Defaults to
        the most caught-up unfenced replica. Returns
        `(donor, donor_ltail)`."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        ltails = np.asarray(self.log.ltails)
        eligible = np.ones(self.n_replicas, bool)
        eligible[rid] = False
        if self._fenced is not None:
            eligible &= ~self._fenced
        if donor is None:
            if not eligible.any():
                raise RuntimeError(
                    "no healthy donor replica available (all fenced)"
                )
            masked = np.where(eligible, ltails, -1)
            donor = int(np.argmax(masked))
        elif donor == rid or not 0 <= donor < self.n_replicas:
            raise ValueError(f"bad donor replica {donor}")
        elif self._is_fenced(donor):
            raise ReplicaFencedError(donor)
        donor_ltail = int(ltails[donor])
        self.states = jax.tree.map(
            lambda x: x.at[rid].set(x[donor]), self.states
        )
        self.log = self.log._replace(
            ltails=self.log.ltails.at[rid].set(donor_ltail)
        )
        self._inflight[rid] = deque()
        get_tracer().emit(
            "fault-clone", rid=rid, donor=donor,
            donor_ltail=donor_ltail,
        )
        return donor, donor_ltail

    # ------------------------------------------------- durability (durable/)

    @property
    def wal(self):
        """The attached write-ahead log (None when not durable)."""
        return self._wal

    @_locked
    def attach_wal(self, wal, backfill: bool = True) -> None:
        """Attach a `durable/wal.py:WriteAheadLog`: every subsequent
        combiner append is persisted into it (fsync per its policy),
        and the exec loop drives segment reclamation from GC-head
        progress.

        `backfill=True` (default) persists entries the log already
        holds past the WAL's tail — `[wal.tail, tail)` read back from
        the ring (`core/log.py:ring_slice`) — so a WAL can attach to a
        live, mid-traffic instance. That is only possible while the
        ring still physically holds those entries; attaching later
        than `capacity` appends needs a snapshot-based recovery
        (`durable/recovery.py`) instead. A WAL ahead of the log is
        refused: its unreplayed tail must go through recovery first.
        """
        if self._wal is not None:
            raise RuntimeError("a WAL is already attached")
        tail = int(self.log.tail)
        wal_tail = wal.tail
        if wal_tail > tail:
            raise ValueError(
                f"WAL tail {wal_tail} is ahead of the log tail {tail}; "
                f"recover the WAL into the fleet first "
                f"(durable/recovery.py)"
            )
        if wal_tail < tail:
            if not backfill:
                raise ValueError(
                    f"WAL tail {wal_tail} is behind the log tail "
                    f"{tail} and backfill=False"
                )
            opcodes, args = ring_slice(self.spec, self.log,
                                       wal_tail, tail)
            wal.append(wal_tail, [
                (int(opcodes[i]), *(int(a) for a in args[i]))
                for i in range(opcodes.shape[0])
            ])
        self._wal = wal
        get_tracer().emit("wal-attach", tail=tail,
                          backfilled=tail - wal_tail)

    @_locked
    def detach_wal(self):
        """Detach and return the WAL (not closed — the caller owns its
        lifecycle)."""
        wal, self._wal = self._wal, None
        return wal

    def wal_sync(self) -> int:
        """fsync the attached WAL (`WriteAheadLog.sync`) — the serve
        frontend's durable-ack barrier. Deliberately NOT under the
        combiner lock: fsync latency must not stall concurrent
        combiner rounds; the WAL has its own lock."""
        wal = self._wal
        if wal is None:
            raise RuntimeError("no WAL attached (attach_wal)")
        return wal.sync()

    @_locked
    def execute_mut(self, op: tuple, token: ReplicaToken):
        """Stage one write op, combine, and return its response
        (`Replica::execute_mut`, `nr/src/replica.rs:345-356`)."""
        ctx = self._contexts[(token.rid, token.tid)]
        if not ctx.enqueue(op[0], tuple(op[1:])):
            self.combine(token.rid)
            ctx.enqueue(op[0], tuple(op[1:]))
        self.combine(token.rid)
        # This op is the thread's newest enqueue, so after the combine its
        # response is the newest delivered. Earlier `enqueue_mut`
        # responses stay queued, in order, for `responses()`.
        return ctx.res_newest()

    @_locked
    def enqueue_mut(self, op: tuple, token: ReplicaToken) -> None:
        """Stage a write without combining (explicit flat-combining batch
        building). Combines first if this thread's 32-slot ring is full."""
        ctx = self._contexts[(token.rid, token.tid)]
        if not ctx.enqueue(op[0], tuple(op[1:])):
            self.combine(token.rid)
            ctx.enqueue(op[0], tuple(op[1:]))

    @_locked
    def flush(self, rid: int | None = None) -> None:
        """Combine pending batches (all replicas by default)."""
        for r in range(self.n_replicas) if rid is None else [rid]:
            self.combine(r)

    @_locked
    def responses(self, token: ReplicaToken) -> list:
        """Drain delivered responses for this thread, in enqueue order."""
        ctx = self._contexts[(token.rid, token.tid)]
        out = []
        r = ctx.res()
        while r is not None:
            out.append(r)
            r = ctx.res()
        return out

    @_locked
    def execute(self, op: tuple, token: ReplicaToken):
        """Read path (`Replica::execute` → `read_only`,
        `nr/src/replica.rs:404-410`, `483-497`): wait until this replica has
        replayed up to the completed tail (helping replay while waiting),
        then dispatch locally against replica state."""
        rid = token.rid
        if self._is_fenced(rid):
            raise ReplicaFencedError(rid)
        fault_hook("read-sync", rid, self)
        ctail = int(self.log.ctail)
        rounds = 0
        while int(np.asarray(self.log.ltails)[rid]) < ctail:
            self._exec_round()
            rounds = self._watchdog(rounds, "read-sync")
        return self._dispatch_read(rid, op)

    def _dispatch_read(self, rid: int, op: tuple) -> int:
        """Shared read-dispatch tail: pack args, run the read jit
        against replica `rid`'s current state. `execute` (synced) and
        `execute_stale` (brownout) must never diverge on this step.
        Caller holds the combiner lock and has fence-checked."""
        args = np.zeros((self.spec.arg_width,), np.int32)
        args[: len(op) - 1] = op[1:]
        return int(
            self._read_jit(
                self.states,
                jnp.int32(rid),
                jnp.int32(op[0]),
                jnp.asarray(args),
            )
        )

    @_locked
    def read_lag(self, rid: int) -> int:
        """Positions the completed tail leads replica `rid`'s applied
        cursor by — the staleness a sync-free read on `rid` would
        serve at. Locked for the same buffer-donation reason as
        `ltail`. The serve brownout read path
        (`serve/frontend.py:read`) checks this against its staleness
        bound before taking `execute_stale`."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        ctail = int(self.log.ctail)
        return max(0, ctail - int(np.asarray(self.log.ltails)[rid]))

    @_locked
    def execute_stale(self, op: tuple, token: ReplicaToken):
        """Bounded-staleness read: dispatch against this replica's
        CURRENT state with NO read-sync — the on-primary analog of the
        follower read path (`repl/follower.py`), used by the serve
        brownout mode. The caller owns the staleness contract: check
        `read_lag(rid)` against the bound first (under load the
        combiner rounds advance the replica continuously, so the lag
        observed there still bounds what this read serves at — replay
        only moves the replica FORWARD). Fenced replicas reject as on
        every other entry point."""
        rid = token.rid
        if self._is_fenced(rid):
            raise ReplicaFencedError(rid)
        return self._dispatch_read(rid, op)

    @_locked
    def execute_stale_bounded(self, op: tuple, token: ReplicaToken,
                              max_lag: int):
        """`execute_stale` with the staleness bound enforced ATOMICALLY:
        lag check and dispatch happen under one lock acquisition, so a
        concurrent batch cannot advance the completed tail between a
        caller's `read_lag` peek and the dispatch (that window would
        let a "bounded" read silently serve beyond its bound — and
        under-report the lag the bound gate records). Returns
        `(value, lag)` when `lag <= max_lag`, else None (the caller
        falls back to the synced path)."""
        rid = token.rid
        if self._is_fenced(rid):
            raise ReplicaFencedError(rid)
        ctail = int(self.log.ctail)
        lag = max(0, ctail - int(np.asarray(self.log.ltails)[rid]))
        if lag > int(max_lag):
            return None
        return self._dispatch_read(rid, op), lag

    @_locked
    def combine(self, rid: int) -> None:
        """Drain this replica's thread contexts (thread order —
        `nr/src/replica.rs:555-557`), append the batch, and replay until
        this replica has applied its own ops (`nr/src/replica.rs:543-595`).
        Responses are delivered to every replica's contexts as replay
        progresses."""
        ops: list[tuple] = []  # (opcode, *args)
        tids: list[int] = []  # per-op response destination
        for tid in range(self._threads_per_replica[rid]):
            for opcode, args in self._contexts[(rid, tid)].ops():
                ops.append((opcode, *args))
                tids.append(tid)
        if not ops:
            self._exec_round()  # combine with nothing staged still helps
            return
        self._append_and_replay(ops, rid, tids)

    @_locked
    def _try_fused_round(self, ops, rid, tids, n, pos0, pad,
                         opcodes, args, pending=None,
                         fkey: tuple = ()) -> bool:
        """Route one combiner round through the fused engine when
        eligible; False falls back to the append+exec chain. The
        eligibility is exactly the lock-step precondition the fused
        kernel requires, checked host-side against one fused cursor
        readback: every LIVE cursor at the pre-append tail, no
        in-flight responses owed (the fused round delivers only its
        own batch), and a window the engine's ring-span append
        supports. Results are bit-identical to the chain either way;
        only launch count and latency differ.

        With `pending` (a `_PendingRound` — the split-round path), the
        kernel is LAUNCHED and journaled here but the response
        readback (the round's host fence) is deferred to
        `_finish_round`: the whole device round overlaps whatever host
        work the caller does between begin and finish. `fkey` is the
        round's fence-mask calibration key, computed once by
        `_begin_round`."""
        eng = self._fused_tier_wanted(pad, fkey)
        if eng is None:
            return False
        if self._fenced is not None and not eng.supports_fenced:
            self._m_fused_fallback.inc()
            return False
        if not eng.supports(pad):
            self._m_fused_fallback.inc()
            return False
        if any(self._inflight):
            self._m_fused_fallback.inc()
            return False
        cur = np.asarray(
            jnp.concatenate([self.log.ltails, self.log.tail[None]])
        ).copy()
        lts, tail = cur[:-1], int(cur[-1])
        live = lts if self._fenced is None else lts[~self._fenced]
        if not (live.size
                and int(live.min()) == tail == int(live.max())):
            self._m_fused_fallback.inc()
            return False
        # tail == pos0: the GC-help loop never appends
        timing = self._fused_calibrating(fkey)
        t0 = time.perf_counter()
        fenced = self._fenced
        extra = {"deferred": True} if pending is not None else {}
        if eng.tier == "mesh_fused":
            extra["devices"] = eng.devices
        with span("fused-round", rid=rid, n=n, pos0=pos0,
                  window=pad, **extra) as sp:
            self.log, self.states, resps = eng.round(
                self.log, self.states, opcodes, args, n, fenced=fenced
            )
            if pending is None:
                # the response readback is also the round's host
                # fence: delivery below needs the values, and the
                # calibration timing needs completed device work
                resps_np = np.asarray(resps)
                sp.fence(self.log, self.states)
        if timing:
            self._note_fused_sample(
                "pallas_fused", pad, time.perf_counter() - t0, fkey
            )
        if self._wal is not None:
            # same order as the chain: journal once the ops ARE in the
            # in-memory log, before any response is delivered
            self._wal.append(pos0, ops)
            if fenced is None or not fenced.any():
                floor = pos0 + n
            else:
                floor = min(int(lts[fenced].min()), pos0 + n)
            self._wal.maybe_reclaim(floor)
        self._fused_rounds += 1
        self._m_engine_fused.inc()
        if eng.tier == "mesh_fused":
            # a mesh round by tier: counted next to the shmap/gspmd
            # chain rounds (nr.exec.mesh.*)
            self._m_mesh_fused_round.inc()
        if pending is not None:
            # split round: the launch is in flight; `_finish_round`
            # reads the responses back and delivers
            pending.fused_resps = resps
            pending.tier = eng.tier
            return True
        for j, tid in enumerate(tids):
            self._contexts[(rid, tid)].enqueue_resps(
                [int(resps_np[rid, j])]
            )
        self.last_round_tier = eng.tier
        self._tier_by_rid[rid] = eng.tier
        self._pos_by_rid[rid] = pos0
        return True

    @_locked
    def _begin_round(self, ops: list[tuple], rid: int,
                     tids: list[int], batch: bool = False,
                     defer: bool = False) -> _PendingRound:
        """First half of the shared combiner-round protocol (one
        protocol, every caller): fence guard, append-site fault hook,
        wait for ring space (helping GC), encode + append the batch,
        journal it, record each op's in-flight response destination.
        Returns the `_PendingRound` that `_finish_round` completes.

        `defer=False` is the serial shape: the caller runs
        `_finish_round` immediately (that composition IS
        `_append_and_replay`). `defer=True` (the split-round path,
        `begin_mut_batch`) leaves this replica's replay-to-target —
        or, on the fused tier, the response readback of the
        already-launched kernel — for `finish`, so a pipelined caller
        overlaps the next batch's host work with this round's device
        work. Calibration rounds (`engine='auto'`, verdict pending)
        ignore `defer`: honest tier timing needs the round
        back-to-back. The lock is reentrant: callers already hold it.

        When the fused pallas tier is selected and the round is
        lock-step eligible, the whole round — append, replay, response
        gather — is ONE kernel launch (`_try_fused_round`); the WAL
        journaling, response-delivery order, and cursor lattice are
        identical by construction."""
        if self._is_fenced(rid):
            # a fenced replica's replay is frozen: waiting for it to
            # apply its own batch would hang forever — fail fast, the
            # serve layer re-homes (`ServeFrontend._fail_replica`)
            raise ReplicaFencedError(rid)
        fault_hook("append", rid, self)
        n = len(ops)
        max_batch = self.spec.capacity - self.spec.gc_slack
        if n > max_batch:
            raise LogTooSmallError(
                f"batch of {n} exceeds appendable capacity {max_batch}"
            )
        self._m_batch.observe(n)
        rounds = 0
        while int(log_space(self.spec, self.log)) < n:
            self._exec_round()
            rounds = self._watchdog(rounds, "append-gc")

        pos0 = int(self.log.tail)
        pad = 1 << (max(n, 1) - 1).bit_length()
        opcodes, args, _ = encode_ops(
            ops, self.spec.arg_width, pad_to=pad
        )
        fkey = self._fused_fence_key()  # once per round: O(R) scan
        timing = self._fused_calibrating(fkey)
        defer = defer and not timing
        pending = _PendingRound(rid, list(tids), n, pos0, batch=batch)
        pending.pad = pad
        pending.fkey = fkey
        if self._try_fused_round(ops, rid, tids, n, pos0, pad,
                                 opcodes, args,
                                 pending if defer else None,
                                 fkey=fkey):
            if pending.fused_resps is None:
                pending.done = True  # ran eagerly end-to-end
            return pending
        if timing:
            pending.t_chain = time.perf_counter()
        extra = {"batch": True} if batch else {}
        with span("append", rid=rid, n=n, pos0=pos0, **extra) as sp:
            self.log = self._append_call(opcodes, args, n)
            sp.fence(self.log)
        if self._wal is not None:
            # WAL write AFTER the device append, under the same lock:
            # a WAL record exists only for ops that ARE in the
            # in-memory log, so the two never disagree about history.
            # A WAL failure here raises out of the round after the ops
            # are appended — the post-append failure class the serve
            # layer already treats as maybe_executed (not retryable);
            # with fsync policy `always` the records are durable
            # before any response is delivered.
            self._wal.append(pos0, ops)
        inflight = self._inflight[rid]
        for j, tid in enumerate(tids):
            inflight.append((pos0 + j, tid))
        return pending

    @_locked
    def _finish_round(self, pending: _PendingRound) -> None:
        """Second half of the combiner-round protocol: replay until
        replica `rid` has applied its own ops (chain tier), or read
        back and deliver the fused launch's responses. No-op for a
        round `begin` already completed eagerly."""
        if pending.done:
            return
        pending.done = True
        rid = pending.rid
        if self._is_fenced(rid):
            # fenced between begin and finish (failover quarantine):
            # the chain replay cursor is frozen — waiting on it would
            # hang — and `fence_replica` dropped the in-flight
            # deliveries with crash semantics, so a computed fused
            # round's responses are equally undeliverable. Post-append
            # by construction: maybe_executed semantics.
            raise ReplicaFencedError(rid)
        if pending.fused_resps is not None:
            # the readback is the split round's host fence: the fused
            # launch (append+replay+gather) completes here
            resps_np = np.asarray(pending.fused_resps)
            pending.fused_resps = None
            for j, tid in enumerate(pending.tids):
                self._contexts[(rid, tid)].enqueue_resps(
                    [int(resps_np[rid, j])]
                )
            tier = pending.tier or "pallas_fused"
            self.last_round_tier = tier
            self._tier_by_rid[rid] = tier
            self._pos_by_rid[rid] = pending.pos0
            return
        target = pending.target
        rounds = 0
        with span("combine-replay", rid=rid, target=target) as sp:
            while int(np.asarray(self.log.ltails)[rid]) < target:
                self._exec_round()
                rounds = self._watchdog(rounds, "combine-replay")
            sp.fence(self.log, self.states)
        self.last_round_tier = self.engine
        self._tier_by_rid[rid] = self.engine
        self._pos_by_rid[rid] = pending.pos0
        if pending.t_chain is not None:
            # the replay loop's cursor readbacks serialize the chain,
            # so the wall delta is an honest device-time sample (keyed
            # on the fence mask the round BEGAN under)
            self._note_fused_sample("chain", pending.pad,
                                    time.perf_counter()
                                    - pending.t_chain, pending.fkey)

    @_locked
    def _append_and_replay(self, ops: list[tuple], rid: int,
                           tids: list[int], batch: bool = False) -> None:
        """Shared combiner-round tail (one protocol, every caller):
        `_begin_round` + `_finish_round` back-to-back. `combine`, the
        batch entry points, and nothing else — serve-path,
        split-round, and thread-context rounds cannot diverge because
        they all run this composition (the serve pipeline merely
        spreads the two halves across its stages)."""
        self._finish_round(
            self._begin_round(ops, rid, tids, batch=batch)
        )

    @_locked
    def _drop_batch_inflight(self, rid: int) -> None:
        """Failed-batch hygiene: appended ops stay in the log (they
        WILL replay — the log is the source of truth), but their
        responses are undeliverable. Drop this batch's pending
        deliveries and reset the sink so the NEXT batch's responses
        cannot be prefixed with stale replies."""
        self._inflight[rid] = deque(
            (p, t) for p, t in self._inflight[rid]
            if t != BATCH_TID
        )
        self._contexts[(rid, BATCH_TID)].reset()

    @_locked
    def begin_mut_batch(self, ops: list[tuple],
                        rid: int = 0) -> _PendingRound:
        """Split-round batch entry, first half (the serve pipeline's
        assembly stage, `serve/frontend.py`): GC-wait, encode, append,
        journal — everything up to (not including) this replica's
        replay-to-target, which `finish_mut_batch` completes. On the
        fused tier the kernel (append+replay+response gather in one
        launch) is already ISSUED when this returns; only the readback
        waits — so the whole device round overlaps whatever host work
        the caller does before `finish`.

        At most ONE begun-but-unfinished round per replica
        (`RuntimeError` otherwise): a second in-flight round would
        interleave response delivery and make post-append failure
        attribution (`maybe_executed`) ambiguous — that invariant is
        why the serve pipeline's overlap depth is capped at 1.

        Failure semantics: a raise out of `begin` is pre-append only
        when it is the fence guard or an append-site injection
        (`FaultError(site='append')`) — both fire before the batch
        reaches the log; anything later (WAL journal failure) is
        post-append. A raise out of `finish` is always post-append:
        the ops are in the log and WILL replay, only responses are
        lost."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        if self._pending_batch.get(rid) is not None:
            raise RuntimeError(
                f"replica {rid} already has a round in flight; "
                f"finish_mut_batch it before beginning another "
                f"(at most one split round per replica)"
            )
        n = len(ops)
        sink = self._contexts.get((rid, BATCH_TID))
        if sink is None:
            sink = _BatchSink()
            self._contexts[(rid, BATCH_TID)] = sink
        if n == 0:
            pending = _PendingRound(rid, [], 0, int(self.log.tail),
                                    batch=True)
            pending.done = True
            self._pending_batch[rid] = pending
            return pending
        sink.expect(n)
        try:
            pending = self._begin_round(
                list(ops), rid, [BATCH_TID] * n, batch=True,
                defer=True,
            )
        except BaseException:
            self._drop_batch_inflight(rid)
            raise
        self._pending_batch[rid] = pending
        return pending

    @_locked
    def finish_mut_batch(self, pending: _PendingRound) -> list:
        """Split-round batch entry, second half (the serve pipeline's
        completion stage): replay to the round's target (or read back
        the fused launch), collect the responses, release the
        replica's in-flight slot. Responses come back in op order.
        `pending` must be the replica's registered in-flight round
        (`begin_mut_batch`'s return value, finished exactly once)."""
        rid = pending.rid
        if self._pending_batch.get(rid) is not pending:
            raise RuntimeError(
                f"pending round for replica {rid} is not this "
                f"replica's in-flight round (already finished?)"
            )
        sink = self._contexts[(rid, BATCH_TID)]
        try:
            self._finish_round(pending)
            resps = sink.take()
            assert len(resps) == pending.n, (len(resps), pending.n)
            return resps
        except BaseException:
            self._drop_batch_inflight(rid)
            raise
        finally:
            self._pending_batch.pop(rid, None)

    @_locked
    def abort_mut_batch(self, pending: _PendingRound) -> None:
        """Abandon a begun-but-unfinished split round (the serve
        pipeline's failover teardown): its ops are in the log — they
        WILL replay, the log is the source of truth — but their
        responses are undeliverable, so the batch's pending deliveries
        drop (`_drop_batch_inflight`) and the replica's in-flight slot
        releases. Idempotent; a no-op for a round already finished or
        already torn down (e.g. by `fence_replica`'s crash
        semantics)."""
        rid = pending.rid
        if self._pending_batch.get(rid) is not pending:
            return
        self._pending_batch.pop(rid, None)
        pending.done = True
        pending.fused_resps = None
        self._drop_batch_inflight(rid)

    @_locked
    def execute_mut_batch(self, ops: list[tuple],
                          rid: int = 0) -> list:
        """Execute a caller-assembled batch of write ops as ONE
        flat-combining round and return their responses in op order.

        The serve frontend's serial entry point (`serve/frontend.py`):
        the frontend's worker already holds a whole batch, so routing
        it through per-thread 32-slot contexts would just re-chunk it.
        This IS `begin_mut_batch` + `finish_mut_batch` back-to-back
        under one lock hold — the split-round protocol and the serial
        path cannot diverge because the serial path is the
        composition. One `encode_ops` + one append + one
        replay-to-target pass, sharing the combiner lock, GC helping
        loop, and response-delivery machinery with `combine`;
        responses collect through a dedicated `_BatchSink` keyed
        `(rid, BATCH_TID)` so concurrent per-thread contexts on the
        same replica keep their own deliveries.

        Interleaving with `execute_mut`/`enqueue_mut` from other OS
        threads is safe: the reentrant lock serializes rounds, and the
        shared `_inflight` deque orders deliveries by log position.
        """
        return self.finish_mut_batch(self.begin_mut_batch(ops, rid))

    @_locked
    def sync(self, rid: int | None = None) -> None:
        """Catch replicas up with the log tail (`Replica::sync`,
        `nr/src/replica.rs:469-479`); `rid=None` syncs all UNFENCED
        replicas (a fenced replica's replay is frozen — waiting on it
        would never terminate; syncing it explicitly fails fast).

        On a mesh, a large uniform backlog takes the RING tier first
        (`_ring_catchup` — `parallel/collectives.py:make_ring_exec`):
        the pending window shards over the chips and chunks rotate the
        ICI ring while replica shards stay resident, so catch-up
        bandwidth scales with the mesh instead of one chip's replay
        rate. Falls back to ordinary exec rounds for the remainder."""
        if rid is not None and self._is_fenced(rid):
            raise ReplicaFencedError(rid)
        rounds = 0
        while True:
            ltails = np.asarray(self.log.ltails)
            tail = int(self.log.tail)
            if rid is None:
                live = (
                    ltails if self._fenced is None
                    else ltails[~self._fenced]
                )
                done = all(int(lt) >= tail for lt in live)
            else:
                done = int(ltails[rid]) >= tail
            if done:
                return
            if self._ring_catchup():
                continue  # made >= shard-count positions of progress
            self._exec_round()
            rounds = self._watchdog(rounds, "sync")

    @_locked
    def _ring_catchup(self) -> bool:
        """One ring-replay pass over the pending window — the mesh
        catch-up tier (`nr.exec.engine.ring` counter). Eligible only
        when it is provably equivalent to the scan rounds it replaces:
        a mesh is placed, no replica is fenced (the ring applies the
        window to EVERY shard), no in-flight responses are owed (the
        ring produces none — the reference's catch-up likewise applies
        other replicas' entries without delivering their responses),
        and every cursor sits at the same position (one shared window).
        Applies `chunk * shards` entries in log order to all replicas
        (bit-identical to the scan by the ring-schedule contract,
        tests/test_collectives.py) and joins the cursor lattice
        host-side. Returns False when ineligible; progress when True
        is >= 2*shards positions, so callers cannot livelock on it."""
        if self.mesh is None or self._mesh_tier == "gspmd":
            return False
        if self._fenced is not None or any(self._inflight):
            return False
        cur = np.asarray(
            jnp.concatenate([self.log.ltails, self.log.tail[None]])
        ).copy()
        lts, tail = cur[:-1], int(cur[-1])
        lt = int(lts.min())
        if int(lts.max()) != lt:
            return False
        shards = self._mesh_shards
        pending = tail - lt
        if shards < 2 or pending < 2 * shards:
            return False
        # power-of-two per-chip chunk bounded by exec_window: bounds
        # the per-window jit specializations (one per distinct W,
        # keyed by the static `window` argument) to log2 widths
        chunk = min(self.exec_window,
                    1 << ((pending // shards).bit_length() - 1))
        W = chunk * shards
        if self._ring_gather is None:
            self._ring_gather = jax.jit(
                partial(gather_window, self.spec),
                static_argnames=("window",),
            )
        opc, args = self._ring_gather(self.log.opcodes, self.log.args,
                                      jnp.int64(lt), jnp.int64(tail),
                                      window=W)
        from jax.sharding import NamedSharding, PartitionSpec

        sh = NamedSharding(self.mesh, PartitionSpec("replica"))
        opc = jax.device_put(opc, sh)
        args = jax.device_put(args, sh)
        if self._ring_fn is None:
            from node_replication_tpu.parallel.collectives import (
                make_ring_exec,
            )

            self._ring_fn = make_ring_exec(self.dispatch, self.mesh)
        with span("ring-exec", window=W, chunk=chunk,
                  shards=shards, start=lt) as sp:
            self.states = self._ring_fn(opc, args, self.states)
            sp.fence(self.states)
        # cursor-lattice join, host-side: every replica consumed
        # [lt, lt+W) in order, so ltails/ctail/head land at lt+W
        # (head = min(ltails); no fenced mask here by eligibility)
        new_lt = lt + W
        self.log = self.log._replace(
            ltails=jax.device_put(
                np.full(self.n_replicas, new_lt, np.int64), sh
            ),
            ctail=jnp.maximum(self.log.ctail, jnp.int64(new_lt)),
            head=jnp.int64(new_lt),
        )
        if self._wal is not None:
            self._wal.maybe_reclaim(new_lt)
        self._ring_rounds += 1
        self._m_ring.inc()
        # rotated-window ICI traffic: each chip forwards its chunk
        # around the ring (2*shards - 1 hops of W/shards entries ≈ 2x
        # the window) — counted once per pass, documented estimate
        self._m_mesh_sync_bytes.inc(2 * (opc.nbytes + args.nbytes))
        return True

    @_locked
    def checkpoint(self, path: str) -> None:
        """Durable snapshot of log + all replica states (see
        `core/checkpoint.py`; the recovery model is deterministic-init +
        replay, SURVEY.md §5)."""
        from node_replication_tpu.core.checkpoint import save_snapshot

        save_snapshot(path, self.spec, self.log, self.states)

    @classmethod
    def restore(cls, path: str, dispatch: Dispatch,
                **kwargs) -> "NodeReplicated":
        """Rebuild a NodeReplicated from a snapshot. Thread registrations
        are not part of a snapshot (tokens are process-local, like the
        reference's !Send ReplicaToken); re-register after restore."""
        from node_replication_tpu.core.checkpoint import (
            load_snapshot,
            peek_spec,
        )

        spec = peek_spec(path)
        nr = cls(dispatch, n_replicas=spec.n_replicas,
                 log_entries=spec.capacity, gc_slack=spec.gc_slack,
                 **kwargs)
        _, nr.log, nr.states = load_snapshot(path, nr.states)
        nr._place_on_mesh()  # loaded arrays land on the default device
        return nr

    @_locked
    def recover(self, base_states=None, base_pos: int | None = None) -> None:
        """Discard replica states and rebuild them by replay
        (deterministic-init + replay — the reference's recovery model,
        SURVEY.md §5). Without a base, replay starts at position 0, which
        requires `tail <= capacity` (no slot overwritten yet); a
        long-running instance passes `base_states`/`base_pos` from a
        `checkpoint()` snapshot instead. In-flight responses are lost,
        matching a crash."""
        from node_replication_tpu.core.checkpoint import recover_states

        self.log, self.states = recover_states(
            self.dispatch, self.spec, self.log,
            base_states=base_states, base_pos=base_pos,
            window=self.exec_window,
        )
        self._place_on_mesh()  # rebuilt states: restore the shardings
        self._inflight = [deque() for _ in range(self.n_replicas)]
        # crash semantics: begun-but-unfinished split rounds die with
        # the rebuild (their ops are in the log and replayed; the
        # responses are gone, like every other in-flight delivery)
        self._pending_batch.clear()
        # full-fleet rebuild: every replica is freshly consistent, so
        # any quarantine fencing is moot
        self._fenced = None

    @_locked
    def stats(self) -> dict:
        """Flat observability counters (the harness's per-second ops
        capture is the reference's profiling story,
        `benches/mkbench.rs:755-761`). The original five keys are stable;
        `snapshot()` is the structured superset."""
        ltails = np.asarray(self.log.ltails)
        tail = int(self.log.tail)
        return {
            "appended": tail,
            "head": int(self.log.head),
            "ctail": int(self.log.ctail),
            "min_ltail": int(ltails.min()),
            "exec_rounds": self._exec_rounds,
            "idle_rounds": self._idle_rounds,
            "ring_rounds": self._ring_rounds,
            "engine": self.engine,
            "fused_rounds": self._fused_rounds,
            "fused_tier": self._fused_tier_state(),
            "mesh_devices": self._mesh_shards,
            "max_lag": tail - int(ltails.min()),
        }

    @_locked
    def snapshot(self) -> dict:
        """Structured observability snapshot (JSON-safe): log cursors and
        ring occupancy, per-replica lag (`tail - ltails[r]`), exec-round
        progress vs. idle skips, in-flight response depths, and the
        process-wide metrics registry view when enabled. One host
        readback of the cursor arrays; safe to call on a live instance.
        """
        ltails = np.asarray(self.log.ltails)
        tail = int(self.log.tail)
        head = int(self.log.head)
        lags = [tail - int(lt) for lt in ltails]
        return {
            "log": {
                "tail": tail,
                "head": head,
                "ctail": int(self.log.ctail),
                "capacity": self.spec.capacity,
                # append occupancy: live entries held against GC slack
                "occupancy": (tail - head) / self.spec.capacity,
                "space": int(log_space(self.spec, self.log)),
            },
            "replicas": {
                "n": self.n_replicas,
                "ltails": [int(lt) for lt in ltails],
                "lag": lags,
                "max_lag": max(lags) if lags else 0,
                "threads": list(self._threads_per_replica),
                "inflight": [len(q) for q in self._inflight],
                "fenced": self.fenced_rids,
            },
            "exec": {
                "engine": self.engine,
                "window": self.exec_window,
                "rounds": self._exec_rounds,
                "idle_rounds": self._idle_rounds,
                "ring_rounds": self._ring_rounds,
                "fused_rounds": self._fused_rounds,
                "fused_tier": self._fused_tier_state(),
            },
            "mesh": (
                # shard shape only: a per-rid device dict would be
                # O(R) reshapes + strings per snapshot poll at fleet
                # scale (R=4096) — per-rid lookup is replica_device()
                None if self.mesh is None else {
                    "devices": self._mesh_shards,
                    "tier": self._mesh_tier,
                    "replicas_per_device":
                        self.n_replicas // self._mesh_shards,
                }
            ),
            "metrics": get_registry().snapshot(),
        }

    @_locked
    def verify(self, fn: Callable[[Any], Any], rid: int = 0):
        """Test hook (`Replica::verify`, `nr/src/replica.rs:443-467`):
        force-sync, then expose replica `rid`'s state (as host numpy pytree)
        to `fn` for assertions."""
        self.sync()
        state = jax.tree.map(lambda a: np.asarray(a[rid]), self.states)
        return fn(state)

    @_locked
    def replicas_equal(self) -> bool:
        """All replicas converged to identical state."""
        return states_equal(self.states)

    # ------------------------------------------------------------ internals

    def _append_call(self, opcodes, args, n):
        if self.debug:
            from node_replication_tpu.utils.checks import debug_checks

            with debug_checks(True):  # checks live at (re-)trace time
                err, log = self._append_jit(self.log, opcodes, args, n)
            err.throw()
            return log
        return self._append_jit(self.log, opcodes, args, n)

    @_locked
    def _exec_round(self) -> bool:
        """One static-window replay round for every replica, plus response
        distribution. Returns True if any replica made progress.

        Idle short-circuit (ADVICE r5): when every replica is already at
        the tail there is nothing to replay, so the device round — a full
        sort+merge on the combined engine — is skipped entirely with a
        host-side cursor check. Empty-combine "help" calls and read-sync
        polling hit this constantly; the skip is counted in the
        `idle_rounds` stat / `nr.exec.idle_rounds` metric. Every caller
        loops on a cursor condition that is already satisfied when
        `min(ltails) == tail` (target <= tail, ctail <= tail), so
        skipping cannot livelock.
        """
        fault_hook("replay", -1, self)
        fenced = self._fenced
        # one fused cursor readback (ltails + tail): on the tunneled TPU
        # platform each D2H costs an ~100ms RTT, so two serial fetches
        # would double every round's host-sync latency
        cur = np.asarray(
            jnp.concatenate([self.log.ltails, self.log.tail[None]])
        ).copy()
        ltails_before, tail = cur[:-1], int(cur[-1])
        # skip only when EVERY live cursor sits exactly at the tail: for
        # valid states min==tail implies that already (ltails <= tail),
        # and the max bound keeps a corrupted ltail > tail falling
        # through to the device round so debug-mode invariants still
        # fire on it. Fenced cursors are frozen and don't count — but a
        # freshly fenced laggard may still pin the GC head below the
        # live min, and only a device round advances head, so the skip
        # additionally requires head to have caught up.
        live = (
            ltails_before if fenced is None
            else ltails_before[~fenced]
        )
        idle = bool(
            live.size
            and int(live.min()) >= tail
            and int(live.max()) <= tail
        )
        if idle and fenced is not None:
            idle = int(np.asarray(self.log.head)) >= int(live.min())
        if idle:
            self._idle_rounds += 1
            self._m_idle.inc()
            return False
        self._exec_rounds += 1
        self._m_rounds.inc()
        self._m_engine.inc()
        tracer = get_tracer()
        # manual span: the hot path pays one branch when tracing is off
        # (no context-manager frame, no clock read); mesh rounds always
        # time — the collective-time histogram is part of the mesh.*
        # observability contract
        t0 = (
            time.perf_counter()
            if (tracer.enabled or self.mesh is not None) else 0.0
        )
        f_arr = None if fenced is None else jnp.asarray(fenced)
        if self.debug:
            from node_replication_tpu.utils.checks import debug_checks

            with debug_checks(True):  # checks live at (re-)trace time
                if f_arr is None:
                    err, (self.log, self.states, resps) = self._exec_jit(
                        self.log, self.states, window=self.exec_window
                    )
                else:
                    err, (self.log, self.states, resps) = (
                        self._exec_fenced_jit(
                            self.log, self.states, f_arr,
                            window=self.exec_window,
                        )
                    )
            err.throw()
        elif f_arr is None:
            self.log, self.states, resps = self._exec_jit(
                self.log, self.states, window=self.exec_window
            )
        else:
            self.log, self.states, resps = self._exec_fenced_jit(
                self.log, self.states, f_arr, window=self.exec_window
            )
        ltails_after = np.asarray(self.log.ltails)
        # worst remaining lag after this round (tail is fixed across the
        # round: replay never appends); one observe, values already host
        self._m_lag.observe(tail - int(ltails_after.min()))
        if self._wal is not None:
            # GC/head coupling (`durable/wal.py`): min(ltails) is the
            # head this round just computed (<= head under fencing —
            # an under-estimate only ever under-reclaims); O(1) when
            # no whole segment has fallen below the floor
            self._wal.maybe_reclaim(int(ltails_after.min()))
        resps_np = np.asarray(resps)
        for r in range(self.n_replicas):
            q = self._inflight[r]
            while q and q[0][0] < int(ltails_after[r]):
                pos, tid = q.popleft()
                self._contexts[(r, tid)].enqueue_resps(
                    [int(resps_np[r, pos - int(ltails_before[r])])]
                )
        progressed = bool(np.any(ltails_after > ltails_before))
        sync_bytes = 0
        if self.mesh is not None:
            # mesh.* observability: rounds by tier, collective/round
            # time, and the cross-device bytes this round FORCED back
            # to the host (response matrix + the two cursor readbacks —
            # the measurable gather traffic; the on-ICI lattice
            # reductions are a few scalars on top)
            sync_bytes = resps_np.nbytes + cur.nbytes + \
                ltails_after.nbytes
            self._m_mesh_round.inc()
            self._m_mesh_dur.observe(time.perf_counter() - t0)
            self._m_mesh_sync_bytes.inc(sync_bytes)
        if tracer.enabled:
            if tracer.fence_spans:
                # device-honest end: block_until_ready returns at
                # enqueue-ack on the tunneled platform (utils/fence.py)
                from node_replication_tpu.utils.fence import fence

                fence(self.log, self.states)
            extra = (
                {"mesh_tier": self._mesh_tier,
                 "mesh_devices": self._mesh_shards,
                 "sync_bytes": sync_bytes}
                if self.mesh is not None else {}
            )
            tracer.emit(
                "exec-round",
                duration_s=time.perf_counter() - t0,
                fenced=tracer.fence_spans,
                engine=self.engine,
                window=self.exec_window,
                progressed=progressed,
                advanced=int((ltails_after - ltails_before).sum()),
                **extra,
            )
        return progressed

    def _watchdog(self, rounds: int, where: str) -> int:
        rounds += 1
        # Re-warn every WARN_ROUNDS, not once: the reference's spin
        # diagnostics fire every WARN_THRESHOLD iterations forever
        # (`nr/src/log.rs:43`, `351-358`) so a genuinely stuck run stays
        # loud (VERDICT r1 weak #4).
        if rounds % WARN_ROUNDS == 0:
            self._m_stalls.inc()
            dormant = int(np.argmin(np.asarray(self.log.ltails)))
            ltail = int(np.asarray(self.log.ltails)[dormant])
            tail = int(self.log.tail)
            logger.warning(
                "replay stalled in %s after %d rounds; most dormant "
                "replica=%d (ltail=%d, tail=%d)",
                where, rounds, dormant, ltail, tail,
            )
            get_tracer().emit(
                "watchdog", where=where, rounds=rounds, dormant=dormant,
                ltail=ltail, tail=tail,
            )
            if self.gc_callback is not None:
                self.gc_callback(0, dormant)
        return rounds
