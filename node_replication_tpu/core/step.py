"""Fused append→replay→read step: the jit-hot batch path.

This is the TPU answer to the reference's whole write+read pipeline
(`nr/src/replica.rs:345-356` staging → `nr/src/log.rs:343-427` append →
`nr/src/log.rs:473-524` replay → `nr/src/replica.rs:483-497` read): one
compiled XLA program per step that

1. concatenates every replica's write batch in replica-major order — the
   linearization point; the batched substitute for per-combiner CAS tail
   reservations (offsets are a static prefix sum since batches are
   fixed-shape),
2. appends the combined batch to the device-resident log,
3. replays the exact appended window into all replicas (vmapped scan),
4. answers each replica's read batch against its own post-replay state —
   read-your-writes holds by construction, which is precisely the
   `ltail >= ctail` read gate of the reference in lock-step form.

Precondition: all replicas are synced (`ltails == tail`) when the step
begins AND hold identical states — both true by induction since every
replica replays exactly what the fused step appends, from identical
init. The combined engines lean on this: `window_plan` (stack, queue)
computes the window's sorts ONCE from replica 0 and would silently
impose replica 0's results on a hand-built fleet with divergent
buffers. Use `NodeReplicated` when replicas drift — its catch-up replay
takes the scan path.

The returned step function is pure and shape-stable, so it can be jitted
with sharding annotations (see `node_replication_tpu/parallel/mesh.py`) to
run the replica axis across a TPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from node_replication_tpu.core.log import (
    LogSpec,
    gather_window,
    log_append,
    log_exec_all,
)
from node_replication_tpu.ops.encoding import Dispatch, dispatch_reads


def make_step(
    dispatch: Dispatch,
    spec: LogSpec,
    writes_per_replica: int,
    reads_per_replica: int,
    jit: bool = True,
    donate: bool = True,
    combined: bool | None = None,
    check_lockstep: bool | None = None,
):
    """Build `step(log, states, wr_opcodes, wr_args, rd_opcodes, rd_args)`.

    Shapes (R = spec.n_replicas, Bw/Br = writes/reads per replica,
    A = spec.arg_width):

      wr_opcodes int32[R, Bw], wr_args int32[R, Bw, A]
      rd_opcodes int32[R, Br], rd_args int32[R, Br, A]

    Returns `(log, states, wr_resps int32[R, Bw], rd_resps int32[R, Br])`
    where `wr_resps[r, j]` answers replica r's j-th write (produced by r's
    own replay of its own entry — the reference's response-distribution
    contract, `nr/src/replica.rs:584-594`) and `rd_resps[r, j]` answers its
    j-th read. NOOP-padded slots answer 0.

    `combined` selects the replay engine: True = the model's
    `Dispatch.window_apply` combined replay (one parallel reduction per
    window instead of a W-long sequential scan; bit-identical semantics),
    False = the generic vmapped scan, None (default) = combined when the
    model provides it. Both read the window back from the ring, so the
    log remains the source of truth either way.

    `check_lockstep` guards the combined engines' precondition at
    runtime: when True (or env NR_TPU_CHECK_LOCKSTEP=1 with the default
    None), a combined step verifies cursors are synced on entry — both
    combined branches replay only the appended span and then force
    `ltails = tail` — and the plan/merge split additionally verifies
    every replica's state bit-equals replica 0's before imposing
    replica-0's plan; violations RAISE (via checkify) instead of
    silently corrupting state. Costs one checkify wrap + an R-way
    equality reduce per step; off by default for the hot path.
    """
    R = spec.n_replicas
    Bw = int(writes_per_replica)
    Br = int(reads_per_replica)
    span = R * Bw
    max_batch = spec.capacity - spec.gc_slack
    if span > max_batch:
        raise ValueError(
            f"step appends {span} entries but log fits {max_batch}; "
            f"grow LogSpec.capacity or shrink the per-step batch"
        )
    if (dispatch.window_plan is None) != (dispatch.window_merge is None):
        raise ValueError(
            f"{dispatch.name}: window_plan and window_merge come as a "
            f"pair (got only one)"
        )
    has_combined = (
        dispatch.window_apply is not None
        or dispatch.window_plan is not None
    )
    if combined is None:
        combined = has_combined
    if combined and not has_combined:
        raise ValueError(
            f"combined=True but {dispatch.name} has no window_apply "
            f"or window_plan/window_merge"
        )
    if check_lockstep is None:
        import os

        check_lockstep = os.environ.get("NR_TPU_CHECK_LOCKSTEP", "") == "1"
    # both combined branches replay only the just-appended span and then
    # force ltails = tail, so BOTH require synced cursors on entry; the
    # plan/merge split additionally imposes replica-0's plan, so it also
    # requires bit-identical states
    guard_combined = bool(check_lockstep and combined)
    guard_plan = guard_combined and dispatch.window_plan is not None
    if guard_combined:
        from jax.experimental import checkify

    def step(log, states, wr_opcodes, wr_args, rd_opcodes, rd_args):
        if guard_combined:
            ok = jnp.all(log.ltails == log.tail)
            msg = ("combined step requires synced cursors "
                   "(ltails == tail)")
            if guard_plan:
                for leaf in jax.tree.leaves(states):
                    ok = ok & jnp.all(leaf == leaf[:1])
                msg = ("plan/merge fast path requires a lock-step fleet "
                       "(synced cursors + identical replica states)")
            # deliberately ALWAYS armed: this guard is locally
            # checkify.checkify-wrapped below, independent of the
            # debug_checks() arming contract
            # nrlint: disable=raw-checkify-check
            checkify.check(
                ok,
                msg + "; use combined=False or NodeReplicated catch-up "
                "for divergent fleets",
            )
        # 1-2. replica-major concatenation + one batched append.
        log = log_append(
            spec,
            log,
            wr_opcodes.reshape(span),
            wr_args.reshape(span, spec.arg_width),
            span,
        )
        # 3. replay exactly the appended window into every replica.
        if combined and span == 0:
            # read-only step: nothing appended, nothing to replay
            resps = jnp.zeros((R, 0), jnp.int32)
        elif combined:
            # combined replay: gather the appended window from the ring
            # and apply it as one reduction per replica (vmap keeps the
            # window-wide sort unbatched — it is shared by the fleet)
            opc_w, args_w = gather_window(
                spec, log.opcodes, log.args, log.tail - span, log.tail,
                span,
            )
            if dispatch.window_plan is not None:
                # plan/merge split: the sorts+scans run ONCE on a
                # representative replica (sound by the lock-step
                # precondition above — states are identical by
                # induction); the vmapped merge does the per-replica
                # dense replay work
                plan = dispatch.window_plan(
                    jax.tree.map(lambda x: x[0], states), opc_w, args_w
                )
                states, resps = jax.vmap(
                    lambda s: dispatch.window_merge(s, plan)
                )(states)
            else:
                states, resps = jax.vmap(
                    lambda s: dispatch.window_apply(s, opc_w, args_w)
                )(states)
            # lock-step cursor bookkeeping (every replica consumed the
            # span): same lattice updates as log_exec_all
            new_ltails = jnp.broadcast_to(log.tail, (R,))
            log = log._replace(
                ltails=new_ltails, ctail=log.tail, head=log.tail
            )
        else:
            log, states, resps = log_exec_all(
                spec, dispatch, log, states, span
            )
        # Replica r's own writes sit at window offsets [r*Bw, (r+1)*Bw).
        own = jnp.arange(R, dtype=jnp.int32)[:, None] * Bw + jnp.arange(
            Bw, dtype=jnp.int32
        )[None, :]
        wr_resps = jnp.take_along_axis(resps, own, axis=1)
        # 4. per-replica read batches against post-replay local state.
        rd_resps = dispatch_reads(dispatch, states, rd_opcodes, rd_args)
        return log, states, wr_resps, rd_resps

    if guard_combined:
        inner = checkify.checkify(step)
        if jit:
            inner = jax.jit(inner, donate_argnums=(0, 1) if donate else ())

        def checked_step(*args):
            err, out = inner(*args)
            err.throw()
            return out

        return checked_step
    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step
