"""Multi-log node replication: the CNR (`cnr` crate) equivalent.

The reference's `cnr` partitions the operation stream over many logs by a
commutativity hash (`LogMapper`, `cnr/src/lib.rs:123-137`): conflicting ops
must map to the same log; commutative ops may map to different logs and are
then combined/replayed in parallel by per-log combiners
(`cnr/src/replica.rs:93-98`, `430-445`).

TPU-first re-design (SURVEY.md §7 "CNR"):

- The L logs are one stacked `LogState` with a leading log axis
  (`opcodes: int32[L, C]`, cursors `[L]`, `ltails: [L, R]`) — a pytree that
  shards naturally over a `Mesh` 'log' axis (the tensor/expert-parallel
  analog of the op stream, SURVEY.md §2.5 #3).
- `LogMapper` is a host-side function `(opcode, args) -> hash`; the hash is
  reduced `% nlogs` exactly as `cnr/src/replica.rs:435`.
- Per-log combiner locks disappear (lock-step); what survives is that each
  log gets its own independent append batch and its own replay scan —
  `vmap` over the log axis replaces parallel combiner threads, and
  dispatch against shared replica state must be commutative across logs
  within a step (the same contract `dispatch_mut(&self)` demands of the
  user's concurrent DS, `cnr/src/lib.rs:167`).
- Reads sync only their mapped log (`cnr/src/replica.rs:599-617`);
  `sync_log` targets one log (`cnr/src/replica.rs:579-597`).

Mesh placement: `MultiLogState` is the pytree `parallel/mesh.py:place`
shards over a ('replica', 'log') mesh — rings and per-log cursors on
their 'log' column, replica states (and the ltails replica dimension)
over 'replica' rows — and every exec path here is sharding-agnostic:
`MultiLogReplicated(mesh=...)` and `ShardedCnrRunner` run these same
programs with GSPMD inserting the collectives (the annotation tier;
tests/test_mesh_fleet.py pins the wrapper bit-identical to the
un-meshed twin).

Replay layout: `multilog_exec_all` vmaps the single-log scan over
(log × replica). Because ops on different logs commute by contract, applying
each log's span to disjoint *state partitions* is exact. The bundled
partitioned models (`models/partitioned.py`, `PartitionedModel`) provide
`split`/`merge` reshapes plus a per-partition sub-Dispatch, so all L scans
run as ONE vmapped computation — the parallel-combining payoff. For
monolithic states the replay falls back to sequential per-log folding.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from node_replication_tpu.core.log import LogSpec, gather_window
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.ops.encoding import (
    Dispatch,
    NOOP,
    apply_write,
    dispatch_reads,
)
from node_replication_tpu.utils.checks import check

PyTree = Any

# Multi-log replay-engine selection counters (host-side of the tier
# decision in `multilog_exec_all`; under jit they count per trace —
# see the `log.engine.*` note in core/log.py).
_m_ml_lockstep = get_registry().counter("multilog.engine.combined_lockstep")
_m_ml_combined = get_registry().counter("multilog.engine.combined")
_m_ml_part_scan = get_registry().counter("multilog.engine.partitioned_scan")
_m_ml_seq = get_registry().counter("multilog.engine.sequential")

# LogMapper: host-side commutativity hash (`cnr/src/lib.rs:123-137`).
LogMapper = Callable[[int, tuple], int]


@dataclasses.dataclass(frozen=True)
class MultiLogSpec:
    """Static config for a stacked multi-log (hashable jit static)."""

    nlogs: int
    capacity: int = 1 << 14
    n_replicas: int = 1
    arg_width: int = 3
    gc_slack: int = 1024

    def __post_init__(self):
        cap = max(int(self.capacity), 2 * self.gc_slack)
        cap = 1 << (cap - 1).bit_length()
        object.__setattr__(self, "capacity", cap)
        if self.nlogs < 1:
            raise ValueError("need at least one log")

    @property
    def mask(self) -> int:
        return self.capacity - 1

    def one_log(self) -> LogSpec:
        return LogSpec(
            capacity=self.capacity,
            n_replicas=self.n_replicas,
            arg_width=self.arg_width,
            gc_slack=self.gc_slack,
        )


class MultiLogState(NamedTuple):
    """L stacked rings; every cursor grows a leading log axis.

    Mirrors `cnr`'s `slog: Vec<Arc<Log>>` + per-log registration
    (`cnr/src/replica.rs:93-98`) as one shardable pytree.
    """

    opcodes: jax.Array  # int32[L, C]
    args: jax.Array  # int32[L, C, A]
    head: jax.Array  # int64[L]
    tail: jax.Array  # int64[L]
    ctail: jax.Array  # int64[L]
    ltails: jax.Array  # int64[L, R]


def multilog_init(spec: MultiLogSpec) -> MultiLogState:
    L, C = spec.nlogs, spec.capacity
    return MultiLogState(
        opcodes=jnp.full((L, C), NOOP, jnp.int32),
        args=jnp.zeros((L, C, spec.arg_width), jnp.int32),
        head=jnp.zeros((L,), jnp.int64),
        tail=jnp.zeros((L,), jnp.int64),
        ctail=jnp.zeros((L,), jnp.int64),
        ltails=jnp.zeros((L, spec.n_replicas), jnp.int64),
    )


def multilog_space(spec: MultiLogSpec, ml: MultiLogState) -> jax.Array:
    return jnp.maximum(
        spec.capacity - spec.gc_slack - (ml.tail - ml.head), 0
    )


def multilog_append(
    spec: MultiLogSpec,
    ml: MultiLogState,
    opcodes: jax.Array,  # int32[L, B] — already partitioned per log
    args: jax.Array,  # int32[L, B, A]
    counts: jax.Array,  # int64[L] — valid prefix per log
) -> MultiLogState:
    """Per-log batched append (each log's combiner append,
    `cnr/src/replica.rs:708`, vectorized over the log axis)."""
    B = opcodes.shape[1]
    lanes = jnp.arange(B, dtype=jnp.int64)[None, :]
    counts = jnp.asarray(counts, jnp.int64)
    valid = lanes < counts[:, None]
    slot = jnp.where(
        valid, (ml.tail[:, None] + lanes) & spec.mask, spec.capacity
    ).astype(jnp.int32)

    def scatter_one(ring, slots, vals):
        return ring.at[slots].set(vals, mode="drop")

    return ml._replace(
        opcodes=jax.vmap(scatter_one)(ml.opcodes, slot, opcodes),
        args=jax.vmap(scatter_one)(ml.args, slot, args),
        tail=ml.tail + counts,
    )


def _exec_one_log(spec, d, opcodes_ring, args_ring, tail, state, ltail,
                  window: int):
    """Single (log, replica) replay scan — same algorithm as
    `core/log.py:_exec_one` over one ring of the stack."""

    def body(state, j):
        pos = ltail + j
        active = pos < tail
        idx = (pos & spec.mask).astype(jnp.int32)
        opcode = jnp.where(active, opcodes_ring[idx], NOOP)
        state, resp = apply_write(d, state, opcode, args_ring[idx])
        return state, resp

    state, resps = lax.scan(body, state, jnp.arange(window, dtype=jnp.int64))
    return state, resps, jnp.minimum(ltail + window, tail)


def _exec_one_log_combined(spec, d, opcodes_ring, args_ring, tail, state,
                           ltail, window: int):
    """Combined twin of `_exec_one_log`: gather the pending window from
    the ring (positions past `tail` mask to NOOP — inactive under
    `window_apply`) and apply it as one reduction (`Dispatch.window_apply`
    semantics; bit-identical to the scan)."""
    if window == 0:
        return state, jnp.zeros((0,), jnp.int32), ltail
    opcodes, args = gather_window(
        spec, opcodes_ring, args_ring, ltail, tail, window
    )
    state, resps = d.window_apply(state, opcodes, args)
    return state, resps, jnp.minimum(ltail + window, tail)


def multilog_exec_all(
    spec: MultiLogSpec,
    d: Dispatch,
    ml: MultiLogState,
    states: PyTree,
    window: int,
    partitioned: "PartitionedModel | None" = None,
    combined: bool | None = None,
    lockstep: bool = False,
):
    """Replay `window` pending entries of every log into every replica.

    With a `PartitionedModel` (`models/partitioned.py`) the L per-log scans
    run as ONE computation vmapped over (log × replica), each scan mutating
    only its disjoint state partition — the lock-step analog of L combiners
    replaying in parallel (`cnr/src/replica.rs:713-720`). Without it, logs
    fold sequentially per replica (still correct for any state; ops on
    different logs commute by the LogMapper contract so order is free).

    `combined` selects the per-(log, replica) replay engine when the
    partitioned sub-model provides `window_apply` (None = auto): each
    log's window collapses to one parallel reduction on its partition
    instead of a `window`-long scan — the multi-log form of the combined
    replay (`core/step.py`).

    `lockstep=True` declares the caller's precondition that every replica
    of a log starts at the same ltail (true inside `make_multilog_step`):
    the combined path then gathers each log's window ONCE (ltails[0]
    speaks for the fleet) and shares its sort across the replica vmap —
    without it the window (and its sort) is recomputed per (log, replica)
    because ltails are formally per-replica values. The precondition is
    verified only under debug checks (`utils/checks.check`, armed by
    `debug_checks(True)` around a `checked()` trace — zero-cost
    otherwise); an unchecked caller with divergent ltails silently gets
    ltails[0] imposed on all replicas (ADVICE r3).

    Returns `(ml, states, resps[L, R, window])`.
    """
    if partitioned is not None:
        if partitioned.nlogs != spec.nlogs:
            raise ValueError(
                f"PartitionedModel is {partitioned.nlogs}-way but the "
                f"multilog has {spec.nlogs} logs"
            )
        if combined is None:
            combined = partitioned.sub.window_apply is not None
        if combined and partitioned.sub.window_apply is None:
            raise ValueError(
                f"combined=True but {partitioned.sub.name} has no "
                f"window_apply"
            )
        exec_one = _exec_one_log_combined if combined else _exec_one_log
        # [R, ...] states → per-replica split → [R, L, sub...] → [L, R, ...]
        stacked = jax.vmap(partitioned.split)(states)
        stacked = jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1), stacked)

        if combined and lockstep and window > 0:
            # nrlint: disable=obs-in-traced — per-trace tier counter
            _m_ml_lockstep.inc()

            # lock-step: gather each log's window once (ltails[0] speaks
            # for the fleet) so the window-wide sort inside window_apply
            # stays UNBATCHED across the replica vmap
            def per_log(opc, arg, tail, sub_states, ltails):
                lt0 = ltails[0]
                check(
                    jnp.all(ltails == lt0),
                    "lockstep multilog replay requires equal per-replica "
                    "ltails on every log",
                )
                opc_w, args_w = gather_window(
                    spec, opc, arg, lt0, tail, window
                )
                new_states, resps = jax.vmap(
                    lambda s: partitioned.sub.window_apply(
                        s, opc_w, args_w
                    )
                )(sub_states)
                new_lt = jnp.minimum(lt0 + window, tail)
                return (
                    new_states,
                    resps,
                    jnp.broadcast_to(new_lt, ltails.shape),
                )
        else:
            # nrlint: disable=obs-in-traced — per-trace tier counter
            (_m_ml_combined if combined else _m_ml_part_scan).inc()

            def per_log(opc, arg, tail, sub_states, ltails):
                return jax.vmap(
                    lambda s, lt: exec_one(
                        spec, partitioned.sub, opc, arg, tail, s, lt,
                        window,
                    )
                )(sub_states, ltails)

        new_subs, resps, new_ltails = jax.vmap(per_log)(
            ml.opcodes, ml.args, ml.tail, stacked, ml.ltails
        )
        new_subs = jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1), new_subs)
        states = jax.vmap(partitioned.merge)(new_subs)
    else:
        # nrlint: disable=obs-in-traced — per-trace tier counter
        _m_ml_seq.inc()
        resps_list = []
        ltails_list = []
        for l in range(spec.nlogs):
            states, resps_l, lt_l = jax.vmap(
                lambda s, lt, _l=l: _exec_one_log(
                    spec, d, ml.opcodes[_l], ml.args[_l], ml.tail[_l],
                    s, lt, window,
                )
            )(states, ml.ltails[l])
            resps_list.append(resps_l)
            ltails_list.append(lt_l)
        resps = jnp.stack(resps_list)
        new_ltails = jnp.stack(ltails_list)

    ml = ml._replace(
        ltails=new_ltails,
        ctail=jnp.maximum(ml.ctail, jnp.max(new_ltails, axis=1)),
        head=jnp.min(new_ltails, axis=1),
    )
    return ml, states, resps


def is_log_synced_for_reads(
    ml: MultiLogState, log_idx: int, ridx: int, ctail: jax.Array
) -> jax.Array:
    """Reads sync only their mapped log (`cnr/src/replica.rs:599-617`)."""
    return ml.ltails[log_idx, ridx] >= ctail


def make_multilog_step(
    dispatch: Dispatch,
    spec: MultiLogSpec,
    writes_per_log: int,
    reads_per_replica: int,
    partitioned: "PartitionedModel | None" = None,
    jit: bool = True,
    donate: bool = True,
    combined: bool | None = None,
    debug: bool = False,
):
    """Fused CNR step: per-log append → per-log replay → reads.

    The batch is already LogMapper-partitioned (see `partition_ops`):
    `wr_opcodes int32[L, B]`, `wr_args int32[L, B, A]`, `counts int64[L]`.
    Each log appends its bucket and every replica replays every log's new
    span — the lock-step analog of L parallel combiners
    (`cnr/src/replica.rs:673-720`). Reads run after replay against local
    replica state (per-log read sync holds trivially).

    Returns `(ml, states, wr_resps int32[L, R, B], rd_resps int32[R, Br])`.
    Precondition: all replicas synced on all logs at entry (true by
    induction when driven step-after-step).

    `debug=True` compiles the device-side invariants (`utils/checks`,
    here the lockstep equal-ltails precondition) into the program via
    checkify and raises on violation — the `make_multilog_step` twin of
    `NodeReplicated(debug=True)`. Donation is disabled in debug mode.
    """
    B = int(writes_per_log)
    Br = int(reads_per_replica)
    max_batch = spec.capacity - spec.gc_slack
    if B > max_batch:
        raise ValueError(
            f"per-log batch {B} exceeds appendable capacity {max_batch}"
        )

    def step(ml, states, wr_opcodes, wr_args, counts, rd_opcodes, rd_args):
        ml = multilog_append(spec, ml, wr_opcodes, wr_args, counts)
        ml, states, wr_resps = multilog_exec_all(
            spec, dispatch, ml, states, B, partitioned=partitioned,
            combined=combined, lockstep=True,
        )
        rd_resps = dispatch_reads(dispatch, states, rd_opcodes, rd_args)
        return ml, states, wr_resps, rd_resps

    if debug:
        from node_replication_tpu.utils.checks import checked, debug_checks

        inner = checked(step)
        if jit:
            inner = jax.jit(inner)

        def step_checked(*args):
            with debug_checks(True):  # checks live at (re-)trace time
                err, out = inner(*args)
            err.throw()
            return out

        return step_checked
    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step


def partition_ops(
    mapper: LogMapper,
    nlogs: int,
    ops: list[tuple[int, tuple]],
    arg_width: int,
    pad_to: int | None = None,
):
    """Host-side LogMapper application: split an op list into per-log
    fixed-shape batches (`hash % nlogs`, `cnr/src/replica.rs:435`).

    Returns `(opcodes int32[L, B], args int32[L, B, A], counts int64[L],
    placements)` where `placements[i] = (log, slot)` for op i.
    """
    import numpy as np

    buckets: list[list[tuple[int, tuple]]] = [[] for _ in range(nlogs)]
    placements = []
    for opcode, args in ops:
        h = mapper(opcode, args) % nlogs
        placements.append((h, len(buckets[h])))
        buckets[h].append((opcode, args))
    B = pad_to if pad_to is not None else max(
        1, max(len(b) for b in buckets)
    )
    opcodes = np.full((nlogs, B), NOOP, np.int32)
    args_arr = np.zeros((nlogs, B, arg_width), np.int32)
    counts = np.zeros((nlogs,), np.int64)
    for l, bucket in enumerate(buckets):
        if len(bucket) > B:
            raise ValueError(f"log {l} bucket {len(bucket)} > pad {B}")
        counts[l] = len(bucket)
        for j, (opcode, a) in enumerate(bucket):
            opcodes[l, j] = opcode
            args_arr[l, j, : len(a)] = a
    return (
        jnp.asarray(opcodes),
        jnp.asarray(args_arr),
        jnp.asarray(counts),
        placements,
    )
