from node_replication_tpu.core.log import (
    DEFAULT_LOG_ENTRIES,
    GC_FROM_HEAD,
    LogSpec,
    LogState,
    is_replica_synced_for_reads,
    log_append,
    log_exec_all,
    log_init,
    log_reset,
    log_space,
)
from node_replication_tpu.core.replica import NodeReplicated, ReplicaToken
from node_replication_tpu.core.step import make_step

__all__ = [
    "DEFAULT_LOG_ENTRIES",
    "GC_FROM_HEAD",
    "LogSpec",
    "LogState",
    "is_replica_synced_for_reads",
    "log_append",
    "log_exec_all",
    "log_init",
    "log_reset",
    "log_space",
    "NodeReplicated",
    "ReplicaToken",
    "make_step",
]
