"""Checkpoint/resume and recovery-by-replay.

The reference has no checkpoint subsystem; its recovery model is structural
(SURVEY.md §5): replica state is reconstructable from a deterministic
`Default` by replaying the log from head — `Log::reset` exists only for
bench reuse (`nr/src/log.rs:582-611`) and `D: Default` is required
precisely so replay-from-scratch is well-defined
(`nr/examples/stack.rs:30-35`). This module makes both halves first-class
for the TPU build, where jobs are preempted routinely:

- `save_snapshot` / `load_snapshot` — durable npz snapshots of the log ring
  + cursors + replica states (numpy container: dependency-free and
  readable anywhere; swap in orbax for sharded async checkpoints when the
  fleet outgrows one host).
- `recover_states` — the reference's recovery model, compiled: start every
  replica from `init_state()` (or a snapshot taken at a known position)
  and replay `[base_pos, tail)` through the same vmapped scan used for
  live replay. Determinism of `Dispatch` transitions makes the result
  bit-identical to the lost states.

The RUNTIME consumer of this recovery model is `fault/`
(`fault/repair.py`): a quarantined replica is rebuilt live — donor
snapshot at the donor's ltail, then replay to tail — turning
recover-by-replay from an offline utility into the repair half of the
detect/quarantine/repair lifecycle (serve failover rides it through
`ReplicaLifecycleManager`).
"""

from __future__ import annotations

import dataclasses
import io
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from node_replication_tpu.core.log import (
    LogSpec,
    LogState,
    log_catchup_all,
)
from node_replication_tpu.core.replica import replicate_state
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.ops.encoding import Dispatch
from node_replication_tpu.utils.trace import span

PyTree = Any

_SPEC_FIELDS = ("capacity", "n_replicas", "arg_width", "gc_slack")


def save_snapshot(path: str, spec: LogSpec, log: LogState,
                  states: PyTree) -> None:
    """Write a durable snapshot: spec + log ring/cursors + replica states.

    States may be any pytree of arrays; the tree structure is rebuilt at
    load from the flattened leaf order plus the treedef of the caller's
    template, so save/load pairs must use the same Dispatch.
    """
    t0 = time.perf_counter()
    # np.asarray on device outputs is a data-dependent readback, so the
    # span below covers real device drain + serialization, not dispatch
    with span("checkpoint-save", path=path,
              tail=int(np.asarray(log.tail))):
        leaves, _ = jax.tree.flatten(states)
        payload = {
            "spec": np.asarray([getattr(spec, f) for f in _SPEC_FIELDS],
                               np.int64),
            "log_opcodes": np.asarray(log.opcodes),
            "log_args": np.asarray(log.args),
            "log_head": np.asarray(log.head),
            "log_tail": np.asarray(log.tail),
            "log_ctail": np.asarray(log.ctail),
            "log_ltails": np.asarray(log.ltails),
            "n_state_leaves": np.int64(len(leaves)),
        }
        for i, leaf in enumerate(leaves):
            payload[f"state_{i}"] = np.asarray(leaf)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    get_registry().histogram("checkpoint.save_s").observe(
        time.perf_counter() - t0
    )


def peek_spec(path: str) -> LogSpec:
    """Read only the LogSpec from a snapshot (owns the `_SPEC_FIELDS`
    encoding, so callers never index the raw array)."""
    with np.load(path) as z:
        return LogSpec(
            **dict(zip(_SPEC_FIELDS, (int(v) for v in z["spec"])))
        )


def load_snapshot(path: str, states_template: PyTree
                  ) -> tuple[LogSpec, LogState, PyTree]:
    """Load a snapshot; `states_template` supplies the pytree structure
    (e.g. `replicate_state(d.init_state(), R)`)."""
    t0 = time.perf_counter()
    with span("checkpoint-load", path=path), np.load(path) as z:
        spec = LogSpec(**dict(zip(_SPEC_FIELDS,
                                  (int(v) for v in z["spec"]))))
        log = LogState(
            opcodes=jnp.asarray(z["log_opcodes"]),
            args=jnp.asarray(z["log_args"]),
            head=jnp.asarray(z["log_head"]),
            tail=jnp.asarray(z["log_tail"]),
            ctail=jnp.asarray(z["log_ctail"]),
            ltails=jnp.asarray(z["log_ltails"]),
        )
        n = int(z["n_state_leaves"])
        leaves = [jnp.asarray(z[f"state_{i}"]) for i in range(n)]
    get_registry().histogram("checkpoint.load_s").observe(
        time.perf_counter() - t0
    )
    treedef = jax.tree.structure(states_template)
    return spec, log, jax.tree.unflatten(treedef, leaves)


def recover_states(
    dispatch: Dispatch,
    spec: LogSpec,
    log: LogState,
    base_states: PyTree | None = None,
    base_pos: int | None = None,
    window: int = 256,
) -> tuple[LogState, PyTree]:
    """Rebuild replica states by replaying the log (the recovery model).

    `base_states`/`base_pos` resume from a snapshot taken at logical
    position `base_pos`. By default recovery starts from `init_state()` at
    position 0 — valid while the ring still physically holds every entry
    of `[0, tail)`, i.e. `tail <= capacity` (GC moves `head` logically but
    only a wrap overwrites slots). Past that point a base snapshot is
    required. Returns `(log, states)` with every `ltails[r]` = tail.
    """
    if base_states is None:
        base_states = replicate_state(
            dispatch.init_state(), spec.n_replicas
        )
    start = 0 if base_pos is None else int(base_pos)
    if int(log.tail) - start > spec.capacity:
        raise ValueError(
            f"entries [{start}, {int(log.tail) - spec.capacity}) have been "
            f"overwritten by ring wrap; recovery needs a base snapshot at "
            f"position >= {int(log.tail) - spec.capacity}"
        )
    log = log._replace(
        ltails=jnp.full((spec.n_replicas,), start, jnp.int64)
    )
    # Combined catch-up (`log_catchup_all`): recovery replays at
    # combined speed when the model provides it, scan otherwise — the
    # reference recovers through the same hot exec loop it always runs
    # (`nr/src/log.rs:473-524`), and so does this. Pure recovery has no
    # response consumers, so skip the O(R x window) response re-index.
    exec_jit = jax.jit(
        lambda lg, st: log_catchup_all(spec, dispatch, lg, st, window,
                                       need_resps=False)
    )
    states = base_states
    t0 = time.perf_counter()
    rounds = 0
    with span("recover", start=start, tail=int(log.tail),
              window=window) as sp:
        while int(jnp.min(log.ltails)) < int(log.tail):
            log, states, _ = exec_jit(log, states)
            rounds += 1
        sp.add(rounds=rounds)
        sp.fence(log, states)
    reg = get_registry()
    reg.histogram("checkpoint.recover_s").observe(
        time.perf_counter() - t0
    )
    reg.counter("checkpoint.recover_rounds").inc(rounds)
    return log, states
