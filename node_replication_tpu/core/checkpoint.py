"""Checkpoint/resume and recovery-by-replay.

The reference has no checkpoint subsystem; its recovery model is structural
(SURVEY.md §5): replica state is reconstructable from a deterministic
`Default` by replaying the log from head — `Log::reset` exists only for
bench reuse (`nr/src/log.rs:582-611`) and `D: Default` is required
precisely so replay-from-scratch is well-defined
(`nr/examples/stack.rs:30-35`). This module makes both halves first-class
for the TPU build, where jobs are preempted routinely:

- `save_snapshot` / `load_snapshot` — durable npz snapshots of the log ring
  + cursors + replica states (numpy container: dependency-free and
  readable anywhere; swap in orbax for sharded async checkpoints when the
  fleet outgrows one host).
- `recover_states` — the reference's recovery model, compiled: start every
  replica from `init_state()` (or a snapshot taken at a known position)
  and replay `[base_pos, tail)` through the same vmapped scan used for
  live replay. Determinism of `Dispatch` transitions makes the result
  bit-identical to the lost states.

The RUNTIME consumers of this recovery model:

- `fault/` (`fault/repair.py`): a quarantined replica is rebuilt live —
  donor snapshot at the donor's ltail, then replay to tail — turning
  recover-by-replay from an offline utility into the repair half of the
  detect/quarantine/repair lifecycle (serve failover rides it through
  `ReplicaLifecycleManager`).
- `durable/` (`durable/recovery.py`): the CRASH-time consumer — on
  process restart the newest valid snapshot loaded here is the base,
  and the write-ahead log (`durable/wal.py`) supplies the tail
  `[snapshot_pos, durable_tail)` that replays through the same
  dispatch scan, making a kill -9 or preemption restart bit-identical.

Durability discipline: `save_snapshot` fsyncs the tmp file before the
atomic `os.replace` and fsyncs the parent directory after it (a crash
can never leave a published-but-empty snapshot), and every payload is
sealed with a blake2b manifest digest that `load_snapshot` verifies —
truncation, bit rot, or missing fields raise the typed
`SnapshotCorruptError` so recovery can fall back to an older snapshot
instead of folding garbage into a fleet.
"""

from __future__ import annotations

import dataclasses
import io
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from node_replication_tpu.core.log import (
    LogSpec,
    LogState,
    log_catchup_all,
)
from node_replication_tpu.core.replica import replicate_state
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.ops.encoding import Dispatch
from node_replication_tpu.utils.trace import span

PyTree = Any

_SPEC_FIELDS = ("capacity", "n_replicas", "arg_width", "gc_slack")

# Manifest key holding the payload digest; never part of the digest.
_DIGEST_KEY = "manifest_digest"


class SnapshotCorruptError(RuntimeError):
    """The snapshot failed integrity validation (digest mismatch,
    truncated archive, missing fields). Typed so recovery
    (`durable/recovery.py`) can fall back to an older snapshot instead
    of crashing on a bare numpy/zipfile error."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"corrupt snapshot {path}: {detail}")
        self.path = path
        self.detail = detail


def _payload_digest(payload: dict) -> np.ndarray:
    """blake2b over every payload entry (key + dtype + shape + bytes,
    key-sorted) — order-independent of dict construction, sensitive to
    any bit of any array."""
    import hashlib

    h = hashlib.blake2b(digest_size=32)
    for key in sorted(payload):
        if key == _DIGEST_KEY:
            continue
        arr = np.ascontiguousarray(np.asarray(payload[key]))
        h.update(key.encode())
        h.update(str(arr.dtype).encode())
        h.update(repr(arr.shape).encode())
        h.update(arr.tobytes())
    return np.frombuffer(h.digest(), np.uint8).copy()


def save_snapshot(path: str, spec: LogSpec, log: LogState,
                  states: PyTree) -> None:
    """Write a durable snapshot: spec + log ring/cursors + replica states.

    States may be any pytree of arrays; the tree structure is rebuilt at
    load from the flattened leaf order plus the treedef of the caller's
    template, so save/load pairs must use the same Dispatch.
    """
    t0 = time.perf_counter()
    # np.asarray on device outputs is a data-dependent readback, so the
    # span below covers real device drain + serialization, not dispatch
    with span("checkpoint-save", path=path,
              tail=int(np.asarray(log.tail))):
        leaves, _ = jax.tree.flatten(states)
        payload = {
            "spec": np.asarray([getattr(spec, f) for f in _SPEC_FIELDS],
                               np.int64),
            "log_opcodes": np.asarray(log.opcodes),
            "log_args": np.asarray(log.args),
            "log_head": np.asarray(log.head),
            "log_tail": np.asarray(log.tail),
            "log_ctail": np.asarray(log.ctail),
            "log_ltails": np.asarray(log.ltails),
            "n_state_leaves": np.int64(len(leaves)),
        }
        for i, leaf in enumerate(leaves):
            payload[f"state_{i}"] = np.asarray(leaf)
        payload[_DIGEST_KEY] = _payload_digest(payload)
        tmp = f"{path}.{os.getpid()}.tmp"
        # publish durably: fsync the payload BEFORE the atomic rename
        # and the directory entry AFTER it — otherwise a crash between
        # replace and writeback publishes a name pointing at nothing
        # (machine-checked by nrlint `non-durable-publish`)
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dfd = os.open(
            os.path.dirname(os.path.abspath(path)), os.O_RDONLY
        )
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    get_registry().histogram("checkpoint.save_s").observe(
        time.perf_counter() - t0
    )


def _open_snapshot(path: str):
    """np.load with zip/format failures mapped to the typed error."""
    import zipfile

    try:
        return np.load(path)
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, OSError, ValueError, EOFError) as e:
        raise SnapshotCorruptError(
            path, f"unreadable archive ({type(e).__name__}: {e})"
        ) from e


def peek_spec(path: str) -> LogSpec:
    """Read only the LogSpec from a snapshot (owns the `_SPEC_FIELDS`
    encoding, so callers never index the raw array). Raises
    `SnapshotCorruptError` on truncation or missing manifest fields."""
    with _open_snapshot(path) as z:
        try:
            if _DIGEST_KEY not in z.files:
                raise SnapshotCorruptError(
                    path, "missing manifest digest"
                )
            spec_row = z["spec"]
            if spec_row.shape != (len(_SPEC_FIELDS),):
                raise SnapshotCorruptError(
                    path, f"spec field has shape {spec_row.shape}"
                )
            return LogSpec(
                **dict(zip(_SPEC_FIELDS, (int(v) for v in spec_row)))
            )
        except KeyError as e:
            raise SnapshotCorruptError(
                path, f"missing field {e.args[0]!r}"
            ) from e


def load_snapshot(path: str, states_template: PyTree
                  ) -> tuple[LogSpec, LogState, PyTree]:
    """Load a snapshot; `states_template` supplies the pytree structure
    (e.g. `replicate_state(d.init_state(), R)`). The payload's blake2b
    manifest digest is recomputed and verified — mismatch, truncation,
    or missing fields raise `SnapshotCorruptError`."""
    t0 = time.perf_counter()
    import zipfile

    with span("checkpoint-load", path=path), _open_snapshot(path) as z:
        try:
            # np.load is lazy: per-entry reads are where a truncated
            # or bit-flipped archive actually surfaces
            payload = {k: z[k] for k in z.files}
        except (KeyError, ValueError, OSError, EOFError,
                zipfile.BadZipFile) as e:
            raise SnapshotCorruptError(
                path, f"truncated payload ({type(e).__name__}: {e})"
            ) from e
        if _DIGEST_KEY not in payload:
            raise SnapshotCorruptError(path, "missing manifest digest")
        want = payload[_DIGEST_KEY]
        got = _payload_digest(payload)
        if not np.array_equal(want, got):
            raise SnapshotCorruptError(
                path, "manifest digest mismatch (payload corrupted)"
            )
        missing = [
            k for k in ("spec", "log_opcodes", "log_args", "log_head",
                        "log_tail", "log_ctail", "log_ltails",
                        "n_state_leaves")
            if k not in payload
        ]
        if missing:
            raise SnapshotCorruptError(
                path, f"missing fields {missing}"
            )
        spec = LogSpec(**dict(zip(_SPEC_FIELDS,
                                  (int(v) for v in payload["spec"]))))
        log = LogState(
            opcodes=jnp.asarray(payload["log_opcodes"]),
            args=jnp.asarray(payload["log_args"]),
            head=jnp.asarray(payload["log_head"]),
            tail=jnp.asarray(payload["log_tail"]),
            ctail=jnp.asarray(payload["log_ctail"]),
            ltails=jnp.asarray(payload["log_ltails"]),
        )
        n = int(payload["n_state_leaves"])
        try:
            leaves = [jnp.asarray(payload[f"state_{i}"])
                      for i in range(n)]
        except KeyError as e:
            raise SnapshotCorruptError(
                path, f"missing state leaf {e.args[0]!r}"
            ) from e
    get_registry().histogram("checkpoint.load_s").observe(
        time.perf_counter() - t0
    )
    treedef = jax.tree.structure(states_template)
    return spec, log, jax.tree.unflatten(treedef, leaves)


def recover_states(
    dispatch: Dispatch,
    spec: LogSpec,
    log: LogState,
    base_states: PyTree | None = None,
    base_pos: int | None = None,
    window: int = 256,
) -> tuple[LogState, PyTree]:
    """Rebuild replica states by replaying the log (the recovery model).

    `base_states`/`base_pos` resume from a snapshot taken at logical
    position `base_pos`. By default recovery starts from `init_state()` at
    position 0 — valid while the ring still physically holds every entry
    of `[0, tail)`, i.e. `tail <= capacity` (GC moves `head` logically but
    only a wrap overwrites slots). Past that point a base snapshot is
    required. Returns `(log, states)` with every `ltails[r]` = tail.
    """
    if base_states is None:
        base_states = replicate_state(
            dispatch.init_state(), spec.n_replicas
        )
    start = 0 if base_pos is None else int(base_pos)
    if int(log.tail) - start > spec.capacity:
        raise ValueError(
            f"entries [{start}, {int(log.tail) - spec.capacity}) have been "
            f"overwritten by ring wrap; recovery needs a base snapshot at "
            f"position >= {int(log.tail) - spec.capacity}"
        )
    log = log._replace(
        ltails=jnp.full((spec.n_replicas,), start, jnp.int64)
    )
    # Combined catch-up (`log_catchup_all`): recovery replays at
    # combined speed when the model provides it, scan otherwise — the
    # reference recovers through the same hot exec loop it always runs
    # (`nr/src/log.rs:473-524`), and so does this. Pure recovery has no
    # response consumers, so skip the O(R x window) response re-index.
    exec_jit = jax.jit(
        lambda lg, st: log_catchup_all(spec, dispatch, lg, st, window,
                                       need_resps=False)
    )
    states = base_states
    t0 = time.perf_counter()
    rounds = 0
    with span("recover", start=start, tail=int(log.tail),
              window=window) as sp:
        while int(jnp.min(log.ltails)) < int(log.tail):
            log, states, _ = exec_jit(log, states)
            rounds += 1
        sp.add(rounds=rounds)
        sp.fence(log, states)
    reg = get_registry()
    reg.histogram("checkpoint.recover_s").observe(
        time.perf_counter() - t0
    )
    reg.counter("checkpoint.recover_rounds").inc(rounds)
    return log, states
