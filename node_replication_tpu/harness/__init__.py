"""Benchmark/test harness: the mkbench equivalent (`benches/mkbench.rs`).

- `trait`     — the ReplicaTrait abstraction: one runner protocol that NR
                fleets, CNR multi-log fleets, partitioned comparisons,
                single concurrent-DS baselines, and the native CPU engine
                all implement (`benches/mkbench.rs:77-139`).
- `workloads` — op-stream generators (uniform/zipf keys, write-ratio mix),
                the port of `benches/hashmap.rs:131-162`.
- `mkbench`   — ScaleBenchBuilder sweeps, baseline_comparison, CSV output,
                `>> X Mops` reporting (`benches/mkbench.rs:189-319`,
                `950-1182`).
"""

from node_replication_tpu.harness.trait import (
    ConcurrentDsRunner,
    FleetRunner,
    MultiLogRunner,
    NativeRunner,
    PartitionedRunner,
    ReplicatedRunner,
    ShardedRunner,
)
from node_replication_tpu.harness.workloads import (
    WorkloadSpec,
    generate_batches,
    zipf_keys,
)
from node_replication_tpu.harness.mkbench import (
    ScaleBenchBuilder,
    baseline_comparison,
)

__all__ = [
    "FleetRunner",
    "ReplicatedRunner",
    "MultiLogRunner",
    "PartitionedRunner",
    "ConcurrentDsRunner",
    "NativeRunner",
    "ShardedRunner",
    "WorkloadSpec",
    "generate_batches",
    "zipf_keys",
    "ScaleBenchBuilder",
    "baseline_comparison",
]
