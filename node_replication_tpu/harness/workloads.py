"""Workload generation: the op-stream side of the bench harness.

Port of the reference's generator (`benches/hashmap.rs:131-162`): `nop`
operations over a bounded keyspace, keys drawn uniform or zipf
(`benches/hashmap.rs:29-48` uses zipf-or-uniform behind a feature flag),
write ratio in percent selecting Put vs Get. Everything is generated
up-front shaped `[S, R, B]` (steps × replicas × batch) so the measured
loop never touches the host (SURVEY.md §7 "honest throughput accounting").

Batches are returned as HOST (numpy) arrays and staged onto the device by
each runner's `prepare`. This is deliberate: on the tunneled TPU platform a
single device→host transfer degrades every subsequent dispatch ~10×
(discovered in round 2 — it made CNR look 14× slower than NR in round 1's
sweeps purely because its `prepare` round-tripped device arrays through
numpy for re-keying). Keeping generation on host means the measured loop
performs zero D2H transfers.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Bench workload config (`ScaleBenchBuilder`-style knobs,
    `benches/mkbench.rs:1041-1093` + `benches/hashmap.rs:29-48`)."""

    keyspace: int = 10_000
    write_ratio: int = 50  # percent of ops that are writes
    distribution: str = "uniform"  # or "skewed" (zipf)
    zipf_theta: float = 1.03
    seed: int = 0


def zipf_keys(rng: np.random.Generator, n: int, keyspace: int,
              theta: float) -> np.ndarray:
    """Zipf-distributed keys over [0, keyspace) via rejection-free inverse
    CDF on a truncated harmonic (the 'skewed' distribution of
    `benches/hashmap.rs:143-150`)."""
    # Probability p(k) ∝ 1/(k+1)^theta over the truncated support.
    ranks = np.arange(1, keyspace + 1, dtype=np.float64)
    weights = 1.0 / np.power(ranks, theta)
    cdf = np.cumsum(weights)
    cdf /= cdf[-1]
    u = rng.random(n)
    return np.searchsorted(cdf, u).astype(np.int32)


def generate_batches(
    spec: WorkloadSpec,
    n_steps: int,
    n_replicas: int,
    writes_per_replica: int,
    reads_per_replica: int,
    wr_opcode: int | tuple = 1,
    rd_opcode: int | tuple = 1,
    arg_width: int = 3,
):
    """Generate `[S, R, B]`-shaped device batches for the fused step path.

    Every write slot carries (key, value) args; every read slot carries
    (key,). The write/read split is structural (separate batches) — the
    reference's per-op coin flip (`benches/hashmap.rs:152-160`) determines
    the *ratio*, which here fixes the Bw:Br shape instead, keeping shapes
    static for jit.

    Returns `(wr_opc, wr_args, rd_opc, rd_args)` as HOST numpy arrays
    (`wr_opc int32[S, R, Bw]`, `wr_args int32[S, R, Bw, A]`, etc.) —
    runners `device_put` them in `prepare` (see module docstring for why
    they must not start life on device).
    """
    rng = np.random.default_rng(spec.seed)
    S, R, Bw, Br = n_steps, n_replicas, writes_per_replica, reads_per_replica

    def keys(n):
        if spec.distribution == "skewed":
            return zipf_keys(rng, n, spec.keyspace, spec.zipf_theta)
        return rng.integers(0, spec.keyspace, n, dtype=np.int32)

    def opcodes(choice, shape):
        # A tuple of opcodes means "pick uniformly per slot" (e.g. the
        # stack bench's 50/50 push/pop mix, `benches/stack.rs`).
        if isinstance(choice, (tuple, list)):
            return rng.choice(np.asarray(choice, np.int32), shape)
        return np.full(shape, choice, np.int32)

    wr_opc = opcodes(wr_opcode, (S, R, Bw))
    wr_args = np.zeros((S, R, Bw, arg_width), np.int32)
    wr_args[..., 0] = keys(S * R * Bw).reshape(S, R, Bw)
    wr_args[..., 1] = rng.integers(0, 1 << 31, (S, R, Bw), dtype=np.int32)
    rd_opc = opcodes(rd_opcode, (S, R, Br))
    rd_args = np.zeros((S, R, Br, arg_width), np.int32)
    rd_args[..., 0] = keys(S * R * Br).reshape(S, R, Br)
    return wr_opc, wr_args, rd_opc, rd_args


def split_write_read(total_per_replica: int, write_ratio: int) -> tuple[int, int]:
    """Fix the static (Bw, Br) shape that realizes `write_ratio` percent
    writes out of `total_per_replica` ops: at least one of each side when
    the ratio is strictly between 0 and 100 and the batch allows it
    (`total >= 2`); a single-op batch goes to whichever side the ratio
    favors."""
    if write_ratio <= 0:
        return 0, total_per_replica
    if write_ratio >= 100:
        return total_per_replica, 0
    if total_per_replica == 1:
        return (1, 0) if write_ratio >= 50 else (0, 1)
    bw = round(total_per_replica * write_ratio / 100)
    bw = min(max(bw, 1), total_per_replica - 1)
    return bw, total_per_replica - bw
