"""ScaleBench harness: sweeps, measurement, CSV output.

The mkbench equivalent (`benches/mkbench.rs`):

- `ScaleBenchBuilder` — cross-product sweeps of (replica count ×
  log strategy × batch size), mirroring `ScaleBenchBuilder::configure`'s
  (ReplicaStrategy × LogStrategy × ThreadMapping × #threads × batch)
  matrix (`benches/mkbench.rs:950-1182`). Replica placement strategies are
  mesh shapes on TPU, so the sweep axis is the simulated replica count and
  the log shard count.
- per-second throughput capture and CSV records with the reference's
  column shape (name, rs, ls, tm, batch, threads, duration, thread_id,
  core_id, second, ops — `benches/mkbench.rs:498-552`).
- `>> X Mops (min, max)` stdout summaries (`benches/mkbench.rs:592-604`).
- `baseline_comparison` — single-replica, same workload, data structure
  direct vs behind-the-log (`benches/mkbench.rs:189-319`).
"""

from __future__ import annotations

import csv
import dataclasses
import functools
import os
import random
import threading
import time
from typing import Callable, Sequence

import numpy as np

from node_replication_tpu.harness.trait import (
    ConcurrentDsRunner,
    FleetRunner,
    MultiLogRunner,
    NativeRunner,
    PartitionedRunner,
    ReplicatedRunner,
    ShardedCnrRunner,
    ShardedRunner,
)
from node_replication_tpu.harness.workloads import (
    WorkloadSpec,
    generate_batches,
    split_write_read,
)
from node_replication_tpu.utils.trace import get_tracer

SCALEOUT_CSV = "scaleout_benchmarks.csv"
SKEW_CSV = "cnr_skew_stats.csv"
# spread_pct/attempts (r5): the contention-aware annotations the
# flagship bench carries (bench.py) — blank on rows measured without
# the attempts loop
_SKEW_FIELDS = [
    "name", "rs", "ls", "batch", "distribution", "imbalance",
    "per_log_tails", "client_mops", "replay_mops", "spread_pct",
    "attempts",
]
BASELINE_CSV = "baseline_comparison.csv"
SERVE_CSV = "serve_benchmarks.csv"
CHAOS_CSV = "chaos_benchmarks.csv"
RECOVERY_CSV = "recovery_benchmarks.csv"
REPLICATION_CSV = "replication_benchmarks.csv"
TREE_CSV = "tree_benchmarks.csv"
OVERLOAD_CSV = "overload_benchmarks.csv"
MESH_CSV = "mesh_benchmarks.csv"
SHARDED_CSV = "sharded_benchmarks.csv"
# One row per sharded-fleet measurement (`bench.py --sharded`): N
# keyspace-sharded primary processes behind a `ShardRouter`.
# `baseline_ops` is the 1-shard acked-write throughput under the same
# client load; `aggregate_ops`/`scaling_x` are the horizontal-scaling
# claim (the gate: N=3 must clear 2.2x). The failover block is the
# per-shard one — SIGKILL of `victim_shard`'s primary, parent-side
# promotion, router re-home — with `survivor_hold` = the OTHER
# shards' goodput during the outage window over their pre-kill
# window (gate: >= 0.9), and the two hard gates `lost`/`duplicated`
# from the per-shard ack-chain verifier (both must be 0).
_SHARDED_FIELDS = [
    "name", "n_shards", "clients", "duration",
    "baseline_ops", "aggregate_ops", "scaling_x",
    "acked", "victim_shard", "victim_acked",
    "detect_s", "promote_s", "rto_s", "survivor_hold",
    "lost", "duplicated", "post_promote_ops",
    # --txn 2PC crash-matrix columns (blank on plain --sharded rows;
    # `_append_csv`'s header-upgrade rewrite keeps pre-txn CSVs
    # aligned): kill rounds run / acked txns verified by per-key
    # read-back / in-doubt intents found and resolved after restart /
    # half-committed txns observed (gated to 0) / non-txn throughput
    # parity vs a with_txn=False fleet (gated >= 0.9)
    "txn_rounds", "txn_acked", "txn_in_doubt", "txn_resolved",
    "txn_half_committed", "txn_parity",
    # --reshard live-split columns: keys re-homed by the N->2N split /
    # acked writes lost or duplicated across the cutover (gated to 0)
    # / the split's fence window / the worst measured per-moved-key
    # ack gap (the ONLINE claim, gated)
    "moved_keys", "reshard_lost", "reshard_dup",
    "fence_s", "moved_unavail_s",
]
# One row per (device count) point of a mesh scaling curve
# (`bench.py --mesh`): replayed-dispatch throughput at that width,
# `scaling_x` = throughput / the curve's 1-device throughput, and
# `efficiency` = scaling_x / devices (1.0 = perfectly linear).
# `bit_identical` is the curve's hard gate: the sharded fleet's states
# after the verification steps equal the un-sharded fleet's
# bit-for-bit (blank-or-1 rows are gate-worthy; 0 means the curve is
# measuring a DIFFERENT computation and the bench exits nonzero).
_MESH_FIELDS = [
    "name", "devices", "replicas", "batch", "keys", "duration",
    "throughput_mdps", "scaling_x", "efficiency", "bit_identical",
    "spread_pct", "tier", "launches_per_round",
]
# One row per overload run (`bench.py --overload`), static baseline
# and adaptive controller side by side: open-loop Poisson arrivals at
# `rate` (a multiple of the measured closed-loop `capacity_ops`) with
# a heavy-tailed burst mix, `good` = completed within the deadline
# SLO, `goodput_ops` = good/duration — the gated metric. Shed columns
# split by priority class; `lost`/`duplicated` are the ack-chain
# verifier's hard gates (both must be 0).
_OVERLOAD_FIELDS = [
    "name", "mode", "pipeline_overlap", "clients", "capacity_ops",
    "rate", "deadline_ms",
    "duration", "arrivals", "accepted", "completed", "good",
    "goodput_ops", "shed", "shed_critical", "shed_normal",
    "shed_bulk", "evicted", "circuit_open", "deadline_miss",
    "brownout_reads", "max_brownout_lag", "priority_inversions",
    "p50_ms", "p99_ms", "lost", "duplicated",
]
# One row per follower-failover measurement (`bench.py --follower`):
# the staleness-bounded read-scale-out phase (reads served against a
# live follower, stale rejections counted) and the failover phase —
# SIGKILL of the primary, heartbeat detection, most-advanced election,
# promotion — with the measured RTO split (detect + promote) and the
# two hard gates (lost/duplicated fsync-acked writes, both must be 0).
_REPLICATION_FIELDS = [
    "name", "clients", "acked", "kill_after_acks", "max_lag_pos",
    "reads", "stale_reads", "applied_pos", "new_epoch",
    "drained_records", "detect_s", "promote_s", "rto_s",
    "lost", "duplicated", "post_restart_ops",
]
# One row per tree-replication measurement (`bench.py --tree`): a
# socket-transported 1 -> relays -> followers topology. The three
# gated claims, one column group each: `agg_reads_ops`/`read_scaling_x`
# (aggregate follower read throughput vs one follower — must scale)
# with `primary_tput_hold` (primary write throughput under the full
# tree / alone, must hold within tolerance), `bootstrap_s` vs
# `full_replay_s` (a snapshot-bootstrapped cold follower must catch
# up faster than full-WAL replay), and the mid-tree failover block
# (detect/promote/rto + `lost`/`duplicated`, both must be 0).
_TREE_FIELDS = [
    "name", "relays", "followers", "acked", "agg_reads_ops",
    "single_reads_ops", "read_scaling_x", "primary_tput_hold",
    "bootstrap_pos", "bootstrap_s", "full_replay_s",
    "bootstrap_speedup_x", "detect_s", "promote_s", "rto_s",
    "lost", "duplicated", "post_restart_ops",
    "obs_nodes", "obs_records", "obs_multiproc_records",
]
# One row per crash-recovery measurement (`bench.py --crash`): what
# the seeded SIGKILL destroyed vs. what recovery restored — fsync-acked
# ops before the kill, the snapshot/WAL split the restart replayed
# from, restore latency, and the two hard gates (lost/duplicated
# fsync-acked responses, both must be 0).
_RECOVERY_FIELDS = [
    "name", "clients", "durability", "acked", "kill_after_acks",
    "snapshot_pos", "wal_records", "wal_ops", "truncated_bytes",
    "recovery_s", "tail", "lost", "duplicated", "post_restart_ops",
]
# One row per chaos measurement (`bench.py --chaos`): availability
# (completed/attempts), re-homed request count, and repair-latency
# percentiles next to the usual serve latency columns. `kills` is how
# many injected faults actually fired during the window.
_CHAOS_FIELDS = [
    "name", "clients", "duration", "attempts", "completed", "lost",
    "kills", "repairs", "rehomed", "availability",
    "repair_p50_ms", "repair_p95_ms", "repair_max_ms",
    "throughput_ops", "p50_ms", "p95_ms", "p99_ms",
]
# One row per serve measurement (not per-second): client-perceived
# latency percentiles + admission accounting next to throughput, the
# serve analog of the reference's `>> X Mops` summaries. `rate` is the
# open-loop target (blank for closed loop); shed/deadline_miss are the
# typed-rejection counts the frontend recorded over the run.
_SERVE_FIELDS = [
    "name", "mode", "pipeline_overlap", "clients", "rate", "duration",
    "attempts", "accepted", "completed", "shed", "deadline_miss",
    "throughput_ops", "p50_ms", "p95_ms", "p99_ms",
    # host-profiling columns (`bench.py --serve --profile`,
    # obs/profile.py): "" on unprofiled rows — `_append_csv`'s schema
    # upgrade backfills "" into pre-profile files
    "profile_hz", "profile_samples", "profile_duty_cycle",
    "profile_attributed_frac", "profile_overhead_ratio",
]
# Reference column shape (`benches/mkbench.rs:498-552`) with one addition:
# `ops` counts *completed client ops* (the reference's Mops semantics,
# cross-system comparable) and `dispatches` counts *replayed dispatches*
# (NR replays every entry on every replica). VERDICT r1 #3.
# Derivation note (ADVICE r2): native rows carry dispatches measured
# in-loop; JAX-runner per-second rows derive dispatches as
# ops * (total_dispatches / total_client_ops) — exact, not an estimate,
# because the step runners execute a fixed dispatches:client-ops ratio
# every step by construction.
# Placement note (VERDICT r2 weak #6): JAX fleet rows are per-SECOND
# aggregates of a single lock-step device program — no OS threads exist,
# so thread_id/core_id are -1 (not a fabricated 0). Native rows carry
# real (thread, core) ids from the engine's in-loop bins.
_CSV_FIELDS = [
    "name", "rs", "ls", "tm", "batch", "threads", "duration",
    "thread_id", "core_id", "second", "ops", "dispatches", "wr_eff",
]
# `wr_eff` (r5; VERDICT r2→r4 carryover): the EFFECTIVE write percentage
# a swept row actually ran, computed from the static (Bw, Br) shape that
# `split_write_read` realized — rounding makes wr=10 at batch 32 really
# 9.4% and at batch 4 really 25%, and the row name's nominal ratio hid
# that. Native rows flip a per-op coin (`nr_bench_hashmap`), so their
# effective ratio IS the nominal one.


def _append_csv(path: str, fields: list[str], rows: list[dict]) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    if os.path.exists(path):
        # schema upgrade: whenever the existing header differs from the
        # current schema IN ANY WAY — new columns, removed columns, or a
        # reordered same-set header — rewrite the file once under the
        # canonical field order. Old rows keep "" in columns they predate
        # and drop columns the schema no longer has, so historical
        # measurements stay valid and appended rows can never land
        # misaligned under a stale header (ADVICE r5: the old
        # strict-subset check let reordered/removed-column headers fall
        # through to a misaligned append).
        with open(path, newline="") as f:
            r = csv.reader(f)
            header = next(r, None)
            if header is not None and header != fields:
                old_rows = [dict(zip(header, row)) for row in r]
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "w", newline="") as g:
                    w = csv.DictWriter(g, fieldnames=fields,
                                       restval="",
                                       extrasaction="ignore")
                    w.writeheader()
                    w.writerows(old_rows)
                os.replace(tmp, path)
    fresh = not os.path.exists(path)
    with open(path, "a", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields, restval="")
        if fresh:
            w.writeheader()
        w.writerows(rows)


@dataclasses.dataclass
class MeasureResult:
    name: str
    total_dispatches: int
    duration_s: float
    per_second: list[tuple[int, int]]  # (second, client ops)
    total_client_ops: int = 0

    @property
    def mops(self) -> float:
        """Replayed-dispatch Mops (the driver's aggregate-replay metric)."""
        return self.total_dispatches / self.duration_s / 1e6

    @property
    def client_mops(self) -> float:
        """Completed-client-op Mops (the reference's cross-system
        comparable metric, `benches/mkbench.rs:592-604`)."""
        return self.total_client_ops / self.duration_s / 1e6


def measure_step_runner(
    runner: FleetRunner,
    wr_opc,
    wr_args,
    rd_opc,
    rd_args,
    duration_s: float = 2.0,
    warmup_steps: int = 3,
    chunk: int = 8,
) -> MeasureResult:
    """Drive a step runner for ~`duration_s`, bucketing op counts by
    wall-clock second (the per-second capture of
    `benches/mkbench.rs:755-761`). Steps cycle over the pre-staged
    workload.

    `chunk` is the INITIAL steps-per-fence; it doubles whenever a fenced
    round finishes in under ~0.25s so the fence's D2H readback RTT
    (~100ms through the tunnel) is amortized instead of dominating fast
    runners (the real barrier is a readback — see `utils/fence.py`)."""
    S = wr_opc.shape[0]
    runner.prepare(wr_opc, wr_args, rd_opc, rd_args)
    for s in range(min(warmup_steps, S)):
        runner.run_step(s)
    runner.block()
    client_per_step = runner.client_ops_per_step or runner.dispatches_per_step

    buckets: dict[int, int] = {}
    total = 0
    total_client = 0
    idx = 0
    t0 = time.perf_counter()
    while True:
        r0 = time.perf_counter()
        for _ in range(chunk):
            runner.run_step(idx % S)
            idx += 1
        runner.block()
        now = time.perf_counter()
        total += chunk * runner.dispatches_per_step
        done_client = chunk * client_per_step
        total_client += done_client
        buckets[int(now - t0)] = buckets.get(int(now - t0), 0) + done_client
        if now - t0 >= duration_s:
            break
        if now - r0 < 0.25:
            chunk *= 2
    dur = time.perf_counter() - t0
    tracer = get_tracer()
    if tracer.enabled:
        # per-second capture into the trace (the reference's per-second
        # counters, `benches/mkbench.rs:755-761`): one `throughput`
        # event per wall-clock-second bucket — the report CLI's timeline
        for sec, ops in sorted(buckets.items()):
            tracer.emit("throughput", runner=runner.name, second=sec,
                        ops=ops)
        tracer.emit(
            "measure", runner=runner.name, duration_s=dur,
            client_ops=total_client, dispatches=total,
        )
    return MeasureResult(
        name=runner.name,
        total_dispatches=total,
        duration_s=dur,
        per_second=sorted(buckets.items()),
        total_client_ops=total_client,
    )


def baseline_comparison(
    dispatch_factory: Callable,
    name: str,
    workload: WorkloadSpec,
    batch_sizes: Sequence[int] = (1, 8, 32, 128),
    duration_s: float = 1.0,
    out_dir: str = ".",
    log_capacity: int | None = None,
) -> list[MeasureResult]:
    """Single-replica baseline: the same op stream applied to the data
    structure directly vs through the log (`baseline_comparison`,
    `benches/mkbench.rs:189-319`). Quantifies log overhead per batch size.
    Writes `baseline_comparison.csv`."""
    results = []
    rows = []
    for batch in batch_sizes:
        bw, br = split_write_read(batch, workload.write_ratio)
        gen = generate_batches(workload, 16, 1, bw, br)
        for system in ("direct", "log"):
            if system == "direct":
                runner: FleetRunner = ConcurrentDsRunner(
                    dispatch_factory(), 1, bw, br
                )
            else:
                runner = ReplicatedRunner(
                    dispatch_factory(), 1, bw, br, log_capacity=log_capacity
                )
            res = measure_step_runner(
                runner, *gen, duration_s=duration_s
            )
            res.name = f"{name}-{system}"
            results.append(res)
            rows.append(
                {
                    "name": name,
                    "rs": "one",
                    "ls": system,
                    "tm": "none",
                    "batch": batch,
                    "threads": 1,
                    "duration": round(res.duration_s, 3),
                    "thread_id": -1,  # fleet-aggregate row (see note)
                    "core_id": -1,
                    "second": -1,
                    "ops": res.total_client_ops,
                    "dispatches": res.total_dispatches,
                    "wr_eff": effective_write_pct(bw, br),
                }
            )
            print(f">> {res.name} batch={batch}: "
                  f"{res.client_mops:.2f} Mops client "
                  f"({res.mops:.2f} Mops replayed)")
    _append_csv(os.path.join(out_dir, BASELINE_CSV), _CSV_FIELDS, rows)
    return results


class ScaleBenchBuilder:
    """Sweep builder (`ScaleBenchBuilder`, `benches/mkbench.rs:1041-1093`).

    Axes: replica counts (ReplicaStrategy analog — how many lock-step
    replicas the fleet simulates), log strategy (1 = NR single log, n > 1 =
    CNR key-partitioned logs, `LogStrategy::Custom(n)`), ops per replica
    per step (combiner batch), and the comparison systems to include.
    """

    def __init__(self, dispatch_factory: Callable, name: str,
                 workload: WorkloadSpec | None = None):
        self.dispatch_factory = dispatch_factory
        self.name = name
        self.workload = workload or WorkloadSpec()
        self._replicas = [4]
        self._log_strategies = [1]
        self._batches = [32]
        self._systems = ["nr"]
        self._duration_s = 2.0
        self._steps = 16
        self._log_capacity: int | None = None
        self._out_dir = "."
        self._partitioned_factory: Callable | None = None
        self._strategies: list = [None]
        self._replay: str = "auto"
        self._max_attempts = 1
        self._spread_threshold = 5.0
        self._repeats = 3

    def replicas(self, counts: Sequence[int]):
        self._replicas = list(counts)
        return self

    def log_strategies(self, ns: Sequence[int]):
        self._log_strategies = list(ns)
        return self

    def batches(self, bs: Sequence[int]):
        self._batches = list(bs)
        return self

    def systems(self, names: Sequence[str]):
        """Subset of {nr, cnr, partitioned, concurrent}."""
        self._systems = list(names)
        return self

    def duration(self, seconds: float):
        self._duration_s = seconds
        return self

    def log_capacity(self, entries: int):
        self._log_capacity = entries
        return self

    def partitioned(self, factory: Callable):
        """`factory(nlogs) -> PartitionedModel` enabling parallel per-log
        replay for the cnr system (`models/partitioned.py`)."""
        self._partitioned_factory = factory
        return self

    def replica_strategies(self, strategies: Sequence):
        """ReplicaStrategy sweep for the 'sharded' system: each strategy
        maps to a device set via the topology walk (the One/Socket/L1
        ladder, `benches/mkbench.rs:321-362`, `838-945`)."""
        self._strategies = list(strategies)
        return self

    def out_dir(self, path: str):
        self._out_dir = path
        return self

    def replay(self, mode: str):
        """Replay engine for nr/cnr runners: 'auto' (combined when the
        model provides `window_apply`), 'scan' (force the per-entry
        vmapped scan — the faithful analog of the reference's replay
        loop), 'combined' (require `window_apply`)."""
        if mode not in ("auto", "scan", "combined"):
            raise ValueError(f"unknown replay mode {mode!r}")
        self._replay = mode
        return self

    def attempts(self, max_attempts: int, spread_threshold: float = 5.0,
                 repeats: int = 3):
        """Contention-aware measurement (the flagship bench's retry
        loop, bench.py, applied to sweeps): measure each config as
        `repeats` back-to-back windows, accept the attempt whose
        min-to-max spread across repeats is within `spread_threshold`
        percent, retry up to `max_attempts` windows, else keep the
        cleanest. The accepted spread/attempt count annotate the skew
        sidecar rows so ms-scale harness numbers on the shared chip are
        quotable (VERDICT r4 weak #4)."""
        self._max_attempts = max(1, int(max_attempts))
        self._spread_threshold = float(spread_threshold)
        self._repeats = max(1, int(repeats))
        return self

    def _measure_attempts(self, runner, gen):
        """Measure one config under the attempts policy (see
        `attempts`); returns `(result, spread_pct, n_attempts)` —
        result is the median-throughput repeat of the accepted attempt.
        With the default single-attempt policy this is one plain
        `measure_step_runner` call and spread 0."""
        if self._max_attempts <= 1:
            return measure_step_runner(
                runner, *gen, duration_s=self._duration_s
            ), 0.0, 1
        best = None
        n_att = 0
        for attempt in range(self._max_attempts):
            n_att += 1
            reps = [
                measure_step_runner(
                    runner, *gen, duration_s=self._duration_s
                )
                for _ in range(self._repeats)
            ]
            vals = sorted(r.client_mops for r in reps)
            med = vals[len(vals) // 2]
            spread = (
                100.0 * (vals[-1] - vals[0]) / med if med else 0.0
            )
            res = min(
                reps, key=lambda r: abs(r.client_mops - med)
            )
            if best is None or spread < best[1]:
                best = (res, spread)
            if spread <= self._spread_threshold:
                break
            print(f"## attempt {attempt + 1}: spread {spread:.1f}% > "
                  f"{self._spread_threshold}% — contended window")
        return best[0], best[1], n_att

    def _make_runner(self, system: str, nlogs: int, R: int, bw: int,
                     br: int, strategy=None) -> FleetRunner | None:
        d = self.dispatch_factory()
        combined = {"auto": None, "scan": False, "combined": True}[
            self._replay
        ]
        if system == "nr" and nlogs == 1:
            return ReplicatedRunner(d, R, bw, br, self._log_capacity,
                                    combined=combined)
        if system in ("cnr", "sharded-cnr") and nlogs > 1:
            label = f"{system}{nlogs}"
            part = None
            if self._partitioned_factory is not None:
                try:
                    part = self._partitioned_factory(nlogs)
                except ValueError as e:
                    # e.g. keyspace not divisible by this swept nlogs:
                    # fall back to the sequential fold rather than
                    # aborting the whole sweep mid-run.
                    print(f"## {label}: partitioned replay unavailable "
                          f"({e}); using sequential fold")
            if combined and part is None:
                # never mislabel: a forced-combined config without a
                # partitioned model would silently measure the scan fold
                print(f"## {label}: skipping — replay 'combined' "
                      f"forced but no partitioned model")
                return None
            cls = (ShardedCnrRunner if system == "sharded-cnr"
                   else MultiLogRunner)
            try:
                return cls(d, R, nlogs, bw, br, self._log_capacity,
                           partitioned=part,
                           keyspace=self.workload.keyspace,
                           combined=combined)
            except ValueError as e:
                # e.g. the fleet does not divide over the mesh rows:
                # skip this config (parity with the 'sharded' branch)
                print(f"## {label}: skipping — {e}")
                return None
        if system == "partitioned" and nlogs == 1:
            return PartitionedRunner(d, R, bw, br)
        if system == "concurrent" and nlogs == 1:
            return ConcurrentDsRunner(d, R, bw, br)
        if system == "sharded" and nlogs == 1:
            import jax as _jax

            if strategy is not None:
                from node_replication_tpu.parallel.mesh import (
                    strategy_devices,
                )

                n_dev = len(strategy_devices(strategy))
                if R % n_dev == 0:
                    return ShardedRunner(
                        d, R, bw, br, log_capacity=self._log_capacity,
                        strategy=strategy,
                    )
                return None
            n_dev = len(_jax.devices())
            if R % n_dev == 0:
                return ShardedRunner(d, R, bw, br, n_devices=n_dev,
                                     log_capacity=self._log_capacity)
        return None

    def run(self) -> list[MeasureResult]:
        """Execute the full cross-product; print Mops lines and append
        per-second CSV records (`scaleout_benchmarks.csv`)."""
        results = []
        rows = []
        skew_rows = []
        for R in self._replicas:
            for nlogs in self._log_strategies:
                for batch in self._batches:
                    bw, br = split_write_read(
                        batch, self.workload.write_ratio
                    )
                    for system in self._systems:
                      for strat in (self._strategies
                                    if system == "sharded" else [None]):
                        runner = self._make_runner(
                            system, nlogs, R, bw, br, strategy=strat
                        )
                        if runner is None:
                            continue
                        if (self._replay != "auto"
                                and system in ("nr", "cnr",
                                               "sharded-cnr")):
                            runner.name += f"-{self._replay}"
                        gen = generate_batches(
                            self.workload, self._steps, R, bw, br
                        )
                        res, spread, n_att = self._measure_attempts(
                            runner, gen
                        )
                        results.append(res)
                        ann = (
                            f" | spread {spread:.1f}% over "
                            f"{self._repeats}x{n_att}"
                            if self._max_attempts > 1 else ""
                        )
                        print(
                            f">> {self.name}/{runner.name} R={R} "
                            f"logs={nlogs} batch={batch}: "
                            f"{res.client_mops:.2f} Mops client "
                            f"({res.mops:.2f} Mops replayed){ann}"
                        )
                        if nlogs > 1 and hasattr(runner, "stats"):
                            # skew-faithful routing: per-log appended
                            # depths expose zipf imbalance (VERDICT r2
                            # #6), PERSISTED to the sidecar CSV so the
                            # phenomenon is a committed artifact
                            # (VERDICT r3 #5), not just a printout
                            st = runner.stats()
                            print(
                                f"## {runner.name} per-log tails "
                                f"{st['per_log_tail']} imbalance "
                                f"{st['imbalance']:.2f}"
                            )
                            skew_rows.append({
                                "name": f"{self.name}/{runner.name}",
                                "rs": R, "ls": nlogs, "batch": batch,
                                "distribution":
                                    self.workload.distribution,
                                "imbalance":
                                    round(st["imbalance"], 4),
                                "per_log_tails": "|".join(
                                    str(t) for t in st["per_log_tail"]
                                ),
                                "client_mops":
                                    round(res.client_mops, 4),
                                "replay_mops": round(res.mops, 4),
                                "spread_pct": (
                                    round(spread, 2)
                                    if self._max_attempts > 1 else ""
                                ),
                                "attempts": (
                                    n_att
                                    if self._max_attempts > 1 else ""
                                ),
                            })
                        rows.extend(sweep_rows(
                            self.name, runner.name, res, R, nlogs, batch,
                            tm=(strat.value if strat is not None
                                else "none"),
                            wr_eff=effective_write_pct(bw, br),
                        ))
        _append_csv(
            os.path.join(self._out_dir, SCALEOUT_CSV), _CSV_FIELDS, rows
        )
        if skew_rows:
            _append_csv(
                os.path.join(self._out_dir, SKEW_CSV), _SKEW_FIELDS,
                skew_rows,
            )
        return results


def effective_write_pct(bw: int, br: int) -> float:
    """The write percentage the static (Bw, Br) split actually realizes
    (`split_write_read` rounds; this records what ran — the `wr_eff`
    column's single source of truth)."""
    total = bw + br
    return round(100.0 * bw / total, 2) if total else 0.0


def sweep_rows(
    name: str, runner_name: str, res, rs: int, ls: int, batch: int,
    tm: str = "none", wr_eff: float | str = "",
) -> list[dict]:
    """Per-second CSV rows for one measured step-runner config — the
    shared row shape of SCALEOUT_CSV (used by the ScaleBenchBuilder
    sweep and by standalone benches like benches/vspace.py, so the
    dispatches derivation cannot drift between them)."""
    disp_frac = res.total_dispatches / max(res.total_client_ops, 1)
    return [
        {
            "name": f"{name}/{runner_name}",
            "rs": rs, "ls": ls, "tm": tm, "batch": batch,
            "threads": rs, "duration": round(res.duration_s, 3),
            "thread_id": -1, "core_id": -1, "second": sec,
            "ops": ops, "dispatches": int(ops * disp_frac),
            "wr_eff": wr_eff,
        }
        for sec, ops in res.per_second
    ]


@dataclasses.dataclass
class ServeResult:
    """One serve-benchmark measurement (closed- or open-loop)."""

    name: str
    mode: str  # "closed" | "open"
    clients: int
    rate: float | None  # open-loop target ops/sec (None for closed)
    duration_s: float
    latencies_s: list  # completed ops only, client-perceived seconds
    attempts: int  # submissions tried (accepted + shed)
    accepted: int
    completed: int
    shed: int
    deadline_missed: int
    errors: list  # (client, op_index, message) from the CHECKER only
    # typed ServeError failures (retry-exhausted Overloaded, deadline
    # misses, closed frontend) — transport outcomes, NOT oracle
    # violations; kept apart so `errors` can gate linearizability
    transport_errors: list
    # serve-pipeline overlap depth the frontend ran at
    # (`ServeConfig.pipeline_depth`; 0 = serial worker)
    pipeline_overlap: int = 0

    def percentile_ms(self, p: float) -> float:
        if not self.latencies_s:
            return 0.0
        return float(
            np.percentile(np.asarray(self.latencies_s), p)
        ) * 1e3

    @property
    def throughput(self) -> float:
        """Completed client ops per second over the measured wall."""
        return self.completed / self.duration_s if self.duration_s else 0.0

    @property
    def shed_rate(self) -> float:
        return self.shed / self.attempts if self.attempts else 0.0


def measure_serve(
    frontend,
    op_of: Callable[[int, int], tuple],
    n_ops: int,
    clients: int,
    mode: str = "closed",
    rate: float | None = None,
    retry=None,
    rid_of: Callable[[int], int] | None = None,
    check: Callable[[int, int, int], str | None] | None = None,
    name: str = "serve",
) -> ServeResult:
    """Drive a `ServeFrontend` from `clients` OS threads and measure
    client-perceived latency (the serve analog of the reference's
    per-thread measurement loops, `benches/mkbench.rs:592-604`).

    - `op_of(client, i)` builds op `i` of client `client`
      (`i` in `[0, n_ops // clients)`); `rid_of(client)` picks the
      submission replica (defaults to round-robin over the frontend's
      served rids).
    - **closed loop** (`mode="closed"`): each client submits, waits for
      the response, then issues its next op; `retry` (a
      `serve.client.RetryPolicy`) re-submits `Overloaded` rejections
      with backoff, and the recorded latency spans the FULL op
      (backoff included — what a closed-loop caller experiences).
    - **open loop** (`mode="open"`, requires `rate`): each client
      submits at its share of `rate` ops/sec without waiting;
      `Overloaded` sheds the op (no retry — open-loop arrivals don't
      pause), and latency is harvested from the resolved futures after
      a final `drain()`.
    - `check(client, i, resp)` returns an error string for a wrong
      response (None = ok) — the sequence-numbered no-loss/no-dup
      verification hook (`models/seqreg.py`).
    """
    if mode not in ("closed", "open"):
        raise ValueError(f"unknown serve mode {mode!r}")
    if mode == "open" and not rate:
        raise ValueError("open-loop serve measurement needs a rate")
    from node_replication_tpu.serve import (
        Overloaded,
        ServeError,
        call_with_retry,
    )

    rids = frontend.rids
    if rid_of is None:
        rid_of = lambda c: rids[c % len(rids)]  # noqa: E731
    per_client = n_ops // clients
    lat_lock = threading.Lock()
    latencies: list[float] = []
    errors: list[tuple] = []
    transport: list[tuple] = []
    attempts = [0] * clients
    open_futs: list[list] = [[] for _ in range(clients)]

    def record(lat_s: float | None, err, kind=errors) -> None:
        with lat_lock:
            if lat_s is not None:
                latencies.append(lat_s)
            if err is not None:
                kind.append(err)

    def closed_client(c: int) -> None:
        rng = random.Random(0xC0FFEE + c)
        shed_seen = [0]

        def on_shed(attempt, delay):
            shed_seen[0] += 1

        rid = rid_of(c)
        exhausted = 0
        for i in range(per_client):
            op = op_of(c, i)
            t0 = time.monotonic()
            try:
                if retry is not None:
                    resp = call_with_retry(
                        frontend, op, rid=rid, policy=retry, rng=rng,
                        on_shed=on_shed,
                    )
                else:
                    resp = frontend.call(op, rid=rid)
            except ServeError as e:
                if retry is not None and isinstance(e, Overloaded):
                    # every one of this op's submissions was a shed
                    # already counted by on_shed; don't let the
                    # per_client slot double-count it in `attempts`
                    exhausted += 1
                record(None, (c, i, f"{type(e).__name__}: {e}"),
                       kind=transport)
                continue
            lat = time.monotonic() - t0
            err = check(c, i, resp) if check is not None else None
            record(lat, (c, i, err) if err else None)
        attempts[c] = per_client + shed_seen[0] - exhausted

    def open_client(c: int) -> None:
        rid = rid_of(c)
        interval = clients / rate
        tried = 0
        next_t = time.monotonic()
        for i in range(per_client):
            now = time.monotonic()
            if now < next_t:
                time.sleep(next_t - now)
            next_t += interval
            tried += 1
            try:
                open_futs[c].append((i, frontend.submit(op_of(c, i),
                                                        rid=rid)))
            except Overloaded:
                pass  # open-loop: shed, move on (frontend counts it)
        attempts[c] = tried

    before = frontend.stats()
    target = closed_client if mode == "closed" else open_client
    threads = [
        threading.Thread(target=target, args=(c,),
                         name=f"serve-client-{c}")
        for c in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if mode == "open":
        frontend.drain()
    duration = time.perf_counter() - t0
    if mode == "open":
        for c, futs in enumerate(open_futs):
            for i, fut in futs:
                exc = fut.exception(timeout=5.0)
                if exc is not None:  # deadline miss / closed
                    record(None,
                           (c, i, f"{type(exc).__name__}: {exc}"),
                           kind=transport)
                    continue
                err = (
                    check(c, i, fut.result()) if check is not None
                    else None
                )
                record(fut.latency_s, (c, i, err) if err else None)
    after = frontend.stats()
    delta = {
        k: after[k] - before[k]
        for k in ("accepted", "completed", "shed", "deadline_missed")
    }
    return ServeResult(
        name=name,
        mode=mode,
        clients=clients,
        rate=rate,
        duration_s=duration,
        latencies_s=latencies,
        attempts=sum(attempts),
        accepted=delta["accepted"],
        completed=delta["completed"],
        shed=delta["shed"],
        deadline_missed=delta["deadline_missed"],
        errors=errors,
        transport_errors=transport,
        pipeline_overlap=int(getattr(
            getattr(frontend, "cfg", None), "pipeline_depth", 0,
        ) or 0),
    )


def serve_rows(name: str, res: ServeResult,
               profile: dict | None = None) -> list[dict]:
    """The SERVE_CSV row for one measurement. `profile` (a
    `bench.py --serve --profile` summary: hz / samples / duty_cycle /
    attributed_frac / overhead_ratio) fills the profile columns;
    unprofiled rows leave them ""."""
    prof = profile or {}
    return [{
        "name": f"{name}/{res.name}",
        "mode": res.mode,
        "pipeline_overlap": res.pipeline_overlap,
        "clients": res.clients,
        "rate": "" if res.rate is None else res.rate,
        "duration": round(res.duration_s, 3),
        "attempts": res.attempts,
        "accepted": res.accepted,
        "completed": res.completed,
        "shed": res.shed,
        "deadline_miss": res.deadline_missed,
        "throughput_ops": round(res.throughput, 1),
        "p50_ms": round(res.percentile_ms(50), 3),
        "p95_ms": round(res.percentile_ms(95), 3),
        "p99_ms": round(res.percentile_ms(99), 3),
        "profile_hz": prof.get("hz", ""),
        "profile_samples": prof.get("samples", ""),
        "profile_duty_cycle": prof.get("duty_cycle", ""),
        "profile_attributed_frac": prof.get("attributed_frac", ""),
        "profile_overhead_ratio": prof.get("overhead_ratio", ""),
    }]


def append_serve_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, SERVE_CSV), _SERVE_FIELDS, rows)


@dataclasses.dataclass
class MeshPoint:
    """One device-count point of a mesh scaling curve
    (`bench.py --mesh`)."""

    devices: int
    result: MeasureResult
    bit_identical: bool
    spread_pct: float = 0.0


def measure_mesh(
    dispatch_factory: Callable,
    device_counts: Sequence[int],
    n_replicas: int,
    writes_per_replica: int = 1,
    reads_per_replica: int = 1,
    keyspace: int = 1024,
    duration_s: float = 1.0,
    verify_steps: int = 4,
    seed: int = 0,
    wr_opcode: int = 1,
    rd_opcode: int = 1,
    repeats: int = 2,
) -> list[MeshPoint]:
    """Measure the 1→N-device scaling curve of the replica-sharded
    fused step (`ShardedRunner` over `parallel/mesh.py`), with the
    bit-identity gate the curve's honesty depends on: before each
    point is timed, the sharded fleet replays `verify_steps` fixed
    steps and its states must equal the 1-device reference fleet's
    bit-for-bit — placement must never change results, only their
    speed (the mesh acceptance contract, tests/test_mesh_fleet.py).

    `device_counts` entries must divide `n_replicas`; entry 1 runs the
    plain un-sharded runner (the flagship configuration). Each point
    is measured `repeats` times; the reported result is the MEDIAN
    repeat and `spread_pct` is the min→max spread across them (the
    flagship bench's contention annotation — a shared chip can hand a
    window a misleading slot). Returns one `MeshPoint` per count, in
    order; `mesh_rows` turns them into `mesh_benchmarks.csv` rows with
    scaling/efficiency relative to the first point.
    """
    spec = WorkloadSpec(keyspace=keyspace, write_ratio=50, seed=seed)
    S = 8
    streams = generate_batches(
        spec, S, n_replicas, writes_per_replica, reads_per_replica,
        wr_opcode=wr_opcode, rd_opcode=rd_opcode,
    )

    # 1-device reference states after the verification steps — every
    # sharded point must reproduce these bit-for-bit
    import jax

    ref = ReplicatedRunner(dispatch_factory(), n_replicas,
                           writes_per_replica, reads_per_replica)
    ref.prepare(*streams)
    for s in range(verify_steps):
        ref.run_step(s % S)
    ref.block()
    ref_leaves = [np.asarray(a) for a in jax.tree.leaves(ref.states)]

    points: list[MeshPoint] = []
    for n_dev in device_counts:
        if n_dev == 1:
            runner = ReplicatedRunner(
                dispatch_factory(), n_replicas, writes_per_replica,
                reads_per_replica,
            )
        else:
            runner = ShardedRunner(
                dispatch_factory(), n_replicas, writes_per_replica,
                reads_per_replica, n_devices=n_dev,
            )
        runner.prepare(*streams)
        for s in range(verify_steps):
            runner.run_step(s % S)
        runner.block()
        got = [np.asarray(a) for a in jax.tree.leaves(runner.states)]
        bit_identical = all(
            np.array_equal(a, b) for a, b in zip(ref_leaves, got)
        )
        results = [
            measure_step_runner(runner, *streams,
                                duration_s=duration_s)
            for _ in range(max(1, repeats))
        ]
        results.sort(key=lambda r: r.mops)
        res = results[len(results) // 2]  # median repeat
        spread = (
            100.0 * (results[-1].mops - results[0].mops) / res.mops
            if res.mops else 0.0
        )
        points.append(MeshPoint(devices=int(n_dev), result=res,
                                bit_identical=bit_identical,
                                spread_pct=spread))
    return points


def mesh_rows(name: str, points: list[MeshPoint], batch: int,
              keys: int, replicas: int | str = "") -> list[dict]:
    """MESH_CSV rows: throughput + scaling efficiency vs the curve's
    first (narrowest) point."""
    if not points:
        return []
    base = points[0].result.mops or 1e-9
    rows = []
    for p in points:
        scaling = p.result.mops / base
        rows.append({
            "name": f"{name}/mesh{p.devices}",
            "devices": p.devices,
            "replicas": replicas,
            "batch": batch,
            "keys": keys,
            "duration": round(p.result.duration_s, 3),
            "throughput_mdps": round(p.result.mops, 3),
            "scaling_x": round(scaling, 4),
            "efficiency": round(scaling / p.devices, 4),
            "bit_identical": int(p.bit_identical),
            "spread_pct": round(p.spread_pct, 2),
            "tier": "step",  # the fused lock-step scaling curve
        })
    return rows


def mesh_tier_rows(name: str, window: int,
                   points: list["KernelPoint"]) -> list[dict]:
    """MESH_CSV rows for the per-width exec-TIER column (`bench.py
    --mesh`): one row per (devices, tier) from the combiner-round
    sweep (`measure_kernel(devices=...)`) — mesh_fused vs the shmap
    chain at each width, with the counter-derived launch count."""
    return [{
        "name": f"{name}/tier{p.devices}/{p.tier}",
        "devices": p.devices,
        "replicas": p.n_replicas,
        "batch": window,
        "keys": p.n_keys,
        "duration": round(p.duration_s, 3),
        "throughput_mdps": round(p.dispatches_per_sec / 1e6, 3),
        "bit_identical": int(p.bit_identical),
        "tier": p.tier,
        "launches_per_round": p.launches_per_round,
    } for p in points]


def append_mesh_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, MESH_CSV), _MESH_FIELDS, rows)


# --------------------------------------------------------------- kernel
KERNEL_CSV = "kernel_benchmarks.csv"
_KERNEL_FIELDS = [
    "name", "tier", "devices", "replicas", "keys", "window",
    "capacity", "rounds", "duration", "dispatches_per_sec",
    "launches_per_round", "p50_ms", "p95_ms", "bit_identical",
    "interpret",
]


@dataclasses.dataclass
class KernelPoint:
    """One (config, tier) measurement of the combiner-round engines
    (`bench.py --kernel`): fused pallas round vs the append+exec chain
    on the combined and scan engines — and, with `devices > 1`, the
    MESH-FUSED shard_map round vs the shmap append+exec chain —
    bit-identity verified BEFORE any timing (a fast wrong kernel is
    worthless). `launches_per_round` is derived from the
    `kernel.launches` counter delta over the timed rounds, never a
    hardcoded constant, so the CSV cannot drift from what actually
    ran."""

    tier: str
    n_replicas: int
    n_keys: int
    window: int
    capacity: int
    rounds: int
    duration_s: float
    dispatches_per_sec: float
    launches_per_round: int
    p50_ms: float
    p95_ms: float
    bit_identical: bool
    interpret: bool
    devices: int = 1


def _kernel_batches(n_keys: int, window: int, arg_width: int, seed: int,
                    count: int = 8):
    """Seeded full-window PUT/REMOVE batches (NOOP-free: every slot
    live, the flagship round shape)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    batches = []
    for _ in range(count):
        opc = np.where(rng.rand(window) < 0.7, 1, 2).astype(np.int32)
        args = np.zeros((window, arg_width), np.int32)
        args[:, 0] = rng.randint(0, n_keys, window)
        args[:, 1] = rng.randint(0, 1 << 20, window)
        batches.append((jnp.asarray(opc), jnp.asarray(args)))
    return batches


def measure_kernel(
    n_keys: int,
    n_replicas: int,
    window: int,
    duration_s: float = 1.0,
    tiers: Sequence[str] | None = None,
    interpret: bool | None = None,
    verify_rounds: int = 4,
    seed: int = 0,
    devices: int = 1,
) -> list[KernelPoint]:
    """Measure one (R, K, W[, devices]) point across the
    combiner-round tiers.

    At `devices=1`: chain tiers (`combined`/`scan`) run the round the
    wrapper's `_append_and_replay` actually runs — an append program,
    a host boundary, then one exec program over the appended window —
    and the `pallas_fused` tier runs the `FusedHashmapEngine` raw
    round with TRANSPOSED-RESIDENT state (state stays in kernel layout
    across rounds — the flagship configuration), usually 1 launch.

    At `devices>1` the tiers are the MESH pair: `shmap` = the
    replicated append program + `make_shmap_exec` round (the PR 9
    chain, 2 programs per round), `mesh_fused` = `MeshFusedEngine`
    (`parallel/collectives.py`) — one shard_map-wrapped launch per
    device, state under `P('replica')`. The kernel_benchmarks.csv
    claim this axis exists for: `launches_per_round` stays 1 as
    devices scale.

    Before any timing, every tier replays `verify_rounds` identical
    batches from identical init and must match the 1-DEVICE SCAN tier
    bit-for-bit: model-layout states, every log cursor, the ring
    content, and per-round responses. Per-round latency (p50/p95) is
    fenced — each timed round ends on a real device fence
    (`utils/fence.py`), so the per-batch latency floor is honest, not
    dispatch-rate fiction. `launches_per_round` is the
    `kernel.launches` counter delta over the timed rounds divided by
    the round count — every runner routes its launches through that
    counter (the fused tiers via the engines' `note_round`
    instrumentation hook, the chains by counting each program
    dispatch), so the CSV reports what ran, not a constant.
    """
    import jax
    import jax.numpy as jnp

    from node_replication_tpu.core.log import (
        LogSpec,
        log_append,
        log_catchup_all,
        log_exec_all,
        log_init,
    )
    from node_replication_tpu.core.replica import replicate_state
    from node_replication_tpu.models import make_hashmap
    from node_replication_tpu.obs.metrics import get_registry
    from node_replication_tpu.utils.fence import fence

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if tiers is None:
        tiers = (
            ("mesh_fused", "shmap") if devices > 1
            else ("pallas_fused", "combined", "scan")
        )
    W = int(window)
    spec = LogSpec(
        capacity=max(4 * W, 512), n_replicas=n_replicas, arg_width=3,
        gc_slack=min(128, W),
    )
    d = make_hashmap(n_keys)
    batches = _kernel_batches(n_keys, W, spec.arg_width, seed)
    S = len(batches)
    mesh = None
    if devices > 1:
        from node_replication_tpu.parallel.mesh import replica_mesh

        if n_replicas % devices:
            raise ValueError(
                f"R={n_replicas} not divisible by devices={devices}"
            )
        if devices > len(jax.devices()):
            raise ValueError(
                f"devices={devices} requested, "
                f"{len(jax.devices())} visible"
            )
        mesh = replica_mesh(devices)
    reg = get_registry()
    launch_c = reg.counter("kernel.launches")

    def fresh_fleet():
        return log_init(spec), replicate_state(d.init_state(),
                                               n_replicas)

    def chain_runner(exec_jit, init_fn):
        # ONE chain shape for the single-device and shmap tiers:
        # append program, host boundary, exec program — each counted
        # at its dispatch site, so a change to the round protocol or
        # the launch accounting cannot diverge between them
        append_jit = jax.jit(
            functools.partial(log_append, spec), donate_argnums=(0,)
        )

        class Chain:
            def __init__(self):
                self.reset()

            def reset(self):
                # fresh fleet, SAME compiled programs: the timing
                # phase reuses the verify phase's jits instead of
                # paying every compile twice per point
                self.log, self.states = init_fn()

            def round(self, opc, args):
                self.log = append_jit(self.log, opc, args, W)
                launch_c.inc()
                self.log, self.states, resps = exec_jit(
                    self.log, self.states
                )
                launch_c.inc()
                return resps

            def model_states(self):
                return self.states

            def fence_all(self):
                fence(self.log, self.states)

        return Chain()

    def make_chain(engine: str):
        exec_fn = log_exec_all if engine == "scan" else log_catchup_all

        def exec_round(log, states):
            return exec_fn(spec, d, log, states, window=W)

        return chain_runner(
            jax.jit(exec_round, donate_argnums=(0, 1)), fresh_fleet
        )

    def make_shmap():
        from node_replication_tpu.parallel.collectives import (
            make_shmap_exec,
        )
        from node_replication_tpu.parallel.mesh import place

        return chain_runner(
            make_shmap_exec(d, spec, mesh, W),
            lambda: place(*fresh_fleet(), mesh),
        )

    def make_fused():
        eng = d.fused_factory(spec, interpret=interpret)
        if not eng.supports(W):
            raise ValueError(
                f"fused engine rejects window {W} at capacity "
                f"{spec.capacity}"
            )
        raw = eng.raw_round(W)
        run = raw if interpret else jax.jit(raw, donate_argnums=(0,))
        K = n_keys
        kp = eng.kp

        class Fused:
            def __init__(self):
                self.reset()

            def reset(self):
                self.log = log_init(spec)
                st = replicate_state(d.init_state(), n_replicas)
                self.vals = jnp.zeros((kp, n_replicas), jnp.int32).at[
                    :K].set(st["values"].T)
                self.pres = jnp.zeros_like(self.vals).at[:K].set(
                    st["present"].T.astype(jnp.int32)
                )

            def round(self, opc, args):
                t0 = time.perf_counter()
                self.log, self.vals, self.pres, resps = run(
                    self.log, self.vals, self.pres, opc, args, W
                )
                # the bench embeds raw_round in its own loop, so the
                # engine's round() wrapper never runs — report through
                # the same instrumentation hook (kernel.launches et
                # al.; one contract, never two)
                eng.note_round(W, W, time.perf_counter() - t0)
                return resps.T  # [R, W], the chain layout

            def model_states(self):
                return {
                    "values": self.vals[:K].T,
                    "present": self.pres[:K].T > 0,
                }

            def fence_all(self):
                fence(self.log, self.vals, self.pres)

        return Fused()

    def make_mesh_fused():
        from node_replication_tpu.parallel.collectives import (
            MeshFusedEngine,
        )
        from node_replication_tpu.parallel.mesh import place

        eng = MeshFusedEngine(d, spec, mesh, interpret=interpret)
        if not eng.supports(W):
            raise ValueError(
                f"mesh-fused engine rejects window {W} at capacity "
                f"{spec.capacity} over {devices} devices"
            )

        class MeshFused:
            def __init__(self):
                self.reset()

            def reset(self):
                self.log, self.states = place(
                    log_init(spec),
                    replicate_state(d.init_state(), n_replicas),
                    mesh,
                )

            def round(self, opc, args):
                # the host entry: cached shard_map program + the
                # note_round instrumentation (kernel.launches counts
                # the per-device launches)
                self.log, self.states, resps = eng.round(
                    self.log, self.states, opc, args, W
                )
                return resps

            def model_states(self):
                return self.states

            def fence_all(self):
                fence(self.log, self.states)

        return MeshFused()

    def build(tier: str):
        if tier == "pallas_fused":
            return make_fused()
        if tier == "mesh_fused":
            return make_mesh_fused()
        if tier == "shmap":
            return make_shmap()
        return make_chain(tier)

    was_enabled = reg.enabled
    reg.enable()  # launches_per_round is a counter delta
    try:
        # ---- bit-identity BEFORE timing (the 1-device scan chain is
        # the reference at EVERY devices count) --------------------
        ref = make_chain("scan")
        ref_resps = []
        for i in range(verify_rounds):
            ref_resps.append(np.asarray(ref.round(*batches[i % S])))
        ref.fence_all()
        ref_states = [np.asarray(a)
                      for a in jax.tree.leaves(ref.model_states())]
        ref_log = jax.tree.map(np.asarray, ref.log)

        points: list[KernelPoint] = []
        for tier in tiers:
            runner = build(tier)
            ok = True
            for i in range(verify_rounds):
                got = np.asarray(runner.round(*batches[i % S]))
                if not np.array_equal(got, ref_resps[i]):
                    ok = False
            runner.fence_all()
            got_states = [
                np.asarray(a)
                for a in jax.tree.leaves(runner.model_states())
            ]
            ok = ok and all(
                np.array_equal(a, b)
                for a, b in zip(ref_states, got_states)
            ) and all(
                np.array_equal(np.asarray(a), b)
                for a, b in zip(jax.tree.leaves(runner.log),
                                jax.tree.leaves(ref_log))
            )
            # ---- fenced per-round timing on a fresh fleet ----------
            # (same runner: the verify rounds already compiled +
            # warmed every program; reset() only re-inits the fleet)
            runner.reset()
            runner.round(*batches[0])  # warm from the fresh init
            runner.fence_all()
            lat: list[float] = []
            total = 0.0
            i = 0
            mark = launch_c.value
            while total < duration_s or len(lat) < 3:
                t0 = time.perf_counter()
                runner.round(*batches[i % S])
                runner.fence_all()
                dt = time.perf_counter() - t0
                lat.append(dt)
                total += dt
                i += 1
                if len(lat) >= 10_000:  # interpret-mode safety valve
                    break
            lat.sort()
            rounds = len(lat)
            dps = n_replicas * W * rounds / total if total else 0.0
            points.append(KernelPoint(
                tier=tier, n_replicas=n_replicas, n_keys=n_keys,
                window=W, capacity=spec.capacity, rounds=rounds,
                duration_s=total, dispatches_per_sec=dps,
                launches_per_round=(
                    (launch_c.value - mark) // rounds
                ),
                p50_ms=1e3 * lat[rounds // 2],
                p95_ms=1e3 * lat[min(rounds - 1, int(rounds * 0.95))],
                bit_identical=ok, interpret=interpret,
                devices=devices,
            ))
    finally:
        reg.enabled = was_enabled
    return points


def kernel_rows(name: str, points: list[KernelPoint]) -> list[dict]:
    """KERNEL_CSV rows for one (R, K, W[, devices]) point's tier
    sweep."""
    return [{
        "name": f"{name}/{p.tier}",
        "tier": p.tier,
        "devices": p.devices,
        "replicas": p.n_replicas,
        "keys": p.n_keys,
        "window": p.window,
        "capacity": p.capacity,
        "rounds": p.rounds,
        "duration": round(p.duration_s, 3),
        "dispatches_per_sec": round(p.dispatches_per_sec, 1),
        "launches_per_round": p.launches_per_round,
        "p50_ms": round(p.p50_ms, 4),
        "p95_ms": round(p.p95_ms, 4),
        "bit_identical": int(p.bit_identical),
        "interpret": int(p.interpret),
    } for p in points]


def append_kernel_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, KERNEL_CSV), _KERNEL_FIELDS, rows)


@dataclasses.dataclass
class ChaosResult:
    """One chaos measurement: a sequence-verified closed-loop serve run
    with a `FaultPlan` killing (and the lifecycle manager repairing)
    replicas mid-flight (`bench.py --chaos`)."""

    serve: "ServeResult"
    fired: list  # the plan's fired-fault records
    repairs: list  # ReplicaLifecycleManager repair reports
    rehomed: int
    health: dict  # HealthTracker snapshot after the run settles

    @property
    def availability(self) -> float:
        """Completed / attempted client ops over the chaos window —
        with pre-append failover + transparent retry this should be
        1.0: a kill costs latency, never responses."""
        a = self.serve.attempts
        return self.serve.completed / a if a else 0.0

    def repair_ms(self, p: float) -> float:
        durs = sorted(r["duration_s"] for r in self.repairs)
        if not durs:
            return 0.0
        k = max(0, min(len(durs) - 1,
                       int(round(p / 100.0 * (len(durs) - 1)))))
        return durs[k] * 1e3


def measure_chaos(
    frontend,
    manager,
    plan,
    op_of: Callable[[int, int], tuple],
    n_ops: int,
    clients: int,
    retry=None,
    check: Callable[[int, int, int], str | None] | None = None,
    name: str = "chaos",
    settle_timeout_s: float = 60.0,
) -> ChaosResult:
    """Closed-loop `measure_serve` with `plan` armed for the duration:
    injected kills retire replicas, the lifecycle `manager` repairs and
    readmits them, and clients ride `call_with_retry`'s failover
    re-route — so the oracle (`check`, usually seqreg) verifies that
    the kill cost latency, not correctness. Waits for outstanding
    repairs to settle before reporting."""
    stats0 = frontend.stats()
    with plan.armed():
        res = measure_serve(
            frontend, op_of, n_ops, clients, mode="closed",
            retry=retry, check=check, name=name,
        )
    if not manager.wait_idle(settle_timeout_s):
        res.transport_errors.append(
            (-1, -1, "repair did not settle within "
                     f"{settle_timeout_s}s")
        )
    rehomed = frontend.stats()["rehomed"] - stats0.get("rehomed", 0)
    return ChaosResult(
        serve=res,
        fired=list(plan.fired),
        repairs=list(manager.repairs),
        rehomed=rehomed,
        health=manager.health.snapshot(),
    )


def chaos_rows(name: str, res: ChaosResult) -> list[dict]:
    """The CHAOS_CSV row for one measurement."""
    s = res.serve
    return [{
        "name": f"{name}/{s.name}",
        "clients": s.clients,
        "duration": round(s.duration_s, 3),
        "attempts": s.attempts,
        "completed": s.completed,
        "lost": s.attempts - s.completed,
        "kills": len(res.fired),
        "repairs": len(res.repairs),
        "rehomed": res.rehomed,
        "availability": round(res.availability, 6),
        "repair_p50_ms": round(res.repair_ms(50), 3),
        "repair_p95_ms": round(res.repair_ms(95), 3),
        "repair_max_ms": round(res.repair_ms(100), 3),
        "throughput_ops": round(s.throughput, 1),
        "p50_ms": round(s.percentile_ms(50), 3),
        "p95_ms": round(s.percentile_ms(95), 3),
        "p99_ms": round(s.percentile_ms(99), 3),
    }]


def append_chaos_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, CHAOS_CSV), _CHAOS_FIELDS, rows)


def recovery_rows(name: str, report, *, clients: int, durability: str,
                  acked: int, kill_after: int, lost: int,
                  duplicated: int, post_restart_ops: int) -> list[dict]:
    """The RECOVERY_CSV row for one crash-recovery measurement
    (`report` is a `durable/recovery.py:RecoveryReport`; the kwargs
    carry what the crash harness observed around it)."""
    return [{
        "name": f"{name}/crash-seqreg",
        "clients": clients,
        "durability": durability,
        "acked": acked,
        "kill_after_acks": kill_after,
        "snapshot_pos": report.snapshot_pos,
        "wal_records": report.wal_records,
        "wal_ops": report.wal_ops,
        "truncated_bytes": report.wal_truncated_bytes,
        "recovery_s": round(report.duration_s, 4),
        "tail": report.tail,
        "lost": lost,
        "duplicated": duplicated,
        "post_restart_ops": post_restart_ops,
    }]


def append_recovery_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, RECOVERY_CSV),
                _RECOVERY_FIELDS, rows)


def replication_rows(name: str, report, *, clients: int, acked: int,
                     kill_after: int, max_lag_pos: int, reads: int,
                     stale_reads: int, lost: int, duplicated: int,
                     post_restart_ops: int) -> list[dict]:
    """The REPLICATION_CSV row for one follower-failover measurement
    (`report` is a `repl/promote.py:PromotionReport`; the kwargs carry
    what the follower harness observed around it)."""
    return [{
        "name": f"{name}/follower-seqreg",
        "clients": clients,
        "acked": acked,
        "kill_after_acks": kill_after,
        "max_lag_pos": max_lag_pos,
        "reads": reads,
        "stale_reads": stale_reads,
        "applied_pos": report.applied_pos,
        "new_epoch": report.new_epoch,
        "drained_records": report.drained_records,
        "detect_s": round(report.detect_s, 4),
        "promote_s": round(report.promote_s, 4),
        "rto_s": round(report.rto_s, 4),
        "lost": lost,
        "duplicated": duplicated,
        "post_restart_ops": post_restart_ops,
    }]


def overload_rows(name: str, run: dict) -> list[dict]:
    """The OVERLOAD_CSV row for one `bench.py --overload` run dict
    (the bench builds one per mode: `static` and `adaptive`)."""
    return [{
        "name": f"{name}/{run['mode']}",
        "mode": run["mode"],
        "pipeline_overlap": run.get("pipeline_overlap", 0),
        "clients": run["clients"],
        "capacity_ops": round(run["capacity_ops"], 1),
        "rate": round(run["rate"], 1),
        "deadline_ms": round(run["deadline_s"] * 1e3, 3),
        "duration": round(run["duration_s"], 3),
        "arrivals": run["arrivals"],
        "accepted": run["accepted"],
        "completed": run["completed"],
        "good": run["good"],
        "goodput_ops": round(run["goodput"], 1),
        "shed": run["shed"],
        "shed_critical": run["shed_by_priority"].get("critical", 0),
        "shed_normal": run["shed_by_priority"].get("normal", 0),
        "shed_bulk": run["shed_by_priority"].get("bulk", 0),
        "evicted": run["evicted"],
        "circuit_open": run["circuit_open"],
        "deadline_miss": run["deadline_miss"],
        "brownout_reads": run["brownout_reads"],
        "max_brownout_lag": run["max_brownout_lag"],
        "priority_inversions": run["priority_inversions"],
        "p50_ms": round(run["p50_ms"], 3),
        "p99_ms": round(run["p99_ms"], 3),
        "lost": run["lost"],
        "duplicated": run["duplicated"],
    }]


def append_overload_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, OVERLOAD_CSV),
                _OVERLOAD_FIELDS, rows)


def append_replication_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, REPLICATION_CSV),
                _REPLICATION_FIELDS, rows)


def tree_rows(name: str, run: dict) -> list[dict]:
    """The TREE_CSV row for one `bench.py --tree` run dict (see
    `_TREE_FIELDS` for the gated column groups)."""
    return [{
        "name": f"{name}/tree-seqreg",
        "relays": run["relays"],
        "followers": run["followers"],
        "acked": run["acked"],
        "agg_reads_ops": round(run["agg_reads_ops"], 1),
        "single_reads_ops": round(run["single_reads_ops"], 1),
        "read_scaling_x": round(run["read_scaling_x"], 3),
        "primary_tput_hold": round(run["primary_tput_hold"], 3),
        "bootstrap_pos": run["bootstrap_pos"],
        "bootstrap_s": round(run["bootstrap_s"], 4),
        "full_replay_s": round(run["full_replay_s"], 4),
        "bootstrap_speedup_x": round(run["bootstrap_speedup_x"], 3),
        "detect_s": round(run["detect_s"], 4),
        "promote_s": round(run["promote_s"], 4),
        "rto_s": round(run["rto_s"], 4),
        "lost": run["lost"],
        "duplicated": run["duplicated"],
        "post_restart_ops": run["post_restart_ops"],
        # --tree-obs fleet-observability columns (0 when the run had
        # no exporters; _append_csv's header-mismatch rewrite keeps
        # pre-obs CSVs aligned)
        "obs_nodes": run.get("obs_nodes", 0),
        "obs_records": run.get("obs_records", 0),
        "obs_multiproc_records": run.get("obs_multiproc_records", 0),
    }]


def append_tree_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, TREE_CSV), _TREE_FIELDS, rows)


def sharded_rows(name: str, run: dict) -> list[dict]:
    """The SHARDED_CSV row for one `bench.py --sharded` run dict (see
    `_SHARDED_FIELDS` for the gated column groups)."""
    return [{
        "name": f"{name}/sharded-seqreg",
        "n_shards": run["n_shards"],
        "clients": run["clients"],
        "duration": round(run["duration"], 3),
        "baseline_ops": round(run["baseline_ops"], 1),
        "aggregate_ops": round(run["aggregate_ops"], 1),
        "scaling_x": round(run["scaling_x"], 3),
        "acked": run["acked"],
        "victim_shard": run["victim_shard"],
        "victim_acked": run["victim_acked"],
        "detect_s": round(run["detect_s"], 4),
        "promote_s": round(run["promote_s"], 4),
        "rto_s": round(run["rto_s"], 4),
        "survivor_hold": round(run["survivor_hold"], 3),
        "lost": run["lost"],
        "duplicated": run["duplicated"],
        "post_promote_ops": run["post_promote_ops"],
    }]


def append_sharded_csv(out_dir: str, rows: list[dict]) -> None:
    _append_csv(os.path.join(out_dir, SHARDED_CSV),
                _SHARDED_FIELDS, rows)


def txn_rows(name: str, run: dict) -> list[dict]:
    """The SHARDED_CSV row for one `bench.py --txn` run dict: the
    SIGKILL-matrix atomicity gate plus the non-txn throughput-parity
    leg (columns the plain --sharded rows leave blank)."""
    return [{
        "name": f"{name}/sharded-txn",
        "n_shards": run["n_shards"],
        "clients": run["clients"],
        "duration": round(run["duration"], 3),
        "acked": run["acked"],
        "lost": run["lost"],
        "duplicated": run["duplicated"],
        "txn_rounds": run["txn_rounds"],
        "txn_acked": run["txn_acked"],
        "txn_in_doubt": run["txn_in_doubt"],
        "txn_resolved": run["txn_resolved"],
        "txn_half_committed": run["txn_half_committed"],
        "txn_parity": round(run["txn_parity"], 3),
    }]


def reshard_rows(name: str, run: dict) -> list[dict]:
    """The SHARDED_CSV row for one `bench.py --reshard` run dict: the
    live N->2N split's exactness + bounded-unavailability gates."""
    return [{
        "name": f"{name}/sharded-reshard",
        "n_shards": run["n_shards"],
        "clients": run["clients"],
        "duration": round(run["duration"], 3),
        "acked": run["acked"],
        "lost": run["lost"],
        "duplicated": run["duplicated"],
        "moved_keys": run["moved_keys"],
        "reshard_lost": run["reshard_lost"],
        "reshard_dup": run["reshard_dup"],
        "fence_s": round(run["fence_s"], 4),
        "moved_unavail_s": round(run["moved_unavail_s"], 4),
    }]


def measure_native(
    runner: NativeRunner, duration_s: float = 2.0, seed: int = 1
) -> MeasureResult:
    """Measure a native-engine runner (threads in C++). Per-second buckets
    come from the engine's real in-loop bins, and `native_rows` returns
    genuine per-(thread, second) CSV records — not a fabricated division
    (VERDICT r1 #3; reference granularity `benches/mkbench.rs:498-552`).
    For the native engine every completed client op is exactly one
    dispatch on the issuing replica's path, so ops == dispatches."""
    total, per, per_sec = runner.run_duration(int(duration_s * 1000), seed)
    runner.last_per_thread = per
    runner.last_per_sec = per_sec
    by_sec = per_sec.sum(axis=0)
    return MeasureResult(
        name=runner.name,
        total_dispatches=int(total),
        duration_s=duration_s,
        per_second=[(s, int(by_sec[s])) for s in range(len(by_sec))],
        total_client_ops=int(total),
    )


def native_rows(
    runner: NativeRunner, res: MeasureResult, name: str, batch: int,
    wr_eff: float | str = "",
) -> list[dict]:
    """Per-(thread, second) CSV rows from the native engine's real bins.
    Native loops flip a per-op coin, so their effective write ratio IS
    the nominal one — callers pass it through as `wr_eff`."""
    per_sec = runner.last_per_sec
    rows = []
    n_threads, n_secs = per_sec.shape
    for t in range(n_threads):
        for s in range(n_secs):
            rows.append(
                {
                    "name": f"{name}/{runner.name}",
                    "rs": runner.n_replicas,
                    "ls": runner.nlogs,
                    "tm": "none",
                    "batch": batch,
                    "threads": n_threads,
                    "duration": round(res.duration_s, 3),
                    "thread_id": t,
                    "core_id": t % runner.n_replicas,
                    "second": s,
                    "ops": int(per_sec[t, s]),
                    "dispatches": int(per_sec[t, s]),
                    "wr_eff": wr_eff,
                }
            )
    return rows
