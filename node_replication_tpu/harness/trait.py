"""The ReplicaTrait abstraction: one runner protocol for every system.

The reference's harness runs NR replicas, CNR replicas, partitioned data
structures, and plain concurrent data structures under one `ReplicaTrait`
(`benches/mkbench.rs:77-139`), with `Partitioner<T>` and `ConcurrentDs<T>`
as the comparison wrappers (`benches/hashmap_comparisons.rs:25-142`). The
TPU equivalents here are *fleet step runners*: each owns pre-staged
`[S, R, B]` workload arrays and exposes `run_step(s)` as one device
computation, plus the native CPU engine as a duration-based runner.

Dispatch accounting is honest per SURVEY.md §7: `dispatches_per_step`
counts *executed* dispatches — NR replay applies every appended entry on
every replica (R × span), partitioned/concurrent baselines apply each op
once.
"""

from __future__ import annotations

import abc
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from node_replication_tpu.core.log import LogSpec, log_init
from node_replication_tpu.utils.fence import fence
from node_replication_tpu.core.multilog import (
    MultiLogSpec,
    make_multilog_step,
    multilog_init,
)
from node_replication_tpu.core.replica import replicate_state
from node_replication_tpu.core.step import make_step
from node_replication_tpu.ops.encoding import (
    Dispatch,
    apply_write,
    dispatch_reads,
)


class FleetRunner(abc.ABC):
    """A system under test, driven step-by-step over pre-staged batches.

    Two throughput counters per step (VERDICT r1 #3 — the reference's Mops
    counts *completed client ops* regardless of replication,
    `benches/mkbench.rs:592-604`, while the repo's driver metric counts
    *replayed dispatches*):

    - `client_ops_per_step` — ops a client issued and got answered
      (cross-system comparable: one write is ONE client op no matter how
      many replicas replay it);
    - `dispatches_per_step` — executed dispatches (NR replays every entry
      on every replica: R × span + reads).
    """

    name: str = "base"
    n_replicas: int = 1
    dispatches_per_step: int = 0
    client_ops_per_step: int = 0

    @abc.abstractmethod
    def prepare(self, wr_opc, wr_args, rd_opc, rd_args) -> None:
        """Stage `[S, R, B]`-shaped workload arrays on device."""

    @abc.abstractmethod
    def run_step(self, s: int) -> None:
        """Execute step `s` (asynchronously; call `block()` to fence)."""

    def block(self) -> None:
        """Fence outstanding device work. Implementations MUST use
        `utils.fence.fence` (a data-dependent D2H readback):
        `jax.block_until_ready` does not wait for execution on the
        tunneled axon platform, and fencing with it turns every timed
        region into a dispatch-rate fiction (round-3 discovery)."""

    def state_dump(self, rid: int = 0):
        """Replica state as a host pytree (the verify hook)."""
        raise NotImplementedError

    def replicas_equal(self) -> bool:
        return True


class ReplicatedRunner(FleetRunner):
    """NR: R replicas behind one shared log (`nr` crate equivalent)."""

    def __init__(self, dispatch: Dispatch, n_replicas: int,
                 writes_per_replica: int, reads_per_replica: int,
                 log_capacity: int | None = None,
                 track_resp: int | None = None,
                 combined: bool | None = None,
                 make_engine: bool = True):
        self.name = "nr"
        self.dispatch = dispatch
        self.n_replicas = n_replicas
        self.Bw, self.Br = writes_per_replica, reads_per_replica
        span = n_replicas * writes_per_replica
        self.spec = LogSpec(
            capacity=log_capacity or max(4 * span, 1 << 14),
            n_replicas=n_replicas,
            arg_width=dispatch.arg_width,
            gc_slack=min(8192, span),
        )
        # make_engine=False: a subclass brings its own step + states
        # (e.g. the pallas vspace runner) — skip building the default
        # engine and the replicated model state it would allocate
        self._combined = combined
        if make_engine:
            self.step = make_step(dispatch, self.spec, self.Bw, self.Br,
                                  combined=combined)
            self.states = replicate_state(dispatch.init_state(),
                                          n_replicas)
        self.log = log_init(self.spec)
        # Each appended entry is replayed by every replica + local reads.
        self.dispatches_per_step = n_replicas * span + n_replicas * self.Br
        # A client write is one op regardless of replication.
        self.client_ops_per_step = span + n_replicas * self.Br
        # `track_resp`: count write responses equal to this value across
        # the run, accumulated ON DEVICE (no per-step D2H) — e.g. the
        # open-addressing map's -2 window-full drops (VERDICT r2 #9).
        self.track_resp = track_resp
        self._tracked = jnp.zeros((), jnp.int64)
        self._writes_seen = 0

    def grow(self, k: int = 1) -> None:
        """Dynamic replica registration under the harness
        (`Log::register`, `nr/src/log.rs:272-292`): widen a LIVE runner by
        `k` replicas between steps. The runner fleet is lock-step by
        construction (every step leaves `ltails == tail` and identical
        states), so the newcomers are bit-copies of replica 0 at the
        current cursor — no catch-up needed, exactly the degenerate case
        of `NodeReplicated.grow_fleet`'s donor-snapshot join. The step is
        rebuilt for the wider fleet; call `prepare()` again with
        `[S, R+k, ...]` batches before the next `run_step`.
        """
        import dataclasses

        if k < 1:
            raise ValueError("grow needs k >= 1")
        if type(self) is not ReplicatedRunner:
            # subclasses bring their own step (sharded jit, pallas
            # kernel); rebuilding the generic one here would silently
            # drop their engine — they must override grow themselves
            raise NotImplementedError(
                f"{type(self).__name__} does not support grow()"
            )
        # validate + build the wider step FIRST: if the new span doesn't
        # fit the log, make_step raises before any runner state mutates
        # (a caller catching the error keeps a consistent runner)
        new_R = self.n_replicas + k
        new_spec = dataclasses.replace(self.spec, n_replicas=new_R)
        new_step = make_step(self.dispatch, new_spec, self.Bw, self.Br,
                             combined=self._combined)
        self.states = jax.tree.map(
            lambda x: jnp.concatenate([x] + [x[:1]] * k, axis=0),
            self.states,
        )
        self.log = self.log._replace(
            ltails=jnp.concatenate(
                [self.log.ltails,
                 jnp.broadcast_to(self.log.tail[None], (k,))]
            )
        )
        self.n_replicas = new_R
        self.spec = new_spec
        self.step = new_step
        span = new_R * self.Bw
        self.dispatches_per_step = new_R * span + new_R * self.Br
        self.client_ops_per_step = span + new_R * self.Br

    def prepare(self, wr_opc, wr_args, rd_opc, rd_args):
        self._w = (jax.device_put(wr_opc), jax.device_put(wr_args))
        self._r = (jax.device_put(rd_opc), jax.device_put(rd_args))

    def run_step(self, s: int):
        self.log, self.states, wr, self._last = self.step(
            self.log, self.states,
            self._w[0][s], self._w[1][s], self._r[0][s], self._r[1][s],
        )
        if self.track_resp is not None:
            # wr[r, j] answers replica r's own j-th write: summing the
            # whole matrix counts each client write exactly once
            self._tracked = self._tracked + jnp.sum(
                (wr == self.track_resp).astype(jnp.int64)
            )
            self._writes_seen += self.n_replicas * self.Bw

    def tracked_rate(self) -> tuple[int, int]:
        """(count, writes_seen) of tracked write responses; one readback."""
        return int(self._tracked), self._writes_seen

    def block(self):
        fence(self.log, self.states)

    def state_dump(self, rid: int = 0):
        return jax.tree.map(lambda a: np.asarray(a[rid]), self.states)

    def replicas_equal(self) -> bool:
        from node_replication_tpu.core.replica import states_equal

        return states_equal(self.states)


class MultiLogRunner(FleetRunner):
    """CNR: R replicas behind L key-hash-partitioned logs (`cnr`
    equivalent).

    Routing is SKEW-FAITHFUL (VERDICT r2 #6): every write goes to log
    `key % L` — the LogMapper hash (`cnr/src/replica.rs:435`) — with NO
    re-balancing, so a zipf-hot key concentrates its whole conflict class
    on one log and per-log load imbalance is visible exactly as the
    reference's CNR experiences it (`benches/hashmap.rs:143-150`). Per-log
    batches are padded to the stream's worst bucket (static shapes); the
    per-STEP `counts[s, l]` differ, and `stats()` exposes the per-log
    appended depths so imbalance can be measured.

    Because `log = key % L`, the routed buckets satisfy the congruence
    invariant (`key ≡ log (mod L)`) by construction, so a
    `PartitionedModel` (`models/partitioned.py`) can replay all L logs in
    one vmapped computation (the parallel-combining payoff) with no key
    rewriting. Pass `rebalance=True` to opt back into the r2-style
    balanced congruence re-key (equal buckets; maximizes vmap occupancy
    at the cost of workload fidelity).
    """

    def __init__(self, dispatch: Dispatch, n_replicas: int, nlogs: int,
                 writes_per_replica: int, reads_per_replica: int,
                 log_capacity: int | None = None,
                 partitioned=None, keyspace: int | None = None,
                 rebalance: bool = False,
                 combined: bool | None = None):
        self.name = f"cnr{nlogs}" + ("p" if partitioned is not None else "")
        self.dispatch = dispatch
        self.n_replicas = n_replicas
        self.nlogs = nlogs
        self.keyspace = keyspace
        self.rebalance = rebalance
        self.partitioned = partitioned
        self.log_capacity = log_capacity
        self.combined = combined
        self.Bw, self.Br = writes_per_replica, reads_per_replica
        self.B = None  # per-log pad width; fixed by prepare() from data
        self.step = None

    def _build(self, B: int):
        """Instantiate spec/step/state once the per-log pad width is
        known (prepare time — B is the routed stream's worst bucket)."""
        self.B = B
        self.spec = MultiLogSpec(
            nlogs=self.nlogs,
            capacity=self.log_capacity or max(4 * B, 1 << 12),
            n_replicas=self.n_replicas,
            arg_width=self.dispatch.arg_width,
            gc_slack=min(1024, max(B, 1)),
        )
        if self.combined and self.partitioned is None:
            raise ValueError(
                "combined=True needs a PartitionedModel (per-log "
                "window_apply runs on state partitions); the "
                "partitioned=None fold path is scan-only"
            )
        self.ml = multilog_init(self.spec)
        self.states = replicate_state(
            self.dispatch.init_state(), self.n_replicas
        )
        self.step = self._jit_step(B)

    def _jit_step(self, B: int):
        """Build the jitted step (hook: ShardedCnrRunner re-jits with
        mesh shardings and places self.ml/self.states on the mesh)."""
        return make_multilog_step(
            self.dispatch, self.spec, B, self.Br,
            partitioned=self.partitioned,
            combined=self.combined if self.partitioned is not None
            else None,
        )

    def _place_streams(self, opc_b, args_b, counts, rd_opc, rd_args):
        """Stage the routed streams on device (hook: the sharded runner
        pins them to mesh axes instead)."""
        self._w = (jnp.asarray(opc_b), jnp.asarray(args_b))
        self._counts = jnp.asarray(counts, jnp.int64)
        self._r = (jax.device_put(rd_opc), jax.device_put(rd_args))

    def prepare(self, wr_opc, wr_args, rd_opc, rd_args):
        S = wr_opc.shape[0]
        L = self.nlogs
        A = wr_args.shape[-1]
        N = int(np.prod(wr_opc.shape[1:]))  # client writes per step
        if N == 0:  # read-only sweep: no write buckets
            self._build(0)
            # through the placement hook, so the sharded runner pins
            # even an empty write stream + the reads to their mesh axes
            self._place_streams(
                np.zeros((S, L, 0), np.int32),
                np.zeros((S, L, 0, A), np.int32),
                np.zeros((S, L), np.int64), rd_opc, rd_args,
            )
            self.dispatches_per_step = self.n_replicas * self.Br
            self.client_ops_per_step = self.n_replicas * self.Br
            return
        if wr_opc.shape[1:] != (self.n_replicas, self.Bw):
            raise ValueError(
                f"write stream is shaped {wr_opc.shape[1:]}, but this "
                f"runner was declared (R={self.n_replicas}, "
                f"Bw={self.Bw}) writes per step"
            )
        flat_opc = np.ascontiguousarray(np.asarray(wr_opc).reshape(S, N))
        flat_args = np.ascontiguousarray(
            np.asarray(wr_args).reshape(S, N, A)
        )
        if self.rebalance:
            opc_b, args_b, counts = self._rebalanced(flat_opc, flat_args)
        else:
            opc_b, args_b, counts = self._hash_routed(flat_opc, flat_args)
        self._build(opc_b.shape[2])
        self._place_streams(opc_b, args_b, counts, rd_opc, rd_args)
        # Appended entries per step from the ACTUAL routed counts (they
        # sum to N for hash routing, and to L*ceil(N/L) for the tiled
        # rebalance) — each is one client write, replayed by every
        # replica; padding slots beyond counts never append.
        appended = int(counts[0].sum())
        self.dispatches_per_step = (
            self.n_replicas * appended + self.n_replicas * self.Br
        )
        self.client_ops_per_step = appended + self.n_replicas * self.Br

    def _hash_routed(self, flat_opc, flat_args):
        """Stable-bucket the stream by `key % L` (the LogMapper hash),
        preserving per-log stream order; pad to the worst bucket."""
        S, N = flat_opc.shape
        L = self.nlogs
        logidx = flat_args[..., 0].astype(np.int64) % L
        counts = np.zeros((S, L), np.int64)
        for s in range(S):
            counts[s] = np.bincount(logidx[s], minlength=L)
        B = int(counts.max())
        opc_b = np.zeros((S, L, B), np.int32)  # NOOP padding
        args_b = np.zeros((S, L, B, flat_args.shape[-1]), np.int32)
        # padded slots keep the congruence invariant (key ≡ log mod L)
        args_b[..., 0] = np.arange(L, dtype=np.int32)[None, :, None]
        for s in range(S):
            order = np.argsort(logidx[s] * N + np.arange(N))
            slog = logidx[s][order]
            pos = np.arange(N) - np.searchsorted(slog, slog)
            opc_b[s, slog, pos] = flat_opc[s][order]
            args_b[s, slog, pos] = flat_args[s][order]
        return opc_b, args_b, counts

    def _rebalanced(self, flat_opc, flat_args):
        """r2-style balanced congruence re-key (opt-in): equal per-log
        buckets; keys rewritten into the bucket's congruence class within
        the keyspace truncated to a multiple of L."""
        S, N = flat_opc.shape
        L = self.nlogs
        B = -(-N // L)
        need = L * B
        if N < need:
            reps = -(-need // N)
            flat_opc = np.tile(flat_opc, (1, reps))
            flat_args = np.tile(flat_args, (1, reps, 1))
        opc_b = flat_opc[:, :need].reshape(S, L, B)
        args_b = flat_args[:, :need].reshape(S, L, B, -1).copy()
        base = (
            self.keyspace
            if self.keyspace is not None
            else int(args_b[..., 0].max()) + 1
        )
        if base < L:
            raise ValueError(
                f"keyspace {base} < nlogs {L}: the congruence re-key "
                f"cannot give every log a distinct key class"
            )
        k_eff = (base // L) * L
        lanes = np.arange(L, dtype=np.int32)[None, :, None]
        args_b[..., 0] = (
            (args_b[..., 0] % k_eff) // L
        ) * L + lanes
        counts = np.full((S, L), B, np.int64)
        return opc_b, args_b, counts

    def run_step(self, s: int):
        self.ml, self.states, _, self._last = self.step(
            self.ml, self.states, self._w[0][s], self._w[1][s],
            self._counts[s], self._r[0][s], self._r[1][s],
        )

    def block(self):
        fence(self.ml, self.states)

    def stats(self) -> dict:
        """Per-log progress — the observable where zipf imbalance shows:
        a hot key's log runs ahead of the others in appended depth."""
        tails = [int(x) for x in np.asarray(self.ml.tail)]
        total = sum(tails)
        mean = total / max(len(tails), 1)
        return {
            "per_log_tail": tails,
            "appended_total": total,
            "imbalance": (max(tails) / mean) if mean else 1.0,
        }

    def state_dump(self, rid: int = 0):
        return jax.tree.map(lambda a: np.asarray(a[rid]), self.states)


class PartitionedRunner(FleetRunner):
    """`Partitioner<T>` comparison (`benches/hashmap_comparisons.rs:25-84`):
    one data structure per replica, NO shared log — each shard applies only
    its own batch. The no-replication upper bound on write scaling."""

    def __init__(self, dispatch: Dispatch, n_replicas: int,
                 writes_per_replica: int, reads_per_replica: int):
        self.name = "partitioned"
        self.dispatch = dispatch
        self.n_replicas = n_replicas
        self.Bw, self.Br = writes_per_replica, reads_per_replica
        self.states = replicate_state(dispatch.init_state(), n_replicas)
        self.dispatches_per_step = n_replicas * (self.Bw + self.Br)
        self.client_ops_per_step = self.dispatches_per_step

        def step(states, wr_opc, wr_args, rd_opc, rd_args):
            def one(state, opcs, args):
                def body(st, x):
                    o, a = x
                    st, resp = apply_write(dispatch, st, o, a)
                    return st, resp

                return jax.lax.scan(body, state, (opcs, args))

            states, wr = jax.vmap(one)(states, wr_opc, wr_args)
            rd = dispatch_reads(dispatch, states, rd_opc, rd_args)
            return states, wr, rd

        self.step = jax.jit(step, donate_argnums=(0,))

    def prepare(self, wr_opc, wr_args, rd_opc, rd_args):
        self._w = (jax.device_put(wr_opc), jax.device_put(wr_args))
        self._r = (jax.device_put(rd_opc), jax.device_put(rd_args))

    def run_step(self, s: int):
        self.states, _, self._last = self.step(
            self.states, self._w[0][s], self._w[1][s],
            self._r[0][s], self._r[1][s],
        )

    def block(self):
        fence(self.states)

    def state_dump(self, rid: int = 0):
        return jax.tree.map(lambda a: np.asarray(a[rid]), self.states)


class ConcurrentDsRunner(FleetRunner):
    """`ConcurrentDs<T>` passthrough (`benches/hashmap_comparisons.rs:
    92-142`): ONE un-replicated data structure; the whole fleet's ops fold
    into it sequentially. The single-structure baseline."""

    def __init__(self, dispatch: Dispatch, n_replicas: int,
                 writes_per_replica: int, reads_per_replica: int):
        self.name = "concurrent"
        self.dispatch = dispatch
        self.n_replicas = n_replicas
        self.Bw, self.Br = writes_per_replica, reads_per_replica
        self.state = dispatch.init_state()
        self.dispatches_per_step = n_replicas * (self.Bw + self.Br)
        self.client_ops_per_step = self.dispatches_per_step

        def step(state, wr_opc, wr_args, rd_opc, rd_args):
            def body(st, x):
                o, a = x
                st, resp = apply_write(dispatch, st, o, a)
                return st, resp

            A = wr_args.shape[-1]
            state, wr = jax.lax.scan(
                body, state, (wr_opc.reshape(-1), wr_args.reshape(-1, A))
            )
            rd = dispatch_reads(
                dispatch,
                jax.tree.map(lambda x: x[None], state),
                rd_opc.reshape(1, -1),
                rd_args.reshape(1, -1, A),
            )
            return state, wr, rd

        self.step = jax.jit(step, donate_argnums=(0,))

    def prepare(self, wr_opc, wr_args, rd_opc, rd_args):
        self._w = (jax.device_put(wr_opc), jax.device_put(wr_args))
        self._r = (jax.device_put(rd_opc), jax.device_put(rd_args))

    def run_step(self, s: int):
        self.state, _, self._last = self.step(
            self.state, self._w[0][s], self._w[1][s],
            self._r[0][s], self._r[1][s],
        )

    def block(self):
        fence(self.state)

    def state_dump(self, rid: int = 0):
        return jax.tree.map(np.asarray, self.state)


class ShardedRunner(ReplicatedRunner):
    """NR fleet sharded over a device mesh: the harness form of the
    multi-chip path. Replica states shard over the mesh's 'replica' axis
    (the ReplicaStrategy↔mesh-shape analog, `benches/mkbench.rs:321-362`),
    the log replicates, and GSPMD places the collectives. Device order
    comes from the topology walk + ThreadMapping placement
    (`benches/utils/topology.rs:174-219`). Stepping, fencing, and state
    inspection are inherited from `ReplicatedRunner` — only construction
    (mesh + sharded jit) and batch placement differ."""

    def __init__(self, dispatch: Dispatch, n_replicas: int,
                 writes_per_replica: int, reads_per_replica: int,
                 n_devices: int | None = None,
                 thread_mapping=None,
                 log_capacity: int | None = None,
                 strategy=None):
        from node_replication_tpu.parallel.mesh import (
            make_mesh,
            place,
            shard_step,
            strategy_devices,
        )
        from node_replication_tpu.parallel.topology import (
            MachineTopology,
            ThreadMapping,
        )

        topo = MachineTopology()
        mapping = thread_mapping or ThreadMapping.SEQUENTIAL
        if strategy is not None:
            # ReplicaStrategy picks the device set (One/Socket/L1 ladder,
            # `benches/mkbench.rs:321-362`); explicit n_devices overrides.
            devices = strategy_devices(strategy, topo, mapping)
            if n_devices is not None:
                devices = devices[:n_devices]
            n_devices = len(devices)
        else:
            n_devices = n_devices or topo.n_devices()
            devices = topo.allocate(mapping, n_devices)
        if n_replicas % n_devices:
            raise ValueError(
                f"R={n_replicas} not divisible by {n_devices} devices"
            )
        super().__init__(dispatch, n_replicas, writes_per_replica,
                         reads_per_replica, log_capacity)
        self.strategy = strategy
        self.name = f"nr-mesh{n_devices}" + (
            f"-{strategy.value}" if strategy is not None else ""
        )
        self.mesh = make_mesh(n_devices, 1, devices=devices)
        base = make_step(dispatch, self.spec, self.Bw, self.Br, jit=False)
        self.log, self.states = place(self.log, self.states, self.mesh)
        self.step = shard_step(
            base, self.mesh, self.log, self.states, donate=True
        )

    def prepare(self, wr_opc, wr_args, rd_opc, rd_args):
        from jax.sharding import NamedSharding, PartitionSpec as P

        # batches shard over 'replica' on their R axis (axis 1 of [S, R, B])
        sh = NamedSharding(self.mesh, P(None, "replica"))
        self._w = (jax.device_put(wr_opc, sh), jax.device_put(wr_args, sh))
        self._r = (jax.device_put(rd_opc, sh), jax.device_put(rd_args, sh))


class ShardedCnrRunner(MultiLogRunner):
    """CNR MultiLog sharded over a ('replica', 'log') device mesh — the
    multi-chip form of the more-combiners-need-more-chips story
    (`cnr/src/replica.rs:93-98`): each log's ring, cursors, and routed
    write buckets live in their own mesh column (the per-log append and
    replay run WITHOUT cross-log traffic), replica states shard over the
    'replica' axis, and XLA places the collectives that join them. The
    configuration `tests/test_mesh.py` proves correct on the virtual
    8-device mesh (multi-log sharding + sharding-is-real assertions) is
    hereby drivable from
    `ScaleBenchBuilder` (`systems(["sharded-cnr"])`): on an L-chip mesh
    each combiner owns a chip; on one real chip it degrades to a 1x1
    mesh (same program, GSPMD inserts nothing) so the sweep stays
    runnable today and becomes a measurement the day multi-chip hardware
    exists. Routing, padding, stats, and accounting are inherited from
    MultiLogRunner — only device placement differs.
    """

    def __init__(self, dispatch: Dispatch, n_replicas: int, nlogs: int,
                 writes_per_replica: int, reads_per_replica: int,
                 log_capacity: int | None = None,
                 n_log_shards: int | None = None,
                 n_replica_shards: int | None = None,
                 partitioned=None, keyspace: int | None = None,
                 combined: bool | None = None):
        super().__init__(
            dispatch, n_replicas, nlogs, writes_per_replica,
            reads_per_replica, log_capacity, partitioned=partitioned,
            keyspace=keyspace, combined=combined,
        )
        from node_replication_tpu.parallel.mesh import make_mesh

        n_dev = len(jax.devices())
        if n_log_shards is None:
            # prefer the log axis (the CNR scaling story): split the
            # logs over every device when they divide evenly, give each
            # log its own column when the devices over-provision, else
            # leave the log axis unsharded
            if nlogs % n_dev == 0:
                n_log_shards = n_dev
            elif n_dev % nlogs == 0:
                n_log_shards = nlogs
            else:
                n_log_shards = 1
        if n_replica_shards is None:
            # widest replica split the fleet actually divides into
            # (an unused remainder of the device grid is fine)
            cap = max(1, n_dev // n_log_shards)
            n_replica_shards = next(
                r for r in range(min(cap, n_replicas), 0, -1)
                if n_replicas % r == 0
            )
        if nlogs % n_log_shards:
            raise ValueError(
                f"L={nlogs} logs cannot shard over {n_log_shards} mesh "
                f"columns"
            )
        if n_replicas % n_replica_shards:
            raise ValueError(
                f"R={n_replicas} replicas cannot shard over "
                f"{n_replica_shards} mesh rows"
            )
        used = n_replica_shards * n_log_shards
        self.mesh = make_mesh(
            n_replica_shards, n_log_shards,
            devices=jax.devices()[:used],
        )
        self.name = (
            f"sharded-cnr{nlogs}"
            + ("p" if partitioned is not None else "")
            + f"-mesh{n_replica_shards}x{n_log_shards}"
        )

    def _jit_step(self, B: int):
        # jit the step with mesh shardings and place the state the base
        # _build created (per-log batches/counts ride 'log', read
        # batches ride 'replica' — dryrun_multichip path C's layout)
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from node_replication_tpu.core.multilog import make_multilog_step
        from node_replication_tpu.parallel.mesh import (
            _log_spec_tree,
            _states_spec_tree,
            place,
        )

        base = make_multilog_step(
            self.dispatch, self.spec, B, self.Br,
            partitioned=self.partitioned,
            combined=self.combined if self.partitioned is not None
            else None,
            jit=False,
        )
        self.ml, self.states = place(self.ml, self.states, self.mesh)
        logsh = NamedSharding(self.mesh, P("log"))
        repsh = NamedSharding(self.mesh, P("replica"))
        self._logsh = NamedSharding(self.mesh, P(None, "log"))
        self._repsh = NamedSharding(self.mesh, P(None, "replica"))
        return jax.jit(
            base,
            in_shardings=(
                _log_spec_tree(self.ml, self.mesh),
                _states_spec_tree(self.states, self.mesh),
                logsh, logsh, logsh, repsh, repsh,
            ),
            # pin outputs too: without this XLA may hand back e.g.
            # ltails replicated over 'replica', and the NEXT step's
            # in_shardings reject it (hit by the partitioned-combined
            # path on a 2x4 mesh, r5)
            out_shardings=(
                _log_spec_tree(self.ml, self.mesh),
                _states_spec_tree(self.states, self.mesh),
                NamedSharding(self.mesh, P("log", "replica")),
                repsh,
            ),
            donate_argnums=(0, 1),
        )

    def _place_streams(self, opc_b, args_b, counts, rd_opc, rd_args):
        # one transfer per stream, straight onto its mesh axis
        # ([S, L, ...] on 'log'; [S, R, ...] on 'replica')
        self._w = (
            jax.device_put(jnp.asarray(opc_b), self._logsh),
            jax.device_put(jnp.asarray(args_b), self._logsh),
        )
        self._counts = jax.device_put(
            jnp.asarray(counts, jnp.int64), self._logsh
        )
        self._r = (
            jax.device_put(rd_opc, self._repsh),
            jax.device_put(rd_args, self._repsh),
        )


class NativeRunner:
    """The native CPU engine as a duration-based runner (real OS threads;
    the measured loop lives in C++, `nr_bench_hashmap`)."""

    def __init__(self, model: int, model_param: int, n_replicas: int,
                 threads_per_replica: int, write_pct: int, keyspace: int,
                 nlogs: int = 1, batch: int = 32,
                 log_capacity: int = 1 << 18):
        from node_replication_tpu.native import NativeEngine

        self.name = f"native{'-cnr' + str(nlogs) if nlogs > 1 else ''}"
        self.n_replicas = n_replicas
        self.nlogs = nlogs
        self.threads_per_replica = threads_per_replica
        self.write_pct = write_pct
        self.keyspace = keyspace
        self.batch = batch
        self.engine = NativeEngine(
            model, model_param, n_replicas, log_capacity, nlogs
        )

    def run_duration(self, duration_ms: int, seed: int = 1):
        """Returns (total_ops, per_thread_ops, per_sec_ops[t, s])."""
        return self.engine.bench_hashmap(
            self.threads_per_replica, self.write_pct, self.keyspace,
            self.batch, duration_ms, seed,
        )

    def replicas_equal(self) -> bool:
        self.engine.sync()
        return self.engine.replicas_equal()

    def close(self):
        self.engine.close()
