"""Repair-by-replay and the replica lifecycle manager.

The repair half of `fault/`: a quarantined replica is rebuilt from a
healthy donor's snapshot plus log replay — the same two invariants the
repo already proves elsewhere, now composed at runtime:

- **donor-copy invariant** (`NodeReplicated.grow_fleet`): a replica's
  state is the fold of `[0, ltails[r])` from deterministic init, so a
  bit-copy of a healthy donor's state at exactly `ltails[donor]` is a
  consistent snapshot, and inheriting the donor's cursor keeps
  `head = min(healthy ltails)` untouched.
- **recovery-by-replay** (`core/checkpoint.py:recover_states`):
  deterministic `Dispatch` transitions make replaying
  `[donor_ltail, tail)` bit-identical to never having faulted.

`repair_replica` runs the whole sequence against a live wrapper:
clone from the most caught-up healthy donor (`clone_replica_from`),
unfence, and catch up through the same exec loop every replica uses
(`sync(rid)`). Linearizability holds THROUGH the repair because the
log is the source of truth — the repaired replica replays exactly the
entries everyone else already applied, in the same order.

`ReplicaLifecycleManager` closes the loop with the serve frontend:
a dead worker reports through `ServeFrontend.on_replica_failed`; the
manager suspects -> quarantines (fencing the replica out of GC) ->
repairs on a dedicated medic thread -> readmits by restarting the
replica's worker (`restart_replica`). `probe()` runs the divergence
vote for silent corruption the exception path cannot see.

Mesh fleets: the whole sequence is placement-agnostic. Fencing on a
`NodeReplicated(mesh=...)` fleet keeps the GC-head mask correct when
the corpse lives on a different chip than the combiner — the shmap
exec tier reduces `head = min(unfenced ltails)` over ICI with the
fenced shard masked out (`parallel/collectives.py:make_shmap_exec`),
the gspmd tier runs the same `_gc_head` reduction GSPMD-sharded — and
`clone_replica_from` is a cross-device donor copy under the canonical
sharding. Pinned in tests/test_mesh_fleet.py's fenced differential.
"""

from __future__ import annotations

import logging
import threading

from node_replication_tpu.analysis.locks import make_lock

from node_replication_tpu.fault.health import (
    HEALTHY,
    QUARANTINED,
    REPAIRING,
    HealthTracker,
)
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer

logger = logging.getLogger("node_replication_tpu")


def repair_replica(nr, rid: int, donor: int | None = None) -> dict:
    """Rebuild fenced replica `rid` from a healthy donor and readmit it.

    Requires `rid` to be fenced (`nr.fence_replica(rid)`) — repair of a
    live replica would race its own replay. Returns a report dict
    (`rid`, `donor`, `donor_ltail`, `replayed`, `duration_s`); also
    counted in `fault.repair` / observed in `fault.repair_s` and
    emitted as a `fault-repair` trace event.
    """
    t0 = get_clock().now()
    donor, donor_ltail = nr.clone_replica_from(rid, donor=donor)
    nr.unfence_replica(rid)
    nr.sync(rid)
    import numpy as np

    tail = int(np.asarray(nr.log.tail)) if hasattr(nr.log, "tail") else 0
    dur = get_clock().now() - t0
    reg = get_registry()
    reg.counter("fault.repair").inc()
    reg.histogram("fault.repair_s").observe(dur)
    get_tracer().emit(
        "fault-repair", rid=rid, donor=donor, donor_ltail=donor_ltail,
        replayed=tail - donor_ltail, duration_s=dur,
    )
    return {
        "rid": rid,
        "donor": donor,
        "donor_ltail": donor_ltail,
        "replayed": tail - donor_ltail,
        "duration_s": dur,
    }


class ReplicaLifecycleManager:
    """Ties wrapper + frontend + health tracker into one repair loop.

    Wiring: construction installs `self._on_worker_failure` as the
    frontend's `on_replica_failed` callback (when a frontend is given).
    A failed worker then drives, asynchronously on a medic thread:

        report_worker_exception (-> SUSPECT)
        quarantine + `nr.fence_replica`   (GC unblocked, replica frozen)
        REPAIRING + `repair_replica`      (donor clone + replay)
        HEALTHY + `frontend.restart_replica` (rejoins admission)

    `probe()` covers the silent-corruption path: a divergence vote
    that names a minority replica quarantines and repairs it through
    the same pipeline, no worker death required. `wait_idle` joins the
    medic threads (test/bench barrier); `repairs` records every
    completed repair's report for latency accounting
    (`bench.py --chaos`).
    """

    def __init__(self, nr, frontend=None, health: HealthTracker | None = None):
        self.nr = nr
        self.frontend = frontend
        self.health = health or HealthTracker(nr.n_replicas)
        self.repairs: list[dict] = []
        self._lock = make_lock("ReplicaLifecycleManager._lock")
        self._medics: list[threading.Thread] = []
        if frontend is not None:
            frontend.on_replica_failed = self._on_worker_failure

    # ------------------------------------------------------------ pipeline

    def _on_worker_failure(self, rid: int, exc: BaseException) -> None:
        """Frontend callback: a worker died serving `rid`. Runs on the
        dying worker thread — only marks and hands off; the repair
        itself runs on a medic thread so the worker can exit."""
        self.health.report_worker_exception(rid, exc)
        t = threading.Thread(
            target=self._quarantine_and_repair, args=(rid,),
            name=f"fault-medic-r{rid}", daemon=True,
        )
        with self._lock:
            self._medics.append(t)
        t.start()

    def _quarantine_and_repair(self, rid: int) -> None:
        try:
            st = self.health.state(rid)
            if st != QUARANTINED:
                # `quarantine` walks HEALTHY through SUSPECT first, so
                # this is legal even when the tracker's strike
                # threshold (> 1) left the replica HEALTHY after the
                # report that killed its worker
                self.health.quarantine(rid)
            self.nr.fence_replica(rid)
            self.health.transition(rid, REPAIRING)
            report = repair_replica(self.nr, rid)
            self.health.transition(rid, HEALTHY)
            with self._lock:
                self.repairs.append(report)
            if self.frontend is not None:
                self.frontend.restart_replica(rid)
        except Exception as exc:
            logger.exception("repair of replica %d failed", rid)
            # back to quarantine for another attempt; the strike is
            # recorded so the health view shows the failed repair
            if self.health.state(rid) == REPAIRING:
                self.health.transition(rid, QUARANTINED)
            self.health.report_worker_exception(rid, exc)

    # ------------------------------------------------------------ entries

    def quarantine_and_repair(self, rid: int) -> None:
        """Synchronously quarantine + repair `rid` (test/ops entry;
        the async path is the frontend callback)."""
        self._quarantine_and_repair(rid)

    def probe(self) -> list[int]:
        """One divergence vote over the wrapper's states; every named
        minority replica is quarantined and repaired synchronously.
        Returns the rids the vote named."""
        minority = self.health.probe(self.nr.states)
        for rid in minority:
            self._quarantine_and_repair(rid)
        return minority

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Join outstanding medic threads. False on timeout.

        Medics are REAL threads, so the budget is accounted in real
        time by bounded join slices (a `Thread.join` is the rule's
        real-thread-barrier exemption) — an injected-clock deadline
        here would never fire under `SimClock`, turning a hung medic
        into an unbounded wait. Each slice charges at most its own
        length, so `timeout` bounds the total wall wait to within one
        slice."""
        remaining = None if timeout is None else float(timeout)
        while True:
            with self._lock:
                medics = [t for t in self._medics if t.is_alive()]
                self._medics = medics
            if not medics:
                return True
            if remaining is None:
                medics[0].join()
                continue
            if remaining <= 0:
                return False
            piece = min(remaining, 0.1)
            medics[0].join(piece)
            remaining -= piece
