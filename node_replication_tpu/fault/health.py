"""Per-replica health state machine + divergence probe.

The detection half of the replica lifecycle (`fault/`): each replica
walks

    HEALTHY -> SUSPECT -> QUARANTINED -> REPAIRING -> HEALTHY

driven by three evidence streams —

- **worker exceptions** (`report_worker_exception`): a serve worker or
  combiner round that threw; one strike suspects by default because an
  exception out of a batch round is never routine.
- **stall counts** (`report_stall`): watchdog-visible no-progress
  rounds attributed to a replica (`NodeReplicated._watchdog` names the
  most dormant replica); `stall_threshold` strikes suspect it.
- **divergence votes** (`divergence_vote`): a periodic digest election
  over the `[R, ...]` state pytree. Every replica's slice is hashed;
  replicas whose digest differs from the majority digest are the
  minority — with deterministic replay from common init, a minority
  digest can only mean corruption, so the vote NAMES the broken
  replica(s) instead of merely observing `states_equal() == False`.

A SUSPECT replica either clears probation (`clear_suspect`, back to
HEALTHY) or is quarantined. QUARANTINED replicas are fenced out of the
log's `head = min(ltails)` GC reduction by the wrapper
(`NodeReplicated.fence_replica`, `core/log.py` fenced mask) so one dead
replica cannot stall log GC for the fleet. Repair
(`fault/repair.py`) walks QUARANTINED -> REPAIRING -> HEALTHY; a failed
repair drops back to QUARANTINED for another attempt.

Every transition is recorded in the tracker's timeline, emitted as a
`fault-transition` trace event, and counted (`fault.quarantine` on
entry to QUARANTINED) — `obs/report.py`'s fault section renders the
per-replica timeline from exactly these events.
"""

from __future__ import annotations

import hashlib
import threading

from node_replication_tpu.analysis.locks import make_lock
from collections import Counter

import numpy as np

from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer

HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
REPAIRING = "repairing"

STATES = (HEALTHY, SUSPECT, QUARANTINED, REPAIRING)

# Legal edges of the lifecycle machine. SUSPECT -> HEALTHY is probation
# clearing; REPAIRING -> QUARANTINED is a failed repair going back for
# another attempt.
_LEGAL = frozenset({
    (HEALTHY, SUSPECT),
    (SUSPECT, HEALTHY),
    (SUSPECT, QUARANTINED),
    (QUARANTINED, REPAIRING),
    (REPAIRING, HEALTHY),
    (REPAIRING, QUARANTINED),
})


class IllegalTransition(RuntimeError):
    """A transition outside the lifecycle machine's legal edge set."""

    def __init__(self, rid: int, frm: str, to: str):
        super().__init__(
            f"replica {rid}: illegal health transition {frm} -> {to}"
        )
        self.rid = rid
        self.frm = frm
        self.to = to


def state_digest(states, rid: int) -> str:
    """Stable content digest of replica `rid`'s slice of an `[R, ...]`
    state pytree (host readback; probe-cadence cost, not hot-path)."""
    import jax

    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree.leaves(states):
        h.update(np.ascontiguousarray(np.asarray(leaf[rid])).tobytes())
    return h.hexdigest()


def divergence_vote(states) -> list[int]:
    """Digest election naming the minority replica(s).

    Returns the rids whose state digest differs from the STRICT
    majority digest ([] when the fleet is unanimous). Without a strict
    majority (> R/2 identical digests) the vote cannot tell corrupt
    from healthy — in a 2-replica fleet a 1-1 split would name an
    arbitrary bloc, and repairing from the "winner" could clone the
    corruption fleet-wide — so a quorumless split returns [] and the
    caller must fall back to out-of-band evidence (worker exceptions,
    a `recover()` from checkpoint).
    """
    import jax

    leaves = jax.tree.leaves(states)
    if not leaves:
        return []
    R = int(leaves[0].shape[0])
    digests = [state_digest(states, r) for r in range(R)]
    counts = Counter(digests)
    if len(counts) == 1:
        return []
    majority, n_major = counts.most_common(1)[0]
    if n_major * 2 <= R:
        return []  # no quorum: the vote cannot name a culprit
    return [r for r, d in enumerate(digests) if d != majority]


class HealthTracker:
    """Health states + strike counters for one fleet of `n` replicas.

    Thread-safe: serve workers, the watchdog, and the repair medic all
    report concurrently. Transition legality is enforced — an illegal
    edge raises `IllegalTransition` rather than silently teleporting a
    replica's state.
    """

    def __init__(self, n_replicas: int, exc_threshold: int = 1,
                 stall_threshold: int = 3):
        if n_replicas < 1:
            raise ValueError("need at least one replica")
        if exc_threshold < 1 or stall_threshold < 1:
            raise ValueError("thresholds must be >= 1")
        # nrcheck: lock-order HealthTracker._lock -> Tracer._lock — state transitions emit trace events under the lock
        self._lock = make_lock("HealthTracker._lock")
        self._states = [HEALTHY] * n_replicas
        self._exc_counts = [0] * n_replicas
        self._stall_counts = [0] * n_replicas
        self.exc_threshold = exc_threshold
        self.stall_threshold = stall_threshold
        #: every transition, in order: (clock_ts, rid, from, to) —
        #: stamped with the injected clock (`utils/clock.py`;
        #: real-monotonic by default, virtual under simulation)
        self.timeline: list[tuple[float, int, str, str]] = []
        reg = get_registry()
        self._m_quarantine = reg.counter("fault.quarantine")

    # ------------------------------------------------------------- queries

    @property
    def n_replicas(self) -> int:
        return len(self._states)

    def state(self, rid: int) -> str:
        with self._lock:
            return self._states[rid]

    def states(self) -> list[str]:
        with self._lock:
            return list(self._states)

    def healthy_rids(self) -> list[int]:
        with self._lock:
            return [r for r, s in enumerate(self._states)
                    if s == HEALTHY]

    def snapshot(self) -> dict:
        """JSON-safe view: states, strike counters, timeline length."""
        with self._lock:
            return {
                "states": list(self._states),
                "exc_counts": list(self._exc_counts),
                "stall_counts": list(self._stall_counts),
                "transitions": len(self.timeline),
            }

    # ---------------------------------------------------------- transitions

    def _transition_locked(self, rid: int, to: str) -> None:
        frm = self._states[rid]
        if (frm, to) not in _LEGAL:
            raise IllegalTransition(rid, frm, to)
        self._states[rid] = to
        # injected clock, not time.monotonic(): under `SimClock`
        # (`sim/`) lifecycle timelines — and obs/report.py's fault
        # section built from them — carry meaningful virtual stamps
        self.timeline.append((get_clock().now(), rid, frm, to))
        if to == QUARANTINED:
            self._m_quarantine.inc()
        get_tracer().emit("fault-transition", rid=rid, frm=frm, to=to)

    def transition(self, rid: int, to: str) -> None:
        """One legal edge (raises `IllegalTransition` otherwise)."""
        with self._lock:
            self._transition_locked(rid, to)

    def grow(self, k: int = 1) -> None:
        """Track `k` new replicas (the `grow_fleet` twin); newcomers
        start HEALTHY."""
        with self._lock:
            self._states.extend([HEALTHY] * k)
            self._exc_counts.extend([0] * k)
            self._stall_counts.extend([0] * k)

    # ------------------------------------------------------------- evidence

    def report_worker_exception(self, rid: int, exc=None) -> str:
        """A worker/combiner exception attributed to `rid`; suspects the
        replica once `exc_threshold` strikes accumulate. Returns the
        post-report state."""
        del exc  # classification hook: today every exception is a strike
        with self._lock:
            self._exc_counts[rid] += 1
            if (self._states[rid] == HEALTHY
                    and self._exc_counts[rid] >= self.exc_threshold):
                self._transition_locked(rid, SUSPECT)
            return self._states[rid]

    def report_stall(self, rid: int) -> str:
        """A watchdog no-progress round attributed to `rid` (the most
        dormant replica); suspects after `stall_threshold` strikes."""
        with self._lock:
            self._stall_counts[rid] += 1
            if (self._states[rid] == HEALTHY
                    and self._stall_counts[rid] >= self.stall_threshold):
                self._transition_locked(rid, SUSPECT)
            return self._states[rid]

    def clear_suspect(self, rid: int) -> None:
        """Probation cleared: SUSPECT back to HEALTHY, strikes reset."""
        with self._lock:
            self._transition_locked(rid, HEALTHY)
            self._exc_counts[rid] = 0
            self._stall_counts[rid] = 0

    def quarantine(self, rid: int) -> None:
        """Drive `rid` to QUARANTINED (through SUSPECT when needed —
        a divergence vote quarantines a HEALTHY replica directly)."""
        with self._lock:
            if self._states[rid] == HEALTHY:
                self._transition_locked(rid, SUSPECT)
            self._transition_locked(rid, QUARANTINED)

    def probe(self, states) -> list[int]:
        """Run one divergence vote over the fleet's state pytree and
        quarantine every named minority replica not already in the
        repair pipeline. Returns the rids the vote named."""
        minority = divergence_vote(states)
        for rid in minority:
            with self._lock:
                if self._states[rid] in (HEALTHY, SUSPECT):
                    if self._states[rid] == HEALTHY:
                        self._transition_locked(rid, SUSPECT)
                    self._transition_locked(rid, QUARANTINED)
        return minority
