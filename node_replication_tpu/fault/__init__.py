"""fault/: replica lifecycle — injection, health, quarantine, repair.

The robustness layer (ISSUE 4) that turns the repo's structural
recovery property (any replica is the fold of the log from
deterministic init — `core/checkpoint.py:recover_states`) into live
high availability:

- `fault.inject`  — deterministic, seedable `FaultPlan`s armed at
  named host-loop sites (`replay`, `append`, `read-sync`,
  `serve-batch`); one-branch free when disarmed.
- `fault.health`  — per-replica HEALTHY -> SUSPECT -> QUARANTINED ->
  REPAIRING -> HEALTHY state machine plus the digest-vote divergence
  probe that NAMES a corrupted replica.
- `fault.repair`  — repair-by-replay from a healthy donor snapshot
  (the `grow_fleet` donor-copy invariant, applied in place) and the
  `ReplicaLifecycleManager` wiring serve failover to automatic repair.

    from node_replication_tpu.fault import (
        FaultPlan, FaultSpec, HealthTracker, ReplicaLifecycleManager,
    )

    plan = FaultPlan([FaultSpec(site="serve-batch", action="raise",
                                rid=1, after=20)])
    mgr = ReplicaLifecycleManager(nr, frontend)   # auto-wires failover
    with plan.armed():
        ...serve traffic; replica 1 dies, is repaired, rejoins...
"""

from node_replication_tpu.fault.health import (
    HEALTHY,
    QUARANTINED,
    REPAIRING,
    SUSPECT,
    HealthTracker,
    IllegalTransition,
    divergence_vote,
    state_digest,
)
from node_replication_tpu.fault.inject import (
    ACTIONS,
    MAX_STALL_S,
    SITES,
    FaultError,
    FaultPlan,
    FaultSpec,
    corrupt_states,
    fault_hook,
)
from node_replication_tpu.fault.repair import (
    ReplicaLifecycleManager,
    repair_replica,
)

__all__ = [
    "ACTIONS",
    "FaultError",
    "FaultPlan",
    "FaultSpec",
    "HEALTHY",
    "HealthTracker",
    "IllegalTransition",
    "MAX_STALL_S",
    "QUARANTINED",
    "REPAIRING",
    "ReplicaLifecycleManager",
    "SITES",
    "SUSPECT",
    "corrupt_states",
    "divergence_vote",
    "fault_hook",
    "repair_replica",
    "state_digest",
]
