"""Fault injection plane: deterministic, seedable fault schedules.

The reference has no fault story at runtime — its recovery model is
structural (replay the log from a deterministic base, SURVEY.md §5,
`core/checkpoint.py`). This module supplies the OTHER half of a live
high-availability loop: a way to make replicas fail on purpose, on a
reproducible schedule, so the detect/quarantine/repair machinery
(`fault/health.py`, `fault/repair.py`) has something real to exercise
in tests and in the chaos bench (`bench.py --chaos`).

Design (the `obs/metrics.py` discipline applied to faults):

- **Sites** are host-side choke points named by string — `replay`
  (`NodeReplicated._exec_round` / `MultiLogReplicated._exec_round`),
  `append` (`_append_and_replay` / `_append_and_replay_log`),
  `read-sync` (`execute`), `serve-batch` (`ServeFrontend._run_batch`
  and the pipelined assembly stage's `_assemble`, BEFORE the batch
  touches the wrapper, so an injected kill is guaranteed pre-append
  and therefore safely retryable in BOTH worker shapes), and
  `serve-complete` (the pipelined completion stage, AFTER
  `begin_mut_batch` appended the round — a kill there is post-append
  by construction, the `maybe_executed=True` class). Each site is one
  `fault_hook(site, rid, owner)` call.
- **Disarmed is free**: `fault_hook` loads one module global and
  branches; no allocation, no lock, no clock — the same one-branch
  contract the metrics registry keeps, so the hooks stay compiled into
  the hot host loops unconditionally.
- **Armed is deterministic**: a `FaultPlan` fires specs by counting
  hook hits per site under a lock. Same seed + same call sequence =>
  same fault schedule (`tests/test_fault.py` pins this).

Actions:

- ``raise``   — raise `FaultError` out of the site (a wedged/killed
  replica as the caller observes it).
- ``stall``   — sleep `stall_s` seconds, clamped to `MAX_STALL_S` so an
  injected stall is always bounded and watchdog/health-visible without
  ever wedging a run.
- ``corrupt`` — perturb one replica's slice of the owner's state pytree
  (`corrupt_states`), giving divergence detection
  (`fault/health.py:divergence_vote`) something real to catch.
- ``corrupt-bytes`` — flip one byte of the owner's last on-disk WAL
  record (`durable/wal.py:_corrupt_tail_bytes`), giving the CRC
  validation on reopen something real to catch. Ignored at owners
  without that hook.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import threading

from node_replication_tpu.analysis.locks import make_lock

from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer

# Every armable site, in hook order of the write path; the `wal-*`
# sites are the durability plane's choke points (`durable/wal.py`:
# segment open/scan, record append, fsync barrier); `ship` and
# `repl-apply` are the replication plane's (`repl/shipper.py` ship
# loop, `repl/follower.py` apply loop — a raise there exercises the
# worker-failure reporting the follower-fleet gates depend on).
SITES = ("replay", "append", "read-sync", "serve-batch",
         "serve-complete",
         "wal-append", "wal-fsync", "wal-open",
         "ship", "repl-apply",
         # the 2PC plane (`shard/txn.py`): after a participant's
         # durable yes-vote / after the coordinator's durable decision
         # publish / between a participant's apply and its resolved
         # record — the three windows the txn recovery story must
         # survive (bench.py --txn kills processes at exactly these)
         "txn-prepare", "txn-decide", "txn-commit")
ACTIONS = ("raise", "stall", "corrupt", "corrupt-bytes", "kill")

#: what `FaultPlan.chaos` samples from — the ORIGINAL in-process-safe
#: subsets, pinned: existing seeds keep their schedules, and a random
#: schedule can never draw `kill` (which would SIGKILL the host
#: process) or a txn site the armed workload does not exercise.
CHAOS_SITES = SITES[:10]
CHAOS_ACTIONS = ACTIONS[:4]

# Upper bound on an injected stall: stalls must stay bounded so a
# chaos run can never wedge — long enough for the watchdog/health
# layer to notice, short enough to keep CI budgets honest.
MAX_STALL_S = 2.0


class FaultError(RuntimeError):
    """The injected failure. Carries its site/rid so handlers (serve
    failover, tests) can route on where the fault fired."""

    def __init__(self, site: str, rid: int, detail: str = ""):
        super().__init__(
            f"injected fault at site {site!r} (rid={rid})"
            + (f": {detail}" if detail else "")
        )
        self.site = site
        self.rid = rid


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed fault.

    Fires on the `(after+1)`-th hook hit at `site` that matches `rid`,
    then `count-1` more times on subsequent matching hits; a spent
    spec never fires again. `rid=-1` matches any replica and counts
    hits site-wide; a rid-filtered spec counts hits per `(site, rid)`
    — so in a multi-replica fleet the fire position is pinned to the
    VICTIM's own hit sequence, not to whichever thread interleaving
    the other replicas' hits happened to produce. `stall_s` is clamped
    to `MAX_STALL_S` at fire time.
    """

    site: str
    action: str
    rid: int = -1
    after: int = 0
    count: int = 1
    stall_s: float = 0.05

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r} "
                             f"(sites: {', '.join(SITES)})")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r} "
                             f"(actions: {', '.join(ACTIONS)})")
        if self.after < 0 or self.count < 1:
            raise ValueError("after must be >= 0 and count >= 1")

    @property
    def effective_stall_s(self) -> float:
        return min(float(self.stall_s), MAX_STALL_S)


def corrupt_states(states, rid: int, seed: int = 0):
    """Deterministically perturb replica `rid`'s slice of an `[R, ...]`
    state pytree (returns a NEW pytree; callers assign it back).

    Flips the low bit of every element of the first integer leaf (or
    adds 1.0 to a float leaf) — a real divergence `states_equal` and
    the digest vote both catch, while shapes/dtypes stay intact.
    """
    import jax
    import jax.numpy as jnp

    del seed  # reserved: perturbation site selection, kept stable now
    leaves, treedef = jax.tree.flatten(states)
    if not leaves:
        return states
    leaf = leaves[0]
    row = leaf[rid]
    if jnp.issubdtype(leaf.dtype, jnp.integer):
        row = row ^ jnp.asarray(1, leaf.dtype)
    else:
        row = row + jnp.asarray(1.0, leaf.dtype)
    leaves[0] = leaf.at[rid].set(row)
    return jax.tree.unflatten(treedef, leaves)


class FaultPlan:
    """A deterministic schedule of `FaultSpec`s plus arming state.

    Construct explicitly (`FaultPlan([spec, ...], seed=7)`) or sample a
    reproducible random schedule with `FaultPlan.chaos(seed, ...)`.
    Arm with `arm()`/`disarm()` or the `armed()` context manager; while
    armed, the module-level `fault_hook` routes every site hit through
    `_fire`. Every fired fault is recorded in `self.fired` (host
    truth for tests), counted in the `fault.injected` metric, and
    emitted as a `fault-inject` trace event.
    """

    def __init__(self, specs=(), seed: int = 0):
        self.specs = tuple(specs)
        self.seed = int(seed)
        self._lock = make_lock("FaultPlan._lock")
        self._hits = {site: 0 for site in SITES}
        self._rid_hits: dict[tuple[str, int], int] = {}
        self._fired_counts = [0] * len(self.specs)
        self.fired: list[dict] = []
        self._m_injected = get_registry().counter("fault.injected")

    # ------------------------------------------------------------ schedule

    @classmethod
    def chaos(cls, seed: int, n_faults: int = 3, n_replicas: int = 2,
              sites=CHAOS_SITES, actions=CHAOS_ACTIONS,
              max_after: int = 64) -> "FaultPlan":
        """Sample a reproducible random schedule: `n_faults` specs drawn
        from `sites` x `actions` x `[0, n_replicas)` x `[0, max_after]`
        with `random.Random(seed)` — same seed, same schedule."""
        rng = random.Random(seed)
        specs = [
            FaultSpec(
                site=rng.choice(tuple(sites)),
                action=rng.choice(tuple(actions)),
                rid=rng.randrange(n_replicas),
                after=rng.randrange(max_after + 1),
                stall_s=round(rng.uniform(0.01, MAX_STALL_S), 3),
            )
            for _ in range(n_faults)
        ]
        return cls(specs, seed=seed)

    def schedule(self) -> tuple:
        """The plan as a comparable value (the determinism contract)."""
        return tuple(dataclasses.astuple(s) for s in self.specs)

    # -------------------------------------------------------------- arming

    def arm(self) -> "FaultPlan":
        global _armed_plan
        _armed_plan = self
        return self

    def disarm(self) -> None:
        global _armed_plan
        if _armed_plan is self:
            _armed_plan = None

    def armed(self):
        """Context manager: arm on enter, disarm on exit."""
        return _Armed(self)

    # -------------------------------------------------------------- firing

    def _fire(self, site: str, rid: int, owner) -> None:
        """One hook hit: match specs, perform at most one action."""
        with self._lock:
            hit = self._hits[site]
            self._hits[site] = hit + 1
            rid_hit = self._rid_hits.get((site, rid), 0)
            self._rid_hits[(site, rid)] = rid_hit + 1
            spec = None
            fired_hit = 0
            for i, s in enumerate(self.specs):
                if s.site != site:
                    continue
                if s.rid != -1 and rid != -1 and s.rid != rid:
                    continue
                # rid-filtered specs trigger on the victim's OWN hit
                # count (deterministic under concurrent workers);
                # wildcard specs trigger on the site-wide count
                eff = hit if s.rid == -1 else rid_hit
                if eff < s.after or self._fired_counts[i] >= s.count:
                    continue
                spec = s
                fired_hit = eff
                self._fired_counts[i] += 1
                break
            if spec is None:
                return
            self.fired.append({
                "site": site, "rid": rid, "action": spec.action,
                "hit": fired_hit,
            })
        self._m_injected.inc()
        get_tracer().emit("fault-inject", site=site, rid=rid,
                          action=spec.action, hit=hit)
        target = spec.rid if spec.rid != -1 else (rid if rid != -1 else 0)
        if spec.action == "raise":
            raise FaultError(site, target)
        if spec.action == "kill":
            # a REAL SIGKILL of this process — no atexit, no flushes,
            # no unwinding: the crash the durability planes' fsync-
            # before-ack contracts are written against. Only the txn
            # bench's child processes arm this (`bench.py --txn`);
            # never sample it into an in-process chaos schedule.
            os.kill(os.getpid(), signal.SIGKILL)
            return  # pragma: no cover — unreachable after SIGKILL
        if spec.action == "stall":
            # injected clock: under `SimClock` a stall is a virtual-
            # time event (instant in wall time, visible in timelines)
            get_clock().sleep(spec.effective_stall_s)
            return
        if spec.action == "corrupt-bytes":
            # flip a byte of the owner's last on-disk record (the
            # owner is the WAL whose operation hit the hook)
            if owner is not None and hasattr(owner,
                                             "_corrupt_tail_bytes"):
                owner._corrupt_tail_bytes()
            return
        # corrupt: perturb the owner's state pytree in place (the owner
        # is the wrapper whose host loop hit the hook)
        if owner is not None and hasattr(owner, "states"):
            owner.states = corrupt_states(owner.states, target,
                                          seed=self.seed)


class _Armed:
    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return self.plan.arm()

    def __exit__(self, *exc) -> None:
        self.plan.disarm()


_armed_plan: FaultPlan | None = None


def fault_hook(site: str, rid: int = -1, owner=None) -> None:
    """The per-site choke point compiled into the host hot loops.

    Disarmed (the default, and the only state benchmarks run in) this
    is one global load and one branch — the `obs/metrics.py` cost
    contract. Armed, it defers to the plan's deterministic matcher.
    """
    plan = _armed_plan
    if plan is None:
        return
    plan._fire(site, rid, owner)


def armed_plan() -> FaultPlan | None:
    """The currently armed plan (None when disarmed)."""
    return _armed_plan
