"""Replicated in-memory file system (block store).

The reference replays the btfs in-memory FS through NR, with even reads
forced through the log as write-ops so all replicas observe access order
(`benches/memfs.rs:24-86`, `294-322`); the CNR variant (nrfs) partitions by
file with a per-file LogMapper `fd-1` (`benches/nrfs.rs:25-39`).

TPU-first: a fixed grid of files × blocks, `data: int32[n_files, n_blocks]`
plus per-file sizes. The per-file LogMapper for the CNR path is exported as
`memfs_log_mapper` (ops on different files commute; ops on one file share a
log, exactly the nrfs contract).

Write opcodes:
  FS_WRITE=1     args (fd, block, val) → write one block, extend size;
                 resp = new size (blocks), or -1 if fd/block out of range.
  FS_TRUNCATE=2  args (fd) → resp = old size.
  FS_READ_LOGGED=3  args (fd, block) → a *read through the log* (the memfs
                 reads-as-writes idiom); resp = block value, state unchanged.
Read opcodes:
  FS_READ=1      args (fd, block) → block value, or -1 out of range.
  FS_SIZE=2      args (fd) → size in blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

FS_WRITE = 1
FS_TRUNCATE = 2
FS_READ_LOGGED = 3
FS_READ = 1
FS_SIZE = 2


def memfs_log_mapper(opcode: int, args: tuple) -> int:
    """Per-file commutativity hash (`benches/nrfs.rs:25-39`: `fd - 1`)."""
    return args[0]


def make_memfs(n_files: int, n_blocks: int) -> Dispatch:
    def make_state():
        return {
            "data": jnp.zeros((n_files, n_blocks), jnp.int32),
            "size": jnp.zeros((n_files,), jnp.int32),
        }

    def _ok(fd, block):
        return (fd >= 0) & (fd < n_files) & (block >= 0) & (block < n_blocks)

    def write(state, args):
        fd, block, val = args[0], args[1], args[2]
        ok = _ok(fd, block)
        fdc = jnp.clip(fd, 0, n_files - 1)
        blc = jnp.clip(block, 0, n_blocks - 1)
        data = jnp.where(
            ok, state["data"].at[fdc, blc].set(val), state["data"]
        )
        new_size = jnp.maximum(state["size"][fdc], blc + 1)
        size = jnp.where(ok, state["size"].at[fdc].set(new_size),
                         state["size"])
        return {"data": data, "size": size}, jnp.where(
            ok, new_size, jnp.int32(-1)
        )

    def truncate(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        old = state["size"][fd]
        row = jnp.zeros((n_blocks,), jnp.int32)
        return {
            "data": state["data"].at[fd].set(row),
            "size": state["size"].at[fd].set(0),
        }, old

    def read_logged(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        block = jnp.clip(args[1], 0, n_blocks - 1)
        val = jnp.where(_ok(args[0], args[1]), state["data"][fd, block],
                        jnp.int32(-1))
        return state, val

    def read(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        block = jnp.clip(args[1], 0, n_blocks - 1)
        return jnp.where(_ok(args[0], args[1]), state["data"][fd, block],
                         jnp.int32(-1))

    def size(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        return state["size"][fd]

    def window_plan(state, opcodes, args):
        """Combined replay for the FS (see `Dispatch.window_apply`).

        Unlike the pure last-writer-wins models, memfs has two coupled
        histories per file — block writes and whole-file truncates — and
        running-size responses. The window still collapses to parallel
        passes:

        1. per-FILE segmented scan (sort by file, `associative_scan` over
           max-affine elements `s → max(s·m, c)`) gives every op its
           size-before/size-after and every position its
           last-truncate-index-so-far;
        2. per-CELL grouping (sort by file×block) gives every op the
           last in-window write to its cell;
        3. a logged read's value is its cell's last prior write UNLESS a
           later truncate of the file intervened (then 0), else the
           replica's initial block;
        4. final state: per-cell last write survives only if it follows
           the file's last truncate; final sizes are the scan results.

        Bit-identical to folding write/truncate/read_logged in order
        (tests/test_window.py::TestMemfsWindowApply).

        Packaged as plan/merge (r5): the two sorts + three segmented
        scans run once per window; the plan's final sizes are ABSOLUTE
        (the max-affine scan folds the representative's initial sizes
        in) and the data delta is wins/value/cleared — prefix-absorbing,
        so the fused step shares it across the fleet and the
        union-window catch-up engine can use it.
        """
        W = opcodes.shape[0]
        NEG = jnp.int64(-1)
        fd = args[:, 0]
        blk = args[:, 1]
        val = args[:, 2]
        is_wr = opcodes == FS_WRITE
        is_tr = opcodes == FS_TRUNCATE
        is_rd = opcodes == FS_READ_LOGGED
        wr_ok = is_wr & _ok(fd, blk)
        # truncate/read clip fd into range (matching the sequential ops)
        fd_c = jnp.clip(fd, 0, n_files - 1)
        blk_c = jnp.clip(blk, 0, n_blocks - 1)
        idx = jnp.arange(W, dtype=jnp.int64)

        # ---- pass 1: per-file segmented size scan -------------------
        # ops that touch a file's size history: valid writes (max with
        # blk+1), truncates (reset to 0). Logged READS ride the same
        # ordering as identity elements — they change nothing but receive
        # their last-truncate-before position from the shared scan (saves
        # a whole third sort+scan per window). Everything else goes to a
        # sentinel segment.
        size_active = wr_ok | is_tr
        f_eff = jnp.where(
            size_active | is_rd, fd_c.astype(jnp.int64), n_files
        )
        # stable argsort keeps window order within a file — no composite
        # sort key (overflows int32 under NR_TPU_NO_X64=1, ADVICE r3)
        order_f = jnp.argsort(f_eff, stable=True)
        sf = f_eff[order_f]
        seg_start = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sf[1:] != sf[:-1]]
        )
        # max-affine element (m, c): s → max(s + m, c) in max-plus form
        # (m = 0 keep / -inf drop). write: (0, blk+1); truncate: (-inf, 0)
        # big-negative sentinel with headroom for pairwise additions in
        # `compose`; derived from the EFFECTIVE int dtype so the
        # NR_TPU_NO_X64=1 opt-out (int64 canonicalized to int32) doesn't
        # overflow a hard-coded literal
        eff_i64 = jnp.zeros((), jnp.int64).dtype
        NINF = jnp.asarray(jnp.iinfo(eff_i64).min // 4, eff_i64)
        # write: (0, blk+1); truncate: (-inf, 0); read/other: identity
        # (0, -inf)
        m_el = jnp.where(is_tr[order_f], NINF, jnp.int64(0))
        c_el = jnp.where(
            is_tr[order_f],
            jnp.int64(0),
            jnp.where(wr_ok[order_f], (blk_c[order_f] + 1).astype(jnp.int64),
                      NINF),
        )
        # segment-start folds in the file's initial size so the prefix
        # IS the size-after value: element (0, s0) composed first
        s0 = state["size"].at[
            jnp.minimum(sf, n_files - 1).astype(jnp.int32)
        ].get(mode="clip").astype(jnp.int64)
        # compose a∘b (a then b): s → max(max(s+ma, ca)+mb, cb)
        #                           = max(s + (ma+mb), max(ca+mb, cb))
        def compose(a, b):
            ma, ca, fa = a
            mb, cb, fb = b
            m = jnp.where(fb, mb, jnp.maximum(ma + mb, NINF))
            c = jnp.where(fb, cb, jnp.maximum(ca + mb, cb))
            return m, c, fa | fb

        start_m = jnp.where(seg_start, NINF, m_el)
        start_c = jnp.where(
            seg_start,
            # fold s0 through this element: max(s0 + m, c)
            jnp.maximum(s0 + m_el, c_el),
            c_el,
        )
        _, pc, _ = jax.lax.associative_scan(
            compose, (start_m, start_c, seg_start)
        )
        # size AFTER each size-active op (sorted order); size BEFORE it
        # = prefix up to the previous element (or s0 at segment start)
        size_after_s = pc  # m of prefix applied to nothing: c carries it
        prev_pc = jnp.concatenate([pc[:1] * 0, pc[:-1]])
        size_before_s = jnp.where(seg_start, s0, prev_pc)
        size_after = jnp.zeros((W,), jnp.int64).at[order_f].set(size_after_s)
        size_before = jnp.zeros((W,), jnp.int64).at[order_f].set(
            size_before_s
        )
        # running last-truncate index over the file-sorted order — used
        # for the FINAL per-file truncate position (reads get their own
        # pass below, which includes them in the ordering)
        tr_idx_el = jnp.where(is_tr[order_f], idx[order_f], NEG)

        def run_max(a, b):
            va, fa = a
            vb, fb = b
            return jnp.where(fb, vb, jnp.maximum(va, vb)), fa | fb

        tm, _ = jax.lax.associative_scan(run_max, (tr_idx_el, seg_start))
        # each op's (exclusive) last-truncate-before — the logged reads'
        # share of the ride
        prev_tm = jnp.concatenate([jnp.full((1,), NEG), tm[:-1]])
        last_tr_before_s = jnp.where(seg_start, NEG, prev_tm)
        last_tr_before = jnp.full((W,), NEG).at[order_f].set(
            last_tr_before_s
        )

        # final per-file: size = scan value at segment END; last truncate
        # index overall = tm at segment end
        seg_end = jnp.concatenate([sf[1:] != sf[:-1], jnp.ones((1,), bool)])
        file_slot = jnp.where(
            seg_end & (sf < n_files), sf, n_files
        ).astype(jnp.int32)
        new_size = state["size"].astype(jnp.int64).at[file_slot].set(
            size_after_s, mode="drop"
        )
        last_tr_of_file = jnp.full((n_files + 1,), NEG).at[file_slot].set(
            tm, mode="drop"
        )[:n_files]

        # ---- pass 2: per-cell grouping (writes + logged reads) ------
        cell_active = wr_ok | is_rd
        cell = jnp.where(
            cell_active,
            fd_c.astype(jnp.int64) * n_blocks + blk_c.astype(jnp.int64),
            jnp.int64(n_files) * n_blocks,
        )
        order_c = jnp.argsort(cell, stable=True)
        sc = cell[order_c]
        cstart = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sc[1:] != sc[:-1]]
        )
        # running last-write (index) over the cell order, exclusive
        w_idx_el = jnp.where(wr_ok[order_c], idx[order_c], NEG)
        cm, _ = jax.lax.associative_scan(run_max, (w_idx_el, cstart))
        prev_cm = jnp.concatenate([jnp.full((1,), NEG), cm[:-1]])
        last_wr_before_s = jnp.where(cstart, NEG, prev_cm)
        last_wr_before = jnp.full((W,), NEG).at[order_c].set(
            last_wr_before_s
        )

        # ---- responses ----------------------------------------------
        # write: new size (or -1 invalid); truncate: old size;
        # read_logged: cell value just before it
        j = last_wr_before  # candidate write feeding each logged read
        k = last_tr_before  # its file's last truncate before it (pass 1)
        init_val = state["data"][fd_c, blk_c]
        rd_val = jnp.where(
            j > k,
            val[jnp.clip(j, 0).astype(jnp.int32)],
            jnp.where(
                k >= 0,
                jnp.int32(0),
                jnp.where(_ok(fd, blk), init_val, jnp.int32(-1)),
            ),
        )
        # a read of an out-of-range (fd, blk) answers -1 regardless
        rd_val = jnp.where(_ok(fd, blk), rd_val, jnp.int32(-1))
        resps = jnp.where(
            is_wr,
            jnp.where(wr_ok, size_after.astype(jnp.int32), jnp.int32(-1)),
            jnp.where(
                is_tr,
                size_before.astype(jnp.int32),
                jnp.where(is_rd, rd_val, jnp.int32(0)),
            ),
        )

        # ---- final state --------------------------------------------
        # per-cell last write (idx, val): survives iff it follows the
        # file's LAST truncate; truncated cells with no later write are 0
        cell_wr = jnp.where(wr_ok, cell, jnp.int64(n_files) * n_blocks)
        last_w = (
            jnp.full((n_files * n_blocks + 1,), NEG)
            .at[cell_wr].max(idx)[: n_files * n_blocks]
            .reshape(n_files, n_blocks)
        )
        li = jnp.clip(last_w, 0).astype(jnp.int32)
        lv = val[li]
        ltr = last_tr_of_file[:, None]
        return {
            "data_wins": (last_w >= 0) & (last_w > ltr),
            "data_value": lv,
            "data_cleared": ltr >= 0,
            "size_final": new_size.astype(jnp.int32),
            "resps": resps,
        }

    def window_merge(state, plan):
        data = jnp.where(
            plan["data_wins"], plan["data_value"],
            jnp.where(plan["data_cleared"], 0, state["data"]),
        )
        return {"data": data, "size": plan["size_final"]}, plan["resps"]

    def window_apply(state, opcodes, args):
        # arbitrary-state form: the plan's size scan and read answers
        # fold THIS state's sizes/blocks in, so the composition is the
        # full per-replica sequential fold
        return window_merge(state, window_plan(state, opcodes, args))

    return Dispatch(
        name=f"memfs{n_files}x{n_blocks}",
        make_state=make_state,
        write_ops=(write, truncate, read_logged),
        read_ops=(read, size),
        arg_width=3,
        window_apply=window_apply,
        window_plan=window_plan,
        window_merge=window_merge,
        window_canonical=True,
    )
