"""Replicated in-memory file system (block store).

The reference replays the btfs in-memory FS through NR, with even reads
forced through the log as write-ops so all replicas observe access order
(`benches/memfs.rs:24-86`, `294-322`); the CNR variant (nrfs) partitions by
file with a per-file LogMapper `fd-1` (`benches/nrfs.rs:25-39`).

TPU-first: a fixed grid of files × blocks, `data: int32[n_files, n_blocks]`
plus per-file sizes. The per-file LogMapper for the CNR path is exported as
`memfs_log_mapper` (ops on different files commute; ops on one file share a
log, exactly the nrfs contract).

Write opcodes:
  FS_WRITE=1     args (fd, block, val) → write one block, extend size;
                 resp = new size (blocks), or -1 if fd/block out of range.
  FS_TRUNCATE=2  args (fd) → resp = old size.
  FS_READ_LOGGED=3  args (fd, block) → a *read through the log* (the memfs
                 reads-as-writes idiom); resp = block value, state unchanged.
Read opcodes:
  FS_READ=1      args (fd, block) → block value, or -1 out of range.
  FS_SIZE=2      args (fd) → size in blocks.
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

FS_WRITE = 1
FS_TRUNCATE = 2
FS_READ_LOGGED = 3
FS_READ = 1
FS_SIZE = 2


def memfs_log_mapper(opcode: int, args: tuple) -> int:
    """Per-file commutativity hash (`benches/nrfs.rs:25-39`: `fd - 1`)."""
    return args[0]


def make_memfs(n_files: int, n_blocks: int) -> Dispatch:
    def make_state():
        return {
            "data": jnp.zeros((n_files, n_blocks), jnp.int32),
            "size": jnp.zeros((n_files,), jnp.int32),
        }

    def _ok(fd, block):
        return (fd >= 0) & (fd < n_files) & (block >= 0) & (block < n_blocks)

    def write(state, args):
        fd, block, val = args[0], args[1], args[2]
        ok = _ok(fd, block)
        fdc = jnp.clip(fd, 0, n_files - 1)
        blc = jnp.clip(block, 0, n_blocks - 1)
        data = jnp.where(
            ok, state["data"].at[fdc, blc].set(val), state["data"]
        )
        new_size = jnp.maximum(state["size"][fdc], blc + 1)
        size = jnp.where(ok, state["size"].at[fdc].set(new_size),
                         state["size"])
        return {"data": data, "size": size}, jnp.where(
            ok, new_size, jnp.int32(-1)
        )

    def truncate(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        old = state["size"][fd]
        row = jnp.zeros((n_blocks,), jnp.int32)
        return {
            "data": state["data"].at[fd].set(row),
            "size": state["size"].at[fd].set(0),
        }, old

    def read_logged(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        block = jnp.clip(args[1], 0, n_blocks - 1)
        val = jnp.where(_ok(args[0], args[1]), state["data"][fd, block],
                        jnp.int32(-1))
        return state, val

    def read(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        block = jnp.clip(args[1], 0, n_blocks - 1)
        return jnp.where(_ok(args[0], args[1]), state["data"][fd, block],
                         jnp.int32(-1))

    def size(state, args):
        fd = jnp.clip(args[0], 0, n_files - 1)
        return state["size"][fd]

    return Dispatch(
        name=f"memfs{n_files}x{n_blocks}",
        make_state=make_state,
        write_ops=(write, truncate, read_logged),
        read_ops=(read, size),
        arg_width=3,
    )
