"""Replicated hash map, dense-keyspace variant.

The reference's flagship workload (`benches/hashmap.rs:29-48`: a
`HashMap<u64, u64>` with Put/Get behind NR). TPU-first re-design
(SURVEY.md §7 "data-structure state as arrays"): the bench keyspace is
bounded, so the map is a dense `values: int32[K]` + `present: bool[K]` pair,
making every Put one scatter and every Get one gather — both vectorize
perfectly across a vmapped replica axis. An open-addressing variant for
sparse keyspaces lives in `models/oahashmap.py`.

Write opcodes: HM_PUT=1 (args k, v → resp 0), HM_REMOVE=2 (args k → resp 1
if the key was present else 0).
Read opcodes: HM_GET=1 (args k → resp value, or -1 when absent — the
encoding of the reference's `Option<u64>` response).
Keys hash onto the dense table with `k % K` (uniform bench keys are already
dense; the modulus mirrors a hash).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

HM_PUT = 1
HM_REMOVE = 2
HM_GET = 1

ABSENT = -1


def make_hashmap(n_keys: int, prefill_value: int | None = None) -> Dispatch:
    """Build the hashmap Dispatch over a dense table of `n_keys` slots.

    `prefill_value` pre-populates every key (the reference prefills 2^26
    entries before measuring, `benches/hashmap.rs:131-139`).
    """

    def make_state():
        if prefill_value is None:
            return {
                "values": jnp.zeros((n_keys,), jnp.int32),
                "present": jnp.zeros((n_keys,), jnp.bool_),
            }
        return {
            "values": jnp.full((n_keys,), prefill_value, jnp.int32),
            "present": jnp.ones((n_keys,), jnp.bool_),
        }

    def put(state, args):
        k = args[0] % n_keys
        return {
            "values": state["values"].at[k].set(args[1]),
            "present": state["present"].at[k].set(True),
        }, jnp.int32(0)

    def remove(state, args):
        k = args[0] % n_keys
        was = state["present"][k]
        return {
            "values": state["values"].at[k].set(0),
            "present": state["present"].at[k].set(False),
        }, was.astype(jnp.int32)

    def get(state, args):
        k = args[0] % n_keys
        return jnp.where(
            state["present"][k], state["values"][k], jnp.int32(ABSENT)
        )

    def window_plan(state, opcodes, args):
        """Combined replay of a whole window (see `Dispatch.window_apply`).

        PUT/REMOVE are last-writer-wins per key, so the final state needs
        only each key's LAST active entry, and a REMOVE's response
        (was-present) needs only its immediate same-key PREDECESSOR — both
        parallel computations:

        1. group entries by key with one stable sort,
        2. presence-before(entry) = predecessor-was-PUT, or the replica's
           initial presence for each key's first touch,
        3. merge each key's last write into the dense table (elementwise).

        Bit-identical to folding put/remove over the window in order
        (differentially tested in tests/test_window.py). Replaces the
        reference's per-entry replay loop (`nr/src/log.rs:473-524`) with
        O(W log W) parallel work instead of W sequential scatters.

        Packaged as plan/merge (r5): the sort half runs once per window
        (fused step AND union-window catch-up — the plan is
        prefix-absorbing: per-key finals are absolute); the vmapped
        merge is the honest per-replica dense blend.
        """
        W = opcodes.shape[0]
        k = args[:, 0] % n_keys
        v = args[:, 1]
        is_put = opcodes == HM_PUT
        is_rem = opcodes == HM_REMOVE
        active = is_put | is_rem
        # inactive slots (NOOP / unknown opcodes) group into a sentinel
        # bucket past the keyspace so they never touch real keys
        key_eff = jnp.where(active, k, n_keys).astype(jnp.int64)
        idx = jnp.arange(W, dtype=jnp.int64)
        # stable key grouping: argsort is stable, so equal keys keep
        # window order — no composite `key*(W+1)+idx` key, which would
        # overflow int32 under the NR_TPU_NO_X64=1 opt-out (ADVICE r3)
        order = jnp.argsort(key_eff, stable=True)
        sk = key_eff[order]
        same_prev = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), sk[1:] == sk[:-1]]
        )
        prev = jnp.concatenate([order[:1], order[:-1]])
        # presence just before each entry: its same-key predecessor's
        # effect, else the replica's initial presence of that key
        # sentinel index n_keys clamps onto the last real key; harmless
        # because sentinel slots are never REMOVEs (resp forced to 0)
        init_present = state["present"].at[
            sk.astype(jnp.int32)
        ].get(mode="clip")
        pres_before = jnp.where(same_prev, is_put[prev], init_present)
        resp_sorted = jnp.where(
            is_rem[order], pres_before.astype(jnp.int32), jnp.int32(0)
        )
        resps = jnp.zeros((W,), jnp.int32).at[order].set(resp_sorted)
        # last active entry per key wins (scatter-max of window position;
        # sentinel bucket absorbs inactive slots)
        last = (
            jnp.full((n_keys + 1,), -1, jnp.int64)
            .at[key_eff].max(idx)[:n_keys]
        )
        touched = last >= 0
        li = jnp.clip(last, 0).astype(jnp.int32)
        last_is_put = is_put[li]
        return {
            "touched": touched,
            "value": jnp.where(last_is_put, v[li], 0),
            "present": last_is_put,
            "resps": resps,
        }

    def window_merge(state, plan):
        return {
            "values": jnp.where(plan["touched"], plan["value"],
                                state["values"]),
            "present": jnp.where(plan["touched"], plan["present"],
                                 state["present"]),
        }, plan["resps"]

    def window_apply(state, opcodes, args):
        # arbitrary-state form: the plan's presence-before half reads
        # THIS state, so the composition is the full per-replica fold
        return window_merge(state, window_plan(state, opcodes, args))

    # fused pallas combiner round (ops/pallas_replay.py): one kernel
    # launch per serve batch — append + replay + response gather on the
    # transposed [K, R] planes. Lazily imported so the model stays
    # importable where pallas is not.
    def fused_factory(spec, interpret=None):
        from node_replication_tpu.ops.pallas_replay import (
            FusedHashmapEngine,
        )

        return FusedHashmapEngine(n_keys, spec, interpret=interpret)

    return Dispatch(
        name=f"hashmap{n_keys}",
        make_state=make_state,
        write_ops=(put, remove),
        read_ops=(get,),
        arg_width=3,
        window_apply=window_apply,
        window_plan=window_plan,
        window_merge=window_merge,
        # prefix-absorbing plan + canonical responses pinned by
        # tests/test_window.py::test_plan_is_prefix_absorbing
        window_canonical=True,
        fused_factory=fused_factory,
    )
