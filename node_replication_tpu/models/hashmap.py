"""Replicated hash map, dense-keyspace variant.

The reference's flagship workload (`benches/hashmap.rs:29-48`: a
`HashMap<u64, u64>` with Put/Get behind NR). TPU-first re-design
(SURVEY.md §7 "data-structure state as arrays"): the bench keyspace is
bounded, so the map is a dense `values: int32[K]` + `present: bool[K]` pair,
making every Put one scatter and every Get one gather — both vectorize
perfectly across a vmapped replica axis. An open-addressing variant for
sparse keyspaces lives in `models/oahashmap.py`.

Write opcodes: HM_PUT=1 (args k, v → resp 0), HM_REMOVE=2 (args k → resp 1
if the key was present else 0).
Read opcodes: HM_GET=1 (args k → resp value, or -1 when absent — the
encoding of the reference's `Option<u64>` response).
Keys hash onto the dense table with `k % K` (uniform bench keys are already
dense; the modulus mirrors a hash).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

HM_PUT = 1
HM_REMOVE = 2
HM_GET = 1

ABSENT = -1


def make_hashmap(n_keys: int, prefill_value: int | None = None) -> Dispatch:
    """Build the hashmap Dispatch over a dense table of `n_keys` slots.

    `prefill_value` pre-populates every key (the reference prefills 2^26
    entries before measuring, `benches/hashmap.rs:131-139`).
    """

    def make_state():
        if prefill_value is None:
            return {
                "values": jnp.zeros((n_keys,), jnp.int32),
                "present": jnp.zeros((n_keys,), jnp.bool_),
            }
        return {
            "values": jnp.full((n_keys,), prefill_value, jnp.int32),
            "present": jnp.ones((n_keys,), jnp.bool_),
        }

    def put(state, args):
        k = args[0] % n_keys
        return {
            "values": state["values"].at[k].set(args[1]),
            "present": state["present"].at[k].set(True),
        }, jnp.int32(0)

    def remove(state, args):
        k = args[0] % n_keys
        was = state["present"][k]
        return {
            "values": state["values"].at[k].set(0),
            "present": state["present"].at[k].set(False),
        }, was.astype(jnp.int32)

    def get(state, args):
        k = args[0] % n_keys
        return jnp.where(
            state["present"][k], state["values"][k], jnp.int32(ABSENT)
        )

    return Dispatch(
        name=f"hashmap{n_keys}",
        make_state=make_state,
        write_ops=(put, remove),
        read_ops=(get,),
        arg_width=3,
    )
