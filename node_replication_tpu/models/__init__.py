from node_replication_tpu.models.hashmap import (
    HM_GET,
    HM_PUT,
    HM_REMOVE,
    make_hashmap,
)
from node_replication_tpu.models.stack import (
    ST_PEEK,
    ST_POP,
    ST_PUSH,
    make_stack,
)
from node_replication_tpu.models.synthetic import (
    SYN_READ,
    SYN_WRITE,
    make_synthetic,
)

__all__ = [
    "HM_GET",
    "HM_PUT",
    "HM_REMOVE",
    "make_hashmap",
    "ST_PEEK",
    "ST_POP",
    "ST_PUSH",
    "make_stack",
    "SYN_READ",
    "SYN_WRITE",
    "make_synthetic",
]
