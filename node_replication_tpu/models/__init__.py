from node_replication_tpu.models.hashmap import (
    HM_GET,
    HM_PUT,
    HM_REMOVE,
    make_hashmap,
)
from node_replication_tpu.models.stack import (
    ST_PEEK,
    ST_POP,
    ST_PUSH,
    make_stack,
)
from node_replication_tpu.models.synthetic import (
    SYN_READ,
    SYN_WRITE,
    make_synthetic,
)
from node_replication_tpu.models.vspace import (
    VS_IDENTIFY,
    VS_MAP,
    VS_RESOLVED,
    VS_UNMAP,
    make_vspace,
)
from node_replication_tpu.models.memfs import (
    FS_READ,
    FS_READ_LOGGED,
    FS_SIZE,
    FS_TRUNCATE,
    FS_WRITE,
    make_memfs,
    memfs_log_mapper,
)
from node_replication_tpu.models.oahashmap import (
    OA_GET,
    OA_PUT,
    OA_REMOVE,
    make_oahashmap,
)
from node_replication_tpu.models.queue import (
    Q_DEQ,
    Q_ENQ,
    Q_FRONT,
    Q_LEN,
    make_queue,
)
from node_replication_tpu.models.partitioned import (
    PartitionedModel,
    make_partitioned_hashmap,
    make_partitioned_memfs,
    make_partitioned_sortedset,
)
from node_replication_tpu.models.sortedset import (
    SS_CONTAINS,
    SS_INSERT,
    SS_RANGE_COUNT,
    SS_RANK,
    SS_REMOVE,
    make_sortedset,
    sortedset_log_mapper,
)

__all__ = [
    "HM_GET",
    "HM_PUT",
    "HM_REMOVE",
    "make_hashmap",
    "ST_PEEK",
    "ST_POP",
    "ST_PUSH",
    "make_stack",
    "SYN_READ",
    "SYN_WRITE",
    "make_synthetic",
    "VS_IDENTIFY",
    "VS_MAP",
    "VS_RESOLVED",
    "VS_UNMAP",
    "make_vspace",
    "FS_READ",
    "FS_READ_LOGGED",
    "FS_SIZE",
    "FS_TRUNCATE",
    "FS_WRITE",
    "make_memfs",
    "memfs_log_mapper",
    "Q_DEQ",
    "Q_ENQ",
    "Q_FRONT",
    "Q_LEN",
    "make_queue",
    "OA_GET",
    "OA_PUT",
    "OA_REMOVE",
    "make_oahashmap",
    "PartitionedModel",
    "make_partitioned_hashmap",
    "make_partitioned_memfs",
    "make_partitioned_sortedset",
    "SS_CONTAINS",
    "SS_INSERT",
    "SS_RANGE_COUNT",
    "SS_RANK",
    "SS_REMOVE",
    "make_sortedset",
    "sortedset_log_mapper",
]
