"""Synthetic tunable-cost data structure.

The reference's `AbstractDataStructure` models per-op cache-line footprint:
`n` lines of state, each op touching `cold_reads/cold_writes` random lines
and `hot_reads/hot_writes` lines from a small hot set
(`benches/synthetic.rs:59-110`; defaults 200k/20/5/2/1 at `:75-79`). It
exists to sweep op cost × replica count.

TPU-first: state is `lines: int32[n]`; an op's "random lines" derive
deterministically from its args via a splitmix-style hash (replay must be
deterministic on every replica), and touches become fixed-count gathers
(reads fold into a checksum) and scatters (writes). Costs are Dispatch
construction parameters so the harness sweeps op cost exactly like the
reference bench.

Write opcode SYN_WRITE=1 (args seed → resp checksum of read lines);
read opcode SYN_READ=1 (same footprint, no mutation).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

SYN_WRITE = 1
SYN_READ = 1


def _mix(x):
    # splitmix32-style avalanche; deterministic across replicas/devices.
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _lines(seed, count, n, salt):
    i = jnp.arange(count, dtype=jnp.uint32)
    return (_mix(seed.astype(jnp.uint32) + salt * jnp.uint32(0x9E3779B9) + i)
            % jnp.uint32(n)).astype(jnp.int32)


def make_synthetic(
    n: int = 200_000,
    cold_reads: int = 20,
    cold_writes: int = 5,
    hot_reads: int = 2,
    hot_writes: int = 1,
    hot_set: int = 1024,
) -> Dispatch:
    hot_set = min(hot_set, n)

    def make_state():
        return {"lines": jnp.zeros((n,), jnp.int32)}

    def footprint(state, seed):
        cr = _lines(seed, cold_reads, n, jnp.uint32(1))
        hr = _lines(seed, hot_reads, hot_set, jnp.uint32(2))
        idx = jnp.concatenate([cr, hr]) if hot_reads else cr
        return state["lines"][idx].sum()

    def write(state, args):
        seed = args[0]
        checksum = footprint(state, seed)
        cw = _lines(seed, cold_writes, n, jnp.uint32(3))
        hw = _lines(seed, hot_writes, hot_set, jnp.uint32(4))
        idx = jnp.concatenate([cw, hw]) if hot_writes else cw
        lines = state["lines"].at[idx].add(seed + checksum)
        return {"lines": lines}, checksum

    def read(state, args):
        return footprint(state, args[0])

    return Dispatch(
        name=f"synthetic{n}",
        make_state=make_state,
        write_ops=(write,),
        read_ops=(read,),
        arg_width=3,
    )
