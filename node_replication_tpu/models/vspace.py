"""Replicated virtual address space (page-table workload).

The reference replays a full x86-64 4-level page table (PML4→PDPT→PD→PT)
through NR with Map / MapDevice / Identify ops — the NrOS use-case
(`benches/vspace.rs:176-481`, ops at `483-526`).

TPU-first: pointer-chasing radix levels are hostile to fixed-shape compiled
replay, and the workload's semantics are a partial map vpage→pframe over a
bounded VA window. State is the flattened last-level table
`frames: int32[n_pages]` (0 = unmapped; the radix walk is an addressing
scheme, not semantics). Multi-page maps become one masked iota scatter —
the fixed-shape equivalent of the reference's per-page PT walk loop.

Write opcodes:
  VS_MAP=1       args (vpage, pframe, npages) → maps vpage+i ↦ pframe+i for
                 i < min(npages, max_span); resp = #pages newly mapped.
  VS_UNMAP=2     args (vpage, npages) → resp = #pages that were mapped.
Read opcodes:
  VS_IDENTIFY=1  args (vpage) → pframe, or -1 if unmapped
                 (`benches/vspace.rs` Identify).
  VS_RESOLVED=2  args (vpage, npages) → count of mapped pages in range.
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

VS_MAP = 1
VS_UNMAP = 2
VS_IDENTIFY = 1
VS_RESOLVED = 2

UNMAPPED = 0


def make_vspace(n_pages: int, max_span: int = 16) -> Dispatch:
    """`max_span` bounds pages touched per op (fixed scatter width)."""

    def make_state():
        return {"frames": jnp.zeros((n_pages,), jnp.int32)}

    def _span_idx(vpage, npages):
        lanes = jnp.arange(max_span, dtype=jnp.int32)
        n = jnp.clip(npages, 0, max_span)
        # out-of-range lanes scatter to n_pages → dropped
        idx = jnp.where(
            (lanes < n) & (vpage + lanes < n_pages),
            (vpage + lanes) % n_pages,
            n_pages,
        )
        return idx, lanes, n

    def vmap_(state, args):
        vpage, pframe, npages = args[0], args[1], args[2]
        idx, lanes, n = _span_idx(vpage, npages)
        frames = state["frames"]
        newly = jnp.sum(
            jnp.where(idx < n_pages, frames.at[idx].get(mode="fill",
                                                        fill_value=1)
                      == UNMAPPED, False)
        )
        # pframe 0 is reserved (means unmapped); map to pframe+1 offset is
        # the caller's concern — we store pframe+lanes as given.
        frames = frames.at[idx].set(pframe + lanes, mode="drop")
        return {"frames": frames}, newly.astype(jnp.int32)

    def unmap(state, args):
        vpage, npages = args[0], args[1]
        idx, lanes, n = _span_idx(vpage, npages)
        frames = state["frames"]
        was = jnp.sum(
            jnp.where(idx < n_pages, frames.at[idx].get(mode="fill",
                                                        fill_value=UNMAPPED)
                      != UNMAPPED, False)
        )
        frames = frames.at[idx].set(UNMAPPED, mode="drop")
        return {"frames": frames}, was.astype(jnp.int32)

    def identify(state, args):
        vpage = args[0] % n_pages
        f = state["frames"][vpage]
        return jnp.where(f == UNMAPPED, jnp.int32(-1), f)

    def resolved(state, args):
        vpage, npages = args[0], args[1]
        idx, lanes, n = _span_idx(vpage, npages)
        return jnp.sum(
            jnp.where(idx < n_pages,
                      state["frames"].at[idx].get(
                          mode="fill", fill_value=UNMAPPED) != UNMAPPED,
                      False)
        ).astype(jnp.int32)

    return Dispatch(
        name=f"vspace{n_pages}",
        make_state=make_state,
        write_ops=(vmap_, unmap),
        read_ops=(identify, resolved),
        arg_width=3,
    )
