"""Replicated virtual address space (page-table workload).

The reference replays a full x86-64 4-level page table (PML4→PDPT→PD→PT)
through NR with Map / MapDevice / Identify ops — the NrOS use-case
(`benches/vspace.rs:176-481`, ops at `483-526`).

TPU-first: pointer-chasing radix levels are hostile to fixed-shape compiled
replay, and the workload's semantics are a partial map vpage→pframe over a
bounded VA window. State is the flattened last-level table
`frames: int32[n_pages]` (0 = unmapped; the radix walk is an addressing
scheme, not semantics). Multi-page maps become one masked iota scatter —
the fixed-shape equivalent of the reference's per-page PT walk loop.

Write opcodes:
  VS_MAP=1       args (vpage, pframe, npages) → maps vpage+i ↦ pframe+i for
                 i < min(npages, max_span); resp = #pages newly mapped.
  VS_UNMAP=2     args (vpage, npages) → resp = #pages that were mapped.
Read opcodes:
  VS_IDENTIFY=1  args (vpage) → pframe, or -1 if unmapped
                 (`benches/vspace.rs` Identify).
  VS_RESOLVED=2  args (vpage, npages) → count of mapped pages in range.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

VS_MAP = 1
VS_UNMAP = 2
VS_IDENTIFY = 1
VS_RESOLVED = 2

UNMAPPED = 0


def make_vspace(n_pages: int, max_span: int = 16) -> Dispatch:
    """`max_span` bounds pages touched per op (fixed scatter width)."""

    def make_state():
        return {"frames": jnp.zeros((n_pages,), jnp.int32)}

    def _span_idx(vpage, npages):
        lanes = jnp.arange(max_span, dtype=jnp.int32)
        n = jnp.clip(npages, 0, max_span)
        # out-of-range lanes scatter to n_pages → dropped
        idx = jnp.where(
            (lanes < n) & (vpage + lanes < n_pages),
            (vpage + lanes) % n_pages,
            n_pages,
        )
        return idx, lanes, n

    def vmap_(state, args):
        vpage, pframe, npages = args[0], args[1], args[2]
        idx, lanes, n = _span_idx(vpage, npages)
        frames = state["frames"]
        newly = jnp.sum(
            jnp.where(idx < n_pages, frames.at[idx].get(mode="fill",
                                                        fill_value=1)
                      == UNMAPPED, False)
        )
        # pframe 0 is reserved (means unmapped); map to pframe+1 offset is
        # the caller's concern — we store pframe+lanes as given.
        frames = frames.at[idx].set(pframe + lanes, mode="drop")
        return {"frames": frames}, newly.astype(jnp.int32)

    def unmap(state, args):
        vpage, npages = args[0], args[1]
        idx, lanes, n = _span_idx(vpage, npages)
        frames = state["frames"]
        was = jnp.sum(
            jnp.where(idx < n_pages, frames.at[idx].get(mode="fill",
                                                        fill_value=UNMAPPED)
                      != UNMAPPED, False)
        )
        frames = frames.at[idx].set(UNMAPPED, mode="drop")
        return {"frames": frames}, was.astype(jnp.int32)

    def identify(state, args):
        vpage = args[0] % n_pages
        f = state["frames"][vpage]
        return jnp.where(f == UNMAPPED, jnp.int32(-1), f)

    def resolved(state, args):
        vpage, npages = args[0], args[1]
        idx, lanes, n = _span_idx(vpage, npages)
        return jnp.sum(
            jnp.where(idx < n_pages,
                      state["frames"].at[idx].get(
                          mode="fill", fill_value=UNMAPPED) != UNMAPPED,
                      False)
        ).astype(jnp.int32)

    def window_plan(state, opcodes, args):
        """Combined replay for the flat vspace (see `Dispatch.window_apply`).

        Map/Unmap are last-writer-wins *per page*; what makes vspace more
        than the hashmap is that one op touches a whole span. Each op is
        expanded into `max_span` page-EVENTS (lanes beyond the op's span
        park at a sentinel page), after which the window is exactly the
        hashmap algebra over W x max_span events:

        1. group events by page with one stable sort,
        2. presence-before(event) = same-page predecessor's stored value
           != UNMAPPED, else the replica's initial frame,
        3. per-op response = lane-sum of its events' presence bits
           (newly-mapped for MAP, was-mapped for UNMAP),
        4. final frames = per-page last event's stored value.

        Bit-identical to folding vmap_/unmap over the window in order
        (tests/test_window.py::TestVSpaceWindowApply). Replaces the
        sequential replay loop (`nr/src/log.rs:473-524`) with O(E log E)
        parallel work, E = W * max_span.

        Packaged as plan/merge (r5): the sorts and scans — the whole
        O(E log E) half — depend on the window plus the representative
        state, so under the fused step they run ONCE per window; the
        vmapped `window_merge` is the honest per-replica dense blend
        (one [P]-wide select against the replica's own frames). This is
        what makes long-log vspace throughput scale linearly with R
        instead of paying R sorts (the r4 bottleneck).
        """
        W = opcodes.shape[0]
        S = max_span
        vpage, pframe = args[:, 0], args[:, 1]
        is_map = opcodes == VS_MAP
        is_un = opcodes == VS_UNMAP
        active = is_map | is_un
        # MAP's span rides args[2]; UNMAP's rides args[1] (its arg tuple
        # is (vpage, npages) — matching the sequential ops)
        npages = jnp.where(is_un, args[:, 1], args[:, 2])
        lanes = jnp.arange(S, dtype=jnp.int32)[None, :]
        n = jnp.clip(npages, 0, S)[:, None]
        raw = vpage[:, None] + lanes
        lane_ok = (lanes < n) & (raw < n_pages) & active[:, None]
        # mirror _span_idx exactly: negative vpage wraps through the mod
        page = jnp.where(lane_ok, raw % n_pages, n_pages)
        # MAP stores pframe+lane (which CAN be UNMAPPED=0 — a map to
        # frame 0 reads back as unmapped, as in the sequential op);
        # UNMAP stores 0
        stored = jnp.where(is_map[:, None], pframe[:, None] + lanes,
                           jnp.int32(0))
        E = W * S
        pe = page.reshape(E).astype(jnp.int64)
        se = stored.reshape(E)
        # stable sort by page: equal pages keep flattened (= window)
        # order; no composite sort key (int32 overflow under the
        # NR_TPU_NO_X64=1 opt-out, ADVICE r3)
        order = jnp.argsort(pe, stable=True)
        sp = pe[order]
        same_prev = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), sp[1:] == sp[:-1]]
        )
        prev = jnp.concatenate([order[:1], order[:-1]])
        init_pres = (
            state["frames"].at[
                jnp.minimum(sp, n_pages - 1).astype(jnp.int32)
            ].get(mode="clip")
            != UNMAPPED
        )
        pres_before_s = jnp.where(
            same_prev, se[prev] != UNMAPPED, init_pres
        )
        pres_before = (
            jnp.zeros((E,), jnp.bool_).at[order].set(pres_before_s)
            .reshape(W, S)
        )
        newly = jnp.sum(lane_ok & is_map[:, None] & ~pres_before, axis=1)
        was = jnp.sum(lane_ok & is_un[:, None] & pres_before, axis=1)
        resps = jnp.where(
            is_map, newly, jnp.where(is_un, was, 0)
        ).astype(jnp.int32)
        # last event per page wins (sentinel slot absorbs parked lanes)
        last = (
            jnp.full((n_pages + 1,), -1, jnp.int64)
            .at[pe].max(jnp.arange(E, dtype=jnp.int64))[:n_pages]
        )
        li = jnp.clip(last, 0).astype(jnp.int32)
        return {"touched": last >= 0, "value": se[li], "resps": resps}

    def window_merge(state, plan):
        return {
            "frames": jnp.where(plan["touched"], plan["value"],
                                state["frames"])
        }, plan["resps"]

    def window_apply(state, opcodes, args):
        # arbitrary-state form (catch-up, divergent fleets): the plan's
        # presence-before/response half reads THIS state, so the
        # composition is the full sequential-fold semantics per replica
        return window_merge(state, window_plan(state, opcodes, args))

    ok_combined = max_span <= n_pages

    # fused pallas combiner round (ops/pallas_vspace.py): the span
    # kernel with the ring-window append fused in — one launch per
    # serve batch. The factory rejects configs the span kernel's
    # row-overlap rule excludes; wrappers then fall back to the chain.
    def fused_factory(spec, interpret=None):
        from node_replication_tpu.ops.pallas_vspace import (
            FusedVspaceEngine,
        )

        return FusedVspaceEngine(n_pages, max_span, spec,
                                 interpret=interpret)

    return Dispatch(
        name=f"vspace{n_pages}",
        make_state=make_state,
        write_ops=(vmap_, unmap),
        read_ops=(identify, resolved),
        arg_width=3,
        # degenerate config guard: with max_span > n_pages one op's
        # mod-wrapped span can revisit a page, and the event expansion
        # (one predecessor per event) diverges from the sequential fold
        # -> fall back to the scan engine there
        window_apply=window_apply if ok_combined else None,
        window_plan=window_plan if ok_combined else None,
        window_merge=window_merge if ok_combined else None,
        window_canonical=ok_combined,
        fused_factory=fused_factory,
    )


# --------------------------------------------------------------- radix
# The 4-level variant (`benches/vspace.rs:176-481` models the full x86-64
# PML4→PDPT→PD→PT walk). Radix indices: 9 bits per level over the bounded
# window, so level l covers 512^l pages per entry.

VSR_MAP = 1
VSR_MAP_DEVICE = 2
VSR_UNMAP = 3
VSR_UNMAP_TABLE = 4

VSR_IDENTIFY = 1
VSR_RESOLVED = 2
VSR_TABLES = 3

# pt entry encoding: 0 = not present; else (pframe + 1) | device << 30
_DEV_BIT = jnp.int32(1 << 30)
_FRAME_MASK = jnp.int32((1 << 30) - 1)


def make_vspace_radix(n_pages: int, max_span: int = 16) -> Dispatch:
    """4-level page-table vspace with per-level present tables.

    Semantics note (the r2 question "is flat-last-level complete?"): over
    a BOUNDED VA window with on-demand intermediate tables, the pointer
    radix of the reference (`benches/vspace.rs:176-481`) is an addressing
    scheme for a 256 TiB sparse space — a fixed-shape device model does
    not need pointers to cover the same op semantics. What the radix adds
    *observably* is (a) table-granular operations and (b) table
    allocation accounting. This model keeps the flat PT as the last level
    and maintains real PML4/PDPT/PD present tables on every walk:

    Write opcodes:
      VSR_MAP=1          (vpage, pframe, npages) → maps vpage+i ↦
                         pframe+i, allocating the walk's tables;
                         resp = #pages newly mapped.
      VSR_MAP_DEVICE=2   same, but entries carry the device attribute
                         (uncacheable MMIO — the reference's MapDevice);
                         resp = #pages newly mapped.
      VSR_UNMAP=3        (vpage, npages) → clears PT entries (tables
                         stay allocated, as on a real unmap);
                         resp = #pages that were mapped.
      VSR_UNMAP_TABLE=4  (vpage) → tears down the PD-level table covering
                         vpage: its 512-page region unmaps at once and
                         the table deallocates (the radix-only O(table)
                         region operation); resp = #pages that were
                         mapped in the region.
    Read opcodes:
      VSR_IDENTIFY=1     (vpage) → (pframe+1) | device<<30 after a FULL
                         walk (every level present), or -1.
      VSR_RESOLVED=2     (vpage, npages) → #fully-walked mapped pages.
      VSR_TABLES=3       () → #allocated PD tables (the memory-accounting
                         observable the radix exists for).
    """
    l2 = max(1, -(-n_pages // 512))
    l3 = max(1, -(-n_pages // (512 ** 2)))
    l4 = max(1, -(-n_pages // (512 ** 3)))

    def make_state():
        return {
            "pt": jnp.zeros((n_pages,), jnp.int32),
            "pd": jnp.zeros((l2,), jnp.bool_),
            "pdpt": jnp.zeros((l3,), jnp.bool_),
            "pml4": jnp.zeros((l4,), jnp.bool_),
        }

    def _span_idx(vpage, npages):
        lanes = jnp.arange(max_span, dtype=jnp.int32)
        n = jnp.clip(npages, 0, max_span)
        idx = jnp.where(
            (lanes < n) & (vpage + lanes < n_pages),
            (vpage + lanes) % n_pages,
            n_pages,
        )
        return idx, lanes

    def _walk_present(state, pages):
        """Full 4-level walk for page indices (n_pages → False)."""
        safe = jnp.minimum(pages, n_pages - 1)
        ok = pages < n_pages
        return (
            ok
            & state["pml4"].at[safe >> 27].get(mode="clip")
            & state["pdpt"].at[safe >> 18].get(mode="clip")
            & state["pd"].at[safe >> 9].get(mode="clip")
            & (state["pt"].at[safe].get(mode="fill", fill_value=0) != 0)
        )

    # level-entry scatter width: a max_span run crosses at most this many
    # PD entries (and always at most 2 at the higher levels)
    _pd_w = -(-max_span // 512) + 1

    def _mark_levels(state, vpage, npages):
        n = jnp.clip(npages, 0, max_span)
        # an empty map (npages <= 0) must not allocate tables — the
        # VSR_TABLES accounting would report phantom allocations
        live = n > 0
        last = jnp.maximum(vpage + n - 1, vpage)
        pd_lanes = (vpage >> 9) + jnp.arange(_pd_w, dtype=jnp.int32)
        pd_idx = jnp.where(
            live & (pd_lanes <= (last >> 9)) & (pd_lanes < l2),
            pd_lanes, l2,
        )
        hi = jnp.stack([vpage >> 18, last >> 18])
        hi_idx = jnp.where(live & (hi < l3), hi, l3)
        top = jnp.stack([vpage >> 27, last >> 27])
        top_idx = jnp.where(live & (top < l4), top, l4)
        return {
            "pt": state["pt"],
            "pd": state["pd"].at[pd_idx].set(True, mode="drop"),
            "pdpt": state["pdpt"].at[hi_idx].set(True, mode="drop"),
            "pml4": state["pml4"].at[top_idx].set(True, mode="drop"),
        }

    def _map_common(state, args, device):
        vpage, pframe, npages = args[0], args[1], args[2]
        vpage = vpage % n_pages
        idx, lanes = _span_idx(vpage, npages)
        newly = jnp.sum(
            jnp.where(idx < n_pages, ~_walk_present(state, idx), False)
        )
        entry = ((pframe + lanes + 1) & _FRAME_MASK) | (
            _DEV_BIT if device else 0
        )
        state = _mark_levels(state, vpage, npages)
        state = dict(state, pt=state["pt"].at[idx].set(entry, mode="drop"))
        return state, newly.astype(jnp.int32)

    def map_(state, args):
        return _map_common(state, args, device=False)

    def map_device(state, args):
        return _map_common(state, args, device=True)

    def unmap(state, args):
        vpage, npages = args[0] % n_pages, args[1]
        idx, _ = _span_idx(vpage, npages)
        was = jnp.sum(
            jnp.where(idx < n_pages, _walk_present(state, idx), False)
        )
        return dict(
            state, pt=state["pt"].at[idx].set(0, mode="drop")
        ), was.astype(jnp.int32)

    def unmap_table(state, args):
        # tear down the PD table covering vpage: count mapped pages in
        # its 512-page region, zero the region's PT slice, clear the
        # PD entry (fixed-shape: one 512-lane masked scatter)
        vpage = args[0] % n_pages
        pd_i = vpage >> 9
        base = pd_i << 9
        lanes = base + jnp.arange(512, dtype=jnp.int32)
        idx = jnp.where(lanes < n_pages, lanes, n_pages)
        was = jnp.sum(
            jnp.where(idx < n_pages, _walk_present(state, idx), False)
        )
        return dict(
            state,
            pt=state["pt"].at[idx].set(0, mode="drop"),
            pd=state["pd"].at[pd_i].set(False),
        ), was.astype(jnp.int32)

    def identify(state, args):
        v = args[0] % n_pages
        ok = _walk_present(state, jnp.asarray(v))
        return jnp.where(ok, state["pt"][v], jnp.int32(-1))

    def resolved(state, args):
        vpage, npages = args[0] % n_pages, args[1]
        idx, _ = _span_idx(vpage, npages)
        return jnp.sum(
            jnp.where(idx < n_pages, _walk_present(state, idx), False)
        ).astype(jnp.int32)

    def tables(state, args):
        return jnp.sum(state["pd"]).astype(jnp.int32)

    def window_plan(state, opcodes, args):
        """Combined replay for the 4-level radix vspace.

        The hardest window algebra in the repo (alongside memfs): four
        COUPLED per-entry histories instead of one —

          pt[p]    written by map/unmap lanes, bulk-cleared by
                   UNMAP_TABLE over a 512-page region;
          pd[r]    set by maps' table walks, cleared by UNMAP_TABLE;
          pdpt/pml4  MONOTONE — only ever set (teardown stops at PD),
                   so presence-before(t) is just first-set-time < t.

        Decomposition into parallel passes, all bit-identical to the
        sequential fold (tests/test_window.py::TestVSpaceRadixWindowApply):

        1. *page stream* (W x max_span events): stable sort by page gives
           every lane its same-page predecessor/successor write.
        2. *region stream*: one stable sort by PD entry over interleaved
           per-op [lane queries | table query | pd-mark updates | clear
           update] columns (queries sort before their own op's updates, so
           every query sees strictly-pre-op state). Three segmented
           associative scans yield last-pd-update (pd presence-before),
           last-clear-before (pt epoch start), and first-clear-after
           (epoch assignment for teardown responses).
        3. pt-before(lane) joins 1+2: the predecessor write wins iff it
           postdates the last region clear, else cleared-0, else the
           replica's initial pt.
        4. UNMAP_TABLE's response — #fully-walked pages in its region,
           pre-op — uses epoch algebra: each clear t on region r counts
           (a) in-epoch pages whose LAST write before t is nonzero
           (epoch-last markers scatter-added into a bucket keyed by their
           first-clear-after = t) plus, when t is r's first clear, (b)
           initially-mapped pages not yet written (per-region initial
           census minus first-epoch touched pages), gated by the
           region-uniform pml4/pdpt/pd walk bits.
        5. final state: per-page last write vs last region clear; per-PD
           last update; pdpt/pml4 = init | ever-set.

        Packaged as plan/merge (r5): every sort/scan/scatter — the whole
        O(E log E) half above — runs ONCE per window on the
        representative replica; the vmapped `window_merge` does the
        honest per-replica dense work (pt/pd/pdpt/pml4 blends against
        the replica's own tables). r4 relied on XLA hoisting the sorts
        out of the replica vmap, which it does not do for
        gather/scatter-carrying pipelines — the split makes long-log
        throughput scale linearly with R (BENCH_NOTES r5).
        """
        W = opcodes.shape[0]
        S = max_span
        t_op = jnp.arange(W, dtype=jnp.int32)
        vpage = args[:, 0] % n_pages
        pframe = args[:, 1]
        is_map = (opcodes == VSR_MAP) | (opcodes == VSR_MAP_DEVICE)
        is_dev = opcodes == VSR_MAP_DEVICE
        is_un = opcodes == VSR_UNMAP
        is_tbl = opcodes == VSR_UNMAP_TABLE
        # MAP spans ride args[2]; UNMAP's span rides args[1] (its arg
        # tuple is (vpage, npages) — matching the sequential ops)
        npages = jnp.where(is_un, args[:, 1], args[:, 2])
        lanes = jnp.arange(S, dtype=jnp.int32)[None, :]
        nn = jnp.clip(npages, 0, S)
        raw = vpage[:, None] + lanes
        lane_ok = (lanes < nn[:, None]) & (raw < n_pages) & (
            is_map | is_un
        )[:, None]
        page = jnp.where(lane_ok, raw, n_pages)  # vpage>=0: mod is a no-op
        stored = jnp.where(
            is_map[:, None],
            ((pframe[:, None] + lanes + 1) & _FRAME_MASK)
            | jnp.where(is_dev[:, None], _DEV_BIT, 0),
            jnp.int32(0),
        )
        safe = jnp.minimum(page, n_pages - 1)

        # ---- level marks (mirrors _mark_levels' exact conditions) ----
        live = is_map & (nn > 0)
        last_pg = jnp.maximum(vpage + nn - 1, vpage)
        pd_lanes = (vpage >> 9)[:, None] + jnp.arange(
            _pd_w, dtype=jnp.int32
        )[None, :]
        pd_mark = jnp.where(
            live[:, None]
            & (pd_lanes <= (last_pg >> 9)[:, None])
            & (pd_lanes < l2),
            pd_lanes, l2,
        )
        hi = jnp.stack([vpage >> 18, last_pg >> 18], axis=1)
        hi_mark = jnp.where(live[:, None] & (hi < l3), hi, l3)
        top = jnp.stack([vpage >> 27, last_pg >> 27], axis=1)
        top_mark = jnp.where(live[:, None] & (top < l4), top, l4)

        # ---- monotone levels: first-set time per entry ---------------
        tt2 = jnp.broadcast_to(t_op[:, None], (W, 2))
        fs_pdpt = jnp.full((l3 + 1,), W, jnp.int32).at[hi_mark].min(tt2)[:l3]
        fs_pml4 = jnp.full((l4 + 1,), W, jnp.int32).at[top_mark].min(
            tt2
        )[:l4]
        init_pdpt, init_pml4 = state["pdpt"], state["pml4"]
        init_pd, init_pt = state["pd"], state["pt"]

        def pdpt_before(entry, t):
            return init_pdpt[entry] | (fs_pdpt[entry] < t)

        def pml4_before(entry, t):
            return init_pml4[entry] | (fs_pml4[entry] < t)

        # ---- page stream: same-page predecessor / successor ----------
        E = W * S
        pe = page.reshape(E).astype(jnp.int64)
        se = stored.reshape(E)
        te = jnp.broadcast_to(t_op[:, None], (W, S)).reshape(E)
        ordp = jnp.argsort(pe, stable=True)
        spg = pe[ordp]
        samep = spg[1:] == spg[:-1]
        prevp = jnp.concatenate([ordp[:1], ordp[:-1]])
        nextp = jnp.concatenate([ordp[1:], ordp[-1:]])
        f_ = jnp.zeros((1,), jnp.bool_)
        unsort = lambda v, fill: jnp.full((E,), fill, v.dtype).at[ordp].set(v)
        has_pred = unsort(jnp.concatenate([f_, samep]), False).reshape(W, S)
        t_pred = unsort(te[prevp], 0).reshape(W, S)
        v_pred = unsort(se[prevp], 0).reshape(W, S)
        has_succ = unsort(jnp.concatenate([samep, f_]), False).reshape(W, S)
        t_succ = unsort(te[nextp], 0).reshape(W, S)

        # ---- region stream: [lane q | tbl q | pd marks | clear] ------
        reg_tbl = vpage >> 9
        tbl_q = jnp.where(is_tbl, reg_tbl, l2)[:, None]
        clear_u = tbl_q
        lane_q = jnp.where(lane_ok, page >> 9, l2)
        Wc = S + 1 + _pd_w + 1
        keys = jnp.concatenate([lane_q, tbl_q, pd_mark, clear_u], axis=1)
        one_r = lambda v: jnp.broadcast_to(
            jnp.asarray(v, jnp.bool_)[None, :], (W, Wc)
        )
        col_upd = one_r([False] * (S + 1) + [True] * (_pd_w + 1))
        col_val = one_r([False] * (S + 1) + [True] * _pd_w + [False])
        is_upd = col_upd & (keys < l2)
        is_clear = is_upd & ~col_val
        N = W * Wc
        kz = keys.reshape(N).astype(jnp.int64)
        tz = jnp.broadcast_to(t_op[:, None], (W, Wc)).reshape(N)
        uz = is_upd.reshape(N)
        vz = col_val.reshape(N)
        cz = is_clear.reshape(N)
        ordr = jnp.argsort(kz, stable=True)
        skr = kz[ordr]
        segf = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), skr[1:] != skr[:-1]]
        )

        def seg_last(a, b):
            ta, va, ha, fa = a
            tb, vb, hb, fb = b
            tk = jnp.where(fb, tb, jnp.where(hb, tb, ta))
            vk = jnp.where(fb, vb, jnp.where(hb, vb, va))
            hk = jnp.where(fb, hb, ha | hb)
            return tk, vk, hk, fa | fb

        # last pd update (presence value) before each position
        pt_, pv_, ph_, _ = jax.lax.associative_scan(
            seg_last, (tz[ordr], vz[ordr], uz[ordr], segf)
        )
        # last CLEAR before each position (pt epoch start)
        ct_, _, ch_, _ = jax.lax.associative_scan(
            seg_last, (tz[ordr], vz[ordr], cz[ordr], segf)
        )
        # first clear AFTER each position: same scan over the reversal
        segb = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), skr[::-1][1:] != skr[::-1][:-1]]
        )
        nt_, _, nh_, _ = jax.lax.associative_scan(
            seg_last, (tz[ordr][::-1], vz[ordr][::-1], cz[ordr][::-1], segb)
        )
        nt_, nh_ = nt_[::-1], nh_[::-1]
        unsR = lambda v, fill: jnp.full((N,), fill, v.dtype).at[ordr].set(v)
        pd_has = unsR(ph_, False).reshape(W, Wc)
        pd_val = unsR(pv_, False).reshape(W, Wc)
        lcb = unsR(jnp.where(ch_, ct_, -1), -1).reshape(W, Wc)
        nca = unsR(jnp.where(nh_, nt_, W), W).reshape(W, Wc)
        init_pd_q = init_pd.at[
            jnp.minimum(keys, l2 - 1).astype(jnp.int32)
        ].get(mode="clip")
        pd_b = jnp.where(pd_has, pd_val, init_pd_q)

        # ---- per-lane walk-present just before its op ----------------
        lane_pd_b = pd_b[:, :S]
        lane_lcb = lcb[:, :S]
        lane_nc = nca[:, :S]
        pt_b = jnp.where(
            has_pred & (t_pred > lane_lcb),
            v_pred,
            jnp.where(lane_lcb >= 0, 0, init_pt[safe]),
        )
        t_b = t_op[:, None]
        walk_b = (
            pml4_before(safe >> 27, t_b)
            & pdpt_before(safe >> 18, t_b)
            & lane_pd_b
            & (pt_b != 0)
        )
        resp_map = jnp.sum(lane_ok & ~walk_b, axis=1)
        resp_un = jnp.sum(lane_ok & walk_b, axis=1)

        # ---- UNMAP_TABLE responses: epoch algebra --------------------
        # a lane write is LAST-IN-ITS-EPOCH iff its next same-page write
        # falls beyond the epoch's terminating clear
        epoch_last = lane_ok & (~has_succ | (t_succ > lane_nc))
        feeds = epoch_last & (lane_nc < W)
        ncf = jnp.clip(lane_nc, 0, W).reshape(E)
        a_bucket = jnp.zeros((W + 1,), jnp.int32).at[ncf].add(
            (feeds & (stored != 0)).reshape(E)
        )
        init_nz_lane = init_pt[safe] != 0
        b_sub = jnp.zeros((W + 1,), jnp.int32).at[ncf].add(
            (feeds & (lane_lcb == -1) & init_nz_lane).reshape(E)
        )
        # per-region census of initially-mapped pages
        padded = jnp.zeros((l2 * 512,), jnp.bool_).at[: n_pages].set(
            init_pt != 0
        )
        init_nz_count = jnp.sum(
            padded.reshape(l2, 512), axis=1
        ).astype(jnp.int32)
        c0 = lcb[:, S]
        levels_tbl = (
            pml4_before(jnp.minimum(reg_tbl >> 18, l4 - 1), t_op)
            & pdpt_before(jnp.minimum(reg_tbl >> 9, l3 - 1), t_op)
            & pd_b[:, S]
        )
        count_pt = a_bucket[t_op] + jnp.where(
            c0 == -1, init_nz_count[reg_tbl] - b_sub[t_op], 0
        )
        resp_tbl = jnp.where(levels_tbl, count_pt, 0)

        resps = jnp.where(
            is_map, resp_map,
            jnp.where(is_un, resp_un, jnp.where(is_tbl, resp_tbl, 0)),
        ).astype(jnp.int32)

        # ---- final state ---------------------------------------------
        lastw = (
            jnp.full((n_pages + 1,), -1, jnp.int64)
            .at[pe].max(jnp.arange(E, dtype=jnp.int64))[:n_pages]
        )
        li = jnp.clip(lastw, 0).astype(jnp.int32)
        lw_t, lw_v = te[li], se[li]
        lc_reg = (
            jnp.full((l2 + 1,), -1, jnp.int32)
            .at[clear_u[:, 0]].max(jnp.where(is_tbl, t_op, -1))[:l2]
        )
        lc_pg = lc_reg[jnp.arange(n_pages) >> 9]
        upd_keys = jnp.concatenate([pd_mark, clear_u], axis=1)
        Uc = _pd_w + 1
        upd_vals = jnp.broadcast_to(
            jnp.asarray([True] * _pd_w + [False])[None, :], (W, Uc)
        )
        U = W * Uc
        lastu = (
            jnp.full((l2 + 1,), -1, jnp.int64)
            .at[upd_keys.reshape(U).astype(jnp.int64)]
            .max(jnp.arange(U, dtype=jnp.int64))[:l2]
        )
        return {
            # per-page: last in-window write (and whether it postdates
            # the last region clear), plus the clear mask itself
            "pt_wins": (lastw >= 0) & (lw_t > lc_pg),
            "pt_value": lw_v,
            "pt_cleared": lc_pg >= 0,
            # per-PD-entry: last update (mark=True / clear=False)
            "pd_touched": lastu >= 0,
            "pd_value": upd_vals.reshape(U)[
                jnp.clip(lastu, 0).astype(jnp.int32)
            ],
            # monotone levels: entries first set inside the window
            "pdpt_set": fs_pdpt < W,
            "pml4_set": fs_pml4 < W,
            "resps": resps,
        }

    def window_merge(state, plan):
        pt = jnp.where(
            plan["pt_wins"], plan["pt_value"],
            jnp.where(plan["pt_cleared"], 0, state["pt"]),
        )
        pd = jnp.where(plan["pd_touched"], plan["pd_value"], state["pd"])
        return {
            "pt": pt, "pd": pd,
            "pdpt": state["pdpt"] | plan["pdpt_set"],
            "pml4": state["pml4"] | plan["pml4_set"],
        }, plan["resps"]

    def window_apply(state, opcodes, args):
        # arbitrary-state form (catch-up, divergent fleets): the plan's
        # walk-before/epoch half reads THIS state, so the composition is
        # the full sequential-fold semantics per replica
        return window_merge(state, window_plan(state, opcodes, args))

    return Dispatch(
        name=f"vspace_radix{n_pages}",
        make_state=make_state,
        write_ops=(map_, map_device, unmap, unmap_table),
        read_ops=(identify, resolved, tables),
        arg_width=3,
        window_apply=window_apply,
        window_plan=window_plan,
        window_merge=window_merge,
        window_canonical=True,
    )
