"""Replicated virtual address space (page-table workload).

The reference replays a full x86-64 4-level page table (PML4→PDPT→PD→PT)
through NR with Map / MapDevice / Identify ops — the NrOS use-case
(`benches/vspace.rs:176-481`, ops at `483-526`).

TPU-first: pointer-chasing radix levels are hostile to fixed-shape compiled
replay, and the workload's semantics are a partial map vpage→pframe over a
bounded VA window. State is the flattened last-level table
`frames: int32[n_pages]` (0 = unmapped; the radix walk is an addressing
scheme, not semantics). Multi-page maps become one masked iota scatter —
the fixed-shape equivalent of the reference's per-page PT walk loop.

Write opcodes:
  VS_MAP=1       args (vpage, pframe, npages) → maps vpage+i ↦ pframe+i for
                 i < min(npages, max_span); resp = #pages newly mapped.
  VS_UNMAP=2     args (vpage, npages) → resp = #pages that were mapped.
Read opcodes:
  VS_IDENTIFY=1  args (vpage) → pframe, or -1 if unmapped
                 (`benches/vspace.rs` Identify).
  VS_RESOLVED=2  args (vpage, npages) → count of mapped pages in range.
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

VS_MAP = 1
VS_UNMAP = 2
VS_IDENTIFY = 1
VS_RESOLVED = 2

UNMAPPED = 0


def make_vspace(n_pages: int, max_span: int = 16) -> Dispatch:
    """`max_span` bounds pages touched per op (fixed scatter width)."""

    def make_state():
        return {"frames": jnp.zeros((n_pages,), jnp.int32)}

    def _span_idx(vpage, npages):
        lanes = jnp.arange(max_span, dtype=jnp.int32)
        n = jnp.clip(npages, 0, max_span)
        # out-of-range lanes scatter to n_pages → dropped
        idx = jnp.where(
            (lanes < n) & (vpage + lanes < n_pages),
            (vpage + lanes) % n_pages,
            n_pages,
        )
        return idx, lanes, n

    def vmap_(state, args):
        vpage, pframe, npages = args[0], args[1], args[2]
        idx, lanes, n = _span_idx(vpage, npages)
        frames = state["frames"]
        newly = jnp.sum(
            jnp.where(idx < n_pages, frames.at[idx].get(mode="fill",
                                                        fill_value=1)
                      == UNMAPPED, False)
        )
        # pframe 0 is reserved (means unmapped); map to pframe+1 offset is
        # the caller's concern — we store pframe+lanes as given.
        frames = frames.at[idx].set(pframe + lanes, mode="drop")
        return {"frames": frames}, newly.astype(jnp.int32)

    def unmap(state, args):
        vpage, npages = args[0], args[1]
        idx, lanes, n = _span_idx(vpage, npages)
        frames = state["frames"]
        was = jnp.sum(
            jnp.where(idx < n_pages, frames.at[idx].get(mode="fill",
                                                        fill_value=UNMAPPED)
                      != UNMAPPED, False)
        )
        frames = frames.at[idx].set(UNMAPPED, mode="drop")
        return {"frames": frames}, was.astype(jnp.int32)

    def identify(state, args):
        vpage = args[0] % n_pages
        f = state["frames"][vpage]
        return jnp.where(f == UNMAPPED, jnp.int32(-1), f)

    def resolved(state, args):
        vpage, npages = args[0], args[1]
        idx, lanes, n = _span_idx(vpage, npages)
        return jnp.sum(
            jnp.where(idx < n_pages,
                      state["frames"].at[idx].get(
                          mode="fill", fill_value=UNMAPPED) != UNMAPPED,
                      False)
        ).astype(jnp.int32)

    return Dispatch(
        name=f"vspace{n_pages}",
        make_state=make_state,
        write_ops=(vmap_, unmap),
        read_ops=(identify, resolved),
        arg_width=3,
    )


# --------------------------------------------------------------- radix
# The 4-level variant (`benches/vspace.rs:176-481` models the full x86-64
# PML4→PDPT→PD→PT walk). Radix indices: 9 bits per level over the bounded
# window, so level l covers 512^l pages per entry.

VSR_MAP = 1
VSR_MAP_DEVICE = 2
VSR_UNMAP = 3
VSR_UNMAP_TABLE = 4

VSR_IDENTIFY = 1
VSR_RESOLVED = 2
VSR_TABLES = 3

# pt entry encoding: 0 = not present; else (pframe + 1) | device << 30
_DEV_BIT = jnp.int32(1 << 30)
_FRAME_MASK = jnp.int32((1 << 30) - 1)


def make_vspace_radix(n_pages: int, max_span: int = 16) -> Dispatch:
    """4-level page-table vspace with per-level present tables.

    Semantics note (the r2 question "is flat-last-level complete?"): over
    a BOUNDED VA window with on-demand intermediate tables, the pointer
    radix of the reference (`benches/vspace.rs:176-481`) is an addressing
    scheme for a 256 TiB sparse space — a fixed-shape device model does
    not need pointers to cover the same op semantics. What the radix adds
    *observably* is (a) table-granular operations and (b) table
    allocation accounting. This model keeps the flat PT as the last level
    and maintains real PML4/PDPT/PD present tables on every walk:

    Write opcodes:
      VSR_MAP=1          (vpage, pframe, npages) → maps vpage+i ↦
                         pframe+i, allocating the walk's tables;
                         resp = #pages newly mapped.
      VSR_MAP_DEVICE=2   same, but entries carry the device attribute
                         (uncacheable MMIO — the reference's MapDevice);
                         resp = #pages newly mapped.
      VSR_UNMAP=3        (vpage, npages) → clears PT entries (tables
                         stay allocated, as on a real unmap);
                         resp = #pages that were mapped.
      VSR_UNMAP_TABLE=4  (vpage) → tears down the PD-level table covering
                         vpage: its 512-page region unmaps at once and
                         the table deallocates (the radix-only O(table)
                         region operation); resp = #pages that were
                         mapped in the region.
    Read opcodes:
      VSR_IDENTIFY=1     (vpage) → (pframe+1) | device<<30 after a FULL
                         walk (every level present), or -1.
      VSR_RESOLVED=2     (vpage, npages) → #fully-walked mapped pages.
      VSR_TABLES=3       () → #allocated PD tables (the memory-accounting
                         observable the radix exists for).
    """
    l2 = max(1, -(-n_pages // 512))
    l3 = max(1, -(-n_pages // (512 ** 2)))
    l4 = max(1, -(-n_pages // (512 ** 3)))

    def make_state():
        return {
            "pt": jnp.zeros((n_pages,), jnp.int32),
            "pd": jnp.zeros((l2,), jnp.bool_),
            "pdpt": jnp.zeros((l3,), jnp.bool_),
            "pml4": jnp.zeros((l4,), jnp.bool_),
        }

    def _span_idx(vpage, npages):
        lanes = jnp.arange(max_span, dtype=jnp.int32)
        n = jnp.clip(npages, 0, max_span)
        idx = jnp.where(
            (lanes < n) & (vpage + lanes < n_pages),
            (vpage + lanes) % n_pages,
            n_pages,
        )
        return idx, lanes

    def _walk_present(state, pages):
        """Full 4-level walk for page indices (n_pages → False)."""
        safe = jnp.minimum(pages, n_pages - 1)
        ok = pages < n_pages
        return (
            ok
            & state["pml4"].at[safe >> 27].get(mode="clip")
            & state["pdpt"].at[safe >> 18].get(mode="clip")
            & state["pd"].at[safe >> 9].get(mode="clip")
            & (state["pt"].at[safe].get(mode="fill", fill_value=0) != 0)
        )

    # level-entry scatter width: a max_span run crosses at most this many
    # PD entries (and always at most 2 at the higher levels)
    _pd_w = -(-max_span // 512) + 1

    def _mark_levels(state, vpage, npages):
        n = jnp.clip(npages, 0, max_span)
        # an empty map (npages <= 0) must not allocate tables — the
        # VSR_TABLES accounting would report phantom allocations
        live = n > 0
        last = jnp.maximum(vpage + n - 1, vpage)
        pd_lanes = (vpage >> 9) + jnp.arange(_pd_w, dtype=jnp.int32)
        pd_idx = jnp.where(
            live & (pd_lanes <= (last >> 9)) & (pd_lanes < l2),
            pd_lanes, l2,
        )
        hi = jnp.stack([vpage >> 18, last >> 18])
        hi_idx = jnp.where(live & (hi < l3), hi, l3)
        top = jnp.stack([vpage >> 27, last >> 27])
        top_idx = jnp.where(live & (top < l4), top, l4)
        return {
            "pt": state["pt"],
            "pd": state["pd"].at[pd_idx].set(True, mode="drop"),
            "pdpt": state["pdpt"].at[hi_idx].set(True, mode="drop"),
            "pml4": state["pml4"].at[top_idx].set(True, mode="drop"),
        }

    def _map_common(state, args, device):
        vpage, pframe, npages = args[0], args[1], args[2]
        vpage = vpage % n_pages
        idx, lanes = _span_idx(vpage, npages)
        newly = jnp.sum(
            jnp.where(idx < n_pages, ~_walk_present(state, idx), False)
        )
        entry = ((pframe + lanes + 1) & _FRAME_MASK) | (
            _DEV_BIT if device else 0
        )
        state = _mark_levels(state, vpage, npages)
        state = dict(state, pt=state["pt"].at[idx].set(entry, mode="drop"))
        return state, newly.astype(jnp.int32)

    def map_(state, args):
        return _map_common(state, args, device=False)

    def map_device(state, args):
        return _map_common(state, args, device=True)

    def unmap(state, args):
        vpage, npages = args[0] % n_pages, args[1]
        idx, _ = _span_idx(vpage, npages)
        was = jnp.sum(
            jnp.where(idx < n_pages, _walk_present(state, idx), False)
        )
        return dict(
            state, pt=state["pt"].at[idx].set(0, mode="drop")
        ), was.astype(jnp.int32)

    def unmap_table(state, args):
        # tear down the PD table covering vpage: count mapped pages in
        # its 512-page region, zero the region's PT slice, clear the
        # PD entry (fixed-shape: one 512-lane masked scatter)
        vpage = args[0] % n_pages
        pd_i = vpage >> 9
        base = pd_i << 9
        lanes = base + jnp.arange(512, dtype=jnp.int32)
        idx = jnp.where(lanes < n_pages, lanes, n_pages)
        was = jnp.sum(
            jnp.where(idx < n_pages, _walk_present(state, idx), False)
        )
        return dict(
            state,
            pt=state["pt"].at[idx].set(0, mode="drop"),
            pd=state["pd"].at[pd_i].set(False),
        ), was.astype(jnp.int32)

    def identify(state, args):
        v = args[0] % n_pages
        ok = _walk_present(state, jnp.asarray(v))
        return jnp.where(ok, state["pt"][v], jnp.int32(-1))

    def resolved(state, args):
        vpage, npages = args[0] % n_pages, args[1]
        idx, _ = _span_idx(vpage, npages)
        return jnp.sum(
            jnp.where(idx < n_pages, _walk_present(state, idx), False)
        ).astype(jnp.int32)

    def tables(state, args):
        return jnp.sum(state["pd"]).astype(jnp.int32)

    return Dispatch(
        name=f"vspace_radix{n_pages}",
        make_state=make_state,
        write_ops=(map_, map_device, unmap, unmap_table),
        read_ops=(identify, resolved, tables),
        arg_width=3,
    )
