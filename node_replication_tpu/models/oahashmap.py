"""Open-addressing hash map for sparse keyspaces.

The dense `models/hashmap.py` assumes a bounded keyspace (table slot =
`k % K`). This variant is a real hash table over arbitrary int32 keys —
the analog of the reference bench's 50M-keyspace map (`benches/hashmap.rs:
29-48`) when the keyspace can't be materialized densely.

TPU-first design: linear probing with a STATIC probe window of `probe`
slots. Every op is a fixed-shape gather of the window, a masked
first-match/first-free selection, and one scatter — no data-dependent
loops, so it vectorizes across the vmapped replica axis like any other
model. Tombstones keep lookups correct after removals; keys are only ever
stored inside their own probe window, so membership = "match anywhere in
the window" without early-exit scanning.

An insert whose window is full is DROPPED with resp = -2: deterministic
(every replica replays the same outcome), mirroring how the bounded stack
drops overflowing pushes. Size the table ≥ 2× the live key count to make
that a non-event.

Write opcodes: OA_PUT=1 (k, v → 0 ok, -2 window-full),
OA_REMOVE=2 (k → 1 was present, 0 absent).
Read opcodes: OA_GET=1 (k → value, or -1 absent).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

OA_PUT = 1
OA_REMOVE = 2
OA_GET = 1

ABSENT = -1
DROPPED = -2

_EMPTY = 0
_OCC = 1
_TOMB = 2


def _mix(x):
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def make_oahashmap(n_slots: int, probe: int = 16) -> Dispatch:
    """Open-addressed table of `n_slots` with a `probe`-slot linear window."""

    def make_state():
        return {
            "keys": jnp.zeros((n_slots,), jnp.int32),
            "vals": jnp.zeros((n_slots,), jnp.int32),
            "flag": jnp.zeros((n_slots,), jnp.int32),
        }

    def _window(k):
        h = (_mix(k) % jnp.uint32(n_slots)).astype(jnp.int32)
        return (h + jnp.arange(probe, dtype=jnp.int32)) % n_slots

    def put(state, args):
        k, v = args[0], args[1]
        idx = _window(k)
        flags = state["flag"][idx]
        match = (flags == _OCC) & (state["keys"][idx] == k)
        free = flags != _OCC
        any_match = jnp.any(match)
        any_free = jnp.any(free)
        target = jnp.where(
            any_match, jnp.argmax(match), jnp.argmax(free)
        )
        ok = any_match | any_free
        # dropped ops scatter to n_slots → mode="drop" discards
        slot = jnp.where(ok, idx[target], n_slots).astype(jnp.int32)
        return {
            "keys": state["keys"].at[slot].set(k, mode="drop"),
            "vals": state["vals"].at[slot].set(v, mode="drop"),
            "flag": state["flag"].at[slot].set(_OCC, mode="drop"),
        }, jnp.where(ok, jnp.int32(0), jnp.int32(DROPPED))

    def remove(state, args):
        k = args[0]
        idx = _window(k)
        match = (state["flag"][idx] == _OCC) & (state["keys"][idx] == k)
        was = jnp.any(match)
        slot = jnp.where(was, idx[jnp.argmax(match)], n_slots).astype(
            jnp.int32
        )
        return {
            "keys": state["keys"],
            "vals": state["vals"],
            "flag": state["flag"].at[slot].set(_TOMB, mode="drop"),
        }, was.astype(jnp.int32)

    def get(state, args):
        k = args[0]
        idx = _window(k)
        match = (state["flag"][idx] == _OCC) & (state["keys"][idx] == k)
        return jnp.where(
            jnp.any(match),
            state["vals"][idx[jnp.argmax(match)]],
            jnp.int32(ABSENT),
        )

    return Dispatch(
        name=f"oahashmap{n_slots}p{probe}",
        make_state=make_state,
        write_ops=(put, remove),
        read_ops=(get,),
        arg_width=3,
    )
