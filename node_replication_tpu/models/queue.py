"""Replicated bounded FIFO queue.

The reference's cnr stack example replicates a concurrent `SegQueue`
(`cnr/examples/stack.rs` uses crossbeam's queue as the internally-
concurrent data structure). This is that structure's TPU model: a bounded
ring of int32 values with monotone head/tail cursors — enqueue is one
scatter, dequeue one gather, both fixed-shape.

Write opcodes: Q_ENQ=1 (v → new length, or -1 when full),
Q_DEQ=2 (→ dequeued value, or -1 when empty).
Read opcodes: Q_FRONT=1 (→ front value or -1), Q_LEN=2 (→ length).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

Q_ENQ = 1
Q_DEQ = 2
Q_FRONT = 1
Q_LEN = 2

EMPTY = -1


def make_queue(capacity: int) -> Dispatch:
    """Bounded FIFO over a power-of-two-free ring (modulo indexing)."""

    def make_state():
        return {
            "buf": jnp.zeros((capacity,), jnp.int32),
            "head": jnp.zeros((), jnp.int32),
            "tail": jnp.zeros((), jnp.int32),
        }

    def enq(state, args):
        n = state["tail"] - state["head"]
        ok = n < capacity
        idx = jnp.where(ok, state["tail"] % capacity, 0)
        buf = jnp.where(ok, state["buf"].at[idx].set(args[0]), state["buf"])
        tail = jnp.where(ok, state["tail"] + 1, state["tail"])
        return {"buf": buf, "head": state["head"], "tail": tail}, jnp.where(
            ok, n + 1, jnp.int32(EMPTY)
        )

    def deq(state, args):
        ok = state["tail"] > state["head"]
        idx = jnp.where(ok, state["head"] % capacity, 0)
        val = jnp.where(ok, state["buf"][idx], jnp.int32(EMPTY))
        head = jnp.where(ok, state["head"] + 1, state["head"])
        return {"buf": state["buf"], "head": head, "tail": state["tail"]}, val

    def front(state, args):
        ok = state["tail"] > state["head"]
        return jnp.where(
            ok, state["buf"][state["head"] % capacity], jnp.int32(EMPTY)
        )

    def length(state, args):
        return state["tail"] - state["head"]

    def window_apply(state, opcodes, args):
        """Combined replay for the FIFO (see `Dispatch.window_apply` and
        the stack's docstring — same decomposition, two cursors).

        The length n = tail - head is the +-1 walk clamped to
        [0, capacity] (`ops/windowkit.clamped_walk`); each cursor then
        advances by the EXCLUSIVE count of effective ops of its kind
        (plain cumsums), which fixes every op's ring slot up front:

        - effective ENQ at tail t writes slot t % capacity (LWW update;
          resp n+1, full enqueues resp -1),
        - effective DEQ at head h reads slot h % capacity — the latest
          earlier in-window enqueue to that slot, else the replica's
          initial buffer. A later GENERATION (tail = h + capacity) can
          never overwrite the slot before its dequeue consumes it (that
          enqueue would need n >= capacity and is dropped), so per-slot
          last-writer-wins resolution is exact.

        Bit-identical to folding enq/deq in order
        (tests/test_window.py::TestQueueWindowApply).
        """
        plan = window_plan(state, opcodes, args)
        return window_merge(state, plan)

    def window_plan(state, opcodes, args):
        """Shared half of the combined replay (see the stack's
        `window_plan` and `Dispatch.window_plan`)."""
        from node_replication_tpu.ops.windowkit import (
            clamped_walk,
            last_update_table,
            slot_resolve,
        )

        is_enq = opcodes == Q_ENQ
        is_deq = opcodes == Q_DEQ
        v = args[:, 0]
        delta = jnp.where(is_enq, 1, jnp.where(is_deq, -1, 0))
        n0 = state["tail"] - state["head"]
        before, after = clamped_walk(delta, 0, capacity, n0)
        eff_enq = is_enq & (before < capacity)
        eff_deq = is_deq & (before > 0)
        enq_sum = jnp.cumsum(eff_enq.astype(jnp.int32))
        deq_sum = jnp.cumsum(eff_deq.astype(jnp.int32))
        t_before = state["tail"].astype(jnp.int32) + enq_sum - (
            eff_enq.astype(jnp.int32)
        )
        h_before = state["head"].astype(jnp.int32) + deq_sum - (
            eff_deq.astype(jnp.int32)
        )
        slot_upd = jnp.where(eff_enq, t_before % capacity, capacity)
        slot_qry = jnp.where(eff_deq, h_before % capacity, capacity)
        dequeued = slot_resolve(slot_upd, v, slot_qry, state["buf"],
                                capacity)
        resps = jnp.where(
            is_enq,
            jnp.where(eff_enq, before + 1, jnp.int32(EMPTY)),
            jnp.where(
                is_deq,
                jnp.where(eff_deq, dequeued, jnp.int32(EMPTY)),
                jnp.int32(0),
            ),
        ).astype(jnp.int32)
        touched, lastv = last_update_table(slot_upd, v, capacity)
        W = opcodes.shape[0]
        enq_total = enq_sum[W - 1] if W > 0 else jnp.int32(0)
        deq_total = deq_sum[W - 1] if W > 0 else jnp.int32(0)
        return {
            "touched": touched, "lastv": lastv, "resps": resps,
            # ABSOLUTE final cursors (not deltas): under lock-step this
            # is identical to state + delta, and it makes the plan
            # prefix-absorbing — merging it into a replica that already
            # applied a window prefix (`log_catchup_all`'s union-window
            # engine) must not double-count the prefix's cursor moves
            "head_final": (state["head"] + deq_total).astype(jnp.int32),
            "tail_final": (state["tail"] + enq_total).astype(jnp.int32),
        }

    def window_merge(state, plan):
        buf = jnp.where(plan["touched"], plan["lastv"], state["buf"])
        return {
            "buf": buf,
            "head": plan["head_final"],
            "tail": plan["tail_final"],
        }, plan["resps"]

    return Dispatch(
        name=f"queue{capacity}",
        make_state=make_state,
        write_ops=(enq, deq),
        read_ops=(front, length),
        arg_width=3,
        window_apply=window_apply,
        window_plan=window_plan,
        window_merge=window_merge,
        window_canonical=True,
    )
