"""Replicated bounded FIFO queue.

The reference's cnr stack example replicates a concurrent `SegQueue`
(`cnr/examples/stack.rs` uses crossbeam's queue as the internally-
concurrent data structure). This is that structure's TPU model: a bounded
ring of int32 values with monotone head/tail cursors — enqueue is one
scatter, dequeue one gather, both fixed-shape.

Write opcodes: Q_ENQ=1 (v → new length, or -1 when full),
Q_DEQ=2 (→ dequeued value, or -1 when empty).
Read opcodes: Q_FRONT=1 (→ front value or -1), Q_LEN=2 (→ length).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

Q_ENQ = 1
Q_DEQ = 2
Q_FRONT = 1
Q_LEN = 2

EMPTY = -1


def make_queue(capacity: int) -> Dispatch:
    """Bounded FIFO over a power-of-two-free ring (modulo indexing)."""

    def make_state():
        return {
            "buf": jnp.zeros((capacity,), jnp.int32),
            "head": jnp.zeros((), jnp.int32),
            "tail": jnp.zeros((), jnp.int32),
        }

    def enq(state, args):
        n = state["tail"] - state["head"]
        ok = n < capacity
        idx = jnp.where(ok, state["tail"] % capacity, 0)
        buf = jnp.where(ok, state["buf"].at[idx].set(args[0]), state["buf"])
        tail = jnp.where(ok, state["tail"] + 1, state["tail"])
        return {"buf": buf, "head": state["head"], "tail": tail}, jnp.where(
            ok, n + 1, jnp.int32(EMPTY)
        )

    def deq(state, args):
        ok = state["tail"] > state["head"]
        idx = jnp.where(ok, state["head"] % capacity, 0)
        val = jnp.where(ok, state["buf"][idx], jnp.int32(EMPTY))
        head = jnp.where(ok, state["head"] + 1, state["head"])
        return {"buf": state["buf"], "head": head, "tail": state["tail"]}, val

    def front(state, args):
        ok = state["tail"] > state["head"]
        return jnp.where(
            ok, state["buf"][state["head"] % capacity], jnp.int32(EMPTY)
        )

    def length(state, args):
        return state["tail"] - state["head"]

    return Dispatch(
        name=f"queue{capacity}",
        make_state=make_state,
        write_ops=(enq, deq),
        read_ops=(front, length),
        arg_width=3,
    )
