"""Replicated stack.

The reference's second example/bench workload (`nr/examples/stack.rs`,
`benches/stack.rs`: push/pop 50/50). State is a fixed-capacity buffer plus a
top cursor (the reference's `Vec<u32>` grows; fixed shapes require a
capacity, and overflowing pushes are dropped with resp=-1 so behavior stays
deterministic and testable).

Write opcodes: ST_PUSH=1 (args v → resp new depth, or -1 when full),
ST_POP=2 (→ resp popped value, or -1 when empty — `Option<u32>` encoding,
`nr/examples/stack.rs:46-49`).
Read opcodes: ST_PEEK=1 (→ top value or -1), ST_LEN=2 (→ depth).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

ST_PUSH = 1
ST_POP = 2
ST_PEEK = 1
ST_LEN = 2

EMPTY = -1


def make_stack(capacity: int) -> Dispatch:
    def make_state():
        return {
            "buf": jnp.zeros((capacity,), jnp.int32),
            "top": jnp.zeros((), jnp.int32),
        }

    def push(state, args):
        top = state["top"]
        ok = top < capacity
        idx = jnp.where(ok, top, capacity - 1)
        buf = jnp.where(
            ok, state["buf"].at[idx].set(args[0]), state["buf"]
        )
        new_top = jnp.where(ok, top + 1, top)
        return {"buf": buf, "top": new_top}, jnp.where(
            ok, new_top, jnp.int32(EMPTY)
        )

    def pop(state, args):
        top = state["top"]
        ok = top > 0
        idx = jnp.where(ok, top - 1, 0)
        val = jnp.where(ok, state["buf"][idx], jnp.int32(EMPTY))
        return {"buf": state["buf"], "top": jnp.where(ok, top - 1, top)}, val

    def peek(state, args):
        top = state["top"]
        return jnp.where(
            top > 0, state["buf"][jnp.maximum(top - 1, 0)], jnp.int32(EMPTY)
        )

    def length(state, args):
        return state["top"]

    return Dispatch(
        name=f"stack{capacity}",
        make_state=make_state,
        write_ops=(push, pop),
        read_ops=(peek, length),
        arg_width=3,
    )
