"""Replicated stack.

The reference's second example/bench workload (`nr/examples/stack.rs`,
`benches/stack.rs`: push/pop 50/50). State is a fixed-capacity buffer plus a
top cursor (the reference's `Vec<u32>` grows; fixed shapes require a
capacity, and overflowing pushes are dropped with resp=-1 so behavior stays
deterministic and testable).

Write opcodes: ST_PUSH=1 (args v → resp new depth, or -1 when full),
ST_POP=2 (→ resp popped value, or -1 when empty — `Option<u32>` encoding,
`nr/examples/stack.rs:46-49`).
Read opcodes: ST_PEEK=1 (→ top value or -1), ST_LEN=2 (→ depth).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

ST_PUSH = 1
ST_POP = 2
ST_PEEK = 1
ST_LEN = 2

EMPTY = -1


def make_stack(capacity: int) -> Dispatch:
    def make_state():
        return {
            "buf": jnp.zeros((capacity,), jnp.int32),
            "top": jnp.zeros((), jnp.int32),
        }

    def push(state, args):
        top = state["top"]
        ok = top < capacity
        idx = jnp.where(ok, top, capacity - 1)
        buf = jnp.where(
            ok, state["buf"].at[idx].set(args[0]), state["buf"]
        )
        new_top = jnp.where(ok, top + 1, top)
        return {"buf": buf, "top": new_top}, jnp.where(
            ok, new_top, jnp.int32(EMPTY)
        )

    def pop(state, args):
        top = state["top"]
        ok = top > 0
        idx = jnp.where(ok, top - 1, 0)
        val = jnp.where(ok, state["buf"][idx], jnp.int32(EMPTY))
        return {"buf": state["buf"], "top": jnp.where(ok, top - 1, top)}, val

    def peek(state, args):
        top = state["top"]
        return jnp.where(
            top > 0, state["buf"][jnp.maximum(top - 1, 0)], jnp.int32(EMPTY)
        )

    def length(state, args):
        return state["top"]

    def window_apply(state, opcodes, args):
        """Combined replay for the stack (see `Dispatch.window_apply`).

        The stack looked inherently sequential — every op's effect
        depends on the running depth — but the depth is a +-1 walk
        CLAMPED to [0, capacity] (full pushes and empty pops are
        dropped), and clamped walks are one `associative_scan` over
        composition-closed `x -> min(max(x+a, lo), hi)` triples
        (`ops/windowkit.clamped_walk`). With every op's depth-before in
        hand, the rest is the parenthesis-matching insight made LWW:

        - an effective PUSH at depth d writes slot d — a per-slot
          last-writer-wins update (resp d+1; dropped pushes resp -1),
        - an effective POP at depth d reads slot d-1 — its value is the
          latest earlier push to that slot, else the replica's initial
          buffer (pops never clear `buf` in this model), resolved for
          the whole window by one slot-keyed stable sort + segmented
          scan (`ops/windowkit.slot_resolve`),
        - final state: per-slot last push (`last_update_table`) and
          the walk's final depth.

        Bit-identical to folding push/pop in order
        (tests/test_window.py::TestStackWindowApply); closes the
        "order-dependent models are pinned to the scan" gap (VERDICT r3
        #2) with O(W log W) parallel work and no W x span expansion.
        """
        plan = window_plan(state, opcodes, args)
        return window_merge(state, plan)

    def window_plan(state, opcodes, args):
        """The shared (sorting) half of the combined replay: everything
        that is identical across a lock-step fleet — the clamped walk,
        the slot-keyed sort resolving pops, the per-slot last-push table
        and the response vector (see `Dispatch.window_plan`)."""
        from node_replication_tpu.ops.windowkit import (
            clamped_walk,
            last_update_table,
            slot_resolve,
        )

        is_push = opcodes == ST_PUSH
        is_pop = opcodes == ST_POP
        v = args[:, 0]
        delta = jnp.where(is_push, 1, jnp.where(is_pop, -1, 0))
        before, after = clamped_walk(delta, 0, capacity, state["top"])
        eff_push = is_push & (before < capacity)
        eff_pop = is_pop & (before > 0)
        slot_upd = jnp.where(eff_push, before, capacity)
        slot_qry = jnp.where(eff_pop, before - 1, capacity)
        popped = slot_resolve(slot_upd, v, slot_qry, state["buf"],
                              capacity)
        resps = jnp.where(
            is_push,
            jnp.where(eff_push, before + 1, jnp.int32(EMPTY)),
            jnp.where(
                is_pop,
                jnp.where(eff_pop, popped, jnp.int32(EMPTY)),
                jnp.int32(0),
            ),
        ).astype(jnp.int32)
        touched, lastv = last_update_table(slot_upd, v, capacity)
        W = opcodes.shape[0]
        top = (
            after[W - 1] if W > 0 else state["top"]
        ).astype(jnp.int32)
        return {"touched": touched, "lastv": lastv, "top": top,
                "resps": resps}

    def window_merge(state, plan):
        """Per-replica dense merge of the shared plan (elementwise; the
        honest per-replica replay work of the combined engine)."""
        buf = jnp.where(plan["touched"], plan["lastv"], state["buf"])
        return {"buf": buf, "top": plan["top"]}, plan["resps"]

    return Dispatch(
        name=f"stack{capacity}",
        make_state=make_state,
        write_ops=(push, pop),
        read_ops=(peek, length),
        arg_width=3,
        window_apply=window_apply,
        window_plan=window_plan,
        window_merge=window_merge,
        window_canonical=True,
    )
