"""Replicated ordered set — the skiplist ("mlnr") workload analog.

The reference's lockfree benches replay a concurrent skiplist through CNR,
sweeping the number of logs (`benches/lockfree.rs:243-276`). A skiplist is
a pointer structure chosen for O(log n) ordered ops on a CPU; on TPU the
same *semantics* over a bounded keyspace are a presence bitmap — membership
is one gather, and ordered queries (rank/range-count) are masked reductions
that vectorize across the replica axis. Order-statistic reads cost O(K)
lanes but run at full VPU width; the dense layout is the TPU-native trade.

`sortedset_log_mapper` partitions by key (`cnr` LogMapper contract: equal
keys conflict → same log; distinct keys commute).

Write opcodes:
  SS_INSERT=1  args (k) → resp 1 if newly inserted else 0.
  SS_REMOVE=2  args (k) → resp 1 if present else 0.
Read opcodes:
  SS_CONTAINS=1    args (k) → 0/1.
  SS_RANGE_COUNT=2 args (lo, hi) → #elements in [lo, hi).
  SS_RANK=3        args (k) → #elements < k.
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

SS_INSERT = 1
SS_REMOVE = 2
SS_CONTAINS = 1
SS_RANGE_COUNT = 2
SS_RANK = 3


def sortedset_log_mapper(opcode: int, args: tuple) -> int:
    return args[0]


def make_sortedset(n_keys: int) -> Dispatch:
    def make_state():
        return {"present": jnp.zeros((n_keys,), jnp.bool_)}

    def insert(state, args):
        k = args[0] % n_keys
        was = state["present"][k]
        return {"present": state["present"].at[k].set(True)}, (
            ~was
        ).astype(jnp.int32)

    def remove(state, args):
        k = args[0] % n_keys
        was = state["present"][k]
        return {"present": state["present"].at[k].set(False)}, was.astype(
            jnp.int32
        )

    def contains(state, args):
        return state["present"][args[0] % n_keys].astype(jnp.int32)

    def range_count(state, args):
        ks = jnp.arange(n_keys, dtype=jnp.int32)
        mask = (ks >= args[0]) & (ks < args[1]) & state["present"]
        return jnp.sum(mask).astype(jnp.int32)

    def rank(state, args):
        ks = jnp.arange(n_keys, dtype=jnp.int32)
        return jnp.sum((ks < args[0]) & state["present"]).astype(jnp.int32)

    def window_plan(state, opcodes, args):
        """Combined replay (see `Dispatch.window_apply` and the hashmap
        twin, `models/hashmap.py`): insert/remove are last-writer-wins
        per key, and every response is presence-just-before — the
        same-key predecessor's effect, or the replica's initial presence
        on first touch. One stable sort + predecessor lookup + dense
        merge, bit-identical to the sequential fold
        (tests/test_window.py). Packaged as plan/merge (r5): the sort
        half runs once per window; per-key finals are absolute, so the
        plan is prefix-absorbing (union-window catch-up eligible)."""
        W = opcodes.shape[0]
        k = args[:, 0] % n_keys
        is_ins = opcodes == SS_INSERT
        is_rem = opcodes == SS_REMOVE
        active = is_ins | is_rem
        key_eff = jnp.where(active, k, n_keys).astype(jnp.int64)
        idx = jnp.arange(W, dtype=jnp.int64)
        # stable sort on the key alone (composite key*(W+1)+idx overflows
        # int32 under NR_TPU_NO_X64=1 — ADVICE r3)
        order = jnp.argsort(key_eff, stable=True)
        sk = key_eff[order]
        same_prev = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), sk[1:] == sk[:-1]]
        )
        prev = jnp.concatenate([order[:1], order[:-1]])
        init_present = state["present"].at[
            sk.astype(jnp.int32)
        ].get(mode="clip")
        pres_before = jnp.where(same_prev, is_ins[prev], init_present)
        # insert → 1 if newly inserted (= !present-before); remove → 1 if
        # present-before; inactive slots answer 0
        resp_sorted = jnp.where(
            is_ins[order],
            (~pres_before).astype(jnp.int32),
            jnp.where(is_rem[order], pres_before.astype(jnp.int32), 0),
        )
        resps = jnp.zeros((W,), jnp.int32).at[order].set(resp_sorted)
        last = (
            jnp.full((n_keys + 1,), -1, jnp.int64)
            .at[key_eff].max(idx)[:n_keys]
        )
        touched = last >= 0
        li = jnp.clip(last, 0).astype(jnp.int32)
        return {"touched": touched, "present": is_ins[li],
                "resps": resps}

    def window_merge(state, plan):
        return {
            "present": jnp.where(plan["touched"], plan["present"],
                                 state["present"])
        }, plan["resps"]

    def window_apply(state, opcodes, args):
        # arbitrary-state form: the plan's presence-before half reads
        # THIS state, so the composition is the full per-replica fold
        return window_merge(state, window_plan(state, opcodes, args))

    return Dispatch(
        name=f"sortedset{n_keys}",
        make_state=make_state,
        write_ops=(insert, remove),
        read_ops=(contains, range_count, rank),
        arg_width=3,
        window_apply=window_apply,
        window_plan=window_plan,
        window_merge=window_merge,
        window_canonical=True,
    )
