"""Replicated ordered set — the skiplist ("mlnr") workload analog.

The reference's lockfree benches replay a concurrent skiplist through CNR,
sweeping the number of logs (`benches/lockfree.rs:243-276`). A skiplist is
a pointer structure chosen for O(log n) ordered ops on a CPU; on TPU the
same *semantics* over a bounded keyspace are a presence bitmap — membership
is one gather, and ordered queries (rank/range-count) are masked reductions
that vectorize across the replica axis. Order-statistic reads cost O(K)
lanes but run at full VPU width; the dense layout is the TPU-native trade.

`sortedset_log_mapper` partitions by key (`cnr` LogMapper contract: equal
keys conflict → same log; distinct keys commute).

Write opcodes:
  SS_INSERT=1  args (k) → resp 1 if newly inserted else 0.
  SS_REMOVE=2  args (k) → resp 1 if present else 0.
Read opcodes:
  SS_CONTAINS=1    args (k) → 0/1.
  SS_RANGE_COUNT=2 args (lo, hi) → #elements in [lo, hi).
  SS_RANK=3        args (k) → #elements < k.
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

SS_INSERT = 1
SS_REMOVE = 2
SS_CONTAINS = 1
SS_RANGE_COUNT = 2
SS_RANK = 3


def sortedset_log_mapper(opcode: int, args: tuple) -> int:
    return args[0]


def make_sortedset(n_keys: int) -> Dispatch:
    def make_state():
        return {"present": jnp.zeros((n_keys,), jnp.bool_)}

    def insert(state, args):
        k = args[0] % n_keys
        was = state["present"][k]
        return {"present": state["present"].at[k].set(True)}, (
            ~was
        ).astype(jnp.int32)

    def remove(state, args):
        k = args[0] % n_keys
        was = state["present"][k]
        return {"present": state["present"].at[k].set(False)}, was.astype(
            jnp.int32
        )

    def contains(state, args):
        return state["present"][args[0] % n_keys].astype(jnp.int32)

    def range_count(state, args):
        ks = jnp.arange(n_keys, dtype=jnp.int32)
        mask = (ks >= args[0]) & (ks < args[1]) & state["present"]
        return jnp.sum(mask).astype(jnp.int32)

    def rank(state, args):
        ks = jnp.arange(n_keys, dtype=jnp.int32)
        return jnp.sum((ks < args[0]) & state["present"]).astype(jnp.int32)

    return Dispatch(
        name=f"sortedset{n_keys}",
        make_state=make_state,
        write_ops=(insert, remove),
        read_ops=(contains, range_count, rank),
        arg_width=3,
    )
