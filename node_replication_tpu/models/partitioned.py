"""State-partitioned models: the parallel multi-log replay payoff.

The reference's CNR exists so that L combiners replay L logs *in parallel*
(`cnr/src/replica.rs:93-98`, dispatch concurrent across logs at
`cnr/src/replica.rs:713-720`); its lockfree bench sweeps #logs to show
throughput rising with L (`benches/lockfree.rs:243-276`). The TPU
equivalent: because the LogMapper contract guarantees ops on different logs
commute (`cnr/src/lib.rs:123-137`), each log's span can be applied to a
*disjoint partition* of the state — and then all L per-log scans run as one
`vmap` over the (log × replica) axes instead of a sequential per-log fold.

A `PartitionedModel` packages what that needs:

- `full`   — the ordinary `Dispatch` (reads always run against merged full
  state; also the fold-path replay dispatch for differential tests),
- `sub`    — a `Dispatch` over ONE partition's sub-state; write args arrive
  untransformed (full keys), the sub ops map them into the partition
  (`k → k // L` for the congruence partition),
- `split(state) -> stacked` — reshape the state pytree into `[L, ...]`
  stacked partitions (pure layout change, no gather),
- `merge(stacked) -> state` — the inverse.

The bundled partitions are *congruence classes of args[0]* (key for
hashmap / sorted set, fd for memfs): partition l owns every key ≡ l
(mod L), matching the benches' LogMapper `hash = args[0] % nlogs`. Keys
land in slot `k // L` of their partition, so `split` is a reshape
`[K] → [K/L, L] → (moveaxis) → [L, K/L]`.

Correctness contract: replay through `split → vmapped per-log scans with
`sub` → merge` is bit-identical to the sequential fold with `full` IFF
every op appended to log l satisfies `args[0] % L == l` (the LogMapper
invariant). Ops that violate it would mutate the wrong partition — the
same undefined behavior the reference ascribes to a non-conforming
LogMapper impl.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from node_replication_tpu.models.hashmap import make_hashmap
from node_replication_tpu.models.memfs import make_memfs
from node_replication_tpu.models.sortedset import make_sortedset
from node_replication_tpu.ops.encoding import Dispatch

PyTree = Any


@dataclasses.dataclass(frozen=True)
class PartitionedModel:
    """A Dispatch plus its L-way disjoint state partition (frozen →
    hashable → usable as a jit static argument)."""

    full: Dispatch
    sub: Dispatch
    nlogs: int
    split: Callable[[PyTree], PyTree]
    merge: Callable[[PyTree], PyTree]

    @property
    def name(self) -> str:
        return f"{self.full.name}/p{self.nlogs}"


def _congruence_split(nlogs: int):
    """split/merge for pytrees whose every leaf is keyed by a leading axis
    of congruence classes: `[K, ...] → [L, K/L, ...]` with
    `stacked[l, j] = state[j * L + l]`."""

    def split(state: PyTree) -> PyTree:
        return jax.tree.map(
            lambda x: jnp.moveaxis(
                x.reshape((x.shape[0] // nlogs, nlogs) + x.shape[1:]), 1, 0
            ),
            state,
        )

    def merge(stacked: PyTree) -> PyTree:
        return jax.tree.map(
            lambda x: jnp.moveaxis(x, 0, 1).reshape(
                (x.shape[0] * x.shape[1],) + x.shape[2:]
            ),
            stacked,
        )

    return split, merge


def _div_arg0(d: Dispatch, nlogs: int, name: str) -> Dispatch:
    """Wrap a Dispatch so args[0] is divided by L before each op: the
    partition-local addressing `k → k // L` of the congruence partition.
    The combined `window_apply` (when the model has one) gets the same
    key transform on its whole window."""

    def wrap(f):
        def g(s, a):
            return f(s, a.at[0].set(a[0] // nlogs))

        return g

    wa = d.window_apply
    if wa is not None:
        def window_apply(state, opcodes, args):
            return wa(state, opcodes, args.at[:, 0].set(
                args[:, 0] // nlogs
            ))
    else:
        window_apply = None

    return dataclasses.replace(
        d,
        name=name,
        write_ops=tuple(wrap(f) for f in d.write_ops),
        read_ops=tuple(wrap(f) for f in d.read_ops),
        window_apply=window_apply,
    )


def _check_divisible(n: int, nlogs: int, what: str) -> None:
    if nlogs < 1:
        raise ValueError("need at least one log")
    if n % nlogs:
        raise ValueError(
            f"{what}={n} must be a multiple of nlogs={nlogs} for the "
            f"congruence partition (pad {what} up)"
        )


def make_partitioned_hashmap(
    n_keys: int, nlogs: int, prefill_value: int | None = None
) -> PartitionedModel:
    """Key-congruence partition of the dense hashmap: log l owns keys
    ≡ l (mod L); each partition is itself a dense hashmap of K/L slots."""
    _check_divisible(n_keys, nlogs, "n_keys")
    full = make_hashmap(n_keys, prefill_value)
    sub = _div_arg0(
        make_hashmap(n_keys // nlogs, prefill_value),
        nlogs,
        f"hashmap{n_keys}sub{nlogs}",
    )
    split, merge = _congruence_split(nlogs)
    return PartitionedModel(full, sub, nlogs, split, merge)


def make_partitioned_sortedset(n_keys: int, nlogs: int) -> PartitionedModel:
    """Key-congruence partition of the ordered set. Single-key writes
    (insert/remove) act on one partition; order-statistic reads
    (range-count/rank) span partitions and therefore always run against
    the merged full state — exactly why the reference requires multi-key
    ops to share a log or sync (`cnr/src/lib.rs:123-137`)."""
    _check_divisible(n_keys, nlogs, "n_keys")
    full = make_sortedset(n_keys)
    sub = _div_arg0(
        make_sortedset(n_keys // nlogs),
        nlogs,
        f"sortedset{n_keys}sub{nlogs}",
    )
    split, merge = _congruence_split(nlogs)
    return PartitionedModel(full, sub, nlogs, split, merge)


def make_partitioned_memfs(
    n_files: int, n_blocks: int, nlogs: int
) -> PartitionedModel:
    """Per-file partition of the in-memory FS (the nrfs `fd - 1` LogMapper,
    `benches/nrfs.rs:25-39`): log l owns files ≡ l (mod L)."""
    _check_divisible(n_files, nlogs, "n_files")
    full = make_memfs(n_files, n_blocks)
    sub = _div_arg0(
        make_memfs(n_files // nlogs, n_blocks),
        nlogs,
        f"memfs{n_files}x{n_blocks}sub{nlogs}",
    )
    split, merge = _congruence_split(nlogs)
    return PartitionedModel(full, sub, nlogs, split, merge)
