"""Sequence-register model: per-slot fetch-and-set, the serve-layer
correctness oracle.

A dense array of int32 registers where the ONLY write op atomically
sets a slot and returns its PREVIOUS value. That response makes lost,
duplicated, and reordered executions all observable from the client
side: a client that owns slot `s` and writes the values `1, 2, 3, …`
in order must read back exactly `0, 1, 2, …` — any gap is a lost op,
any repeat is a duplicate, any other mismatch is a reorder. The serve
bench (`bench.py --serve`) and the elasticity-under-load test drive
10k+ ops through the frontend and check every response against this
invariant (the sequence-numbered linearizability check of ISSUE 3).

Responses depend on the pre-state of each entry, so the model has no
combined window form on purpose — it exercises the generic per-entry
scan replay, the faithful analog of the reference's replay loop
(`nr/src/log.rs:473-524`).

Write opcodes: SR_SET=1 (args slot, v → resp previous value).
Read opcodes: SR_GET=1 (args slot → resp current value).
"""

from __future__ import annotations

import jax.numpy as jnp

from node_replication_tpu.ops.encoding import Dispatch

SR_SET = 1
SR_GET = 1


def make_seqreg(n_slots: int) -> Dispatch:
    """Build the sequence-register Dispatch over `n_slots` registers
    (all initially 0). Slots index with `slot % n_slots`."""

    def make_state():
        return {"values": jnp.zeros((n_slots,), jnp.int32)}

    def fetch_and_set(state, args):
        s = args[0] % n_slots
        old = state["values"][s]
        return {"values": state["values"].at[s].set(args[1])}, old

    def get(state, args):
        return state["values"][args[0] % n_slots]

    return Dispatch(
        name=f"seqreg{n_slots}",
        make_state=make_state,
        write_ops=(fetch_and_set,),
        read_ops=(get,),
        arg_width=3,
    )
