"""Pallas TPU replay kernels for the vspace models (flat + 4-level radix).

This generalizes the hashmap replay template (`ops/pallas_replay.py`) to
the model class the r3 verdict called out: ops that touch a SPAN of state
per entry (page-table map/unmap over up to `max_span` contiguous pages,
plus the radix model's 512-page table teardown) — the NrOS workload the
reference replays through its hot loop (`nr/src/log.rs:473-524`,
`benches/vspace.rs:176-481`).

Layout (vs the hashmap kernel's `[K, R]` transpose):

- page-table state lives per replica as `[ROWS, 128]` int32 — pages on
  (sublane, lane) in row-major 128-page rows. A map/unmap span of
  `n <= max_span` contiguous pages covers a STATIC number of rows, read
  with one dynamic-sublane slice and updated as a lane-masked blend:
  `page_id = row_base*128 + iota`, `mask = (page >= v) & (page < v+n)`,
  value affine in the page id. No per-page loop — the span IS the vector.
  The radix teardown clears a 512-page region = 4 aligned rows riding
  the same unified read-blend-write (row base and masks select per op
  kind), so the whole entry is STRAIGHT-LINE code: no branches.
- the grid processes replicas in GROUPS of `G` (largest VMEM-fitting
  divisor of R): the per-entry scalar work (SMEM window reads, level
  walks, index math) — which dominates a sequential replay loop — is
  paid once per group instead of once per replica, while the state
  blend is a `[G, H, 128]` vector op that does the honest per-replica
  work on the vector units.
- PML4/PDPT/PD present tables are SMALL (`ceil(P/512)` entries and up).
  PD lives in SMEM, read/written as scalars by dynamic index (a span
  crosses at most 2 entries). PDPT/PML4 (at most a few entries under
  the VMEM page gate) are carried IN REGISTERS through the replay loop
  and written back once.

Lock-step invariant: the fused step replays the identical window into
every replica, so replica states are identical by induction from
identical init. The kernel therefore keeps ONE canonical copy of the
level tables and of the response vector (they are provably equal across
replicas), while the page-table state — where the replay work lives —
stays per replica. `make_pallas_vspace_step` documents and preserves
this invariant; it is the same lock-step precondition `core/step`'s
combined engine already requires.

The kernel applies entries strictly in order, so — unlike the combined
`window_apply` reduction — it needs no algebraic window form and is the
rescue path for order-dependent replay at hardware speed. Responses are
bit-identical to the sequential fold (tests/test_pallas_vspace.py pins
this in interpret mode; `NR_TPU_SMOKE=1` runs the hardware check).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from node_replication_tpu.core.log import LogSpec, log_append
from node_replication_tpu.ops.pallas_ring import FusedEngineHost
from node_replication_tpu.utils.compat import x64_disabled

_FRAME_MASK = (1 << 30) - 1
_DEV_BIT = 1 << 30
_VMEM_BUDGET = 12 << 20


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _page_grid(row0, height):
    """`page_id[height, 128]` for rows starting at `row0` (scalar)."""
    return (
        row0 * 128
        + jax.lax.broadcasted_iota(jnp.int32, (height, 128), 0) * 128
        + jax.lax.broadcasted_iota(jnp.int32, (height, 128), 1)
    )


def _sum32(x):
    """int32 full reduction of `[rows, 128]` by unrolled adds.

    Mosaic's reduce lowering consults the ambient x64 config when the
    kernel is re-traced at jit-COMPILE time (outside any caller-side
    `enable_x64(False)`), inserting an int64 accumulator convert it then
    rejects — so fold rows with static slices and halve the lane axis
    with shifted adds instead; no reduce primitive at all.
    """
    row = x[0:1, :]
    for r in range(1, x.shape[0]):
        row = row + x[r:r + 1, :]
    w = x.shape[1]
    while w > 1:
        w //= 2
        row = row[:, :w] + row[:, w:2 * w]
    return row[0, 0]


def _floored_mod(x, m: int):
    r = jax.lax.rem(x, jnp.int32(m))
    return jnp.where(r < 0, r + jnp.int32(m), r)


def _smem_copy(dst, src, width: int):
    """Element-wise SMEM copy (Mosaic only loads scalars from SMEM)."""

    def cp(j, c):
        dst[0, 0, j] = src[0, 0, j]
        return c

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(width), cp, jnp.int32(0))


# --------------------------------------------------------------- flat
def _flat_kernel(opc_ref, a0_ref, a1_ref, a2_ref, fr_in, fr_out, resp_ref,
                 *, n_pages: int, max_span: int, window: int, rows: int,
                 span_rows: int):
    # the kernel is (re-)traced at jit-COMPILE time, outside any caller's
    # enable_x64(False) context — guard here so an x64 session can't
    # leak int64 converts into the Mosaic lowering
    with x64_disabled():
        _flat_body(opc_ref, a0_ref, a1_ref, a2_ref, fr_in, fr_out,
                   resp_ref, n_pages, max_span, window, rows, span_rows,
                   copy_in=True)


def _flat_plan_kernel(opc_ref, a0_ref, a1_ref, a2_ref, fr_in, tch_in,
                      fr_out, tch_out, resp_ref,
                      *, n_pages: int, max_span: int, window: int,
                      rows: int, span_rows: int):
    # plan variant (r5): one canonical replica, plus a TOUCHED plane
    # marking every page written in-window — the dense delta the vmapped
    # model-side `window_merge` blends per replica (see
    # make_pallas_vspace_plan_step)
    del tch_in  # aliased to tch_out
    with x64_disabled():
        _flat_body(opc_ref, a0_ref, a1_ref, a2_ref, fr_in, fr_out,
                   resp_ref, n_pages, max_span, window, rows, span_rows,
                   tch_out=tch_out)


def _flat_body(opc_ref, a0_ref, a1_ref, a2_ref, fr_in, fr_out, resp_ref,
               n_pages, max_span, window, rows, span_rows, tch_out=None,
               copy_in=False):
    # copy_in=True: UN-aliased in/out — aliased blocked state races with
    # the pipeline's prefetch/writeback on hardware past ~32 grid steps
    # (see ops/pallas_oahashmap._oa_body); the grid=1 plan kernels keep
    # in-place aliasing (copy_in=False)
    if copy_in:
        fr_out[...] = fr_in[...]
    else:
        del fr_in
    P = jnp.int32(n_pages)

    def body(i, carry):
        op = opc_ref[i]
        vs = a0_ref[i]          # RAW vpage: the flat model mods per lane
        a1 = a1_ref[i]
        is_map = op == 1
        is_un = op == 2
        is_span = is_map | is_un
        n = jnp.clip(jnp.where(is_un, a1, a2_ref[i]), 0,
                     jnp.int32(max_span))
        # scalar gate instead of a branch: inactive entries get an empty
        # span (n_eff=0) and the blends write state back unchanged
        n_eff = jnp.where(is_span, n, 0)
        vm = _floored_mod(vs, n_pages)

        def run(blk, row0, base_page):
            page = _page_grid(row0, span_rows)
            lane = page - base_page  # int32 wrap matches the model
            raw = vs + lane
            mask = (
                (lane >= 0) & (lane < n_eff) & (raw < P) & (page < P)
                & (page >= base_page) & (page < base_page + n_eff)
            )
            # arithmetic select: Mosaic cannot legalize a scalar-cond
            # select over i1 vectors (maps count absent pages, unmaps
            # count present ones). Replica 0 speaks for the group under
            # the lock-step invariant.
            pres = (blk[0] != 0).astype(jnp.int32)
            im = is_map.astype(jnp.int32)
            bits = im * (1 - pres) + (1 - im) * pres
            cnt = _sum32(mask.astype(jnp.int32) * bits)
            newv = jnp.where(is_map, a1 + lane, 0)
            return cnt, mask, jnp.where(mask[None], newv[None], blk)

        # run B: lanes with vm+lane < P (pages [vm, vm+n) direct)
        row0 = jnp.minimum(vm >> 7, jnp.int32(rows - span_rows))
        c_b, m_b, out_b = run(fr_out[:, pl.ds(row0, span_rows), :],
                              row0, vm)
        fr_out[:, pl.ds(row0, span_rows), :] = out_b
        if tch_out is not None:
            tb = tch_out[:, pl.ds(row0, span_rows), :]
            tch_out[:, pl.ds(row0, span_rows), :] = jnp.where(
                m_b[None], jnp.int32(1), tb
            )
        # run A: wrapped lanes (pages [0, vm+n-P)) — reachable only when
        # the raw vpage was negative (mod wraps the span). Rows start at
        # STATIC 0 (a concrete-constant pl.ds start miscompiles in
        # Mosaic). Run-A rows never overlap run-B's for n_pages >=
        # span_rows*128 + max_span (checked in make_vspace_replay), so
        # the read-after-write is clean.
        c_a, m_a, out_a = run(fr_out[:, :span_rows, :], 0, vm - P)
        fr_out[:, :span_rows, :] = out_a
        if tch_out is not None:
            ta = tch_out[:, :span_rows, :]
            tch_out[:, :span_rows, :] = jnp.where(
                m_a[None], jnp.int32(1), ta
            )
        resp_ref[0, 0, i] = c_b + c_a
        return carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(window), body, jnp.int32(0))


# -------------------------------------------------------------- radix
def _radix_kernel(opc_ref, a0_ref, a1_ref, a2_ref,
                  pt_in, pd_in, pdpt_in, pml4_in,
                  pt_out, pd_out, pdpt_out, pml4_out, resp_ref,
                  *, n_pages: int, max_span: int, window: int, rows: int,
                  height: int, l2: int, l3: int, l4: int):
    # see _flat_kernel: guard the compile-time re-trace against x64
    with x64_disabled():
        _radix_body(opc_ref, a0_ref, a1_ref, a2_ref, pt_in, pd_in,
                    pdpt_in, pml4_in, pt_out, pd_out, pdpt_out, pml4_out,
                    resp_ref, n_pages, max_span, window, rows, height,
                    l2, l3, l4, copy_in=True)


def _radix_plan_kernel(opc_ref, a0_ref, a1_ref, a2_ref,
                       pt_in, pd_in, pdpt_in, pml4_in,
                       wins_in, clr_in, pdt_in,
                       pt_out, pd_out, pdpt_out, pml4_out, resp_ref,
                       wins_out, clr_out, pdt_out,
                       *, n_pages: int, max_span: int, window: int,
                       rows: int, height: int, l2: int, l3: int,
                       l4: int):
    # plan variant (r5): one canonical replica, extended with the dense
    # delta planes the model-side `window_merge` consumes — WINS (page
    # written since the last region clear), CLEARED (page's region torn
    # down in-window), and the per-PD-entry TOUCHED flags. All three
    # ride the same lane masks as the state blends; the scalar stream is
    # unchanged except two SMEM flag stores per entry.
    del wins_in, clr_in, pdt_in  # aliased to their outs
    with x64_disabled():
        _radix_body(opc_ref, a0_ref, a1_ref, a2_ref, pt_in, pd_in,
                    pdpt_in, pml4_in, pt_out, pd_out, pdpt_out, pml4_out,
                    resp_ref, n_pages, max_span, window, rows, height,
                    l2, l3, l4,
                    plan_refs=(wins_out, clr_out, pdt_out))


def _radix_body(opc_ref, a0_ref, a1_ref, a2_ref, pt_in, pd_in, pdpt_in,
                pml4_in, pt_out, pd_out, pdpt_out, pml4_out, resp_ref,
                n_pages, max_span, window, rows, height, l2, l3, l4,
                plan_refs=None, copy_in=False):
    # copy_in=True: UN-aliased pt in/out (the aliased-block pipeline
    # race — see _flat_body); the grid=1 plan kernel keeps aliasing.
    # pd is the grid-invariant SHARED copy and must be reset from its
    # (unaliased) input at every grid step — later grid steps recompute
    # the identical level trajectory so their responses stay correct
    if copy_in:
        pt_out[...] = pt_in[...]
    else:
        del pt_in
    _smem_copy(pd_out, pd_in, l2)
    P = jnp.int32(n_pages)
    H = height

    def body(i, carry):
        # carry = (pdpt_0..pdpt_{l3-1}, pml4_0) — the upper levels ride
        # registers through the loop (monotone except for the final
        # write-back; they are only ever SET)
        pdpt_c = carry[:l3]
        pml4_c = carry[l3]
        op = opc_ref[i]
        vs = _floored_mod(a0_ref[i], n_pages)  # the model mods up front
        a1 = a1_ref[i]
        is_map = (op == 1) | (op == 2)
        is_dev = op == 2
        is_un = op == 3
        is_tbl = op == 4
        is_span = is_map | is_un
        n = jnp.clip(jnp.where(is_un, a1, a2_ref[i]), 0,
                     jnp.int32(max_span))
        # scalar gates instead of branches: inactive entries see an
        # empty span and an empty region, and every blend becomes an
        # identity write
        n_eff = jnp.where(is_span, n, 0)
        tbl_lim = jnp.where(is_tbl, P, jnp.int32(-1))
        r0 = vs >> 9
        r1 = jnp.minimum(r0 + 1, jnp.int32(l2 - 1))
        q_span = jnp.minimum(vs >> 7, jnp.int32(rows - H))
        q_tbl = jnp.minimum(r0 * 4, jnp.int32(rows - H))
        row0 = jnp.where(is_tbl, q_tbl, q_span)
        blk = pt_out[:, pl.ds(row0, H), :]            # [G, H, 128]
        page = _page_grid(row0, H)                    # [H, 128]
        mask_span = (page >= vs) & (page < vs + n_eff) & (page < P)
        mask_tbl = (page < tbl_lim) & ((page >> 9) == r0)
        # ---- full walk BEFORE the op (levels read pre-update) --------
        pd0 = pd_out[0, 0, r0]
        pd1 = pd_out[0, 0, r1]
        pd_l = jnp.where((page >> 9) == r0, pd0, pd1)
        pdpt_l = jnp.broadcast_to(pdpt_c[l3 - 1], page.shape)
        for k in range(l3 - 1):
            pdpt_l = jnp.where((page >> 18) == k, pdpt_c[k], pdpt_l)
        # P < 2^27 (VMEM gate) => every page's PML4 entry is 0
        walk = (
            (pd_l > 0) & (pdpt_l > 0) & (pml4_c > 0) & (blk[0] != 0)
        ).astype(jnp.int32)
        # responses: maps count not-fully-walked span pages, unmaps
        # count walked ones, teardown counts walked region pages —
        # arithmetic select (scalar-cond select over i1 vectors does not
        # legalize in Mosaic)
        im = is_map.astype(jnp.int32)
        span_bits = mask_span.astype(jnp.int32) * (
            im * (1 - walk) + (1 - im) * walk
        )
        tbl_bits = mask_tbl.astype(jnp.int32) * walk
        resp_ref[0, 0, i] = _sum32(span_bits + tbl_bits)
        # ---- unified state blend -------------------------------------
        entry = ((a1 + (page - vs) + 1) & jnp.int32(_FRAME_MASK)) | (
            jnp.where(is_dev, jnp.int32(_DEV_BIT), 0)
        )
        newv = jnp.where(is_map, entry, 0)            # unmap stores 0
        out = jnp.where(mask_span[None], newv[None], blk)
        out = jnp.where(mask_tbl[None], 0, out)
        pt_out[:, pl.ds(row0, H), :] = out
        if plan_refs is not None:
            wins_out, clr_out, _pdt = plan_refs
            # wins: written-since-last-clear — map/unmap lanes set, a
            # region teardown resets its pages
            wblk = wins_out[:, pl.ds(row0, H), :]
            wnew = jnp.where(mask_span[None], jnp.int32(1), wblk)
            wnew = jnp.where(mask_tbl[None], jnp.int32(0), wnew)
            wins_out[:, pl.ds(row0, H), :] = wnew
            cblk = clr_out[:, pl.ds(row0, H), :]
            clr_out[:, pl.ds(row0, H), :] = jnp.where(
                mask_tbl[None], jnp.int32(1), cblk
            )
        # ---- level updates (mirrors _mark_levels + teardown) ---------
        live = is_map & (n > 0)
        last = jnp.maximum(vs + n - 1, vs)
        ok0 = live & (r0 <= (last >> 9))
        ok1 = live & (r0 + 1 <= (last >> 9)) & (r0 + 1 < l2)
        value0 = jnp.where(is_tbl, 0, jnp.where(ok0, 1, pd0))
        value1 = jnp.where(ok1, 1, jnp.where(r1 == r0, value0, pd1))
        pd_out[0, 0, r0] = value0
        pd_out[0, 0, r1] = value1
        if plan_refs is not None:
            _pdt = plan_refs[2]
            # touched = a real update landed (mark under ok0/ok1, clear
            # under is_tbl); passthrough writes don't count
            _pdt[0, 0, r0] = jnp.where(ok0 | is_tbl, 1, _pdt[0, 0, r0])
            _pdt[0, 0, r1] = jnp.where(ok1, 1, _pdt[0, 0, r1])
        h0 = vs >> 18
        hl = last >> 18
        new_pdpt = tuple(
            jnp.where(live & ((h0 == k) | (hl == k)), 1, pdpt_c[k])
            for k in range(l3)
        )
        new_pml4 = jnp.where(live, 1, pml4_c)  # vs>>27 == 0 under gate
        return new_pdpt + (new_pml4,)

    init = tuple(pdpt_in[0, 0, k] for k in range(l3)) + (pml4_in[0, 0, 0],)
    final = jax.lax.fori_loop(jnp.int32(0), jnp.int32(window), body, init)
    for k in range(l3):
        pdpt_out[0, 0, k] = final[k]
    pml4_out[0, 0, 0] = final[l3]


def _levels(n_pages: int):
    l2 = max(1, -(-n_pages // 512))
    l3 = max(1, -(-n_pages // (512 ** 2)))
    l4 = max(1, -(-n_pages // (512 ** 3)))
    return l2, l3, l4


def _grid_layout(n_pages: int, n_replicas: int, interpret: bool,
                 what: str, aliased: bool = False):
    """ROWS (page rows per replica) and G (replicas per grid step).

    `aliased=True` (the grid=1 plan kernels): one in-place pt buffer.
    `aliased=False` (multi-grid-step classic kernels): separate in+out
    blocks (the pipeline race — see _flat_body), each double-buffered.
    """
    rows = max(4, _round_up(n_pages, 512) // 128)
    per = (2 if aliased else 4) * rows * 128 * 4
    if per > _VMEM_BUDGET and not interpret:
        raise ValueError(
            f"{what} pallas replay needs {per >> 20} MB of VMEM for "
            f"n_pages={n_pages}; use the combined/scan engines "
            f"(core/step.make_step) for this config"
        )
    group = 1
    for g in range(n_replicas, 0, -1):
        if n_replicas % g == 0 and g * per <= _VMEM_BUDGET:
            group = g
            break
    return rows, group


def make_vspace_replay(
    n_pages: int,
    n_replicas: int,
    window: int,
    max_span: int,
    radix: bool,
    interpret: bool = False,
):
    """Build the chunk replayer.

    flat:  `replay(opc[W], args[W,3], frames[R, ROWS, 128])
            -> (frames, resps[W])`
    radix: `replay(opc[W], args[W,3], pt[R, ROWS, 128], pd[l2],
            pdpt[l3], pml4[l4]) -> (pt, pd, pdpt, pml4, resps[W])`

    Levels and responses are single canonical copies under the lock-step
    identical-replicas invariant (see module docstring).
    """
    from jax.experimental.pallas import tpu as pltpu

    if max_span > 512:
        raise ValueError("max_span > 512 breaks the 2-entry/level "
                         "invariant of the radix walk kernel")
    what = "radix vspace" if radix else "flat vspace"
    rows, group = _grid_layout(n_pages, n_replicas, interpret, what)
    span_rows = min(-(-max_span // 128) + 1, rows)
    if not radix and n_pages < span_rows * 128 + max_span:
        raise ValueError(
            f"flat vspace pallas replay needs n_pages >= "
            f"{span_rows * 128 + max_span} so a mod-wrapped span's two "
            f"row blends never overlap; use the combined engine for "
            f"n_pages={n_pages}"
        )
    from node_replication_tpu.ops.pallas_chunk import (
        build_calls,
        chunk_size,
        run_chunks,
    )

    chunk_r = chunk_size(n_replicas, group)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    # single canonical copies: every grid step recomputes the identical
    # values from the identical window (idempotent revisions)
    shared = lambda width: pl.BlockSpec(
        (1, 1, width), lambda i: (0, 0, 0), memory_space=pltpu.SMEM)

    if not radix:
        kernel = functools.partial(
            _flat_kernel, n_pages=n_pages, max_span=max_span,
            window=window, rows=rows, span_rows=span_rows,
        )

        def build_call(sub_r: int):
            state_spec = pl.BlockSpec((group, rows, 128),
                                      lambda i: (i, 0, 0))
            return pl.pallas_call(
                kernel,
                grid=(sub_r // group,),
                in_specs=[smem(), smem(), smem(), smem(), state_spec],
                out_specs=[state_spec, shared(window)],
                out_shape=[
                    jax.ShapeDtypeStruct((sub_r, rows, 128), jnp.int32),
                    jax.ShapeDtypeStruct((1, 1, window), jnp.int32),
                ],
                # NO aliasing: un-aliased in/out (pipeline race)
                interpret=interpret,
            )

        calls = build_calls(n_replicas, chunk_r, build_call)

        def replay(opc, args, frames):
            with x64_disabled():
                a0, a1, a2 = args[:, 0], args[:, 1], args[:, 2]
                (frames,), (resps,) = run_chunks(
                    n_replicas, chunk_r, calls,
                    lambda call, r0, sub: call(
                        opc, a0, a1, a2, frames[r0:r0 + sub]
                    ),
                    n_plane_outs=1,
                )
            return frames, resps.reshape(window)

        return replay

    l2, l3, l4 = _levels(n_pages)
    assert l4 == 1, "unreachable: the VMEM gate caps n_pages << 2^27"
    height = max(span_rows, 4)
    kernel = functools.partial(
        _radix_kernel, n_pages=n_pages, max_span=max_span, window=window,
        rows=rows, height=height, l2=l2, l3=l3, l4=l4,
    )

    def build_call(sub_r: int):
        state_spec = pl.BlockSpec((group, rows, 128),
                                  lambda i: (i, 0, 0))
        return pl.pallas_call(
            kernel,
            grid=(sub_r // group,),
            in_specs=[smem(), smem(), smem(), smem(), state_spec,
                      shared(l2), shared(l3), shared(l4)],
            out_specs=[state_spec, shared(l2), shared(l3), shared(l4),
                       shared(window)],
            out_shape=[
                jax.ShapeDtypeStruct((sub_r, rows, 128), jnp.int32),
                jax.ShapeDtypeStruct((1, 1, l2), jnp.int32),
                jax.ShapeDtypeStruct((1, 1, l3), jnp.int32),
                jax.ShapeDtypeStruct((1, 1, l4), jnp.int32),
                jax.ShapeDtypeStruct((1, 1, window), jnp.int32),
            ],
            # NO aliasing: un-aliased in/out (pipeline race)
            interpret=interpret,
        )

    calls = build_calls(n_replicas, chunk_r, build_call)

    def replay(opc, args, pt, pd, pdpt, pml4):
        with x64_disabled():
            a0, a1, a2 = args[:, 0], args[:, 1], args[:, 2]
            pd3 = pd.reshape(1, 1, l2)
            pdpt3 = pdpt.reshape(1, 1, l3)
            pml43 = pml4.reshape(1, 1, l4)
            # the level tables are canonical: each chunk recomputes the
            # identical trajectory, so the LAST chunk's outputs speak
            # for the fleet (run_chunks' `rest` contract)
            (pt,), (pd_o, pdpt_o, pml4_o, resps) = run_chunks(
                n_replicas, chunk_r, calls,
                lambda call, r0, sub: call(
                    opc, a0, a1, a2, pt[r0:r0 + sub], pd3, pdpt3, pml43
                ),
                n_plane_outs=1,
            )
        return (pt, pd_o.reshape(l2), pdpt_o.reshape(l3),
                pml4_o.reshape(l4), resps.reshape(window))

    return replay


def make_vspace_plan_replay(
    n_pages: int,
    window: int,
    max_span: int,
    radix: bool,
    interpret: bool = False,
):
    """Canonical-replica PLAN kernel: the span kernel run with R=1,
    extended to emit the dense in-window delta planes `window_merge`
    consumes (see `make_pallas_vspace_plan_step`).

    flat:  `plan_replay(opc[W], args[W,3], frames[1,ROWS,128],
            tch[1,ROWS,128]) -> (frames, tch, resps[W])`
    radix: `plan_replay(opc, args, pt[1,ROWS,128], pd[l2], pdpt[l3],
            pml4[l4], wins[1,ROWS,128], clr[1,ROWS,128], pdt[l2])
            -> (pt, pd, pdpt, pml4, wins, clr, pdt, resps[W])`

    All planes are carried across chunk calls, so a step's chunks
    compose: a later chunk's region clear resets earlier chunks' wins.
    """
    from jax.experimental.pallas import tpu as pltpu

    if max_span > 512:
        raise ValueError("max_span > 512 breaks the 2-entry/level "
                         "invariant of the radix walk kernel")
    what = "radix vspace plan" if radix else "flat vspace plan"
    rows, _ = _grid_layout(n_pages, 1, interpret, what, aliased=True)
    span_rows = min(-(-max_span // 128) + 1, rows)
    if not radix and n_pages < span_rows * 128 + max_span:
        raise ValueError(
            f"flat vspace plan replay needs n_pages >= "
            f"{span_rows * 128 + max_span}; use the combined engine for "
            f"n_pages={n_pages}"
        )
    grid = (1,)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    plane = pl.BlockSpec((1, rows, 128), lambda i: (0, 0, 0))
    shared = lambda width: pl.BlockSpec(
        (1, 1, width), lambda i: (0, 0, 0), memory_space=pltpu.SMEM)
    pshape = jax.ShapeDtypeStruct((1, rows, 128), jnp.int32)

    if not radix:
        kernel = functools.partial(
            _flat_plan_kernel, n_pages=n_pages, max_span=max_span,
            window=window, rows=rows, span_rows=span_rows,
        )
        call = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[smem(), smem(), smem(), smem(), plane, plane],
            out_specs=[plane, plane, shared(window)],
            out_shape=[
                pshape, pshape,
                jax.ShapeDtypeStruct((1, 1, window), jnp.int32),
            ],
            input_output_aliases={4: 0, 5: 1},
            interpret=interpret,
        )

        def plan_replay(opc, args, frames, tch):
            with x64_disabled():
                frames, tch, resps = call(
                    opc, args[:, 0], args[:, 1], args[:, 2], frames, tch
                )
            return frames, tch, resps.reshape(window)

        return plan_replay

    l2, l3, l4 = _levels(n_pages)
    height = max(span_rows, 4)
    kernel = functools.partial(
        _radix_plan_kernel, n_pages=n_pages, max_span=max_span,
        window=window, rows=rows, height=height, l2=l2, l3=l3, l4=l4,
    )
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[smem(), smem(), smem(), smem(), plane,
                  shared(l2), shared(l3), shared(l4),
                  plane, plane, shared(l2)],
        out_specs=[plane, shared(l2), shared(l3), shared(l4),
                   shared(window), plane, plane, shared(l2)],
        out_shape=[
            pshape,
            jax.ShapeDtypeStruct((1, 1, l2), jnp.int32),
            jax.ShapeDtypeStruct((1, 1, l3), jnp.int32),
            jax.ShapeDtypeStruct((1, 1, l4), jnp.int32),
            jax.ShapeDtypeStruct((1, 1, window), jnp.int32),
            pshape, pshape,
            jax.ShapeDtypeStruct((1, 1, l2), jnp.int32),
        ],
        input_output_aliases={4: 0, 8: 5, 9: 6, 10: 7},
        interpret=interpret,
    )

    def plan_replay(opc, args, pt, pd, pdpt, pml4, wins, clr, pdt):
        with x64_disabled():
            pt, pd, pdpt, pml4, resps, wins, clr, pdt = call(
                opc, args[:, 0], args[:, 1], args[:, 2], pt,
                pd.reshape(1, 1, l2), pdpt.reshape(1, 1, l3),
                pml4.reshape(1, 1, l4), wins, clr,
                pdt.reshape(1, 1, l2),
            )
        return (pt, pd.reshape(l2), pdpt.reshape(l3), pml4.reshape(l4),
                wins, clr, pdt.reshape(l2), resps.reshape(window))

    return plan_replay


def make_pallas_vspace_plan_step(
    n_pages: int,
    spec: LogSpec,
    writes_per_replica: int,
    reads_per_replica: int,
    max_span: int,
    radix: bool,
    dispatch,
    interpret: bool = False,
    jit: bool = True,
    donate: bool = True,
):
    """Pallas-PLANNED combined step: the fleet-scale vspace engine (r5).

    The window's sequential semantics run ONCE, on a single canonical
    replica, inside the span kernel (bit-exact, fixed-size chunks so
    compile cost is window-independent); the kernel additionally emits
    the dense in-window delta planes, from which the model's own
    `window_merge` does the honest per-replica dense replay work —
    vmapped over the fleet in MODEL layout, pure HBM-bound blends.

    Why this is the scaling engine: step time ≈ span x ~1.2 µs (the
    kernel's Mosaic scalar stream, R-independent) + R x O(P/HBM-BW)
    merge, so fleet throughput grows ~linearly with R, where the classic
    grouped kernel is capped at G/450 ns by VMEM (G replicas per grid
    step) and the XLA plan pays ~19 µs/entry in sort/scatter passes
    whose COMPILE time also grows with the window
    (BENCH_NOTES r5). Same lock-step precondition as `core/step`'s
    plan/merge path; differential suite:
    tests/test_pallas_vspace.py::TestPlanStep.
    """
    from node_replication_tpu.ops.encoding import dispatch_reads

    R = spec.n_replicas
    Bw = int(writes_per_replica)
    span = R * Bw
    chunk = span
    while chunk > 4096 and chunk % 2 == 0:
        chunk //= 2
    replay = make_vspace_plan_replay(
        n_pages, chunk, max_span, radix, interpret=interpret
    )
    rows, _ = _grid_layout(n_pages, 1, interpret,
                           "vspace plan (layout)", aliased=True)
    P = n_pages

    def to_plane(flat, dtype=jnp.int32):
        padded = jnp.zeros((rows * 128,), dtype).at[:P].set(
            flat.astype(dtype)
        )
        return padded.reshape(1, rows, 128)

    def from_plane(plane):
        return plane.reshape(-1)[:P]

    def step(log, states, wr_opcodes, wr_args, rd_opcodes, rd_args):
        opc = wr_opcodes.reshape(span)
        args = wr_args.reshape(span, spec.arg_width)
        log = log_append(spec, log, opc, args, span)
        # distinct allocations: wins/clr are separately aliased kernel
        # in/outs and must not share one buffer
        zero_plane = lambda: jnp.zeros((1, rows, 128), jnp.int32)
        resp_chunks = []
        if radix:
            l2 = states["pd"].shape[-1]
            pt = to_plane(states["pt"][0])
            pd = states["pd"][0].astype(jnp.int32)
            pdpt0 = states["pdpt"][0]
            pml40 = states["pml4"][0]
            pdpt = pdpt0.astype(jnp.int32)
            pml4 = pml40.astype(jnp.int32)
            wins, clr = zero_plane(), zero_plane()
            pdt = jnp.zeros((l2,), jnp.int32)
            for c0 in range(0, span, chunk):
                pt, pd, pdpt, pml4, wins, clr, pdt, r = replay(
                    opc[c0:c0 + chunk], args[c0:c0 + chunk], pt, pd,
                    pdpt, pml4, wins, clr, pdt,
                )
                resp_chunks.append(r)
            plan = {
                "pt_wins": from_plane(wins) > 0,
                "pt_value": from_plane(pt),
                "pt_cleared": from_plane(clr) > 0,
                "pd_touched": pdt > 0,
                "pd_value": pd > 0,
                # monotone levels: in-window first-sets = final & ~init
                "pdpt_set": (pdpt > 0) & ~pdpt0,
                "pml4_set": (pml4 > 0) & ~pml40,
                "resps": (
                    jnp.concatenate(resp_chunks)
                    if len(resp_chunks) > 1 else resp_chunks[0]
                ),
            }
        else:
            frames = to_plane(states["frames"][0])
            tch = zero_plane()
            for c0 in range(0, span, chunk):
                frames, tch, r = replay(
                    opc[c0:c0 + chunk], args[c0:c0 + chunk], frames, tch
                )
                resp_chunks.append(r)
            plan = {
                "touched": from_plane(tch) > 0,
                "value": from_plane(frames),
                "resps": (
                    jnp.concatenate(resp_chunks)
                    if len(resp_chunks) > 1 else resp_chunks[0]
                ),
            }
        # honest per-replica dense replay: the model's own merge blends
        # the plan against every replica's own tables
        states, resps = jax.vmap(
            lambda s: dispatch.window_merge(s, plan)
        )(states)
        log = log._replace(
            ltails=jnp.broadcast_to(log.tail, (R,)), ctail=log.tail,
            head=log.tail,
        )
        own = jnp.arange(R, dtype=jnp.int32)[:, None] * Bw + jnp.arange(
            Bw, dtype=jnp.int32
        )[None, :]
        wr_resps = jnp.take_along_axis(resps, own, axis=1)
        rd_resps = dispatch_reads(dispatch, states, rd_opcodes, rd_args)
        return log, states, wr_resps, rd_resps

    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step


# ------------------------------------------------- state converters
def pallas_vspace_state(n_pages: int, n_replicas: int, radix: bool,
                        model_state=None):
    """Pallas-layout state, optionally seeded from one model-state pytree
    (`make_vspace`/`make_vspace_radix` `init_state()` shapes). Page
    tables are per replica; level tables are the single canonical copy
    of the lock-step invariant."""
    rows = max(4, _round_up(n_pages, 512) // 128)

    def grid3(flat):
        padded = jnp.zeros((rows * 128,), jnp.int32).at[:n_pages].set(flat)
        return jnp.broadcast_to(
            padded.reshape(rows, 128), (n_replicas, rows, 128)
        )

    if not radix:
        frames = (
            model_state["frames"] if model_state is not None
            else jnp.zeros((n_pages,), jnp.int32)
        )
        return {"frames": grid3(frames)}
    l2, l3, l4 = _levels(n_pages)

    def lvl(width, key):
        if model_state is None:
            return jnp.zeros((width,), jnp.int32)
        return model_state[key].astype(jnp.int32)

    pt = (
        model_state["pt"] if model_state is not None
        else jnp.zeros((n_pages,), jnp.int32)
    )
    return {
        "pt": grid3(pt), "pd": lvl(l2, "pd"), "pdpt": lvl(l3, "pdpt"),
        "pml4": lvl(l4, "pml4"),
    }


def model_view(state, n_pages: int, radix: bool):
    """Model-layout view of pallas state (per replica), for reads and
    differential tests: `{"pt": int32[R, P], "pd": bool[R, l2], ...}`."""
    if not radix:
        R = state["frames"].shape[0]
        return {"frames": state["frames"].reshape(R, -1)[:, :n_pages]}
    R = state["pt"].shape[0]
    bc = lambda v: jnp.broadcast_to(v > 0, (R,) + v.shape)
    return {
        "pt": state["pt"].reshape(R, -1)[:, :n_pages],
        "pd": bc(state["pd"]),
        "pdpt": bc(state["pdpt"]),
        "pml4": bc(state["pml4"]),
    }


def _vspace_reads(n_pages: int, max_span: int, radix: bool):
    """Per-replica read dispatch DIRECTLY on the pallas layout.

    Bit-identical to `dispatch_reads` over `model_view` (the step test
    pins this against the scan step) but without materializing the view:
    the `[R, ROWS, 128]` page grid answers reads through small gathers
    (`p -> [r, p>>7, p&127]`) instead of a whole-state relayout copy per
    step. Opcodes follow `models/vspace.py`: identify=1, resolved=2,
    (radix) tables=3; NOOP/unknown answer 0.
    """
    P = n_pages
    S = max_span

    def gather_pt(grid3, pages):
        # pages int32[R, B, L] (sentinel P -> 0-fill)
        safe = jnp.clip(pages, 0, P - 1)
        r_ix = jnp.arange(grid3.shape[0], dtype=jnp.int32).reshape(
            -1, *([1] * (pages.ndim - 1))
        )
        vals = grid3[r_ix, safe >> 7, safe & 127]
        return jnp.where(pages < P, vals, 0)

    def span_pages(vpage, npages):
        lanes = jnp.arange(S, dtype=jnp.int32)
        n = jnp.clip(npages, 0, S)[..., None]
        raw = vpage[..., None] + lanes
        return jnp.where((lanes < n) & (raw < P), raw % P, P)

    def reads(states, rd_opcodes, rd_args):
        a0, a1 = rd_args[..., 0], rd_args[..., 1]
        if not radix:
            grid3 = states["frames"]
            v = a0 % P
            f = gather_pt(grid3, v[..., None])[..., 0]
            ident = jnp.where(f == 0, jnp.int32(-1), f)
            pages = span_pages(a0, a1)
            resolved = jnp.sum(
                (pages < P) & (gather_pt(grid3, pages) != 0), axis=-1
            ).astype(jnp.int32)
            out = jnp.where(rd_opcodes == 1, ident, 0)
            return jnp.where(rd_opcodes == 2, resolved, out)
        grid3 = states["pt"]
        pd, pdpt, pml4 = states["pd"], states["pdpt"], states["pml4"]

        def walk(pages):
            safe = jnp.clip(pages, 0, P - 1)
            return (
                (pages < P)
                & (pml4[jnp.clip(safe >> 27, 0, pml4.shape[0] - 1)] > 0)
                & (pdpt[jnp.clip(safe >> 18, 0, pdpt.shape[0] - 1)] > 0)
                & (pd[safe >> 9] > 0)
                & (gather_pt(grid3, pages) != 0)
            )

        v = a0 % P
        pt_v = gather_pt(grid3, v[..., None])[..., 0]
        ident = jnp.where(walk(v[..., None])[..., 0], pt_v, jnp.int32(-1))
        pages = span_pages(a0 % P, a1)
        resolved = jnp.sum(walk(pages), axis=-1).astype(jnp.int32)
        tables = jnp.sum(pd > 0).astype(jnp.int32)
        out = jnp.where(rd_opcodes == 1, ident, 0)
        out = jnp.where(rd_opcodes == 2, resolved, out)
        return jnp.where(rd_opcodes == 3, tables, out)

    return reads


# ------------------------------------------------- fused combiner round
def _fused_flat_kernel(meta_ref, opc_ref, a0_ref, a1_ref, a2_ref,
                       app_opc_lo, app_args_lo, app_opc_hi, app_args_hi,
                       ring_opc_in, ring_args_in, fr_in,
                       ring_opc_out, ring_args_out, fr_out, resp_ref,
                       sem, *, n_pages: int, max_span: int, window: int,
                       rows: int, span_rows: int, win_rows: int):
    """Fused flat-vspace combiner round: the span-machinery replay body
    (`_flat_body` — unchanged, so the replay semantics cannot drift
    from the replay-only kernel) prefixed with the ring-window append
    DMA (`ops/pallas_ring.py`). One launch appends the batch to the
    ring AND replays it into every replica group."""
    from node_replication_tpu.ops.pallas_ring import ring_append_dma

    del ring_opc_in, ring_args_in  # content flows via the aliasing
    with x64_disabled():
        @pl.when(pl.program_id(0) == 0)
        def _append():
            ring_append_dma(
                sem, meta_ref[0], win_rows,
                (app_opc_lo, app_args_lo), (app_opc_hi, app_args_hi),
                (ring_opc_out, ring_args_out),
            )

        _flat_body(opc_ref, a0_ref, a1_ref, a2_ref, fr_in, fr_out,
                   resp_ref, n_pages, max_span, window, rows,
                   span_rows, copy_in=True)



class FusedVspaceEngine(FusedEngineHost):
    """Fused append+replay engine for the FLAT vspace model — the
    span-machinery twin of `ops/pallas_replay.FusedHashmapEngine` (same
    engine contract, same `core/replica.py` tier routing). Page-table
    state crosses the boundary in MODEL layout (`frames: int32[R, P]`);
    the `[R, ROWS, 128]` grid padding lives inside the round. Responses
    are the kernel's canonical copy broadcast per replica — sound under
    the same lock-step precondition the tier's eligibility check
    enforces. No fenced variant: the span kernel's group layout lets
    replica 0 speak for its group, which a frozen corrupt lane would
    poison — fenced fleets fall back to the chain
    (`supports_fenced=False`), meshed or not: the MESH-FUSED
    composition (`parallel/collectives.py:MeshFusedEngine`) builds
    this engine per replica shard through the same factory, and its
    canonical responses broadcast per shard exactly as they do
    fleet-wide. The radix model keeps the replay-only kernels (its
    level tables ride registers; a fused variant is a follow-up)."""

    supports_fenced = False

    def __init__(self, n_pages: int, max_span: int, spec,
                 interpret: bool | None = None):
        import jax as _jax

        from node_replication_tpu.ops.pallas_ring import fused_window_ok

        if interpret is None:
            interpret = _jax.default_backend() != "tpu"
        rows, group = _grid_layout(n_pages, spec.n_replicas, interpret,
                                   "fused flat vspace")
        span_rows = min(-(-max_span // 128) + 1, rows)
        if n_pages < span_rows * 128 + max_span:
            raise ValueError(
                f"fused flat vspace needs n_pages >= "
                f"{span_rows * 128 + max_span} (mod-wrapped span row "
                f"non-overlap); got {n_pages}"
            )
        if not fused_window_ok(spec.capacity, 1):
            raise ValueError(
                f"fused vspace engine: ring capacity {spec.capacity} "
                f"has no 128-slot row layout"
            )
        self.n_pages = int(n_pages)
        self.max_span = int(max_span)
        self.spec = spec
        self.interpret = bool(interpret)
        self._rows = rows
        self._group = group
        self._calls: dict = {}
        self._init_host()

    def supports(self, window: int) -> bool:
        from node_replication_tpu.ops.pallas_ring import fused_window_ok

        # 4096-entry SMEM window bound: the replay-only step chunks
        # past it; the fused round keeps one launch and falls back
        return (
            window <= 4096
            and fused_window_ok(self.spec.capacity, window)
            and window <= self.spec.capacity - self.spec.gc_slack
        )

    def launches(self, window: int) -> int:
        # derived from the BUILT chunk structure (the same chunk_r the
        # round loop iterates), like the hashmap engine — not a
        # recomputation that could drift from what actually dispatches
        _, chunk_r = self._built(window)
        return -(-self.spec.n_replicas // chunk_r)

    def _built(self, window: int):
        calls = self._calls.get(window)
        if calls is None:
            calls = self._build_calls(window)
            self._calls[window] = calls
        return calls

    def _build_calls(self, window: int):
        from jax.experimental.pallas import tpu as pltpu

        from node_replication_tpu.ops.pallas_chunk import (
            build_calls,
            chunk_size,
        )
        from node_replication_tpu.ops.pallas_ring import (
            ring_rows,
            window_rows,
        )

        spec = self.spec
        rows, group = self._rows, self._group
        span_rows = min(-(-self.max_span // 128) + 1, rows)
        win = window_rows(window)
        nrr = ring_rows(spec.capacity)
        A = spec.arg_width
        kernel = functools.partial(
            _fused_flat_kernel, n_pages=self.n_pages,
            max_span=self.max_span, window=window, rows=rows,
            span_rows=span_rows, win_rows=win,
        )
        smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
        anyspec = lambda: pl.BlockSpec(memory_space=pltpu.ANY)
        vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
        shared = pl.BlockSpec((1, 1, window), lambda i: (0, 0, 0),
                              memory_space=pltpu.SMEM)

        def build_call(sub_r: int):
            state_spec = pl.BlockSpec((group, rows, 128),
                                      lambda i: (i, 0, 0))
            return pl.pallas_call(
                kernel,
                grid=(sub_r // group,),
                in_specs=[
                    smem(),                       # meta
                    smem(), smem(), smem(), smem(),  # opc/a0/a1/a2
                    vmem(), vmem(), vmem(), vmem(),  # append planes
                    anyspec(), anyspec(),            # ring planes
                    state_spec,
                ],
                out_specs=[anyspec(), anyspec(), state_spec, shared],
                out_shape=[
                    jax.ShapeDtypeStruct((nrr, 128), jnp.int32),
                    jax.ShapeDtypeStruct((nrr, 128, A), jnp.int32),
                    jax.ShapeDtypeStruct((sub_r, rows, 128), jnp.int32),
                    jax.ShapeDtypeStruct((1, 1, window), jnp.int32),
                ],
                # UN-BLOCKED ring planes aliased in->out (outside the
                # grid pipeline — the r5-safe aliasing regime)
                input_output_aliases={9: 0, 10: 1},
                scratch_shapes=[pltpu.SemaphoreType.DMA(())],
                interpret=self.interpret,
            )

        chunk_r = chunk_size(spec.n_replicas, group)
        return build_calls(spec.n_replicas, chunk_r, build_call), chunk_r

    def round_fn(self, window: int, fenced: bool = False):
        from node_replication_tpu.ops.pallas_ring import (
            append_window_planes,
            fused_cursor_lattice,
            ring_rows,
        )

        if fenced:
            raise ValueError(
                "fused vspace round has no fenced variant "
                "(supports_fenced=False)"
            )
        calls, chunk_r = self._built(window)
        spec = self.spec
        R, A, P = spec.n_replicas, spec.arg_width, self.n_pages
        rows = self._rows
        nrr = ring_rows(spec.capacity)

        def fn(log, states, opcodes, args, count, fenced_vec=None):
            ring_opc = log.opcodes.reshape(nrr, 128)
            ring_args = log.args.reshape(nrr, 128, A)
            s_lo, planes = append_window_planes(
                spec.mask, ring_opc, ring_args, opcodes, args,
                log.tail, count,
            )
            meta = jnp.stack([s_lo, jnp.asarray(count, jnp.int32)])
            fr = jnp.zeros((R, rows * 128), jnp.int32).at[:, :P].set(
                states["frames"]
            ).reshape(R, rows, 128)
            a0, a1, a2 = args[:, 0], args[:, 1], args[:, 2]
            fr_chunks = []
            resp = None
            with x64_disabled():
                for r0 in range(0, R, chunk_r):
                    sub = min(chunk_r, R - r0)
                    ring_opc, ring_args, f, resp = calls[sub](
                        meta, opcodes, a0, a1, a2, *planes,
                        ring_opc, ring_args, fr[r0:r0 + sub],
                    )
                    fr_chunks.append(f)
            fr = (
                fr_chunks[0] if len(fr_chunks) == 1
                else jnp.concatenate(fr_chunks, axis=0)
            )
            log = log._replace(
                opcodes=ring_opc.reshape(spec.capacity),
                args=ring_args.reshape(spec.capacity, A),
            )
            log = fused_cursor_lattice(log, count, None)
            states = {"frames": fr.reshape(R, -1)[:, :P]}
            # canonical responses, shared by the lock-step fleet
            resps = jnp.broadcast_to(
                resp.reshape(window)[None], (R, window)
            )
            return log, states, resps

        return fn

    # round() — the host entry with metrics + the kernel-launch event —
    # is inherited from FusedEngineHost (ops/pallas_ring.py); the
    # fenced-mask rejection falls out of supports_fenced=False there


def make_pallas_vspace_step(
    n_pages: int,
    spec: LogSpec,
    writes_per_replica: int,
    reads_per_replica: int,
    max_span: int,
    radix: bool,
    interpret: bool = False,
    jit: bool = True,
    donate: bool = True,
):
    """Pallas twin of `core/step.make_step` for the vspace models: append
    the fleet's batch to the ring, replay it in order into every replica
    via the kernel (chunked to bound SMEM), answer reads natively on the
    pallas layout (`_vspace_reads` — bit-identical to the model's read
    ops, pinned by the step test).

    Requires — and preserves — the lock-step identical-replicas
    invariant (every replica starts from the same init and replays the
    full window each step), which is already the precondition of the
    fused `core/step` contract.
    """
    R = spec.n_replicas
    Bw = int(writes_per_replica)
    span = R * Bw
    # chunk the window only past 4096 entries: the window rides SMEM
    # (5 int32 arrays -> 80 KB at 4096, within v5e scalar memory), and
    # each extra chunk re-pays the call's fixed dispatch+DMA cost
    chunk = span
    while chunk > 4096 and chunk % 2 == 0:
        chunk //= 2
    replay = make_vspace_replay(
        n_pages, R, chunk, max_span, radix, interpret=interpret
    )
    reads = _vspace_reads(n_pages, max_span, radix)

    def step(log, states, wr_opcodes, wr_args, rd_opcodes, rd_args):
        opc = wr_opcodes.reshape(span)
        args = wr_args.reshape(span, spec.arg_width)
        log = log_append(spec, log, opc, args, span)
        resp_chunks = []
        if radix:
            pt, pd, pdpt, pml4 = (states["pt"], states["pd"],
                                  states["pdpt"], states["pml4"])
            for c0 in range(0, span, chunk):
                pt, pd, pdpt, pml4, r = replay(
                    opc[c0:c0 + chunk], args[c0:c0 + chunk], pt, pd,
                    pdpt, pml4,
                )
                resp_chunks.append(r)
            states = {"pt": pt, "pd": pd, "pdpt": pdpt, "pml4": pml4}
        else:
            frames = states["frames"]
            for c0 in range(0, span, chunk):
                frames, r = replay(
                    opc[c0:c0 + chunk], args[c0:c0 + chunk], frames
                )
                resp_chunks.append(r)
            states = {"frames": frames}
        resps = (
            jnp.concatenate(resp_chunks, axis=0)
            if len(resp_chunks) > 1 else resp_chunks[0]
        )  # [span] — shared across replicas (lock-step invariant)
        log = log._replace(
            ltails=log.ltails + span, ctail=log.ctail + span,
            head=log.head + span,
        )
        own = jnp.arange(R, dtype=jnp.int32)[:, None] * Bw + jnp.arange(
            Bw, dtype=jnp.int32
        )[None, :]
        wr_resps = resps[own]
        rd_resps = reads(states, rd_opcodes, rd_args)
        return log, states, wr_resps, rd_resps

    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step
