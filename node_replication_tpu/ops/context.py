"""Per-thread operation batching: the `Context` equivalent.

The reference gives every thread a fixed 32-slot SPSC ring holding
`(Option<op>, Option<resp>)` pairs with three cursors (`tail` for the owner's
enqueues, `comb` for the combiner, `head` for response dequeues), relying on
x86-TSO for its unsynchronized `Cell`s (`nr/src/context.rs:12`, `32-55`).

On the TPU build the combiner is host-side and lock-step (SURVEY.md §7:
combiner *election* is meaningless without racing threads), so the Context
keeps only the batching semantics: a bounded ring of pending ops per logical
thread, drained whole by the combiner, with responses delivered back in
enqueue order. `MAX_PENDING_OPS` (32) is preserved as the flat-combining
batch size per thread (`nr/src/context.rs:12`). A native C++ Context with the
real three-cursor/atomic layout backs the CPU engine in
`node_replication_tpu/native/`.
"""

from __future__ import annotations

from collections import deque

# Flat-combining batch size per thread (`nr/src/context.rs:12`).
MAX_PENDING_OPS = 32


class ContextFullError(RuntimeError):
    """Raised instead of the reference's spin-retry when a batch is full
    (`nr/src/replica.rs:350-351` retries `make_pending` forever)."""


class Context:
    """Bounded pending-op ring for one logical thread.

    `enqueue` mirrors `nr/src/context.rs:88-106` (fails when
    `tail - head == MAX_PENDING_OPS`), `ops` mirrors the combiner drain
    (`nr/src/context.rs:135-175`), `enqueue_resps`/`res` mirror response
    delivery (`nr/src/context.rs:111-131`, `178-194`).
    """

    __slots__ = ("_pending", "_resps", "_inflight")

    def __init__(self) -> None:
        self._pending: deque = deque()
        self._resps: deque = deque()
        self._inflight = 0

    def enqueue(self, opcode: int, args: tuple) -> bool:
        """Stage one op; False if the batch is full (caller must combine)."""
        if len(self._pending) + self._inflight >= MAX_PENDING_OPS:
            return False
        self._pending.append((opcode, args))
        return True

    def ops(self) -> list[tuple[int, tuple]]:
        """Drain all staged ops to the combiner (marks them in flight)."""
        out = list(self._pending)
        self._pending.clear()
        self._inflight += len(out)
        return out

    def enqueue_resps(self, resps) -> None:
        """Deliver combiner responses, in the order `ops()` returned."""
        n = len(resps)
        if n > self._inflight:
            raise ValueError(
                f"{n} responses for {self._inflight} in-flight ops"
            )
        self._inflight -= n
        self._resps.extend(resps)

    def res(self):
        """Pop the next response, or None if not yet delivered."""
        if not self._resps:
            return None
        return self._resps.popleft()

    def res_newest(self):
        """Pop the MOST RECENTLY delivered response, leaving earlier ones
        queued in order for `res()`. `execute_mut`'s own-response
        accounting: its op is the thread's newest enqueue, so after the
        combine its response is the newest delivered — popping from the
        tail returns exactly it without eating the thread's
        `enqueue_mut` backlog (r3 VERDICT weak #4)."""
        if not self._resps:
            return None
        return self._resps.pop()

    def __len__(self) -> int:
        return len(self._pending)
