"""Shared building blocks for combined window replay (`window_apply`).

The order-dependent models (stack, queue) looked scan-bound — every op's
effect depends on the running depth — but decompose into two parallel
passes the LWW models don't need:

1. `clamped_walk`: the depth/length before every op. Push/pop (enq/deq)
   move a counter by ±1 CLAMPED to [0, capacity] — a fold of functions
   `x -> min(max(x + a, lo), hi)`, a family CLOSED under composition, so
   the whole window collapses to one `associative_scan` over (a, lo, hi)
   triples (the min-plus cousin of memfs's max-affine size scan).
2. `slot_resolve`: once depths are known, every effective push/enq is a
   last-writer-wins UPDATE of a known slot and every effective pop/deq
   is a QUERY of a known slot — one stable sort by slot + one segmented
   rightmost-non-identity scan answers all queries against strictly
   earlier updates (the same machinery as the vspace radix region
   stream), and the buffer never needs per-entry replay at all (pops
   don't clear `buf` in these models; slots are only overwritten).

All helpers are jit-safe and fixed-shape. The walk origin and the query
fallback depend on replica state, so models package these passes as
`Dispatch.window_plan` (run once per window on a representative replica
— a per-replica vmap of the sort would batch R sorts and dominates the
step at fleet scale) and keep the plain `window_apply` form for
arbitrary-state use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def clamped_walk(delta, lo: int, hi: int, x0):
    """Value of the clamped counter BEFORE and AFTER each op.

    `delta int[W]` (+1/-1/0), bounds [lo, hi] applied at every step:
    `x_{t+1} = min(max(x_t + delta_t, lo), hi)`. Returns
    `(before int[W], after int[W])` for origin `x0` (a scalar; may be a
    traced per-replica value — the scan itself is origin-independent).
    """
    d = delta.astype(jnp.int32)
    a = d
    l_el = jnp.full_like(d, lo)
    h_el = jnp.full_like(d, hi)

    def compose(f, g):
        # f then g over x -> min(max(x+a, l), h)
        af, lf, hf = f
        ag, lg, hg = g
        return (
            af + ag,
            jnp.minimum(jnp.maximum(lf + ag, lg), hg),
            jnp.minimum(jnp.maximum(hf + ag, lg), hg),
        )

    pa, pl, ph = jax.lax.associative_scan(compose, (a, l_el, h_el))
    x0 = jnp.asarray(x0, jnp.int32)
    after = jnp.minimum(jnp.maximum(x0 + pa, pl), ph)
    before = jnp.concatenate([x0[None], after[:-1]])
    return before, after


def slot_resolve(slot_upd, upd_val, slot_qry, init_vals, n_slots: int):
    """Answer every query with the latest earlier update to its slot.

    Per window position t, AT MOST one of update/query is active
    (`slot_upd[t]`/`slot_qry[t]` in [0, n_slots), or the `n_slots`
    sentinel when inactive). Returns `resp int[W]` where active queries
    get the value of the last active update to their slot at an earlier
    position, falling back to `init_vals[slot]`; inactive positions get
    `init_vals` garbage that callers must mask.
    """
    W = slot_upd.shape[0]
    is_upd = slot_upd < n_slots
    is_qry = slot_qry < n_slots
    key = jnp.where(is_upd, slot_upd, slot_qry).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    sk = key[order]
    segf = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sk[1:] != sk[:-1]]
    )

    def seg_last(a, b):
        va, ha, fa = a
        vb, hb, fb = b
        keep_b = fb | hb
        return (
            jnp.where(keep_b, vb, va),
            jnp.where(fb, hb, ha | hb),
            fa | fb,
        )

    pv, ph, _ = jax.lax.associative_scan(
        seg_last, (upd_val[order], is_upd[order], segf)
    )
    # a query position is the identity element, so its inclusive scan
    # value covers exactly the strictly-earlier updates of its segment
    init_q = init_vals.at[
        jnp.minimum(sk, n_slots - 1).astype(jnp.int32)
    ].get(mode="clip")
    resolved_s = jnp.where(ph & is_qry[order], pv, init_q)
    return jnp.zeros((W,), init_vals.dtype).at[order].set(resolved_s)


def last_update_table(slot_upd, upd_val, n_slots: int):
    """Per-slot last active update as a dense `(touched bool[n_slots],
    value int32[n_slots])` pair — the SHARED half of the final-state
    merge; callers blend `where(touched, value, buf)` per replica
    (`slot_upd` uses the `n_slots` sentinel for inactive). int32
    throughout: at int64 a big capacity doubles the scatter buffer.
    """
    W = slot_upd.shape[0]
    last = (
        jnp.full((n_slots + 1,), -1, jnp.int32)
        .at[slot_upd.astype(jnp.int32)]
        .max(jnp.arange(W, dtype=jnp.int32))[:n_slots]
    )
    li = jnp.clip(last, 0).astype(jnp.int32)
    return last >= 0, upd_val[li]
