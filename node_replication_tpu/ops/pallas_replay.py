"""Pallas TPU kernel for the hashmap replay hot loop.

The generic replay path (`core/log.log_exec_all`) is a vmapped `lax.scan`
whose every iteration scatters one element per replica into HBM-resident
state. This kernel is the hand-tiled alternative for the flagship hashmap
model (SURVEY.md §7: "Pallas kernels for the append/reserve and
scan-replay inner loops if XLA fusion falls short"):

- state is laid out TRANSPOSED, `[K, R]`: keys on the sublane axis,
  replicas on the 128-wide lane axis. Replay touches one dynamic KEY per
  entry but all replicas at once — on TPU the dynamically-indexed axis
  must be the sublane one (Mosaic has no dynamic lane indexing), and the
  replica axis is naturally lane-parallel;
- the replica axis is tiled into VMEM blocks (`[Kp, tile_r]`, ~16 MB/core
  budget); each entry is a dynamic single-ROW read-modify-write IN VMEM
  (`ref[pl.ds(k, 1), :]`), so the inner loop never round-trips HBM;
- per-tile state is written back exactly once.

All replicas replay the same window at the same offsets (the lock-step
precondition of the fused step), so one kernel grid covers the fleet.

Hardware-proven (round 3, TPU v5e, fenced D2H measurement): at
R=4096/K=1024 the Mosaic lowering compiles and runs, and `bench.py
--pallas` measures 1.22G dispatches/s vs 13.0M for the generic vmapped
scan at the identical config — a ~94x win over per-entry XLA replay, the
comparison this kernel exists for (`nr/src/log.rs:473-524` is the
reference's hot loop). The *combined* window replay
(`Dispatch.window_apply`, `models/hashmap.py`) measures 1.75G at the same
config by replacing sequential replay with a parallel reduction — an
algorithmic change, available only to models with last-writer-wins write
semantics; this kernel remains the fast path for per-entry sequential
replay (and the template for models that need it). Non-interpret smoke:
`NR_TPU_SMOKE=1 pytest tests/test_pallas.py::TestHardwareSmoke`.

Opcodes follow `models/hashmap.py`: PUT=1 (k, v → 0), REMOVE=2 (k → was
present). `present` is int32 here (lane-friendly); `make_pallas_step`
exposes the same step contract as `core/step.make_step` over the
transposed state (`pallas_hashmap_state`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from node_replication_tpu.core.log import LogSpec, log_append
from node_replication_tpu.utils.compat import x64_disabled


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _replay_kernel(opc_ref, key_ref, val_ref, val_in, pres_in, val_out,
                   pres_out, resp_ref, *, n_keys: int, window: int):
    # load the tile's state into the output VMEM blocks once
    val_out[:] = val_in[:]
    pres_out[:] = pres_in[:]

    def body(i, carry):
        # opcode/key/value live in SMEM: Mosaic requires dynamic-slice
        # indices to come from scalar memory, not VMEM loads
        opcode = opc_ref[i]
        # floored mod (matching the generic model's non-negative `%`):
        # lax.rem truncates toward zero, so adjust negatives or a negative
        # key would index a negative VMEM row
        k = jax.lax.rem(key_ref[i], jnp.int32(n_keys))
        k = jnp.where(k < 0, k + jnp.int32(n_keys), k)
        v = val_ref[i]
        is_put = opcode == 1
        is_rem = opcode == 2
        row_v = val_out[pl.ds(k, 1), :]
        row_p = pres_out[pl.ds(k, 1), :]
        val_out[pl.ds(k, 1), :] = jnp.where(
            is_put, v, jnp.where(is_rem, 0, row_v)
        )
        pres_out[pl.ds(k, 1), :] = jnp.where(
            is_put, 1, jnp.where(is_rem, 0, row_p)
        )
        resp_ref[pl.ds(i, 1), :] = jnp.where(is_rem, row_p, 0)
        return carry

    # int32 loop bounds: under jax_enable_x64 a Python-int fori_loop index
    # becomes int64, which Mosaic cannot lower
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(window), body, jnp.int32(0))


def make_hashmap_replay(
    n_keys: int,
    n_replicas: int,
    window: int,
    tile_r: int = 512,
    interpret: bool = False,
):
    """Build `replay(opcodes[W], keys[W], vals[W], values[Kp, R],
    present[Kp, R]) -> (values, present, resps[W, R])` with Kp = n_keys
    padded to the 8-sublane boundary. Window entries replay in order into
    every replica.
    """
    from jax.experimental.pallas import tpu as pltpu
    kp = _round_up(n_keys, 8)
    # lane (last) dim of a block must be a multiple of 128 or the full
    # array dim; sublane dims of the state blocks are full (Kp, W). The
    # four state blocks (values/present × in/out) plus the resp block must
    # fit the ~16 MB VMEM: shrink the replica tile until they do.
    budget = 14 << 20

    def block_bytes(t: int) -> int:
        # x2: Mosaic double-buffers every DMA'd block for grid pipelining
        return 2 * 4 * (4 * kp * t + window * t)

    candidates = [t for t in (1024, 512, 256, 128)
                  if n_replicas % t == 0] or [n_replicas]
    for t in candidates:
        if (n_replicas % tile_r == 0
                and (tile_r % 128 == 0 or tile_r == n_replicas)
                and block_bytes(tile_r) <= budget):
            break  # caller's tile is legal and fits
        tile_r = t
        if block_bytes(t) <= budget:
            break
    if block_bytes(tile_r) > budget and not interpret:
        raise ValueError(
            f"hashmap pallas replay needs {block_bytes(tile_r)} bytes of "
            f"VMEM at the smallest legal tile ({tile_r} replicas) for "
            f"n_keys={n_keys}, window={window}; use the generic scan path "
            f"(core/step.make_step) for this config"
        )
    grid = (n_replicas // tile_r,)
    kernel = functools.partial(
        _replay_kernel, n_keys=n_keys, window=window
    )
    state_spec = pl.BlockSpec((kp, tile_r), lambda i: (0, i))
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            state_spec,
            state_spec,
        ],
        out_specs=[
            state_spec,
            state_spec,
            pl.BlockSpec((window, tile_r), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, n_replicas), jnp.int32),
            jax.ShapeDtypeStruct((kp, n_replicas), jnp.int32),
            jax.ShapeDtypeStruct((window, n_replicas), jnp.int32),
        ],
        interpret=interpret,
    )

    def replay(opcodes, keys, vals, values, present):
        # trace the kernel with x64 off: the package enables jax_enable_x64
        # for int64 log cursors, but x64-canonicalized index-map constants
        # (i64) send the Mosaic lowering into an unsupported-convert loop.
        # Every kernel operand is int32, so the narrowing context is inert.
        with x64_disabled():
            return call(opcodes, keys, vals, values, present)

    return replay


def make_pallas_step(
    n_keys: int,
    spec: LogSpec,
    writes_per_replica: int,
    reads_per_replica: int,
    tile_r: int = 512,
    interpret: bool = False,
    jit: bool = True,
    donate: bool = True,
):
    """Pallas twin of `core/step.make_step` for the hashmap model.

    Same contract: append the fleet's write batch to the ring, replay it
    into every replica (via the kernel), answer reads locally. State is
    `{"values": int32[Kp, R], "present": int32[Kp, R]}` (transposed) —
    create it with `pallas_hashmap_state(n_keys, R)`.
    """
    R = spec.n_replicas
    Bw = int(writes_per_replica)
    span = R * Bw
    # replay in window chunks: a smaller kernel window frees VMEM for the
    # state blocks (the chunks apply strictly in order, so semantics hold)
    chunk = span
    while chunk > 1024 and chunk % 2 == 0:
        chunk //= 2
    replay = make_hashmap_replay(
        n_keys, R, chunk, tile_r=tile_r, interpret=interpret
    )

    def step(log, states, wr_opcodes, wr_args, rd_opcodes, rd_args):
        opc = wr_opcodes.reshape(span)
        args = wr_args.reshape(span, spec.arg_width)
        log = log_append(spec, log, opc, args, span)
        values, present = states["values"], states["present"]
        resp_chunks = []
        for c0 in range(0, span, chunk):
            values, present, r = replay(
                opc[c0 : c0 + chunk],
                args[c0 : c0 + chunk, 0],
                args[c0 : c0 + chunk, 1],
                values,
                present,
            )
            resp_chunks.append(r)
        resps = (
            jnp.concatenate(resp_chunks, axis=0)
            if len(resp_chunks) > 1
            else resp_chunks[0]
        )
        states = {"values": values, "present": present}
        # cursors advance in lock-step (every replica replayed the span)
        log = log._replace(
            ltails=log.ltails + span,
            ctail=log.ctail + span,
            head=log.head + span,
        )
        # resps is [W, R]; replica r's own writes are entries
        # [r*Bw, (r+1)*Bw)
        own = jnp.arange(R, dtype=jnp.int32)[:, None] * Bw + jnp.arange(
            Bw, dtype=jnp.int32
        )[None, :]  # [R, Bw]
        wr_resps = resps[own, jnp.arange(R, dtype=jnp.int32)[:, None]]
        # reads: gather values[k, r] per (replica, read slot)
        k = rd_args[..., 0] % n_keys  # [R, Br]
        r_idx = jnp.arange(R, dtype=jnp.int32)[:, None]
        vals = values[k, r_idx]
        pres = present[k, r_idx]
        rd_resps = jnp.where(
            (rd_opcodes == 1) & (pres > 0), vals, jnp.int32(-1)
        )
        rd_resps = jnp.where(rd_opcodes == 0, 0, rd_resps)
        return log, states, wr_resps, rd_resps

    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step


def pallas_hashmap_state(n_keys: int, n_replicas: int):
    kp = _round_up(n_keys, 8)
    return {
        "values": jnp.zeros((kp, n_replicas), jnp.int32),
        "present": jnp.zeros((kp, n_replicas), jnp.int32),
    }
