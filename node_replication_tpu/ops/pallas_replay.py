"""Pallas TPU kernels for the hashmap hot loop: replay, and the FUSED
append+replay combiner round.

Two contracts live here:

1. **Replay-only** (`make_hashmap_replay` / `make_pallas_step`): the
   original hand-tiled window replay — the caller appends to the ring
   separately and hands the kernel the window.
2. **Fused round** (`FusedHashmapEngine` / `make_fused_hashmap_calls`):
   a whole combiner round is ONE `pallas_call` — the log-window append
   (two pre-blended DMA spans over the un-blocked, aliased ring planes,
   `ops/pallas_ring.py`), the per-entry replay into the transposed
   `[K, R]` state tiles, the response gather, and the fenced-lane mask
   (quarantined replicas skip state writeback and report zeroed
   responses, `fault/health.py`) all happen inside the kernel. The
   engine is the `log.engine.pallas_fused` tier `NodeReplicated` /
   `MultiLogReplicated` route `_append_and_replay` rounds through when
   winner selection picks it (`core/replica.py`), collapsing the
   host-sequenced encode → `log_append` → sort/merge → replay chain —
   and its per-round host syncs — into one launch per serve batch.
   Interpret-mode bit-identity vs the scan engine (ring wrap, fenced,
   batch, CNR sub-batch paths): tests/test_pallas_fused.py.

The generic replay path (`core/log.log_exec_all`) is a vmapped `lax.scan`
whose every iteration scatters one element per replica into HBM-resident
state. These kernels are the hand-tiled alternative for the flagship
hashmap model (SURVEY.md §7: "Pallas kernels for the append/reserve and
scan-replay inner loops if XLA fusion falls short"):

- state is laid out TRANSPOSED, `[K, R]`: keys on the sublane axis,
  replicas on the 128-wide lane axis. Replay touches one dynamic KEY per
  entry but all replicas at once — on TPU the dynamically-indexed axis
  must be the sublane one (Mosaic has no dynamic lane indexing), and the
  replica axis is naturally lane-parallel;
- the replica axis is tiled into VMEM blocks (`[Kp, tile_r]`, ~16 MB/core
  budget); each entry is a dynamic single-ROW read-modify-write IN VMEM
  (`ref[pl.ds(k, 1), :]`), so the inner loop never round-trips HBM;
- per-tile state is written back exactly once.

All replicas replay the same window at the same offsets (the lock-step
precondition of the fused step), so one kernel grid covers the fleet.

Hardware-proven (round 3, TPU v5e, fenced D2H measurement): at
R=4096/K=1024 the Mosaic lowering compiles and runs, and `bench.py
--pallas` measures 1.22G dispatches/s vs 13.0M for the generic vmapped
scan at the identical config — a ~94x win over per-entry XLA replay, the
comparison this kernel exists for (`nr/src/log.rs:473-524` is the
reference's hot loop). The *combined* window replay
(`Dispatch.window_apply`, `models/hashmap.py`) measures 1.75G at the same
config by replacing sequential replay with a parallel reduction — an
algorithmic change, available only to models with last-writer-wins write
semantics; this kernel remains the fast path for per-entry sequential
replay (and the template for models that need it). Non-interpret smoke:
`NR_TPU_SMOKE=1 pytest tests/test_pallas.py::TestHardwareSmoke`.

Opcodes follow `models/hashmap.py`: PUT=1 (k, v → 0), REMOVE=2 (k → was
present). `present` is int32 here (lane-friendly); `make_pallas_step`
exposes the same step contract as `core/step.make_step` over the
transposed state (`pallas_hashmap_state`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from node_replication_tpu.core.log import LogSpec, log_append
from node_replication_tpu.ops.pallas_ring import FusedEngineHost
from node_replication_tpu.utils.compat import x64_disabled


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _replay_kernel(opc_ref, key_ref, val_ref, val_in, pres_in, val_out,
                   pres_out, resp_ref, *, n_keys: int, window: int):
    # load the tile's state into the output VMEM blocks once
    val_out[:] = val_in[:]
    pres_out[:] = pres_in[:]

    def body(i, carry):
        # opcode/key/value live in SMEM: Mosaic requires dynamic-slice
        # indices to come from scalar memory, not VMEM loads
        opcode = opc_ref[i]
        # floored mod (matching the generic model's non-negative `%`):
        # lax.rem truncates toward zero, so adjust negatives or a negative
        # key would index a negative VMEM row
        k = jax.lax.rem(key_ref[i], jnp.int32(n_keys))
        k = jnp.where(k < 0, k + jnp.int32(n_keys), k)
        v = val_ref[i]
        is_put = opcode == 1
        is_rem = opcode == 2
        row_v = val_out[pl.ds(k, 1), :]
        row_p = pres_out[pl.ds(k, 1), :]
        val_out[pl.ds(k, 1), :] = jnp.where(
            is_put, v, jnp.where(is_rem, 0, row_v)
        )
        pres_out[pl.ds(k, 1), :] = jnp.where(
            is_put, 1, jnp.where(is_rem, 0, row_p)
        )
        resp_ref[pl.ds(i, 1), :] = jnp.where(is_rem, row_p, 0)
        return carry

    # int32 loop bounds: under jax_enable_x64 a Python-int fori_loop index
    # becomes int64, which Mosaic cannot lower
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(window), body, jnp.int32(0))


def make_hashmap_replay(
    n_keys: int,
    n_replicas: int,
    window: int,
    tile_r: int = 512,
    interpret: bool = False,
):
    """Build `replay(opcodes[W], keys[W], vals[W], values[Kp, R],
    present[Kp, R]) -> (values, present, resps[W, R])` with Kp = n_keys
    padded to the 8-sublane boundary. Window entries replay in order into
    every replica.
    """
    from jax.experimental.pallas import tpu as pltpu
    kp = _round_up(n_keys, 8)
    # lane (last) dim of a block must be a multiple of 128 or the full
    # array dim; sublane dims of the state blocks are full (Kp, W). The
    # four state blocks (values/present × in/out) plus the resp block must
    # fit the ~16 MB VMEM: shrink the replica tile until they do.
    budget = 14 << 20

    def block_bytes(t: int) -> int:
        # x2: Mosaic double-buffers every DMA'd block for grid pipelining
        return 2 * 4 * (4 * kp * t + window * t)

    candidates = [t for t in (1024, 512, 256, 128)
                  if n_replicas % t == 0] or [n_replicas]
    for t in candidates:
        if (n_replicas % tile_r == 0
                and (tile_r % 128 == 0 or tile_r == n_replicas)
                and block_bytes(tile_r) <= budget):
            break  # caller's tile is legal and fits
        tile_r = t
        if block_bytes(t) <= budget:
            break
    if block_bytes(tile_r) > budget and not interpret:
        raise ValueError(
            f"hashmap pallas replay needs {block_bytes(tile_r)} bytes of "
            f"VMEM at the smallest legal tile ({tile_r} replicas) for "
            f"n_keys={n_keys}, window={window}; use the generic scan path "
            f"(core/step.make_step) for this config"
        )
    grid = (n_replicas // tile_r,)
    kernel = functools.partial(
        _replay_kernel, n_keys=n_keys, window=window
    )
    state_spec = pl.BlockSpec((kp, tile_r), lambda i: (0, i))
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            state_spec,
            state_spec,
        ],
        out_specs=[
            state_spec,
            state_spec,
            pl.BlockSpec((window, tile_r), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, n_replicas), jnp.int32),
            jax.ShapeDtypeStruct((kp, n_replicas), jnp.int32),
            jax.ShapeDtypeStruct((window, n_replicas), jnp.int32),
        ],
        interpret=interpret,
    )

    def replay(opcodes, keys, vals, values, present):
        # trace the kernel with x64 off: the package enables jax_enable_x64
        # for int64 log cursors, but x64-canonicalized index-map constants
        # (i64) send the Mosaic lowering into an unsupported-convert loop.
        # Every kernel operand is int32, so the narrowing context is inert.
        with x64_disabled():
            return call(opcodes, keys, vals, values, present)

    return replay


def make_pallas_step(
    n_keys: int,
    spec: LogSpec,
    writes_per_replica: int,
    reads_per_replica: int,
    tile_r: int = 512,
    interpret: bool = False,
    jit: bool = True,
    donate: bool = True,
):
    """Pallas twin of `core/step.make_step` for the hashmap model.

    Same contract: append the fleet's write batch to the ring, replay it
    into every replica (via the kernel), answer reads locally. State is
    `{"values": int32[Kp, R], "present": int32[Kp, R]}` (transposed) —
    create it with `pallas_hashmap_state(n_keys, R)`.
    """
    R = spec.n_replicas
    Bw = int(writes_per_replica)
    span = R * Bw
    # replay in window chunks: a smaller kernel window frees VMEM for the
    # state blocks (the chunks apply strictly in order, so semantics hold)
    chunk = span
    while chunk > 1024 and chunk % 2 == 0:
        chunk //= 2
    replay = make_hashmap_replay(
        n_keys, R, chunk, tile_r=tile_r, interpret=interpret
    )

    def step(log, states, wr_opcodes, wr_args, rd_opcodes, rd_args):
        opc = wr_opcodes.reshape(span)
        args = wr_args.reshape(span, spec.arg_width)
        log = log_append(spec, log, opc, args, span)
        values, present = states["values"], states["present"]
        resp_chunks = []
        for c0 in range(0, span, chunk):
            values, present, r = replay(
                opc[c0 : c0 + chunk],
                args[c0 : c0 + chunk, 0],
                args[c0 : c0 + chunk, 1],
                values,
                present,
            )
            resp_chunks.append(r)
        resps = (
            jnp.concatenate(resp_chunks, axis=0)
            if len(resp_chunks) > 1
            else resp_chunks[0]
        )
        states = {"values": values, "present": present}
        # cursors advance in lock-step (every replica replayed the span)
        log = log._replace(
            ltails=log.ltails + span,
            ctail=log.ctail + span,
            head=log.head + span,
        )
        # resps is [W, R]; replica r's own writes are entries
        # [r*Bw, (r+1)*Bw)
        own = jnp.arange(R, dtype=jnp.int32)[:, None] * Bw + jnp.arange(
            Bw, dtype=jnp.int32
        )[None, :]  # [R, Bw]
        wr_resps = resps[own, jnp.arange(R, dtype=jnp.int32)[:, None]]
        # reads: gather values[k, r] per (replica, read slot)
        k = rd_args[..., 0] % n_keys  # [R, Br]
        r_idx = jnp.arange(R, dtype=jnp.int32)[:, None]
        vals = values[k, r_idx]
        pres = present[k, r_idx]
        rd_resps = jnp.where(
            (rd_opcodes == 1) & (pres > 0), vals, jnp.int32(-1)
        )
        rd_resps = jnp.where(rd_opcodes == 0, 0, rd_resps)
        return log, states, wr_resps, rd_resps

    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step


def pallas_hashmap_state(n_keys: int, n_replicas: int):
    kp = _round_up(n_keys, 8)
    return {
        "values": jnp.zeros((kp, n_replicas), jnp.int32),
        "present": jnp.zeros((kp, n_replicas), jnp.int32),
    }


# ---------------------------------------------------------------------------
# Fused append+replay engine (one pallas_call per combiner round)
# ---------------------------------------------------------------------------


def _fused_hashmap_kernel(meta_ref, opc_ref, key_ref, val_ref,
                          app_opc_lo, app_args_lo, app_opc_hi,
                          app_args_hi, ring_opc_in, ring_args_in,
                          val_in, pres_in, *rest,
                          n_keys: int, window: int, win_rows: int,
                          fenced: bool):
    """One combiner round: ring-window append (DMA, grid step 0) +
    in-order replay of the SMEM batch into the `[Kp, tile_r]` state
    blocks + response gather. `meta = [s_lo, count]`; batch slots at or
    past `count` are NOOP by the `encode_ops` contract, so the replay
    loop needs no count gate. With `fenced`, an extra `[1, tile_r]`
    int32 plane marks quarantined lanes: they replay in VMEM like
    everyone (keeping the loop branch-free) but their writeback is
    restored from the input at the end — state and responses of a
    fenced replica must not move (the caller zeroes their resp rows)."""
    from node_replication_tpu.ops.pallas_ring import ring_append_dma

    if fenced:
        (fen_in, ring_opc_out, ring_args_out, val_out, pres_out,
         resp_ref, sem) = rest
    else:
        (ring_opc_out, ring_args_out, val_out, pres_out, resp_ref,
         sem) = rest
        fen_in = None
    # the ring content only flows through the aliasing: the replay
    # reads the batch from SMEM (append happens-before replay by the
    # lock-step data dependence, core/log.py)
    del ring_opc_in, ring_args_in
    with x64_disabled():
        @pl.when(pl.program_id(0) == 0)
        def _append():
            ring_append_dma(
                sem, meta_ref[0], win_rows,
                (app_opc_lo, app_args_lo), (app_opc_hi, app_args_hi),
                (ring_opc_out, ring_args_out),
            )

        val_out[:] = val_in[:]
        pres_out[:] = pres_in[:]

        def body(i, carry):
            opcode = opc_ref[i]
            k = jax.lax.rem(key_ref[i], jnp.int32(n_keys))
            k = jnp.where(k < 0, k + jnp.int32(n_keys), k)
            v = val_ref[i]
            is_put = opcode == 1
            is_rem = opcode == 2
            row_v = val_out[pl.ds(k, 1), :]
            row_p = pres_out[pl.ds(k, 1), :]
            val_out[pl.ds(k, 1), :] = jnp.where(
                is_put, v, jnp.where(is_rem, 0, row_v)
            )
            pres_out[pl.ds(k, 1), :] = jnp.where(
                is_put, 1, jnp.where(is_rem, 0, row_p)
            )
            resp_ref[pl.ds(i, 1), :] = jnp.where(is_rem, row_p, 0)
            return carry

        jax.lax.fori_loop(jnp.int32(0), jnp.int32(window), body,
                          jnp.int32(0))
        if fenced:
            fen = fen_in[0:1, :]
            val_out[:] = jnp.where(fen > 0, val_in[:], val_out[:])
            pres_out[:] = jnp.where(fen > 0, pres_in[:], pres_out[:])


def make_fused_hashmap_calls(
    n_keys: int,
    spec: LogSpec,
    window: int,
    tile_r: int = 512,
    interpret: bool = False,
    fenced: bool = False,
):
    """Build the per-chunk fused `pallas_call`s for one window size.

    Returns `(calls, chunk_r, tile_r)` where `calls[sub]` runs `sub`
    replica lanes (`sub // tile_r` grid steps, capped at MAX_GRID per
    call by `pallas_chunk` chunking — the r5 belt-and-braces rule).
    The ring planes thread through the chunk calls via aliasing, so a
    multi-chunk round re-issues the (idempotent) append DMA per chunk.
    """
    from jax.experimental.pallas import tpu as pltpu

    from node_replication_tpu.ops.pallas_chunk import (
        build_calls,
        chunk_size,
    )
    from node_replication_tpu.ops.pallas_ring import (
        fused_window_ok,
        ring_rows,
        window_rows,
    )

    if not fused_window_ok(spec.capacity, window):
        raise ValueError(
            f"fused hashmap round: window {window} does not fit the "
            f"ring-row append spans of capacity {spec.capacity}"
        )
    R = spec.n_replicas
    A = spec.arg_width
    kp = _round_up(n_keys, 8)
    win = window_rows(window)
    rows = ring_rows(spec.capacity)
    budget = 14 << 20
    app_bytes = 2 * 4 * (2 * win * 128 * (1 + A))

    def block_bytes(t: int) -> int:
        # states (values/present x in/out) + the resp block, all
        # double-buffered by the grid pipeline, plus the append planes
        return 2 * 4 * (4 * kp * t + window * t) + app_bytes

    candidates = [t for t in (1024, 512, 256, 128)
                  if R % t == 0] or [R]
    for t in candidates:
        if (R % tile_r == 0
                and (tile_r % 128 == 0 or tile_r == R)
                and block_bytes(tile_r) <= budget):
            break
        tile_r = t
        if block_bytes(t) <= budget:
            break
    if block_bytes(tile_r) > budget and not interpret:
        raise ValueError(
            f"fused hashmap round needs {block_bytes(tile_r)} bytes of "
            f"VMEM at the smallest legal tile ({tile_r} lanes) for "
            f"n_keys={n_keys}, window={window}; fall back to the "
            f"append+exec chain for this config"
        )
    kernel = functools.partial(
        _fused_hashmap_kernel, n_keys=n_keys, window=window,
        win_rows=win, fenced=fenced,
    )
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    anyspec = lambda: pl.BlockSpec(memory_space=pltpu.ANY)

    def build_call(sub_r: int):
        state_spec = pl.BlockSpec((kp, tile_r), lambda i: (0, i))
        in_specs = [
            smem(),                                   # meta
            smem(), smem(), smem(),                   # opc/key/val
            pl.BlockSpec(memory_space=pltpu.VMEM),    # app_opc_lo
            pl.BlockSpec(memory_space=pltpu.VMEM),    # app_args_lo
            pl.BlockSpec(memory_space=pltpu.VMEM),    # app_opc_hi
            pl.BlockSpec(memory_space=pltpu.VMEM),    # app_args_hi
            anyspec(), anyspec(),                     # ring planes
            state_spec, state_spec,                   # values/present
        ]
        if fenced:
            in_specs.append(
                pl.BlockSpec((1, tile_r), lambda i: (0, i))
            )
        return pl.pallas_call(
            kernel,
            grid=(sub_r // tile_r,),
            in_specs=in_specs,
            out_specs=[
                anyspec(), anyspec(),                 # ring planes out
                state_spec, state_spec,
                pl.BlockSpec((window, tile_r), lambda i: (0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((rows, 128), jnp.int32),
                jax.ShapeDtypeStruct((rows, 128, A), jnp.int32),
                jax.ShapeDtypeStruct((kp, sub_r), jnp.int32),
                jax.ShapeDtypeStruct((kp, sub_r), jnp.int32),
                jax.ShapeDtypeStruct((window, sub_r), jnp.int32),
            ],
            # UN-BLOCKED ring planes aliased in->out: outside the grid
            # pipeline, so exempt from the r5 blocked-plane rule (see
            # ops/pallas_ring.py and nrlint aliased-pallas-planes)
            input_output_aliases={8: 0, 9: 1},
            scratch_shapes=[pltpu.SemaphoreType.DMA(())],
            interpret=interpret,
        )

    chunk_r = chunk_size(R, tile_r)
    return build_calls(R, chunk_r, build_call), chunk_r, tile_r



class FusedHashmapEngine(FusedEngineHost):
    """The fused combiner-round engine for the hashmap model.

    `round(log, states, opcodes, args, count, fenced=None)` executes
    one whole combiner round — append `count` entries at the tail,
    replay them into every (unfenced) replica, gather responses — as a
    single jitted program whose device work is ONE kernel launch per
    replica chunk (usually exactly one). Requires the lock-step
    precondition the caller checks host-side: every live cursor at the
    pre-append tail (`core/replica._try_fused_round`).

    States cross the boundary in MODEL layout (`[R, K]` values +
    bool present, `models/hashmap.py`); the transposes to the kernel's
    `[Kp, R]` planes live inside the jit. `raw_round` exposes the
    transposed-resident form for the kernel bench
    (`harness/mkbench.measure_kernel`), where state stays in kernel
    layout across rounds — the flagship configuration.

    The tile layout keeps the replica axis as the blocked lane axis in
    contiguous `tile_r`-wide chunks, i.e. exactly the
    `P('replica')`-sharded slicing of the PR 9 mesh tier: a per-shard
    invocation of the chunk calls is the shard-local program
    (tests/test_pallas_fused.py pins chunk-slice composability). The
    MESH-FUSED exec tier (`parallel/collectives.py:MeshFusedEngine`)
    is exactly that composition routed into the wrapper: this engine
    built at the shard's slice of the replica axis, wrapped in
    shard_map with the cursor lattice joined over ICI — one launch
    per device per combiner round at every mesh width.
    """

    supports_fenced = True

    def __init__(self, n_keys: int, spec: LogSpec, tile_r: int = 512,
                 interpret: bool | None = None):
        from node_replication_tpu.ops.pallas_ring import fused_window_ok

        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if not fused_window_ok(spec.capacity, 1):
            raise ValueError(
                f"fused hashmap engine: ring capacity {spec.capacity} "
                f"has no 128-slot row layout"
            )
        self.n_keys = int(n_keys)
        self.spec = spec
        self.tile_r = int(tile_r)
        self.interpret = bool(interpret)
        self.kp = _round_up(self.n_keys, 8)
        self._calls: dict = {}    # (W, fenced) -> (calls, chunk_r)
        self._init_host()

    def supports(self, window: int) -> bool:
        """Window fits the ring-row spans, the appendable capacity,
        and (non-interpret) the VMEM tile budget."""
        from node_replication_tpu.ops.pallas_ring import fused_window_ok

        if not fused_window_ok(self.spec.capacity, window):
            return False
        if window > self.spec.capacity - self.spec.gc_slack:
            return False
        try:
            self._built(window, False)
        except ValueError:
            return False
        return True

    def launches(self, window: int) -> int:
        """Kernel launches per round (chunk calls over the replica
        axis; 1 unless MAX_GRID or VMEM splits the fleet)."""
        _, chunk_r = self._built(window, False)
        return -(-self.spec.n_replicas // chunk_r)

    def _built(self, window: int, fenced: bool):
        key = (window, fenced)
        if key not in self._calls:
            calls, chunk_r, _ = make_fused_hashmap_calls(
                self.n_keys, self.spec, window, tile_r=self.tile_r,
                interpret=self.interpret, fenced=fenced,
            )
            self._calls[key] = (calls, chunk_r)
        return self._calls[key]

    def raw_round(self, window: int, fenced: bool = False):
        """Pure fn over TRANSPOSED planes: `(log, vals_t, pres_t,
        opcodes, args, count[, fenced_vec]) -> (log, vals_t, pres_t,
        resps[W, R])`. Composable inside a caller's jit (the CNR
        per-log wrapper, the kernel bench)."""
        from node_replication_tpu.ops.pallas_ring import (
            append_window_planes,
            fused_cursor_lattice,
            ring_rows,
        )

        calls, chunk_r = self._built(window, fenced)
        spec = self.spec
        R, A = spec.n_replicas, spec.arg_width
        rows = ring_rows(spec.capacity)

        def raw(log, vals_t, pres_t, opcodes, args, count,
                fenced_vec=None):
            ring_opc = log.opcodes.reshape(rows, 128)
            ring_args = log.args.reshape(rows, 128, A)
            s_lo, planes = append_window_planes(
                spec.mask, ring_opc, ring_args, opcodes, args,
                log.tail, count,
            )
            meta = jnp.stack(
                [s_lo, jnp.asarray(count, jnp.int32)]
            )
            key = args[:, 0]
            val = args[:, 1]
            fen_plane = (
                None if fenced_vec is None
                else jnp.asarray(fenced_vec, jnp.int32).reshape(1, R)
            )
            v_chunks, p_chunks, r_chunks = [], [], []
            with x64_disabled():
                for r0 in range(0, R, chunk_r):
                    sub = min(chunk_r, R - r0)
                    ins = [meta, opcodes, key, val, *planes,
                           ring_opc, ring_args,
                           vals_t[:, r0:r0 + sub],
                           pres_t[:, r0:r0 + sub]]
                    if fen_plane is not None:
                        ins.append(fen_plane[:, r0:r0 + sub])
                    (ring_opc, ring_args, v, p, r) = calls[sub](*ins)
                    v_chunks.append(v)
                    p_chunks.append(p)
                    r_chunks.append(r)
            cat = (
                lambda xs: xs[0] if len(xs) == 1
                else jnp.concatenate(xs, axis=1)
            )
            vals_t, pres_t = cat(v_chunks), cat(p_chunks)
            resps = cat(r_chunks)
            log = log._replace(
                opcodes=ring_opc.reshape(spec.capacity),
                args=ring_args.reshape(spec.capacity, A),
            )
            log = fused_cursor_lattice(log, count, fenced_vec)
            return log, vals_t, pres_t, resps

        return raw

    def round_fn(self, window: int, fenced: bool = False):
        """Pure MODEL-layout round fn (transposes inside): `(log,
        states, opcodes, args, count[, fenced_vec]) -> (log, states,
        resps[R, W])` with `resps[r, j]` answering window offset j
        (= logical position tail+j under lock-step) and fenced rows
        zeroed — the layout response delivery consumes."""
        raw = self.raw_round(window, fenced)
        K, kp = self.n_keys, self.kp

        def fn(log, states, opcodes, args, count, fenced_vec=None):
            vals_t = jnp.zeros(
                (kp, states["values"].shape[0]), jnp.int32
            ).at[:K].set(states["values"].T)
            pres_t = jnp.zeros_like(vals_t).at[:K].set(
                states["present"].T.astype(jnp.int32)
            )
            log, vals_t, pres_t, resps = raw(
                log, vals_t, pres_t, opcodes, args, count, fenced_vec
            )
            states = {
                "values": vals_t[:K].T,
                "present": pres_t[:K].T > 0,
            }
            resps = resps.T  # [R, W]
            if fenced_vec is not None:
                resps = jnp.where(
                    jnp.asarray(fenced_vec, bool)[:, None], 0, resps
                )
            return log, states, resps

        return fn

    # round() — the host entry with metrics + the kernel-launch event —
    # is inherited from FusedEngineHost (ops/pallas_ring.py)
