"""Ring-window machinery shared by the FUSED append+replay kernels.

The fused engines (`ops/pallas_replay.py:FusedHashmapEngine`,
`ops/pallas_vspace.py:FusedVspaceEngine`) run a whole combiner round —
log-window append, replay, response gather — as ONE `pallas_call`. The
append half is the part they share, and it lives here.

Layout contract: the log's ring arrays enter the kernel UN-BLOCKED
(`memory_space=pltpu.ANY`, aliased in→out), viewed 2-D as
`[capacity/128, 128]` — ring rows of 128 slots each, a free row-major
reshape of the canonical `LogState` planes. The appended window
`[tail, tail+count)` covers at most `window_rows(W)` consecutive rows
(mod ring wrap), so the kernel updates the ring with TWO fixed-size
async copies of pre-blended row spans:

- the **lo span**: `win_rows` rows starting at the (dynamic, clamped)
  row of the tail slot,
- the **hi span**: rows `[0, win_rows)` — the wrap landing zone.

`append_window_planes` builds both spans XLA-side in O(window) work: it
gathers the spans' current content, blends the batch over exactly the
slots `[tail, tail+count)` (delta-mod arithmetic handles the wrap), and
leaves every other covered slot bit-identical — so DMA-ing a span back
rewrites untouched slots with their own values. When the window does
not wrap, the hi span degenerates to an identity rewrite of the first
rows. Both spans may overlap on small rings; they carry identical
content, and the kernel issues them sequentially.

Why DMA instead of per-entry stores: Mosaic has no dynamic LANE
indexing, and a ring row puts the slot index on the lane axis. The
pre-blended spans turn the scatter into two aligned block copies — the
double-buffered-VMEM-window idiom over the ring — while the un-blocked
ANY refs keep the aliasing OUTSIDE the grid pipeline, which is exactly
the regime the r5 corruption rule (`ops/pallas_chunk.py`, nrlint
`aliased-pallas-planes`) says is safe: only BLOCKED planes race the
pipeline's prefetch/writeback.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

RING_LANES = 128


def window_rows(window: int) -> int:
    """Ring rows covering any 128-phase alignment of `window` slots."""
    return -(-window // RING_LANES) + 1


def ring_rows(capacity: int) -> int:
    return capacity // RING_LANES


def fused_window_ok(capacity: int, window: int) -> bool:
    """Can a `window`-slot append ride the two fixed row spans?

    Needs the ring to be row-shaped (capacity a multiple of 128 — every
    power of two >= 128 qualifies) and tall enough that a span of
    `window_rows(window)` rows fits; `window + 128 <= capacity` keeps
    the lo span's clamp (`min(r0, rows - win_rows)`) able to cover the
    tail row. Callers fall back to the ordinary append+exec chain when
    this is False.
    """
    if capacity % RING_LANES or window < 1:
        return False
    return (
        window_rows(window) <= ring_rows(capacity)
        and window + RING_LANES <= capacity
    )


def append_window_planes(mask: int, ring_opc2d, ring_args3d,
                         opcodes, args, tail, count):
    """XLA-side prep: desired POST-append content of the two row spans.

    `ring_opc2d`/`ring_args3d` are the `[rows, 128]` / `[rows, 128, A]`
    views of the ring planes, `opcodes`/`args` the NOOP-padded batch
    (`[W]` / `[W, A]`), `tail` the int64 append cursor and `count` the
    number of live entries (`count <= W`). Returns
    `(s_lo, (opc_lo, args_lo, opc_hi, args_hi))` with `s_lo` the lo
    span's starting row (int32) and each plane shaped
    `[win_rows, 128(, A)]` — ready to DMA over the ring rows.
    """
    W = opcodes.shape[0]
    win = window_rows(W)
    rows = (mask + 1) // RING_LANES
    tail_slot = (tail & mask).astype(jnp.int32)
    r0 = tail_slot // RING_LANES
    s_lo = jnp.minimum(r0, jnp.int32(rows - win))
    count32 = jnp.asarray(count, jnp.int32)

    def span(row0):
        s = row0 * RING_LANES + jnp.arange(
            win * RING_LANES, dtype=jnp.int32
        )
        # slot-space delta from the tail: in [0, capacity); slots whose
        # delta lands below `count` are the appended entries
        d = (s - tail_slot) & jnp.int32(mask)
        live = d < count32
        gi = jnp.clip(d, 0, W - 1)
        old_opc = lax.dynamic_slice(
            ring_opc2d, (row0, jnp.int32(0)), (win, RING_LANES)
        ).reshape(win * RING_LANES)
        old_args = lax.dynamic_slice(
            ring_args3d, (row0, jnp.int32(0), jnp.int32(0)),
            (win, RING_LANES, ring_args3d.shape[2]),
        ).reshape(win * RING_LANES, ring_args3d.shape[2])
        opc = jnp.where(live, opcodes[gi], old_opc)
        arg = jnp.where(live[:, None], args[gi], old_args)
        return (
            opc.reshape(win, RING_LANES),
            arg.reshape(win, RING_LANES, ring_args3d.shape[2]),
        )

    opc_lo, args_lo = span(s_lo)
    opc_hi, args_hi = span(jnp.int32(0))
    return s_lo, (opc_lo, args_lo, opc_hi, args_hi)


def ring_append_dma(sem, s_lo, win_rows: int, lo_planes, hi_planes,
                    ring_outs):
    """Kernel-side append: copy the pre-blended spans over the ring.

    `lo_planes`/`hi_planes` are VMEM refs of the planes built by
    `append_window_planes`; `ring_outs` the matching UN-BLOCKED
    (aliased) ring output refs, 2-D/3-D row views. Copies run
    sequentially — the spans may overlap on small rings, and they carry
    identical content for shared rows, so ordering only matters for
    write-write tearing, which the serialization removes.
    """
    from jax.experimental.pallas import tpu as pltpu

    for src, dst in zip(lo_planes, ring_outs):
        cp = pltpu.make_async_copy(
            src, dst.at[pl.ds(s_lo, win_rows)], sem
        )
        cp.start()
        cp.wait()
    for src, dst in zip(hi_planes, ring_outs):
        cp = pltpu.make_async_copy(
            src, dst.at[pl.ds(0, win_rows)], sem
        )
        cp.start()
        cp.wait()


class FusedEngineHost:
    """Shared host-side plumbing for the fused engines
    (`ops/pallas_replay.FusedHashmapEngine`,
    `ops/pallas_vspace.FusedVspaceEngine`): the per-window round cache
    (jit on TPU, EAGER in interpret mode — jit + interpret + the
    package's x64 default trips an MLIR where-fn dtype mismatch in
    this jax, the same reason every interpret test passes jit=False),
    the `kernel.*` metrics, the `log.engine.pallas_fused` tier counter,
    and the `kernel-launch` trace event. Subclasses provide
    `round_fn(window, fenced)`, `launches(window)`, `supports(window)`,
    a `supports_fenced` class flag, and set `self.interpret`.

    `note_round` is public so callers that embed `round_fn` in their
    own program (the CNR per-log wrapper, the kernel bench) report the
    same metrics as callers of `round()` — one instrumentation
    contract, never two.

    `tier`/`devices` identify the engine in that contract: the plain
    single-device engines are `pallas_fused` on 1 device; the
    shard_map-wrapped mesh composition
    (`parallel/collectives.py:MeshFusedEngine`) overrides both, so its
    rounds count under `log.engine.mesh_fused` and its `kernel-launch`
    events carry the mesh width.
    """

    supports_fenced = False
    tier = "pallas_fused"
    devices = 1

    def _init_host(self) -> None:
        from node_replication_tpu.obs.metrics import (
            COUNT_BUCKETS,
            get_registry,
        )

        reg = get_registry()
        self._m_launches = reg.counter("kernel.launches")
        self._m_ops = reg.counter("kernel.fused_window_ops")
        self._m_window = reg.histogram("kernel.window",
                                       buckets=COUNT_BUCKETS)
        self._m_dur = reg.histogram("kernel.round.duration_s")
        self._rounds: dict = {}

    def note_round(self, window: int, count: int, duration_s: float,
                   fenced: bool = False) -> None:
        """Count one fused round: tier counter, kernel.* metrics,
        kernel-launch event. Duration is enqueue-side (the tunneled
        platform returns at dispatch); fenced timing is the caller's
        span contract. `kernel.launches` advances by
        `launches(window)` — the engine's claim, derived from the same
        built chunk structure the round loop iterates (a compiled
        round's dispatches are invisible to the host, so this is the
        best available truth; the bench's chain runners, whose
        dispatches ARE host calls, count at the call sites instead)."""
        from node_replication_tpu.core import log as _corelog
        from node_replication_tpu.utils.trace import get_tracer

        n_launch = self.launches(window)
        if self.tier == "mesh_fused":
            _corelog._m_engine_mesh_fused.inc()
        else:
            _corelog._m_engine_pallas_fused.inc()
        self._m_launches.inc(n_launch)
        self._m_ops.inc(int(count))
        self._m_window.observe(window)
        self._m_dur.observe(duration_s)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.emit(
                "kernel-launch", tier=self.tier, window=window,
                count=int(count), launches=n_launch,
                devices=self.devices,
                duration_s=duration_s, fenced=fenced,
            )

    def round(self, log, states, opcodes, args, count, fenced=None):
        """Host entry: cached model-layout round + instrumentation.
        `count` is a host int; `opcodes` must be NOOP-padded past it
        (`encode_ops`)."""
        import time as _time

        import jax as _jax
        import jax.numpy as _jnp

        if fenced is not None and not self.supports_fenced:
            raise ValueError(
                f"{type(self).__name__} has no fenced kernel variant "
                f"(supports_fenced=False)"
            )
        W = int(opcodes.shape[0])
        is_fenced = fenced is not None
        fn = self._rounds.get((W, is_fenced))
        if fn is None:
            inner = self.round_fn(W, is_fenced)
            fn = (
                inner if self.interpret
                else _jax.jit(inner, donate_argnums=(0, 1))
            )
            self._rounds[(W, is_fenced)] = fn
        t0 = _time.perf_counter()
        if is_fenced:
            out = fn(log, states, opcodes, args, count,
                     _jnp.asarray(fenced, bool))
        else:
            out = fn(log, states, opcodes, args, count)
        self.note_round(W, count, _time.perf_counter() - t0,
                        fenced=is_fenced)
        return out


def fused_cursor_lattice(log, count, fenced=None):
    """The fused round's cursor join — the same lattice `log_exec_all`
    computes, specialized to the lock-step precondition (every live
    cursor at the pre-append tail, the whole window consumed):

    - `tail += count`;
    - unfenced `ltails` land on the new tail, fenced cursors freeze;
    - `ctail = max(ctail, max(ltails))` (= the new tail, since the
      eligibility check guarantees a live replica);
    - `head` = the `_gc_head` reduction (min over unfenced, clamped
      monotone), so a fenced corpse neither stalls GC nor rewinds it.
    """
    from node_replication_tpu.core.log import _gc_head

    new_tail = log.tail + jnp.asarray(count, jnp.int64)
    R = log.ltails.shape[0]
    if fenced is None:
        new_lt = jnp.broadcast_to(new_tail, (R,))
        # ctail/head written as their true lattice joins (both reduce
        # to the new tail here) rather than re-using `new_tail`: three
        # cursor outputs sharing ONE buffer would make the next
        # donating program reject the log ("donate the same buffer
        # twice")
        return log._replace(
            tail=new_tail,
            ltails=new_lt,
            ctail=jnp.maximum(log.ctail, new_tail),
            head=jnp.maximum(log.head, jnp.min(new_lt)),
        )
    fen = jnp.asarray(fenced, bool)
    new_lt = jnp.where(fen, log.ltails, new_tail)
    out = log._replace(
        tail=new_tail,
        ltails=new_lt,
        ctail=jnp.maximum(log.ctail, jnp.max(new_lt)),
    )
    return out._replace(head=_gc_head(out, new_lt, fen))
