"""Operation encoding and the `Dispatch` contract.

Replaces the reference's `Dispatch` trait (`nr/src/lib.rs:103-125`): instead
of associated `ReadOperation` / `WriteOperation` / `Response` types and
`dispatch(&self)` / `dispatch_mut(&mut self)` methods, an operation here is a
fixed-width record `(opcode: int32, args: int32[arg_width])` and the data
structure is described by a `Dispatch` value holding

- `make_state()` — builds the replica state pytree (the reference requires
  `D: Default`, `nr/examples/stack.rs:30-35`; deterministic init is the
  recovery model, SURVEY.md §5),
- `write_ops[i]`  : (state, args) -> (state, resp)   — pure `dispatch_mut`,
- `read_ops[i]`   : (state, args) -> resp            — pure `dispatch`.

Opcode 0 is reserved as NOOP in both spaces so that padded / masked batch
slots replay as no-ops (the fixed-shape substitute for the reference's
`Option<T>` log-entry payloads and `alivef` liveness bits,
`nr/src/log.rs:51-65`). User write opcodes therefore start at 1.

Everything here is jit-safe: `apply_write` / `apply_read` lower to a single
`lax.switch`, which XLA compiles to a branch table executed uniformly across
a vmapped replica axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any

# Reserved opcode: replay/padding no-op in both the write and read spaces.
NOOP = 0

# Responses are a single int32 lane. The reference's responses are
# word-sized as well (`Response = Option<u64>` style, e.g.
# `nr/examples/stack.rs:46-49`); "None" is conventionally encoded as -1 by
# the bundled models.
RESP_DTYPE = jnp.int32


@dataclasses.dataclass(frozen=True)
class Dispatch:
    """A replicated data structure: state constructor + pure transitions.

    Hashable (frozen, tuples of functions) so it can be a jit static arg.

    `window_plan` / `window_merge` (optional, come as a pair) split the
    combined replay for models whose window algebra DEPENDS on the
    running state (stack, queue: every slot assignment needs the clamped
    depth walk from the initial top): `window_plan(state, opcodes, args)
    -> plan` runs ONCE per window on a representative replica — this is
    where the sorts and scans live — and `window_merge(state, plan) ->
    (state, resps)` applies the plan's dense result per replica
    (elementwise, the honest per-replica replay work). Sound under the
    fused step's lock-step precondition (all replicas identical by
    induction).

    STRENGTHENED CONTRACT for divergent cursors (`window_canonical`):
    the union-window catch-up tier is an explicit OPT-IN, not implied
    by the pair's presence. A model sets `window_canonical=True` to
    declare its plan/merge satisfies the stronger contract below;
    only then do `NodeReplicated(engine='auto')` and `log_catchup_all`
    route it through `core/log.py:_catchup_union_plan` (and
    `engine='combined'` still FORCES that tier explicitly, canonical
    flag or not — the caller is asserting the contract). The tier
    merges the plan of the union window `[min(ltails), end)` (computed
    from the most-lagging replica's state) into replicas that already
    applied an arbitrary PREFIX of that window. Beyond the lock-step
    precondition this requires:

    - **prefix-absorbing plan**: for every split point p in the window,
      merging `window_plan(state(m), W)` into `state(p)` (the fold of
      the prefix `[m, p)`) must equal `state(end)` — cells the window
      touches take the plan's final value regardless of how much of the
      window the replica already applied, untouched cells keep the
      replica's value;
    - **canonical (state-independent) merge responses**: the per-position
      responses `window_merge` reports must depend only on the plan
      (equivalently: on the shared replay trajectory), never on the
      merging replica's pre-merge state, because catch-up re-indexes the
      donor plan's responses for every replica's own offsets.

    A model whose plan/merge satisfies only the lock-step contract
    simply leaves `window_canonical=False` (the default): it keeps the
    fused lock-step fast path, and catch-up falls back to the
    per-replica `window_apply` tier or the scan — third-party models
    are never silently routed through the stronger-contract engine
    (ADVICE r5). Hand-built off-trajectory fleets additionally pass
    `log_catchup_all(..., on_trajectory=False)`. Differential
    coverage: `tests/test_window.py::TestCombinedCatchup`.

    `window_apply` (optional) is the *combined replay* fast path:
    `(state, opcodes[W], args[W, A]) -> (state, resps[W])`, bit-identical
    to folding `apply_write` over the window in order. Models whose write
    ops are per-key last-writer-wins (hashmap, sorted set, page tables…)
    can compute a whole window with one sort + predecessor lookup + one
    dense merge instead of W sequential scatters — the flat-combining idea
    (`nr/src/replica.rs:543-595` batches ops to amortize the log CAS)
    taken to its TPU conclusion: the *application* itself is batched into
    a parallel reduction, turning the HBM-bound sequential replay scan
    into a handful of vectorized passes. `core/step.make_step` uses it
    automatically when present; the generic `lax.scan` path remains for
    order-dependent models (stack, queue) and divergent-cursor replay.
    """

    name: str
    make_state: Callable[[], PyTree]
    write_ops: tuple
    read_ops: tuple
    arg_width: int = 3
    window_apply: Callable | None = None
    window_plan: Callable | None = None
    window_merge: Callable | None = None
    # Explicit opt-in to the union-window catch-up tier: asserts the
    # plan is prefix-absorbing and merge responses are canonical (see
    # class docstring). Mere presence of window_plan/window_merge only
    # claims the weaker lock-step contract.
    window_canonical: bool = False
    # Fused pallas combiner-round engine (optional): a callable
    # `(spec: LogSpec, interpret=None) -> engine` building the model's
    # one-kernel-launch append+replay round (e.g.
    # `ops/pallas_replay.py:FusedHashmapEngine`). The engine contract:
    # `round(log, states, opcodes, args, count, fenced=None)` under the
    # lock-step precondition, `supports(window)`, `launches(window)`,
    # and a `supports_fenced` class flag. Raising ValueError from the
    # factory means "no fused form at this config" — wrappers fall
    # back to the append+exec chain (`core/replica.py` winner
    # selection).
    fused_factory: Callable | None = None

    @property
    def n_write_ops(self) -> int:
        return len(self.write_ops)

    @property
    def n_read_ops(self) -> int:
        return len(self.read_ops)

    def init_state(self) -> PyTree:
        return self.make_state()


def _noop_write(state: PyTree, args: jax.Array):
    return state, RESP_DTYPE(0)


def _noop_read(state: PyTree, args: jax.Array):
    return RESP_DTYPE(0)


def apply_write(d: Dispatch, state: PyTree, opcode: jax.Array, args: jax.Array):
    """Apply one encoded write op: the jit-safe `dispatch_mut`.

    Unknown / out-of-range opcodes route to the NOOP branch (inert),
    mirroring how padded log slots must replay as no-ops — and matching
    the native engine's unknown-opcode behavior for differential tests.
    """

    def wrap(f):
        def g(s, a):
            s2, r = f(s, a)
            return s2, RESP_DTYPE(r)

        return g

    branches = (_noop_write,) + tuple(wrap(f) for f in d.write_ops)
    valid = (opcode >= 0) & (opcode < len(branches))
    idx = jnp.where(valid, opcode, 0)
    return lax.switch(idx, branches, state, args)


def apply_read(d: Dispatch, state: PyTree, opcode: jax.Array, args: jax.Array):
    """Apply one encoded read op: the jit-safe `dispatch` (never mutates)."""

    def wrap(f):
        def g(s, a):
            return RESP_DTYPE(f(s, a))

        return g

    branches = (_noop_read,) + tuple(wrap(f) for f in d.read_ops)
    valid = (opcode >= 0) & (opcode < len(branches))
    idx = jnp.where(valid, opcode, 0)
    return lax.switch(idx, branches, state, args)


def dispatch_reads(d: Dispatch, states: PyTree, rd_opcodes, rd_args):
    """Answer per-replica read batches against local replica state:
    `rd_opcodes int32[R, Br]`, `rd_args int32[R, Br, A]` → `int32[R, Br]`.
    The batched read path shared by the single- and multi-log steps
    (`nr/src/replica.rs:483-497` local dispatch, vectorized)."""
    return jax.vmap(
        lambda state, opcs, args: jax.vmap(
            lambda o, a: apply_read(d, state, o, a)
        )(opcs, args)
    )(states, rd_opcodes, rd_args)


def encode_ops(
    ops: Sequence[tuple], arg_width: int, pad_to: int | None = None
) -> tuple[jax.Array, jax.Array, int]:
    """Encode a host-side list of `(opcode, *args)` tuples into device arrays.

    Returns `(opcodes: int32[B], args: int32[B, arg_width], count)` where
    slots past `count` are NOOP padding. `pad_to` fixes B (for shape-stable
    jit entry); defaults to `len(ops)`.
    """
    n = len(ops)
    pad = n if pad_to is None else pad_to
    if n > pad:
        raise ValueError(f"{n} ops do not fit in pad_to={pad}")
    opcodes = [int(o[0]) for o in ops] + [NOOP] * (pad - n)
    args = [
        list(o[1:]) + [0] * (arg_width - (len(o) - 1)) for o in ops
    ] + [[0] * arg_width] * (pad - n)
    return (
        jnp.asarray(opcodes, jnp.int32),
        jnp.asarray(args, jnp.int32).reshape(pad, arg_width),
        n,
    )
