from node_replication_tpu.ops.encoding import (
    Dispatch,
    NOOP,
    apply_read,
    apply_write,
    encode_ops,
)
from node_replication_tpu.ops.context import Context

__all__ = [
    "Dispatch",
    "NOOP",
    "apply_read",
    "apply_write",
    "encode_ops",
    "Context",
]
