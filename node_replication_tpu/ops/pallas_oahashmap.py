"""Pallas TPU replay kernel for the open-addressing hashmap.

Third instantiation of the in-VMEM sequential replay template (after the
dense hashmap, `ops/pallas_replay.py`, and the vspace span kernels,
`ops/pallas_vspace.py`), covering the probe-window RMW class the r3
verdict named: every op gathers a `probe`-slot LINEAR WINDOW from its
key's hash home, picks first-match/first-free, and writes one slot —
order-dependent through the occupancy/tombstone history, so no
algebraic `window_apply` exists and the generic scan was its only
engine.

Kernel shape (the vspace layout, three planes):

- `keys/vals/flag` live per replica as `[ROWS, 128]` int32 planes; a
  probe window is a STATIC `ceil(probe/128)+1`-row dynamic-sublane
  slice, wrapped windows split into two runs exactly like the flat
  vspace's mod-wrapped spans;
- first-match/first-free become masked MIN-reductions over the probe
  position vector (`pos | BIG` halving-min — no reduce primitive, same
  x64 rationale as `_sum32`), combined across the two runs; the write
  is a one-hot lane blend at the winning position;
- the key mix runs in int32 with explicit logical shifts and an
  unsigned-mod emulation, bit-identical to the model's uint32 math;
- replicas are processed in VMEM-fitting GROUPS with
  `input_output_aliases`, and responses are the single canonical copy
  of the lock-step invariant (see ops/pallas_vspace.py's module
  docstring — the same contract applies here).

Opcodes follow `models/oahashmap.py`: PUT=1 (k, v -> 0 ok / -2
window-full), REMOVE=2 (k -> was-present). Bit-exact vs the sequential
fold in interpret mode (tests/test_pallas_oahashmap.py) and on hardware
(`NR_TPU_SMOKE=1`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from node_replication_tpu.core.log import LogSpec, log_append
from node_replication_tpu.utils.compat import x64_disabled

_OCC = 1
_TOMB = 2
_BIG = 1 << 20
_VMEM_BUDGET = 12 << 20


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _grid2(row0, height):
    return (
        row0 * 128
        + jax.lax.broadcasted_iota(jnp.int32, (height, 128), 0) * 128
        + jax.lax.broadcasted_iota(jnp.int32, (height, 128), 1)
    )


def _min32(x):
    """int32 full MIN-reduction of `[rows, 128]` by unrolled ops (no
    reduce primitive — see ops/pallas_vspace._sum32 for why)."""
    row = x[0:1, :]
    for r in range(1, x.shape[0]):
        row = jnp.minimum(row, x[r:r + 1, :])
    w = x.shape[1]
    while w > 1:
        w //= 2
        row = jnp.minimum(row[:, :w], row[:, w:2 * w])
    return row[0, 0]


def _mix_mod(x, n_slots: int):
    """`models/oahashmap._mix` then `% n_slots`, in pure int32.

    The model mixes in uint32; multiplies and xors are bit-identical in
    two's-complement int32, shifts must be LOGICAL, and the final
    unsigned modulo is emulated as
    `((x & 0x7fffffff) % n + (2^31 % n) * signbit) % n`.
    """
    lsr = lambda a, b: jax.lax.shift_right_logical(a, jnp.int32(b))
    x = (x ^ lsr(x, 16)) * jnp.int32(0x7FEB352D)
    x = (x ^ lsr(x, 15)) * jnp.int32(-2073254261)  # 0x846CA68B as i32
    x = x ^ lsr(x, 16)
    n = jnp.int32(n_slots)
    lo = jax.lax.rem(x & jnp.int32(0x7FFFFFFF), n)
    hi = jnp.int32((1 << 31) % n_slots) * lsr(x, 31)
    return jax.lax.rem(lo + hi, n)


def _oa_kernel(opc_ref, a0_ref, a1_ref,
               k_in, v_in, f_in, k_out, v_out, f_out, resp_ref,
               *, n_slots: int, probe: int, window: int, rows: int,
               span_rows: int):
    # compile-time re-trace happens outside any caller's x64 guard
    with x64_disabled():
        _oa_body(opc_ref, a0_ref, a1_ref, k_in, v_in, f_in, k_out,
                 v_out, f_out, resp_ref, n_slots, probe, window, rows,
                 span_rows)


def _oa_body(opc_ref, a0_ref, a1_ref, k_in, v_in, f_in, k_out, v_out,
             f_out, resp_ref, n_slots, probe, window, rows, span_rows):
    # UN-aliased in/out (r5): aliased blocked state planes race with the
    # pipeline's prefetch/writeback on hardware — replicas in later grid
    # steps read stale or shifted blocks, nondeterministically (bisected
    # on TPU v5e: ~always corrupt past 32 grid steps, occasionally at
    # 32). Copy the input block in and work in the output block; only
    # the grid=1 plan kernels keep in-place aliasing.
    k_out[...] = k_in[...]
    v_out[...] = v_in[...]
    f_out[...] = f_in[...]
    N = jnp.int32(n_slots)

    def body(i, carry):
        op = opc_ref[i]
        k = a0_ref[i]
        v = a1_ref[i]
        is_put = op == 1
        is_rem = op == 2
        h = _mix_mod(k, n_slots)

        def scan_run(row0, base):
            slot = _grid2(row0, span_rows)
            pos = slot - base
            valid = (pos >= 0) & (pos < probe) & (slot < N)
            flg = f_out[:, pl.ds(row0, span_rows), :][0]
            key = k_out[:, pl.ds(row0, span_rows), :][0]
            match = valid & (flg == _OCC) & (key == k)
            free = valid & (flg != _OCC)
            mm = _min32(jnp.where(match, pos, _BIG))
            mf = _min32(jnp.where(free, pos, _BIG))
            return mm, mf

        # run B from the hash home; run A holds the wrapped tail of the
        # probe window (rows from STATIC 0 — see the flat vspace kernel)
        row_b = jnp.minimum(h >> 7, jnp.int32(rows - span_rows))
        mm_b, mf_b = scan_run(row_b, h)
        mm_a, mf_a = scan_run(0, h - N)
        mm = jnp.minimum(mm_b, mm_a)
        mf = jnp.minimum(mf_b, mf_a)
        any_match = mm < _BIG
        any_free = mf < _BIG
        ok = any_match | any_free
        # PUT targets first match else first free; REMOVE only a match.
        # write_en gating rides the target (scalar select), never a
        # scalar-bool & vector-bool (does not legalize in Mosaic)
        t_put = jnp.where(any_match, mm, mf)
        write_en = jnp.where(is_put, ok, is_rem & any_match)
        target = jnp.where(
            write_en, jnp.where(is_put, t_put, mm), jnp.int32(-1)
        )
        fv = jnp.where(is_put, jnp.int32(_OCC), jnp.int32(_TOMB))

        def blend_run(row0, base):
            slot = _grid2(row0, span_rows)
            pos = slot - base
            valid = (pos >= 0) & (pos < probe) & (slot < N)
            wmask = valid & (pos == target)
            blk_k = k_out[:, pl.ds(row0, span_rows), :]
            blk_v = v_out[:, pl.ds(row0, span_rows), :]
            blk_f = f_out[:, pl.ds(row0, span_rows), :]
            kv = jnp.where(is_put, k, blk_k)
            vv = jnp.where(is_put, v, blk_v)
            k_out[:, pl.ds(row0, span_rows), :] = jnp.where(
                wmask[None], kv, blk_k
            )
            v_out[:, pl.ds(row0, span_rows), :] = jnp.where(
                wmask[None], vv, blk_v
            )
            f_out[:, pl.ds(row0, span_rows), :] = jnp.where(
                wmask[None], fv, blk_f
            )

        blend_run(row_b, h)
        blend_run(0, h - N)
        resp_ref[0, 0, i] = jnp.where(
            is_put,
            jnp.where(ok, jnp.int32(0), jnp.int32(-2)),
            jnp.where(is_rem, any_match.astype(jnp.int32), jnp.int32(0)),
        )
        return carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(window), body, jnp.int32(0))


def _layout(n_slots: int, probe: int, n_replicas: int, interpret: bool):
    rows = max(2, _round_up(n_slots, 128) // 128 + 1)  # +1 guard row
    span_rows = min(-(-probe // 128) + 1, rows)
    # three planes per replica, separate in+out blocks (un-aliased),
    # each double-buffered
    per = 2 * 2 * 3 * rows * 128 * 4
    if per > _VMEM_BUDGET and not interpret:
        raise ValueError(
            f"oahashmap pallas replay needs {per >> 20} MB of VMEM for "
            f"n_slots={n_slots}; use the scan engine for this config"
        )
    if n_slots < span_rows * 128 + probe:
        raise ValueError(
            f"oahashmap pallas replay needs n_slots >= "
            f"{span_rows * 128 + probe} so a wrapped probe window's two "
            f"row blends never overlap"
        )
    group = 1
    for g in range(n_replicas, 0, -1):
        if n_replicas % g == 0 and g * per <= _VMEM_BUDGET:
            group = g
            break
    return rows, span_rows, group


def make_oahashmap_replay(
    n_slots: int,
    probe: int,
    n_replicas: int,
    window: int,
    interpret: bool = False,
):
    """`replay(opc[W], args[W,3], keys[R,ROWS,128], vals[...], flag[...])
    -> (keys, vals, flag, resps[W])`. Responses are the single canonical
    copy of the lock-step invariant."""
    from jax.experimental.pallas import tpu as pltpu

    if probe > 128:
        raise ValueError("probe > 128 breaks the two-run window split")
    rows, span_rows, group = _layout(n_slots, probe, n_replicas,
                                     interpret)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    kernel = functools.partial(
        _oa_kernel, n_slots=n_slots, probe=probe, window=window,
        rows=rows, span_rows=span_rows,
    )

    def build_call(sub_r: int):
        plane = pl.BlockSpec((group, rows, 128), lambda i: (i, 0, 0))
        resp_spec = pl.BlockSpec((1, 1, window), lambda i: (0, 0, 0),
                                 memory_space=pltpu.SMEM)
        return pl.pallas_call(
            kernel,
            grid=(sub_r // group,),
            in_specs=[smem(), smem(), smem(), plane, plane, plane],
            out_specs=[plane, plane, plane, resp_spec],
            out_shape=[
                jax.ShapeDtypeStruct((sub_r, rows, 128), jnp.int32),
                jax.ShapeDtypeStruct((sub_r, rows, 128), jnp.int32),
                jax.ShapeDtypeStruct((sub_r, rows, 128), jnp.int32),
                jax.ShapeDtypeStruct((1, 1, window), jnp.int32),
            ],
            # NO input_output_aliases: see _oa_body's un-aliased note
            interpret=interpret,
        )

    from node_replication_tpu.ops.pallas_chunk import (
        build_calls,
        chunk_size,
        run_chunks,
    )

    chunk_r = chunk_size(n_replicas, group)
    calls = build_calls(n_replicas, chunk_r, build_call)

    def replay(opc, args, keys, vals, flag):
        with x64_disabled():
            a0, a1 = args[:, 0], args[:, 1]
            (keys, vals, flag), (resps,) = run_chunks(
                n_replicas, chunk_r, calls,
                lambda call, r0, sub: call(
                    opc, a0, a1, keys[r0:r0 + sub], vals[r0:r0 + sub],
                    flag[r0:r0 + sub],
                ),
                n_plane_outs=3,
            )
        return keys, vals, flag, resps.reshape(window)

    return replay


def pallas_oahashmap_state(n_slots: int, n_replicas: int,
                           model_state=None):
    rows = max(2, _round_up(n_slots, 128) // 128 + 1)

    def grid3(key):
        flat = (
            model_state[key] if model_state is not None
            else jnp.zeros((n_slots,), jnp.int32)
        )
        padded = jnp.zeros((rows * 128,), jnp.int32).at[:n_slots].set(flat)
        return jnp.broadcast_to(
            padded.reshape(rows, 128), (n_replicas, rows, 128)
        )

    return {"keys": grid3("keys"), "vals": grid3("vals"),
            "flag": grid3("flag")}


def oahashmap_model_view(state, n_slots: int):
    R = state["keys"].shape[0]
    return {
        k: state[k].reshape(R, -1)[:, :n_slots]
        for k in ("keys", "vals", "flag")
    }


def make_pallas_oahashmap_step(
    n_slots: int,
    probe: int,
    spec: LogSpec,
    writes_per_replica: int,
    reads_per_replica: int,
    interpret: bool = False,
    jit: bool = True,
    donate: bool = True,
):
    """Pallas twin of `core/step.make_step` for the open-addressing map
    (same lock-step contract as `make_pallas_vspace_step`). Reads (GET)
    run as direct probe-window gathers on the plane layout."""
    import numpy as np

    R = spec.n_replicas
    Bw = int(writes_per_replica)
    span = R * Bw
    chunk = span
    while chunk > 4096 and chunk % 2 == 0:
        chunk //= 2
    replay = make_oahashmap_replay(n_slots, probe, R, chunk,
                                   interpret=interpret)

    def reads(states, rd_opcodes, rd_args):
        from node_replication_tpu.models.oahashmap import _mix

        k = rd_args[..., 0]
        h = (_mix(k) % jnp.uint32(n_slots)).astype(jnp.int32)
        idx = (h[..., None] + jnp.arange(probe, dtype=jnp.int32)) % (
            n_slots
        )
        view = oahashmap_model_view(states, n_slots)
        r_ix = jnp.arange(R, dtype=jnp.int32).reshape(
            -1, *([1] * (idx.ndim - 1))
        )
        flg = view["flag"][r_ix, idx]
        key = view["keys"][r_ix, idx]
        val = view["vals"][r_ix, idx]
        match = (flg == _OCC) & (key == k[..., None])
        found = jnp.any(match, axis=-1)
        sel = jnp.argmax(match, axis=-1)
        got = jnp.take_along_axis(val, sel[..., None], axis=-1)[..., 0]
        out = jnp.where(found, got, jnp.int32(-1))
        return jnp.where(rd_opcodes == 1, out, 0)

    def step(log, states, wr_opcodes, wr_args, rd_opcodes, rd_args):
        opc = wr_opcodes.reshape(span)
        args = wr_args.reshape(span, spec.arg_width)
        log = log_append(spec, log, opc, args, span)
        keys, vals, flag = states["keys"], states["vals"], states["flag"]
        resp_chunks = []
        for c0 in range(0, span, chunk):
            keys, vals, flag, r = replay(
                opc[c0:c0 + chunk], args[c0:c0 + chunk], keys, vals,
                flag,
            )
            resp_chunks.append(r)
        states = {"keys": keys, "vals": vals, "flag": flag}
        resps = (
            jnp.concatenate(resp_chunks, axis=0)
            if len(resp_chunks) > 1 else resp_chunks[0]
        )
        log = log._replace(
            ltails=log.ltails + span, ctail=log.ctail + span,
            head=log.head + span,
        )
        own = jnp.arange(R, dtype=jnp.int32)[:, None] * Bw + jnp.arange(
            Bw, dtype=jnp.int32
        )[None, :]
        wr_resps = resps[own]
        rd_resps = reads(states, rd_opcodes, rd_args)
        return log, states, wr_resps, rd_resps

    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step
