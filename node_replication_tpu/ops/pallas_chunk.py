"""Bounded-grid chunking shared by the multi-grid-step pallas kernels.

MAX_GRID is an empirical Mosaic limit found in r5 (TPU v5e): a
pallas_call whose blocked state planes were ALIASED in->out silently
corrupted state once the grid pipelined deep enough — always at >= 64
grid steps, occasionally at 32, never in interpret mode (bisected with
the oahashmap kernel across rows/group/slot-count combinations; the
corruption was replicas in later grid steps reading stale or shifted
blocks). The kernels now use separate in/out planes with an in-kernel
block copy, which removes the observed corruption; the grid cap stays
as belt and braces, and the replica axis is split into <= MAX_GRID-step
calls at the XLA level by the helpers here.
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_GRID = 32


def chunk_size(n_replicas: int, group: int) -> int:
    """Replicas per pallas_call: `group` replicas per grid step, at most
    MAX_GRID steps."""
    return min(n_replicas, group * MAX_GRID)


def build_calls(n_replicas: int, chunk_r: int, build_call):
    """One compiled pallas_call per DISTINCT chunk length (the full
    chunks plus at most one remainder)."""
    calls = {}
    for r0 in range(0, n_replicas, chunk_r):
        sub = min(chunk_r, n_replicas - r0)
        if sub not in calls:
            calls[sub] = build_call(sub)
    return calls


def run_chunks(n_replicas: int, chunk_r: int, calls, invoke,
               n_plane_outs: int):
    """Map the replica axis through the per-chunk calls.

    `invoke(call, r0, sub)` runs one chunk and returns a tuple whose
    FIRST `n_plane_outs` entries are replica-axis plane outputs
    (concatenated across chunks) and whose remaining entries are
    canonical single copies (every chunk recomputes identical values —
    the lock-step invariant — so the last chunk's win). Returns
    `(planes: list, rest: tuple)`.
    """
    planes = [[] for _ in range(n_plane_outs)]
    rest = ()
    for r0 in range(0, n_replicas, chunk_r):
        sub = min(chunk_r, n_replicas - r0)
        out = invoke(calls[sub], r0, sub)
        for i in range(n_plane_outs):
            planes[i].append(out[i])
        rest = tuple(out[n_plane_outs:])
    cat = [
        p[0] if len(p) == 1 else jnp.concatenate(p, axis=0)
        for p in planes
    ]
    return cat, rest
