"""Metrics exporter: serve one process's observability state on a side
port.

The fleet half of the observability layer starts here. A
`MetricsExporter` runs inside every process of a replication tree
(primary frontend, relay, leaf follower — `ServeConfig(obs_port=...)`,
`RelayNode(obs_port=...)`, `Follower(obs_port=...)`) and answers
scrapes with one JSON document:

- the metrics registry `snapshot()` (`obs/metrics.py`),
- the flight recorder's recent trace tail (memory/ring mode,
  incremental via the scraper's cursor — `Tracer.events_since`),
- structured `stats()` blobs registered by the process's subsystems
  (serve frontend, relay, follower, shipper — whatever the host wires
  in via `add_stats`),
- identity: a `node_id` + `role` label every consumer stamps onto
  merged data, and the node's wall clock (`now_ts`) so the collector
  can align per-process clocks without ever comparing raw monotonic
  stamps across processes.

Wire format: the repo's length+CRC framing idiom (`durable/wal.py`
framing, `repl/transport.py` on the wire) — every message is one
frame `u32 length | u32 crc32(payload) | payload`, request and
response payloads are JSON. Request kinds: `{"cmd": "scrape"}` (the
original, and still the hot path), plus the remote-capture plane
(`obs/profile.py`): `profile-start` / `profile-stop` /
`profile-fetch` drive this process's host sampling profiler from any
box that can reach the port, and `device-trace` arms an on-demand
`jax.profiler.trace` device capture (answered as skipped off-TPU —
the command is safe to broadcast fleet-wide). A torn frame means
"reconnect and re-ask", never bad data.

Scrape it three ways:

- `python -m node_replication_tpu.obs.export --scrape host:port` —
  Prometheus-style text exposition on stdout (counters/gauges as
  `nr_tpu_<name>{node=...,role=...}`, histograms as `_count`/`_sum` +
  quantile series);
- the same CLI with `--json` — the raw scrape document;
- `obs/collect.py:FleetCollector` — the programmatic consumer that
  merges N exporters into one fleet view.

Cost contract: an exporter exists only when a port was asked for
(`obs_port=None` is the default everywhere), so the disabled path adds
ZERO per-operation work — not even a branch; construction is the only
choke point. Enabled, all cost is on the scrape path (registry
snapshot + JSON encode), never on the serving hot path.

Pure stdlib on purpose (like `obs/report.py`): the scrape CLI must run
on a machine without jax.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading

from node_replication_tpu.analysis.locks import make_lock
import time
import zlib

logger = logging.getLogger("node_replication_tpu")

_FRAME = struct.Struct("<II")  # payload length, crc32(payload)

#: scrape payloads are JSON metric documents, not data-plane streams;
#: anything bigger than this is a framing error, not a big fleet
MAX_FRAME_BYTES = 1 << 24


class ExportError(RuntimeError):
    """A scrape failed (connect, torn frame, bad CRC, closed server)."""


# ==========================================================================
# framing (the WAL/transport idiom, self-contained to keep obs/ jax-free)
# ==========================================================================


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (TimeoutError, socket.timeout) as e:
            raise ExportError(f"socket timeout mid-frame: {e}") from e
        except OSError as e:
            raise ExportError(f"socket error: {e}") from e
        if not chunk:
            raise ExportError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    try:
        sock.sendall(
            _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        )
    except (TimeoutError, socket.timeout) as e:
        raise ExportError(f"socket timeout on send: {e}") from e
    except OSError as e:
        raise ExportError(f"socket error on send: {e}") from e


def recv_frame(sock: socket.socket) -> bytes:
    hdr = _recv_exact(sock, _FRAME.size)
    length, crc = _FRAME.unpack(hdr)
    if length > MAX_FRAME_BYTES:
        raise ExportError(f"implausible frame length {length}")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) != crc:
        raise ExportError("frame CRC mismatch (torn stream)")
    return payload


# ==========================================================================
# server
# ==========================================================================


class MetricsExporter:
    """Serves this process's registry/tracer/stats over a side port.

        exporter = MetricsExporter(role="primary", port=0)
        host, port = exporter.address         # hand to the collector
        exporter.add_stats("serve", frontend.stats)

    `port=0` binds an ephemeral port (the normal case — publish
    `address` through whatever channel the deployment already has);
    `node_id` defaults to `$NR_TPU_NODE_ID` or `<role>-<pid>` so every
    scrape is attributable without configuration. One exporter per
    process is the natural grain (the registry and tracer are
    process-wide); multiple exporters in one process are legal and
    serve the same registry under their own identities (the in-process
    relay/test topology).
    """

    def __init__(
        self,
        node_id: str | None = None,
        role: str = "node",
        host: str = "127.0.0.1",
        port: int = 0,
        registry=None,
        tracer=None,
        stats_fns: dict | None = None,
        accept_timeout_s: float = 0.2,
        io_timeout_s: float = 5.0,
        auto_start: bool = True,
    ):
        from node_replication_tpu.obs.metrics import get_registry
        from node_replication_tpu.obs.recorder import get_tracer

        self.role = str(role)
        self.node_id = str(
            node_id
            or os.environ.get("NR_TPU_NODE_ID")
            or f"{self.role}-{os.getpid()}"
        )
        self._registry = registry if registry is not None else get_registry()
        self._tracer = tracer if tracer is not None else get_tracer()
        self.accept_timeout_s = float(accept_timeout_s)
        self.io_timeout_s = float(io_timeout_s)

        self._lock = make_lock("MetricsExporter._lock")
        self._stats_fns: dict[str, object] = dict(stats_fns or {})
        self._stop = False
        self._conns: dict[int, socket.socket] = {}
        self._conn_seq = 0
        self._threads: list[threading.Thread] = []
        self._scrapes = 0
        self._scrape_errors = 0
        # remote-capture plane (`obs/profile.py`): the profiler this
        # exporter serves. None until a `profile-start` command (or an
        # owner's `attach_profiler`) creates one — the object-does-
        # not-exist discipline survives remote control: a node nobody
        # profiles never holds a sampler.
        self._profiler = None
        self._profiler_owned = False
        self._device_trace_thread: threading.Thread | None = None

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self._sock.settimeout(self.accept_timeout_s)
        self.address: tuple[str, int] = self._sock.getsockname()[:2]

        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"obs-export-{self.node_id}",
            daemon=True,
        )
        if auto_start:
            self.start()

    # -------------------------------------------------------- lifecycle

    @property
    def accept_thread(self) -> threading.Thread:
        """The accept-loop thread — for thread introspection
        (`ServeFrontend.threads()`), not lifecycle."""
        return self._accept_thread

    def start(self) -> None:
        if not self._accept_thread.is_alive() \
                and not self._accept_thread.ident:
            self._accept_thread.start()
            self._tracer.emit("obs-export-serve", node=self.node_id,
                              role=self.role, host=self.address[0],
                              port=self.address[1])

    def close(self) -> None:
        with self._lock:
            if self._stop:
                return
            self._stop = True
            conns = list(self._conns.values())
            threads = list(self._threads)
            prof = self._profiler if self._profiler_owned else None
            self._profiler = None
        if prof is not None:
            prof.stop()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread.ident:
            self._accept_thread.join(5.0)
        for t in threads:
            if t.ident:
                t.join(5.0)

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ stats

    def add_stats(self, name: str, fn) -> None:
        """Register a `() -> dict` provider under `name`; its result is
        embedded in every scrape as `stats[name]`. A provider that
        raises is reported as `{"error": ...}` for that scrape — one
        sick subsystem never takes down the node's whole export."""
        with self._lock:
            self._stats_fns[str(name)] = fn

    def scrape_count(self) -> int:
        # nrcheck: unshared — lock-free poll; one int load
        return self._scrapes

    # ------------------------------------------------- remote capture

    def attach_profiler(self, profiler) -> None:
        """Serve an externally owned `SamplingProfiler` (e.g. the one
        `ServeConfig(profile_hz=...)` builds) instead of creating one
        on the first `profile-start`. Lifecycle stays with the owner:
        `close()` does not stop an attached profiler."""
        with self._lock:
            self._profiler = profiler
            self._profiler_owned = False

    def profile_start(self, hz: float | None = None,
                      max_stacks: int | None = None) -> dict:
        """Start (or resume) this process's sampling profiler — the
        `profile-start` command body, also callable in-process."""
        from node_replication_tpu.obs.profile import (
            DEFAULT_HZ,
            DEFAULT_MAX_STACKS,
            SamplingProfiler,
        )

        with self._lock:
            prof = self._profiler
            if prof is None:
                prof = SamplingProfiler(
                    hz=float(hz) if hz else DEFAULT_HZ,
                    max_stacks=(int(max_stacks) if max_stacks
                                else DEFAULT_MAX_STACKS),
                )
                self._profiler = prof
                self._profiler_owned = True
        already = prof.running
        if not already:
            prof.start()
        return {"ok": True, "running": True, "already": already,
                "hz": prof.hz, "node_id": self.node_id}

    def profile_stop(self) -> dict:
        """Stop sampling; the aggregate stays fetchable."""
        with self._lock:
            prof = self._profiler
        if prof is not None:
            prof.stop()
        return {"ok": True, "running": False,
                "had_profiler": prof is not None,
                "node_id": self.node_id}

    def profile_fetch(self, stop: bool = False) -> dict:
        """The profile document: snapshot + folded text, stamped with
        this node's identity (the `profile-fetch` command body)."""
        from node_replication_tpu.obs.profile import (
            folded_from_snapshot,
            host_budget,
        )

        with self._lock:
            prof = self._profiler
        if prof is None:
            raise ValueError(
                "no profiler on this node (send profile-start first, "
                "or attach one in-process)"
            )
        if stop:
            prof.stop()
        snap = prof.snapshot()
        return {
            "node_id": self.node_id,
            "role": self.role,
            "pid": os.getpid(),
            "profile": snap,
            "budget": host_budget(snap),
            "folded": folded_from_snapshot(snap),
        }

    def device_trace(self, out_dir: str,
                     duration_s: float = 3.0,
                     force: bool = False) -> dict:
        """Arm an on-demand `jax.profiler.trace` device capture into
        `out_dir` for `duration_s` (the `device-trace` command body).
        Guarded off-TPU: without a TPU backend (or `force`) it answers
        `{"ok": False, "skipped": ...}` instead of spinning up a
        capture nobody asked to pay for — the command is safe to
        broadcast across a mixed fleet."""
        if not out_dir:
            raise ValueError("device-trace needs a 'dir' to write to")
        try:
            import jax
        except ImportError as e:  # jax-less box: obs/ stays stdlib
            return {"ok": False,
                    "skipped": f"jax unavailable: {type(e).__name__}"}
        backend = jax.default_backend()
        if backend != "tpu" and not force:
            return {"ok": False, "backend": backend,
                    "skipped": f"device trace requires a TPU backend "
                               f"(have {backend!r}); pass force to "
                               f"capture anyway"}
        with self._lock:
            t = self._device_trace_thread
            if t is not None and t.is_alive():
                return {"ok": False, "skipped": "capture in progress"}

            def run():
                with jax.profiler.trace(str(out_dir)):
                    time.sleep(float(duration_s))

            t = threading.Thread(
                target=run,
                name=f"obs-device-trace-{self.node_id}",
                daemon=True,
            )
            self._device_trace_thread = t
        t.start()
        return {"ok": True, "dir": str(out_dir),
                "duration_s": float(duration_s), "backend": backend}

    # ------------------------------------------------------------ serve

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
            try:
                conn, _addr = self._sock.accept()
            except (TimeoutError, socket.timeout):
                continue  # the periodic stop-flag check
            except OSError:
                with self._lock:
                    stopping = self._stop
                if stopping:
                    return
                continue
            conn.settimeout(self.io_timeout_s)
            with self._lock:
                if self._stop:
                    conn.close()
                    return
                cid = self._conn_seq
                self._conn_seq += 1
                self._conns[cid] = conn
                t = threading.Thread(
                    target=self._serve_conn, args=(cid, conn),
                    name=f"obs-export-conn-{self.node_id}-{cid}",
                    daemon=True,
                )
                self._threads.append(t)
                self._threads = [x for x in self._threads
                                 if x.is_alive() or not x.ident]
            t.start()

    def _serve_conn(self, cid: int, conn: socket.socket) -> None:
        try:
            while True:
                with self._lock:
                    if self._stop:
                        return
                try:
                    req = recv_frame(conn)
                except ExportError:
                    return  # scraper went away; it re-asks on reconnect
                try:
                    payload = self._handle(req)
                except Exception as e:
                    # answered, never swallowed: the failure is
                    # counted/logged and the scraper sees it as a
                    # typed JSON error document
                    self._record_failure(e, cid)
                    payload = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode()
                send_frame(conn, payload)
        except ExportError:
            return
        finally:
            with self._lock:
                self._conns.pop(cid, None)
            try:
                conn.close()
            except OSError:
                pass

    def _record_failure(self, exc: Exception, cid: int) -> None:
        """Count + log a scrape-handling failure (the sanctioned
        worker-exception path: the error is also RETURNED to the
        scraper as a typed JSON document by the caller)."""
        with self._lock:
            self._scrape_errors += 1
        logger.exception("obs exporter %s: scrape failed on conn %d",
                         self.node_id, cid)

    def _handle(self, req: bytes) -> bytes:
        msg = json.loads(req.decode("utf-8"))
        cmd = msg.get("cmd")
        if cmd == "scrape":
            doc = self.scrape_doc(since=int(msg.get("since", 0)))
            with self._lock:
                self._scrapes += 1
        elif cmd == "profile-start":
            doc = self.profile_start(hz=msg.get("hz"),
                                     max_stacks=msg.get("max_stacks"))
        elif cmd == "profile-stop":
            doc = self.profile_stop()
        elif cmd == "profile-fetch":
            doc = self.profile_fetch(stop=bool(msg.get("stop")))
        elif cmd == "device-trace":
            doc = self.device_trace(
                msg.get("dir"),
                duration_s=float(msg.get("duration_s", 3.0)),
                force=bool(msg.get("force")),
            )
        else:
            raise ValueError(f"unknown command {cmd!r}")
        return json.dumps(doc).encode()

    def scrape_doc(self, since: int = 0) -> dict:
        """One scrape document (also callable in-process — the
        collector's loopback fast path and the tests' ground truth)."""
        seq, events = self._tracer.events_since(since)
        stats: dict[str, object] = {}
        with self._lock:
            fns = list(self._stats_fns.items())
        for name, fn in fns:
            try:
                stats[name] = fn()
            # the failure IS recorded — into the scrape document the
            # caller returns to the scraper, keyed under the sick
            # provider's name — so nothing is swallowed; the usual
            # future/health sinks do not exist on a scrape path
            # nrlint: disable=swallowed-worker-exception
            except Exception as e:
                stats[name] = {"error": f"{type(e).__name__}: {e}"}
        return {
            "node_id": self.node_id,
            "role": self.role,
            "pid": os.getpid(),
            # wall clock as the CROSS-PROCESS correlation stamp: the
            # collector differences it against its own wall clock at
            # receive time to estimate a per-node offset; monotonic
            # stamps never compare across processes
            "now_ts": time.time(),  # nrlint: disable=wall-clock-time — cross-process correlation field (module docstring)
            "now_mono": time.monotonic(),
            "seq": seq,
            "metrics": self._registry.snapshot(),
            "stats": stats,
            "events": events,
        }


# ==========================================================================
# client
# ==========================================================================


def request(host: str, port: int, msg: dict,
            timeout_s: float = 5.0) -> dict:
    """One framed JSON command round-trip against an exporter. Raises
    `ExportError` on any transport failure and `RuntimeError` on a
    server-side error document."""
    try:
        sock = socket.create_connection((host, int(port)),
                                        timeout=timeout_s)
    except OSError as e:
        raise ExportError(
            f"cannot connect to exporter {host}:{port}: {e}"
        ) from e
    try:
        sock.settimeout(timeout_s)
        send_frame(sock, json.dumps(msg).encode())
        doc = json.loads(recv_frame(sock).decode("utf-8"))
    finally:
        try:
            sock.close()
        except OSError:
            pass
    if "error" in doc and "node_id" not in doc:
        raise RuntimeError(f"exporter error: {doc['error']}")
    return doc


def scrape(host: str, port: int, since: int = 0,
           timeout_s: float = 5.0) -> dict:
    """One scrape round-trip (see `request` for the error contract)."""
    return request(host, port,
                   {"cmd": "scrape", "since": int(since)},
                   timeout_s=timeout_s)


def profile_start(host: str, port: int, hz: float | None = None,
                  max_stacks: int | None = None,
                  timeout_s: float = 5.0) -> dict:
    """Start the remote node's sampling profiler (`obs/profile.py`)."""
    msg: dict = {"cmd": "profile-start"}
    if hz is not None:
        msg["hz"] = float(hz)
    if max_stacks is not None:
        msg["max_stacks"] = int(max_stacks)
    return request(host, port, msg, timeout_s=timeout_s)


def profile_stop(host: str, port: int,
                 timeout_s: float = 5.0) -> dict:
    """Stop the remote node's sampling profiler (aggregate survives)."""
    return request(host, port, {"cmd": "profile-stop"},
                   timeout_s=timeout_s)


def profile_fetch(host: str, port: int, stop: bool = False,
                  timeout_s: float = 10.0) -> dict:
    """Fetch the remote node's profile document (snapshot + host
    budget + folded stacks); `stop=True` halts sampling first."""
    return request(host, port,
                   {"cmd": "profile-fetch", "stop": bool(stop)},
                   timeout_s=timeout_s)


def device_trace(host: str, port: int, out_dir: str,
                 duration_s: float = 3.0, force: bool = False,
                 timeout_s: float = 5.0) -> dict:
    """Arm a `jax.profiler.trace` device capture on the remote node
    (answered as skipped off-TPU unless `force`)."""
    return request(host, port,
                   {"cmd": "device-trace", "dir": str(out_dir),
                    "duration_s": float(duration_s),
                    "force": bool(force)},
                   timeout_s=timeout_s)


# ==========================================================================
# Prometheus-style text exposition
# ==========================================================================


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() else "_")
    s = "".join(out)
    return s if s[:1].isalpha() else f"m_{s}"


def _prom_escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def to_prometheus(doc: dict) -> str:
    """Render a scrape document as Prometheus text exposition. Every
    series carries the node's identity labels; histograms expose
    `_count`/`_sum` plus the snapshot's precomputed quantiles (the
    summary shape — the registry keeps fixed buckets internally but
    snapshots percentile estimates, `obs/metrics.py`)."""
    labels = (f'node="{_prom_escape(doc.get("node_id", "?"))}",'
              f'role="{_prom_escape(doc.get("role", "?"))}"')
    lines = [
        f'# scrape of node_id={doc.get("node_id", "?")} '
        f'role={doc.get("role", "?")} pid={doc.get("pid", "?")}',
    ]
    for name, val in sorted((doc.get("metrics") or {}).items()):
        pname = "nr_tpu_" + _prom_name(name)
        if isinstance(val, dict):  # histogram snapshot
            lines.append(f"# TYPE {pname} summary")
            lines.append(
                f'{pname}_count{{{labels}}} {int(val.get("count", 0))}'
            )
            lines.append(
                f'{pname}_sum{{{labels}}} {float(val.get("sum", 0.0))}'
            )
            for q, key in (("0.5", "p50"), ("0.95", "p95"),
                           ("0.99", "p99")):
                if key in val:
                    lines.append(
                        f'{pname}{{{labels},quantile="{q}"}} '
                        f'{float(val[key])}'
                    )
        else:
            # registry counters snapshot as int, gauges as float —
            # a distinction JSON round-trips faithfully
            kind = "gauge" if isinstance(val, float) else "counter"
            lines.append(f"# TYPE {pname} {kind}")
            lines.append(f"{pname}{{{labels}}} {val}")
    lines.append(
        f'nr_tpu_trace_events_total{{{labels}}} '
        f'{int(doc.get("seq", 0))}'
    )
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.obs.export",
        description="Scrape a MetricsExporter and print its state.",
    )
    p.add_argument("--scrape", required=True, metavar="HOST:PORT",
                   help="exporter address to scrape once")
    p.add_argument("--json", action="store_true",
                   help="print the raw scrape document instead of "
                        "Prometheus text exposition")
    p.add_argument("--timeout", type=float, default=5.0)
    args = p.parse_args(argv)
    host, port = args.scrape.rsplit(":", 1)
    try:
        doc = scrape(host, int(port), timeout_s=args.timeout)
    except (ExportError, RuntimeError, ValueError) as e:
        print(f"# scrape failed: {e}", file=sys.stderr)
        return 1
    if args.json:
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(to_prometheus(doc))
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
