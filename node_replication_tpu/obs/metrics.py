"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The metrics half of the observability layer (the trace/metrics split
production runtimes use; the flight recorder in `obs/recorder.py` is the
trace half). The reference's only numeric observability is the harness's
per-second throughput counters (`benches/mkbench.rs:755-761`); this module
generalizes that into named process-wide instruments the runtime hot paths
update:

- `Counter` — monotonically increasing int (`inc`).
- `Gauge` — last-write-wins float (`set`).
- `Histogram` — fixed exponential buckets with Prometheus-style
  interpolated percentiles (`observe`, `percentile`).

Cost contract: every instrument checks ONE flag (`registry.enabled`)
before touching its lock, so a disabled registry costs one attribute load
+ one branch per call site and allocates nothing — cheap enough to leave
instrumentation compiled into `_exec_round`/`combine` unconditionally.
Instrument handles are created once (at wrapper construction or module
import) and cached; `counter()`/`gauge()`/`histogram()` are get-or-create
and thread-safe.

Enable with `NR_TPU_METRICS=1` or `get_registry().enable()`. `snapshot()`
returns a plain-dict view suitable for JSON (`NodeReplicated.snapshot()`
and `MultiLogReplicated.snapshot()` embed it).
"""

from __future__ import annotations

import bisect
import os
import threading

from node_replication_tpu.analysis.locks import make_lock

# Default histogram buckets for durations in seconds: 1us .. ~100s,
# roughly x4 per step (14 buckets; small enough to snapshot cheaply).
DURATION_BUCKETS_S = tuple(1e-6 * 4**i for i in range(14))

# Default buckets for counts (batch sizes, rounds): powers of two 1 .. 64Ki.
COUNT_BUCKETS = tuple(float(1 << i) for i in range(17))


class Counter:
    """Monotonic counter. `inc` is one branch when the registry is off."""

    __slots__ = ("name", "_reg", "_lock", "_value")

    def __init__(self, name: str, reg: "MetricsRegistry"):
        self.name = name
        self._reg = reg
        self._lock = make_lock("Counter._lock")
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        # nrcheck: unshared — single int load, GIL-atomic; approximate
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0

    def _snapshot(self):
        with self._lock:  # scrape path: exact, not approximate
            return self._value


class Gauge:
    """Last-write-wins value. `set` is one branch when the registry is off."""

    __slots__ = ("name", "_reg", "_value")

    def __init__(self, name: str, reg: "MetricsRegistry"):
        self.name = name
        self._reg = reg
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._reg.enabled:
            return
        self._value = float(v)  # single store: atomic under the GIL

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        self._value = 0.0

    def _snapshot(self):
        return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated percentile estimates.

    `buckets` are ascending upper bounds; observations above the last
    bound land in a +Inf overflow bucket. `percentile(p)` walks the
    cumulative counts and linearly interpolates within the winning bucket
    (the `histogram_quantile` estimator), clamped to the observed
    min/max so small-sample estimates never leave the data's range.
    """

    __slots__ = ("name", "_reg", "_lock", "_bounds", "_counts", "_count",
                 "_sum", "_min", "_max")

    def __init__(self, name: str, reg: "MetricsRegistry",
                 buckets=DURATION_BUCKETS_S):
        self.name = name
        self._reg = reg
        self._lock = make_lock("Histogram._lock")
        self._bounds = tuple(float(b) for b in buckets)
        if list(self._bounds) != sorted(set(self._bounds)):
            raise ValueError(f"{name}: bucket bounds must strictly ascend")
        self._counts = [0] * (len(self._bounds) + 1)  # + overflow
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, v: float) -> None:
        if not self._reg.enabled:
            return
        v = float(v)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        # nrcheck: unshared — single int load, GIL-atomic; approximate
        return self._count

    @property
    def sum(self) -> float:
        # nrcheck: unshared — single float load, GIL-atomic; approximate
        return self._sum

    def percentile(self, p: float) -> float:
        """Estimate the p-quantile (p in [0, 1]) from the bucket counts.

        Reads are deliberately lock-free: percentile() is an
        approximate estimator and may tear against a concurrent
        `observe`; the exact path is `_snapshot`, which holds the
        lock around the same arithmetic (`_snapshot_locked`).
        """
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"percentile {p} outside [0, 1]")
        if self._count == 0:  # nrcheck: unshared — approximate read
            return 0.0
        rank = p * self._count  # nrcheck: unshared — approximate read
        cum = 0
        # nrcheck: unshared — approximate read
        for i, c in enumerate(self._counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = (self._bounds[i] if i < len(self._bounds)
                      else self._max)  # nrcheck: unshared — approx read
                frac = (rank - cum) / c
                est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                # nrcheck: unshared — approximate read
                return max(self._min, min(self._max, est))
            cum += c
        return self._max  # nrcheck: unshared — approximate read

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = float("inf")
            self._max = float("-inf")

    def _snapshot(self):
        # scrape path: hold the lock so count/sum/percentiles agree
        # with each other (the lock-free properties may tear; an
        # exported snapshot must not)
        with self._lock:
            if self._count == 0:
                return {"count": 0, "sum": 0.0}
            return self._snapshot_locked()

    # the lock is held (`_snapshot`); percentile() reads are exact here
    # guarded-by: _lock
    def _snapshot_locked(self):
        return {
            "count": self._count,
            "sum": self._sum,
            "min": self._min,
            "max": self._max,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class MetricsRegistry:
    """Named instruments behind one process-wide enable flag."""

    def __init__(self, enabled: bool = False):
        # nrcheck: lock-order MetricsRegistry._lock -> Counter._lock — reset() zeroes instruments under the registry lock
        # nrcheck: lock-order MetricsRegistry._lock -> Histogram._lock — reset() zeroes instruments under the registry lock
        self._lock = make_lock("MetricsRegistry._lock")
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self.enabled = bool(enabled)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def _get_or_create(self, name: str, factory, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(
            name, lambda: Counter(name, self), Counter
        )

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, self), Gauge)

    def histogram(self, name: str,
                  buckets=DURATION_BUCKETS_S) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, self, buckets), Histogram
        )

    def remove(self, name: str, instrument=None) -> bool:
        """Unregister an instrument so it stops appearing in
        `snapshot()` (and therefore in exporter scrapes). The retire
        path for per-entity instruments whose entity is gone — e.g. a
        serve replica's `serve.queue_depth.r<rid>` gauge after
        failover retires the replica (`ServeFrontend._fail_replica`);
        without removal every replica ever served haunts the registry
        forever. A still-cached handle keeps working but writes to a
        detached instrument; re-creating the name (`gauge(...)` etc.)
        registers a fresh one.

        Pass `instrument` to make the removal OWNED: the name is only
        dropped when the registered instrument IS that handle. Names
        are get-or-create and process-global, so two owners (two
        frontends serving the same rid in one process) can hold the
        same gauge — an unconditional remove by the first to retire
        would silently detach the survivor's live instrument.
        Returns True when something was removed."""
        with self._lock:
            cur = self._metrics.get(name)
            if cur is None:
                return False
            if instrument is not None and cur is not instrument:
                return False
            del self._metrics[name]
            return True

    def names(self) -> list[str]:
        """Registered instrument names (sorted; includes untouched
        instruments `snapshot()` would skip)."""
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Zero every instrument (names and handles stay registered, so
        cached call-site handles remain valid)."""
        with self._lock:
            for m in self._metrics.values():
                m._reset()

    def snapshot(self) -> dict:
        """Plain-dict view of every non-empty instrument (JSON-safe)."""
        with self._lock:
            items = list(self._metrics.items())
        out = {}
        for name, m in sorted(items):
            v = m._snapshot()
            if v == 0 or v == 0.0 or (isinstance(v, dict)
                                      and not v.get("count")):
                continue  # keep snapshots readable: skip untouched
            out[name] = v
        return out


_registry = MetricsRegistry(
    enabled=os.environ.get("NR_TPU_METRICS", "") == "1"
)


def get_registry() -> MetricsRegistry:
    return _registry
