"""Trace-report CLI: summarize a flight-recorder JSONL trace.

    python -m node_replication_tpu.obs.report trace.jsonl [--json]

Sections:

- **events** — per-event-name counts.
- **spans** — p50/p95/p99/max durations for every event that carries
  `duration_s` (append, combine-replay, exec-round, checkpoint-*, …),
  with a `fenced` marker when the spans were fence-accurate
  (NR_TPU_TRACE_FENCE=1; an unfenced span on the tunneled TPU platform
  measures dispatch rate, not execution — BENCH_NOTES.md).
- **throughput** — ops/sec timeline from `throughput` events (the
  harness's per-second capture, `benches/mkbench.rs:755-761`); when a
  trace has none (e.g. one recorded from examples/nr_hashmap.py), the
  timeline is derived from `append` events (appended ops bucketed by
  second), so any runtime trace yields a timeline.
- **stalls** — watchdog report: stall sites grouped by (where, log),
  with fire counts, max fruitless rounds, and the dormant replicas seen.
- **serve** (when the trace has `serve-*` events, `serve/frontend.py`)
  — queue-depth timeline (max observed depth per second), batch-size
  histogram (power-of-two buckets), and the admission-control counts:
  shed (`Overloaded`) and deadline-missed requests.
- **fault** (when the trace has `fault-*` / `serve-rehome` events,
  `fault/`) — per-replica lifecycle-transition timeline
  (HEALTHY -> SUSPECT -> QUARANTINED -> REPAIRING -> HEALTHY),
  repair-duration histogram (power-of-two millisecond buckets) with
  p50/p95, and the counts the chaos gates watch: injected faults,
  quarantines, completed repairs, re-homed requests.
- **durability** (when the trace has `wal-*` / `recovery*` /
  `durable-snapshot` events, `durable/`) — fsync count and latency
  p50/p95/p99 (`wal-sync` spans), torn-tail truncations, segment
  reclamations, snapshots taken, and the recovery timeline: every
  durability-plane event in order with its `t+` offset, so a
  crash-restart reads as a story (open → truncate → replay → attach).
- **replication** (when the trace has `repl-*` events, `repl/`) —
  shipped vs applied record/op counts, the delivery edge cases the
  feed defines (duplicates skipped, gaps, zombie-fenced records,
  stale reads), an apply-lag timeline (max positions behind the feed
  tail per second, from `repl-apply` events), and every promotion
  with its measured detect/promote/RTO split (`repl-promote` /
  `repl-rto`).
- **fleet** (when the trace is a COLLECTOR merge, `obs/collect.py`:
  events stamped with `node_id`, plus `fleet-scrape` summaries) —
  the node inventory (role, lag, last scrape), and per-record
  CROSS-PROCESS hop timelines: events joined on the record's log
  position `pos` (submit→append→wal-sync→ship→wire→relay-forward→
  apply, with ack closing the loop), ordered causally and placed on
  the collector's timeline via each event's `t_fleet` stamp — NEVER
  by raw `mono`, which does not compare across processes — with
  per-edge latency p50/p95 aggregated over every sampled record.

Pure stdlib on purpose: on a machine without jax, copy this file next
to the trace and run it directly (`python report.py trace.jsonl`) —
only the `-m` spelling pulls in the package __init__ (and with it jax).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter, defaultdict


def _percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile on raw values (exact, not bucketed —
    the trace carries every duration)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.1f}us"


def load_events(path: str) -> list[dict]:
    events = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"# skipping malformed line {i}", file=sys.stderr)
    return events


def _event_time(e: dict, mono0: float | None,
                ts0: float | None) -> float:
    """Seconds since trace start. Monotonic and wall-clock stamps live
    on different epochs, so each is measured against its OWN baseline —
    mixing them (e.g. a legacy ts-only event next to upgraded events in
    an appended-to trace file) would produce garbage offsets."""
    if "mono" in e and mono0 is not None:
        return float(e["mono"]) - mono0
    if "ts" in e and ts0 is not None:
        return float(e["ts"]) - ts0
    return 0.0


# per-record hop chain: causal rank of each hop event in a record's
# submit→ack life. `serve-batch` expands into BOTH ends (submit at
# rank 0 reconstructed from its delay fields, ack at the top); ties
# within a rank order by fleet time.
_HOP_RANK = {
    "submit": 0,
    "append": 1,        # `append` / `fused-round` events (pos0)
    "wal-sync": 2,      # first sync whose `synced_to` covers pos
    "ship": 3,          # repl-ship
    "wire": 4,          # transport-poll (record served downstream)
    "relay-forward": 5,
    "apply": 6,         # repl-apply
    "ack": 7,           # serve-batch (futures resolved)
}
_HOP_OF_EVENT = {
    "append": "append",
    "fused-round": "append",
    "repl-ship": "ship",
    "transport-poll": "wire",
    "relay-forward": "relay-forward",
    "repl-apply": "apply",
}


def _analyze_fleet(events: list[dict]) -> dict | None:
    """The cross-process section: only a COLLECTOR-merged trace
    (`obs/collect.py`) has it — detected by `node_id`-stamped events
    and/or `fleet-scrape` summaries. Joins per-record hop events on
    the record's `pos` across processes; orders them by causal hop
    rank, then by the collector-aligned `t_fleet` stamp (raw `mono`
    never compares across processes)."""
    scrapes = [e for e in events if e.get("event") == "fleet-scrape"]
    tagged = [e for e in events if e.get("node_id") is not None]
    if not scrapes and not tagged:
        return None

    def _t(e):
        v = e.get("t_fleet", e.get("ts"))
        return float(v) if v is not None else None

    # ---- node inventory: the LAST scrape summary per node ----------
    nodes: dict[str, dict] = {}
    for e in scrapes:
        nid = str(e.get("node_id", "?"))
        metrics = e.get("metrics") or {}
        stats = e.get("stats") or {}

        def _num(d, *path, default=None):
            cur = d
            for k in path:
                if not isinstance(cur, dict) or k not in cur:
                    return default
                cur = cur[k]
            return cur if isinstance(cur, (int, float)) else default

        nodes[nid] = {
            "node_id": nid,
            "role": str(e.get("role", "?")),
            "last_t": e.get("t"),
            "applied": _num(stats, "follower", "applied",
                            default=_num(stats, "relay", "cursor")),
            "ship_lag": _num(metrics, "repl.ship_lag_pos"),
            "apply_lag": _num(metrics, "repl.apply_lag_pos"),
            "relay_lag": _num(metrics, "repl.relay.lag_pos"),
            "completed": _num(stats, "serve", "completed"),
            "queued": _num(stats, "serve", "queued"),
            "shed": _num(stats, "serve", "shed"),
            "scrapes": nodes.get(nid, {}).get("scrapes", 0) + 1,
        }
    for e in tagged:  # nodes that emitted events but no summary yet
        nid = str(e["node_id"])
        if nid not in nodes:
            nodes[nid] = {"node_id": nid,
                          "role": str(e.get("role", "?")),
                          "scrapes": 0}

    # ---- per-record hop chains keyed by pos ------------------------
    chains: dict[int, list] = defaultdict(list)
    syncs_by_node: dict[str, list] = defaultdict(list)
    for e in tagged:
        name = e.get("event")
        nid = str(e["node_id"])
        t = _t(e)
        if t is None:
            continue
        if name == "wal-sync":
            syncs_by_node[nid].append(
                (int(e.get("synced_to", -1)), t)
            )
            continue
        if name == "serve-batch":
            pos = e.get("pos")
            if pos is None:
                continue
            pos = int(pos)
            chains[pos].append((_HOP_RANK["ack"], "ack", nid, t))
            # the submit stamp is reconstructable: the ack event
            # carries queue delay (admission→assembly) and round
            # duration (assembly→ack)
            back = (float(e.get("queue_delay_s", 0.0))
                    + float(e.get("duration_s", 0.0)))
            chains[pos].append(
                (_HOP_RANK["submit"], "submit", nid, t - back)
            )
            continue
        hop = _HOP_OF_EVENT.get(name)
        if hop is None:
            continue
        pos = e.get("pos", e.get("pos0"))
        if pos is None:
            continue
        chains[int(pos)].append((_HOP_RANK[hop], hop, nid, t))
    # wal-sync joins by coverage: the first sync on the appending
    # node whose durable boundary passed the record's position
    for nid in syncs_by_node:
        syncs_by_node[nid].sort()
    for pos, hops in chains.items():
        for nid in {n for _, h, n, _ in hops if h == "append"}:
            for synced_to, t in syncs_by_node.get(nid, ()):
                if synced_to > pos:
                    hops.append(
                        (_HOP_RANK["wal-sync"], "wal-sync", nid, t)
                    )
                    break

    # ---- order, dedup, measure edges -------------------------------
    timelines = []
    edge_samples: dict[str, list] = defaultdict(list)
    for pos in sorted(chains):
        raw = sorted(chains[pos])
        # one entry per (hop, node): re-served records (reconnects,
        # duplicate delivery) re-emit hops; the FIRST occurrence is
        # the causal one
        seen = set()
        hops = []
        for rank, hop, nid, t in raw:
            if (hop, nid) in seen:
                continue
            seen.add((hop, nid))
            hops.append({"hop": hop, "node": nid,
                         "t": round(t, 6)})
        if not hops:
            continue
        # origin discipline: followers replay records through the
        # SAME combiner protocol the primary used, so every follower
        # re-emits `append`/`wal-sync` for the record — those are
        # apply-side details (already narrated by the apply hop), not
        # the record's origin. Keep append/wal-sync only on the node
        # that served the submit/ack.
        origin = next((h["node"] for h in hops
                       if h["hop"] in ("submit", "ack")), None)
        if origin is not None:
            hops = [h for h in hops
                    if h["hop"] not in ("append", "wal-sync")
                    or h["node"] == origin]
        procs = {h["node"] for h in hops}
        names = [h["hop"] for h in hops]
        complete = "submit" in names and "ack" in names
        t0 = hops[0]["t"]
        # per-edge samples over the CAUSAL path only (submit→...→
        # apply), between the EARLIEST occurrence of each hop — a hop
        # can occur on several nodes (two relays forwarding, N
        # followers applying, a record re-served over a reconnect)
        # and pairing across those occurrences would manufacture
        # negative "latencies". Earliest by TIME, not list order: the
        # hop list is (rank, node)-sorted, so "first in list" would
        # pick the alphabetically-first node. ack is concurrent with
        # the downstream hops (ship-before-ack puts it after ship but
        # racing the relays), so the client-visible edge is measured
        # separately as submit->ack.
        first: dict[str, float] = {}
        for h in hops:
            if h["hop"] == "ack":
                continue
            cur = first.get(h["hop"])
            if cur is None or h["t"] < cur:
                first[h["hop"]] = h["t"]
        labels = sorted(first, key=lambda k: _HOP_RANK[k])
        for a, b in zip(labels, labels[1:]):
            edge_samples[f"{a}->{b}"].append(first[b] - first[a])
        if complete:
            t_sub = min(h["t"] for h in hops
                        if h["hop"] == "submit")
            t_ack = max(h["t"] for h in hops if h["hop"] == "ack")
            edge_samples["submit->ack"].append(t_ack - t_sub)
        timelines.append({
            "pos": pos,
            "processes": len(procs),
            "complete": complete,
            "hops": [{**h, "t": round(h["t"] - t0, 6)}
                     for h in hops],
        })
    edges = {}
    for label, vals in sorted(edge_samples.items()):
        vals = sorted(vals)
        edges[label] = {
            "count": len(vals),
            "p50_s": _percentile(vals, 0.50),
            "p95_s": _percentile(vals, 0.95),
            "max_s": vals[-1],
        }
    complete_multi = [
        tl for tl in timelines
        if tl["complete"] and tl["processes"] >= 3
    ]
    return {
        "nodes": [nodes[k] for k in sorted(nodes)],
        "scrapes": len(scrapes),
        "scrape_errors": sum(
            1 for e in events
            if e.get("event") == "fleet-scrape-error"
        ),
        "records": len(timelines),
        "complete_records": sum(
            1 for tl in timelines if tl["complete"]
        ),
        "complete_multiprocess_records": len(complete_multi),
        "edges": edges,
        # the renderable exemplars: widest-spanning complete chains
        # first (the --json consumer gets every chain's summary via
        # records/edges; full per-hop dumps stay bounded)
        "timelines": sorted(
            timelines,
            key=lambda tl: (-int(tl["complete"]), -tl["processes"],
                            tl["pos"]),
        )[:8],
    }


def analyze(events: list[dict]) -> dict:
    """Reduce a trace to the report's structured form (the --json
    payload; the text renderer consumes the same dict)."""
    counts = Counter(e.get("event", "?") for e in events)

    spans: dict[str, list[float]] = defaultdict(list)
    fenced: dict[str, bool] = {}
    for e in events:
        if "duration_s" in e:
            name = e.get("event", "?")
            spans[name].append(float(e["duration_s"]))
            fenced[name] = fenced.get(name, True) and bool(
                e.get("fenced", False)
            )
    span_stats = {}
    for name, vals in spans.items():
        vals = sorted(vals)
        span_stats[name] = {
            "count": len(vals),
            "total_s": sum(vals),
            "p50_s": _percentile(vals, 0.50),
            "p95_s": _percentile(vals, 0.95),
            "p99_s": _percentile(vals, 0.99),
            "max_s": vals[-1],
            "fenced": fenced[name],
        }

    # throughput timeline: explicit per-second samples, else derive one
    # from append events so every runtime trace has a timeline
    monos = [float(e["mono"]) for e in events if "mono" in e]
    tss = [float(e["ts"]) for e in events if "ts" in e]
    mono0 = min(monos) if monos else None
    ts0 = min(tss) if tss else None
    timeline: dict[int, int] = defaultdict(int)
    source = None
    tp = [e for e in events if e.get("event") == "throughput"]
    if tp:
        source = "throughput"
        for e in tp:
            sec = e.get("second")
            if sec is None or sec < 0:
                sec = int(_event_time(e, mono0, ts0))
            timeline[int(sec)] += int(e.get("ops", 0))
    else:
        appends = [e for e in events
                   if e.get("event") == "append" and "n" in e]
        if appends:
            source = "append"
            for e in appends:
                timeline[int(_event_time(e, mono0, ts0))] += int(e["n"])

    stalls: dict[tuple, dict] = {}
    for e in events:
        if e.get("event") != "watchdog":
            continue
        key = (e.get("where", "?"), e.get("log", None))
        s = stalls.setdefault(
            key, {"count": 0, "max_rounds": 0, "dormant": set(),
                  "last_ltail": None, "last_tail": None}
        )
        s["count"] += 1
        s["max_rounds"] = max(s["max_rounds"], int(e.get("rounds", 0)))
        if "dormant" in e:
            s["dormant"].add(int(e["dormant"]))
        s["last_ltail"] = e.get("ltail", s["last_ltail"])
        s["last_tail"] = e.get("tail", s["last_tail"])

    # serve section: batch shape + admission control from serve-*
    # events, incl. the overload plane (adaptive limit, priority
    # sheds/evictions, brownout, per-cause client retries, breaker)
    serve = None
    batches = [e for e in events if e.get("event") == "serve-batch"]
    assembles = [e for e in events
                 if e.get("event") == "serve-assemble"]
    sheds = [e for e in events if e.get("event") == "serve-shed"]
    misses = [e for e in events
              if e.get("event") == "serve-deadline-miss"]
    evicts = [e for e in events if e.get("event") == "serve-evict"]
    retries = [e for e in events if e.get("event") == "serve-retry"]
    limits = [e for e in events
              if e.get("event") == "serve-admit-limit"]
    brownouts = [e for e in events
                 if e.get("event") == "serve-brownout"]
    brownout_reads = [e for e in events
                      if e.get("event") == "serve-brownout-read"]
    circuits = [e for e in events if e.get("event") == "serve-circuit"]
    if (batches or assembles or sheds or misses or evicts or retries
            or limits or brownouts or circuits):
        sizes = sorted(int(e.get("n", 0)) for e in batches)
        size_hist: dict[int, int] = defaultdict(int)
        for n in sizes:
            # power-of-two upper-bound buckets: 1, 2, 4, 8, ...
            size_hist[1 << max(0, n - 1).bit_length()] += 1
        qdepth: dict[int, int] = {}
        for e in batches:
            sec = int(_event_time(e, mono0, ts0))
            qdepth[sec] = max(qdepth.get(sec, 0),
                              int(e.get("queue_depth", 0)))
        shed_by_prio: dict[str, int] = defaultdict(int)
        for e in sheds:
            shed_by_prio[str(e.get("prio", "?"))] += 1
        retry_by_cause: dict[str, int] = defaultdict(int)
        for e in retries:
            retry_by_cause[str(e.get("cause", "?"))] += 1
        # adaptive-admission timeline: min limit observed per second
        # (the controller's most constrained moment of that second)
        limit_tl: dict[int, int] = {}
        for e in limits:
            sec = int(_event_time(e, mono0, ts0))
            lim = int(e.get("limit", 0))
            limit_tl[sec] = min(limit_tl.get(sec, 1 << 30), lim)
        # pipelined-serving overlap picture (ISSUE 14): the
        # serve-batch span is the round's device+completion half, the
        # serve-assemble event the host assembly half — their busy
        # fractions over the serve window show how much of the host
        # work the pipeline actually hid (serial traces have no
        # serve-assemble events and skip the line)
        pipe = None
        if assembles:
            times = [_event_time(e, mono0, ts0)
                     for e in batches + assembles]
            window = max(times) - min(times) if len(times) > 1 else 0.0
            device_s = sum(
                float(e.get("duration_s", 0.0)) for e in batches
            )
            asm_s = sum(
                float(e.get("duration_s", 0.0)) for e in assembles
            )
            pipe = {
                "assemble_events": len(assembles),
                "assembly_busy_s": asm_s,
                "device_busy_s": device_s,
                "window_s": window,
                "assembly_busy_frac": (
                    asm_s / window if window > 0 else 0.0
                ),
                "device_busy_frac": (
                    device_s / window if window > 0 else 0.0
                ),
            }
        serve = {
            "batches": len(batches),
            "ops": sum(sizes),
            "late_success": sum(
                int(e.get("late_success", 0) or 0) for e in batches
            ),
            "pipeline": pipe,
            "p50_batch": _percentile([float(s) for s in sizes], 0.50),
            "max_batch": sizes[-1] if sizes else 0,
            "batch_size_hist": dict(sorted(size_hist.items())),
            "queue_depth_timeline": dict(sorted(qdepth.items())),
            "shed": len(sheds),
            "shed_by_priority": dict(sorted(shed_by_prio.items())),
            "evicted": len(evicts),
            "deadline_miss": sum(int(e.get("n", 1)) for e in misses),
            "swept_at_admission": sum(
                int(e.get("n", 1)) for e in misses
                if e.get("swept")
            ),
            "retries_by_cause": dict(sorted(retry_by_cause.items())),
            "admit_limit_timeline": dict(sorted(limit_tl.items())),
            "brownout_transitions": [
                {"t": round(_event_time(e, mono0, ts0), 3),
                 "on": int(e.get("on", 0))}
                for e in brownouts
            ],
            "brownout_reads": len(brownout_reads),
            "max_brownout_lag": max(
                (int(e.get("lag", 0)) for e in brownout_reads),
                default=0,
            ),
            "circuit_transitions": sum(
                1 for e in circuits if e.get("state") == "open"
            ),
        }

    # fault section: lifecycle transitions + repair latencies from
    # fault-* events (fault/health.py, fault/repair.py)
    fault = None
    transitions = [e for e in events
                   if e.get("event") == "fault-transition"]
    repairs = [e for e in events if e.get("event") == "fault-repair"]
    injects = [e for e in events if e.get("event") == "fault-inject"]
    rehomes = [e for e in events if e.get("event") == "serve-rehome"]
    if transitions or repairs or injects or rehomes:
        per_rid: dict[int, list] = defaultdict(list)
        for e in transitions:
            per_rid[int(e.get("rid", -1))].append((
                round(_event_time(e, mono0, ts0), 3),
                e.get("frm", "?"), e.get("to", "?"),
            ))
        durs = sorted(float(e.get("duration_s", 0.0)) for e in repairs)
        repair_hist: dict[int, int] = defaultdict(int)
        for d in durs:
            # power-of-two millisecond upper-bound buckets: 1, 2, 4...
            ms = max(1, int(d * 1e3))
            repair_hist[1 << max(0, ms - 1).bit_length()] += 1
        fault = {
            "injected": len(injects),
            "quarantines": sum(
                1 for e in transitions if e.get("to") == "quarantined"
            ),
            "repairs": len(repairs),
            "rehomed": sum(int(e.get("n", 1)) for e in rehomes),
            "repair_p50_s": _percentile(durs, 0.50),
            "repair_p95_s": _percentile(durs, 0.95),
            "repair_max_s": durs[-1] if durs else 0.0,
            "repair_hist_ms": dict(sorted(repair_hist.items())),
            "timeline": {
                rid: trs for rid, trs in sorted(per_rid.items())
            },
        }

    # durability section: fsync shape + the recovery timeline from
    # wal-*/recovery*/durable-snapshot events (durable/)
    durability = None
    _DUR_EVENTS = ("wal-open", "wal-truncate", "wal-sync", "wal-attach",
                   "wal-reclaim", "durable-snapshot", "recovery",
                   "recovery-done")
    dur_evts = [e for e in events if e.get("event") in _DUR_EVENTS]
    if dur_evts:
        syncs = sorted(float(e.get("duration_s", 0.0))
                       for e in dur_evts
                       if e.get("event") == "wal-sync")
        timeline_d = []
        for e in sorted(dur_evts,
                        key=lambda e: _event_time(e, mono0, ts0)):
            name = e["event"]
            if name == "wal-sync":
                continue  # histogrammed, not narrated (too many)
            detail = {k: v for k, v in e.items()
                      if k not in ("event", "ts", "mono", "tid")}
            timeline_d.append({
                "t": round(_event_time(e, mono0, ts0), 3),
                "event": name,
                **detail,
            })
        recs = [e for e in dur_evts if e.get("event") == "recovery-done"]
        durability = {
            "fsyncs": len(syncs),
            "fsync_p50_s": _percentile(syncs, 0.50),
            "fsync_p95_s": _percentile(syncs, 0.95),
            "fsync_p99_s": _percentile(syncs, 0.99),
            "truncations": sum(1 for e in dur_evts
                               if e.get("event") == "wal-truncate"),
            "reclaimed_segments": sum(
                int(e.get("deleted", 0)) for e in dur_evts
                if e.get("event") == "wal-reclaim"
            ),
            "snapshots": sum(1 for e in dur_evts
                             if e.get("event") == "durable-snapshot"),
            "recoveries": len(recs),
            "replayed_ops": sum(int(e.get("ops", 0)) for e in recs),
            "timeline": timeline_d,
        }

    # replication section: ship/apply volume, delivery edge cases,
    # apply-lag timeline, promotions with RTO split (repl/)
    repl = None
    ships = [e for e in events if e.get("event") == "repl-ship"]
    applies = [e for e in events if e.get("event") == "repl-apply"]
    repl_other = [e for e in events
                  if str(e.get("event", "")).startswith("repl-")
                  and e.get("event") not in ("repl-ship", "repl-apply")]
    # transport lane events count toward section presence too: a
    # relay-only process emits transport-*/relay-* but no repl-*
    transport_events = [e for e in events
                        if str(e.get("event", ""))
                        .startswith(("transport-", "relay-"))]
    if ships or applies or repl_other or transport_events:
        lag_tl: dict[int, int] = {}
        for e in applies:
            sec = int(_event_time(e, mono0, ts0))
            lag_tl[sec] = max(lag_tl.get(sec, 0), int(e.get("lag", 0)))
        promotions = []
        rtos = {e.get("follower"): e for e in events
                if e.get("event") == "repl-rto"}
        for e in events:
            if e.get("event") != "repl-promote":
                continue
            rto = rtos.get(e.get("name"), {})
            promotions.append({
                "t": round(_event_time(e, mono0, ts0), 3),
                "follower": e.get("name", "?"),
                "epoch": e.get("epoch"),
                "applied": e.get("applied"),
                "drained_records": e.get("drained_records", 0),
                "promote_s": float(e.get("duration_s", 0.0)),
                "detect_s": float(rto.get("detect_s", 0.0)),
                "rto_s": float(rto.get("rto_s",
                                       e.get("duration_s", 0.0))),
            })

        def _count(name):
            return sum(1 for e in repl_other if e.get("event") == name)

        # transport lane (repl/transport.py + repl/relay.py): wire
        # lifecycle, relay forwarding/fencing, snapshot bootstraps
        def _tcount(name):
            return sum(1 for e in transport_events
                       if e.get("event") == name)

        repl = {
            "shipped_records": len(ships),
            "shipped_ops": sum(int(e.get("n", 0)) for e in ships),
            "applied_records": len(applies),
            "applied_ops": sum(int(e.get("n", 0)) for e in applies),
            "duplicates": _count("repl-dup"),
            "fenced_records": _count("repl-fenced-record"),
            "fenced_publishes": _count("repl-fenced-publish"),
            "stale_reads": _count("repl-stale-read"),
            "ship_errors": _count("repl-ship-error"),
            "apply_errors": _count("repl-apply-error"),
            "fences": _count("repl-fence"),
            "transport_connects": _tcount("transport-connect"),
            "transport_reconnects": _tcount("transport-reconnect"),
            "transport_errors": _tcount("transport-error"),
            "relay_fenced": _tcount("relay-fenced"),
            "relay_errors": _tcount("relay-error"),
            "snapshots_served": _tcount("transport-snapshot-served"),
            "snapshots_fetched": _tcount("transport-snapshot-fetched"),
            "bootstraps": _count("repl-bootstrap"),
            "bootstrap_failures": _count("repl-bootstrap-failed"),
            "apply_lag_timeline": dict(sorted(lag_tl.items())),
            "promotions": promotions,
        }

    # fleet section: cross-process merge (obs/collect.py output) —
    # node inventory from fleet-scrape summaries + per-record hop
    # timelines joined on (pos, node_id)
    fleet = _analyze_fleet(events)

    # mesh section: placement, rounds by collective tier, collective
    # time, cross-device sync bytes, ring catch-up passes (parallel/)
    mesh = None
    places = [e for e in events if e.get("event") == "mesh-place"]
    mesh_rounds = [e for e in events
                   if e.get("event") == "exec-round"
                   and e.get("mesh_tier")]
    rings = [e for e in events if e.get("event") == "ring-exec"]
    if places or mesh_rounds or rings:
        by_tier: dict[str, int] = defaultdict(int)
        durs = []
        sync_bytes = 0
        for e in mesh_rounds:
            by_tier[str(e["mesh_tier"])] += 1
            durs.append(float(e.get("duration_s", 0.0)))
            sync_bytes += int(e.get("sync_bytes", 0))
        durs.sort()
        mesh = {
            "placements": [
                {"wrapper": e.get("wrapper", "?"),
                 "devices": int(e.get("devices", 0)),
                 "replicas": int(e.get("replicas", 0)),
                 "per_device": int(e.get("per_device", 0)),
                 "tier": e.get("tier", "?")}
                for e in places
            ],
            "rounds_by_tier": dict(sorted(by_tier.items())),
            "collective_time_s": sum(durs),
            "round_p50_s": _percentile(durs, 0.50),
            "round_p95_s": _percentile(durs, 0.95),
            "sync_bytes": sync_bytes,
            "ring_execs": len(rings),
            "ring_ops": sum(int(e.get("window", 0)) for e in rings),
        }

    # kernels section: fused-round launches by tier, window-size
    # histogram, per-launch duration percentiles (kernel-launch events,
    # ops/pallas_*), plus serve batches by combiner engine (the
    # serve-batch `engine` stamp) and the winner-selection verdicts
    # (fused-calibration events, core/replica._FusedTier)
    kernels = None
    klaunches = [e for e in events if e.get("event") == "kernel-launch"]
    serve_engines = [e.get("engine") for e in events
                     if e.get("event") == "serve-batch"
                     and e.get("engine")]
    cals = [e for e in events
            if e.get("event") == "fused-calibration"]
    if klaunches or serve_engines or cals:
        launch_by_tier: dict[str, int] = defaultdict(int)
        window_hist: dict[int, int] = defaultdict(int)
        kdurs = []
        for e in klaunches:
            launch_by_tier[str(e.get("tier", "?"))] += int(
                e.get("launches", 1)
            )
            window_hist[int(e.get("window", 0))] += 1
            kdurs.append(float(e.get("duration_s", 0.0)))
        kdurs.sort()
        batches_by_engine: dict[str, int] = defaultdict(int)
        for eng in serve_engines:
            batches_by_engine[str(eng)] += 1
        kernels = {
            "rounds": len(klaunches),
            "launches_by_tier": dict(sorted(launch_by_tier.items())),
            "window_hist": dict(sorted(window_hist.items())),
            "fused_ops": sum(int(e.get("count", 0)) for e in klaunches),
            "launch_p50_s": _percentile(kdurs, 0.50),
            "launch_p95_s": _percentile(kdurs, 0.95),
            "serve_batches_by_engine": dict(
                sorted(batches_by_engine.items())
            ),
            "calibrations": [
                {"winner": e.get("winner", "?"),
                 "window": int(e.get("window", 0)),
                 # mesh-aware + fence-keyed verdicts (ISSUE 15):
                 # absent on pre-mesh traces, defaults keep old
                 # artifacts renderable
                 "devices": int(e.get("devices", 1) or 1),
                 "fenced": list(e.get("fenced", []) or []),
                 "fused_s": float(e.get("fused_s", 0.0)),
                 "chain_s": float(e.get("chain_s", 0.0))}
                for e in cals
            ],
        }

    # sharding section: routing-tier map adoptions + typed refusals
    # from serve-reroute / shard-refused events (shard/router.py) —
    # the keyspace-sharded fleet's re-home + zombie-fence story
    sharding = None
    reroutes = [e for e in events if e.get("event") == "serve-reroute"]
    refusals = [e for e in events if e.get("event") == "shard-refused"]
    if reroutes or refusals:
        ref_by_shard: dict[int, int] = defaultdict(int)
        ref_by_error: dict[str, int] = defaultdict(int)
        for e in refusals:
            ref_by_shard[int(e.get("shard", -1))] += 1
            ref_by_error[str(e.get("error", "?"))] += 1
        sharding = {
            "map_adoptions": len(reroutes),
            "final_map_version": max(
                (int(e.get("map_version", 0)) for e in reroutes),
                default=0,
            ),
            "adoptions": [
                {"t": round(_event_time(e, mono0, ts0), 3),
                 "reason": e.get("reason", "?"),
                 "from_version": int(e.get("from_version", 0)),
                 "map_version": int(e.get("map_version", 0)),
                 "shards": list(e.get("shards", []) or [])}
                for e in sorted(
                    reroutes,
                    key=lambda e: _event_time(e, mono0, ts0),
                )
            ],
            "refused": len(refusals),
            "refused_by_shard": dict(sorted(ref_by_shard.items())),
            "refused_by_error": dict(sorted(ref_by_error.items())),
        }

    # host budget section: per-stage host-CPU attribution from
    # profile-summary events (obs/profile.SamplingProfiler.emit_summary)
    # joined with the spans the profiler's stages mirror — the direct
    # input to ROADMAP item 2 (why the serve host path acks 1.4k ops/s
    # while the device sustains millions of dispatches)
    host_budget = None
    psums = [e for e in events if e.get("event") == "profile-summary"]
    if psums:
        stage_samples: dict[str, int] = defaultdict(int)
        role_samples: dict[str, int] = defaultdict(int)
        total = 0
        busy_weighted = 0.0
        for e in psums:
            n = int(e.get("thread_samples", 0))
            total += n
            busy_weighted += float(e.get("busy_frac", 0.0)) * n
            for stage, s in (e.get("stages") or {}).items():
                stage_samples[str(stage)] += int(s)
            for role, s in (e.get("roles") or {}).items():
                role_samples[str(role)] += int(s)
        other = stage_samples.get("other", 0)
        # join each budget stage with the wall-clock spans that time
        # the same work, so "fraction of host samples" sits next to
        # "seconds of span time" for the stages both planes cover
        _span_of_stage = {
            "append": ("append", "fused-round", "serve-batch"),
            "encode": ("serve-assemble",),
            "fsync": ("wal-sync",),
        }
        stages = {}
        for stage, n in sorted(stage_samples.items(),
                               key=lambda kv: -kv[1]):
            row = {"samples": n,
                   "frac": n / total if total else 0.0}
            span_total = sum(
                span_stats[s]["total_s"]
                for s in _span_of_stage.get(stage, ())
                if s in span_stats
            )
            if span_total:
                row["span_total_s"] = span_total
            stages[stage] = row
        host_budget = {
            "profiles": len(psums),
            "thread_samples": total,
            "hz": max(float(e.get("hz", 0.0)) for e in psums),
            "duty_cycle": max(float(e.get("duty_cycle", 0.0))
                              for e in psums),
            "busy_frac": busy_weighted / total if total else 0.0,
            "overflow_drops": sum(int(e.get("overflow_drops", 0))
                                  for e in psums),
            "stages": stages,
            "roles": dict(sorted(role_samples.items(),
                                 key=lambda kv: -kv[1])),
            "attributed_frac": (
                (total - other) / total if total else 0.0
            ),
        }

    return {
        "n_events": len(events),
        "event_counts": dict(counts),
        "spans": span_stats,
        "throughput": {
            "source": source,
            "timeline": dict(sorted(timeline.items())),
        },
        "serve": serve,
        "fault": fault,
        "durability": durability,
        "replication": repl,
        "sharding": sharding,
        "fleet": fleet,
        "mesh": mesh,
        "kernels": kernels,
        "host_budget": host_budget,
        "stalls": [
            {"where": where, "log": log, **{k: (sorted(v)
                                               if isinstance(v, set)
                                               else v)
                                            for k, v in s.items()}}
            for (where, log), s in sorted(stalls.items())
        ],
    }


def render(report: dict, out=None) -> None:
    # resolve sys.stdout at call time (an import-time default would pin
    # whatever stream was active when the module first loaded)
    w = (out if out is not None else sys.stdout).write
    w(f"trace: {report.get('n_events', 0)} events\n")
    # explicit per-section data statement up front: a section absent
    # below is absent because the trace holds none of its events, not
    # because the report crashed on partial data
    _sections = ("serve", "fault", "durability", "replication",
                 "sharding", "fleet", "mesh", "kernels",
                 "host_budget")
    present = [s for s in _sections if report.get(s)]
    absent = [s for s in _sections if not report.get(s)]
    w(f"sections: {', '.join(present) if present else '(core only)'}"
      + (f"   [no data: {', '.join(absent)}]" if absent else "")
      + "\n")

    w("\n== event counts ==\n")
    for name, n in sorted(report["event_counts"].items(),
                          key=lambda kv: (-kv[1], kv[0])):
        w(f"  {name:<20} {n}\n")

    w("\n== span durations ==\n")
    if not report["spans"]:
        w("  (no spans recorded)\n")
    else:
        w(f"  {'span':<20} {'count':>6} {'p50':>10} {'p95':>10} "
          f"{'p99':>10} {'max':>10} {'total':>10}  fenced\n")
        for name, s in sorted(report["spans"].items(),
                              key=lambda kv: -kv[1]["total_s"]):
            w(f"  {name:<20} {s['count']:>6} {_fmt_s(s['p50_s']):>10} "
              f"{_fmt_s(s['p95_s']):>10} {_fmt_s(s['p99_s']):>10} "
              f"{_fmt_s(s['max_s']):>10} {_fmt_s(s['total_s']):>10}  "
              f"{'yes' if s['fenced'] else 'NO'}\n")

    w("\n== throughput timeline ==\n")
    tl = report["throughput"]["timeline"]
    if not tl:
        w("  (no throughput samples and no append events)\n")
    else:
        src = report["throughput"]["source"]
        if src == "append":
            w("  (derived from append events: appended ops per second)\n")
        peak = max(tl.values()) or 1
        total = 0
        for sec in sorted(int(s) for s in tl):
            ops = tl[sec] if sec in tl else tl[str(sec)]
            total += ops
            bar = "#" * max(1, round(40 * ops / peak))
            w(f"  t+{sec:>4}s {ops:>12} ops  {bar}\n")
        w(f"  total {total} ops over {len(tl)} sampled second(s), "
          f"peak {peak} ops/s\n")

    serve = report.get("serve")
    if serve:
        w("\n== serve ==\n")
        w(f"  {serve['batches']} batch(es), {serve['ops']} ops, "
          f"p50 batch {serve['p50_batch']:.0f}, "
          f"max batch {serve['max_batch']}\n")
        prio = serve.get("shed_by_priority") or {}
        prio_s = (
            " (" + " ".join(f"{k}={v}"
                            for k, v in sorted(prio.items())) + ")"
            if prio else ""
        )
        w(f"  shed (Overloaded): {serve['shed']}{prio_s}   "
          f"evicted: {serve.get('evicted', 0)}   "
          f"deadline-missed: {serve['deadline_miss']}"
          + (f" ({serve['swept_at_admission']} swept at admission)"
             if serve.get("swept_at_admission") else "")
          + (f"   late successes: {serve['late_success']}"
             if serve.get("late_success") else "") + "\n")
        pipe = serve.get("pipeline")
        if pipe:
            w(f"  pipeline overlap: assembly busy "
              f"{100.0 * pipe['assembly_busy_frac']:.0f}% / device "
              f"busy {100.0 * pipe['device_busy_frac']:.0f}% over "
              f"{pipe['window_s']:.1f}s "
              f"({pipe['assemble_events']} assembled round(s))\n")
        retries = serve.get("retries_by_cause") or {}
        if retries:
            w("  client retries by cause: "
              + "   ".join(f"{k}={v}"
                           for k, v in sorted(retries.items()))
              + "\n")
        if serve.get("circuit_transitions"):
            w(f"  circuit-breaker opens: "
              f"{serve['circuit_transitions']}\n")
        if serve.get("brownout_reads") or serve.get(
                "brownout_transitions"):
            trans = " ".join(
                f"{'on' if t['on'] else 'off'}@t+{t['t']}s"
                for t in serve.get("brownout_transitions", [])
            )
            w(f"  brownout: {serve.get('brownout_reads', 0)} "
              f"degraded read(s), max lag "
              f"{serve.get('max_brownout_lag', 0)} pos"
              + (f"   transitions: {trans}" if trans else "") + "\n")
        ltl = serve.get("admit_limit_timeline") or {}
        if ltl:
            w("  adaptive admission limit (min per second):\n")
            peak = max(ltl.values()) or 1
            for sec in sorted(int(s) for s in ltl):
                d = ltl.get(sec, ltl.get(str(sec), 0))
                bar = "#" * max(1, round(30 * d / peak))
                w(f"    t+{sec:>4}s limit {d:>6}  {bar}\n")
        hist = serve["batch_size_hist"]
        if hist:
            w("  batch-size histogram (<= bucket):\n")
            peak = max(hist.values()) or 1
            for bound in sorted(int(b) for b in hist):
                n = hist.get(bound, hist.get(str(bound), 0))
                bar = "#" * max(1, round(30 * n / peak))
                w(f"    <={bound:>5} {n:>8}  {bar}\n")
        tl = serve["queue_depth_timeline"]
        if tl:
            w("  queue-depth timeline (max observed per second):\n")
            peak = max(tl.values()) or 1
            for sec in sorted(int(s) for s in tl):
                d = tl.get(sec, tl.get(str(sec), 0))
                bar = "#" * max(1, round(30 * d / peak))
                w(f"    t+{sec:>4}s depth {d:>6}  {bar}\n")

    fault = report.get("fault")
    if fault:
        w("\n== fault ==\n")
        w(f"  injected: {fault['injected']}   "
          f"quarantines: {fault['quarantines']}   "
          f"repairs: {fault['repairs']}   "
          f"re-homed requests: {fault['rehomed']}\n")
        if fault["repairs"]:
            w(f"  repair duration p50 {_fmt_s(fault['repair_p50_s'])} "
              f"p95 {_fmt_s(fault['repair_p95_s'])} "
              f"max {_fmt_s(fault['repair_max_s'])}\n")
            hist = fault["repair_hist_ms"]
            if hist:
                w("  repair-duration histogram (<= ms bucket):\n")
                peak = max(hist.values()) or 1
                for bound in sorted(int(b) for b in hist):
                    n = hist.get(bound, hist.get(str(bound), 0))
                    bar = "#" * max(1, round(30 * n / peak))
                    w(f"    <={bound:>6}ms {n:>6}  {bar}\n")
        tl = fault["timeline"]
        if tl:
            w("  lifecycle timeline (per replica):\n")
            for rid in sorted(tl, key=int):
                steps = " -> ".join(
                    f"{to}@t+{t}s" for t, _frm, to in tl[rid]
                )
                w(f"    r{rid}: {steps}\n")

    dur = report.get("durability")
    if dur:
        w("\n== durability ==\n")
        w(f"  fsyncs: {dur['fsyncs']}"
          + (f" (p50 {_fmt_s(dur['fsync_p50_s'])} "
             f"p95 {_fmt_s(dur['fsync_p95_s'])} "
             f"p99 {_fmt_s(dur['fsync_p99_s'])})"
             if dur["fsyncs"] else "")
          + f"   torn-tail truncations: {dur['truncations']}   "
            f"reclaimed segments: {dur['reclaimed_segments']}\n")
        w(f"  snapshots: {dur['snapshots']}   "
          f"recoveries: {dur['recoveries']}"
          + (f" ({dur['replayed_ops']} op(s) replayed from WAL)"
             if dur["recoveries"] else "") + "\n")
        if dur["timeline"]:
            w("  timeline:\n")
            for e in dur["timeline"]:
                detail = " ".join(
                    f"{k}={v}" for k, v in e.items()
                    if k not in ("t", "event")
                )
                w(f"    t+{e['t']:>8.3f}s {e['event']:<17} {detail}\n")

    repl = report.get("replication")
    if repl:
        w("\n== replication ==\n")
        w(f"  shipped: {repl['shipped_records']} record(s) / "
          f"{repl['shipped_ops']} op(s)   applied: "
          f"{repl['applied_records']} record(s) / "
          f"{repl['applied_ops']} op(s)\n")
        w(f"  duplicates skipped: {repl['duplicates']}   "
          f"fenced records: {repl['fenced_records']}   "
          f"fenced publishes: {repl['fenced_publishes']}   "
          f"stale reads: {repl['stale_reads']}\n")
        if repl["ship_errors"] or repl["apply_errors"]:
            w(f"  ship errors: {repl['ship_errors']}   "
              f"apply errors: {repl['apply_errors']}\n")
        if repl.get("transport_connects") or repl.get("relay_fenced") \
                or repl.get("transport_errors"):
            w(f"  transport: {repl['transport_connects']} connect(s), "
              f"{repl['transport_reconnects']} reconnect(s), "
              f"{repl['transport_errors']} server error(s)   "
              f"relay fenced: {repl['relay_fenced']}   "
              f"relay errors: {repl['relay_errors']}\n")
        if repl.get("bootstraps") or repl.get("snapshots_served") \
                or repl.get("bootstrap_failures"):
            w(f"  snapshot bootstrap: {repl['bootstraps']} "
              f"bootstrap(s) ({repl['bootstrap_failures']} fell back "
              f"to full replay), {repl['snapshots_served']} "
              f"served / {repl['snapshots_fetched']} fetched\n")
        tl = repl["apply_lag_timeline"]
        if tl:
            w("  apply-lag timeline (max positions behind feed tail "
              "per second):\n")
            peak = max(tl.values()) or 1
            for sec in sorted(int(s) for s in tl):
                lag = tl.get(sec, tl.get(str(sec), 0))
                bar = "#" * max(1, round(30 * lag / peak))
                w(f"    t+{sec:>4}s lag {lag:>8}  {bar}\n")
        for p in repl["promotions"]:
            w(f"  promotion t+{p['t']}s: {p['follower']} -> epoch "
              f"{p['epoch']} at {p['applied']} "
              f"({p['drained_records']} drained); detect "
              f"{_fmt_s(p['detect_s'])} + promote "
              f"{_fmt_s(p['promote_s'])} = RTO {_fmt_s(p['rto_s'])}\n")

    shd = report.get("sharding")
    if shd:
        w("\n== sharding ==\n")
        w(f"  map adoptions: {shd['map_adoptions']} (final version "
          f"{shd['final_map_version']})   refused submits: "
          f"{shd['refused']}\n")
        for a in shd["adoptions"]:
            moved = (",".join(f"s{s}" for s in a["shards"])
                     if a["shards"] else "none")
            w(f"  adoption t+{a['t']}s [{a['reason']}]: "
              f"v{a['from_version']} -> v{a['map_version']}, "
              f"re-homed: {moved}\n")
        if shd["refused"]:
            by_err = "   ".join(
                f"{k}={v}"
                for k, v in sorted(shd["refused_by_error"].items())
            )
            by_shard = "   ".join(
                f"s{k}={v}"
                for k, v in sorted(shd["refused_by_shard"].items())
            )
            w(f"  refusals by error: {by_err}\n")
            w(f"  refusals by shard: {by_shard}\n")

    fleet = report.get("fleet")
    if fleet:
        w("\n== fleet ==\n")
        nds = fleet.get("nodes") or []
        if not nds:
            w("  (no node summaries — events were node-tagged but no "
              "fleet-scrape lines landed)\n")
        for nd in nds:
            parts = [f"{nd.get('node_id', '?'):<18} "
                     f"role={nd.get('role', '?'):<9}"]
            for key, label in (("applied", "applied"),
                               ("ship_lag", "ship-lag"),
                               ("apply_lag", "apply-lag"),
                               ("relay_lag", "relay-lag"),
                               ("completed", "completed"),
                               ("queued", "queued"),
                               ("shed", "shed")):
                v = nd.get(key)
                if v is not None:
                    parts.append(f"{label}={v:g}")
            w("  " + " ".join(parts) + "\n")
        w(f"  {fleet.get('records', 0)} traced record(s), "
          f"{fleet.get('complete_records', 0)} with a full "
          f"submit->ack chain, "
          f"{fleet.get('complete_multiprocess_records', 0)} spanning "
          f">=3 processes   ({fleet.get('scrapes', 0)} scrape(s)"
          + (f", {fleet['scrape_errors']} scrape error(s)"
             if fleet.get("scrape_errors") else "") + ")\n")
        edges = fleet.get("edges") or {}
        if edges:
            w("  per-edge latency:\n")
            for label, s in edges.items():
                w(f"    {label:<24} x{s.get('count', 0):<5} "
                  f"p50 {_fmt_s(s.get('p50_s', 0.0)):>9} "
                  f"p95 {_fmt_s(s.get('p95_s', 0.0)):>9} "
                  f"max {_fmt_s(s.get('max_s', 0.0)):>9}\n")
        else:
            w("  (no joinable per-record hops — enable tracing on "
              "every node and check NR_TPU_TRACE_SAMPLE)\n")
        for tl in (fleet.get("timelines") or [])[:2]:
            w(f"  record @pos {tl.get('pos')} "
              f"({tl.get('processes', 0)} process(es)"
              + (", complete" if tl.get("complete") else "")
              + "):\n")
            for h in tl.get("hops") or []:
                w(f"    t+{float(h.get('t', 0.0)) * 1e3:9.3f}ms "
                  f"{h.get('hop', '?'):<14} @{h.get('node', '?')}\n")

    mesh = report.get("mesh")
    if mesh:
        w("\n== mesh ==\n")
        for pl in mesh["placements"]:
            w(f"  {pl['wrapper']}: {pl['replicas']} replica(s) over "
              f"{pl['devices']} device(s) "
              f"({pl['per_device']}/device), tier {pl['tier']}\n")
        tiers = mesh["rounds_by_tier"]
        if tiers:
            w("  rounds by tier: "
              + "   ".join(f"{k}={v}" for k, v in sorted(tiers.items()))
              + f"   collective time {_fmt_s(mesh['collective_time_s'])}"
                f" (p50 {_fmt_s(mesh['round_p50_s'])} "
                f"p95 {_fmt_s(mesh['round_p95_s'])})\n")
        w(f"  cross-device sync: {mesh['sync_bytes']} byte(s)\n")
        if mesh["ring_execs"]:
            w(f"  ring catch-up: {mesh['ring_execs']} pass(es), "
              f"{mesh['ring_ops']} op(s) rotated over ICI\n")

    kernels = report.get("kernels")
    if kernels:
        w("\n== kernels ==\n")
        lbt = kernels["launches_by_tier"]
        if lbt:
            w("  launches by tier: "
              + "   ".join(f"{k}={v}" for k, v in sorted(lbt.items()))
              + f"   ({kernels['rounds']} fused round(s), "
                f"{kernels['fused_ops']} window op(s))\n")
            w(f"  launch time p50 {_fmt_s(kernels['launch_p50_s'])} "
              f"p95 {_fmt_s(kernels['launch_p95_s'])}\n")
        wh = kernels["window_hist"]
        if wh:
            w("  window sizes: "
              + "   ".join(f"{k}x{v}" for k, v in sorted(wh.items()))
              + "\n")
        sbe = kernels["serve_batches_by_engine"]
        if sbe:
            w("  serve batches by engine: "
              + "   ".join(f"{k}={v}" for k, v in sorted(sbe.items()))
              + "\n")
        for c in kernels["calibrations"]:
            w(f"  winner selection @ window {c['window']}: "
              f"{c['winner']} (fused {_fmt_s(c['fused_s'])} vs chain "
              f"{_fmt_s(c['chain_s'])})\n")

    hb = report.get("host_budget")
    if hb:
        w("\n== host budget ==\n")
        w(f"  {hb['thread_samples']} thread-sample(s) from "
          f"{hb['profiles']} profile(s) at {hb['hz']:g} Hz   "
          f"host busy {100.0 * hb['busy_frac']:.0f}%   "
          f"profiler duty {100.0 * hb['duty_cycle']:.2f}%"
          + (f"   ({hb['overflow_drops']} overflow drop(s))"
             if hb.get("overflow_drops") else "") + "\n")
        w(f"  {'stage':<16} {'samples':>8} {'share':>7} {'span total':>11}\n")
        for stage, s in hb["stages"].items():
            bar = "#" * max(1, round(30 * s["frac"]))
            span_s = (_fmt_s(s["span_total_s"])
                      if "span_total_s" in s else "-")
            w(f"  {stage:<16} {s['samples']:>8} "
              f"{100.0 * s['frac']:>6.1f}% {span_s:>11}  {bar}\n")
        w(f"  attributed to named stages: "
          f"{100.0 * hb['attributed_frac']:.1f}%\n")
        roles = hb.get("roles") or {}
        if roles:
            w("  samples by role: "
              + "   ".join(f"{r}={n}" for r, n in roles.items())
              + "\n")

    w("\n== stall report ==\n")
    if not report["stalls"]:
        w("  (no watchdog events — no replay stalls observed)\n")
    else:
        for s in report["stalls"]:
            where = s["where"] + (
                f" [log {s['log']}]" if s["log"] is not None else ""
            )
            w(f"  {where}: {s['count']} warning(s), up to "
              f"{s['max_rounds']} fruitless rounds; dormant replicas "
              f"{s['dormant']}; last ltail/tail "
              f"{s['last_ltail']}/{s['last_tail']}\n")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.obs.report",
        description="Summarize a flight-recorder JSONL trace.",
    )
    p.add_argument("trace", help="path to a JSONL trace "
                                 "(NR_TPU_TRACE=<path> output)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as one JSON object instead of "
                        "the text rendering")
    args = p.parse_args(argv)
    events = load_events(args.trace)
    report = analyze(events)
    try:
        if args.json:
            json.dump(report, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            render(report)
        sys.stdout.flush()
    except BrokenPipeError:
        # downstream pager/head closed the pipe: exit quietly, routing
        # the interpreter-shutdown flush at devnull
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
