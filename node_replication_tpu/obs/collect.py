"""Fleet collector: scrape N exporters into one merged view.

The cross-process half of the observability layer: a `FleetCollector`
polls every node's `obs/export.py:MetricsExporter` on an interval and
maintains

- a **time-series ring** per `(node_id, series)` — the congestion/lag
  signal plane (`repl.apply_lag_pos`, `serve.queue_depth.*`, admission
  limits, applied positions) a future `Autoscaler` consumes via
  `series()`, bounded at `history` samples per series;
- a **merged trace** (`fleet.jsonl` when `out_path` is given): every
  node's flight-recorder events, each stamped with the node's
  `node_id`/`role` and a fleet-aligned timestamp `t_fleet`, plus one
  `fleet-scrape` summary line per node per cycle. `obs/report.py`'s
  Fleet section joins this file on `(pos, node_id)` into per-record
  cross-process hop timelines.

Clock discipline: monotonic clocks do NOT compare across processes,
so events are never ordered by their raw `mono` stamps. Instead each
scrape response carries the node's wall clock (`now_ts`), the
collector differences it against its OWN wall clock at receive time
(`offset = t_recv - now_ts`, network latency folded in — honest to
within one RTT), and `t_fleet = event.ts + offset` places every
node's events on the collector's single timeline. Within one node,
`pos` causality (submit before append before ship...) breaks the
remaining ties.

Incremental scraping: the collector passes each node its last `seq`
cursor, so a scrape returns only events the collector has not seen
(`Tracer.events_since`) — a ring-mode tracer under load loses only
what the ring evicted between scrapes, and nothing is merged twice.

CLI:

    python -m node_replication_tpu.obs.collect \\
        --targets host:p1,host:p2 --out fleet.jsonl --seconds 10

Stdlib plus `obs/export.py`'s client only — no jax in this module
(the `-m` spelling still pulls the package `__init__`, as with
`obs/report.py`; copy both files next to each other to run on a
jax-less box).
"""

from __future__ import annotations

import collections
import json
import threading

from node_replication_tpu.analysis.locks import make_lock
import time

from node_replication_tpu.obs.export import (
    ExportError,
    profile_fetch,
    profile_start,
    profile_stop,
    scrape,
)

#: default samples kept per (node, series) ring
DEFAULT_HISTORY = 720


class _Target:
    """One scrape endpoint and its per-node cursor/offset state."""

    __slots__ = ("host", "port", "exporter", "seq", "node_id", "role",
                 "offset", "errors", "last_doc")

    def __init__(self, spec):
        self.exporter = None
        self.host = self.port = None
        self.node_id = None
        self.role = None
        if isinstance(spec, str):
            host, port = spec.rsplit(":", 1)
            self.host, self.port = host, int(port)
        elif isinstance(spec, tuple):
            self.host, self.port = spec[0], int(spec[1])
        else:  # in-process exporter: loopback fast path, no socket —
            # and its identity is known BEFORE the first scrape, so
            # component re-attribution covers events the node emitted
            # before the collector's first cycle
            self.exporter = spec
            self.node_id = spec.node_id
            self.role = spec.role
        self.seq = 0
        self.offset = 0.0
        self.errors = 0
        self.last_doc = None

    def describe(self) -> str:
        if self.exporter is not None:
            return f"in-process:{self.exporter.node_id}"
        return f"{self.host}:{self.port}"

    def fetch(self, timeout_s: float) -> dict:
        if self.exporter is not None:
            return self.exporter.scrape_doc(since=self.seq)
        return scrape(self.host, self.port, since=self.seq,
                      timeout_s=timeout_s)

    def profile_cmd(self, cmd: str, timeout_s: float, **kw) -> dict:
        """Route one remote-capture command to this target (loopback
        fast path for in-process exporters, socket otherwise)."""
        if self.exporter is not None:
            if cmd == "start":
                return self.exporter.profile_start(
                    hz=kw.get("hz"), max_stacks=kw.get("max_stacks"))
            if cmd == "stop":
                return self.exporter.profile_stop()
            return self.exporter.profile_fetch(
                stop=bool(kw.get("stop")))
        if cmd == "start":
            return profile_start(self.host, self.port,
                                 hz=kw.get("hz"),
                                 max_stacks=kw.get("max_stacks"),
                                 timeout_s=timeout_s)
        if cmd == "stop":
            return profile_stop(self.host, self.port,
                                timeout_s=timeout_s)
        return profile_fetch(self.host, self.port,
                             stop=bool(kw.get("stop")),
                             timeout_s=max(timeout_s, 10.0))


class FleetCollector:
    """Scrapes a fleet of exporters on an interval.

        coll = FleetCollector(["127.0.0.1:9101", "127.0.0.1:9102"],
                              interval_s=0.5, out_path="fleet.jsonl")
        coll.start()
        ...
        coll.stop()
        coll.series(node_id, "repl.apply_lag_pos")  # [(t, v), ...]

    Targets may be `"host:port"` strings, `(host, port)` tuples, or
    in-process `MetricsExporter` instances (scraped via `scrape_doc`,
    no socket — deterministic tests and single-process trees). An
    unreachable node is counted and retried next cycle — a flaky
    exporter reads as a stale node, never a dead collector.
    """

    def __init__(
        self,
        targets,
        interval_s: float = 0.5,
        out_path: str | None = None,
        history: int = DEFAULT_HISTORY,
        timeout_s: float = 2.0,
    ):
        self._targets = [_Target(t) for t in targets]
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.out_path = out_path
        self._history = int(history)
        self._lock = make_lock("FleetCollector._lock")
        self._series: dict[tuple[str, str], collections.deque] = {}
        self._latest: dict[str, dict] = {}
        # several exporters can live in ONE process (in-process relay
        # topologies, the follower's frontend exporter next to a
        # relay's) and they all serve the same process-wide tracer —
        # merge each process's event stream exactly once, through the
        # first target that reported its pid
        self._pid_owner: dict[int, str] = {}
        self._t0 = time.monotonic()
        self._cycles = 0
        self._merged_events = 0
        self._fh = open(out_path, "a", buffering=1) if out_path else None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="obs-fleet-collector", daemon=True,
        )

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        if not self._thread.is_alive() and not self._thread.ident:
            self._thread.start()

    def stop(self, final_cycle: bool = True) -> None:
        """Stop the scrape loop; by default run one last cycle so the
        merged trace holds every event emitted before the stop."""
        self._stop.set()
        if self._thread.ident:
            self._thread.join(max(5.0, 2 * self.timeout_s))
        if final_cycle:
            self.collect_once()

    def close(self) -> None:
        self.stop(final_cycle=False)
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "FleetCollector":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.collect_once()
            self._stop.wait(self.interval_s)

    def add_target(self, spec) -> None:
        """Add a scrape endpoint to a live collector (elastic fleets:
        leaves join mid-run). A target that later dies just counts
        scrape errors each cycle — it never stops the loop."""
        with self._lock:
            self._targets.append(_Target(spec))

    # ---------------------------------------------------------- scrape

    def collect_once(self) -> int:
        """One scrape cycle over every target; returns how many nodes
        answered. Callable directly when the loop is not running
        (tests, `--once` tools)."""
        answered = 0
        with self._lock:
            targets = list(self._targets)
        for tgt in targets:
            try:
                doc = tgt.fetch(self.timeout_s)
            except (ExportError, RuntimeError, OSError,
                    ValueError) as e:
                tgt.errors += 1
                self._release_pid_ownership(tgt)
                self._write_line({
                    "event": "fleet-scrape-error",
                    "target": tgt.describe(),
                    "ts": time.time(),  # nrlint: disable=wall-clock-time — merged-trace correlation stamp (module docstring)
                    "cause": f"{type(e).__name__}: {e}",
                })
                continue
            answered += 1
            self._absorb(tgt, doc)
        self._cycles += 1
        return answered

    def _absorb(self, tgt: _Target, doc: dict) -> None:
        t_recv_wall = time.time()  # nrlint: disable=wall-clock-time — cross-process offset estimation (module docstring)
        t_rel = time.monotonic() - self._t0
        tgt.node_id = node = str(doc.get("node_id", tgt.describe()))
        tgt.role = role = str(doc.get("role", "?"))
        tgt.last_doc = doc
        # per-node wall-clock offset onto the collector's timeline
        # (recomputed every cycle: cheap, and it tracks slew)
        now_ts = doc.get("now_ts")
        if now_ts is not None:
            tgt.offset = t_recv_wall - float(now_ts)

        metrics = doc.get("metrics") or {}
        stats = doc.get("stats") or {}
        with self._lock:
            for mname, val in metrics.items():
                if isinstance(val, dict):
                    continue  # histograms are not series points
                self._point(node, mname, t_rel, val)
            for sub, blob in stats.items():
                if not isinstance(blob, dict):
                    continue
                for k, v in blob.items():
                    if isinstance(v, bool) or not isinstance(
                            v, (int, float)):
                        continue
                    self._point(node, f"stats.{sub}.{k}", t_rel, v)
            self._latest[node] = {
                "node_id": node,
                "role": role,
                "t": t_rel,
                "offset": tgt.offset,
                "errors": tgt.errors,
                "metrics": metrics,
                "stats": stats,
            }

        events = doc.get("events") or []
        pid = doc.get("pid")
        owner = True
        if pid is not None:
            with self._lock:
                owner = self._pid_owner.setdefault(
                    int(pid), tgt.describe()
                ) == tgt.describe()
        # only the pid's event owner advances its trace cursor: a
        # non-owner that later inherits ownership (the owner's
        # exporter died) then re-reads the ring from its last MERGED
        # point instead of resuming past events it had been
        # discarding — duplicate hop events are deduped by the report
        # join; silently dropped ones would leave chains incomplete
        if owner:
            tgt.seq = int(doc.get("seq", tgt.seq))
        if owner:
            with self._lock:
                roles = {n: s.get("role", "?")
                         for n, s in self._latest.items()}
                for t in self._targets:
                    if t.node_id and t.node_id not in roles:
                        roles[t.node_id] = t.role or "?"
            for ev in events:
                out = dict(ev)
                # component re-attribution: a shared-process event
                # that names a known node (a relay's `relay-forward`,
                # a follower's stamp) belongs to THAT node in the
                # fleet view, not to whichever co-resident exporter
                # happened to be the pid's canonical event source.
                # A relay's FeedServer stamps `<node>-server`
                # (repl/relay.py) — its wire events belong to the
                # relay too.
                ev_name = ev.get("name")
                if isinstance(ev_name, str) \
                        and ev_name.endswith("-server") \
                        and ev_name[:-len("-server")] in roles:
                    ev_name = ev_name[:-len("-server")]
                if isinstance(ev_name, str) and ev_name in roles:
                    out["node_id"] = ev_name
                    out["role"] = roles[ev_name]
                else:
                    out["node_id"] = node
                    out["role"] = role
                if "ts" in ev:
                    out["t_fleet"] = float(ev["ts"]) + tgt.offset
                self._write_line(out)
            with self._lock:
                self._merged_events += len(events)
        self._write_line({
            "event": "fleet-scrape",
            "node_id": node,
            "role": role,
            "ts": t_recv_wall,
            "t_fleet": t_recv_wall,
            "t": round(t_rel, 3),
            "offset": round(tgt.offset, 6),
            "metrics": metrics,
            "stats": stats,
        })

    # -------------------------------------------------- remote capture

    def _profile_sweep(self, cmd: str, **kw) -> dict[str, dict]:
        """One remote-capture command across every target; a node that
        fails answers as `{"error": ...}` under its name — profiling a
        fleet with one sick node still profiles the rest."""
        out: dict[str, dict] = {}
        with self._lock:
            targets = list(self._targets)
        for tgt in targets:
            key = tgt.node_id or tgt.describe()
            try:
                doc = tgt.profile_cmd(cmd, self.timeout_s, **kw)
            except (ExportError, RuntimeError, OSError,
                    ValueError) as e:
                tgt.errors += 1
                doc = {"error": f"{type(e).__name__}: {e}"}
            out[str(doc.get("node_id", key))] = doc
        return out

    def start_profiles(self, hz: float | None = None,
                       max_stacks: int | None = None) -> dict:
        """Start the sampling profiler on every node
        (`obs/export.py:profile_start` per target)."""
        return self._profile_sweep("start", hz=hz,
                                   max_stacks=max_stacks)

    def stop_profiles(self) -> dict:
        return self._profile_sweep("stop")

    def fetch_profiles(self, stop: bool = True) -> dict[str, dict]:
        """Pull every node's profile document (snapshot + host budget
        + folded stacks), by default stopping the samplers — the
        fleet-wide capture `python -m ...obs.collect --profile` and
        the autoscaler's host-budget input ride on."""
        return self._profile_sweep("fetch", stop=stop)

    def _release_pid_ownership(self, tgt: _Target) -> None:
        """A failing target stops being its process's event-merge
        owner: a surviving co-resident exporter (same pid) takes over
        on its next scrape, so a dead exporter never silences the
        whole process's trace stream."""
        doc = tgt.last_doc
        pid = doc.get("pid") if isinstance(doc, dict) else None
        if pid is None:
            return
        with self._lock:
            if self._pid_owner.get(int(pid)) == tgt.describe():
                del self._pid_owner[int(pid)]

    def _write_line(self, rec: dict) -> None:
        if self._fh is None:
            return
        with self._lock:
            self._fh.write(json.dumps(rec) + "\n")

    # ------------------------------------------------------------ state

    def _point(self, node: str, name: str, t: float, v) -> None:
        key = (node, name)
        ring = self._series.get(key)
        if ring is None:
            ring = self._series[key] = collections.deque(
                maxlen=self._history
            )
        ring.append((round(t, 3), v))

    def series(self, node_id: str, name: str) -> list[tuple]:
        """Sampled `(t_seconds, value)` history for one node's series
        (registry scalars plus flattened `stats.<sub>.<key>` numbers) —
        the Autoscaler's input surface."""
        with self._lock:
            return list(self._series.get((str(node_id), str(name)), ()))

    def series_names(self, node_id: str) -> list[str]:
        with self._lock:
            return sorted(n for (nid, n) in self._series
                          if nid == str(node_id))

    def latest(self) -> dict[str, dict]:
        """node_id -> most recent scrape summary (the dashboard's
        input surface)."""
        with self._lock:
            return {k: dict(v) for k, v in self._latest.items()}

    def nodes(self) -> list[str]:
        with self._lock:
            return sorted(self._latest)

    def uptime_s(self) -> float:
        """Seconds on the collector-relative clock every series point
        and `latest()['t']` stamp is measured on."""
        return time.monotonic() - self._t0

    def stats(self) -> dict:
        with self._lock:
            return {
                "targets": [t.describe() for t in self._targets],
                "cycles": self._cycles,
                "nodes": sorted(self._latest),
                "merged_events": self._merged_events,
                "errors": {t.describe(): t.errors
                           for t in self._targets if t.errors},
            }


def main(argv: list[str] | None = None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.obs.collect",
        description="Scrape a fleet of exporters into a merged "
                    "fleet.jsonl trace + time-series rings.",
    )
    p.add_argument("--targets", required=True,
                   help="comma-separated host:port exporter list")
    p.add_argument("--out", default="fleet.jsonl",
                   help="merged JSONL output path")
    p.add_argument("--interval", type=float, default=0.5)
    p.add_argument("--seconds", type=float, default=10.0,
                   help="how long to collect (0 = one cycle)")
    p.add_argument("--profile", type=float, default=0.0,
                   metavar="SECONDS",
                   help="also run every node's sampling profiler for "
                        "SECONDS and write the fetched profiles to "
                        "<out>.profile.json")
    p.add_argument("--profile-hz", type=float, default=None)
    args = p.parse_args(argv)
    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    coll = FleetCollector(targets, interval_s=args.interval,
                          out_path=args.out)
    if args.profile > 0:
        coll.start_profiles(hz=args.profile_hz)
    if args.seconds <= 0:
        n = coll.collect_once()
        if args.profile > 0:
            time.sleep(args.profile)
    else:
        coll.start()
        try:
            time.sleep(max(args.seconds, args.profile))
        finally:
            coll.stop()
        n = len(coll.nodes())
    if args.profile > 0:
        profiles = coll.fetch_profiles(stop=True)
        ppath = f"{args.out}.profile.json"
        with open(ppath, "w") as fh:
            json.dump(profiles, fh)
        print(f"# fleet profiles ({len(profiles)} node(s)) -> {ppath}",
              file=sys.stderr)
    st = coll.stats()
    print(f"# collected {st['merged_events']} event(s) from "
          f"{len(st['nodes'])}/{len(st['targets'])} node(s) over "
          f"{st['cycles']} cycle(s) -> {args.out}", file=sys.stderr)
    coll.close()
    return 0 if n else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
