"""Flight recorder: structured JSONL events + fence-accurate timing spans.

The trace half of the observability layer (`obs/metrics.py` is the
metrics half). The reference's observability story is the `log` crate
facade plus spin-loop diagnostics every WARN_THRESHOLD iterations
(`nr/src/lib.rs:80-81`, `nr/src/log.rs:351-358`) and the harness's
per-second throughput counters (`benches/mkbench.rs:755-761`). This module
is the TPU build's equivalent: a process-wide `Tracer` that appends JSONL
events (`{"ts", "mono", "event", ...fields}`) to a file, collects them in
an unbounded buffer, or keeps the last N in a ring (flight-recorder
mode — always-on tracing whose memory cost is bounded, dump on incident).

Every event carries both a wall-clock `ts` (time.time, for correlating
with external logs) and a monotonic `mono` (time.monotonic, immune to
clock steps — what the report CLI uses to order and bucket events).

Spans: `span("name", **fields)` times a section and emits `duration_s`
on exit. Because `jax.block_until_ready` returns at enqueue-ack on the
tunneled TPU platform (see `utils/fence.py` — the round-1/2 bench
retraction), a naive span around device work measures DISPATCH rate, not
execution. Opt into fence-accurate spans with `NR_TPU_TRACE_FENCE=1`
(or `get_tracer().fence_spans = True`) and tell the span what to fence:

    with span("exec-round") as sp:
        log, states = run_device_work(...)
        sp.fence(log, states)          # fenced at exit when opted in

At exit the span runs `utils/fence.py:fence()` over the registered
pytrees before taking the end timestamp, so `duration_s` covers actual
device execution; the emitted event carries `fenced: true`. Without the
opt-in, `sp.fence` only records that a fence target existed (zero device
cost) and spans measure host wall time as before.

Disabled by default: `emit` is one branch, and `span` yields a shared
no-op singleton without reading the clock or allocating an event record
(asserted by tests/test_obs.py). Enable with `NR_TPU_TRACE=<path>`
(file), `NR_TPU_TRACE=mem` (in-memory; bound it with
`NR_TPU_TRACE_RING=<n>`), or `get_tracer().enable(...)`.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any


class _Span:
    """Mutable per-span holder the `span` context manager yields: attach
    late fields with `add(...)`, register device pytrees to fence with
    `fence(...)`."""

    __slots__ = ("fields", "fence_args")

    def __init__(self):
        self.fields: dict[str, Any] = {}
        self.fence_args: tuple | None = None

    def add(self, **fields: Any) -> None:
        self.fields.update(fields)

    def fence(self, *trees: Any) -> None:
        self.fence_args = trees


class _NullSpan:
    """Shared do-nothing span for the disabled path (no allocation)."""

    __slots__ = ()

    def add(self, **fields: Any) -> None:
        pass

    def fence(self, *trees: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._buffer: "collections.deque[dict] | list[dict] | None" = None
        self.enabled = False
        # fence-accurate span mode (see module docstring); mutable at
        # runtime so tests and notebooks can flip it per section
        self.fence_spans = (
            os.environ.get("NR_TPU_TRACE_FENCE", "") == "1"
        )

    def enable(self, path: str | None = None,
               ring: int | None = None) -> None:
        """Write events to `path`; with `path=None` buffer in memory —
        unbounded by default, or the last `ring` events when given
        (flight-recorder mode)."""
        with self._lock:
            if self._fh:
                self._fh.close()
            if path:
                self._fh = open(path, "a", buffering=1)
                self._buffer = None
            else:
                self._fh = None
                self._buffer = (
                    collections.deque(maxlen=int(ring))
                    if ring else []
                )
            self.enabled = True

    def disable(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
            self._fh = None
            self._buffer = None
            self.enabled = False

    def emit(self, event: str, **fields: Any) -> None:
        # racy-but-benign fast path: one word read; worst case one
        # event races an enable/disable
        # nrlint: disable=lock-discipline
        if not self.enabled:
            return
        rec = {
            "ts": time.time(),  # nrlint: disable=wall-clock-time — correlation field; `mono` below is the ordering clock
            "mono": time.monotonic(),
            "event": event,
            **fields,
        }
        with self._lock:
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
            elif self._buffer is not None:
                self._buffer.append(rec)

    def events(self) -> list[dict]:
        """Buffered events (memory/ring mode only), oldest first."""
        with self._lock:
            return list(self._buffer or [])


_tracer = Tracer()
_env = os.environ.get("NR_TPU_TRACE")
if _env:
    _ring = os.environ.get("NR_TPU_TRACE_RING")
    if _env in ("mem", ":mem:"):
        _tracer.enable(None, ring=int(_ring) if _ring else None)
    else:
        _tracer.enable(_env)


def get_tracer() -> Tracer:
    return _tracer


@contextlib.contextmanager
def span(event: str, **fields: Any):
    """Time a section; emits `<event>` with `duration_s` on exit.

    Yields a `_Span`: call `sp.add(...)` for fields only known inside the
    section and `sp.fence(*pytrees)` to make the span fence device work
    before the end timestamp under `NR_TPU_TRACE_FENCE=1` (see module
    docstring). Disabled tracer: yields a shared no-op span, reads no
    clock, allocates no record.
    """
    t = _tracer
    if not t.enabled:
        yield _NULL_SPAN
        return
    sp = _Span()
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        fenced = False
        if t.fence_spans and sp.fence_args is not None:
            # import at call time: utils.fence pulls in jax, and the
            # utils package __init__ imports this module back
            from node_replication_tpu.utils.fence import fence

            fence(*sp.fence_args)
            fenced = True
        dur = time.perf_counter() - t0
        t.emit(event, duration_s=dur, fenced=fenced, **fields,
               **sp.fields)
