"""Flight recorder: structured JSONL events + fence-accurate timing spans.

The trace half of the observability layer (`obs/metrics.py` is the
metrics half). The reference's observability story is the `log` crate
facade plus spin-loop diagnostics every WARN_THRESHOLD iterations
(`nr/src/lib.rs:80-81`, `nr/src/log.rs:351-358`) and the harness's
per-second throughput counters (`benches/mkbench.rs:755-761`). This module
is the TPU build's equivalent: a process-wide `Tracer` that appends JSONL
events (`{"ts", "mono", "event", ...fields}`) to a file, collects them in
an unbounded buffer, or keeps the last N in a ring (flight-recorder
mode — always-on tracing whose memory cost is bounded, dump on incident).

Every event carries both a wall-clock `ts` (time.time, for correlating
with external logs) and a monotonic `mono` (time.monotonic, immune to
clock steps — what the report CLI uses to order and bucket events).

Spans: `span("name", **fields)` times a section and emits `duration_s`
on exit. Because `jax.block_until_ready` returns at enqueue-ack on the
tunneled TPU platform (see `utils/fence.py` — the round-1/2 bench
retraction), a naive span around device work measures DISPATCH rate, not
execution. Opt into fence-accurate spans with `NR_TPU_TRACE_FENCE=1`
(or `get_tracer().fence_spans = True`) and tell the span what to fence:

    with span("exec-round") as sp:
        log, states = run_device_work(...)
        sp.fence(log, states)          # fenced at exit when opted in

At exit the span runs `utils/fence.py:fence()` over the registered
pytrees before taking the end timestamp, so `duration_s` covers actual
device execution; the emitted event carries `fenced: true`. Without the
opt-in, `sp.fence` only records that a fence target existed (zero device
cost) and spans measure host wall time as before.

Disabled by default: `emit` is one branch, and `span` yields a shared
no-op singleton without reading the clock or allocating an event record
(asserted by tests/test_obs.py). Enable with `NR_TPU_TRACE=<path>`
(file), `NR_TPU_TRACE=mem` (in-memory; bound it with
`NR_TPU_TRACE_RING=<n>`), or `get_tracer().enable(...)`.

Per-record sampling (`NR_TPU_TRACE_SAMPLE=1/N` or `=N`): the fleet
trace plane (`obs/export.py` / `obs/collect.py`) joins events across
processes on a record's log position `pos`, so per-record hop events
(repl-ship, relay-forward, repl-apply, ...) must agree on which
records they narrate. `pos_sampled(pos)` is that agreement: it keeps
a record iff `pos % N == 0` — a pure function of the position, so
every process samples the SAME records and a sampled record's chain
is always complete (never a partial hop sequence), while unsampled
records are dropped wholesale. N=1 (the default) keeps everything.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading

from node_replication_tpu.analysis.locks import make_lock
import time
from typing import Any


class _Span:
    """Mutable per-span holder the `span` context manager yields: attach
    late fields with `add(...)`, register device pytrees to fence with
    `fence(...)`."""

    __slots__ = ("fields", "fence_args")

    def __init__(self):
        self.fields: dict[str, Any] = {}
        self.fence_args: tuple | None = None

    def add(self, **fields: Any) -> None:
        self.fields.update(fields)

    def fence(self, *trees: Any) -> None:
        self.fence_args = trees


class _NullSpan:
    """Shared do-nothing span for the disabled path (no allocation)."""

    __slots__ = ()

    def add(self, **fields: Any) -> None:
        pass

    def fence(self, *trees: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    def __init__(self):
        self._lock = make_lock("Tracer._lock")
        self._fh = None
        self._buffer: "collections.deque[dict] | list[dict] | None" = None
        #: total events ever emitted to the current sink — with
        #: `len(buffer)` this locates the ring's window in the global
        #: event sequence (`events_since`, the exporter's cursor)
        self._emitted = 0
        self.enabled = False
        # fence-accurate span mode (see module docstring); mutable at
        # runtime so tests and notebooks can flip it per section
        self.fence_spans = (
            os.environ.get("NR_TPU_TRACE_FENCE", "") == "1"
        )

    def enable(self, path: str | None = None,
               ring: int | None = None) -> None:
        """Write events to `path`; with `path=None` buffer in memory —
        unbounded by default, or the last `ring` events when given
        (flight-recorder mode)."""
        with self._lock:
            if self._fh:
                self._fh.close()
            if path:
                self._fh = open(path, "a", buffering=1)
                self._buffer = None
            else:
                self._fh = None
                self._buffer = (
                    collections.deque(maxlen=int(ring))
                    if ring else []
                )
            self._emitted = 0
            self.enabled = True

    def disable(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
            self._fh = None
            self._buffer = None
            self.enabled = False

    def emit(self, event: str, **fields: Any) -> None:
        # racy-but-benign fast path: one word read; worst case one
        # event races an enable/disable
        # nrlint: disable=lock-discipline
        if not self.enabled:
            return
        rec = {
            "ts": time.time(),  # nrlint: disable=wall-clock-time — correlation field; `mono` below is the ordering clock
            "mono": time.monotonic(),
            "event": event,
            **fields,
        }
        with self._lock:
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
                self._emitted += 1
            elif self._buffer is not None:
                self._buffer.append(rec)
                self._emitted += 1

    @property
    def buffered(self) -> bool:
        """True in memory/ring mode — the modes `events()`/
        `events_since()` (and therefore exporter scrapes) can serve
        from. A file-mode tracer exports nothing: the file is the
        export."""
        with self._lock:
            return self._buffer is not None

    def events(self) -> list[dict]:
        """Buffered events (memory/ring mode only), oldest first."""
        with self._lock:
            return list(self._buffer or [])

    def events_since(self, seq: int) -> tuple[int, list[dict]]:
        """Incremental read of the memory/ring buffer: events the
        caller has not seen yet, given the cursor `seq` a previous
        call returned (0 for "from the start"). Returns
        `(new_cursor, events)`; events evicted by the ring before they
        were read are simply gone (flight-recorder semantics — the
        exporter's scrape interval bounds the loss). File-mode tracers
        return `(cursor, [])`: the file itself is the export."""
        with self._lock:
            buf = list(self._buffer or [])
            total = self._emitted
        missed = total - int(seq)
        if missed <= 0:
            return total, []
        return total, buf[max(0, len(buf) - missed):]


_tracer = Tracer()
_env = os.environ.get("NR_TPU_TRACE")
if _env:
    _ring = os.environ.get("NR_TPU_TRACE_RING")
    if _env in ("mem", ":mem:"):
        _tracer.enable(None, ring=int(_ring) if _ring else None)
    else:
        _tracer.enable(_env)


def get_tracer() -> Tracer:
    return _tracer


def _parse_sample(spec: str | None) -> int:
    """`"1/N"` or `"N"` -> N (keep one record in N); anything
    unparsable or < 1 means no sampling (keep all)."""
    if not spec:
        return 1
    s = spec.strip()
    if "/" in s:
        s = s.split("/", 1)[1]
    try:
        n = int(s)
    except ValueError:
        return 1
    return n if n >= 1 else 1


_sample_n = _parse_sample(os.environ.get("NR_TPU_TRACE_SAMPLE"))


def trace_sample_n() -> int:
    """The configured per-record sampling modulus N (1 = keep all)."""
    return _sample_n


def set_trace_sample(n: int) -> None:
    """Override the sampling modulus at runtime (tests, notebooks)."""
    global _sample_n
    _sample_n = max(1, int(n))


def pos_sampled(pos: int) -> bool:
    """Should per-record trace events narrate the record at `pos`?

    Deterministic in the position alone (`pos % N == 0`), so every
    process in a fleet keeps the SAME records and a sampled record's
    cross-process hop chain is complete — never partial (module
    docstring). Callers still guard on `tracer.enabled` first; this
    only thins the per-record firehose."""
    return _sample_n <= 1 or int(pos) % _sample_n == 0


@contextlib.contextmanager
def span(event: str, **fields: Any):
    """Time a section; emits `<event>` with `duration_s` on exit.

    Yields a `_Span`: call `sp.add(...)` for fields only known inside the
    section and `sp.fence(*pytrees)` to make the span fence device work
    before the end timestamp under `NR_TPU_TRACE_FENCE=1` (see module
    docstring). Disabled tracer: yields a shared no-op span, reads no
    clock, allocates no record.
    """
    t = _tracer
    if not t.enabled:
        yield _NULL_SPAN
        return
    sp = _Span()
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        fenced = False
        if t.fence_spans and sp.fence_args is not None:
            # import at call time: utils.fence pulls in jax, and the
            # utils package __init__ imports this module back
            from node_replication_tpu.utils.fence import fence

            fence(*sp.fence_args)
            fenced = True
        dur = time.perf_counter() - t0
        t.emit(event, duration_s=dur, fenced=fenced, **fields,
               **sp.fields)
