"""Host-path sampling profiler: per-role folded stacks from thread names.

The missing third leg of the observability layer (`obs/metrics.py` is
the metrics half, `obs/recorder.py` the trace half): spans can say
*what stages exist* but not *where host CPU time goes* inside them —
the exact question ROADMAP item 2 (device ~7 G dispatches/s vs the
Python frontend's ~1.4 k acked ops/s) needs answered before anyone
tunes the host path. A `SamplingProfiler` is a stdlib-only
`sys._current_frames()` sampler thread at a configurable rate that
aggregates folded call stacks **per thread role**, where roles come
from the repo's disciplined thread names (`serve-worker-r<rid>`,
`serve-asm-r<rid>`, `repl-shipper`, `fault-medic-r<rid>`, ... — the
contract nrlint's `unnamed-worker-thread` rule enforces):

    prof = SamplingProfiler(hz=97)
    prof.start()
    ...serve traffic...
    prof.stop()
    print(prof.folded())          # flamegraph.pl / speedscope input
    budget = host_budget(prof.snapshot())

Cost contracts, mirroring the rest of obs/:

- disabled = the object does not exist (the `obs_port=None`
  discipline, `obs/export.py`): no hot-path branch anywhere pays for
  profiling being off — `ServeConfig(profile_hz=None)` builds nothing.
- bounded memory: at most `max_stacks` unique (role, stack) entries;
  further novel stacks aggregate into a per-role `[overflow]` bucket
  (counted in `overflow_drops`) instead of growing the table — the
  flight-recorder idea applied to stack aggregation.
- self-measured: the sampler publishes its own duty cycle (time spent
  sampling / wall time) to the `obs.profiler.duty_cycle` gauge, so the
  profiler's overhead is itself observable; `bench.py --serve
  --profile` gates ON-vs-OFF throughput at <= 5% on top of it.

Each sampled stack is classified once into a host-budget **stage**
(`admission`, `encode`, `append`, `readback`, `fsync`,
`future-resolve`, `lock-wait`, `other`) by walking frames leaf -> root
against the serve/core call-site tables below; `host_budget(snapshot)`
reduces a profile to the per-stage attribution the "Host budget"
report section (`obs/report.py`) and the bench gate consume. A thread
whose leaf frame is a wait primitive (`Condition.wait`, socket
receive, `sleep`, ...) is `lock-wait` — blocked, not burning the GIL.

Folded output (`folded()` / `folded_from_snapshot`) is the
flamegraph/speedscope line format, one stack per line, role as the
root frame:

    serve-worker;frontend.py:_worker_loop;frontend.py:_run_batch;... 42

Remote capture rides the exporter (`obs/export.py`):
`profile-start` / `profile-stop` / `profile-fetch` commands over the
same length+CRC framing, and `FleetCollector.fetch_profiles` pulls a
profile from every node. Pure stdlib (plus `obs/metrics.py`) so all of
that works on a jax-less box.
"""

from __future__ import annotations

import os
import sys
import threading

from node_replication_tpu.analysis.locks import make_lock
import time

from node_replication_tpu.obs.metrics import get_registry

#: default sampling rate; prime so the sampler cannot phase-lock with
#: millisecond-periodic serve work (the classic 100 Hz aliasing trap)
DEFAULT_HZ = 97.0

#: default unique-(role, stack) cap before the overflow bucket engages
DEFAULT_MAX_STACKS = 4096

#: frames kept per stack (leafmost); deeper stacks get a root marker
DEFAULT_MAX_DEPTH = 48

TRUNCATED_FRAME = "[truncated]"
OVERFLOW_FRAME = "[overflow]"

# --------------------------------------------------------------------------
# thread-name -> role (the contract `ServeFrontend.threads()` pins and
# the lint rule `unnamed-worker-thread` enforces)
# --------------------------------------------------------------------------

_ROLE_PREFIXES = (
    ("serve-worker-", "serve-worker"),
    ("serve-asm-", "serve-assembly"),
    ("serve-cpl-", "serve-completion"),
    ("serve-client-", "serve-client"),
    ("repl-shipper", "repl-shipper"),
    ("repl-relay-", "repl-relay"),
    ("repl-apply-", "repl-apply"),
    ("repl-feed-", "repl-feed"),
    ("repl-promotion-watch", "repl-promote"),
    ("fault-medic-", "fault-medic"),
    ("obs-export-", "obs-export"),
    ("obs-device-trace-", "obs-export"),
    ("obs-fleet-collector", "obs-collect"),
    ("obs-profiler", "obs-profiler"),
    ("MainThread", "main"),
)

#: every role `role_of` can produce (the profiler's bucket universe)
KNOWN_ROLES = frozenset(r for _, r in _ROLE_PREFIXES) | {"other"}


def role_of(thread_name: str) -> str:
    """Map a thread name onto its profiler role bucket. Unnamed or
    foreign threads collapse into `"other"` — which is exactly why
    nrlint warns on `threading.Thread` without `name=` in the worker
    subsystems (`unnamed-worker-thread`)."""
    name = str(thread_name)
    for prefix, role in _ROLE_PREFIXES:
        if name.startswith(prefix):
            return role
    return "other"


# --------------------------------------------------------------------------
# stage classification (the host-budget vocabulary, ROADMAP item 2a-c)
# --------------------------------------------------------------------------

#: a thread whose LEAF frame is one of these is blocked, not running —
#: Python-level wait primitives (`Condition.wait` in threading.py, the
#: clock shim's `wait`, framed-socket receive loops). C-level blockers
#: (`lock.acquire`, `socket.recv`, `os.fsync`, `time.sleep`) have no
#: Python frame of their own; their CALLERS appear here when the call
#: site is itself a dedicated wait helper.
_WAIT_LEAF_FUNCS = frozenset({
    "wait", "wait_for", "acquire", "select", "poll", "accept",
    "recv", "recvfrom", "recv_into", "_recv_exact", "sleep", "join",
    "_wait_for_tstate_lock",  # threading.Thread.join's blocking leaf
    "wait_idle", "wait_clear", "park", "readinto", "getch",
})

#: funcname -> budget stage for frames INSIDE this package (matching
#: foreign frames by bare function name would misattribute jax/numpy
#: internals; deep foreign frames attribute to the nearest in-package
#: caller instead, which is the attribution that can be acted on)
_STAGE_FUNCS = {
    # admission: client-side submit/offer path up to the queue
    "submit": "admission", "offer": "admission",
    "readmit": "admission", "call": "admission",
    "call_with_retry": "admission", "_sweep_expired_unlocked":
    "admission",
    # encode: batch assembly — drain, deadline sweep, op staging
    "take_batch": "encode", "_assemble": "encode",
    "_sweep_batch": "encode", "_run_batch": "encode",
    "_worker_loop": "encode", "_assembly_loop": "encode",
    # append: the combiner round's device dispatch
    "execute_mut_batch": "append", "begin_mut_batch": "append",
    "finish_mut_batch": "append", "execute_mut": "append",
    "combine": "append", "_exec_round": "append", "append": "append",
    "sync_log": "append", "log_catchup_all": "append",
    "_begin_round": "append", "_finish_round": "append",
    # readback: read-path sync + device->host result fetch
    "execute": "readback", "execute_stale": "readback",
    "read": "readback", "_readback": "readback",
    # fsync: WAL durability barrier
    "fsync": "fsync", "_fsync": "fsync", "sync": "fsync",
    "ship_barrier": "fsync", "barrier": "fsync",
    # future resolution: response delivery back to clients
    "_finish_delivery": "future-resolve", "_complete": "future-resolve",
    "_completion_loop": "future-resolve", "_resolve": "future-resolve",
    "_reject": "future-resolve", "set_result": "future-resolve",
    "batch_done": "future-resolve",
}

#: device-readback entry points that live OUTSIDE the package (jax);
#: these may match anywhere in the stack
_FOREIGN_STAGE_FUNCS = {
    "block_until_ready": "readback", "device_get": "readback",
    "__array__": "readback", "copy_to_host_async": "readback",
}

#: the full stage vocabulary, render order for the report section
STAGES = ("lock-wait", "append", "readback", "encode", "admission",
          "fsync", "future-resolve", "other")

_PKG_MARKER = os.sep + "node_replication_tpu" + os.sep


def _classify(frames_leaf_first) -> str:
    """Budget stage for one sampled stack: `lock-wait` when the leaf
    is a wait primitive, else the first (leafmost) frame matching the
    stage tables — so jax internals under `execute_mut_batch` read as
    `append`, and `_run_batch`'s own bookkeeping (no deeper match)
    reads as `encode`."""
    if not frames_leaf_first:
        return "other"
    if frames_leaf_first[0][1] in _WAIT_LEAF_FUNCS:
        return "lock-wait"
    for filename, func in frames_leaf_first:
        stage = _FOREIGN_STAGE_FUNCS.get(func)
        if stage is not None:
            return stage
        if _PKG_MARKER in filename:
            stage = _STAGE_FUNCS.get(func)
            if stage is not None:
                return stage
    return "other"


class _StackRec:
    """Aggregated counts for one unique (role, stack)."""

    __slots__ = ("count", "stage", "wait")

    def __init__(self, stage: str, wait: bool):
        self.count = 0
        self.stage = stage
        self.wait = wait


class SamplingProfiler:
    """Samples every live thread's stack at `hz` from one daemon
    thread (`obs-profiler`), aggregating per-role folded stacks.

    The object IS the enablement: construct + `start()` to profile,
    `stop()` to halt (restartable); code that does not hold one pays
    nothing. Thread-safe: `snapshot()`/`folded()` may be called from
    any thread, running or stopped (the remote-capture path fetches
    from a live profiler).
    """

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        max_stacks: int = DEFAULT_MAX_STACKS,
        max_depth: int = DEFAULT_MAX_DEPTH,
        registry=None,
    ):
        if not hz > 0:
            raise ValueError(f"hz must be > 0, got {hz}")
        if max_stacks < 1:
            raise ValueError("max_stacks must be >= 1")
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._lock = make_lock("SamplingProfiler._lock")
        self._stacks: dict[tuple, _StackRec] = {}
        self._roles: dict[str, dict] = {}
        self._role_threads: dict[str, set] = {}
        self._ticks = 0
        self._thread_samples = 0
        self._busy_samples = 0
        self._overflow_drops = 0
        self._spent_s = 0.0    # sampler's own CPU-ish time (duty cycle)
        self._wall_s = 0.0     # accumulated across start/stop segments
        self._t_start: float | None = None
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        reg = registry if registry is not None else get_registry()
        # one gauge pair per process (get-or-create): the profiler's
        # own overhead and the host's busy fraction — `obs/top.py`'s
        # `host` column and the overhead gate read these
        self._g_duty = reg.gauge("obs.profiler.duty_cycle")
        self._g_busy = reg.gauge("obs.host.busy_frac")

    # ------------------------------------------------------------ lifecycle

    @property
    def running(self) -> bool:
        # nrcheck: unshared — lock-free poll; one reference load
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def thread(self) -> threading.Thread | None:
        """The live sampler thread (None when stopped) — for thread
        introspection (`ServeFrontend.threads()`), not lifecycle."""
        with self._lock:
            return self._thread

    def start(self) -> None:
        """Start (or restart) the sampler thread; idempotent while
        running. Counts accumulate across segments — `reset()` wipes."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            evt = threading.Event()
            self._stop_evt = evt
            self._t_start = time.monotonic()
            t = threading.Thread(
                target=self._loop, args=(evt,),
                name="obs-profiler", daemon=True,
            )
            self._thread = t
        t.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        """Stop sampling (idempotent); the aggregate survives for
        `snapshot()`/`folded()`."""
        with self._lock:
            t = self._thread
            self._stop_evt.set()
        if t is not None and t.is_alive():
            t.join(timeout_s)
        with self._lock:
            if self._t_start is not None:
                self._wall_s += time.monotonic() - self._t_start
                self._t_start = None
            self._thread = None

    def __enter__(self) -> "SamplingProfiler":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def reset(self) -> None:
        """Drop every aggregate (the running wall segment restarts)."""
        with self._lock:
            self._stacks.clear()
            self._roles.clear()
            self._role_threads.clear()
            self._ticks = 0
            self._thread_samples = 0
            self._busy_samples = 0
            self._overflow_drops = 0
            self._spent_s = 0.0
            self._wall_s = 0.0
            if self._t_start is not None:
                self._t_start = time.monotonic()

    # ------------------------------------------------------------- sampling

    def _loop(self, stop_evt: threading.Event) -> None:
        period = 1.0 / self.hz
        next_t = time.monotonic() + period
        last_pub = time.monotonic()
        pub = {"spent": 0.0, "samples": 0, "busy": 0}
        while not stop_evt.wait(
                max(0.0, next_t - time.monotonic())):
            t0 = time.monotonic()
            samples, busy = self.sample_once()
            t1 = time.monotonic()
            cost = t1 - t0
            with self._lock:
                self._spent_s += cost
            pub["spent"] += cost
            pub["samples"] += samples
            pub["busy"] += busy
            next_t += period
            if next_t < t1:
                # sampling fell behind the period: drop missed ticks
                # instead of bursting to catch up (duty stays bounded)
                next_t = t1 + period
            if t1 - last_pub >= 1.0:
                self._publish(pub, t1 - last_pub)
                last_pub = t1
                pub = {"spent": 0.0, "samples": 0, "busy": 0}
        # final window so short runs still publish their gauges
        now = time.monotonic()
        if pub["samples"] or pub["spent"]:
            self._publish(pub, max(now - last_pub, 1e-9))

    def _publish(self, pub: dict, window_s: float) -> None:
        self._g_duty.set(min(1.0, pub["spent"] / window_s))
        if pub["samples"]:
            self._g_busy.set(pub["busy"] / pub["samples"])

    def sample_once(self) -> tuple[int, int]:
        """One sweep over every live thread (the sampler's tick, also
        directly callable for deterministic tests). Returns
        `(thread_samples, busy_samples)` for this sweep."""
        me = threading.get_ident()
        with self._lock:
            t = self._thread
        skip = {me}
        if t is not None and t.ident is not None:
            skip.add(t.ident)
        names = {}
        for th in threading.enumerate():
            if th.ident is not None:
                names[th.ident] = th.name
        sampled = []
        # sys._current_frames() is a point-in-time dict; frames may
        # keep running while we walk them — good enough for sampling
        for ident, frame in sys._current_frames().items():
            if ident in skip:
                continue
            leaf_first = []
            f = frame
            depth = 0
            while f is not None and depth < self.max_depth:
                code = f.f_code
                leaf_first.append((code.co_filename, code.co_name))
                f = f.f_back
                depth += 1
            truncated = f is not None
            stage = _classify(leaf_first)
            frames = tuple(
                f"{fn.rsplit(os.sep, 1)[-1]}:{func}"
                for fn, func in reversed(leaf_first)
            )
            if truncated:
                frames = (TRUNCATED_FRAME,) + frames
            role = role_of(names.get(ident, ""))
            sampled.append((role, frames, stage,
                            stage == "lock-wait",
                            names.get(ident, f"tid-{ident}")))
        busy = 0
        with self._lock:
            self._ticks += 1
            for role, frames, stage, wait, name in sampled:
                self._thread_samples += 1
                if not wait:
                    busy += 1
                    self._busy_samples += 1
                rstat = self._roles.get(role)
                if rstat is None:
                    rstat = self._roles[role] = {"samples": 0,
                                                 "busy": 0}
                rstat["samples"] += 1
                if not wait:
                    rstat["busy"] += 1
                seen = self._role_threads.setdefault(role, set())
                if len(seen) < 64:
                    seen.add(name)
                key = (role, frames)
                rec = self._stacks.get(key)
                if rec is None:
                    if len(self._stacks) >= self.max_stacks:
                        # bounded memory: novel stacks past the cap
                        # fold into the per-role overflow bucket
                        self._overflow_drops += 1
                        key = (role, (OVERFLOW_FRAME,))
                        rec = self._stacks.get(key)
                        if rec is None:
                            rec = self._stacks[key] = _StackRec(
                                stage, wait)
                    else:
                        rec = self._stacks[key] = _StackRec(stage,
                                                            wait)
                rec.count += 1
        return len(sampled), busy

    # -------------------------------------------------------------- output

    @property
    def wall_s(self) -> float:
        with self._lock:
            wall = self._wall_s
            if self._t_start is not None:
                wall += time.monotonic() - self._t_start
            return wall

    @property
    def duty_cycle(self) -> float:
        """Fraction of wall time the sampler spent sampling — the
        profiler's self-measured overhead."""
        wall = self.wall_s
        with self._lock:
            return self._spent_s / wall if wall > 0 else 0.0

    def snapshot(self) -> dict:
        """JSON-safe full view: config, self-measurement, per-role
        totals + seen thread names, and every aggregated stack (each
        with its precomputed budget stage) — the document the
        exporter's `profile-fetch` returns."""
        wall = self.wall_s
        with self._lock:
            stacks = [
                {"role": role, "frames": list(frames),
                 "count": rec.count, "stage": rec.stage,
                 "wait": rec.wait}
                for (role, frames), rec in self._stacks.items()
            ]
            roles = {
                role: {
                    "samples": st["samples"], "busy": st["busy"],
                    "threads": sorted(
                        self._role_threads.get(role, ())),
                }
                for role, st in self._roles.items()
            }
            doc = {
                "hz": self.hz,
                "running": self.running,
                "wall_s": wall,
                "spent_s": self._spent_s,
                "duty_cycle": (self._spent_s / wall
                               if wall > 0 else 0.0),
                "ticks": self._ticks,
                "thread_samples": self._thread_samples,
                "busy_samples": self._busy_samples,
                "busy_frac": (
                    self._busy_samples / self._thread_samples
                    if self._thread_samples else 0.0
                ),
                "unique_stacks": len(self._stacks),
                "max_stacks": self.max_stacks,
                "overflow_drops": self._overflow_drops,
                "roles": roles,
            }
        stacks.sort(key=lambda s: (-s["count"], s["role"],
                                   s["frames"]))
        doc["stacks"] = stacks
        return doc

    def folded(self) -> str:
        """Folded-stack text (flamegraph.pl / speedscope "folded"
        importer): `role;frame;frame... count`, hottest first."""
        return folded_from_snapshot(self.snapshot())

    def emit_summary(self, tracer=None, **extra) -> dict:
        """Reduce the profile to its host budget and emit it as ONE
        `profile-summary` trace event, the join point `obs/report.py`'s
        Host budget section reads from a trace artifact. Returns the
        snapshot it summarized."""
        from node_replication_tpu.obs.recorder import get_tracer

        snap = self.snapshot()
        budget = host_budget(snap)
        t = tracer if tracer is not None else get_tracer()
        t.emit(
            "profile-summary",
            hz=self.hz,
            wall_s=round(snap["wall_s"], 6),
            ticks=snap["ticks"],
            thread_samples=snap["thread_samples"],
            duty_cycle=round(snap["duty_cycle"], 6),
            busy_frac=round(snap["busy_frac"], 6),
            unique_stacks=snap["unique_stacks"],
            overflow_drops=snap["overflow_drops"],
            roles={r: d["samples"] for r, d in snap["roles"].items()},
            stages={s: d["samples"]
                    for s, d in budget["stages"].items()},
            attributed_frac=budget["attributed_frac"],
            **extra,
        )
        return snap


# --------------------------------------------------------------------------
# snapshot reductions (pure functions — shared by bench, report, CLI)
# --------------------------------------------------------------------------


def folded_from_snapshot(snapshot: dict) -> str:
    """Folded-stack lines from a `SamplingProfiler.snapshot()` (local
    or fetched over the exporter protocol)."""
    lines = []
    for s in snapshot.get("stacks", ()):
        frames = ";".join([s["role"]] + list(s["frames"]))
        lines.append(f"{frames} {int(s['count'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_folded(text: str) -> list[tuple[list[str], int]]:
    """Parse folded-stack text back into `([frames...], count)` rows
    (round-trip validation for the remote-capture tests and any
    speedscope-compatible consumer)."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        rows.append((stack.split(";"), int(count)))
    return rows


def host_budget(snapshot: dict) -> dict:
    """Per-stage host-time attribution from one profile snapshot: the
    "Host budget" (ROADMAP item 2a-c). Sample counts are the time
    proxy (each thread-sample is ~1/hz of one thread's wall time);
    `attributed_frac` is the share landing in a NAMED stage (everything
    but `other`) — the bench gate wants >= 0.9."""
    totals: dict[str, int] = {}
    total = 0
    for s in snapshot.get("stacks", ()):
        n = int(s["count"])
        totals[s["stage"]] = totals.get(s["stage"], 0) + n
        total += n
    stages = {}
    for stage in STAGES:
        n = totals.pop(stage, 0)
        if n:
            stages[stage] = {"samples": n, "frac": n / total}
    for stage, n in sorted(totals.items()):  # future-proof: unknowns
        stages[stage] = {"samples": n, "frac": n / total}
    other = stages.get("other", {}).get("samples", 0)
    return {
        "thread_samples": total,
        "wall_s": float(snapshot.get("wall_s", 0.0)),
        "hz": float(snapshot.get("hz", 0.0)),
        "duty_cycle": float(snapshot.get("duty_cycle", 0.0)),
        "busy_frac": float(snapshot.get("busy_frac", 0.0)),
        "stages": stages,
        "attributed_frac": (
            (total - other) / total if total else 0.0
        ),
    }
