"""Live fleet dashboard: a refreshing tree view over exporter scrapes.

    python -m node_replication_tpu.obs.top \\
        --targets host:p1,host:p2,host:p3

Runs a `FleetCollector` (`obs/collect.py`) against the given
exporters and redraws one frame per interval: a row per node —
role, applied position, ship/apply/relay lag, adaptive admission
limit, shed count and SLO burn (shed + deadline-missed over
accepted), host-busy % (the sampling profiler's
`obs.host.busy_frac` gauge, "-" on unprofiled nodes),
brownout/circuit state — ordered primary → relays →
followers so the table reads as the tree.

Rendering is a PURE function (`render_frame(latest) -> str`), so the
dashboard is testable without a terminal and scriptable:

- `--once`: print a single frame and exit (CI smoke, cron capture);
- `--frames N`: stop after N redraws;
- default: run until interrupted, using curses when stdout is a
  terminal (falls back to ANSI clear + reprint anywhere else).

Stdlib plus the fleet tooling's own modules (`obs/collect.py`,
`obs/export.py`) — no jax in any of them, so the dashboard runs from
any box that can reach the exporter ports.
"""

from __future__ import annotations

import time

from node_replication_tpu.obs.collect import FleetCollector

_ROLE_ORDER = {"router": 0, "primary": 1, "shard": 1, "relay": 2,
               "follower": 3}

_COLUMNS = ("node", "role", "applied", "ship-lag", "apply-lag",
            "limit", "shed", "burn", "host", "p99", "state")


def _num(d, *path):
    cur = d
    for k in path:
        if not isinstance(cur, dict) or k not in cur:
            return None
        cur = cur[k]
    return cur if isinstance(cur, (int, float)) else None


def _fmt(v, pct=False) -> str:
    if v is None:
        return "-"
    if pct:
        return f"{100.0 * v:.1f}%"
    if isinstance(v, float) and not v.is_integer():
        return f"{v:.3g}"
    return f"{int(v)}"


def node_row(summary: dict) -> dict:
    """One dashboard row from one node's latest scrape summary
    (`FleetCollector.latest()` values)."""
    metrics = summary.get("metrics") or {}
    stats = summary.get("stats") or {}
    role = str(summary.get("role", "?"))
    serve = stats.get("serve") if isinstance(stats.get("serve"),
                                             dict) else {}
    overload = serve.get("overload") if isinstance(
        serve.get("overload"), dict) else {}
    limits = overload.get("limits") if isinstance(
        overload.get("limits"), dict) else {}
    limit = min((v for v in limits.values()
                 if isinstance(v, (int, float))), default=None)
    accepted = _num(serve, "accepted")
    shed = _num(serve, "shed")
    missed = _num(serve, "deadline_missed")
    burn = None
    if accepted is not None and (shed or missed):
        burn = ((shed or 0) + (missed or 0)) / max(1, accepted)
    lat = metrics.get("serve.request.latency_s")
    p99 = lat.get("p99") if isinstance(lat, dict) else None
    # host-busy %: published by the node's sampling profiler
    # (obs/profile.py `obs.host.busy_frac` gauge); "-" when the node
    # isn't profiled — the gauge, like the profiler, does not exist
    busy = metrics.get("obs.host.busy_frac")
    if not isinstance(busy, (int, float)):
        busy = None
    state = []
    if overload.get("brownout"):
        state.append("BROWNOUT")
    if (_num(overload, "backpressure") or 0) >= 1:
        state.append("BACKPRESSURE")
    if summary.get("stale"):
        state.append("STALE")
    applied = _num(stats, "follower", "applied")
    if applied is None:
        applied = _num(stats, "relay", "cursor")
    if applied is None:
        applied = _num(stats, "serve", "completed")
    return {
        "node": str(summary.get("node_id", "?")),
        "role": role,
        "order": (_ROLE_ORDER.get(role, 4),
                  str(summary.get("node_id", "?"))),
        "applied": _fmt(applied),
        "ship-lag": _fmt(metrics.get("repl.ship_lag_pos")),
        "apply-lag": _fmt(
            metrics.get("repl.apply_lag_pos")
            if metrics.get("repl.apply_lag_pos") is not None
            else metrics.get("repl.relay.lag_pos")
        ),
        "limit": _fmt(limit),
        "shed": _fmt(shed),
        "burn": _fmt(burn, pct=True) if burn is not None else "-",
        "host": _fmt(busy, pct=True) if busy is not None else "-",
        "p99": (f"{float(p99) * 1e3:.1f}ms"
                if isinstance(p99, (int, float)) else "-"),
        "state": " ".join(state) or "ok",
    }


def render_frame(latest: dict[str, dict], now_s: float | None = None,
                 stale_after_s: float = 5.0) -> str:
    """One dashboard frame from `FleetCollector.latest()`. `now_s` is
    the collector-relative clock (`latest[*]['t']` epoch) used to mark
    nodes whose last scrape is older than `stale_after_s`."""
    rows = []
    for nid in sorted(latest):
        summary = dict(latest[nid])
        if now_s is not None and summary.get("t") is not None:
            summary["stale"] = (now_s - float(summary["t"])
                                > stale_after_s)
        rows.append(node_row(summary))
    rows.sort(key=lambda r: r["order"])
    widths = {c: len(c) for c in _COLUMNS}
    for r in rows:
        for c in _COLUMNS:
            widths[c] = max(widths[c], len(str(r[c])))
    lines = [
        "fleet: "
        + (f"{len(rows)} node(s)" if rows
           else "no nodes answered yet")
    ]
    header = "  ".join(f"{c:<{widths[c]}}" for c in _COLUMNS)
    lines.append(header)
    lines.append("-" * len(header))
    for r in rows:
        # tree shape: indent by role depth so primary -> relay ->
        # follower reads as the topology
        pad = " " * (2 * r["order"][0])
        cells = "  ".join(f"{str(r[c]):<{widths[c]}}"
                          for c in _COLUMNS)
        lines.append((pad + cells)[:200])
    return "\n".join(lines) + "\n"


def _run_plain(coll: FleetCollector, interval_s: float,
               frames: int | None, out) -> None:
    n = 0
    try:
        while frames is None or n < frames:
            coll.collect_once()
            frame = render_frame(coll.latest(), now_s=coll.uptime_s())
            if n and frames is None:
                out.write("\x1b[2J\x1b[H")  # ANSI clear + home
            out.write(frame)
            out.flush()
            n += 1
            if frames is not None and n >= frames:
                break
            time.sleep(interval_s)
    except KeyboardInterrupt:
        pass


def _run_curses(coll: FleetCollector, interval_s: float) -> None:
    import curses

    def loop(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        while True:
            coll.collect_once()
            frame = render_frame(coll.latest(), now_s=coll.uptime_s())
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            for i, line in enumerate(frame.split("\n")[:maxy - 1]):
                scr.addnstr(i, 0, line, maxx - 1)
            scr.refresh()
            t_end = time.monotonic() + interval_s
            while time.monotonic() < t_end:
                if scr.getch() in (ord("q"), 27):
                    return
                time.sleep(0.05)

    curses.wrapper(loop)


def main(argv: list[str] | None = None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.obs.top",
        description="Live fleet dashboard over metrics-exporter "
                    "scrapes.",
    )
    p.add_argument("--targets", required=True,
                   help="comma-separated host:port exporter list")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit")
    p.add_argument("--frames", type=int, default=None,
                   help="stop after N frames (plain renderer)")
    p.add_argument("--plain", action="store_true",
                   help="never use curses (clear+reprint instead)")
    args = p.parse_args(argv)
    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    coll = FleetCollector(targets, interval_s=args.interval)
    try:
        if args.once:
            _run_plain(coll, args.interval, frames=1, out=sys.stdout)
            return 0 if coll.nodes() else 1
        if args.frames is not None:
            _run_plain(coll, args.interval, frames=args.frames,
                       out=sys.stdout)
            return 0 if coll.nodes() else 1
        if args.plain or not sys.stdout.isatty():
            _run_plain(coll, args.interval, frames=None,
                       out=sys.stdout)
            return 0
        _run_curses(coll, args.interval)
        return 0
    finally:
        coll.close()


if __name__ == "__main__":
    import sys

    sys.exit(main())
