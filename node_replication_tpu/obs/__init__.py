"""Observability layer: metrics, tracing, and the fleet plane.

Single-process half (PR 1 lineage):

- `obs.metrics` — process-wide counters/gauges/histograms
  (`get_registry()`; enable with NR_TPU_METRICS=1).
- `obs.recorder` — the `Tracer` flight recorder and `span` timing
  context (enable with NR_TPU_TRACE=<path|mem>; fence-accurate spans
  with NR_TPU_TRACE_FENCE=1; per-record sampling with
  NR_TPU_TRACE_SAMPLE=1/N). `utils/trace.py` re-exports these for
  backward compatibility.
- `obs.report` — trace-report CLI:
  `python -m node_replication_tpu.obs.report trace.jsonl [--json]`.

Fleet half (multi-process trees, `serve/` + `repl/`):

- `obs.export` — `MetricsExporter`: serve one process's registry
  snapshot + trace tail on a side port (CRC-framed JSON; Prometheus
  text via `python -m node_replication_tpu.obs.export --scrape h:p`).
- `obs.collect` — `FleetCollector`: scrape N exporters into
  time-series rings + a merged `fleet.jsonl` whose events carry
  `node_id`/`role`/`t_fleet`; `obs.report`'s Fleet section joins it
  on `(pos, node_id)` into per-record cross-process hop timelines.
- `obs.top` — live fleet dashboard:
  `python -m node_replication_tpu.obs.top --targets h:p1,h:p2`.
"""

from node_replication_tpu.obs.metrics import (
    COUNT_BUCKETS,
    DURATION_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from node_replication_tpu.obs.recorder import (
    Tracer,
    get_tracer,
    pos_sampled,
    set_trace_sample,
    span,
    trace_sample_n,
)

__all__ = [
    "COUNT_BUCKETS",
    "DURATION_BUCKETS_S",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Tracer",
    "get_registry",
    "get_tracer",
    "pos_sampled",
    "set_trace_sample",
    "span",
    "trace_sample_n",
]
