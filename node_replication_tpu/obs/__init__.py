"""Observability layer: metrics registry + flight recorder + trace report.

- `obs.metrics` — process-wide counters/gauges/histograms
  (`get_registry()`; enable with NR_TPU_METRICS=1).
- `obs.recorder` — the `Tracer` flight recorder and `span` timing
  context (enable with NR_TPU_TRACE=<path|mem>; fence-accurate spans
  with NR_TPU_TRACE_FENCE=1). `utils/trace.py` re-exports these for
  backward compatibility.
- `obs.report` — trace-report CLI:
  `python -m node_replication_tpu.obs.report trace.jsonl`.
"""

from node_replication_tpu.obs.metrics import (
    COUNT_BUCKETS,
    DURATION_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from node_replication_tpu.obs.recorder import Tracer, get_tracer, span

__all__ = [
    "COUNT_BUCKETS",
    "DURATION_BUCKETS_S",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Tracer",
    "get_registry",
    "get_tracer",
    "span",
]
