"""Test env: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip hardware is not available in CI; sharding tests run on
`--xla_force_host_platform_device_count=8` virtual CPU devices, the
"multi-node without a cluster" idiom (the reference simulates NUMA nodes
with pinned OS threads in one process, SURVEY.md §4 idiom 5).

Note: the platform must be forced via `jax.config`, not JAX_PLATFORMS — the
environment's TPU plugin re-registers itself over the env var at interpreter
start, and a remote-tunnel TPU would make every host↔device transfer in the
suite cost ~100ms.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 budgeted run (-m 'not slow'); "
        "still runs in the unfiltered CI test job",
    )
