"""VSpace Pallas replay kernel tests (interpret mode on CPU).

Differential contract: the span kernels (flat + 4-level radix) must agree
BIT-identically with the sequential `apply_write` fold — responses and
final state — across adversarial windows: span overlaps, wrapped negative
vpages (flat), table teardown epochs (radix), NOOP padding, unknown
opcodes. `NR_TPU_SMOKE=1` additionally compiles and checks the Mosaic
lowering on real hardware.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from node_replication_tpu.core.log import LogSpec, log_init
from node_replication_tpu.core.replica import replicate_state
from node_replication_tpu.core.step import make_step
from node_replication_tpu.models import make_vspace, make_vspace_radix
from node_replication_tpu.ops.encoding import apply_write
from node_replication_tpu.ops.pallas_vspace import (
    make_pallas_vspace_step,
    make_vspace_replay,
    model_view,
    pallas_vspace_state,
)


def fold(d, state, opcodes, args):
    step = jax.jit(lambda s, o, a: apply_write(d, s, o, a))
    resps = []
    for i in range(len(opcodes)):
        state, r = step(state, opcodes[i], args[i])
        resps.append(int(r))
    return state, resps


def run_kernel(d, n_pages, max_span, radix, model_state, opcodes, args, R=3):
    replay = make_vspace_replay(
        n_pages, R, len(opcodes), max_span, radix, interpret=True
    )
    st = pallas_vspace_state(n_pages, R, radix, model_state)
    if radix:
        pt, pd, pdpt, pml4, resps = replay(
            opcodes, args, st["pt"], st["pd"], st["pdpt"], st["pml4"]
        )
        st = {"pt": pt, "pd": pd, "pdpt": pdpt, "pml4": pml4}
    else:
        frames, resps = replay(opcodes, args, st["frames"])
        st = {"frames": frames}
    return model_view(st, n_pages, radix), resps


class TestFlatKernel:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_matches_sequential_fold(self, seed):
        K, S, W = 300, 5, 48
        d = make_vspace(K, max_span=S)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=W, p=[0.1, 0.5, 0.3, 0.1]),
            jnp.int32,
        )
        # negative vpages wrap through the mod → split spans in-kernel
        args = jnp.asarray(
            np.stack([rng.integers(-4, K + 4, W), rng.integers(0, 50, W),
                      rng.integers(-1, S + 3, W)], axis=1),
            jnp.int32,
        )
        st0 = d.init_state()
        st0["frames"] = st0["frames"].at[::5].set(7)
        ref_state, ref_resps = fold(d, st0, opcodes, args)
        got, resps = run_kernel(d, K, S, False, st0, opcodes, args)
        # responses are the single canonical copy (lock-step invariant)
        assert [int(x) for x in resps] == ref_resps
        for r in range(got["frames"].shape[0]):
            np.testing.assert_array_equal(
                np.asarray(got["frames"][r]), np.asarray(ref_state["frames"])
            )


class TestRadixKernel:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_sequential_fold(self, seed):
        P, S, W = 1500, 20, 64
        d = make_vspace_radix(P, max_span=S)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 3, 4, 9], size=W,
                       p=[0.06, 0.3, 0.14, 0.25, 0.2, 0.05]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack([rng.integers(0, 2 * P, W), rng.integers(-2, 60, W),
                      rng.integers(-1, S + 3, W)], axis=1),
            jnp.int32,
        )
        st0 = d.init_state()
        st0["pt"] = st0["pt"].at[10:40].set(5).at[1100:1130].set(9)
        st0["pd"] = st0["pd"].at[0].set(True).at[1].set(True)
        st0["pdpt"] = st0["pdpt"].at[0].set(True)
        st0["pml4"] = st0["pml4"].at[0].set(True)
        ref_state, ref_resps = fold(d, st0, opcodes, args)
        got, resps = run_kernel(d, P, S, True, st0, opcodes, args)
        assert [int(x) for x in resps] == ref_resps
        for r in range(got["pt"].shape[0]):
            for k in ("pt", "pd", "pdpt", "pml4"):
                np.testing.assert_array_equal(
                    np.asarray(got[k][r]), np.asarray(ref_state[k]), k
                )


class TestPallasVspaceStep:
    def test_step_matches_scan_step(self):
        R, Bw, Br, P, S, STEPS = 3, 4, 2, 1100, 8, 4
        d = make_vspace_radix(P, max_span=S)
        spec = LogSpec(capacity=1 << 10, n_replicas=R, gc_slack=32)
        rng = np.random.default_rng(5)
        scan_step = make_step(d, spec, Bw, Br, jit=False, combined=False)
        pl_step = make_pallas_vspace_step(
            P, spec, Bw, Br, S, radix=True, interpret=True, jit=False
        )
        log_a, st_a = log_init(spec), replicate_state(d.init_state(), R)
        log_b = log_init(spec)
        st_b = pallas_vspace_state(P, R, True, d.init_state())
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, 1, 2, 3, 4], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                np.stack([rng.integers(0, P, (R, Bw)),
                          rng.integers(0, 60, (R, Bw)),
                          rng.integers(0, S + 1, (R, Bw))], axis=-1),
                jnp.int32,
            )
            rd_opc = jnp.asarray(
                rng.choice([1, 2, 3], size=(R, Br)), jnp.int32
            )
            rd_args = jnp.asarray(
                np.stack([rng.integers(0, P, (R, Br)),
                          rng.integers(1, 9, (R, Br)),
                          np.zeros((R, Br))], axis=-1),
                jnp.int32,
            )
            log_a, st_a, wr_a, rd_a = scan_step(
                log_a, st_a, wr_opc, wr_args, rd_opc, rd_args
            )
            log_b, st_b, wr_b, rd_b = pl_step(
                log_b, st_b, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_a), np.asarray(wr_b))
            np.testing.assert_array_equal(np.asarray(rd_a), np.asarray(rd_b))
        view = model_view(st_b, P, True)
        for k in ("pt", "pd", "pdpt", "pml4"):
            np.testing.assert_array_equal(
                np.asarray(view[k]), np.asarray(st_a[k]), k
            )
        for name in ("tail", "ctail"):
            assert int(getattr(log_a, name)) == int(getattr(log_b, name))


class TestPlanStep:
    """Pallas-planned step (r5): canonical-replica kernel plan + vmapped
    model-side window_merge. Bit-exact vs the generic scan step across
    multi-step drives — states, write resps, read resps, cursors."""

    @pytest.mark.parametrize("seed", [0, 1])
    def test_radix_plan_step_matches_scan_step(self, seed):
        from node_replication_tpu.ops.pallas_vspace import (
            make_pallas_vspace_plan_step,
        )

        R, Bw, Br, P, S, STEPS = 3, 4, 2, 1100, 8, 4
        d = make_vspace_radix(P, max_span=S)
        spec = LogSpec(capacity=1 << 10, n_replicas=R, gc_slack=32)
        rng = np.random.default_rng(seed)
        scan_step = make_step(d, spec, Bw, Br, jit=False, combined=False)
        plan_step = make_pallas_vspace_plan_step(
            P, spec, Bw, Br, S, radix=True, dispatch=d, interpret=True,
            jit=False,
        )
        log_a, st_a = log_init(spec), replicate_state(d.init_state(), R)
        log_b, st_b = log_init(spec), replicate_state(d.init_state(), R)
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, 1, 2, 3, 4], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                np.stack([rng.integers(0, P, (R, Bw)),
                          rng.integers(0, 60, (R, Bw)),
                          rng.integers(0, S + 1, (R, Bw))], axis=-1),
                jnp.int32,
            )
            rd_opc = jnp.asarray(
                rng.choice([1, 2, 3], size=(R, Br)), jnp.int32
            )
            rd_args = jnp.asarray(
                np.stack([rng.integers(0, P, (R, Br)),
                          rng.integers(1, 9, (R, Br)),
                          np.zeros((R, Br))], axis=-1),
                jnp.int32,
            )
            log_a, st_a, wr_a, rd_a = scan_step(
                log_a, st_a, wr_opc, wr_args, rd_opc, rd_args
            )
            log_b, st_b, wr_b, rd_b = plan_step(
                log_b, st_b, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_a),
                                          np.asarray(wr_b))
            np.testing.assert_array_equal(np.asarray(rd_a),
                                          np.asarray(rd_b))
        for k in ("pt", "pd", "pdpt", "pml4"):
            np.testing.assert_array_equal(
                np.asarray(st_b[k]), np.asarray(st_a[k]), k
            )
        for name in ("tail", "ctail", "head"):
            assert int(getattr(log_a, name)) == int(getattr(log_b, name))
        np.testing.assert_array_equal(
            np.asarray(log_a.ltails), np.asarray(log_b.ltails)
        )

    def test_flat_plan_step_matches_scan_step(self):
        from node_replication_tpu.models import make_vspace
        from node_replication_tpu.ops.pallas_vspace import (
            make_pallas_vspace_plan_step,
        )

        R, Bw, Br, P, S, STEPS = 2, 4, 2, 1024, 8, 4
        d = make_vspace(P, max_span=S)
        spec = LogSpec(capacity=1 << 10, n_replicas=R, gc_slack=32)
        rng = np.random.default_rng(3)
        scan_step = make_step(d, spec, Bw, Br, jit=False, combined=False)
        plan_step = make_pallas_vspace_plan_step(
            P, spec, Bw, Br, S, radix=False, dispatch=d, interpret=True,
            jit=False,
        )
        log_a, st_a = log_init(spec), replicate_state(d.init_state(), R)
        log_b, st_b = log_init(spec), replicate_state(d.init_state(), R)
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, 1, 2], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                np.stack([rng.integers(-3, P, (R, Bw)),
                          rng.integers(0, 60, (R, Bw)),
                          rng.integers(0, S + 1, (R, Bw))], axis=-1),
                jnp.int32,
            )
            rd_opc = jnp.asarray(
                rng.choice([1, 2], size=(R, Br)), jnp.int32
            )
            rd_args = jnp.asarray(
                np.stack([rng.integers(0, P, (R, Br)),
                          rng.integers(1, 9, (R, Br)),
                          np.zeros((R, Br))], axis=-1),
                jnp.int32,
            )
            log_a, st_a, wr_a, rd_a = scan_step(
                log_a, st_a, wr_opc, wr_args, rd_opc, rd_args
            )
            log_b, st_b, wr_b, rd_b = plan_step(
                log_b, st_b, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_a),
                                          np.asarray(wr_b))
            np.testing.assert_array_equal(np.asarray(rd_a),
                                          np.asarray(rd_b))
        np.testing.assert_array_equal(
            np.asarray(st_b["frames"]), np.asarray(st_a["frames"])
        )


@pytest.mark.skipif(
    not os.environ.get("NR_TPU_SMOKE"),
    reason="hardware smoke (set NR_TPU_SMOKE=1 on a real TPU). Proven r4 "
           "on TPU v5e: long-log R=4 full step 4.7 ms -> 3.48M disp/s vs "
           "0.021M for the generic scan (~166x) at the identical config.",
)
class TestHardwareSmoke:
    def test_radix_kernel_on_device(self):
        # subprocess: the suite's conftest forces jax_platforms=cpu, so
        # the hardware probe needs a fresh interpreter on the default
        # (TPU) platform
        import subprocess
        import sys

        code = """
import numpy as np, jax, jax.numpy as jnp
assert jax.devices()[0].platform != "cpu", jax.devices()
from node_replication_tpu.models import make_vspace_radix
from node_replication_tpu.ops.encoding import apply_write
from node_replication_tpu.ops.pallas_vspace import (
    make_vspace_replay, pallas_vspace_state, model_view)
P, S, W, R = 1 << 14, 64, 256, 4
d = make_vspace_radix(P, max_span=S)
rng = np.random.default_rng(0)
opc = jnp.asarray(rng.choice([1, 2, 3, 4], size=W), jnp.int32)
args = jnp.asarray(np.stack([rng.integers(0, P, W),
    rng.integers(0, 1000, W), 1 + rng.integers(0, S, W)], axis=1),
    jnp.int32)
st0 = d.init_state()
step = jax.jit(lambda s, o, a: apply_write(d, s, o, a))
ref, rresp = st0, []
for i in range(W):
    ref, r = step(ref, opc[i], args[i])
    rresp.append(int(r))
replay = jax.jit(make_vspace_replay(P, R, W, S, radix=True))
st = pallas_vspace_state(P, R, True, st0)
pt, pd, pdpt, pml4, resps = replay(
    opc, args, st["pt"], st["pd"], st["pdpt"], st["pml4"])
view = model_view({"pt": pt, "pd": pd, "pdpt": pdpt, "pml4": pml4}, P, True)
for k in ("pt", "pd", "pdpt", "pml4"):
    for r in range(R):
        np.testing.assert_array_equal(
            np.asarray(view[k][r]), np.asarray(ref[k]), k)
assert [int(x) for x in np.asarray(resps)] == rresp
print("vspace-pallas-on-tpu OK", jax.devices()[0].device_kind)
"""
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=560, cwd="/root/repo",
        )
        assert "vspace-pallas-on-tpu OK" in out.stdout, (
            out.stdout + out.stderr
        )
