"""fault/ — replica lifecycle (ISSUE 4): injection determinism, the
health state machine, fenced-head GC progress, repair bit-identity,
and serve failover under injected kills.

The failover test is the acceptance story: clients drive sequence-
numbered ops through a failover-enabled frontend while a FaultPlan
kills a replica's worker; every client must get either a correct
response or a retryable `ReplicaFailed` — no hangs, and no duplicates
after retry (the seqreg oracle would surface a duplicate as a
mismatched previous-value response).
"""

import threading
import time

import numpy as np
import pytest

from node_replication_tpu import NodeReplicated
from node_replication_tpu.core.replica import ReplicaFencedError
from node_replication_tpu.fault import (
    HEALTHY,
    MAX_STALL_S,
    QUARANTINED,
    REPAIRING,
    SUSPECT,
    FaultError,
    FaultPlan,
    FaultSpec,
    HealthTracker,
    IllegalTransition,
    ReplicaLifecycleManager,
    corrupt_states,
    divergence_vote,
    fault_hook,
    repair_replica,
)
from node_replication_tpu.models import (
    HM_GET,
    HM_PUT,
    SR_GET,
    SR_SET,
    make_hashmap,
    make_seqreg,
)
from node_replication_tpu.serve import (
    ReplicaFailed,
    RetryPolicy,
    ServeConfig,
    ServeFrontend,
    call_with_retry,
)


def small_nr(dispatch=None, **kw):
    kw.setdefault("n_replicas", 2)
    kw.setdefault("log_entries", 512)
    kw.setdefault("gc_slack", 32)
    kw.setdefault("exec_window", 64)
    return NodeReplicated(dispatch or make_seqreg(4), **kw)


class TestFaultPlanDeterminism:
    def test_same_seed_same_schedule(self):
        a = FaultPlan.chaos(seed=42, n_faults=5, n_replicas=4)
        b = FaultPlan.chaos(seed=42, n_faults=5, n_replicas=4)
        assert a.schedule() == b.schedule()

    def test_different_seed_different_schedule(self):
        a = FaultPlan.chaos(seed=1, n_faults=8, n_replicas=4)
        b = FaultPlan.chaos(seed=2, n_faults=8, n_replicas=4)
        assert a.schedule() != b.schedule()

    def test_fires_on_exact_hit_and_spends(self):
        plan = FaultPlan([FaultSpec(site="append", action="raise",
                                    rid=0, after=2, count=1)])
        with plan.armed():
            fault_hook("append", 0)  # hit 0
            fault_hook("append", 0)  # hit 1
            with pytest.raises(FaultError) as ei:
                fault_hook("append", 0)  # hit 2: fires
            assert ei.value.site == "append" and ei.value.rid == 0
            fault_hook("append", 0)  # spent: no second fire
        assert [f["hit"] for f in plan.fired] == [2]

    def test_rid_filter_and_site_isolation(self):
        plan = FaultPlan([FaultSpec(site="replay", action="raise",
                                    rid=1, after=0)])
        with plan.armed():
            fault_hook("append", 1)   # wrong site
            fault_hook("replay", 0)   # wrong rid
            with pytest.raises(FaultError):
                fault_hook("replay", 1)
        assert len(plan.fired) == 1

    def test_disarmed_is_inert(self):
        plan = FaultPlan([FaultSpec(site="replay", action="raise")])
        fault_hook("replay", 0)  # not armed: nothing happens
        plan.arm()
        plan.disarm()
        fault_hook("replay", 0)
        assert plan.fired == []

    def test_same_call_sequence_same_fires(self):
        # determinism end to end: replaying the same hook sequence
        # against two same-seed plans fires identically
        def drive(plan):
            hits = []
            with plan.armed():
                for site, rid in [("replay", 0), ("append", 1),
                                  ("replay", 1), ("serve-batch", 0),
                                  ("replay", 0), ("append", 1)]:
                    try:
                        fault_hook(site, rid)
                    except FaultError:
                        pass
                    time.sleep(0)  # scheduler noise must not matter
                hits = [dict(f) for f in plan.fired]
            return hits

        p1 = FaultPlan.chaos(seed=9, n_faults=4, n_replicas=2,
                             actions=("raise",), max_after=3)
        p2 = FaultPlan.chaos(seed=9, n_faults=4, n_replicas=2,
                             actions=("raise",), max_after=3)
        assert drive(p1) == drive(p2)

    def test_rid_filtered_after_counts_victim_hits_only(self):
        # determinism under concurrency: a rid-filtered spec triggers
        # on the VICTIM's own hit sequence — other replicas' hits at
        # the same site (whatever the thread interleaving produced)
        # must not advance it
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=2)])
        with plan.armed():
            for _ in range(10):
                fault_hook("serve-batch", 0)  # noise from replica 0
            fault_hook("serve-batch", 1)  # victim hit 0
            fault_hook("serve-batch", 1)  # victim hit 1
            with pytest.raises(FaultError):
                fault_hook("serve-batch", 1)  # victim hit 2: fires
        assert plan.fired[0]["hit"] == 2

    def test_stall_is_bounded(self):
        spec = FaultSpec(site="replay", action="stall", stall_s=999.0)
        assert spec.effective_stall_s == MAX_STALL_S
        plan = FaultPlan([FaultSpec(site="replay", action="stall",
                                    stall_s=0.01)])
        t0 = time.monotonic()
        with plan.armed():
            fault_hook("replay", 0)
        assert 0.005 <= time.monotonic() - t0 < 1.0
        assert plan.fired[0]["action"] == "stall"

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            FaultSpec(site="bogus", action="raise")
        with pytest.raises(ValueError):
            FaultSpec(site="replay", action="bogus")
        with pytest.raises(ValueError):
            FaultSpec(site="replay", action="raise", count=0)


class TestHealthStateMachine:
    def test_full_lifecycle_walk(self):
        h = HealthTracker(2)
        assert h.state(0) == HEALTHY
        assert h.report_worker_exception(0) == SUSPECT
        h.transition(0, QUARANTINED)
        h.transition(0, REPAIRING)
        h.transition(0, HEALTHY)
        assert h.state(0) == HEALTHY
        assert h.state(1) == HEALTHY  # untouched
        walked = [(rid, frm, to) for _, rid, frm, to in h.timeline]
        assert walked == [
            (0, HEALTHY, SUSPECT), (0, SUSPECT, QUARANTINED),
            (0, QUARANTINED, REPAIRING), (0, REPAIRING, HEALTHY),
        ]

    def test_timeline_stamps_use_injected_clock(self):
        # ISSUE 8 satellite regression: under SimClock the lifecycle
        # timeline (and obs/report.py's fault section built from it)
        # carries VIRTUAL stamps — a simulated quarantine at t=100.5
        # is recorded at t=100.5, not at some wall-clock instant
        from node_replication_tpu.utils.clock import SimClock, installed

        with installed(SimClock(start=100.0)) as clock:
            h = HealthTracker(1)
            h.report_worker_exception(0)
            clock.advance(0.5)
            h.quarantine(0)
        stamps = [ts for ts, *_ in h.timeline]
        assert stamps == [100.0, 100.5]

    def test_illegal_transitions_raise(self):
        h = HealthTracker(1)
        with pytest.raises(IllegalTransition):
            h.transition(0, REPAIRING)  # healthy -> repairing
        h.report_worker_exception(0)
        with pytest.raises(IllegalTransition):
            h.transition(0, REPAIRING)  # suspect -> repairing

    def test_failed_repair_goes_back_to_quarantine(self):
        h = HealthTracker(1)
        h.quarantine(0)
        h.transition(0, REPAIRING)
        h.transition(0, QUARANTINED)  # legal: repair failed
        assert h.state(0) == QUARANTINED

    def test_stall_threshold(self):
        h = HealthTracker(1, stall_threshold=3)
        assert h.report_stall(0) == HEALTHY
        assert h.report_stall(0) == HEALTHY
        assert h.report_stall(0) == SUSPECT

    def test_probation_clears_strikes(self):
        h = HealthTracker(1, exc_threshold=2)
        h.report_worker_exception(0)
        h.report_worker_exception(0)
        assert h.state(0) == SUSPECT
        h.clear_suspect(0)
        assert h.state(0) == HEALTHY
        # strikes were reset: one new strike does not re-suspect
        assert h.report_worker_exception(0) == HEALTHY

    def test_healthy_rids_and_grow(self):
        h = HealthTracker(3)
        h.quarantine(1)
        assert h.healthy_rids() == [0, 2]
        h.grow(2)
        assert h.healthy_rids() == [0, 2, 3, 4]

    def test_divergence_vote_names_minority(self):
        nr = small_nr(make_seqreg(4), n_replicas=3)
        nr.execute_mut_batch([(SR_SET, i % 4, i + 1)
                              for i in range(12)], rid=0)
        nr.sync()
        assert divergence_vote(nr.states) == []
        nr.states = corrupt_states(nr.states, 1)
        assert divergence_vote(nr.states) == [1]

    def test_vote_without_quorum_names_nobody(self):
        # a 1-1 split in a 2-replica fleet has no strict majority: the
        # vote must NOT name anyone — acting on an arbitrary bloc
        # could quarantine the healthy replica and clone the corrupt
        # donor fleet-wide
        nr = small_nr(make_seqreg(4), n_replicas=2)
        nr.execute_mut_batch([(SR_SET, 0, 1)], rid=0)
        nr.sync()
        nr.states = corrupt_states(nr.states, 0)
        assert divergence_vote(nr.states) == []
        h = HealthTracker(2)
        assert h.probe(nr.states) == []
        assert h.states() == [HEALTHY, HEALTHY]

    def test_probe_quarantines_minority(self):
        nr = small_nr(make_seqreg(4), n_replicas=3)
        nr.sync()
        nr.states = corrupt_states(nr.states, 2)
        h = HealthTracker(3)
        assert h.probe(nr.states) == [2]
        assert h.state(2) == QUARANTINED
        # a second probe does not re-quarantine (already in pipeline)
        assert h.probe(nr.states) == [2]
        assert h.states().count(QUARANTINED) == 1


class TestFencedGC:
    def test_fenced_head_advances_scan_engine(self):
        # seqreg has no window form: the scan engine's fenced path
        nr = small_nr(make_seqreg(2), log_entries=128, gc_slack=16)
        nr.execute_mut_batch([(SR_SET, 0, i + 1)
                              for i in range(20)], rid=0)
        nr.sync()
        nr.fence_replica(1)
        expect = 20
        # 3 x 60 appends push tail to 200 > capacity 128: impossible
        # unless GC advanced head past the fenced replica's ltail (20)
        for _ in range(3):
            resps = nr.execute_mut_batch(
                [(SR_SET, 0, expect + j + 1) for j in range(60)],
                rid=0,
            )
            assert resps == [expect + j for j in range(60)]
            expect += 60
        ltails = np.asarray(nr.log.ltails)
        assert int(ltails[1]) == 20  # frozen
        assert int(np.asarray(nr.log.head)) > 20  # GC passed it
        assert int(np.asarray(nr.log.tail)) == 200
        assert nr.fenced_rids == [1]

    def test_fenced_head_advances_union_engine(self):
        # hashmap routes through the combined catch-up engine
        nr = small_nr(make_hashmap(32), log_entries=128, gc_slack=16)
        assert nr.engine == "combined"
        nr.execute_mut_batch([(HM_PUT, i % 32, i)
                              for i in range(20)], rid=0)
        nr.sync()
        nr.fence_replica(1)
        for _ in range(3):
            nr.execute_mut_batch(
                [(HM_PUT, j % 32, j + 100) for j in range(60)], rid=0
            )
        assert int(np.asarray(nr.log.head)) > 20
        assert int(np.asarray(nr.log.ltails)[1]) == 20

    def test_fenced_guards_fail_fast(self):
        nr = small_nr(make_seqreg(2))
        tok = nr.register(1)
        nr.fence_replica(1)
        with pytest.raises(ReplicaFencedError):
            nr.execute_mut_batch([(SR_SET, 0, 1)], rid=1)
        with pytest.raises(ReplicaFencedError):
            nr.execute((SR_GET, 0), tok)
        with pytest.raises(ReplicaFencedError):
            nr.sync(1)
        nr.sync()  # all-replica sync skips the fenced one: no hang

    def test_fence_idempotent_unfence_restores_fast_path(self):
        nr = small_nr(make_seqreg(2))
        nr.fence_replica(1)
        nr.fence_replica(1)
        assert nr.fenced_rids == [1]
        nr.clone_replica_from(1)
        nr.unfence_replica(1)
        nr.unfence_replica(1)
        assert nr.fenced_rids == []
        assert nr._fenced is None  # no-mask hot path restored

    def test_grow_fleet_never_clones_fenced_donor(self):
        nr = small_nr(make_seqreg(2), n_replicas=2)
        nr.execute_mut_batch([(SR_SET, 0, i + 1)
                              for i in range(8)], rid=0)
        nr.sync()
        nr.states = corrupt_states(nr.states, 1)
        nr.fence_replica(1)
        with pytest.raises(ReplicaFencedError):
            nr.grow_fleet(1, donor=1)
        new = nr.grow_fleet(1)  # auto-donor must pick replica 0
        repair_replica(nr, 1)
        nr.sync()
        assert nr.replicas_equal()
        assert nr.n_replicas == 3 and new == [2]

    def test_snapshot_reports_fenced(self):
        nr = small_nr(make_seqreg(2))
        nr.fence_replica(0)
        assert nr.snapshot()["replicas"]["fenced"] == [0]


class TestRepairBitIdentity:
    def test_repaired_state_matches_never_faulted_fleet(self):
        # fleet A suffers a corruption + quarantine + repair mid-way
        # through an op stream; fleet B runs the same stream untouched.
        # Deterministic replay makes their final states bit-identical.
        def ops(base):
            return [(SR_SET, i % 4, base + i + 1) for i in range(40)]

        a = small_nr(make_seqreg(4), n_replicas=3)
        b = small_nr(make_seqreg(4), n_replicas=3)
        a.execute_mut_batch(ops(0), rid=0)
        b.execute_mut_batch(ops(0), rid=0)
        a.sync()
        b.sync()

        a.states = corrupt_states(a.states, 1)
        assert divergence_vote(a.states) == [1]
        a.fence_replica(1)
        a.execute_mut_batch(ops(100), rid=0)  # traffic during repair
        b.execute_mut_batch(ops(100), rid=0)
        report = repair_replica(a, 1)
        assert report["rid"] == 1 and report["donor"] != 1
        a.sync()
        b.sync()
        assert a.replicas_equal() and b.replicas_equal()
        assert divergence_vote(a.states) == []
        import jax

        for la, lb in zip(jax.tree.leaves(a.states),
                          jax.tree.leaves(b.states)):
            assert np.array_equal(np.asarray(la), np.asarray(lb))

    def test_repair_after_ring_wrap(self):
        # the fenced cursor falls behind the GC head and the ring
        # wraps over its entries; repair must still be exact because
        # it replays from the DONOR's cursor, not the corpse's
        nr = small_nr(make_seqreg(2), log_entries=128, gc_slack=16)
        nr.execute_mut_batch([(SR_SET, 0, i + 1)
                              for i in range(10)], rid=0)
        nr.sync()
        nr.fence_replica(1)
        expect = 10
        for _ in range(4):
            nr.execute_mut_batch(
                [(SR_SET, 0, expect + j + 1) for j in range(60)],
                rid=0,
            )
            expect += 60
        assert int(np.asarray(nr.log.tail)) > 128  # wrapped
        repair_replica(nr, 1)
        nr.sync()
        assert nr.replicas_equal()
        reader = nr.register(1)
        assert nr.execute((SR_GET, 0), reader) == expect

    def test_manager_probe_repairs_silent_corruption(self):
        nr = small_nr(make_seqreg(4), n_replicas=3)
        nr.execute_mut_batch([(SR_SET, i % 4, i + 1)
                              for i in range(12)], rid=0)
        nr.sync()
        mgr = ReplicaLifecycleManager(nr)
        assert mgr.probe() == []  # healthy fleet: vote is unanimous
        nr.states = corrupt_states(nr.states, 2)
        assert mgr.probe() == [2]
        assert mgr.health.state(2) == HEALTHY  # repaired
        assert len(mgr.repairs) == 1
        nr.sync()
        assert nr.replicas_equal()


class TestServeFailover:
    CLIENTS = 8
    PER_CLIENT = 60

    def test_kill_under_load_no_loss_no_dup_no_hang(self):
        """The acceptance story: 8 clients, a kill mid-run, and every
        client gets either a correct response or a retryable
        `ReplicaFailed`; with retry enabled nothing is lost and the
        seqreg oracle proves nothing duplicated."""
        nr = small_nr(make_seqreg(self.CLIENTS), n_replicas=2,
                      log_entries=2048, gc_slack=128,
                      exec_window=128)
        fe = ServeFrontend(nr, ServeConfig(
            queue_depth=128, batch_max_ops=16, batch_linger_s=0.0,
            failover=True,
        ))
        mgr = ReplicaLifecycleManager(nr, fe)
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=10)])
        errors: list = []

        def client(c):
            rid = c % 2
            pol = RetryPolicy(max_attempts=16, base_backoff_s=0.001,
                              max_backoff_s=0.1)
            for i in range(self.PER_CLIENT):
                try:
                    resp = call_with_retry(
                        fe, (SR_SET, c, i + 1), rid=rid, policy=pol,
                        timeout=120.0,
                    )
                except ReplicaFailed as e:
                    # acceptable ONLY if typed retryable (policy
                    # exhausted); an unretryable one means a possible
                    # duplicate and fails the test
                    if not e.retryable:
                        errors.append((c, i, "unretryable", str(e)))
                    else:
                        errors.append((c, i, "exhausted", str(e)))
                    return
                except Exception as e:  # no hangs, no untyped errors
                    errors.append((c, i, type(e).__name__, str(e)))
                    return
                if resp != i:
                    errors.append((c, i, "sequence", resp))
                    return

        with plan.armed():
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(self.CLIENTS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert not any(t.is_alive() for t in threads), "hung client"
        assert not errors, errors[:5]
        assert plan.fired, "kill never fired"
        assert mgr.wait_idle(60)
        assert mgr.health.state(1) == HEALTHY
        assert len(mgr.repairs) == 1
        # the repaired replica serves again on its own queue
        assert fe.healthy_rids() == [0, 1]
        assert fe.call((SR_SET, 0, self.PER_CLIENT + 1), rid=1,
                       timeout=60.0) == self.PER_CLIENT
        st = fe.stats()
        assert st["completed"] == self.CLIENTS * self.PER_CLIENT + 1
        fe.close()
        nr.sync()
        assert nr.replicas_equal()

    def test_submit_to_failed_replica_is_typed_retryable(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, ServeConfig(batch_linger_s=0.0,
                                           failover=True))
        mgr = ReplicaLifecycleManager(nr, fe)
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=0)])
        with plan.armed():
            fut = fe.submit((SR_SET, 0, 1), rid=1)
            with pytest.raises(ReplicaFailed) as ei:
                fut.result(30.0)
            assert ei.value.retryable  # pre-append kill: exactly-once
            # mid-quarantine submits are typed + retryable, never hangs
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    fe.submit((SR_SET, 0, 2), rid=1)
                    break  # restarted already
                except ReplicaFailed as e:
                    assert e.retryable
                    time.sleep(0.01)
        assert mgr.wait_idle(60)
        assert fe.call((SR_SET, 1, 1), rid=1, timeout=30.0) == 0
        fe.close()

    def test_queued_requests_rehomed_to_healthy_replica(self):
        # a paused frontend stacks a backlog on the victim; the first
        # batch takes some, the kill re-homes the remainder onto the
        # healthy replica — every future still resolves correctly
        # (fresh slots: order across replicas is immaterial)
        nr = small_nr(make_seqreg(16), n_replicas=2)
        fe = ServeFrontend(
            nr,
            ServeConfig(queue_depth=32, batch_max_ops=4,
                        batch_linger_s=0.0, failover=True),
            auto_start=False,
        )
        mgr = ReplicaLifecycleManager(nr, fe)
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=0)])
        futs = [fe.submit((SR_SET, s, 7), rid=1) for s in range(12)]
        with plan.armed():
            fe.start()
            outcomes = []
            for s, fut in enumerate(futs):
                try:
                    outcomes.append(("ok", fut.result(60.0)))
                except ReplicaFailed as e:
                    assert e.retryable
                    outcomes.append(("failed", None))
        assert mgr.wait_idle(60)
        oks = [o for o in outcomes if o[0] == "ok"]
        # the first batch (up to batch_max_ops) died; the re-homed
        # remainder completed with the correct previous value 0
        assert len(oks) >= 12 - 4
        assert all(v == 0 for _, v in oks)
        assert fe.stats()["rehomed"] >= 8
        fe.close()

    def test_maybe_executed_is_not_auto_retried(self):
        class OneShotFrontend:
            def __init__(self):
                self.calls = 0

            def call(self, op, rid=0, deadline_s=None, timeout=None):
                self.calls += 1
                raise ReplicaFailed(rid, RuntimeError("mid-replay"),
                                    maybe_executed=True)

            def healthy_rids(self):
                return [0, 1]

        fe = OneShotFrontend()
        with pytest.raises(ReplicaFailed) as ei:
            call_with_retry(fe, (SR_SET, 0, 1),
                            policy=RetryPolicy(max_attempts=5))
        assert fe.calls == 1  # refused: retry could duplicate the op
        assert not ei.value.retryable

    def test_retry_reroutes_to_healthy_rid(self):
        class FailThenServe:
            def __init__(self):
                self.rids_seen = []

            def call(self, op, rid=0, deadline_s=None, timeout=None):
                self.rids_seen.append(rid)
                if rid == 1:
                    raise ReplicaFailed(1, maybe_executed=False)
                return 42

            def healthy_rids(self):
                return [0]

        fe = FailThenServe()
        out = call_with_retry(
            fe, (SR_SET, 0, 1), rid=1,
            policy=RetryPolicy(max_attempts=4, base_backoff_s=0.0001,
                               max_backoff_s=0.001),
        )
        assert out == 42
        assert fe.rids_seen == [1, 0]

    def test_repair_runs_even_below_suspect_threshold(self):
        # a tracker with exc_threshold > 1 leaves the replica HEALTHY
        # after the single report that killed its worker; the medic
        # must still quarantine (through SUSPECT) and repair — not die
        # on an illegal HEALTHY -> QUARANTINED edge
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, ServeConfig(batch_linger_s=0.0,
                                           failover=True))
        mgr = ReplicaLifecycleManager(
            nr, fe, health=HealthTracker(2, exc_threshold=3)
        )
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=0)])
        with plan.armed():
            fut = fe.submit((SR_SET, 0, 1), rid=1)
            with pytest.raises(ReplicaFailed):
                fut.result(30.0)
        assert mgr.wait_idle(60)
        assert len(mgr.repairs) == 1
        assert mgr.health.state(1) == HEALTHY
        assert fe.call((SR_SET, 0, 1), rid=1, timeout=30.0) == 0
        fe.close()

    def test_closed_frontend_wins_over_failed_replica(self):
        # FrontendClosed is permanent; after close() a still-failed
        # rid must not feed retry loops a retryable ReplicaFailed
        from node_replication_tpu.serve import FrontendClosed

        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, ServeConfig(batch_linger_s=0.0,
                                           failover=True))
        # no lifecycle manager: the replica stays failed
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=0)])
        with plan.armed():
            fut = fe.submit((SR_SET, 0, 1), rid=1)
            with pytest.raises(ReplicaFailed):
                fut.result(30.0)
        with pytest.raises(ReplicaFailed):
            fe.submit((SR_SET, 0, 2), rid=1)  # open + failed: typed
        fe.close()
        with pytest.raises(FrontendClosed):
            fe.submit((SR_SET, 0, 3), rid=1)  # closed: permanent

    def test_rehome_does_not_double_count_accepted(self):
        nr = small_nr(make_seqreg(8), n_replicas=2)
        fe = ServeFrontend(
            nr,
            ServeConfig(queue_depth=32, batch_max_ops=4,
                        batch_linger_s=0.0, failover=True),
            auto_start=False,
        )
        mgr = ReplicaLifecycleManager(nr, fe)
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=0)])
        futs = [fe.submit((SR_SET, s, 7), rid=1) for s in range(8)]
        assert fe.stats()["accepted"] == 8
        with plan.armed():
            fe.start()
            for fut in futs:
                try:
                    fut.result(60.0)
                except ReplicaFailed:
                    pass
        assert mgr.wait_idle(60)
        fe.drain(30.0)
        st = fe.stats()
        # re-homing moved requests, it did not re-admit them: the 8
        # original admissions stay 8 (retired-queue folding included)
        assert st["accepted"] == 8, st
        assert st["rehomed"] >= 4
        fe.close()

    def test_restart_requires_failed_replica(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, ServeConfig(failover=True))
        with pytest.raises(ValueError):
            fe.restart_replica(0)
        fe.close()

    def test_failover_off_keeps_worker_alive(self):
        # the pre-fault contract: without failover a failed batch
        # rejects its own futures and the SAME worker keeps serving
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, ServeConfig(batch_linger_s=0.0))
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=0, after=0)])
        with plan.armed():
            fut = fe.submit((SR_SET, 0, 1), rid=0)
            with pytest.raises(FaultError):
                fut.result(30.0)
        assert fe.healthy_rids() == [0, 1]
        assert fe.call((SR_SET, 0, 1), rid=0, timeout=30.0) == 0
        fe.close()


class TestMeasureChaos:
    def test_measure_chaos_and_rows(self):
        from node_replication_tpu.harness.mkbench import (
            chaos_rows,
            measure_chaos,
        )

        clients = 4
        nr = small_nr(make_seqreg(clients), n_replicas=2,
                      log_entries=2048, gc_slack=128)
        fe = ServeFrontend(nr, ServeConfig(
            queue_depth=64, batch_max_ops=8, batch_linger_s=0.0,
            failover=True,
        ))
        mgr = ReplicaLifecycleManager(nr, fe)
        plan = FaultPlan([FaultSpec(site="serve-batch",
                                    action="raise", rid=1, after=5)])

        def check(c, i, resp):
            return None if resp == i else f"{c}/{i}: {resp}"

        with fe:
            res = measure_chaos(
                fe, mgr, plan, lambda c, i: (SR_SET, c, i + 1),
                120, clients, retry=RetryPolicy(max_attempts=16),
                check=check, name="t",
            )
        assert res.serve.completed == 120
        assert res.serve.errors == []
        assert res.availability == 1.0
        assert len(res.fired) == 1 and len(res.repairs) == 1
        assert res.health["states"] == [HEALTHY, HEALTHY]
        assert res.repair_ms(50) > 0
        (row,) = chaos_rows("t", res)
        assert row["lost"] == 0 and row["kills"] == 1
        assert row["availability"] == 1.0
        assert row["repair_p95_ms"] >= row["repair_p50_ms"] > 0


class TestFaultReportSection:
    def test_fault_section_from_events(self):
        from node_replication_tpu.obs.report import analyze, render

        events = [
            {"event": "fault-inject", "mono": 10.0, "site":
                "serve-batch", "rid": 1, "action": "raise"},
            {"event": "fault-transition", "mono": 10.1, "rid": 1,
             "frm": "healthy", "to": "suspect"},
            {"event": "fault-transition", "mono": 10.2, "rid": 1,
             "frm": "suspect", "to": "quarantined"},
            {"event": "fault-transition", "mono": 10.3, "rid": 1,
             "frm": "quarantined", "to": "repairing"},
            {"event": "fault-repair", "mono": 10.8, "rid": 1,
             "donor": 0, "duration_s": 0.5},
            {"event": "fault-transition", "mono": 10.8, "rid": 1,
             "frm": "repairing", "to": "healthy"},
            {"event": "serve-rehome", "mono": 10.15, "rid": 1, "n": 3},
        ]
        rep = analyze(events)
        f = rep["fault"]
        assert f["injected"] == 1 and f["quarantines"] == 1
        assert f["repairs"] == 1 and f["rehomed"] == 3
        assert f["repair_p50_s"] == 0.5
        assert f["repair_hist_ms"] == {512: 1}
        assert [to for _, _, to in f["timeline"][1]] == [
            "suspect", "quarantined", "repairing", "healthy",
        ]
        import io

        out = io.StringIO()
        render(rep, out=out)
        text = out.getvalue()
        assert "== fault ==" in text
        assert "re-homed requests: 3" in text
        assert "r1:" in text

    def test_no_fault_events_no_section(self):
        from node_replication_tpu.obs.report import analyze, render

        rep = analyze([{"event": "append", "mono": 1.0, "n": 2}])
        assert rep["fault"] is None
        import io

        out = io.StringIO()
        render(rep, out=out)
        assert "== fault ==" not in out.getvalue()

    def test_lifecycle_events_flow_to_report(self):
        # end to end: a real quarantine+repair, traced in memory mode,
        # renders a fault section
        from node_replication_tpu.obs.report import analyze
        from node_replication_tpu.utils.trace import get_tracer

        tracer = get_tracer()
        was = tracer.enabled
        tracer.enable(None)  # memory-buffer mode
        try:
            # 3 replicas: the digest vote needs a strict majority
            nr = small_nr(make_seqreg(4), n_replicas=3)
            nr.execute_mut_batch([(SR_SET, 0, 1)], rid=0)
            nr.sync()
            mgr = ReplicaLifecycleManager(nr)
            nr.states = corrupt_states(nr.states, 1)
            mgr.probe()
            rep = analyze(list(tracer.events()))
            assert rep["fault"] is not None
            assert rep["fault"]["quarantines"] == 1
            assert rep["fault"]["repairs"] == 1
        finally:
            if not was:
                tracer.disable()
