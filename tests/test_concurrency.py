"""nrcheck: whole-program lock-discipline analysis + runtime checker
(ISSUE 17).

Static half (`analysis/concurrency.py`): fixture modules exercise the
guarded-by inference (true positive, true negative, both annotation
escape hatches), the global lock-order graph (direct nesting,
interprocedural nesting, declared edges, cycle reporting), and the two
satellite rules. Fixtures follow `test_analysis.py`'s convention:
self-contained snippets written to tmp_path, analyzed purely
syntactically.

Runtime half (`analysis/locks.py`): the instrumented factory under a
private `fresh_state()` — single-thread order inversion, a LIVE
two-thread deadlock interleaving that `LockOrderError` catches before
either thread hangs, reentrancy, trylock probes, Condition
integration, the passthrough contract, and the lockgraph dump that
`--check-dynamic` gates against the static graph.
"""

import json
import textwrap
import threading
import time

import pytest

from node_replication_tpu.analysis import concurrency
from node_replication_tpu.analysis import locks as locks_mod
from node_replication_tpu.analysis.lint import (
    audit_suppressions,
    build_project,
    main,
    run_lint,
)
from node_replication_tpu.analysis.locks import (
    LockOrderError,
    _CheckedLock,
    _CheckedRLock,
    dump_lockgraph,
    fresh_state,
    make_condition,
    make_lock,
    make_rlock,
)


def lint_src(tmp_path, source, name="snippet.py", select=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    diags, errors = run_lint([str(p)], select=select)
    assert not errors, errors
    return diags


def firing(diags, rule_id):
    return [d for d in diags if d.rule_id == rule_id and not d.suppressed]


def analyze_src(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    modules, project, errors = build_project([str(p)])
    assert not errors, errors
    return concurrency.analyze(project)


# a thread-shared fixture class: spawns a role-named worker that
# stores `_v` under `_lock`, so `_v` is inferred guarded-by `_lock`
SHARED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._v = 0
            self._t = threading.Thread(
                target=self._run, name="serve-worker-0"
            )
            self._t.start()

        def _run(self):
            with self._lock:
                self._v += 1
"""


class TestRoleOracle:
    def test_prefixes_mirror_obs_profile(self):
        # the analysis ships its own copy (analysis must not import
        # runtime modules); this pin keeps the two tables in lockstep
        from node_replication_tpu.obs import profile

        assert set(concurrency.ROLE_PREFIXES) == set(
            profile._ROLE_PREFIXES
        )


class TestGuardedByInference:
    def test_unlocked_read_in_shared_class_fires(self, tmp_path):
        diags = lint_src(tmp_path, SHARED_CLASS + """
            def peek(self):
                return self._v
        """ .replace("\n    ", "\n"))
        hits = firing(diags, "nrcheck-guarded-by")
        assert len(hits) == 1
        assert "Box._v" in hits[0].message
        assert "Box._lock" in hits[0].message

    def test_locked_read_clean(self, tmp_path):
        diags = lint_src(tmp_path, SHARED_CLASS + """
            def peek(self):
                with self._lock:
                    return self._v
        """ .replace("\n    ", "\n"))
        assert not firing(diags, "nrcheck-guarded-by")

    def test_unshared_annotation_silences(self, tmp_path):
        diags = lint_src(tmp_path, SHARED_CLASS + """
            def peek(self):
                # nrcheck: unshared — lock-free poll, fixture
                return self._v
        """ .replace("\n    ", "\n"))
        assert not firing(diags, "nrcheck-guarded-by")

    def test_guarded_by_method_annotation_silences(self, tmp_path):
        # caller-holds-the-lock contract: the whole method is a region
        diags = lint_src(tmp_path, SHARED_CLASS + """
            # guarded-by: _lock
            def peek(self):
                return self._v
        """ .replace("\n    ", "\n"))
        assert not firing(diags, "nrcheck-guarded-by")

    def test_unshared_class_not_flagged(self, tmp_path):
        # same shape, but nothing spawns a thread: single-threaded
        # callers may read lock-free without a diagnostic
        diags = lint_src(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._v = 0

                def bump(self):
                    with self._lock:
                        self._v += 1

                def peek(self):
                    return self._v
        """)
        assert not firing(diags, "nrcheck-guarded-by")


class TestLockOrder:
    def test_direct_inversion_cycle_fires(self, tmp_path):
        diags = lint_src(tmp_path, """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass

            def ba():
                with lock_b:
                    with lock_a:
                        pass
        """)
        assert firing(diags, "nrcheck-lock-order")

    def test_consistent_order_clean(self, tmp_path):
        diags = lint_src(tmp_path, """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass

            def ab_again():
                with lock_a:
                    with lock_b:
                        pass
        """)
        assert not firing(diags, "nrcheck-lock-order")

    def test_interprocedural_cycle_fires(self, tmp_path):
        # outer holds A and reaches B only through a call: the edge
        # comes from the callee's transitive acquire summary
        diags = lint_src(tmp_path, """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def outer():
                with lock_a:
                    inner()

            def inner():
                with lock_b:
                    pass

            def rev():
                with lock_b:
                    with lock_a:
                        pass
        """)
        assert firing(diags, "nrcheck-lock-order")

    def test_declared_edge_enters_graph(self, tmp_path):
        # a `# nrcheck: lock-order` declaration is a real edge: with
        # the reverse nesting in code, the cycle is reported
        diags = lint_src(tmp_path, """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            # nrcheck: lock-order snippet.lock_a -> snippet.lock_b — fixture
            def rev():
                with lock_b:
                    with lock_a:
                        pass
        """)
        assert firing(diags, "nrcheck-lock-order")

    def test_static_edge_list(self, tmp_path):
        analysis = analyze_src(tmp_path, """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass
        """)
        assert ["snippet.lock_a", "snippet.lock_b"] in analysis.edge_list()
        assert not analysis.cycles

    def test_check_dynamic_subgraph(self, tmp_path):
        analysis = analyze_src(tmp_path, """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass
        """)
        assert analysis.check_dynamic(
            [["snippet.lock_a", "snippet.lock_b"]]
        ) == []
        rogue = analysis.check_dynamic(
            [["snippet.lock_b", "snippet.lock_a"]]
        )
        assert len(rogue) == 1


class TestAnnotationDiags:
    def test_malformed_nrcheck_comment_warns(self, tmp_path):
        diags = lint_src(tmp_path, """
            import threading

            # nrcheck: unshareable
            x = 1
        """)
        assert firing(diags, "nrcheck-annotation")

    def test_factory_name_drift_warns(self, tmp_path):
        diags = lint_src(tmp_path, """
            from node_replication_tpu.analysis.locks import make_lock

            class Box:
                def __init__(self):
                    self._lock = make_lock("Wrong._lock")
        """)
        hits = firing(diags, "nrcheck-annotation")
        assert len(hits) == 1
        assert "Box._lock" in hits[0].message

    def test_factory_name_match_clean(self, tmp_path):
        diags = lint_src(tmp_path, """
            from node_replication_tpu.analysis.locks import make_lock

            class Box:
                def __init__(self):
                    self._lock = make_lock("Box._lock")
        """)
        assert not firing(diags, "nrcheck-annotation")


class TestConditionWaitRule:
    RULE = "condition-wait-without-predicate-loop"

    def test_bare_wait_fires(self, tmp_path):
        diags = lint_src(tmp_path, """
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False

                def bad(self):
                    with self._cond:
                        self._cond.wait()
        """)
        assert len(firing(diags, self.RULE)) == 1

    def test_wait_in_predicate_loop_clean(self, tmp_path):
        diags = lint_src(tmp_path, """
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False

                def good(self):
                    with self._cond:
                        while not self.ready:
                            self._cond.wait()
        """)
        assert not firing(diags, self.RULE)

    def test_timed_wait_clean(self, tmp_path):
        # a timed wait is a poll: the caller re-checks by construction
        diags = lint_src(tmp_path, """
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()

                def poll(self):
                    with self._cond:
                        self._cond.wait(0.05)
        """)
        assert not firing(diags, self.RULE)


class TestLockHeldAcrossBlockingCall:
    RULE = "lock-held-across-blocking-call"

    def test_sendall_under_lock_fires(self, tmp_path):
        diags = lint_src(tmp_path, """
            import threading

            class S:
                def __init__(self, sock):
                    self._lock = threading.Lock()
                    self.sock = sock

                def bad(self, data):
                    with self._lock:
                        self.sock.sendall(data)
        """)
        assert len(firing(diags, self.RULE)) == 1

    def test_sendall_outside_lock_clean(self, tmp_path):
        diags = lint_src(tmp_path, """
            import threading

            class S:
                def __init__(self, sock):
                    self._lock = threading.Lock()
                    self.sock = sock
                    self.buf = b""

                def good(self, data):
                    with self._lock:
                        self.buf = bytes(data)
                    self.sock.sendall(self.buf)
        """)
        assert not firing(diags, self.RULE)


# ---------------------------------------------------------------- runtime


class TestCheckedLocks:
    def test_single_thread_inversion_raises(self):
        with fresh_state():
            a = _CheckedLock("A")
            b = _CheckedLock("B")
            with a:
                with b:
                    pass
            with b:
                with pytest.raises(LockOrderError):
                    a.acquire()

    def test_two_thread_deadlock_caught_before_hang(self):
        # the LIVE interleaving: T1 takes A then B, T2 takes B then A,
        # a barrier forcing both outer locks held. Unchecked this
        # deadlocks; the checker fails exactly one thread fast and
        # BOTH threads finish.
        with fresh_state():
            a = _CheckedLock("A")
            b = _CheckedLock("B")
            barrier = threading.Barrier(2, timeout=10)
            errs = []

            def run(first, second):
                with first:
                    barrier.wait()
                    try:
                        with second:
                            pass
                    except LockOrderError as e:
                        errs.append(e)

            t1 = threading.Thread(target=run, args=(a, b))
            t2 = threading.Thread(target=run, args=(b, a))
            t1.start()
            t2.start()
            t1.join(10)
            t2.join(10)
            assert not t1.is_alive() and not t2.is_alive()
            assert len(errs) == 1
            assert "cycle" in str(errs[0])

    def test_self_deadlock_raises(self):
        with fresh_state():
            a = _CheckedLock("A")
            with a:
                with pytest.raises(LockOrderError):
                    a.acquire()

    def test_rlock_reentry_no_edges(self):
        with fresh_state() as st:
            r = _CheckedRLock("R")
            with r:
                with r:
                    pass
            assert st.edge_list() == []

    def test_trylock_probe_records_but_never_raises(self):
        # `_locked`'s contention fast path: a non-blocking probe in
        # cycle-closing order records the edge (for the dump) but
        # cannot deadlock, so it must not raise
        with fresh_state() as st:
            a = _CheckedLock("A")
            b = _CheckedLock("B")
            with a:
                with b:
                    pass
            with b:
                assert a.acquire(blocking=False)
                a.release()
            assert ["B", "A"] in st.edge_list()

    def test_nesting_records_all_pairs(self):
        with fresh_state() as st:
            a = _CheckedLock("A")
            b = _CheckedLock("B")
            c = _CheckedLock("C")
            with a:
                with b:
                    with c:
                        pass
            assert st.edge_list() == [
                ["A", "B"], ["A", "C"], ["B", "C"],
            ]

    def test_condition_wait_notify_roundtrip(self, monkeypatch):
        # Condition built on a checked lock: wait() releases and
        # re-acquires through the held-stack bookkeeping
        monkeypatch.setenv("NR_TPU_LOCKCHECK", "1")
        with fresh_state():
            cond = make_condition("Fixture._cond")
            results = []

            def waiter():
                with cond:
                    results.append(cond.wait(timeout=10))

            t = threading.Thread(target=waiter)
            t.start()
            deadline = time.time() + 10
            while not results and time.time() < deadline:
                with cond:
                    cond.notify_all()
                time.sleep(0.005)
            t.join(10)
            assert results == [True]

    def test_checked_rlock_condition_roundtrip(self, monkeypatch):
        # the paired-lock idiom on a reentrant lock: Condition uses
        # _release_save/_acquire_restore, which must keep the
        # held-stack count balanced through the wait
        monkeypatch.setenv("NR_TPU_LOCKCHECK", "1")
        with fresh_state() as st:
            rlock = make_rlock("Fixture._lock")
            cond = make_condition("Fixture._lock", lock=rlock)
            with cond:
                assert not cond.wait(timeout=0.01)  # times out
            assert st.held() == []


class TestFactoryContract:
    def test_passthrough_when_disabled(self, monkeypatch):
        monkeypatch.delenv("NR_TPU_LOCKCHECK", raising=False)
        assert type(make_lock("X._lock")) is type(threading.Lock())
        assert type(make_rlock("X._rlock")) is type(threading.RLock())
        assert isinstance(make_condition("X._cond"), threading.Condition)

    def test_checked_when_enabled(self, monkeypatch):
        monkeypatch.setenv("NR_TPU_LOCKCHECK", "1")
        with fresh_state():
            assert isinstance(make_lock("X._lock"), _CheckedLock)
            assert isinstance(make_rlock("X._rlock"), _CheckedRLock)

    def test_dump_merges_existing(self, tmp_path):
        path = tmp_path / "lockgraph.json"
        path.write_text(json.dumps({"edges": [["P", "Q"]]}))
        with fresh_state():
            a = _CheckedLock("A")
            b = _CheckedLock("B")
            with a:
                with b:
                    pass
            dump_lockgraph(str(path))
        data = json.loads(path.read_text())
        assert ["A", "B"] in data["edges"]
        assert ["P", "Q"] in data["edges"]


# ------------------------------------------------------------- CLI gates


class TestCLI:
    AB = """
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def ab():
            with lock_a:
                with lock_b:
                    pass
    """

    def test_lockgraph_out_and_check_dynamic(self, tmp_path):
        src = tmp_path / "snippet.py"
        src.write_text(textwrap.dedent(self.AB))
        out = tmp_path / "static.json"
        assert main([str(src), "--lockgraph-out", str(out)]) == 0
        data = json.loads(out.read_text())
        assert ["snippet.lock_a", "snippet.lock_b"] in data["edges"]

        dyn = tmp_path / "dyn.json"
        dyn.write_text(json.dumps(
            {"edges": [["snippet.lock_a", "snippet.lock_b"]]}
        ))
        assert main([str(src), "--check-dynamic", str(dyn)]) == 0
        dyn.write_text(json.dumps({"edges": [["rogue.x", "rogue.y"]]}))
        assert main([str(src), "--check-dynamic", str(dyn)]) == 1

    def test_suppressions_audit_flags_stale_and_unjustified(
            self, tmp_path, capsys):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent("""
            import threading

            x = 1  # nrlint: disable=nrcheck-guarded-by
        """))
        assert audit_suppressions([str(p)]) == 1
        out = capsys.readouterr().out
        assert "STALE" in out
        assert "UNJUSTIFIED" in out

    def test_suppressions_audit_accepts_live_justified(
            self, tmp_path, capsys):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent("""
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()

                def bad(self):
                    with self._cond:
                        self._cond.wait()  # nrlint: disable=condition-wait-without-predicate-loop — fixture
        """))
        assert audit_suppressions([str(p)]) == 0
        out = capsys.readouterr().out
        assert "STALE" not in out and "UNJUSTIFIED" not in out

    @pytest.mark.slow
    def test_package_lint_is_clean(self):
        # the acceptance gate: the analysis over the repo's own
        # package must exit 0 (no unguarded shared-attribute access,
        # acyclic lock-order graph, every suppression justified).
        # slow-marked: two whole-package passes (~25s) — the tier-1
        # budgeted run already gates lint-cleanliness through
        # test_analysis.py::TestRepoIsClean (nrcheck rules included),
        # and CI's nrlint job runs both CLI gates directly
        assert main(["node_replication_tpu"]) == 0
        assert audit_suppressions(["node_replication_tpu"]) == 0
