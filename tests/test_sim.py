"""Deterministic chaos plane (ISSUE 8): injectable clock, seeded
cooperative scheduler, oracle-differential property harness, canary
catches, byte-identical replay, delta-debugging shrinker."""

import threading
import time

import pytest

from node_replication_tpu.sim import canary
from node_replication_tpu.sim.oracle import make_oracle
from node_replication_tpu.sim.properties import (
    FLAVORS,
    CaseSpec,
    generate_case,
    run_case,
)
from node_replication_tpu.sim.scheduler import SimScheduler
from node_replication_tpu.sim.shrink import shrink_case
from node_replication_tpu.utils.clock import (
    RealClock,
    SimClock,
    get_clock,
    installed,
    set_clock,
)


class TestClock:
    def test_default_is_real_clock(self):
        assert isinstance(get_clock(), RealClock)

    def test_real_clock_contract(self):
        c = RealClock()
        t0 = c.now()
        assert c.now() >= t0
        cond = threading.Condition()
        with cond:
            t1 = time.monotonic()
            assert c.wait(cond, 0.01) is False  # timeout, no notify
            assert time.monotonic() - t1 < 1.0

    def test_installed_restores(self):
        prev = get_clock()
        sim = SimClock()
        with installed(sim):
            assert get_clock() is sim
        assert get_clock() is prev

    def test_sim_sleep_auto_advances_instantly(self):
        sim = SimClock()
        t0 = time.monotonic()
        sim.sleep(3600.0)
        assert sim.now() == 3600.0
        assert time.monotonic() - t0 < 1.0

    def test_sim_sleep_blocks_until_advanced(self):
        sim = SimClock(auto_advance=False)
        woke = threading.Event()

        def sleeper():
            sim.sleep(5.0)
            woke.set()

        t = threading.Thread(target=sleeper, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not woke.is_set()
        sim.advance(5.0)
        assert woke.wait(5.0)
        t.join(5.0)

    def test_sim_timed_cond_wait_expires_on_advance(self):
        sim = SimClock(auto_advance=False)
        cond = threading.Condition()
        out = {}

        def waiter():
            with cond:
                out["r"] = sim.wait(cond, 5.0)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        for _ in range(200):
            if sim.waiters():
                break
            time.sleep(0.005)
        assert sim.waiters() == [5.0]
        sim.advance(10.0)
        t.join(5.0)
        assert not t.is_alive()
        assert out["r"] is False  # woke because virtual time expired

    def test_sim_timed_cond_wait_honors_real_notify(self):
        sim = SimClock(auto_advance=False)
        cond = threading.Condition()
        out = {}

        def waiter():
            with cond:
                out["r"] = sim.wait(cond, 5.0)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        for _ in range(200):
            if sim.waiters():
                break
            time.sleep(0.005)
        with cond:
            cond.notify_all()
        t.join(5.0)
        assert not t.is_alive()
        assert out["r"] is True  # not expired: a real notification

    def test_set_clock_returns_previous(self):
        sim = SimClock()
        prev = set_clock(sim)
        try:
            assert get_clock() is sim
        finally:
            assert set_clock(prev) is sim


class TestScheduler:
    def test_same_seed_same_schedule(self):
        def build(seed):
            s = SimScheduler(seed)
            log = []
            for name in ("a", "b", "c"):
                s.add(name, lambda n=name: log.append(n) or True,
                      weight={"a": 3.0, "b": 1.0, "c": 1.0}[name])
            s.run(50)
            return log

        assert build(7) == build(7)
        assert build(7) != build(8)

    def test_disable_removes_from_schedule(self):
        s = SimScheduler(1)
        s.add("a", lambda: True)
        s.add("b", lambda: True)
        s.disable("a")
        for _ in range(10):
            name, _ = s.step()
            assert name == "b"

    def test_idle_limit_stops(self):
        s = SimScheduler(1)
        s.add("idle", lambda: False)
        assert s.run(100, idle_limit=3) == 3


class TestOracle:
    def test_hashmap_semantics(self):
        o = make_oracle("hashmap", 8)
        assert o.apply((1, 3, 42)) == 0        # put
        assert o.read((1, 3)) == 42            # get
        assert o.apply((2, 3, 0)) == 1         # remove present
        assert o.apply((2, 3, 0)) == 0         # remove absent
        assert o.read((1, 3)) == -1
        assert o.apply((1, 11, 9)) == 0        # k % 8 == 3
        assert o.read((1, 3)) == 9

    def test_stack_overflow_and_pop_empty(self):
        o = make_oracle("stack", 2)
        assert o.apply((1, 10, 0)) == 1
        assert o.apply((1, 11, 0)) == 2
        assert o.apply((1, 12, 0)) == -1       # full
        assert o.apply((2, 0, 0)) == 11
        assert o.apply((2, 0, 0)) == 10
        assert o.apply((2, 0, 0)) == -1        # empty
        assert o.read((2, 0)) == 0             # len

    def test_queue_fifo_and_wrap(self):
        o = make_oracle("queue", 2)
        assert o.apply((1, 5, 0)) == 1
        assert o.apply((1, 6, 0)) == 2
        assert o.apply((1, 7, 0)) == -1        # full
        assert o.apply((2, 0, 0)) == 5
        assert o.apply((1, 7, 0)) == 2         # ring wraps
        assert o.read((1, 0)) == 6             # front
        assert o.read((2, 0)) == 2             # len

    def test_seqreg_fetch_and_set(self):
        o = make_oracle("seqreg", 4)
        assert o.apply((1, 2, 7)) == 0
        assert o.apply((1, 2, 9)) == 7
        assert o.read((1, 2)) == 9

    def test_copy_is_independent(self):
        o = make_oracle("hashmap", 4)
        o.apply((1, 1, 5))
        c = o.copy()
        c.apply((1, 1, 6))
        assert o.read((1, 1)) == 5 and c.read((1, 1)) == 6


def _find_spec(predicate, max_seed=80, **kw):
    for seed in range(max_seed):
        spec = generate_case(seed, **kw)
        if predicate(spec):
            return spec
    raise AssertionError("no matching spec in seed range")


class TestProperties:
    @pytest.mark.parametrize("flavor", FLAVORS)
    def test_every_flavor_holds_on_clean_code(self, flavor):
        for seed in range(2):
            spec = generate_case(seed, flavors=(flavor,))
            res = run_case(spec)
            assert res.ok, [v.as_dict() for v in res.violations]

    def test_cnr_multilog_runs_the_same_fault_plans(self):
        # the CNR/multilog path under chaos (ISSUE 8 satellite): a
        # MultiLogReplicated case whose schedule injects write faults
        # must hold every property, for both the wrapper and the
        # serve flavor
        for flavor in ("wrapper", "serve"):
            spec = _find_spec(
                lambda s: s.wrapper == "cnr"
                and any(st[0] == "wf" for st in s.steps),
                wrappers=("cnr",), flavors=(flavor,),
            )
            assert spec.wrapper == "cnr"
            res = run_case(spec)
            assert res.ok, [v.as_dict() for v in res.violations]

    def test_corruption_is_detected_and_repaired(self):
        spec = _find_spec(
            lambda s: any(st[0] == "corrupt" for st in s.steps),
            flavors=("wrapper",), wrappers=("nr",),
        )
        assert spec.n_replicas == 3  # quorum for the digest vote
        res = run_case(spec)
        # divergence-detect would fire had the vote missed it; every
        # other property would fire had the repair been wrong
        assert res.ok, [v.as_dict() for v in res.violations]

    def test_replay_is_byte_identical(self):
        spec1 = generate_case(0)
        spec2 = generate_case(0)
        assert spec1 == spec2
        r1, r2 = run_case(spec1), run_case(spec2)
        assert r1.digest == r2.digest
        assert r1.events == r2.events

    def test_spec_roundtrips_through_json(self):
        spec = generate_case(5)
        assert CaseSpec.from_dict(spec.as_dict()) == spec


class TestOverloadBursts:
    def test_generated_burst_case_clean_and_deterministic(self):
        # overload bursts (ISSUE 9): a generated serve/NR case with
        # burst steps holds shed-honesty / priority-inversion /
        # resp-diff, and replays byte-identically
        spec = _find_spec(
            lambda s: any(st[0] == "burst" for st in s.steps),
            flavors=("serve",), wrappers=("nr",),
        )
        r1 = run_case(spec)
        assert r1.ok, [v.as_dict() for v in r1.violations]
        r2 = run_case(spec)
        assert r1.digest == r2.digest
        evs = [e for e in r1.events if e[1] == "burst"]
        assert evs

    def test_crafted_burst_sheds_bulk_completes_critical(self):
        # 6 BULK fill the burst frontend's depth-6 queue, then 6
        # CRITICAL arrivals evict them one by one: every CRITICAL
        # completes, every BULK rejects, the log holds exactly the
        # completed set (shed-honesty), and no priority inversion
        burst = (
            [[2, [1, k, 100 + k]] for k in range(6)]
            + [[0, [1, k, 200 + k]] for k in range(6)]
        )
        spec = CaseSpec(
            seed=0, model="hashmap", wrapper="nr", flavor="serve",
            n_replicas=2, nlogs=1, steps=[["burst", burst], ["sync"]],
        )
        res = run_case(spec)
        assert res.ok, [v.as_dict() for v in res.violations]
        ev = [e for e in res.events if e[1] == "burst"][0]
        outcomes = [o[1] for o in ev[2]["outcomes"]]
        assert outcomes[:6] == ["evicted"] * 6
        assert outcomes[6:] == ["completed"] * 6
        assert ev[2]["applied"] == 6
        assert ev[2]["evicted"] == 6

    def test_non_serve_flavors_unchanged_by_burst_generation(self):
        # the fresh-rng guarantee: crash/repl schedules (and their
        # canary seeds) are byte-identical to the pre-overload
        # generator — no burst step ever appears there
        for flavor in ("wrapper", "crash", "repl"):
            for seed in range(4):
                spec = generate_case(seed, flavors=(flavor,))
                assert not any(st[0] == "burst" for st in spec.steps)


class TestCanaries:
    def test_reclaim_ignores_pins_is_caught(self):
        # the reclaim-vs-ship race PR 6 closed, re-opened: a repl
        # schedule with a lagging shipper across a snapshot+sync must
        # observe a feed gap, and the failing seed replays byte-
        # identically (the fast tier-1 half; the shrinker loop is the
        # slow-marked test below)
        with canary.armed("reclaim-ignores-pins"):
            spec = generate_case(1, flavors=("repl",))
            res = run_case(spec)
            assert any(v.prop == "replication-gap"
                       for v in res.violations), (
                "canary survived", [v.as_dict()
                                    for v in res.violations])
            replay = run_case(generate_case(1, flavors=("repl",)))
            assert replay.digest == res.digest

    @pytest.mark.slow
    def test_reclaim_ignores_pins_shrinks(self):
        # the shrinker reduces the canary's failing schedule while
        # preserving the violation — an 80-run loop (~1 min), so
        # slow-marked out of the tier-1 budget (ISSUE 18 satellite)
        with canary.armed("reclaim-ignores-pins"):
            spec = generate_case(1, flavors=("repl",))
            rep = shrink_case(spec, max_runs=80)
            assert rep.shrunk_steps < rep.original_steps
            assert any(v.prop == "replication-gap"
                       for v in rep.result.violations)

    def test_ack_before_fsync_is_caught(self):
        with canary.armed("ack-before-fsync"):
            spec = generate_case(3, flavors=("crash",))
            res = run_case(spec)
            assert any(v.prop == "durable-ack-survival"
                       for v in res.violations)

    def test_clean_run_after_canary_disarms(self):
        spec = generate_case(3, flavors=("crash",))
        assert run_case(spec).ok

    def test_unknown_canary_raises(self):
        with pytest.raises(ValueError):
            canary.armed("no-such-bug")

class TestShardedFlavor:
    def test_generated_sharded_case_clean_and_deterministic(self):
        # ISSUE 18: a generated 2-shard fleet case with the full
        # kill → promotion → re-home tail holds every property, and
        # replays byte-identically
        spec = _find_spec(
            lambda s: any(st[0] == "skill" for st in s.steps)
            and any(st[0] == "spromote" for st in s.steps),
            flavors=("sharded",),
        )
        assert spec.flavor == "sharded" and spec.n_shards == 2
        r1 = run_case(spec)
        assert r1.ok, [v.as_dict() for v in r1.violations]
        r2 = run_case(spec)
        assert r1.digest == r2.digest

    def test_crafted_failover_isolates_survivor(self):
        # shard 0 dies: its keys get typed `ShardUnavailable` while
        # shard 1 keeps acking (isolation); promotion re-homes shard
        # 0 onto its follower (bumped map), the zombie shipper is
        # fenced, and post-failover writes serve from the promoted
        # history with no lost/dup acks
        steps = [
            ["sw", [1, 0, 11]],                      # shard 0
            ["sw", [1, 1, 12]],                      # shard 1
            ["sbatch", [[1, 2, 13], [1, 3, 14], [1, 4, 15]]],
            ["swal", 0],                             # durable, UNshipped
            ["swal", 1], ["sship", 1],
            ["skill", 0],
            ["sw", [1, 2, 21]],                      # victim-keyed
            ["sw", [1, 3, 22]],                      # survivor-keyed
            ["sread", [1, 3, 0]],
            ["spromote", 0],
            ["szombie", 0],
            ["sw", [1, 2, 23]],
            ["sread", [1, 2, 0]],
        ]
        spec = CaseSpec(
            seed=0, model="hashmap", wrapper="nr", flavor="sharded",
            n_replicas=1, nlogs=1, steps=steps, n_shards=2,
        )
        res = run_case(spec)
        assert res.ok, [v.as_dict() for v in res.violations]
        by_step = {e[0]: e for e in res.events}
        assert by_step[2][1] == "sbatch"
        assert [r[:2] for r in by_step[2][2]["results"]] == [
            [0, "ok"], [1, "ok"], [0, "ok"]]
        # shard 0 dies with 3 durable-but-unshipped records: the
        # shipped-acked survival floor is 0, so promotion legally
        # drops them (no violation) and serves from an empty slice
        assert by_step[6][1] == "skill"
        assert by_step[6][2]["durable"] == 3
        assert by_step[6][2]["acked"] == 0
        # outage window: victim write typed-unavailable, survivor acks
        assert by_step[7][1] == "sw-err"
        assert by_step[7][2] == {"shard": 0,
                                 "err": "ShardUnavailable"}
        assert by_step[8][1] == "sw" and by_step[8][2]["shard"] == 1
        assert by_step[9][1] == "sread"
        # promotion bumps + re-publishes the map; the superseded
        # shipper's publish of the unshipped backlog hits the epoch
        # fence (zombie-unfenced would fire had it landed)
        assert by_step[10][1] == "spromote"
        assert by_step[10][2]["shard"] == 0
        assert by_step[10][2]["applied"] == 0
        assert by_step[10][2]["map_version"] == 2
        assert by_step[11][1] == "sship-fenced"
        # post-failover: the re-homed shard serves its slice again
        assert by_step[12][1] == "sw" and by_step[12][2]["shard"] == 0
        assert by_step[13][1] == "sread"
        assert by_step[13][2] == {"shard": 0, "val": 23}

    def test_non_sharded_flavors_unchanged_by_sharded_generation(self):
        # the fresh-rng guarantee: with "sharded" filtered out the
        # generator is byte-identical to the pre-sharding one, and
        # under the new default only serve/nr seeds ever convert
        legacy = tuple(f for f in FLAVORS if f != "sharded")
        for seed in range(40):
            new = generate_case(seed)
            old = generate_case(seed, flavors=legacy)
            if new.flavor == "sharded":
                assert old.flavor == "serve" and old.wrapper == "nr"
            else:
                assert new == old
        sharded_kinds = {"sw", "sbatch", "sread", "swal", "sship",
                         "sapply", "skill", "spromote", "szombie"}
        for flavor in legacy:
            for seed in range(4):
                spec = generate_case(seed, flavors=(flavor,))
                assert not any(st[0] in sharded_kinds
                               for st in spec.steps)

    def test_n_shards_field_optional_in_artifacts(self):
        # pre-sharding failing-seed artifacts (no "n_shards" key)
        # must keep loading and replaying
        spec = generate_case(0)
        d = spec.as_dict()
        d.pop("n_shards")
        loaded = CaseSpec.from_dict(d)
        assert loaded.n_shards == 0
        sharded = generate_case(0, flavors=("sharded",))
        assert CaseSpec.from_dict(sharded.as_dict()) == sharded


class TestPipelineOverlapKnob:
    def test_overlap_drawn_for_serve_flavor_only(self):
        # ISSUE 14: the serve flavor's overlap knob covers depth-1
        # pipelining in the sweep; every other flavor stays serial,
        # and the FRESH rng stream keeps base schedules byte-identical
        seen = {0: 0, 1: 0}
        for seed in range(120):
            spec = generate_case(seed)
            if spec.flavor == "serve":
                seen[spec.overlap] += 1
            else:
                assert spec.overlap == 0
        assert seen[0] > 0 and seen[1] > 0

    def test_pipelined_serve_case_clean_and_deterministic(self):
        spec = _find_spec(
            lambda s: s.overlap == 1, flavors=("serve",),
        )
        assert spec.overlap == 1
        r1 = run_case(spec)
        assert r1.ok, [v.as_dict() for v in r1.violations]
        r2 = run_case(spec)
        assert r1.digest == r2.digest

    def test_overlap_field_optional_in_artifacts(self):
        # pre-overlap failing-seed artifacts (no "overlap" key) must
        # keep loading and replaying
        spec = generate_case(3)
        d = spec.as_dict()
        d.pop("overlap")
        loaded = CaseSpec.from_dict(d)
        assert loaded.overlap == 0


class TestTxnAndReshardSteps:
    def test_generated_txn_case_clean_and_deterministic(self):
        # ISSUE 20: a generated sharded case driving the real 2PC
        # coordinator (including a crash-variant stxn: coordinator
        # dies right after its decision publish, restart recovery
        # re-drives the commit) holds every property and replays
        # byte-identically
        spec = _find_spec(
            lambda s: any(st[0] == "stxn" and st[2]
                          for st in s.steps),
            flavors=("sharded",),
        )
        r1 = run_case(spec)
        assert r1.ok, [v.as_dict() for v in r1.violations]
        kinds = {e[1] for e in r1.events}
        assert kinds & {"stxn", "stxn-recovered", "stxn-abort"}
        r2 = run_case(spec)
        assert r1.digest == r2.digest

    def test_generated_reshard_case_clean_and_deterministic(self):
        spec = _find_spec(
            lambda s: any(st[0] == "sreshard" for st in s.steps),
            flavors=("sharded",),
        )
        r1 = run_case(spec)
        assert r1.ok, [v.as_dict() for v in r1.violations]
        assert any(e[1] == "sreshard" for e in r1.events)
        r2 = run_case(spec)
        assert r1.digest == r2.digest

    def test_crafted_txn_across_split_topology(self):
        # seed both classes, split shard 0 live, then run a txn whose
        # keys span the REFINED topology (classes 1 and 2 of 4) and
        # read everything back — the global-exactness finalize
        steps = [
            ["sw", [1, 0, 11]],
            ["sw", [1, 2, 12]],                       # moved class
            ["sw", [1, 1, 13]],
            ["stxn", [[1, 4, 21], [1, 5, 22]], 0],    # cross-shard
            ["sreshard", 0],
            ["sw", [1, 2, 31]],                       # lands on recipient
            ["stxn", [[1, 1, 41], [1, 2, 42]], 0],    # classes 1 + 2
            ["sread", [1, 2, 0]],
        ]
        spec = CaseSpec(
            seed=0, model="hashmap", wrapper="nr", flavor="sharded",
            n_replicas=1, nlogs=1, steps=steps, n_shards=2,
        )
        res = run_case(spec)
        assert res.ok, [v.as_dict() for v in res.violations]
        by_step = {e[0]: e for e in res.events}
        assert by_step[3][1] == "stxn"
        assert by_step[3][2]["shards"] == [0, 1]
        assert by_step[4][1] == "sreshard"
        assert by_step[4][2]["moved"] == 2
        assert by_step[4][2]["map_version"] == 2
        assert by_step[6][1] == "stxn"
        assert by_step[6][2]["shards"] == [1, 2]
        assert by_step[7][2] == {"shard": 2, "val": 42}

    def test_crafted_txn_abort_in_kill_window_is_atomic(self):
        # a txn spanning a dead shard aborts whole: the survivor's
        # key must show ZERO effect (the read-back the txn-atomicity
        # property runs at the abort site)
        steps = [
            ["sw", [1, 1, 11]],
            ["skill", 0],
            ["stxn", [[1, 1, 21], [1, 2, 22]], 0],
            ["sread", [1, 1, 0]],
        ]
        spec = CaseSpec(
            seed=0, model="hashmap", wrapper="nr", flavor="sharded",
            n_replicas=1, nlogs=1, steps=steps, n_shards=2,
        )
        res = run_case(spec)
        assert res.ok, [v.as_dict() for v in res.violations]
        by_step = {e[0]: e for e in res.events}
        assert by_step[2][1] == "stxn-abort"
        assert by_step[3][2] == {"shard": 1, "val": 11}

    def test_ack_before_decision_canary_is_caught(self):
        # the re-injectable ISSUE 20 bug: DecisionLog.publish drops
        # the document, so a decided txn presumed-aborts on restart
        with canary.armed("ack-before-decision"):
            spec = _find_spec(
                lambda s: any(st[0] == "stxn" and st[2]
                              for st in s.steps),
                flavors=("sharded",),
            )
            res = run_case(spec)
            assert any(v.prop == "txn-atomicity"
                       for v in res.violations), (
                "canary survived",
                [v.as_dict() for v in res.violations])
            replay = run_case(spec)
            assert replay.digest == res.digest
        # disarmed: the same spec runs clean
        assert run_case(spec).ok
