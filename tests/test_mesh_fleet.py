"""Mesh-sharded fleet differential suite (ISSUE 10 + the ISSUE 15
mesh-fused tier).

The acceptance contract of the mesh work: placement changes SPEED,
never results. Every test drives the same op sequence through an
un-meshed wrapper and a mesh-sharded twin (replica axis under
`NamedSharding(mesh, P('replica'))`, 8 forced host devices — see
conftest.py) and requires bit-identical responses, states, and cursor
lattices — scan AND union engines, both collective tiers (shmap /
gspmd), hashmap AND seqreg models, with a fenced-replica case pinning
the cross-device GC-head mask and a ring-tier case pinning the
collective catch-up path. `TestMeshFused` extends the contract to the
MESH-FUSED exec tier (`parallel/collectives.py:MeshFusedEngine`): one
shard_map-wrapped Pallas launch per combiner round, pinned
bit-identical to the un-meshed scan wrapper across ring wraps, a
fence/repair cycle with the corpse on a non-zero shard, mesh-aware
calibration resets, and depth-1 pipelined serve. This file is the CI
`mesh-smoke` job (the mesh-fused half also rides `kernel-smoke`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from node_replication_tpu import NodeReplicated
from node_replication_tpu.core.cnr import MultiLogReplicated
from node_replication_tpu.core.log import log_append
from node_replication_tpu.models import (
    HM_GET,
    HM_PUT,
    SR_GET,
    SR_SET,
    make_hashmap,
    make_seqreg,
)
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.parallel import make_mesh, replica_mesh


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return replica_mesh(8)


def _assert_fleets_equal(ref, got):
    for a, b in zip(jax.tree.leaves(ref.states),
                    jax.tree.leaves(got.states)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(ref.log.ltails), np.asarray(got.log.ltails)
    )
    for cursor in ("tail", "ctail", "head"):
        assert int(getattr(ref.log, cursor)) == int(
            getattr(got.log, cursor)
        ), cursor


def _seqreg_pair(mesh, **kw):
    mk = lambda **extra: NodeReplicated(
        make_seqreg(8), n_replicas=8, log_entries=1 << 12,
        gc_slack=64, exec_window=32, **extra,
    )
    return mk(**kw), mk(mesh=mesh, **kw)


def _hashmap_pair(mesh, **kw):
    mk = lambda **extra: NodeReplicated(
        make_hashmap(64), n_replicas=8, log_entries=1 << 12,
        gc_slack=64, exec_window=32, **extra,
    )
    return mk(**kw), mk(mesh=mesh, **kw)


class TestNodeReplicatedMesh:
    def test_scan_engine_shmap_tier_bit_identical(self, mesh):
        # seqreg has no combined form on purpose: the scan engine →
        # the explicit-collective shard_map tier
        ref, got = _seqreg_pair(mesh)
        assert got.engine == "scan" and got._mesh_tier == "shmap"
        t_ref, t_got = ref.register(2), got.register(2)
        for i in range(60):
            op = (SR_SET, i % 8, i)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        assert ref.execute((SR_GET, 5), t_ref) == got.execute(
            (SR_GET, 5), t_got
        )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    def test_union_engine_gspmd_tier_bit_identical(self, mesh):
        # hashmap is window_canonical → combined engine → GSPMD tier
        # (the union-plan economics survive sharding by annotation)
        ref, got = _hashmap_pair(mesh)
        assert got.engine == "combined" and got._mesh_tier == "gspmd"
        t_ref, t_got = ref.register(0), got.register(0)
        rng = np.random.default_rng(3)
        for i in range(60):
            op = (HM_PUT, int(rng.integers(64)),
                  int(rng.integers(1000)), 0)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        for k in (0, 7, 31):
            assert ref.execute((HM_GET, k), t_ref) == got.execute(
                (HM_GET, k), t_got
            )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    def test_shmap_forced_on_combined_model(self, mesh):
        # collectives='shmap' on a combined-engine model: the scan
        # collective replaces the union plan — still bit-identical
        # (the engines are pinned equal), placement-only difference
        ref = NodeReplicated(make_hashmap(64), n_replicas=8,
                             log_entries=1 << 12, gc_slack=64,
                             exec_window=32)
        got = NodeReplicated(make_hashmap(64), n_replicas=8,
                             log_entries=1 << 12, gc_slack=64,
                             exec_window=32, mesh=mesh,
                             collectives="shmap")
        assert got._mesh_tier == "shmap"
        t_ref, t_got = ref.register(0), got.register(0)
        for i in range(40):
            op = (HM_PUT, i % 64, i, 0)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    def test_batch_path_bit_identical(self, mesh):
        # the serve entry point (execute_mut_batch) over the mesh
        ref, got = _seqreg_pair(mesh)
        ops = [(SR_SET, i % 8, i) for i in range(96)]
        assert ref.execute_mut_batch(ops, rid=1) == \
            got.execute_mut_batch(ops, rid=1)
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    @pytest.mark.parametrize("pair", ["seqreg", "hashmap"])
    def test_fenced_gc_mask_across_devices(self, mesh, pair):
        # the fenced-head GC mask must stay correct when the corpse
        # lives on a different device than the combiner: fence a
        # replica mid-run on BOTH engines' tiers, require identical
        # heads/ltails/states, then repair and require convergence
        mk = _seqreg_pair if pair == "seqreg" else _hashmap_pair
        ref, got = mk(mesh)
        mkop = (
            (lambda i: (SR_SET, i % 8, i)) if pair == "seqreg"
            else (lambda i: (HM_PUT, i % 64, i, 0))
        )
        for nr in (ref, got):
            t = nr.register(0)
            for i in range(24):
                nr.execute_mut(mkop(i), t)
            nr.fence_replica(5)
            for i in range(24, 48):
                nr.execute_mut(mkop(i), t)
        # the fenced cursor is frozen; head advanced past it
        assert int(np.asarray(got.log.ltails)[5]) < int(got.log.head)
        _assert_fleets_equal(ref, got)
        for nr in (ref, got):
            nr.clone_replica_from(5)
            nr.unfence_replica(5)
            nr.sync()
            assert nr.replicas_equal()
        _assert_fleets_equal(ref, got)

    def test_ring_catchup_tier_bit_identical(self, mesh):
        # a large uniform backlog takes the ring tier on the mesh
        # (make_ring_exec promoted into sync()) — and must land on the
        # same states/cursors as the un-meshed scan rounds
        ref, got = _seqreg_pair(mesh)
        rng = np.random.default_rng(0)
        N = 400
        opc = np.full(N, SR_SET, np.int32)
        args = np.zeros((N, 3), np.int32)
        args[:, 0] = rng.integers(0, 8, N)
        args[:, 1] = rng.integers(0, 1000, N)
        for nr in (ref, got):
            nr.log = log_append(nr.spec, nr.log, jnp.asarray(opc),
                                jnp.asarray(args), N)
            nr.sync()
        assert got._ring_rounds > 0, "ring tier never fired"
        assert ref._ring_rounds == 0
        _assert_fleets_equal(ref, got)

    def test_ring_tier_counter(self, mesh):
        reg = get_registry()
        reg.enable()
        try:
            _, got = _seqreg_pair(mesh)
            before = reg.counter("nr.exec.engine.ring").value
            N = 200
            opc = np.full(N, SR_SET, np.int32)
            args = np.zeros((N, 3), np.int32)
            got.log = log_append(got.spec, got.log, jnp.asarray(opc),
                                 jnp.asarray(args), N)
            got.sync()
            assert reg.counter("nr.exec.engine.ring").value > before
            assert reg.counter("nr.exec.mesh.shmap").value > 0
            assert reg.counter("mesh.sync_bytes").value > 0
            assert reg.gauge("mesh.replicas_per_device").value == 1
        finally:
            reg.disable()

    def test_grow_fleet_keeps_placement(self, mesh):
        ref, got = _seqreg_pair(mesh)
        t_ref, t_got = ref.register(0), got.register(0)
        for i in range(16):
            op = (SR_SET, i % 8, i)
            ref.execute_mut(op, t_ref)
            got.execute_mut(op, t_got)
        # growing by a non-multiple of the shard count is rejected
        # BEFORE any state mutates
        with pytest.raises(ValueError):
            got.grow_fleet(3)
        assert got.n_replicas == 8
        ref.grow_fleet(8)
        new = got.grow_fleet(8)
        assert new == list(range(8, 16))
        for i in range(16, 32):
            op = (SR_SET, i % 8, i)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)
        assert got.replicas_equal()

    def test_checkpoint_restore_replaces(self, mesh, tmp_path):
        _, got = _seqreg_pair(mesh)
        t = got.register(0)
        for i in range(20):
            got.execute_mut((SR_SET, i % 8, i), t)
        path = str(tmp_path / "snap.npz")
        got.checkpoint(path)
        back = NodeReplicated.restore(path, make_seqreg(8), mesh=mesh)
        _assert_fleets_equal(got, back)
        # the restored fleet still runs mesh rounds
        t2 = back.register(0)
        assert back.execute_mut((SR_SET, 0, 999), t2) is not None
        assert back._mesh_tier is not None

    def test_validation(self, mesh):
        with pytest.raises(ValueError):  # 8 shards can't take R=6
            NodeReplicated(make_seqreg(4), n_replicas=6, mesh=mesh)
        with pytest.raises(ValueError):  # unknown tier
            NodeReplicated(make_seqreg(4), n_replicas=8, mesh=mesh,
                           collectives="nope")
        with pytest.raises(ValueError):  # shmap has no checkify twin
            NodeReplicated(make_seqreg(4), n_replicas=8, mesh=mesh,
                           collectives="shmap", debug=True)

    def test_replica_device_map(self, mesh):
        _, got = _seqreg_pair(mesh)
        devs = [str(got.replica_device(r)) for r in range(8)]
        assert len(set(devs)) == 8  # 8 replicas over 8 devices
        snap = got.snapshot()
        assert snap["mesh"]["devices"] == 8
        assert snap["mesh"]["replicas_per_device"] == 1
        un = NodeReplicated(make_seqreg(4), n_replicas=2)
        assert un.replica_device(0) is None
        assert un.snapshot()["mesh"] is None

    def test_serve_frontend_maps_workers_to_devices(self, mesh):
        from node_replication_tpu.serve import ServeConfig, ServeFrontend

        _, got = _seqreg_pair(mesh)
        with ServeFrontend(got, ServeConfig(batch_max_ops=8,
                                            batch_linger_s=0.0)) as fe:
            for i in range(1, 9):
                assert fe.call((SR_SET, 2, i),
                               rid=i % got.n_replicas) == i - 1
            st = fe.stats()
        assert st["mesh"]["devices"] == 8
        assert sum(st["mesh"]["replicas_per_device"].values()) == 8
        assert len(st["mesh"]["device_of_rid"]) == 8


def _mixed_ops(rng, n, n_keys):
    ops = []
    for _ in range(n):
        if rng.rand() < 0.7:
            ops.append((HM_PUT, int(rng.randint(n_keys)),
                        int(rng.randint(1000))))
        else:
            ops.append((2, int(rng.randint(n_keys))))
    return ops


class TestMeshFused:
    """The mesh-fused exec tier differential contract (interpret mode
    on forced host devices; the shard_map program runs eagerly — same
    convention as every other interpret pallas test)."""

    def test_forced_30_rounds_two_wraps_fence_repair(self):
        # 30 mesh-fused combiner rounds vs the un-meshed scan chain:
        # ~18-op batches against a 256-slot ring wrap it twice, a
        # replica is fenced mid-run with the corpse on a NON-ZERO
        # shard (rid 3 = shard 1 of the 2-wide mesh), repaired, and
        # every round's responses + the final states/cursor lattice
        # must be bit-identical — the tier changes launch count, never
        # results
        mesh = replica_mesh(2)
        K, R = 31, 4
        nr_m = NodeReplicated(make_hashmap(K), n_replicas=R,
                              log_entries=256, gc_slack=32,
                              exec_window=32, engine="pallas",
                              mesh=mesh)
        nr_s = NodeReplicated(make_hashmap(K), n_replicas=R,
                              log_entries=256, gc_slack=32,
                              exec_window=32, engine="scan")
        reg = get_registry()
        reg.enable()
        before = reg.counter("log.engine.mesh_fused").value
        mesh_before = reg.counter("nr.exec.mesh.mesh_fused").value
        rng = np.random.RandomState(7)
        for rnd in range(30):
            if rnd == 12:
                for nr in (nr_m, nr_s):
                    nr.fence_replica(3)
                assert 3 in nr_m.fenced_rids
            if rnd == 16:
                for nr in (nr_m, nr_s):
                    nr.clone_replica_from(3, donor=0)
                    nr.unfence_replica(3)
            ops = _mixed_ops(rng, int(rng.randint(18, 26)), K)
            assert nr_m.execute_mut_batch(ops, rid=0) == \
                nr_s.execute_mut_batch(ops, rid=0), rnd
        assert int(nr_m.log.tail) > 2 * 256  # two genuine ring wraps
        nr_m.sync(); nr_s.sync()
        _assert_fleets_equal(nr_s, nr_m)
        assert nr_m.replicas_equal()
        st = nr_m.stats()
        assert st["fused_tier"] == "forced"
        assert st["fused_rounds"] == 30  # every round one meshed launch
        assert st["exec_rounds"] == 0
        assert nr_m.last_round_tier == "mesh_fused"
        assert nr_m.round_tier(0) == "mesh_fused"
        assert reg.counter("log.engine.mesh_fused").value \
            - before == 30
        assert reg.counter("nr.exec.mesh.mesh_fused").value \
            - mesh_before == 30

    def test_shmap_program_matches_sliced_composition(self):
        # the compilation-policy pin: interpret rounds run the
        # shard-sliced composition, TPU jits the shard_map program —
        # the two must be bit-identical, unfenced AND fenced (the
        # _FAR-composed GC join), so the program the TPU compiles is
        # covered by this CPU suite. One eager shard_map call per
        # variant (seconds each on this jax — why the bulk suite uses
        # the sliced path).
        from node_replication_tpu.core.log import LogSpec, log_init
        from node_replication_tpu.core.replica import replicate_state
        from node_replication_tpu.ops.encoding import encode_ops
        from node_replication_tpu.parallel import MeshFusedEngine

        K, R = 13, 4
        spec = LogSpec(capacity=256, n_replicas=R, arg_width=3,
                       gc_slack=32)
        d = make_hashmap(K)
        eng = MeshFusedEngine(d, spec, replica_mesh(2))
        rng = np.random.RandomState(3)
        ops = [(HM_PUT, int(rng.randint(K)), int(rng.randint(100)))
               for _ in range(7)]
        opc, args, n = encode_ops(ops, 3, pad_to=8)
        for fenced_vec in (None, np.array([False, False, True,
                                           False])):
            is_f = fenced_vec is not None
            log = log_init(spec)
            states = replicate_state(d.init_state(), R)
            sliced = eng._sliced_round(8, is_f)
            shmap = eng._shmap_round(8, is_f)
            extra = (
                (jnp.asarray(fenced_vec, bool),) if is_f else ()
            )
            a = sliced(log, states, opc, args, n, *extra)
            b = shmap(log, states, opc, args, n, *extra)
            for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                np.testing.assert_array_equal(
                    np.asarray(la), np.asarray(lb),
                    err_msg=f"fenced={is_f}",
                )

    def test_fenced_head_gc_corpse_on_other_shard(self):
        # the composed _FAR mask: with the corpse fenced on shard 1,
        # mesh-fused rounds must keep advancing head past its frozen
        # cursor (the pmin lattice join excludes it), exactly like the
        # un-meshed fleet
        mesh = replica_mesh(2)
        nr = NodeReplicated(make_hashmap(16), n_replicas=4,
                            log_entries=256, gc_slack=32,
                            engine="pallas", mesh=mesh)
        nr.execute_mut_batch([(HM_PUT, 1, 1), (HM_PUT, 2, 2)], rid=0)
        nr.fence_replica(2)  # shard 1 hosts rids 2, 3
        frozen = int(np.asarray(nr.log.ltails)[2])
        for i in range(3):
            nr.execute_mut_batch([(HM_PUT, i, i * 3)], rid=0)
        assert nr.stats()["fused_rounds"] == 4
        assert int(np.asarray(nr.log.ltails)[2]) == frozen
        assert int(nr.log.head) > frozen  # GC not stalled by the corpse

    def test_grow_resets_calibration_at_devices_key(self, monkeypatch):
        # mesh-aware winner selection: the verdict is measured at the
        # live (R, capacity, devices) point — the fused-calibration
        # event carries devices=, and growth recalibrates
        monkeypatch.setenv("NR_TPU_FUSED_CAL", "1")
        from node_replication_tpu.utils.trace import get_tracer

        mesh = replica_mesh(4)
        t = get_tracer()
        t.enable(None)
        try:
            nr = NodeReplicated(make_hashmap(17), n_replicas=8,
                                log_entries=512, gc_slack=64,
                                engine="auto", mesh=mesh)
            assert nr.stats()["fused_tier"] == "calibrating"
            for i in range(8):
                nr.execute_mut_batch(
                    [(HM_PUT, i % 17, i), (HM_PUT, (i + 5) % 17, i)],
                    rid=0,
                )
            st = nr.stats()
            assert st["fused_tier"] in ("auto:mesh_fused",
                                        "auto:chain"), st
            cal = [e for e in t.events()
                   if e["event"] == "fused-calibration"]
            assert cal and cal[-1]["devices"] == 4
            assert cal[-1]["tier"] == "mesh_fused"
            assert cal[-1]["winner"] in ("mesh_fused", "chain")
            nr.grow_fleet(4)
            assert nr.stats()["fused_tier"] == "calibrating"
        finally:
            t.disable()

    def test_vspace_mesh_fused_and_fenced_fallback(self):
        # the second fused model rides the same factory composition:
        # flat-vspace mesh-fused rounds are bit-identical to the
        # un-meshed scan chain, and a fenced meshed fleet falls back
        # (no fenced kernel variant) with identical results
        from node_replication_tpu.models.vspace import make_vspace

        mesh = replica_mesh(2)
        P_pages = 512
        mk = lambda **kw: NodeReplicated(
            make_vspace(P_pages, max_span=8), n_replicas=4,
            log_entries=512, gc_slack=64, **kw,
        )
        nr_m = mk(engine="pallas", mesh=mesh)
        nr_s = mk(engine="scan")
        rng = np.random.RandomState(11)
        ops = []
        for _ in range(12):
            if rng.rand() < 0.7:
                ops.append((1, int(rng.randint(P_pages)),
                            int(rng.randint(1, 1000)),
                            int(rng.randint(0, 8))))
            else:
                ops.append((2, int(rng.randint(P_pages)),
                            int(rng.randint(0, 8))))
        assert nr_m.execute_mut_batch(ops, rid=0) == \
            nr_s.execute_mut_batch(ops, rid=0)
        assert nr_m.last_round_tier == "mesh_fused"
        reg = get_registry()
        reg.enable()
        fb = reg.counter("nr.exec.engine.fused_fallback")
        before = fb.value
        for nr in (nr_m, nr_s):
            nr.fence_replica(3)
        ops2 = [(1, 9, 99, 4)]
        assert nr_m.execute_mut_batch(ops2, rid=0) == \
            nr_s.execute_mut_batch(ops2, rid=0)
        assert fb.value > before
        assert nr_m.last_round_tier == nr_m.engine  # chain served it
        nr_m.sync(); nr_s.sync()
        for a, b in zip(jax.tree.leaves(nr_m.states),
                        jax.tree.leaves(nr_s.states)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))

    def test_pipelined_serve_depth1_meshed(self):
        # PR 14's overlap on a meshed fleet: defer=True issues the
        # meshed launch at _begin_round (assembly stage) and reads
        # back at _finish_round (completion stage) — serve-batch
        # events must carry the mesh_fused tier and kernel-launch
        # events the mesh width, with responses exact
        from node_replication_tpu.serve import ServeConfig, ServeFrontend
        from node_replication_tpu.utils.trace import get_tracer

        mesh = replica_mesh(2)
        nr = NodeReplicated(make_seqreg(8), n_replicas=2,
                            log_entries=512, gc_slack=64,
                            engine="scan", mesh=mesh)
        # seqreg has no fused factory; the hashmap twin drives the
        # fused tier — use hashmap for the fused serve and seqreg
        # only as the no-factory sanity check
        assert nr.stats()["fused_tier"] == "off"
        nr_f = NodeReplicated(make_hashmap(32), n_replicas=2,
                              log_entries=512, gc_slack=64,
                              engine="pallas", mesh=mesh)
        t = get_tracer()
        t.enable(None)
        try:
            with ServeFrontend(
                nr_f,
                ServeConfig(queue_depth=32, batch_max_ops=8,
                            batch_linger_s=0.002, pipeline_depth=1),
            ) as fe:
                for i in range(24):
                    assert fe.call((HM_PUT, i % 32, i),
                                   rid=fe.rids[i % 2]) == 0
                assert fe.read((HM_GET, 5), rid=fe.rids[0]) >= 0
            events = t.events()
        finally:
            t.disable()
        batches = [e for e in events if e["event"] == "serve-batch"]
        assert batches
        assert all(e.get("engine") == "mesh_fused" for e in batches)
        launches = [e for e in events if e["event"] == "kernel-launch"]
        assert launches
        assert all(e["tier"] == "mesh_fused" and e["devices"] == 2
                   for e in launches)
        assert nr_f.stats()["fused_rounds"] > 0


class TestCnrMesh:
    def _pair(self, mesh_shape=(2, 4)):
        mesh = make_mesh(*mesh_shape)
        mapper = lambda opc, args: args[0]
        mk = lambda **extra: MultiLogReplicated(
            make_hashmap(64), mapper, nlogs=4, n_replicas=2,
            log_entries=1 << 10, gc_slack=32, exec_window=32, **extra,
        )
        return mk(), mk(mesh=mesh)

    def test_cnr_bit_identical(self, mesh):
        ref, got = self._pair()
        rng = np.random.default_rng(5)
        for nr in (ref, got):
            t = nr.register(0)
            r2 = nr.register(1)
            rr = np.random.default_rng(5)
            for i in range(60):
                nr.execute_mut(
                    (HM_PUT, int(rr.integers(64)),
                     int(rr.integers(1000)), 0), t)
            nr.sync()
            assert nr.execute((HM_GET, 7), r2) is not None
        for a, b in zip(jax.tree.leaves(ref.states),
                        jax.tree.leaves(got.states)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for cur in ("tail", "ctail", "head"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref.ml, cur)),
                np.asarray(getattr(got.ml, cur)),
            )
        np.testing.assert_array_equal(
            np.asarray(ref.ml.ltails), np.asarray(got.ml.ltails)
        )
        assert got.snapshot()["mesh"]["shape"] == {
            "replica": 2, "log": 4,
        }

    def test_cnr_batch_bit_identical(self, mesh):
        ref, got = self._pair()
        ops = [(HM_PUT, i % 64, i, 0) for i in range(48)]
        assert ref.execute_mut_batch(ops, rid=0) == \
            got.execute_mut_batch(ops, rid=0)
        ref.sync()
        got.sync()
        for a, b in zip(jax.tree.leaves(ref.states),
                        jax.tree.leaves(got.states)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_cnr_serve_frontend(self, mesh):
        # the frontend serves the meshed CNR twin too: construction
        # must record the worker→device map through replica_device
        # (regression: getattr(nr, 'mesh') passed but the method was
        # NR-only, crashing __init__)
        from node_replication_tpu.serve import ServeConfig, ServeFrontend

        _, got = self._pair()
        with ServeFrontend(got, ServeConfig(batch_max_ops=8,
                                            batch_linger_s=0.0)) as fe:
            assert fe.call((HM_PUT, 3, 7, 0), rid=1) == 0
            st = fe.stats()
        assert len(st["mesh"]["device_of_rid"]) == 2
        assert st["mesh"]["devices"] == 2  # one row device per shard

    def test_cnr_validation(self, mesh):
        mapper = lambda opc, args: args[0]
        with pytest.raises(ValueError):  # L=3 can't shard over 4 cols
            MultiLogReplicated(make_hashmap(8), mapper, nlogs=3,
                               n_replicas=2, mesh=make_mesh(2, 4))
        with pytest.raises(ValueError):  # R=3 can't shard over 2 rows
            MultiLogReplicated(make_hashmap(8), mapper, nlogs=4,
                               n_replicas=3, mesh=make_mesh(2, 4))
        with pytest.raises(ValueError):  # not a ('replica','log') Mesh
            MultiLogReplicated(make_hashmap(8), mapper, nlogs=4,
                               n_replicas=2, mesh=4)
