"""Mesh-sharded fleet differential suite (ISSUE 10).

The acceptance contract of the mesh work: placement changes SPEED,
never results. Every test drives the same op sequence through an
un-meshed wrapper and a mesh-sharded twin (replica axis under
`NamedSharding(mesh, P('replica'))`, 8 forced host devices — see
conftest.py) and requires bit-identical responses, states, and cursor
lattices — scan AND union engines, both collective tiers (shmap /
gspmd), hashmap AND seqreg models, with a fenced-replica case pinning
the cross-device GC-head mask and a ring-tier case pinning the
collective catch-up path. This file is the CI `mesh-smoke` job.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from node_replication_tpu import NodeReplicated
from node_replication_tpu.core.cnr import MultiLogReplicated
from node_replication_tpu.core.log import log_append
from node_replication_tpu.models import (
    HM_GET,
    HM_PUT,
    SR_GET,
    SR_SET,
    make_hashmap,
    make_seqreg,
)
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.parallel import make_mesh, replica_mesh


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return replica_mesh(8)


def _assert_fleets_equal(ref, got):
    for a, b in zip(jax.tree.leaves(ref.states),
                    jax.tree.leaves(got.states)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(ref.log.ltails), np.asarray(got.log.ltails)
    )
    for cursor in ("tail", "ctail", "head"):
        assert int(getattr(ref.log, cursor)) == int(
            getattr(got.log, cursor)
        ), cursor


def _seqreg_pair(mesh, **kw):
    mk = lambda **extra: NodeReplicated(
        make_seqreg(8), n_replicas=8, log_entries=1 << 12,
        gc_slack=64, exec_window=32, **extra,
    )
    return mk(**kw), mk(mesh=mesh, **kw)


def _hashmap_pair(mesh, **kw):
    mk = lambda **extra: NodeReplicated(
        make_hashmap(64), n_replicas=8, log_entries=1 << 12,
        gc_slack=64, exec_window=32, **extra,
    )
    return mk(**kw), mk(mesh=mesh, **kw)


class TestNodeReplicatedMesh:
    def test_scan_engine_shmap_tier_bit_identical(self, mesh):
        # seqreg has no combined form on purpose: the scan engine →
        # the explicit-collective shard_map tier
        ref, got = _seqreg_pair(mesh)
        assert got.engine == "scan" and got._mesh_tier == "shmap"
        t_ref, t_got = ref.register(2), got.register(2)
        for i in range(60):
            op = (SR_SET, i % 8, i)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        assert ref.execute((SR_GET, 5), t_ref) == got.execute(
            (SR_GET, 5), t_got
        )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    def test_union_engine_gspmd_tier_bit_identical(self, mesh):
        # hashmap is window_canonical → combined engine → GSPMD tier
        # (the union-plan economics survive sharding by annotation)
        ref, got = _hashmap_pair(mesh)
        assert got.engine == "combined" and got._mesh_tier == "gspmd"
        t_ref, t_got = ref.register(0), got.register(0)
        rng = np.random.default_rng(3)
        for i in range(60):
            op = (HM_PUT, int(rng.integers(64)),
                  int(rng.integers(1000)), 0)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        for k in (0, 7, 31):
            assert ref.execute((HM_GET, k), t_ref) == got.execute(
                (HM_GET, k), t_got
            )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    def test_shmap_forced_on_combined_model(self, mesh):
        # collectives='shmap' on a combined-engine model: the scan
        # collective replaces the union plan — still bit-identical
        # (the engines are pinned equal), placement-only difference
        ref = NodeReplicated(make_hashmap(64), n_replicas=8,
                             log_entries=1 << 12, gc_slack=64,
                             exec_window=32)
        got = NodeReplicated(make_hashmap(64), n_replicas=8,
                             log_entries=1 << 12, gc_slack=64,
                             exec_window=32, mesh=mesh,
                             collectives="shmap")
        assert got._mesh_tier == "shmap"
        t_ref, t_got = ref.register(0), got.register(0)
        for i in range(40):
            op = (HM_PUT, i % 64, i, 0)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    def test_batch_path_bit_identical(self, mesh):
        # the serve entry point (execute_mut_batch) over the mesh
        ref, got = _seqreg_pair(mesh)
        ops = [(SR_SET, i % 8, i) for i in range(96)]
        assert ref.execute_mut_batch(ops, rid=1) == \
            got.execute_mut_batch(ops, rid=1)
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)

    @pytest.mark.parametrize("pair", ["seqreg", "hashmap"])
    def test_fenced_gc_mask_across_devices(self, mesh, pair):
        # the fenced-head GC mask must stay correct when the corpse
        # lives on a different device than the combiner: fence a
        # replica mid-run on BOTH engines' tiers, require identical
        # heads/ltails/states, then repair and require convergence
        mk = _seqreg_pair if pair == "seqreg" else _hashmap_pair
        ref, got = mk(mesh)
        mkop = (
            (lambda i: (SR_SET, i % 8, i)) if pair == "seqreg"
            else (lambda i: (HM_PUT, i % 64, i, 0))
        )
        for nr in (ref, got):
            t = nr.register(0)
            for i in range(24):
                nr.execute_mut(mkop(i), t)
            nr.fence_replica(5)
            for i in range(24, 48):
                nr.execute_mut(mkop(i), t)
        # the fenced cursor is frozen; head advanced past it
        assert int(np.asarray(got.log.ltails)[5]) < int(got.log.head)
        _assert_fleets_equal(ref, got)
        for nr in (ref, got):
            nr.clone_replica_from(5)
            nr.unfence_replica(5)
            nr.sync()
            assert nr.replicas_equal()
        _assert_fleets_equal(ref, got)

    def test_ring_catchup_tier_bit_identical(self, mesh):
        # a large uniform backlog takes the ring tier on the mesh
        # (make_ring_exec promoted into sync()) — and must land on the
        # same states/cursors as the un-meshed scan rounds
        ref, got = _seqreg_pair(mesh)
        rng = np.random.default_rng(0)
        N = 400
        opc = np.full(N, SR_SET, np.int32)
        args = np.zeros((N, 3), np.int32)
        args[:, 0] = rng.integers(0, 8, N)
        args[:, 1] = rng.integers(0, 1000, N)
        for nr in (ref, got):
            nr.log = log_append(nr.spec, nr.log, jnp.asarray(opc),
                                jnp.asarray(args), N)
            nr.sync()
        assert got._ring_rounds > 0, "ring tier never fired"
        assert ref._ring_rounds == 0
        _assert_fleets_equal(ref, got)

    def test_ring_tier_counter(self, mesh):
        reg = get_registry()
        reg.enable()
        try:
            _, got = _seqreg_pair(mesh)
            before = reg.counter("nr.exec.engine.ring").value
            N = 200
            opc = np.full(N, SR_SET, np.int32)
            args = np.zeros((N, 3), np.int32)
            got.log = log_append(got.spec, got.log, jnp.asarray(opc),
                                 jnp.asarray(args), N)
            got.sync()
            assert reg.counter("nr.exec.engine.ring").value > before
            assert reg.counter("nr.exec.mesh.shmap").value > 0
            assert reg.counter("mesh.sync_bytes").value > 0
            assert reg.gauge("mesh.replicas_per_device").value == 1
        finally:
            reg.disable()

    def test_grow_fleet_keeps_placement(self, mesh):
        ref, got = _seqreg_pair(mesh)
        t_ref, t_got = ref.register(0), got.register(0)
        for i in range(16):
            op = (SR_SET, i % 8, i)
            ref.execute_mut(op, t_ref)
            got.execute_mut(op, t_got)
        # growing by a non-multiple of the shard count is rejected
        # BEFORE any state mutates
        with pytest.raises(ValueError):
            got.grow_fleet(3)
        assert got.n_replicas == 8
        ref.grow_fleet(8)
        new = got.grow_fleet(8)
        assert new == list(range(8, 16))
        for i in range(16, 32):
            op = (SR_SET, i % 8, i)
            assert ref.execute_mut(op, t_ref) == got.execute_mut(
                op, t_got
            )
        ref.sync()
        got.sync()
        _assert_fleets_equal(ref, got)
        assert got.replicas_equal()

    def test_checkpoint_restore_replaces(self, mesh, tmp_path):
        _, got = _seqreg_pair(mesh)
        t = got.register(0)
        for i in range(20):
            got.execute_mut((SR_SET, i % 8, i), t)
        path = str(tmp_path / "snap.npz")
        got.checkpoint(path)
        back = NodeReplicated.restore(path, make_seqreg(8), mesh=mesh)
        _assert_fleets_equal(got, back)
        # the restored fleet still runs mesh rounds
        t2 = back.register(0)
        assert back.execute_mut((SR_SET, 0, 999), t2) is not None
        assert back._mesh_tier is not None

    def test_validation(self, mesh):
        with pytest.raises(ValueError):  # 8 shards can't take R=6
            NodeReplicated(make_seqreg(4), n_replicas=6, mesh=mesh)
        with pytest.raises(ValueError):  # unknown tier
            NodeReplicated(make_seqreg(4), n_replicas=8, mesh=mesh,
                           collectives="nope")
        with pytest.raises(ValueError):  # shmap has no checkify twin
            NodeReplicated(make_seqreg(4), n_replicas=8, mesh=mesh,
                           collectives="shmap", debug=True)

    def test_replica_device_map(self, mesh):
        _, got = _seqreg_pair(mesh)
        devs = [str(got.replica_device(r)) for r in range(8)]
        assert len(set(devs)) == 8  # 8 replicas over 8 devices
        snap = got.snapshot()
        assert snap["mesh"]["devices"] == 8
        assert snap["mesh"]["replicas_per_device"] == 1
        un = NodeReplicated(make_seqreg(4), n_replicas=2)
        assert un.replica_device(0) is None
        assert un.snapshot()["mesh"] is None

    def test_serve_frontend_maps_workers_to_devices(self, mesh):
        from node_replication_tpu.serve import ServeConfig, ServeFrontend

        _, got = _seqreg_pair(mesh)
        with ServeFrontend(got, ServeConfig(batch_max_ops=8,
                                            batch_linger_s=0.0)) as fe:
            for i in range(1, 9):
                assert fe.call((SR_SET, 2, i),
                               rid=i % got.n_replicas) == i - 1
            st = fe.stats()
        assert st["mesh"]["devices"] == 8
        assert sum(st["mesh"]["replicas_per_device"].values()) == 8
        assert len(st["mesh"]["device_of_rid"]) == 8


class TestCnrMesh:
    def _pair(self, mesh_shape=(2, 4)):
        mesh = make_mesh(*mesh_shape)
        mapper = lambda opc, args: args[0]
        mk = lambda **extra: MultiLogReplicated(
            make_hashmap(64), mapper, nlogs=4, n_replicas=2,
            log_entries=1 << 10, gc_slack=32, exec_window=32, **extra,
        )
        return mk(), mk(mesh=mesh)

    def test_cnr_bit_identical(self, mesh):
        ref, got = self._pair()
        rng = np.random.default_rng(5)
        for nr in (ref, got):
            t = nr.register(0)
            r2 = nr.register(1)
            rr = np.random.default_rng(5)
            for i in range(60):
                nr.execute_mut(
                    (HM_PUT, int(rr.integers(64)),
                     int(rr.integers(1000)), 0), t)
            nr.sync()
            assert nr.execute((HM_GET, 7), r2) is not None
        for a, b in zip(jax.tree.leaves(ref.states),
                        jax.tree.leaves(got.states)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for cur in ("tail", "ctail", "head"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref.ml, cur)),
                np.asarray(getattr(got.ml, cur)),
            )
        np.testing.assert_array_equal(
            np.asarray(ref.ml.ltails), np.asarray(got.ml.ltails)
        )
        assert got.snapshot()["mesh"]["shape"] == {
            "replica": 2, "log": 4,
        }

    def test_cnr_batch_bit_identical(self, mesh):
        ref, got = self._pair()
        ops = [(HM_PUT, i % 64, i, 0) for i in range(48)]
        assert ref.execute_mut_batch(ops, rid=0) == \
            got.execute_mut_batch(ops, rid=0)
        ref.sync()
        got.sync()
        for a, b in zip(jax.tree.leaves(ref.states),
                        jax.tree.leaves(got.states)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_cnr_serve_frontend(self, mesh):
        # the frontend serves the meshed CNR twin too: construction
        # must record the worker→device map through replica_device
        # (regression: getattr(nr, 'mesh') passed but the method was
        # NR-only, crashing __init__)
        from node_replication_tpu.serve import ServeConfig, ServeFrontend

        _, got = self._pair()
        with ServeFrontend(got, ServeConfig(batch_max_ops=8,
                                            batch_linger_s=0.0)) as fe:
            assert fe.call((HM_PUT, 3, 7, 0), rid=1) == 0
            st = fe.stats()
        assert len(st["mesh"]["device_of_rid"]) == 2
        assert st["mesh"]["devices"] == 2  # one row device per shard

    def test_cnr_validation(self, mesh):
        mapper = lambda opc, args: args[0]
        with pytest.raises(ValueError):  # L=3 can't shard over 4 cols
            MultiLogReplicated(make_hashmap(8), mapper, nlogs=3,
                               n_replicas=2, mesh=make_mesh(2, 4))
        with pytest.raises(ValueError):  # R=3 can't shard over 2 rows
            MultiLogReplicated(make_hashmap(8), mapper, nlogs=4,
                               n_replicas=3, mesh=make_mesh(2, 4))
        with pytest.raises(ValueError):  # not a ('replica','log') Mesh
            MultiLogReplicated(make_hashmap(8), mapper, nlogs=4,
                               n_replicas=2, mesh=4)
