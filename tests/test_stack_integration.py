"""Integration tests porting `nr/tests/stack.rs` / `cnr/tests/stack.rs`:

- tagged values `(count << 16) | tid` pushed from many logical threads on
  several replicas (`nr/tests/stack.rs:170-343`);
- a VerifyStack whose *dispatch itself* checks per-thread monotonicity on
  every pop — the linearizability smoke test executed inside the replayed
  DS on every replica (invariant at `nr/tests/stack.rs:236-276`). Asserts
  can't fire inside jit, so violations increment a counter in state that
  must be zero under `verify()`;
- `replicas_are_equal`: full state (incl. pop history) identical across
  replicas after random concurrent ops (`nr/tests/stack.rs:434-489`).
"""

import random

import jax.numpy as jnp
import numpy as np

from node_replication_tpu import NodeReplicated
from node_replication_tpu.ops.encoding import Dispatch

VPUSH = 1
VPOP = 2
NTHREADS = 8


def make_verify_stack(capacity: int, n_threads: int) -> Dispatch:
    """Stack that checks, on every pop, that values tagged per thread come
    off in strictly decreasing per-thread count order."""

    def make_state():
        return {
            "buf": jnp.zeros((capacity,), jnp.int32),
            "top": jnp.zeros((), jnp.int32),
            # last count seen per tag; init high so first pop passes
            "last_seen": jnp.full((n_threads,), 1 << 20, jnp.int32),
            "violations": jnp.zeros((), jnp.int32),
            "pop_history": jnp.zeros((capacity,), jnp.int32),
            "pops": jnp.zeros((), jnp.int32),
        }

    def push(state, args):
        top = state["top"]
        ok = top < capacity
        idx = jnp.where(ok, top, capacity - 1)
        buf = jnp.where(ok, state["buf"].at[idx].set(args[0]), state["buf"])
        # a fresh push raises the per-tag ceiling: the next pop of this tag
        # must return exactly this value (it sits above all older ones)
        tid = args[0] & 0xFFFF
        count = args[0] >> 16
        last = jnp.where(
            ok, state["last_seen"].at[tid].set(count + 1),
            state["last_seen"],
        )
        return {**state, "buf": buf, "last_seen": last,
                "top": jnp.where(ok, top + 1, top)}, jnp.int32(0)

    def pop(state, args):
        top = state["top"]
        ok = top > 0
        idx = jnp.where(ok, top - 1, 0)
        val = state["buf"][idx]
        tid = val & 0xFFFF
        count = val >> 16
        # invariant: per-tag counts strictly decrease as we pop
        bad = ok & (count >= state["last_seen"][tid])
        last = jnp.where(
            ok, state["last_seen"].at[tid].set(count), state["last_seen"]
        )
        hist = jnp.where(
            ok, state["pop_history"].at[state["pops"]].set(val),
            state["pop_history"],
        )
        return {
            **state,
            "top": jnp.where(ok, top - 1, top),
            "last_seen": last,
            "violations": state["violations"] + bad.astype(jnp.int32),
            "pop_history": hist,
            "pops": state["pops"] + ok.astype(jnp.int32),
        }, jnp.where(ok, val, jnp.int32(-1))

    return Dispatch(
        name="verify_stack",
        make_state=make_state,
        write_ops=(push, pop),
        read_ops=(),
        arg_width=3,
    )


def test_parallel_push_sequential_pop():
    # Phase 1: 8 threads across 2 replicas push tagged values; phase 2: one
    # thread pops everything; per-thread monotonicity must hold
    # (`nr/tests/stack.rs:170-257` shape).
    per_thread = 64
    d = make_verify_stack(NTHREADS * per_thread + 8, NTHREADS)
    nr = NodeReplicated(d, n_replicas=2, log_entries=1024, gc_slack=64,
                        exec_window=128)
    toks = [nr.register(t % 2) for t in range(NTHREADS)]
    rng = random.Random(9)
    remaining = {t: 1 for t in range(NTHREADS)}  # next count per thread
    live = list(range(NTHREADS))
    while live:
        t = rng.choice(live)
        nr.enqueue_mut((VPUSH, (remaining[t] << 16) | t), toks[t])
        remaining[t] += 1
        if remaining[t] > per_thread:
            live.remove(t)
        if rng.random() < 0.2:
            nr.flush(toks[t].rid)
    nr.flush()
    popper = toks[0]
    for _ in range(NTHREADS * per_thread):
        assert nr.execute_mut((VPOP,), popper) != -1

    def check(s):
        assert int(s["violations"]) == 0
        assert int(s["top"]) == 0
        assert int(s["pops"]) == NTHREADS * per_thread

    nr.verify(check, rid=0)
    nr.verify(check, rid=1)


def test_parallel_push_and_pop_replicas_equal():
    # Interleaved pushes and pops from all threads; invariant checked
    # during replay on every replica; full state incl. pop history equal
    # across replicas at the end (`nr/tests/stack.rs:345-489`).
    per_thread = 48
    d = make_verify_stack(NTHREADS * per_thread + 8, NTHREADS)
    nr = NodeReplicated(d, n_replicas=2, log_entries=1024, gc_slack=64,
                        exec_window=128)
    toks = [nr.register(t % 2) for t in range(NTHREADS)]
    rng = random.Random(10)
    counts = [1] * NTHREADS
    for _ in range(NTHREADS * per_thread):
        t = rng.randrange(NTHREADS)
        if rng.random() < 0.6:
            nr.enqueue_mut((VPUSH, (counts[t] << 16) | t), toks[t])
            counts[t] += 1
        else:
            nr.enqueue_mut((VPOP,), toks[t])
        if rng.random() < 0.15:
            nr.flush(toks[t].rid)
    nr.flush()
    nr.sync()
    assert nr.replicas_equal()
    nr.verify(lambda s: int(s["violations"]) == 0 or
              (_ for _ in ()).throw(AssertionError("monotonicity violated")))
