"""Checkpoint/resume + recovery-by-replay tests.

The recovery model under test is the reference's (SURVEY.md §5): replica
state is reconstructable from deterministic init by replaying the log, so
recovered and surviving replicas must agree bit-for-bit.
"""

import numpy as np

from node_replication_tpu.core.checkpoint import (
    load_snapshot,
    recover_states,
    save_snapshot,
)
from node_replication_tpu.core.log import LogSpec, log_append, log_init
from node_replication_tpu.core.replica import (
    NodeReplicated,
    replicate_state,
)
from node_replication_tpu.models import HM_GET, HM_PUT, make_hashmap
from node_replication_tpu.ops.encoding import encode_ops


def _filled_nr(n_ops=50, n_replicas=2):
    nr = NodeReplicated(
        make_hashmap(64), n_replicas=n_replicas, log_entries=1 << 10,
        gc_slack=32,
    )
    tok = nr.register(0)
    for i in range(n_ops):
        nr.execute_mut((HM_PUT, i % 64, 1000 + i), tok)
    nr.sync()
    return nr


class TestSnapshotRoundtrip:
    def test_save_load_identical(self, tmp_path):
        nr = _filled_nr()
        path = str(tmp_path / "snap.npz")
        nr.checkpoint(path)
        spec, log, states = load_snapshot(path, nr.states)
        assert spec == nr.spec
        assert int(log.tail) == int(nr.log.tail)
        for a, b in zip(
            __import__("jax").tree.leaves(states),
            __import__("jax").tree.leaves(nr.states),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_continues(self, tmp_path):
        nr = _filled_nr()
        path = str(tmp_path / "snap.npz")
        nr.checkpoint(path)
        expect_ctail = int(nr.log.ctail)
        del nr
        nr2 = NodeReplicated.restore(
            path, make_hashmap(64)
        )
        assert int(nr2.log.ctail) == expect_ctail
        tok = nr2.register(1)
        # writes continue from the snapshot position
        nr2.execute_mut((HM_PUT, 7, 4242), tok)
        assert nr2.execute((HM_GET, 7), tok) == 4242
        assert nr2.replicas_equal()


class TestRecoveryByReplay:
    def test_recover_matches_survivors(self):
        nr = _filled_nr()
        survivor = __import__("jax").tree.map(
            lambda a: np.asarray(a[0]).copy(), nr.states
        )
        nr.recover()  # discard states, rebuild from head
        rebuilt = __import__("jax").tree.map(
            lambda a: np.asarray(a[0]), nr.states
        )
        np.testing.assert_array_equal(
            survivor["values"], rebuilt["values"]
        )
        np.testing.assert_array_equal(
            survivor["present"], rebuilt["present"]
        )
        assert nr.replicas_equal()

    def test_recover_from_base_snapshot_position(self):
        # Snapshot states mid-stream, append more, recover from that base.
        spec = LogSpec(capacity=1 << 10, n_replicas=2, gc_slack=32)
        d = make_hashmap(32)
        log = log_init(spec)
        opc, args, n = encode_ops(
            [(HM_PUT, k, k + 1) for k in range(20)], 3
        )
        log = log_append(spec, log, opc, args, n)
        log, states = recover_states(d, spec, log)  # replay all 20
        base = states
        base_pos = int(log.tail)
        opc2, args2, n2 = encode_ops(
            [(HM_PUT, k, 900 + k) for k in range(5)], 3
        )
        log = log_append(spec, log, opc2, args2, n2)
        log, states = recover_states(
            d, spec, log, base_states=base, base_pos=base_pos
        )
        vals = np.asarray(states["values"][0])
        assert all(vals[k] == 900 + k for k in range(5))
        assert all(vals[k] == k + 1 for k in range(5, 20))

    def test_recover_refuses_after_wrap(self):
        import pytest

        from node_replication_tpu.core.log import log_exec_all

        spec = LogSpec(capacity=1 << 10, n_replicas=1, gc_slack=32)
        d = make_hashmap(32)
        log = log_init(spec)
        states = replicate_state(d.init_state(), 1)
        opc, args, n = encode_ops([(HM_PUT, 1, 2)] * 64, 3)
        for _ in range(20):  # 1280 appends > 1024 capacity: ring wraps
            log = log_append(spec, log, opc, args, n)
            log, states, _ = log_exec_all(spec, d, log, states, 64)
        with pytest.raises(ValueError, match="overwritten"):
            recover_states(d, spec, log)

    def test_stats_counters(self):
        nr = _filled_nr(n_ops=10)
        s = nr.stats()
        assert s["appended"] == 10
        assert s["ctail"] == 10
        assert s["exec_rounds"] > 0
