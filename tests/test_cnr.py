"""MultiLogReplicated (CNR per-op surface) + open-addressing hashmap tests."""

import random

import numpy as np
import pytest

from node_replication_tpu.core.cnr import MultiLogReplicated
from node_replication_tpu.core.replica import NodeReplicated
from node_replication_tpu.models import (
    OA_GET,
    OA_PUT,
    OA_REMOVE,
    make_hashmap,
    make_oahashmap,
    make_sortedset,
    sortedset_log_mapper,
)


def _key_mapper(opcode, args):
    return args[0]


class TestMultiLogReplicated:
    def test_basic_write_read_across_replicas(self):
        c = MultiLogReplicated(
            make_hashmap(64), _key_mapper, nlogs=4, n_replicas=2,
            log_entries=1 << 10, gc_slack=32,
        )
        t0, t1 = c.register(0), c.register(1)
        assert c.execute_mut((1, 5, 55), t0) == 0
        assert c.execute((1, 5), t1) == 55  # other replica, mapped-log sync
        assert c.execute_mut((2, 5), t1) == 1
        assert c.execute((1, 5), t0) == -1

    def test_execute_mut_preserves_enqueue_mut_backlog(self):
        # CNR twin of the r3 VERDICT weak-#4 regression: execute_mut must
        # return only its own response; earlier enqueue_mut responses
        # (possibly on OTHER logs) stay queued for responses().
        c = MultiLogReplicated(
            make_hashmap(64), _key_mapper, nlogs=4, n_replicas=1,
            log_entries=1 << 10, gc_slack=32,
        )
        t = c.register(0)
        c.enqueue_mut((1, 0, 100), t)   # log 0, put → resp 0
        c.enqueue_mut((1, 1, 101), t)   # log 1, put → resp 0
        # routed to log 0: combines log 0, delivering the first backlog
        # entry but NOT the log-1 one
        assert c.execute_mut((2, 0), t) == 1    # remove k=0 → was present
        assert c.responses(t) == [0]            # log-0 put only
        c.flush()                               # combine remaining logs
        assert c.responses(t) == [0]            # log-1 put arrives
        assert c.execute((1, 1), t) == 101

    def test_ops_partition_over_logs(self):
        c = MultiLogReplicated(
            make_hashmap(64), _key_mapper, nlogs=4, n_replicas=1,
            log_entries=1 << 10, gc_slack=32,
        )
        t = c.register(0)
        for k in range(16):
            c.execute_mut((1, k, k), t)
        assert c.stats()["tails"] == [4, 4, 4, 4]

    def test_differential_vs_single_log(self):
        # same random op stream through CNR (4 logs) and NR (1 log):
        # final states must agree (ops on distinct keys commute)
        rng = random.Random(9)
        cnr = MultiLogReplicated(
            make_hashmap(32), _key_mapper, nlogs=4, n_replicas=2,
            log_entries=1 << 10, gc_slack=32,
        )
        nr = NodeReplicated(
            make_hashmap(32), n_replicas=2, log_entries=1 << 10,
            gc_slack=32,
        )
        ct = [cnr.register(r) for r in range(2)]
        nt = [nr.register(r) for r in range(2)]
        for _ in range(200):
            r = rng.randrange(2)
            k = rng.randrange(32)
            if rng.random() < 0.6:
                op = (1, k, rng.randrange(1000))
                cnr.execute_mut(op, ct[r])
                nr.execute_mut(op, nt[r])
            else:
                op = (2, k)
                cnr.execute_mut(op, ct[r])
                nr.execute_mut(op, nt[r])
        cnr.sync()
        nr.sync()
        assert cnr.replicas_equal() and nr.replicas_equal()
        a = cnr.verify(lambda s: s)
        b = nr.verify(lambda s: s)
        np.testing.assert_array_equal(a["values"], b["values"])
        np.testing.assert_array_equal(a["present"], b["present"])

    def test_sortedset_with_its_mapper(self):
        c = MultiLogReplicated(
            make_sortedset(128), sortedset_log_mapper, nlogs=2,
            n_replicas=2, log_entries=1 << 10, gc_slack=32,
        )
        t = c.register(0)
        for k in (3, 7, 11):
            assert c.execute_mut((1, k), t) == 1
        assert c.execute((2, 0, 16), c.register(1)) == 3  # range count
        c.sync()
        assert c.replicas_equal()

    def test_gc_callback_fires_on_starved_log(self):
        events = []
        c = MultiLogReplicated(
            make_hashmap(16), _key_mapper, nlogs=2, n_replicas=1,
            log_entries=1 << 10, gc_slack=32, exec_window=4,
            gc_callback=lambda log, rid: events.append((log, rid)),
        )
        # Drive the watchdog directly: the callback contract is
        # (log_idx, dormant_replica)
        c._watchdog(63, 1, "test")
        assert events == [(1, 0)]


class TestOaHashmap:
    def test_shadow_model_with_collisions(self):
        # tiny table + window forces collisions and tombstone reuse
        d = make_oahashmap(32, probe=8)
        nr = NodeReplicated(d, n_replicas=2, log_entries=1 << 10,
                            gc_slack=32)
        t = nr.register(0)
        shadow = {}
        rng = random.Random(4)
        for _ in range(300):
            k = rng.randrange(-50, 50)  # negative keys too
            p = rng.random()
            if p < 0.5:
                v = rng.randrange(1000)
                resp = nr.execute_mut((OA_PUT, k, v), t)
                if resp == 0:
                    shadow[k] = v
                else:
                    assert resp == -2  # deterministic window-full drop
            elif p < 0.75:
                resp = nr.execute_mut((OA_REMOVE, k), t)
                assert resp == (1 if k in shadow else 0)
                shadow.pop(k, None)
            else:
                got = nr.execute((OA_GET, k), t)
                assert got == shadow.get(k, -1)
        nr.sync()
        assert nr.replicas_equal()

    def test_update_in_place_prefers_match_over_tombstone(self):
        d = make_oahashmap(16, probe=16)
        nr = NodeReplicated(d, n_replicas=1, log_entries=1 << 10,
                            gc_slack=32)
        t = nr.register(0)
        nr.execute_mut((OA_PUT, 1, 10), t)
        nr.execute_mut((OA_PUT, 2, 20), t)
        nr.execute_mut((OA_REMOVE, 2, 0), t)  # tombstone early slot
        nr.execute_mut((OA_PUT, 1, 11), t)  # must UPDATE, not re-insert
        assert nr.execute((OA_GET, 1), t) == 11
        # exactly one occupied slot for key 1
        def check(state):
            occ = (state["flag"] == 1) & (state["keys"] == 1)
            assert occ.sum() == 1
        nr.verify(check)

    def test_window_full_drops_deterministically(self):
        d = make_oahashmap(64, probe=2)
        nr = NodeReplicated(d, n_replicas=2, log_entries=1 << 10,
                            gc_slack=32)
        t = nr.register(0)
        # hammer puts until some drop; replicas must still agree
        resps = [nr.execute_mut((OA_PUT, k, k), t) for k in range(64)]
        assert -2 in resps  # with probe=2 some windows overflow
        nr.sync()
        assert nr.replicas_equal()
