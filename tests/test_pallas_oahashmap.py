"""Open-addressing hashmap Pallas kernel tests (interpret mode on CPU).

Differential contract: probe-window first-match/first-free selection,
tombstone transitions, wrapped windows, and window-full drops must agree
BIT-identically with the sequential `apply_write` fold. `NR_TPU_SMOKE=1`
runs the Mosaic lowering on real hardware.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from node_replication_tpu.core.log import LogSpec, log_init
from node_replication_tpu.core.replica import replicate_state
from node_replication_tpu.core.step import make_step
from node_replication_tpu.models import make_oahashmap
from node_replication_tpu.ops.encoding import apply_write
from node_replication_tpu.ops.pallas_oahashmap import (
    make_oahashmap_replay,
    make_pallas_oahashmap_step,
    oahashmap_model_view,
    pallas_oahashmap_state,
)


def fold(d, state, opcodes, args):
    step = jax.jit(lambda s, o, a: apply_write(d, s, o, a))
    resps = []
    for i in range(len(opcodes)):
        state, r = step(state, opcodes[i], args[i])
        resps.append(int(r))
    return state, resps


class TestOaKernel:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_sequential_fold(self, seed):
        # small table + tiny keyspace: heavy window collisions, wraps,
        # tombstone churn, and window-full drops all occur
        S_TAB, PROBE, W, R = 300, 8, 96, 3
        d = make_oahashmap(S_TAB, probe=PROBE)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=W, p=[0.06, 0.55, 0.33, 0.06]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack([rng.integers(-50, 50, W), rng.integers(1, 999, W),
                      np.zeros(W)], axis=1),
            jnp.int32,
        )
        st0 = d.init_state()
        ref_state, ref_resps = fold(d, st0, opcodes, args)
        replay = make_oahashmap_replay(S_TAB, PROBE, R, W,
                                       interpret=True)
        st = pallas_oahashmap_state(S_TAB, R, st0)
        keys, vals, flag, resps = replay(
            opcodes, args, st["keys"], st["vals"], st["flag"]
        )
        assert [int(x) for x in resps] == ref_resps
        view = oahashmap_model_view(
            {"keys": keys, "vals": vals, "flag": flag}, S_TAB
        )
        for k in ("keys", "vals", "flag"):
            for r in range(R):
                np.testing.assert_array_equal(
                    np.asarray(view[k][r]), np.asarray(ref_state[k]), k
                )

    def test_step_matches_scan_step(self):
        S_TAB, PROBE, R, Bw, Br, STEPS = 300, 8, 3, 4, 2, 4
        d = make_oahashmap(S_TAB, probe=PROBE)
        spec = LogSpec(capacity=1 << 10, n_replicas=R, gc_slack=32)
        rng = np.random.default_rng(5)
        scan_step = make_step(d, spec, Bw, Br, jit=False, combined=False)
        pl_step = make_pallas_oahashmap_step(
            S_TAB, PROBE, spec, Bw, Br, interpret=True, jit=False
        )
        log_a, st_a = log_init(spec), replicate_state(d.init_state(), R)
        log_b = log_init(spec)
        st_b = pallas_oahashmap_state(S_TAB, R, d.init_state())
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, 1, 2], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                np.stack([rng.integers(-30, 30, (R, Bw)),
                          rng.integers(1, 99, (R, Bw)),
                          np.zeros((R, Bw))], axis=-1),
                jnp.int32,
            )
            rd_opc = jnp.ones((R, Br), jnp.int32)
            rd_args = jnp.asarray(
                np.stack([rng.integers(-30, 30, (R, Br)),
                          np.zeros((R, Br)), np.zeros((R, Br))],
                         axis=-1),
                jnp.int32,
            )
            log_a, st_a, wr_a, rd_a = scan_step(
                log_a, st_a, wr_opc, wr_args, rd_opc, rd_args
            )
            log_b, st_b, wr_b, rd_b = pl_step(
                log_b, st_b, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_a), np.asarray(wr_b))
            np.testing.assert_array_equal(np.asarray(rd_a), np.asarray(rd_b))
        view = oahashmap_model_view(st_b, S_TAB)
        for k in ("keys", "vals", "flag"):
            np.testing.assert_array_equal(
                np.asarray(view[k]), np.asarray(st_a[k]), k
            )


class TestChunkedGrid:
    def test_replica_axis_splits_into_bounded_calls(self, monkeypatch):
        # the grid cap (ops/pallas_chunk.MAX_GRID — the hardware
        # aliasing-race workaround) splits the replica axis into several
        # pallas calls; force tiny chunks in interpret mode and pin that
        # chunk concatenation and canonical responses survive the split,
        # including a remainder chunk (R not divisible by the chunk)
        from node_replication_tpu.ops import pallas_chunk
        from node_replication_tpu.ops import pallas_oahashmap as poa

        monkeypatch.setattr(pallas_chunk, "MAX_GRID", 2)
        # shrink the VMEM budget so group=1 (R=7 is prime, so any budget
        # below 7 planes-worth forces it): chunk_r = 1*2 = 2 -> chunks
        # of 2, 2, 2 and a remainder of 1 — the split REALLY happens
        monkeypatch.setattr(poa, "_VMEM_BUDGET", 2 * 2 * 2 * 3 * 5 * 128 * 4)
        S_TAB, PROBE, R, W = 300, 16, 7, 24
        rows_, _, group_ = poa._layout(S_TAB, PROBE, R, True)
        assert group_ == 1 and pallas_chunk.chunk_size(R, group_) == 2
        d = make_oahashmap(S_TAB, probe=PROBE)
        rng = np.random.default_rng(4)
        opc = jnp.asarray(rng.choice([1, 2], size=W), jnp.int32)
        args = jnp.zeros((W, 3), jnp.int32).at[:, 0].set(
            jnp.asarray(rng.integers(0, 64, W), jnp.int32)
        ).at[:, 1].set(jnp.asarray(rng.integers(1, 99, W), jnp.int32))
        ref = d.init_state()
        rresp = []
        for i in range(W):
            ref, r = apply_write(d, ref, opc[i], args[i])
            rresp.append(int(r))
        replay = make_oahashmap_replay(S_TAB, PROBE, R, W,
                                       interpret=True)
        st = pallas_oahashmap_state(S_TAB, R)
        k, v, f, resps = replay(opc, args, st["keys"], st["vals"],
                                st["flag"])
        assert k.shape[0] == R  # chunks concatenated back
        assert [int(x) for x in resps] == rresp
        view = oahashmap_model_view(
            {"keys": k, "vals": v, "flag": f}, S_TAB
        )
        for key in ("keys", "vals", "flag"):
            for r in range(R):
                np.testing.assert_array_equal(
                    np.asarray(view[key][r]), np.asarray(ref[key]), key
                )


@pytest.mark.skipif(
    not os.environ.get("NR_TPU_SMOKE"),
    reason="hardware smoke (set NR_TPU_SMOKE=1 on a real TPU)",
)
class TestHardwareSmoke:
    def test_oa_kernel_on_device(self):
        import subprocess
        import sys

        code = """
import numpy as np, jax, jax.numpy as jnp
assert jax.devices()[0].platform != "cpu", jax.devices()
from node_replication_tpu.models import make_oahashmap
from node_replication_tpu.ops.encoding import apply_write
from node_replication_tpu.ops.pallas_oahashmap import (
    make_oahashmap_replay, pallas_oahashmap_state, oahashmap_model_view)
S_TAB, PROBE, W, R = 4096, 16, 256, 4
d = make_oahashmap(S_TAB, probe=PROBE)
rng = np.random.default_rng(0)
opc = jnp.asarray(rng.choice([1, 2], size=W, p=[0.7, 0.3]), jnp.int32)
args = jnp.asarray(np.stack([rng.integers(-500, 500, W),
    rng.integers(1, 999, W), np.zeros(W)], axis=1), jnp.int32)
st0 = d.init_state()
step = jax.jit(lambda s, o, a: apply_write(d, s, o, a))
ref, rresp = st0, []
for i in range(W):
    ref, r = step(ref, opc[i], args[i])
    rresp.append(int(r))
replay = jax.jit(make_oahashmap_replay(S_TAB, PROBE, R, W))
st = pallas_oahashmap_state(S_TAB, R, st0)
keys, vals, flag, resps = replay(opc, args, st["keys"], st["vals"],
                                 st["flag"])
assert [int(x) for x in np.asarray(resps)] == rresp
view = oahashmap_model_view({"keys": keys, "vals": vals, "flag": flag},
                            S_TAB)
for k in ("keys", "vals", "flag"):
    for r in range(R):
        np.testing.assert_array_equal(
            np.asarray(view[k][r]), np.asarray(ref[k]), k)
print("oahashmap-pallas-on-tpu OK", jax.devices()[0].device_kind)
"""
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=560, cwd="/root/repo",
        )
        assert "oahashmap-pallas-on-tpu OK" in out.stdout, (
            out.stdout + out.stderr
        )
