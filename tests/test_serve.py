"""Serve layer (ISSUE 3): batch-submit entry points, admission
control, deadlines, drain/close, retry, elasticity under load, and
the serve report section.

The elasticity test is the satellite's sequence-numbered
linearizability check: 8 client OS threads drive ~10k fetch-and-set
ops through the frontend while `grow()` adds a replica mid-flight;
every response must equal the register's previous value, so a lost,
duplicated, or reordered execution is directly client-observable.
"""

import threading
import time

import pytest

from node_replication_tpu import NodeReplicated
from node_replication_tpu.core.cnr import MultiLogReplicated
from node_replication_tpu.core.replica import LogTooSmallError
from node_replication_tpu.models import (
    HM_GET,
    HM_PUT,
    SR_GET,
    SR_SET,
    make_hashmap,
    make_seqreg,
)
from node_replication_tpu.serve import (
    DeadlineExceeded,
    FrontendClosed,
    Overloaded,
    RetryPolicy,
    ServeConfig,
    ServeFrontend,
    call_with_retry,
)
from node_replication_tpu.serve.future import ServeFuture


def small_nr(dispatch=None, **kw):
    kw.setdefault("n_replicas", 2)
    kw.setdefault("log_entries", 512)
    kw.setdefault("gc_slack", 32)
    kw.setdefault("exec_window", 64)
    return NodeReplicated(dispatch or make_hashmap(64), **kw)


def fast_cfg(**kw):
    kw.setdefault("batch_linger_s", 0.0)
    return ServeConfig(**kw)


class TestExecuteMutBatch:
    def test_responses_in_submission_order(self):
        # seqreg's fetch-and-set response is order-sensitive: resps of
        # sequential writes to one slot must be 0, 1, 2, ...
        nr = small_nr(make_seqreg(4))
        resps = nr.execute_mut_batch(
            [(SR_SET, 0, i + 1) for i in range(100)], rid=0
        )
        assert resps == list(range(100))

    def test_empty_batch(self):
        nr = small_nr()
        assert nr.execute_mut_batch([], rid=0) == []

    def test_oversized_batch_raises(self):
        nr = small_nr(log_entries=128, gc_slack=16)
        with pytest.raises(LogTooSmallError):
            nr.execute_mut_batch(
                [(HM_PUT, 0, 0)] * 200, rid=0
            )

    def test_bad_rid_raises(self):
        nr = small_nr()
        with pytest.raises(ValueError):
            nr.execute_mut_batch([(HM_PUT, 0, 0)], rid=9)

    def test_ring_wrap(self):
        # three 60-op batches through a 128-slot ring: positions wrap,
        # the global per-slot sequence must stay exact
        nr = small_nr(make_seqreg(2), log_entries=128, gc_slack=16)
        expect = 0
        for _ in range(3):
            resps = nr.execute_mut_batch(
                [(SR_SET, 0, expect + j + 1) for j in range(60)],
                rid=0,
            )
            assert resps == [expect + j for j in range(60)]
            expect += 60
        nr.sync()
        assert nr.replicas_equal()

    def test_does_not_drain_staged_thread_contexts(self):
        # a batch appends EXACTLY the given ops; enqueue_mut backlogs
        # stay staged until their own combine
        nr = small_nr()
        tok = nr.register(0)
        nr.enqueue_mut((HM_PUT, 1, 5), tok)
        nr.execute_mut_batch([(HM_PUT, 2, 7)], rid=0)
        assert nr.responses(tok) == []
        nr.flush(0)
        assert nr.responses(tok) == [0]
        reader = nr.register(1)
        assert nr.execute((HM_GET, 1), reader) == 5
        assert nr.execute((HM_GET, 2), reader) == 7

    def test_cnr_batch_submission_order_across_logs(self):
        # slots route to different logs; responses must come back in
        # SUBMISSION order, not per-log completion order
        ml = MultiLogReplicated(
            make_seqreg(4), lambda opc, args: args[0], nlogs=2,
            n_replicas=2, log_entries=128, gc_slack=8, exec_window=16,
        )
        ops, expect = [], []
        counts = [0, 0, 0, 0]
        for i in range(40):
            slot = i % 4
            ops.append((SR_SET, slot, counts[slot] + 1))
            expect.append(counts[slot])
            counts[slot] += 1
        assert ml.execute_mut_batch(ops, rid=0) == expect
        ml.sync()
        assert ml.replicas_equal()

    def test_cnr_empty_and_bad_rid(self):
        ml = MultiLogReplicated(
            make_seqreg(4), lambda opc, args: args[0], nlogs=2,
            n_replicas=1, log_entries=128, gc_slack=8, exec_window=16,
        )
        assert ml.execute_mut_batch([], rid=0) == []
        with pytest.raises(ValueError):
            ml.execute_mut_batch([(SR_SET, 0, 1)], rid=3)


class TestFailedBatchHygiene:
    def test_nr_failed_batch_does_not_poison_next(self, monkeypatch):
        # a replay failure AFTER the append must not leave stale sink
        # state: the next batch's responses are its own, exactly
        nr = small_nr(make_seqreg(2))
        orig = NodeReplicated._exec_round
        state = {"fail": True}

        def flaky(self_nr):
            if state["fail"]:
                state["fail"] = False
                raise RuntimeError("injected replay failure")
            return orig(self_nr)

        monkeypatch.setattr(NodeReplicated, "_exec_round", flaky)
        with pytest.raises(RuntimeError):
            nr.execute_mut_batch(
                [(SR_SET, 0, i + 1) for i in range(5)], rid=0
            )
        # the failed batch's ops ARE in the log and replay; only their
        # responses were lost. The next batch sees clean deliveries.
        resps = nr.execute_mut_batch(
            [(SR_SET, 0, i + 6) for i in range(5)], rid=0
        )
        assert resps == [5, 6, 7, 8, 9]

    def test_cnr_failed_batch_does_not_wedge_replica(self, monkeypatch):
        ml = MultiLogReplicated(
            make_seqreg(4), lambda opc, args: args[0], nlogs=2,
            n_replicas=1, log_entries=128, gc_slack=8, exec_window=16,
        )
        orig = MultiLogReplicated._exec_round
        state = {"fail": True}

        def flaky(self_ml, log_idx):
            if state["fail"]:
                state["fail"] = False
                raise RuntimeError("injected replay failure")
            return orig(self_ml, log_idx)

        monkeypatch.setattr(MultiLogReplicated, "_exec_round", flaky)
        with pytest.raises(RuntimeError):
            ml.execute_mut_batch(
                [(SR_SET, 0, 1), (SR_SET, 1, 1)], rid=0
            )
        resps = ml.execute_mut_batch(
            [(SR_SET, 0, 2), (SR_SET, 1, 2)], rid=0
        )
        # the failure hit during log 0's replay: slot 0's write was
        # already appended (it replays; only its response was lost),
        # while log 1's sub-batch was never appended — sub-batches
        # are per-log combiner passes, not a cross-log transaction.
        # Either way the sink is clean and the next batch's responses
        # are exactly its own.
        assert resps == [1, 0]

    def test_worker_guard_rejects_whole_batch(self):
        # an exception OUTSIDE the execute try-block (here: a metrics
        # handle blowing up in the deadline sweep) must reject the
        # batch's futures instead of stranding their callers
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, fast_cfg(), auto_start=False)

        class BoomOnce:
            armed = True

            def inc(self, n=1):
                if self.armed:
                    self.armed = False
                    raise RuntimeError("metrics boom")

        fe._m_miss = BoomOnce()
        expired = fe.submit((SR_SET, 0, 1), deadline_s=0.001)
        live = fe.submit((SR_SET, 0, 2))
        time.sleep(0.05)
        fe.start()
        with pytest.raises(DeadlineExceeded):
            expired.result(10.0)  # resolved before the boom: kept
        with pytest.raises(RuntimeError):
            live.result(10.0)  # rejected by the worker's guard
        # the worker survived: the frontend still serves
        assert fe.call((SR_SET, 1, 1), timeout=10.0) == 0
        fe.close()


class TestServeFuture:
    def test_resolve_and_done(self):
        f = ServeFuture(rid=0)
        assert not f.done()
        assert f._resolve(42)
        assert f.done() and f.result() == 42
        assert f.exception() is None
        assert f.latency_s is not None and f.latency_s >= 0

    def test_single_resolution_wins(self):
        f = ServeFuture(rid=0)
        assert f._resolve(1)
        assert not f._reject(RuntimeError("late"))
        assert f.result() == 1

    def test_reject_raises_typed(self):
        f = ServeFuture(rid=3)
        f._reject(Overloaded(3, 8))
        with pytest.raises(Overloaded):
            f.result()
        assert isinstance(f.exception(), Overloaded)

    def test_result_timeout(self):
        f = ServeFuture(rid=0)
        with pytest.raises(TimeoutError):
            f.result(timeout=0.01)

    def test_callbacks_before_and_after(self):
        f = ServeFuture(rid=0)
        seen = []
        f.add_done_callback(lambda fut: seen.append(("pre", fut.result())))
        f._resolve(5)
        f.add_done_callback(lambda fut: seen.append(("post", fut.result())))
        assert seen == [("pre", 5), ("post", 5)]

    def test_callback_exception_swallowed(self):
        f = ServeFuture(rid=0)

        def bad(fut):
            raise RuntimeError("handler bug")

        f.add_done_callback(bad)
        assert f._resolve(1)  # must not raise
        assert f.result() == 1


class TestAdmissionControl:
    def test_overload_typed_and_counted(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, fast_cfg(queue_depth=4),
                           auto_start=False)
        futs = [fe.submit((SR_SET, 0, i + 1)) for i in range(4)]
        with pytest.raises(Overloaded) as ei:
            fe.submit((SR_SET, 0, 99))
        assert ei.value.rid == 0 and ei.value.depth == 4
        st = fe.stats()
        assert st["shed"] == 1 and st["accepted"] == 4
        fe.start()
        assert [f.result(10.0) for f in futs] == [0, 1, 2, 3]
        assert fe.stats()["completed"] == 4
        fe.close()

    def test_unknown_rid_raises(self):
        fe = ServeFrontend(small_nr(), fast_cfg())
        with pytest.raises(ValueError):
            fe.submit((HM_PUT, 0, 0), rid=7)
        with pytest.raises(ValueError):
            fe.read((HM_GET, 0), rid=7)
        fe.close()

    def test_backpressure_bounds_memory(self):
        # flood a paused depth-8 frontend with 1000 submissions: 992
        # shed as typed Overloaded, queue never exceeds its bound
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, fast_cfg(queue_depth=8),
                           auto_start=False)
        shed = 0
        for i in range(1000):
            try:
                fe.submit((SR_SET, 0, i + 1))
            except Overloaded:
                shed += 1
        st = fe.stats()
        assert shed == 992
        assert st["queued"] == 8 and st["shed"] == 992
        fe.start()
        fe.close()  # drains the 8 accepted


class TestDeadlines:
    def test_expired_request_dropped_before_append(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, fast_cfg(), auto_start=False)
        fut = fe.submit((SR_SET, 0, 77), deadline_s=0.005)
        time.sleep(0.05)
        fe.start()
        with pytest.raises(DeadlineExceeded):
            fut.result(10.0)
        fe.drain()
        # the op must have had NO effect: register still 0
        assert fe.read((SR_GET, 0)) == 0
        assert fe.stats()["deadline_missed"] == 1
        # frontend still serves after a miss
        assert fe.call((SR_SET, 0, 1), timeout=10.0) == 0
        fe.close()

    def test_default_deadline_from_config(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(
            nr, fast_cfg(default_deadline_s=0.005), auto_start=False
        )
        fut = fe.submit((SR_SET, 1, 5))
        time.sleep(0.05)
        fe.start()
        with pytest.raises(DeadlineExceeded):
            fut.result(10.0)
        fe.close()


class TestDrainClose:
    def test_close_drains_queued_ops(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, fast_cfg())
        futs = [fe.submit((SR_SET, 0, i + 1), rid=0)
                for i in range(50)]
        fe.close()  # drain=True: flush everything first
        assert [f.result(0.0) for f in futs] == list(range(50))
        assert fe.stats()["completed"] == 50

    def test_close_without_drain_rejects_backlog(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, fast_cfg(), auto_start=False)
        futs = [fe.submit((SR_SET, 0, i + 1)) for i in range(5)]
        fe.close(drain=False)
        for f in futs:
            with pytest.raises(FrontendClosed):
                f.result(1.0)
        # the ops never executed
        assert int(nr.log.tail) == 0

    def test_submit_after_close_raises(self):
        fe = ServeFrontend(small_nr(), fast_cfg())
        fe.close()
        with pytest.raises(FrontendClosed):
            fe.submit((HM_PUT, 0, 0))
        fe.close()  # idempotent

    def test_context_manager_drains(self):
        nr = small_nr(make_seqreg(2))
        with ServeFrontend(nr, fast_cfg()) as fe:
            futs = [fe.submit((SR_SET, 1, i + 1)) for i in range(20)]
        assert [f.result(0.0) for f in futs] == list(range(20))

    def test_drain_is_a_flush_not_a_shutdown(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(nr, fast_cfg())
        fe.submit((SR_SET, 0, 1))
        assert fe.drain(timeout=30.0)
        assert fe.stats()["queued"] == 0
        # admission still open
        assert fe.call((SR_SET, 0, 2), timeout=10.0) == 1
        fe.close()


class TestRetry:
    class FlakyFrontend:
        def __init__(self, fail_times):
            self.fail_times = fail_times
            self.calls = 0

        def call(self, op, rid=0, deadline_s=None, timeout=None):
            self.calls += 1
            if self.calls <= self.fail_times:
                raise Overloaded(rid, 8)
            return 42

    def test_retries_overloaded_then_succeeds(self):
        fe = self.FlakyFrontend(fail_times=2)
        sheds = []
        policy = RetryPolicy(max_attempts=5, base_backoff_s=0.0001,
                             max_backoff_s=0.001)
        out = call_with_retry(fe, (HM_PUT, 0, 0), policy=policy,
                              on_shed=lambda a, d: sheds.append(a))
        assert out == 42 and fe.calls == 3
        assert sheds == [0, 1]

    def test_policy_exhaustion_reraises(self):
        fe = self.FlakyFrontend(fail_times=99)
        policy = RetryPolicy(max_attempts=3, base_backoff_s=0.0001,
                             max_backoff_s=0.001)
        sheds = []
        with pytest.raises(Overloaded):
            call_with_retry(fe, (HM_PUT, 0, 0), policy=policy,
                            on_shed=lambda a, d: sheds.append(a))
        assert fe.calls == 3
        # the final exhausted rejection is counted too
        assert sheds == [0, 1, 2]

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)

    def test_backoff_caps(self):
        import random

        policy = RetryPolicy(base_backoff_s=0.01, max_backoff_s=0.04)
        rng = random.Random(7)
        for attempt in range(10):
            assert 0.0 <= policy.backoff_s(attempt, rng) <= 0.04

    # -------------------------------------------- total deadline budget

    def test_total_deadline_budget_bounds_attempts(self):
        # ISSUE 8 satellite regression: the budget is enforced ACROSS
        # attempts — with always-Overloaded service and backoffs far
        # larger than the budget, the call gives up long before the
        # attempt cap, and the whole call (backoffs included) never
        # outlives the budget. SimClock makes the elapsed time exact.
        from node_replication_tpu.utils.clock import SimClock, installed

        fe = self.FlakyFrontend(fail_times=99)
        policy = RetryPolicy(max_attempts=50, base_backoff_s=0.5,
                             max_backoff_s=2.0, total_deadline_s=3.0)
        with installed(SimClock()) as clock:
            with pytest.raises(Overloaded):
                call_with_retry(fe, (HM_PUT, 0, 0), policy=policy)
            # backoff sleeps are capped by the remaining budget, so
            # virtual elapsed time never exceeds it
            assert clock.now() <= 3.0 + 1e-9
        assert fe.calls < 50

    def test_no_backoff_outlives_the_budget(self):
        # a drawn backoff larger than the remaining budget re-raises
        # instead of sleeping (so the slept delays observed by on_shed
        # always fit inside the budget, and total virtual elapsed time
        # never exceeds it)
        from node_replication_tpu.utils.clock import SimClock, installed

        fe = self.FlakyFrontend(fail_times=99)
        delays = []
        policy = RetryPolicy(max_attempts=50, base_backoff_s=1.0,
                             max_backoff_s=10.0, total_deadline_s=2.0)
        with installed(SimClock()) as clock:
            with pytest.raises(Overloaded):
                call_with_retry(
                    fe, (HM_PUT, 0, 0), policy=policy,
                    on_shed=lambda a, d: delays.append(d),
                )
            now = clock.now()
        assert delays, "on_shed observed no attempts"
        assert all(d <= 2.0 for d in delays)
        assert now <= 2.0 + 1e-9

    def test_budget_exhausted_before_sleep_reraises(self):
        # a retry whose backoff would eat the whole remaining budget
        # re-raises instead of sleeping into a guaranteed timeout
        from node_replication_tpu.utils.clock import SimClock, installed

        fe = self.FlakyFrontend(fail_times=99)
        policy = RetryPolicy(max_attempts=5, base_backoff_s=1e9,
                             max_backoff_s=1e9, total_deadline_s=0.5)
        with installed(SimClock()) as clock:
            with pytest.raises(Overloaded):
                call_with_retry(fe, (HM_PUT, 0, 0), policy=policy)
            assert clock.now() == 0.0  # gave up without sleeping
        assert fe.calls >= 1

    def test_no_budget_keeps_legacy_behavior(self):
        fe = self.FlakyFrontend(fail_times=2)
        policy = RetryPolicy(max_attempts=5, base_backoff_s=0.0001,
                             max_backoff_s=0.001)
        assert policy.total_deadline_s is None
        assert call_with_retry(fe, (HM_PUT, 0, 0),
                               policy=policy) == 42

    def test_budget_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(total_deadline_s=0.0)
        with pytest.raises(ValueError):
            RetryPolicy(total_deadline_s=-1.0)


class TestReadPath:
    def test_read_your_writes_and_no_queue_traffic(self):
        nr = small_nr()
        fe = ServeFrontend(nr, fast_cfg())
        assert fe.call((HM_PUT, 3, 30), rid=0, timeout=10.0) == 0
        before = fe.stats()["accepted"]
        # reads on BOTH replicas observe the completed write (ctail
        # gate) and never touch the admission queues
        assert fe.read((HM_GET, 3), rid=0) == 30
        assert fe.read((HM_GET, 3), rid=1) == 30
        assert fe.stats()["accepted"] == before
        fe.close()

    def test_frontend_over_cnr(self):
        ml = MultiLogReplicated(
            make_seqreg(4), lambda opc, args: args[0], nlogs=2,
            n_replicas=2, log_entries=128, gc_slack=8, exec_window=16,
        )
        with ServeFrontend(ml, fast_cfg()) as fe:
            futs = [fe.submit((SR_SET, i % 4, i // 4 + 1),
                              rid=i % 2) for i in range(16)]
            for i, f in enumerate(futs):
                assert f.result(10.0) == i // 4
            assert fe.read((SR_GET, 2), rid=1) == 4


class TestConfigValidation:
    def test_bad_config_raises(self):
        with pytest.raises(ValueError):
            ServeConfig(queue_depth=0)
        with pytest.raises(ValueError):
            ServeConfig(batch_max_ops=0)
        with pytest.raises(ValueError):
            ServeConfig(batch_linger_s=-1.0)

    def test_frontend_requires_batch_entry_point(self):
        with pytest.raises(TypeError):
            ServeFrontend(object())


class TestElasticityUnderLoad:
    """grow_fleet while serve traffic is in flight: the ~10k-op,
    8-thread sequence-numbered linearizability check (ISSUE 3
    satellite). Client c owns register c and writes 1..N in order;
    every fetch-and-set response must equal the previous value, so a
    lost op shows as a gap, a duplicate as a repeat, a reorder as a
    mismatch — no response stream can hide any of them."""

    CLIENTS = 8
    PER_CLIENT = 1250  # 8 x 1250 = 10k ops

    def test_grow_mid_traffic_loses_nothing(self):
        from collections import deque

        nr = small_nr(
            make_seqreg(self.CLIENTS), n_replicas=2,
            log_entries=4096, gc_slack=256, exec_window=256,
        )
        # depth 512 >= clients x window: this run exercises ordering
        # under pipelining, not shedding (TestAdmissionControl does)
        fe = ServeFrontend(
            nr, fast_cfg(queue_depth=512, batch_max_ops=64)
        )
        errors: list = []
        grown = threading.Event()
        WINDOW = 32  # outstanding futures per client (pipelined)

        def client(c: int) -> None:
            rid = c % 2
            outstanding: deque = deque()

            def harvest(down_to: int) -> None:
                while len(outstanding) > down_to:
                    i, fut = outstanding.popleft()
                    resp = fut.result(timeout=120.0)
                    if resp != i:
                        errors.append((c, i, resp))
                        raise AssertionError("sequence broken")

            try:
                for i in range(self.PER_CLIENT):
                    outstanding.append(
                        (i, fe.submit((SR_SET, c, i + 1), rid=rid))
                    )
                    harvest(WINDOW - 1)
                    if c == 0 and i == self.PER_CLIENT // 2:
                        fe.grow(1)  # mid-traffic elasticity
                        grown.set()
                harvest(0)
            except AssertionError:
                pass
            except BaseException as e:  # pragma: no cover
                errors.append((c, type(e).__name__, str(e)))

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(self.CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not errors, errors[:5]
        assert grown.is_set()
        assert nr.n_replicas == 3
        # the grown replica serves: sequences continue seamlessly on it
        for c in range(self.CLIENTS):
            resp = fe.call((SR_SET, c, self.PER_CLIENT + 2), rid=2,
                           timeout=60.0)
            assert resp == self.PER_CLIENT, (c, resp)
        st = fe.stats()
        assert st["completed"] == st["accepted"]
        assert st["deadline_missed"] == 0
        fe.close()
        nr.sync()
        assert nr.replicas_equal()
        reader = nr.register(2)
        for c in range(self.CLIENTS):
            assert nr.execute((SR_GET, c), reader) == \
                self.PER_CLIENT + 2


class TestMeasureServe:
    def test_closed_loop_measurement(self):
        from node_replication_tpu.harness.mkbench import measure_serve

        nr = small_nr(make_seqreg(2))
        errors_expected = []

        def check(c, i, resp):
            return None if resp == i else f"{c}/{i}: {resp}"

        with ServeFrontend(nr, fast_cfg()) as fe:
            res = measure_serve(
                fe, lambda c, i: (SR_SET, c, i + 1), 40, 2,
                mode="closed", check=check, name="t",
            )
        assert res.completed == 40 and res.accepted == 40
        assert res.attempts == 40
        assert res.errors == errors_expected
        assert res.transport_errors == []
        assert len(res.latencies_s) == 40
        assert res.percentile_ms(99) >= res.percentile_ms(50) >= 0
        assert res.throughput > 0

    def test_open_loop_requires_rate(self):
        from node_replication_tpu.harness.mkbench import measure_serve

        with pytest.raises(ValueError):
            measure_serve(None, None, 1, 1, mode="open")
        with pytest.raises(ValueError):
            measure_serve(None, None, 1, 1, mode="bogus")


class TestServeReportSection:
    def test_serve_section_from_events(self):
        from node_replication_tpu.obs.report import analyze, render

        events = [
            {"event": "serve-batch", "mono": 100.0 + 0.1 * i,
             "rid": 0, "n": 4, "queue_depth": i, "duration_s": 0.002}
            for i in range(5)
        ] + [
            {"event": "serve-batch", "mono": 101.5, "rid": 1, "n": 9,
             "queue_depth": 2, "duration_s": 0.004},
            {"event": "serve-shed", "mono": 101.6, "rid": 0,
             "depth": 8},
            {"event": "serve-deadline-miss", "mono": 101.7, "rid": 0,
             "n": 3},
        ]
        rep = analyze(events)
        s = rep["serve"]
        assert s["batches"] == 6 and s["ops"] == 29
        assert s["shed"] == 1 and s["deadline_miss"] == 3
        assert s["max_batch"] == 9
        assert s["batch_size_hist"] == {4: 5, 16: 1}
        # queue-depth timeline keeps the per-second MAX
        assert s["queue_depth_timeline"][0] == 4
        assert s["queue_depth_timeline"][1] == 2
        import io

        out = io.StringIO()
        render(rep, out=out)
        text = out.getvalue()
        assert "== serve ==" in text
        assert "shed (Overloaded): 1" in text

    def test_no_serve_events_no_section(self):
        from node_replication_tpu.obs.report import analyze, render

        rep = analyze([{"event": "append", "mono": 1.0, "n": 2}])
        assert rep["serve"] is None
        import io

        out = io.StringIO()
        render(rep, out=out)
        assert "== serve ==" not in out.getvalue()


class TestServeMetricsAndTrace:
    def test_counters_and_trace_events(self):
        from node_replication_tpu.obs.metrics import get_registry
        from node_replication_tpu.utils.trace import get_tracer

        reg = get_registry()
        was = reg.enabled
        reg.enable()
        tracer = get_tracer()
        was_tracing = tracer.enabled
        tracer.enable(None)  # memory-buffer mode
        try:
            base_sub = reg.counter("serve.submitted").value
            base_shed = reg.counter("serve.shed").value
            nr = small_nr(make_seqreg(2))
            fe = ServeFrontend(nr, fast_cfg(queue_depth=2),
                               auto_start=False)
            fe.submit((SR_SET, 0, 1))
            fe.submit((SR_SET, 0, 2))
            with pytest.raises(Overloaded):
                fe.submit((SR_SET, 0, 3))
            fe.start()
            fe.drain()
            fe.close()
            assert reg.counter("serve.submitted").value - base_sub == 2
            assert reg.counter("serve.shed").value - base_shed == 1
            names = [e.get("event") for e in tracer.events()]
            assert "serve-shed" in names
            assert "serve-batch" in names
            assert "serve-close" in names
        finally:
            if not was:
                reg.disable()
            if not was_tracing:
                tracer.disable()


class TestSplitRoundProtocol:
    """begin_mut_batch / finish_mut_batch (ISSUE 14): the wrapper half
    of pipelined serving."""

    def test_begin_finish_responses_in_order(self):
        nr = small_nr(make_seqreg(4))
        pending = nr.begin_mut_batch(
            [(SR_SET, 0, i + 1) for i in range(20)], rid=0
        )
        assert nr.finish_mut_batch(pending) == list(range(20))
        nr.sync()
        assert nr.replicas_equal()

    def test_at_most_one_round_in_flight(self):
        nr = small_nr(make_seqreg(2))
        pending = nr.begin_mut_batch([(SR_SET, 0, 1)], rid=0)
        with pytest.raises(RuntimeError):
            nr.begin_mut_batch([(SR_SET, 0, 2)], rid=0)
        assert nr.finish_mut_batch(pending) == [0]
        # finished: the slot is free again
        p2 = nr.begin_mut_batch([(SR_SET, 0, 2)], rid=0)
        assert nr.finish_mut_batch(p2) == [1]

    def test_finish_twice_raises(self):
        nr = small_nr(make_seqreg(2))
        pending = nr.begin_mut_batch([(SR_SET, 0, 1)], rid=0)
        nr.finish_mut_batch(pending)
        with pytest.raises(RuntimeError):
            nr.finish_mut_batch(pending)

    def test_empty_begin_finish(self):
        nr = small_nr()
        pending = nr.begin_mut_batch([], rid=0)
        assert nr.finish_mut_batch(pending) == []

    def test_failed_finish_hygiene(self, monkeypatch):
        # a replay failure in finish must not poison the next batch
        # (the execute_mut_batch hygiene regression, split shape)
        nr = small_nr(make_seqreg(2))
        orig = NodeReplicated._exec_round
        state = {"fail": True}

        def flaky(self_nr):
            if state["fail"]:
                state["fail"] = False
                raise RuntimeError("injected replay failure")
            return orig(self_nr)

        monkeypatch.setattr(NodeReplicated, "_exec_round", flaky)
        pending = nr.begin_mut_batch(
            [(SR_SET, 0, i + 1) for i in range(5)], rid=0
        )
        with pytest.raises(RuntimeError):
            nr.finish_mut_batch(pending)
        # the appended ops replay; the next batch's responses are
        # exactly its own
        resps = nr.execute_mut_batch(
            [(SR_SET, 0, i + 6) for i in range(5)], rid=0
        )
        assert resps == [5, 6, 7, 8, 9]

    def test_abort_releases_the_slot(self):
        nr = small_nr(make_seqreg(2))
        pending = nr.begin_mut_batch([(SR_SET, 0, 1)], rid=0)
        nr.abort_mut_batch(pending)
        nr.abort_mut_batch(pending)  # idempotent
        # the aborted round's op IS in the log and replays; only its
        # response was dropped — the next round sees its effect
        resps = nr.execute_mut_batch([(SR_SET, 0, 2)], rid=0)
        assert resps == [1]

    def test_cnr_begin_finish_scatter(self):
        ml = MultiLogReplicated(
            make_seqreg(4), lambda opc, args: args[0], nlogs=2,
            n_replicas=2, log_entries=128, gc_slack=8, exec_window=16,
        )
        ops, expect = [], []
        counts = [0, 0, 0, 0]
        for i in range(16):
            slot = i % 4
            ops.append((SR_SET, slot, counts[slot] + 1))
            expect.append(counts[slot])
            counts[slot] += 1
        pending = ml.begin_mut_batch(ops, rid=0)
        with pytest.raises(RuntimeError):
            ml.begin_mut_batch([(SR_SET, 0, 99)], rid=0)
        assert ml.finish_mut_batch(pending) == expect
        ml.sync()
        assert ml.replicas_equal()


class TestPipelinedServing:
    """ServeConfig.pipeline_depth=1 (ISSUE 14): the assembly /
    completion split, overlap semantics, and its failure discipline."""

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            ServeConfig(pipeline_depth=2)
        with pytest.raises(ValueError):
            ServeConfig(pipeline_depth=-1)

    def test_pipelined_sequence_exact(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(
            nr, fast_cfg(pipeline_depth=1, batch_max_ops=8)
        )
        futs = [fe.submit((SR_SET, 0, i + 1), rid=0)
                for i in range(200)]
        assert [f.result(60.0) for f in futs] == list(range(200))
        st = fe.stats()
        assert st["completed"] == 200 and st["in_service"] == 0
        fe.close()
        nr.sync()
        assert nr.replicas_equal()

    def test_depth0_and_depth1_logs_bit_identical(self):
        # the acceptance pin: same ops through both worker shapes ->
        # same responses AND same log contents (ring_slice)
        from node_replication_tpu.core.log import ring_slice

        outs, slices = [], []
        for depth in (0, 1):
            nr = small_nr(make_seqreg(4))
            fe = ServeFrontend(
                nr, fast_cfg(pipeline_depth=depth, batch_max_ops=4)
            )
            futs = [fe.submit((SR_SET, i % 4, i + 1), rid=0)
                    for i in range(64)]
            outs.append([f.result(60.0) for f in futs])
            fe.close()
            nr.sync()
            slices.append(ring_slice(nr.spec, nr.log, 0,
                                     int(nr.log.tail)))
        assert outs[0] == outs[1]
        ops0, ops1 = slices
        assert (ops0[0] == ops1[0]).all() and (ops0[1] == ops1[1]).all()

    def test_close_drain_waits_for_inflight_round(self):
        nr = small_nr(make_seqreg(2))
        fe = ServeFrontend(
            nr, fast_cfg(pipeline_depth=1, batch_max_ops=4)
        )
        futs = [fe.submit((SR_SET, 1, i + 1), rid=1)
                for i in range(40)]
        fe.close()  # drain=True must flush assembled AND in-flight
        assert [f.result(0.0) for f in futs] == list(range(40))

    def test_worker_death_with_round_in_flight(self):
        # the two-stage failover pin: the in-flight round's futures
        # get post-append ReplicaFailed (maybe_executed=True), the
        # not-yet-begun round's get pre-append retryable
        from node_replication_tpu.fault.inject import (
            FaultPlan,
            FaultSpec,
        )
        from node_replication_tpu.serve import ReplicaFailed

        nr = small_nr(make_seqreg(2), n_replicas=1)
        fe = ServeFrontend(
            nr, fast_cfg(pipeline_depth=1, batch_max_ops=2,
                         failover=True),
            auto_start=False,
        )
        futs = [fe.submit((SR_SET, 0, i + 1), rid=0)
                for i in range(4)]
        plan = FaultPlan([
            FaultSpec(site="serve-complete", action="raise")
        ])
        with plan.armed():
            fe.start()
            excs = [f.exception(30.0) for f in futs]
        assert all(isinstance(e, ReplicaFailed) for e in excs)
        # first batch (2 ops) was in flight: post-append
        assert [e.maybe_executed for e in excs[:2]] == [True, True]
        # the rest never reached begin: exactly-once retryable
        assert [e.maybe_executed for e in excs[2:]] == [False, False]
        fe.close()

    def test_pre_append_kill_retryable_in_assembly_stage(self):
        # serve-batch fires in the ASSEMBLY stage pre-append: a kill
        # there must stay exactly-once retryable (both-stages pin)
        from node_replication_tpu.fault.inject import (
            FaultPlan,
            FaultSpec,
        )
        from node_replication_tpu.serve import ReplicaFailed

        nr = small_nr(make_seqreg(2), n_replicas=1)
        fe = ServeFrontend(
            nr, fast_cfg(pipeline_depth=1, failover=True),
            auto_start=False,
        )
        fut = fe.submit((SR_SET, 0, 1), rid=0)
        plan = FaultPlan([
            FaultSpec(site="serve-batch", action="raise")
        ])
        with plan.armed():
            fe.start()
            exc = fut.exception(30.0)
        assert isinstance(exc, ReplicaFailed)
        assert exc.maybe_executed is False
        # the op provably never reached the log
        assert int(nr.log.tail) == 0
        fe.close()

    def test_deadline_late_success_counted_and_delivered(self):
        # a request that expires while its round is in flight still
        # resolves (first resolution wins, the op executed) but lands
        # in serve.deadline_late_success — SLO honesty
        from node_replication_tpu.fault.inject import (
            FaultPlan,
            FaultSpec,
        )
        from node_replication_tpu.obs.metrics import get_registry

        reg = get_registry()
        was = reg.enabled
        reg.enable()
        try:
            base = reg.counter("serve.deadline_late_success").value
            nr = small_nr(make_seqreg(2))
            fe = ServeFrontend(
                nr, fast_cfg(pipeline_depth=1), auto_start=False
            )
            fut = fe.submit((SR_SET, 0, 7), rid=0, deadline_s=0.05)
            # stall the completion stage past the deadline: the round
            # is begun (appended) when the stall fires
            plan = FaultPlan([
                FaultSpec(site="serve-complete", action="stall",
                          stall_s=0.5)
            ])
            with plan.armed():
                fe.start()
                assert fut.result(30.0) == 0  # delivered, not dropped
            assert (reg.counter("serve.deadline_late_success").value
                    - base) == 1
            assert fe.stats()["deadline_missed"] == 0
            fe.close()
        finally:
            if not was:
                reg.disable()

    def test_grow_mid_traffic_pipelined(self):
        # elasticity under the two-stage worker: sequences stay exact
        # across a grow() while pipelined traffic is in flight
        nr = small_nr(
            make_seqreg(4), n_replicas=2,
            log_entries=4096, gc_slack=256, exec_window=256,
        )
        fe = ServeFrontend(
            nr, fast_cfg(queue_depth=256, batch_max_ops=16,
                         pipeline_depth=1)
        )
        errors = []

        def client(c):
            try:
                for i in range(200):
                    resp = fe.submit(
                        (SR_SET, c, i + 1), rid=c % 2
                    ).result(60.0)
                    if resp != i:
                        errors.append((c, i, resp))
                        return
                    if c == 0 and i == 100:
                        fe.grow(1)
            except Exception as e:  # pragma: no cover
                errors.append((c, type(e).__name__, str(e)))

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors[:3]
        assert nr.n_replicas == 3
        # the grown replica serves pipelined rounds too (client 0
        # wrote 1..200, so the fetch-and-set returns 200)
        assert fe.call((SR_SET, 0, 202), rid=2, timeout=30.0) == 200
        fe.close()
        nr.sync()
        assert nr.replicas_equal()

    def test_cnr_pipelined_frontend(self):
        ml = MultiLogReplicated(
            make_seqreg(4), lambda opc, args: args[0], nlogs=2,
            n_replicas=2, log_entries=128, gc_slack=8, exec_window=16,
        )
        with ServeFrontend(
            ml, fast_cfg(pipeline_depth=1, batch_max_ops=4)
        ) as fe:
            futs = [fe.submit((SR_SET, i % 4, i // 4 + 1),
                              rid=i % 2) for i in range(32)]
            for i, f in enumerate(futs):
                assert f.result(30.0) == i // 4
            assert fe.read((SR_GET, 2), rid=1) == 8

    def test_simclock_pipelined_handoff(self):
        # the two-stage handoff under virtual time: every wait in the
        # channel and queue routes through the injectable clock, so a
        # SimClock(auto_advance) run completes without real sleeps
        from node_replication_tpu.utils.clock import (
            SimClock,
            installed,
        )

        with installed(SimClock(auto_advance=True)):
            nr = small_nr(make_seqreg(2))
            fe = ServeFrontend(
                nr, fast_cfg(pipeline_depth=1, batch_max_ops=4)
            )
            futs = [fe.submit((SR_SET, 0, i + 1), rid=0)
                    for i in range(24)]
            assert [f.result(60.0) for f in futs] == list(range(24))
            fe.close()

    def test_serve_assemble_event_and_report_line(self):
        from node_replication_tpu.obs.report import analyze, render
        from node_replication_tpu.utils.trace import get_tracer

        tracer = get_tracer()
        was = tracer.enabled
        tracer.enable(None)  # memory ring
        try:
            nr = small_nr(make_seqreg(2))
            fe = ServeFrontend(
                nr, fast_cfg(pipeline_depth=1, batch_max_ops=8)
            )
            futs = [fe.submit((SR_SET, 0, i + 1), rid=0)
                    for i in range(40)]
            for f in futs:
                f.result(30.0)
            fe.close()
            events = tracer.events()
            assert any(e.get("event") == "serve-assemble"
                       for e in events)
            rep = analyze(events)
            pipe = rep["serve"]["pipeline"]
            assert pipe is not None
            assert pipe["assemble_events"] >= 1
            assert pipe["device_busy_s"] >= 0.0
            import io

            out = io.StringIO()
            render(rep, out=out)
            assert "pipeline overlap" in out.getvalue()
        finally:
            if not was:
                tracer.disable()


class TestPipelinedFailurePaths:
    """Review-hardening regressions: the pipelined failure paths that
    the first cut left untested."""

    def test_non_failover_finish_failure_keeps_serving(self):
        # a completion-stage failure WITHOUT failover must reject its
        # own round and keep the pipeline alive (the channel's busy
        # flag releases; a wedged channel would hang every later op)
        from node_replication_tpu.fault.inject import (
            FaultError,
            FaultPlan,
            FaultSpec,
        )

        nr = small_nr(make_seqreg(2), n_replicas=1)
        fe = ServeFrontend(
            nr, fast_cfg(pipeline_depth=1, batch_max_ops=4),
            auto_start=False,
        )
        doomed = fe.submit((SR_SET, 0, 1), rid=0)
        plan = FaultPlan([
            FaultSpec(site="serve-complete", action="raise")
        ])
        with plan.armed():
            fe.start()
            with pytest.raises(FaultError):
                doomed.result(30.0)
        # the frontend still serves — and the wrapper's in-flight slot
        # was released, so the next round begins cleanly. The doomed
        # op's append DID land (post-append failure), so the register
        # already moved to 1.
        assert fe.call((SR_SET, 0, 2), rid=0, timeout=30.0) == 1
        assert fe.call((SR_SET, 0, 3), rid=0, timeout=30.0) == 2
        fe.close()

    def test_failover_completion_kill_then_restart_serves(self):
        # the completion-stage kill fires BEFORE finish_mut_batch, so
        # the begun round must be aborted during failover — otherwise
        # restart_replica yields a replica whose first begin refuses
        # forever ("already has a round in flight")
        from node_replication_tpu.fault.inject import (
            FaultPlan,
            FaultSpec,
        )
        from node_replication_tpu.serve import ReplicaFailed

        nr = small_nr(make_seqreg(2), n_replicas=1)
        fe = ServeFrontend(
            nr, fast_cfg(pipeline_depth=1, batch_max_ops=4,
                         failover=True),
            auto_start=False,
        )
        doomed = fe.submit((SR_SET, 0, 1), rid=0)
        plan = FaultPlan([
            FaultSpec(site="serve-complete", action="raise")
        ])
        with plan.armed():
            fe.start()
            exc = doomed.exception(30.0)
        assert isinstance(exc, ReplicaFailed) and exc.maybe_executed
        # restart WITHOUT the lifecycle manager's fence/repair cycle
        # (the path that cannot rely on fence_replica's cleanup)
        fe.restart_replica(0)
        # the killed round's op was appended and replays: register is 1
        assert fe.call((SR_SET, 0, 2), rid=0, timeout=30.0) == 1
        fe.close()

    def test_cnr_serial_batch_refused_while_split_in_flight(self):
        ml = MultiLogReplicated(
            make_seqreg(4), lambda opc, args: args[0], nlogs=2,
            n_replicas=1, log_entries=128, gc_slack=8, exec_window=16,
        )
        pending = ml.begin_mut_batch(
            [(SR_SET, 0, 1), (SR_SET, 1, 1)], rid=0
        )
        with pytest.raises(RuntimeError):
            ml.execute_mut_batch([(SR_SET, 2, 1)], rid=0)
        assert ml.finish_mut_batch(pending) == [0, 0]
        # with the split round finished, serial batches run again
        assert ml.execute_mut_batch([(SR_SET, 2, 1)], rid=0) == [0]
