"""Combined window replay (`Dispatch.window_apply`) vs the generic scan.

The combined path replaces the W-long sequential replay scan with one
parallel reduction (sort + predecessor lookup + dense merge). These tests
pin BIT-identical behavior against folding `apply_write` in order — state,
write responses, and read responses — across adversarial windows: duplicate
keys, PUT/REMOVE interleavings, NOOP padding, unknown opcodes, ring wrap,
and multi-step drives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from node_replication_tpu import LogSpec, log_init, make_step
from node_replication_tpu.core.replica import replicate_state
from node_replication_tpu.models import (
    HM_GET,
    HM_PUT,
    HM_REMOVE,
    make_hashmap,
)
from node_replication_tpu.ops.encoding import apply_write


def fold_reference(d, state, opcodes, args):
    """Host-side ground truth: apply_write folded in window order."""
    resps = []
    for i in range(len(opcodes)):
        state, r = apply_write(d, state, opcodes[i], args[i])
        resps.append(int(r))
    return state, resps


class TestWindowApplySingle:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_sequential_fold(self, seed):
        K, W = 13, 64
        d = make_hashmap(K)
        rng = np.random.default_rng(seed)
        # adversarial mix: heavy key collisions, NOOPs, unknown opcode 7
        opcodes = jnp.asarray(
            rng.choice([0, HM_PUT, HM_REMOVE, 7], size=W,
                       p=[0.15, 0.45, 0.3, 0.1]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack(
                [rng.integers(0, K, W), rng.integers(1, 100, W),
                 np.zeros(W)], axis=1
            ),
            jnp.int32,
        )
        state0 = d.init_state()
        # start from a non-trivial state: some keys pre-present
        state0["present"] = state0["present"].at[::3].set(True)
        state0["values"] = state0["values"].at[::3].set(5)
        ref_state, ref_resps = fold_reference(d, state0, opcodes, args)
        got_state, got_resps = d.window_apply(state0, opcodes, args)
        np.testing.assert_array_equal(
            np.asarray(got_state["values"]), np.asarray(ref_state["values"])
        )
        np.testing.assert_array_equal(
            np.asarray(got_state["present"]),
            np.asarray(ref_state["present"]),
        )
        assert [int(x) for x in got_resps] == ref_resps

    def test_remove_answers_predecessor_not_initial(self):
        # REMOVE after an in-window PUT answers 1 even if the key started
        # absent; a second REMOVE answers 0
        K = 8
        d = make_hashmap(K)
        opcodes = jnp.asarray(
            [HM_PUT, HM_REMOVE, HM_REMOVE, HM_PUT], jnp.int32
        )
        args = jnp.asarray(
            [[3, 9, 0], [3, 0, 0], [3, 0, 0], [3, 11, 0]], jnp.int32
        )
        state, resps = d.window_apply(d.init_state(), opcodes, args)
        assert [int(x) for x in resps] == [0, 1, 0, 0]
        assert int(state["values"][3]) == 11
        assert bool(state["present"][3])

    def test_all_noop_window_is_identity(self):
        K = 4
        d = make_hashmap(K)
        state0 = d.init_state()
        state0["values"] = state0["values"].at[1].set(7)
        state0["present"] = state0["present"].at[1].set(True)
        state, resps = d.window_apply(
            state0, jnp.zeros((8,), jnp.int32), jnp.zeros((8, 3), jnp.int32)
        )
        np.testing.assert_array_equal(
            np.asarray(state["values"]), np.asarray(state0["values"])
        )
        assert not np.any(np.asarray(resps))


class TestSortedSetWindowApply:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_matches_sequential_fold(self, seed):
        from node_replication_tpu.models import make_sortedset

        K, W = 11, 48
        d = make_sortedset(K)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=W, p=[0.1, 0.45, 0.35, 0.1]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack([rng.integers(0, K, W), np.zeros(W), np.zeros(W)],
                     axis=1),
            jnp.int32,
        )
        state0 = d.init_state()
        state0["present"] = state0["present"].at[::2].set(True)
        ref_state, ref_resps = fold_reference(d, state0, opcodes, args)
        got_state, got_resps = d.window_apply(state0, opcodes, args)
        np.testing.assert_array_equal(
            np.asarray(got_state["present"]),
            np.asarray(ref_state["present"]),
        )
        assert [int(x) for x in got_resps] == ref_resps


class TestMemfsWindowApply:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_matches_sequential_fold(self, seed):
        # the hardest combined model: coupled per-file truncate and
        # per-cell write histories plus running-size responses
        from node_replication_tpu.models import make_memfs

        F, B, W = 4, 6, 96
        d = make_memfs(F, B)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 3, 9], size=W,
                       p=[0.08, 0.42, 0.18, 0.27, 0.05]),
            jnp.int32,
        )
        # include out-of-range fds/blocks to pin the clip/-1 semantics
        args = jnp.asarray(
            np.stack(
                [rng.integers(-1, F + 1, W), rng.integers(-1, B + 1, W),
                 rng.integers(1, 100, W)], axis=1
            ),
            jnp.int32,
        )
        state0 = d.init_state()
        state0["data"] = state0["data"].at[1, :3].set(
            jnp.asarray([11, 12, 13], jnp.int32)
        )
        state0["size"] = state0["size"].at[1].set(3)
        ref_state, ref_resps = fold_reference(d, state0, opcodes, args)
        got_state, got_resps = d.window_apply(state0, opcodes, args)
        np.testing.assert_array_equal(
            np.asarray(got_state["data"]), np.asarray(ref_state["data"])
        )
        np.testing.assert_array_equal(
            np.asarray(got_state["size"]), np.asarray(ref_state["size"])
        )
        assert [int(x) for x in got_resps] == ref_resps

    def test_truncate_then_write_then_logged_read(self):
        from node_replication_tpu.models import make_memfs

        d = make_memfs(2, 4)
        state0 = d.init_state()
        state0["data"] = state0["data"].at[0, 0].set(7)
        state0["size"] = state0["size"].at[0].set(1)
        ops = [
            (3, 0, 0, 0),   # read 7 (initial)
            (2, 0, 0, 0),   # truncate → old size 1
            (3, 0, 0, 0),   # read 0 (truncated)
            (1, 0, 2, 55),  # write block 2 → size 3
            (3, 0, 2, 0),   # read 55 (in-window write)
            (3, 0, 0, 0),   # read 0 (still truncated, no later write)
        ]
        opcodes = jnp.asarray([o[0] for o in ops], jnp.int32)
        args = jnp.asarray([list(o[1:]) for o in ops], jnp.int32)
        state, resps = d.window_apply(state0, opcodes, args)
        assert [int(x) for x in resps] == [7, 1, 0, 3, 55, 0]
        assert int(state["size"][0]) == 3
        assert int(state["data"][0, 0]) == 0
        assert int(state["data"][0, 2]) == 55


class TestMultilogCombined:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_partitioned_combined_matches_scan(self, seed):
        # per-log combined replay vs the per-log scan over the same
        # hash-routed stream: states, write resps, read resps, cursors
        from node_replication_tpu.harness.trait import MultiLogRunner
        from node_replication_tpu.models import (
            make_partitioned_sortedset,
            make_sortedset,
        )

        K, L, R, S, Bw = 32, 4, 3, 5, 6
        rng = np.random.default_rng(seed)
        wr_opc = rng.choice([0, 1, 2], size=(S, R, Bw)).astype(np.int32)
        wr_args = np.zeros((S, R, Bw, 3), np.int32)
        wr_args[..., 0] = rng.integers(0, K, (S, R, Bw))
        rd_opc = np.full((S, R, 2), 1, np.int32)
        rd_args = np.zeros((S, R, 2, 3), np.int32)
        rd_args[..., 0] = rng.integers(0, K, (S, R, 2))
        outs = {}
        for mode in (False, True):
            r = MultiLogRunner(
                make_sortedset(K), R, L, Bw, 2,
                partitioned=make_partitioned_sortedset(K, L),
                keyspace=K, combined=mode,
            )
            r.prepare(wr_opc, wr_args, rd_opc, rd_args)
            lasts = []
            for s in range(S):
                r.run_step(s)
                lasts.append(np.asarray(r._last))
            r.block()
            outs[mode] = (
                jax.tree.map(np.asarray, r.states),
                np.asarray(r.ml.ltails),
                lasts,
            )
        st_a, lt_a, rd_a = outs[False]
        st_b, lt_b, rd_b = outs[True]
        for x, y in zip(jax.tree.leaves(st_a), jax.tree.leaves(st_b)):
            np.testing.assert_array_equal(x, y)
        np.testing.assert_array_equal(lt_a, lt_b)
        for x, y in zip(rd_a, rd_b):
            np.testing.assert_array_equal(x, y)


class TestCombinedStep:
    @pytest.mark.parametrize("seed", [0, 3])
    def test_step_bit_identical_to_scan_step(self, seed):
        R, Bw, Br, K, STEPS = 4, 3, 2, 11, 6
        d = make_hashmap(K)
        # capacity small enough that the ring wraps during the drive
        spec = LogSpec(capacity=2 * R * Bw, n_replicas=R, arg_width=3,
                       gc_slack=R * Bw // 2)
        rng = np.random.default_rng(seed)
        s_comb = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=True)
        s_scan = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=False)
        log_c, st_c = log_init(spec), replicate_state(d.init_state(), R)
        log_s, st_s = log_init(spec), replicate_state(d.init_state(), R)
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, HM_PUT, HM_REMOVE], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                rng.integers(0, K, size=(R, Bw, 3)), jnp.int32
            )
            rd_opc = jnp.full((R, Br), HM_GET, jnp.int32)
            rd_args = jnp.asarray(
                rng.integers(0, K, size=(R, Br, 3)), jnp.int32
            )
            log_c, st_c, wr_c, rd_c = s_comb(
                log_c, st_c, wr_opc, wr_args, rd_opc, rd_args
            )
            log_s, st_s, wr_s, rd_s = s_scan(
                log_s, st_s, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_c), np.asarray(wr_s))
            np.testing.assert_array_equal(np.asarray(rd_c), np.asarray(rd_s))
        for leaf_c, leaf_s in zip(jax.tree.leaves(st_c), jax.tree.leaves(st_s)):
            np.testing.assert_array_equal(np.asarray(leaf_c), np.asarray(leaf_s))
        for name in ("head", "tail", "ctail"):
            assert int(getattr(log_c, name)) == int(getattr(log_s, name))
        np.testing.assert_array_equal(
            np.asarray(log_c.ltails), np.asarray(log_s.ltails)
        )

    def test_auto_selects_combined_when_available(self):
        d = make_hashmap(8)
        assert d.window_apply is not None
        spec = LogSpec(capacity=64, n_replicas=2, arg_width=3, gc_slack=8)
        # default (None) → combined; explicit False → scan; both compile
        for combined in (None, False):
            step = make_step(d, spec, 1, 1, jit=True, donate=False,
                             combined=combined)
            log, st = log_init(spec), replicate_state(d.init_state(), 2)
            log, st, wr, rd = step(
                log, st,
                jnp.full((2, 1), HM_PUT, jnp.int32),
                jnp.zeros((2, 1, 3), jnp.int32).at[..., 0].set(3)
                .at[..., 1].set(9),
                jnp.full((2, 1), HM_GET, jnp.int32),
                jnp.zeros((2, 1, 3), jnp.int32).at[..., 0].set(3),
            )
            assert int(rd[0, 0]) == 9

    def test_combined_requires_window_apply(self):
        from node_replication_tpu.models import make_stack

        d = make_stack(16)
        assert d.window_apply is None
        spec = LogSpec(capacity=64, n_replicas=1, arg_width=3, gc_slack=8)
        with pytest.raises(ValueError):
            make_step(d, spec, 1, 0, combined=True)
