"""Combined window replay (`Dispatch.window_apply`) vs the generic scan.

The combined path replaces the W-long sequential replay scan with one
parallel reduction (sort + predecessor lookup + dense merge). These tests
pin BIT-identical behavior against folding `apply_write` in order — state,
write responses, and read responses — across adversarial windows: duplicate
keys, PUT/REMOVE interleavings, NOOP padding, unknown opcodes, ring wrap,
and multi-step drives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from node_replication_tpu import LogSpec, log_init, make_step
from node_replication_tpu.core.replica import replicate_state
from node_replication_tpu.models import (
    HM_GET,
    HM_PUT,
    HM_REMOVE,
    make_hashmap,
)
from node_replication_tpu.ops.encoding import apply_write


def fold_reference(d, state, opcodes, args):
    """Host-side ground truth: apply_write folded in window order."""
    resps = []
    for i in range(len(opcodes)):
        state, r = apply_write(d, state, opcodes[i], args[i])
        resps.append(int(r))
    return state, resps


class TestWindowApplySingle:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_sequential_fold(self, seed):
        K, W = 13, 64
        d = make_hashmap(K)
        rng = np.random.default_rng(seed)
        # adversarial mix: heavy key collisions, NOOPs, unknown opcode 7
        opcodes = jnp.asarray(
            rng.choice([0, HM_PUT, HM_REMOVE, 7], size=W,
                       p=[0.15, 0.45, 0.3, 0.1]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack(
                [rng.integers(0, K, W), rng.integers(1, 100, W),
                 np.zeros(W)], axis=1
            ),
            jnp.int32,
        )
        state0 = d.init_state()
        # start from a non-trivial state: some keys pre-present
        state0["present"] = state0["present"].at[::3].set(True)
        state0["values"] = state0["values"].at[::3].set(5)
        ref_state, ref_resps = fold_reference(d, state0, opcodes, args)
        got_state, got_resps = d.window_apply(state0, opcodes, args)
        np.testing.assert_array_equal(
            np.asarray(got_state["values"]), np.asarray(ref_state["values"])
        )
        np.testing.assert_array_equal(
            np.asarray(got_state["present"]),
            np.asarray(ref_state["present"]),
        )
        assert [int(x) for x in got_resps] == ref_resps

    def test_remove_answers_predecessor_not_initial(self):
        # REMOVE after an in-window PUT answers 1 even if the key started
        # absent; a second REMOVE answers 0
        K = 8
        d = make_hashmap(K)
        opcodes = jnp.asarray(
            [HM_PUT, HM_REMOVE, HM_REMOVE, HM_PUT], jnp.int32
        )
        args = jnp.asarray(
            [[3, 9, 0], [3, 0, 0], [3, 0, 0], [3, 11, 0]], jnp.int32
        )
        state, resps = d.window_apply(d.init_state(), opcodes, args)
        assert [int(x) for x in resps] == [0, 1, 0, 0]
        assert int(state["values"][3]) == 11
        assert bool(state["present"][3])

    def test_all_noop_window_is_identity(self):
        K = 4
        d = make_hashmap(K)
        state0 = d.init_state()
        state0["values"] = state0["values"].at[1].set(7)
        state0["present"] = state0["present"].at[1].set(True)
        state, resps = d.window_apply(
            state0, jnp.zeros((8,), jnp.int32), jnp.zeros((8, 3), jnp.int32)
        )
        np.testing.assert_array_equal(
            np.asarray(state["values"]), np.asarray(state0["values"])
        )
        assert not np.any(np.asarray(resps))


class TestSortedSetWindowApply:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_matches_sequential_fold(self, seed):
        from node_replication_tpu.models import make_sortedset

        K, W = 11, 48
        d = make_sortedset(K)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=W, p=[0.1, 0.45, 0.35, 0.1]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack([rng.integers(0, K, W), np.zeros(W), np.zeros(W)],
                     axis=1),
            jnp.int32,
        )
        state0 = d.init_state()
        state0["present"] = state0["present"].at[::2].set(True)
        ref_state, ref_resps = fold_reference(d, state0, opcodes, args)
        got_state, got_resps = d.window_apply(state0, opcodes, args)
        np.testing.assert_array_equal(
            np.asarray(got_state["present"]),
            np.asarray(ref_state["present"]),
        )
        assert [int(x) for x in got_resps] == ref_resps


class TestMemfsWindowApply:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_matches_sequential_fold(self, seed):
        # the hardest combined model: coupled per-file truncate and
        # per-cell write histories plus running-size responses
        from node_replication_tpu.models import make_memfs

        F, B, W = 4, 6, 96
        d = make_memfs(F, B)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 3, 9], size=W,
                       p=[0.08, 0.42, 0.18, 0.27, 0.05]),
            jnp.int32,
        )
        # include out-of-range fds/blocks to pin the clip/-1 semantics
        args = jnp.asarray(
            np.stack(
                [rng.integers(-1, F + 1, W), rng.integers(-1, B + 1, W),
                 rng.integers(1, 100, W)], axis=1
            ),
            jnp.int32,
        )
        state0 = d.init_state()
        state0["data"] = state0["data"].at[1, :3].set(
            jnp.asarray([11, 12, 13], jnp.int32)
        )
        state0["size"] = state0["size"].at[1].set(3)
        ref_state, ref_resps = fold_reference(d, state0, opcodes, args)
        got_state, got_resps = d.window_apply(state0, opcodes, args)
        np.testing.assert_array_equal(
            np.asarray(got_state["data"]), np.asarray(ref_state["data"])
        )
        np.testing.assert_array_equal(
            np.asarray(got_state["size"]), np.asarray(ref_state["size"])
        )
        assert [int(x) for x in got_resps] == ref_resps

    def test_truncate_then_write_then_logged_read(self):
        from node_replication_tpu.models import make_memfs

        d = make_memfs(2, 4)
        state0 = d.init_state()
        state0["data"] = state0["data"].at[0, 0].set(7)
        state0["size"] = state0["size"].at[0].set(1)
        ops = [
            (3, 0, 0, 0),   # read 7 (initial)
            (2, 0, 0, 0),   # truncate → old size 1
            (3, 0, 0, 0),   # read 0 (truncated)
            (1, 0, 2, 55),  # write block 2 → size 3
            (3, 0, 2, 0),   # read 55 (in-window write)
            (3, 0, 0, 0),   # read 0 (still truncated, no later write)
        ]
        opcodes = jnp.asarray([o[0] for o in ops], jnp.int32)
        args = jnp.asarray([list(o[1:]) for o in ops], jnp.int32)
        state, resps = d.window_apply(state0, opcodes, args)
        assert [int(x) for x in resps] == [7, 1, 0, 3, 55, 0]
        assert int(state["size"][0]) == 3
        assert int(state["data"][0, 0]) == 0
        assert int(state["data"][0, 2]) == 55


def fold_jit(d, state, opcodes, args):
    """fold_reference with a jitted per-op step (radix ops are slow
    eagerly: 512-lane scatters per unmap_table)."""
    step = jax.jit(lambda s, o, a: apply_write(d, s, o, a))
    resps = []
    for i in range(len(opcodes)):
        state, r = step(state, opcodes[i], args[i])
        resps.append(int(r))
    return state, resps


class TestVSpaceWindowApply:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_flat_matches_sequential_fold(self, seed):
        from node_replication_tpu.models import make_vspace

        K, S, W = 37, 5, 64
        d = make_vspace(K, max_span=S)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=W, p=[0.1, 0.5, 0.3, 0.1]),
            jnp.int32,
        )
        # adversarial args: negative/overflowing vpages (the sequential
        # op wraps them through the mod), pframe=0 maps that read back
        # as unmapped, zero/negative/oversized spans
        args = jnp.asarray(
            np.stack(
                [rng.integers(-3, K + 3, W), rng.integers(0, 50, W),
                 rng.integers(-1, S + 3, W)], axis=1
            ),
            jnp.int32,
        )
        st0 = d.init_state()
        st0["frames"] = st0["frames"].at[::4].set(7)
        ref_state, ref_resps = fold_jit(d, st0, opcodes, args)
        got_state, got_resps = d.window_apply(st0, opcodes, args)
        np.testing.assert_array_equal(
            np.asarray(got_state["frames"]), np.asarray(ref_state["frames"])
        )
        assert [int(x) for x in got_resps] == ref_resps

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_radix_matches_sequential_fold(self, seed):
        # the deepest window algebra: coupled pt/pd/pdpt/pml4 histories,
        # region teardown epochs, span-crossing table marks
        from node_replication_tpu.models import make_vspace_radix

        P, S, W = 1500, 20, 96
        d = make_vspace_radix(P, max_span=S)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 3, 4, 9], size=W,
                       p=[0.06, 0.3, 0.14, 0.25, 0.2, 0.05]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack(
                [rng.integers(0, 2 * P, W), rng.integers(-2, 60, W),
                 rng.integers(-1, S + 3, W)], axis=1
            ),
            jnp.int32,
        )
        st0 = d.init_state()
        # torn init: full walk in region 0; pt WITHOUT pd in region 2
        # (walk fails); pd with no pt in region 1
        st0["pt"] = st0["pt"].at[10:40].set(5).at[1100:1130].set(9)
        st0["pd"] = st0["pd"].at[0].set(True).at[1].set(True)
        st0["pdpt"] = st0["pdpt"].at[0].set(True)
        st0["pml4"] = st0["pml4"].at[0].set(True)
        ref_state, ref_resps = fold_jit(d, st0, opcodes, args)
        got_state, got_resps = d.window_apply(st0, opcodes, args)
        for k in ("pt", "pd", "pdpt", "pml4"):
            np.testing.assert_array_equal(
                np.asarray(got_state[k]), np.asarray(ref_state[k]), k
            )
        assert [int(x) for x in got_resps] == ref_resps

    def test_radix_teardown_epochs(self):
        # directed epoch algebra: two teardowns of one region — the
        # first counts initially-mapped + in-window pages, the second
        # counts only pages re-mapped after the first
        from node_replication_tpu.models import make_vspace_radix

        P = 1100  # 3 pd regions (last one partial: 1100-1024=76 pages)
        d = make_vspace_radix(P, max_span=8)
        st0 = d.init_state()
        # 6 initially fully-walked pages in region 1 (512..1023)
        st0["pt"] = st0["pt"].at[600:606].set(3)
        st0["pd"] = st0["pd"].at[1].set(True)
        st0["pdpt"] = st0["pdpt"].at[0].set(True)
        st0["pml4"] = st0["pml4"].at[0].set(True)
        ops = [
            (1, 520, 9, 4),   # map 4 fresh pages in region 1 → newly 4
            (1, 602, 9, 4),   # overwrite 4 of the init pages → newly 0
            (4, 700, 0, 0),   # teardown region 1 → 6 init + 4 new = 10
            (3, 520, 4, 0),   # unmap after teardown → was 0
            (1, 640, 1, 2),   # re-map 2 pages (re-allocates the table)
            (4, 712, 0, 0),   # second teardown → only the 2 re-mapped
            (4, 712, 0, 0),   # third, empty epoch → 0
            (2, 76, 5, 3),    # MapDevice in region 0: pdpt/pml4 already
                              # set, pd fresh → newly 3
            (4, 100, 0, 0),   # teardown region 0 → 3
        ]
        opcodes = jnp.asarray([o[0] for o in ops], jnp.int32)
        args = jnp.asarray([list(o[1:]) for o in ops], jnp.int32)
        ref_state, ref_resps = fold_jit(d, st0, opcodes, args)
        got_state, got_resps = d.window_apply(st0, opcodes, args)
        assert ref_resps == [4, 0, 10, 0, 2, 2, 0, 3, 3]  # pin intent
        assert [int(x) for x in got_resps] == ref_resps
        for k in ("pt", "pd", "pdpt", "pml4"):
            np.testing.assert_array_equal(
                np.asarray(got_state[k]), np.asarray(ref_state[k]), k
            )

    def test_radix_step_combined_matches_scan(self):
        # whole-step integration: combined engine vs scan engine over a
        # multi-step drive with ring wrap
        from node_replication_tpu.models import make_vspace_radix

        R, Bw, Br, P, STEPS = 3, 4, 2, 1100, 5
        d = make_vspace_radix(P, max_span=8)
        spec = LogSpec(capacity=2 * R * Bw, n_replicas=R, arg_width=3,
                       gc_slack=R * Bw // 2)
        rng = np.random.default_rng(7)
        s_comb = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=True)
        s_scan = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=False)
        log_c, st_c = log_init(spec), replicate_state(d.init_state(), R)
        log_s, st_s = log_init(spec), replicate_state(d.init_state(), R)
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, 1, 2, 3, 4], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                np.stack([rng.integers(0, P, (R, Bw)),
                          rng.integers(0, 60, (R, Bw)),
                          rng.integers(0, 9, (R, Bw))], axis=-1),
                jnp.int32,
            )
            rd_opc = jnp.asarray(
                rng.choice([1, 2, 3], size=(R, Br)), jnp.int32
            )
            rd_args = jnp.asarray(
                np.stack([rng.integers(0, P, (R, Br)),
                          rng.integers(1, 9, (R, Br)),
                          np.zeros((R, Br))], axis=-1),
                jnp.int32,
            )
            log_c, st_c, wr_c, rd_c = s_comb(
                log_c, st_c, wr_opc, wr_args, rd_opc, rd_args
            )
            log_s, st_s, wr_s, rd_s = s_scan(
                log_s, st_s, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_c), np.asarray(wr_s))
            np.testing.assert_array_equal(np.asarray(rd_c), np.asarray(rd_s))
        for a, b in zip(jax.tree.leaves(st_c), jax.tree.leaves(st_s)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestStackWindowApply:
    """Order-dependent models via clamped-walk + slot-LWW algebra
    (ops/windowkit.py; VERDICT r3 #2 — parenthesis matching made LWW)."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_sequential_fold(self, seed):
        from node_replication_tpu.models import make_stack

        C, W = 7, 64
        d = make_stack(C)
        rng = np.random.default_rng(seed)
        # heavy churn around both clamps: overfull pushes, empty pops
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=W, p=[0.08, 0.44, 0.4, 0.08]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack([rng.integers(1, 100, W), np.zeros(W),
                      np.zeros(W)], axis=1),
            jnp.int32,
        )
        st0 = d.init_state()
        st0["buf"] = st0["buf"].at[:3].set(
            jnp.asarray([11, 12, 13], jnp.int32)
        )
        st0["top"] = jnp.int32(3)
        ref_state, ref_resps = fold_jit(d, st0, opcodes, args)
        got_state, got_resps = d.window_apply(st0, opcodes, args)
        for k in ("buf", "top"):
            np.testing.assert_array_equal(
                np.asarray(got_state[k]), np.asarray(ref_state[k]), k
            )
        assert [int(x) for x in got_resps] == ref_resps

    def test_pop_sees_in_window_push_not_initial(self):
        from node_replication_tpu.models import make_stack

        d = make_stack(4)
        st0 = d.init_state()
        st0["buf"] = st0["buf"].at[0].set(99)
        st0["top"] = jnp.int32(1)
        ops = [
            (2, 0),    # pop initial 99
            (2, 0),    # pop empty -> -1
            (1, 7),    # push 7 (slot 0)
            (1, 8),    # push 8 (slot 1)
            (2, 0),    # pop 8
            (1, 9),    # push 9 (slot 1 again)
            (2, 0),    # pop 9 (not 8: slot 1 was overwritten)
            (2, 0),    # pop 7
        ]
        opcodes = jnp.asarray([o[0] for o in ops], jnp.int32)
        args = jnp.zeros((len(ops), 3), jnp.int32).at[:, 0].set(
            jnp.asarray([o[1] for o in ops], jnp.int32)
        )
        state, resps = d.window_apply(st0, opcodes, args)
        assert [int(x) for x in resps] == [99, -1, 1, 2, 8, 2, 9, 7]
        assert int(state["top"]) == 0

    def test_step_combined_matches_scan(self):
        from node_replication_tpu.models import make_stack

        R, Bw, Br, C, STEPS = 3, 4, 2, 9, 6
        d = make_stack(C)
        spec = LogSpec(capacity=2 * R * Bw, n_replicas=R, arg_width=3,
                       gc_slack=R * Bw // 2)
        rng = np.random.default_rng(2)
        s_comb = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=True)
        s_scan = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=False)
        log_c, st_c = log_init(spec), replicate_state(d.init_state(), R)
        log_s, st_s = log_init(spec), replicate_state(d.init_state(), R)
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, 1, 2], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                rng.integers(1, 50, size=(R, Bw, 3)), jnp.int32
            )
            rd_opc = jnp.asarray(
                rng.choice([1, 2], size=(R, Br)), jnp.int32
            )
            rd_args = jnp.zeros((R, Br, 3), jnp.int32)
            log_c, st_c, wr_c, rd_c = s_comb(
                log_c, st_c, wr_opc, wr_args, rd_opc, rd_args
            )
            log_s, st_s, wr_s, rd_s = s_scan(
                log_s, st_s, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_c), np.asarray(wr_s))
            np.testing.assert_array_equal(np.asarray(rd_c), np.asarray(rd_s))
        for a, b in zip(jax.tree.leaves(st_c), jax.tree.leaves(st_s)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestQueueWindowApply:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_sequential_fold(self, seed):
        from node_replication_tpu.models import make_queue

        C, W = 7, 64
        d = make_queue(C)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=W, p=[0.08, 0.44, 0.4, 0.08]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack([rng.integers(1, 100, W), np.zeros(W),
                      np.zeros(W)], axis=1),
            jnp.int32,
        )
        st0 = d.init_state()
        st0["buf"] = st0["buf"].at[:3].set(
            jnp.asarray([11, 12, 13], jnp.int32)
        )
        st0["tail"] = jnp.int32(3)
        ref_state, ref_resps = fold_jit(d, st0, opcodes, args)
        got_state, got_resps = d.window_apply(st0, opcodes, args)
        for k in ("buf", "head", "tail"):
            np.testing.assert_array_equal(
                np.asarray(got_state[k]), np.asarray(ref_state[k]), k
            )
        assert [int(x) for x in got_resps] == ref_resps

    def test_ring_wrap_with_offset_cursors(self):
        # cursors far from zero, capacity-3 ring churned through many
        # generations: per-slot LWW must hand each dequeue its own
        # generation's value
        from node_replication_tpu.models import make_queue

        d = make_queue(3)
        st0 = d.init_state()
        st0["buf"] = jnp.asarray([5, 6, 7], jnp.int32)
        st0["head"] = jnp.int32(4)
        st0["tail"] = jnp.int32(6)
        rng = np.random.default_rng(9)
        W = 96
        opcodes = jnp.asarray(rng.choice([1, 2], size=W), jnp.int32)
        args = jnp.zeros((W, 3), jnp.int32).at[:, 0].set(
            jnp.asarray(rng.integers(1, 100, W), jnp.int32)
        )
        ref_state, ref_resps = fold_jit(d, st0, opcodes, args)
        got_state, got_resps = d.window_apply(st0, opcodes, args)
        for k in ("buf", "head", "tail"):
            np.testing.assert_array_equal(
                np.asarray(got_state[k]), np.asarray(ref_state[k]), k
            )
        assert [int(x) for x in got_resps] == ref_resps


class TestCombinedCatchup:
    """`log_catchup_all`: combined replay on DIVERGENT cursors — the
    catch-up-at-hot-loop-speed contract (`nr/src/log.rs:473-524`).
    Bit-identical to `log_exec_all` per round: states, resps, cursors."""

    def _drive(self, d, make_state, seed, model_args):
        from node_replication_tpu.core.log import (
            log_append,
            log_catchup_all,
            log_exec_all,
        )

        R, N, W = 4, 96, 32
        spec = LogSpec(capacity=256, n_replicas=R, arg_width=3,
                       gc_slack=16)
        rng = np.random.default_rng(seed)
        opcodes = jnp.asarray(
            rng.choice([0, 1, 2, 9], size=N, p=[0.1, 0.5, 0.3, 0.1]),
            jnp.int32,
        )
        args = jnp.asarray(
            np.stack([rng.integers(0, model_args, N),
                      rng.integers(1, 100, N),
                      np.zeros(N)], axis=1),
            jnp.int32,
        )
        outs = {}
        for eng in (log_exec_all, log_catchup_all):
            log = log_init(spec)
            log = log_append(spec, log, opcodes, args, N)
            states = replicate_state(d.init_state(), R)
            lim_rounds = []
            # limited rounds diverge the fleet (replica 2 fully dormant),
            # then unlimited rounds converge it — GC stalls in between.
            # Both engines follow the same lattice on the LIMITED rounds
            # (per-replica truncation admits no shared plan); on the
            # unlimited rounds the union-plan engine may advance lagging
            # replicas further per round, so there we compare the
            # position->response mapping and the converged state instead
            # of per-round cursors.
            limit_rounds = [jnp.asarray([10, 35, 0, N], jnp.int64),
                            jnp.asarray([60, 35, 0, N], jnp.int64)]
            for lim in limit_rounds:
                log, states, resps = eng(spec, d, log, states, W, lim)
                lim_rounds.append((np.asarray(resps),
                                   np.asarray(log.ltails),
                                   int(log.head), int(log.ctail)))
            # consumed-response map: replica r's answer for position p
            pos_resps = {r: {} for r in range(R)}
            rounds = 0
            while int(np.min(np.asarray(log.ltails))) < N:
                before = np.asarray(log.ltails).copy()
                log, states, resps = eng(spec, d, log, states, W)
                after = np.asarray(log.ltails)
                resps = np.asarray(resps)
                for r in range(R):
                    for i in range(int(after[r] - before[r])):
                        pos_resps[r][int(before[r]) + i] = int(
                            resps[r, i]
                        )
                rounds += 1
                assert rounds < 64, f"{eng.__name__} failed to converge"
            outs[eng.__name__] = (
                jax.tree.map(np.asarray, states),
                lim_rounds,
                pos_resps,
                np.asarray(log.ltails),
                int(log.head),
            )
        st_scan, lim_scan, pr_scan, lt_scan, h_scan = outs["log_exec_all"]
        st_comb, lim_comb, pr_comb, lt_comb, h_comb = outs[
            "log_catchup_all"
        ]
        for (ra, la, ha, ca), (rb, lb, hb, cb) in zip(lim_scan, lim_comb):
            np.testing.assert_array_equal(ra, rb)
            np.testing.assert_array_equal(la, lb)
            assert ha == hb and ca == cb
        # each replica must answer the SAME positions with the SAME
        # responses, regardless of how rounds chunked the catch-up
        assert pr_scan == pr_comb
        np.testing.assert_array_equal(lt_scan, lt_comb)
        assert h_scan == h_comb
        for a, b in zip(jax.tree.leaves(st_scan), jax.tree.leaves(st_comb)):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_hashmap_divergent_cursors(self, seed):
        self._drive(make_hashmap(13), None, seed, 13)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_stack_divergent_cursors(self, seed):
        # order-dependent model on divergent state: exactly the case the
        # plan/merge fast path excludes and window_apply must cover
        from node_replication_tpu.models import make_stack

        self._drive(make_stack(9), None, seed, 50)

    @pytest.mark.parametrize("seed", [0])
    def test_queue_divergent_cursors(self, seed):
        from node_replication_tpu.models import make_queue

        self._drive(make_queue(9), None, seed, 50)

    @pytest.mark.parametrize("mk,nargs,N,snaps", [
        ("stack", 50, 64, (16, 25, 48)),
        ("queue", 50, 64, (16, 25, 48)),
        ("hashmap", 30, 64, (16, 25, 48)),
        ("sortedset", 30, 64, (16, 25, 48)),
        # fast tier-1 equivalents of the heavy models: the same
        # prefix-absorption contract over a SHORTER schedule (cost is
        # per-op apply + the plan compile, not model capacity — the
        # full-length runs below are ~15-50s each on this machine)
        ("vspace", 40, 20, (5, 9, 15)),
        ("vspace_radix", 40, 12, (3, 6, 9)),
        ("memfs", 5, 20, (5, 9, 15)),
        # full-length heavy schedules, slow-marked to fit the tier-1
        # verify budget; still green in the full suite
        pytest.param("vspace", 40, 64, (16, 25, 48),
                     marks=pytest.mark.slow),
        pytest.param("vspace_radix", 40, 64, (16, 25, 48),
                     marks=pytest.mark.slow),
        pytest.param("memfs", 5, 64, (16, 25, 48),
                     marks=pytest.mark.slow),
    ])
    def test_plan_is_prefix_absorbing(self, mk, nargs, N, snaps):
        # the union-window catch-up contract: merging plan(state(m),
        # [m, end)) into a replica ALREADY at p in [m, end] must land
        # exactly on state(end) — cursors in the plan must be absolute,
        # not deltas (the r5 queue bug: head/tail double-counted)
        from node_replication_tpu import models as M

        d = {
            "stack": lambda: M.make_stack(9),
            "queue": lambda: M.make_queue(9),
            "vspace": lambda: M.make_vspace(600, max_span=8),
            "vspace_radix": lambda: M.make_vspace_radix(1100, max_span=8),
            "hashmap": lambda: M.make_hashmap(30),
            "sortedset": lambda: M.make_sortedset(30),
            "memfs": lambda: M.make_memfs(5, 64),
        }[mk]()
        rng = np.random.default_rng(1)
        n_ops = {"stack": 2, "queue": 2, "vspace": 2, "vspace_radix": 4,
                 "hashmap": 2, "sortedset": 2, "memfs": 3}[mk]
        opcodes = jnp.asarray(
            rng.integers(0, n_ops + 1, N), jnp.int32
        )
        args = jnp.asarray(
            np.stack([rng.integers(0, nargs, N),
                      rng.integers(1, 60, N),
                      rng.integers(0, 9, N)], axis=1),
            jnp.int32,
        )
        lo, _mid, hi = snaps
        snap = {}
        st = d.init_state()
        for i in range(N):
            if i in snaps:
                snap[i] = st
            st, _ = apply_write(d, st, opcodes[i], args[i])
        snap[N] = st
        plan = d.window_plan(snap[lo], opcodes[lo:hi], args[lo:hi])
        for p in snaps:  # window start, mid-window, window end
            merged, _ = d.window_merge(snap[p], plan)
            for a, b in zip(jax.tree.leaves(merged),
                            jax.tree.leaves(snap[hi])):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    f"{mk}: merge from p={p} not canonical",
                )

    def test_off_trajectory_flag_uses_window_apply(self):
        # hand-built fleets whose states are NOT folds of the shared log
        # must opt out of the union-plan tier; on_trajectory=False takes
        # the per-replica window_apply tier, correct for arbitrary state
        from node_replication_tpu.core.log import (
            log_append,
            log_catchup_all,
        )

        K, R, N, W = 16, 2, 8, 8
        d = make_hashmap(K)
        spec = LogSpec(capacity=64, n_replicas=R, arg_width=3,
                       gc_slack=8)
        log = log_init(spec)
        opc = jnp.full((N,), HM_PUT, jnp.int32)
        ag = jnp.zeros((N, 3), jnp.int32).at[:, 0].set(
            jnp.arange(N, dtype=jnp.int32)
        ).at[:, 1].set(100)
        log = log_append(spec, log, opc, ag, N)
        # off-trajectory: replica 1 starts with a key the log never wrote
        states = replicate_state(d.init_state(), R)
        states = dict(states)
        states["values"] = states["values"].at[1, 15].set(999)
        states["present"] = states["present"].at[1, 15].set(True)
        log2, st2, _ = log_catchup_all(
            spec, d, log, states, W, on_trajectory=False
        )
        # replica 1 keeps its private key (untouched by the window) and
        # still applies the log's writes — the per-replica fold semantics
        assert int(st2["values"][1, 15]) == 999
        assert bool(st2["present"][1, 15])
        assert int(st2["values"][0, 15]) == 0
        for r in range(R):
            for k in range(N):
                assert int(st2["values"][r, k]) == 100
        assert (np.asarray(log2.ltails) == N).all()

    def test_union_tier_requires_canonical_opt_in(self):
        # ADVICE r5 / ISSUE 2: presence of window_plan/window_merge only
        # claims the lock-step contract; the union-window catch-up tier
        # needs the EXPLICIT `window_canonical=True` opt-in (all bundled
        # models set it) or an explicit union=True force from an
        # engine='combined' caller. Tier routing is observed through the
        # log.engine.* dispatch counters; results stay bit-equal either
        # way (hashmap satisfies both contracts).
        import dataclasses

        from node_replication_tpu.core.log import (
            log_append,
            log_catchup_all,
        )
        from node_replication_tpu.obs.metrics import get_registry

        K, R, N, W = 16, 2, 8, 8
        d = make_hashmap(K)
        assert d.window_canonical
        d_weak = dataclasses.replace(d, window_canonical=False)
        spec = LogSpec(capacity=64, n_replicas=R, arg_width=3,
                       gc_slack=8)
        opc = jnp.full((N,), HM_PUT, jnp.int32)
        ag = jnp.zeros((N, 3), jnp.int32).at[:, 0].set(
            jnp.arange(N, dtype=jnp.int32)
        ).at[:, 1].set(7)

        def fresh():
            log = log_append(spec, log_init(spec), opc, ag, N)
            return log, replicate_state(d.init_state(), R)

        reg = get_registry()
        was_enabled = reg.enabled
        reg.enable()
        c_union = reg.counter("log.engine.union_plan")
        c_window = reg.counter("log.engine.window_apply")
        try:
            # canonical model: auto routing takes the union tier
            log, states = fresh()
            u0, w0 = c_union.value, c_window.value
            _, st_canon, _ = log_catchup_all(spec, d, log, states, W)
            assert c_union.value == u0 + 1

            # weak model (lock-step-only contract): auto routing must
            # NOT take the stronger-contract engine
            log, states = fresh()
            u0, w0 = c_union.value, c_window.value
            _, st_weak, _ = log_catchup_all(spec, d_weak, log, states, W)
            assert c_union.value == u0
            assert c_window.value == w0 + 1

            # explicit force (the engine='combined' caller asserting
            # the contract) still routes the weak model through union
            log, states = fresh()
            u0 = c_union.value
            _, st_forced, _ = log_catchup_all(
                spec, d_weak, log, states, W, union=True
            )
            assert c_union.value == u0 + 1
        finally:
            if not was_enabled:
                reg.disable()
        for a, b, c in zip(jax.tree.leaves(st_canon),
                           jax.tree.leaves(st_weak),
                           jax.tree.leaves(st_forced)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    def test_auto_engine_honest_for_plan_only_weak_model(self):
        # a plan/merge-only model WITHOUT the canonical opt-in has no
        # combined tier that can actually run outside lock-step, so
        # engine='auto' must resolve (and report) 'scan', not a
        # 'combined' label whose every round falls through to the scan;
        # engine='combined' remains the explicit force
        import dataclasses

        from node_replication_tpu.core.replica import NodeReplicated

        d = make_hashmap(16)
        weak_plan_only = dataclasses.replace(
            d, window_apply=None, window_canonical=False
        )
        nr = NodeReplicated(weak_plan_only, n_replicas=2,
                            log_entries=64, gc_slack=8)
        assert nr.engine == "scan"
        forced = NodeReplicated(weak_plan_only, n_replicas=2,
                                log_entries=64, gc_slack=8,
                                engine="combined")
        assert forced.engine == "combined"
        for inst in (nr, forced):
            t = inst.register(0)
            for k in range(6):
                assert inst.execute_mut((HM_PUT, k, k + 50), t) == 0
            inst.sync()
            assert inst.replicas_equal()
            assert inst.execute((HM_GET, 3), t) == 53

    def test_node_replicated_engines_agree(self):
        # whole-wrapper drive: per-op API with interleaved sync on both
        # engines, responses and final states bit-equal
        from node_replication_tpu.core.replica import NodeReplicated
        from node_replication_tpu.models import HM_PUT, HM_REMOVE

        rng = np.random.default_rng(3)
        ops = [
            (int(rng.choice([HM_PUT, HM_REMOVE])),
             int(rng.integers(0, 16)), int(rng.integers(1, 50)))
            for _ in range(40)
        ]
        outs = {}
        for eng in ("scan", "combined"):
            nr = NodeReplicated(make_hashmap(16), n_replicas=2,
                                log_entries=512, gc_slack=16, engine=eng)
            assert nr.engine == eng
            t0, t1 = nr.register(0), nr.register(1)
            resps = []
            for i, op in enumerate(ops):
                resps.append(nr.execute_mut(op, t0 if i % 2 else t1))
            nr.sync()
            outs[eng] = (resps, jax.tree.map(np.asarray, nr.states))
        assert outs["scan"][0] == outs["combined"][0]
        for a, b in zip(jax.tree.leaves(outs["scan"][1]),
                        jax.tree.leaves(outs["combined"][1])):
            np.testing.assert_array_equal(a, b)


class TestMultilogCombined:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_partitioned_combined_matches_scan(self, seed):
        # per-log combined replay vs the per-log scan over the same
        # hash-routed stream: states, write resps, read resps, cursors
        from node_replication_tpu.harness.trait import MultiLogRunner
        from node_replication_tpu.models import (
            make_partitioned_sortedset,
            make_sortedset,
        )

        K, L, R, S, Bw = 32, 4, 3, 5, 6
        rng = np.random.default_rng(seed)
        wr_opc = rng.choice([0, 1, 2], size=(S, R, Bw)).astype(np.int32)
        wr_args = np.zeros((S, R, Bw, 3), np.int32)
        wr_args[..., 0] = rng.integers(0, K, (S, R, Bw))
        rd_opc = np.full((S, R, 2), 1, np.int32)
        rd_args = np.zeros((S, R, 2, 3), np.int32)
        rd_args[..., 0] = rng.integers(0, K, (S, R, 2))
        outs = {}
        for mode in (False, True):
            r = MultiLogRunner(
                make_sortedset(K), R, L, Bw, 2,
                partitioned=make_partitioned_sortedset(K, L),
                keyspace=K, combined=mode,
            )
            r.prepare(wr_opc, wr_args, rd_opc, rd_args)
            lasts = []
            for s in range(S):
                r.run_step(s)
                lasts.append(np.asarray(r._last))
            r.block()
            outs[mode] = (
                jax.tree.map(np.asarray, r.states),
                np.asarray(r.ml.ltails),
                lasts,
            )
        st_a, lt_a, rd_a = outs[False]
        st_b, lt_b, rd_b = outs[True]
        for x, y in zip(jax.tree.leaves(st_a), jax.tree.leaves(st_b)):
            np.testing.assert_array_equal(x, y)
        np.testing.assert_array_equal(lt_a, lt_b)
        for x, y in zip(rd_a, rd_b):
            np.testing.assert_array_equal(x, y)


class TestCombinedStep:
    @pytest.mark.parametrize("seed", [0, 3])
    def test_step_bit_identical_to_scan_step(self, seed):
        R, Bw, Br, K, STEPS = 4, 3, 2, 11, 6
        d = make_hashmap(K)
        # capacity small enough that the ring wraps during the drive
        spec = LogSpec(capacity=2 * R * Bw, n_replicas=R, arg_width=3,
                       gc_slack=R * Bw // 2)
        rng = np.random.default_rng(seed)
        s_comb = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=True)
        s_scan = make_step(d, spec, Bw, Br, jit=True, donate=False,
                           combined=False)
        log_c, st_c = log_init(spec), replicate_state(d.init_state(), R)
        log_s, st_s = log_init(spec), replicate_state(d.init_state(), R)
        for _ in range(STEPS):
            wr_opc = jnp.asarray(
                rng.choice([0, HM_PUT, HM_REMOVE], size=(R, Bw)), jnp.int32
            )
            wr_args = jnp.asarray(
                rng.integers(0, K, size=(R, Bw, 3)), jnp.int32
            )
            rd_opc = jnp.full((R, Br), HM_GET, jnp.int32)
            rd_args = jnp.asarray(
                rng.integers(0, K, size=(R, Br, 3)), jnp.int32
            )
            log_c, st_c, wr_c, rd_c = s_comb(
                log_c, st_c, wr_opc, wr_args, rd_opc, rd_args
            )
            log_s, st_s, wr_s, rd_s = s_scan(
                log_s, st_s, wr_opc, wr_args, rd_opc, rd_args
            )
            np.testing.assert_array_equal(np.asarray(wr_c), np.asarray(wr_s))
            np.testing.assert_array_equal(np.asarray(rd_c), np.asarray(rd_s))
        for leaf_c, leaf_s in zip(jax.tree.leaves(st_c), jax.tree.leaves(st_s)):
            np.testing.assert_array_equal(np.asarray(leaf_c), np.asarray(leaf_s))
        for name in ("head", "tail", "ctail"):
            assert int(getattr(log_c, name)) == int(getattr(log_s, name))
        np.testing.assert_array_equal(
            np.asarray(log_c.ltails), np.asarray(log_s.ltails)
        )

    def test_auto_selects_combined_when_available(self):
        d = make_hashmap(8)
        assert d.window_apply is not None
        spec = LogSpec(capacity=64, n_replicas=2, arg_width=3, gc_slack=8)
        # default (None) → combined; explicit False → scan; both compile
        for combined in (None, False):
            step = make_step(d, spec, 1, 1, jit=True, donate=False,
                             combined=combined)
            log, st = log_init(spec), replicate_state(d.init_state(), 2)
            log, st, wr, rd = step(
                log, st,
                jnp.full((2, 1), HM_PUT, jnp.int32),
                jnp.zeros((2, 1, 3), jnp.int32).at[..., 0].set(3)
                .at[..., 1].set(9),
                jnp.full((2, 1), HM_GET, jnp.int32),
                jnp.zeros((2, 1, 3), jnp.int32).at[..., 0].set(3),
            )
            assert int(rd[0, 0]) == 9

    def test_combined_requires_window_apply(self):
        # synthetic is the remaining scan-only model (stack/queue gained
        # window_apply in r4)
        from node_replication_tpu.models import make_synthetic

        d = make_synthetic(16)
        assert d.window_apply is None
        spec = LogSpec(capacity=64, n_replicas=1, arg_width=3, gc_slack=8)
        with pytest.raises(ValueError):
            make_step(d, spec, 1, 0, combined=True)
