"""Model smoke/determinism tests for workloads beyond hashmap/stack:
synthetic (`benches/synthetic.rs`), vspace (`benches/vspace.rs`), memfs
(`benches/memfs.rs` / `benches/nrfs.rs`), sortedset (`benches/lockfree.rs`
skiplist analog)."""

import numpy as np

from node_replication_tpu import NodeReplicated
from node_replication_tpu.models import (
    FS_READ,
    FS_READ_LOGGED,
    FS_SIZE,
    FS_TRUNCATE,
    FS_WRITE,
    SS_CONTAINS,
    SS_INSERT,
    SS_RANGE_COUNT,
    SS_RANK,
    SS_REMOVE,
    SYN_READ,
    SYN_WRITE,
    VS_IDENTIFY,
    VS_MAP,
    VS_RESOLVED,
    VS_UNMAP,
    make_memfs,
    make_sortedset,
    make_synthetic,
    make_vspace,
    memfs_log_mapper,
    sortedset_log_mapper,
)


class TestSynthetic:
    def test_deterministic_replay_converges(self):
        # The synthetic DS (`benches/synthetic.rs:59-110` analog) derives
        # its touched lines from op args, so replay on every replica must
        # produce identical state.
        d = make_synthetic(n=512, cold_reads=4, cold_writes=2, hot_reads=2,
                           hot_writes=1, hot_set=32)
        nr = NodeReplicated(d, n_replicas=2, log_entries=256, gc_slack=16,
                            exec_window=16)
        t0, t1 = nr.register(0), nr.register(1)
        for i in range(20):
            nr.enqueue_mut((SYN_WRITE, i * 17 + 3), t0 if i % 2 else t1)
        nr.flush()
        nr.sync()
        assert nr.replicas_equal()
        # state actually changed
        nr.verify(lambda s: None if np.any(s["lines"]) else
                  (_ for _ in ()).throw(AssertionError("no writes landed")))

    def test_read_matches_write_checksum_footprint(self):
        # A read with the same seed as a write sees the post-write lines.
        d = make_synthetic(n=64, cold_reads=2, cold_writes=1, hot_reads=1,
                           hot_writes=1, hot_set=8)
        nr = NodeReplicated(d, n_replicas=1, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        r0 = nr.execute((SYN_READ, 5), tok)
        assert r0 == 0  # zero state → zero checksum
        nr.execute_mut((SYN_WRITE, 5), tok)
        r1 = nr.execute((SYN_READ, 5), tok)
        assert r1 != 0

    def test_zero_cost_knobs(self):
        # cost knobs at zero must not crash (empty concatenate branches).
        d = make_synthetic(n=64, cold_reads=1, cold_writes=1, hot_reads=0,
                           hot_writes=0, hot_set=8)
        nr = NodeReplicated(d, n_replicas=1, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        nr.execute_mut((SYN_WRITE, 1), tok)
        nr.execute((SYN_READ, 1), tok)


class TestVSpace:
    def test_map_identify_unmap(self):
        d = make_vspace(256, max_span=8)
        nr = NodeReplicated(d, n_replicas=2, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        # map 4 pages at vpage 10 -> frames 100..103 (pframe>=1 contract)
        assert nr.execute_mut((VS_MAP, 10, 100, 4), tok) == 4
        assert nr.execute((VS_IDENTIFY, 10), tok) == 100
        assert nr.execute((VS_IDENTIFY, 13), tok) == 103
        assert nr.execute((VS_IDENTIFY, 14), tok) == -1
        assert nr.execute((VS_RESOLVED, 8, 8), tok) == 4
        # remap overlapping: only 2 new pages beyond the existing 4
        assert nr.execute_mut((VS_MAP, 12, 200, 4), tok) == 2
        assert nr.execute((VS_IDENTIFY, 12), tok) == 200
        assert nr.execute_mut((VS_UNMAP, 10, 6), tok) == 6
        assert nr.execute((VS_RESOLVED, 0, 256), tok) == 0
        nr.sync()
        assert nr.replicas_equal()

    def test_span_clipped_to_max_and_bounds(self):
        d = make_vspace(32, max_span=4)
        nr = NodeReplicated(d, n_replicas=1, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        # npages > max_span clips to 4
        assert nr.execute_mut((VS_MAP, 0, 1, 100), tok) == 4
        # map crossing the end of the VA window only touches valid pages
        assert nr.execute_mut((VS_MAP, 30, 50, 4), tok) == 2
        assert nr.execute((VS_IDENTIFY, 31), tok) == 51


class TestVSpaceRadix:
    def test_map_device_and_walk(self):
        from node_replication_tpu.models import (
            VSR_IDENTIFY,
            VSR_MAP,
            VSR_MAP_DEVICE,
            VSR_RESOLVED,
            VSR_TABLES,
            make_vspace_radix,
        )

        d = make_vspace_radix(2048, max_span=8)
        nr = NodeReplicated(d, n_replicas=2, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        assert nr.execute_mut((VSR_MAP, 10, 100, 4), tok) == 4
        # identify encodes (pframe+1) | device<<30 after a FULL walk
        assert nr.execute((VSR_IDENTIFY, 10), tok) == 101
        assert nr.execute((VSR_IDENTIFY, 13), tok) == 104
        assert nr.execute((VSR_IDENTIFY, 14), tok) == -1
        # device mapping carries the attribute bit (`benches/vspace.rs`
        # MapDevice — uncacheable MMIO)
        assert nr.execute_mut((VSR_MAP_DEVICE, 600, 7, 2), tok) == 2
        resp = nr.execute((VSR_IDENTIFY, 600), tok)
        assert resp == (8 | (1 << 30))
        # RESOLVED is span-clipped (fixed scatter width) like the flat
        # model: query per-region
        assert nr.execute((VSR_RESOLVED, 8, 8), tok) == 4
        assert nr.execute((VSR_RESOLVED, 600, 8), tok) == 2
        # pages 10..13 live in PD table 0; 600 in table 1
        assert nr.execute((VSR_TABLES,), tok) == 2
        nr.sync()
        assert nr.replicas_equal()

    def test_unmap_table_tears_down_region(self):
        from node_replication_tpu.models import (
            VSR_IDENTIFY,
            VSR_MAP,
            VSR_RESOLVED,
            VSR_TABLES,
            VSR_UNMAP,
            VSR_UNMAP_TABLE,
            make_vspace_radix,
        )

        d = make_vspace_radix(2048, max_span=8)
        nr = NodeReplicated(d, n_replicas=1, log_entries=512, gc_slack=16)
        tok = nr.register(0)
        nr.execute_mut((VSR_MAP, 0, 100, 8), tok)
        nr.execute_mut((VSR_MAP, 510, 200, 4), tok)  # spans tables 0+1
        assert nr.execute((VSR_TABLES,), tok) == 2
        # plain unmap clears entries but keeps the table allocated
        assert nr.execute_mut((VSR_UNMAP, 0, 4), tok) == 4
        assert nr.execute((VSR_TABLES,), tok) == 2
        # table teardown unmaps the whole 512-page region at once and
        # deallocates the table (the radix-only region op)
        assert nr.execute_mut((VSR_UNMAP_TABLE, 7), tok) == 6
        assert nr.execute((VSR_TABLES,), tok) == 1
        assert nr.execute((VSR_IDENTIFY, 511), tok) == -1
        # table 1 intact: page 512 holds frame 202, encoded +1
        assert nr.execute((VSR_IDENTIFY, 512), tok) == 203
        assert nr.execute((VSR_RESOLVED, 510, 4), tok) == 2
        # remapping reallocates a fresh table; no stale entries resurrect
        assert nr.execute_mut((VSR_MAP, 100, 900, 1), tok) == 1
        assert nr.execute((VSR_TABLES,), tok) == 2
        assert nr.execute((VSR_IDENTIFY, 4), tok) == -1
        assert nr.execute((VSR_IDENTIFY, 100), tok) == 901

    def test_empty_map_allocates_no_tables(self):
        from node_replication_tpu.models import (
            VSR_MAP,
            VSR_TABLES,
            make_vspace_radix,
        )

        d = make_vspace_radix(2048, max_span=8)
        nr = NodeReplicated(d, n_replicas=1, log_entries=64, gc_slack=8)
        tok = nr.register(0)
        assert nr.execute_mut((VSR_MAP, 0, 5, 0), tok) == 0  # npages=0
        assert nr.execute((VSR_TABLES,), tok) == 0  # no phantom tables

    def test_shadow_model_random_ops(self):
        # random map/map-device/unmap/unmap-table stream vs a dict shadow
        from node_replication_tpu.models import (
            VSR_IDENTIFY,
            VSR_MAP,
            VSR_MAP_DEVICE,
            VSR_UNMAP,
            VSR_UNMAP_TABLE,
            make_vspace_radix,
        )

        N, SPAN = 1536, 8
        d = make_vspace_radix(N, max_span=SPAN)
        nr = NodeReplicated(d, n_replicas=2, log_entries=1 << 12,
                            gc_slack=64)
        tok = nr.register(0)
        rng = np.random.default_rng(4)
        shadow = {}  # vpage -> (frame, device)
        for _ in range(120):
            op = rng.choice([VSR_MAP, VSR_MAP_DEVICE, VSR_UNMAP,
                             VSR_UNMAP_TABLE], p=[0.4, 0.2, 0.3, 0.1])
            v = int(rng.integers(0, N))
            if op in (VSR_MAP, VSR_MAP_DEVICE):
                f = int(rng.integers(0, 1 << 16))
                n = int(rng.integers(1, SPAN + 1))
                nr.execute_mut((op, v, f, n), tok)
                for i in range(n):
                    if v + i < N:
                        shadow[v + i] = (f + i, op == VSR_MAP_DEVICE)
            elif op == VSR_UNMAP:
                n = int(rng.integers(1, SPAN + 1))
                nr.execute_mut((op, v, n), tok)
                for i in range(n):
                    shadow.pop(v + i, None)
            else:
                nr.execute_mut((op, v), tok)
                base = (v >> 9) << 9
                for pg in range(base, min(base + 512, N)):
                    shadow.pop(pg, None)
        for v in rng.integers(0, N, 64):
            got = nr.execute((VSR_IDENTIFY, int(v)), tok)
            want = shadow.get(int(v))
            if want is None:
                assert got == -1, (v, got)
            else:
                assert got == ((want[0] + 1) | (int(want[1]) << 30)), v
        nr.sync()
        assert nr.replicas_equal()


class TestMemFS:
    def test_write_read_truncate(self):
        d = make_memfs(4, 8)
        nr = NodeReplicated(d, n_replicas=2, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        assert nr.execute_mut((FS_WRITE, 1, 3, 42), tok) == 4  # size=4
        assert nr.execute((FS_READ, 1, 3), tok) == 42
        assert nr.execute((FS_SIZE, 1), tok) == 4
        # logged read (reads-as-writes idiom) returns value, mutates nothing
        assert nr.execute_mut((FS_READ_LOGGED, 1, 3), tok) == 42
        assert nr.execute((FS_SIZE, 1), tok) == 4
        assert nr.execute_mut((FS_TRUNCATE, 1), tok) == 4  # old size
        assert nr.execute((FS_READ, 1, 3), tok) == 0
        assert nr.execute((FS_SIZE, 1), tok) == 0
        # out of range
        assert nr.execute_mut((FS_WRITE, 9, 0, 1), tok) == -1
        nr.sync()
        assert nr.replicas_equal()

    def test_log_mapper_partitions_by_file(self):
        assert memfs_log_mapper(FS_WRITE, (3, 0, 1)) == 3
        assert memfs_log_mapper(FS_WRITE, (3, 7, 9)) == 3


class TestSortedSet:
    def test_ordered_queries(self):
        d = make_sortedset(64)
        nr = NodeReplicated(d, n_replicas=1, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        for k in (5, 10, 20, 40):
            assert nr.execute_mut((SS_INSERT, k), tok) == 1
        assert nr.execute_mut((SS_INSERT, 10), tok) == 0  # duplicate
        assert nr.execute((SS_CONTAINS, 10), tok) == 1
        assert nr.execute((SS_RANGE_COUNT, 5, 21), tok) == 3
        assert nr.execute((SS_RANK, 21), tok) == 3
        assert nr.execute_mut((SS_REMOVE, 10), tok) == 1
        assert nr.execute_mut((SS_REMOVE, 10), tok) == 0
        assert nr.execute((SS_RANGE_COUNT, 0, 64), tok) == 3

    def test_log_mapper_by_key(self):
        assert sortedset_log_mapper(SS_INSERT, (17,)) == 17


class TestQueue:
    def test_fifo_semantics_vs_shadow(self):
        import random
        from collections import deque as _dq

        from node_replication_tpu.core.replica import NodeReplicated
        from node_replication_tpu.models import (
            Q_DEQ,
            Q_ENQ,
            Q_FRONT,
            Q_LEN,
            make_queue,
        )

        nr = NodeReplicated(
            make_queue(16), n_replicas=2, log_entries=512, gc_slack=16
        )
        t = nr.register(0)
        shadow: _dq = _dq()
        rng = random.Random(2)
        for i in range(300):
            p = rng.random()
            if p < 0.5:
                resp = nr.execute_mut((Q_ENQ, i), t)
                if len(shadow) < 16:
                    shadow.append(i)
                    assert resp == len(shadow)
                else:
                    assert resp == -1  # full
            elif p < 0.8:
                resp = nr.execute_mut((Q_DEQ,), t)
                assert resp == (shadow.popleft() if shadow else -1)
            elif p < 0.9:
                assert nr.execute((Q_FRONT,), t) == (
                    shadow[0] if shadow else -1
                )
            else:
                assert nr.execute((Q_LEN,), t) == len(shadow)
        nr.sync()
        assert nr.replicas_equal()
