"""Model smoke/determinism tests for workloads beyond hashmap/stack."""

import numpy as np

from node_replication_tpu import NodeReplicated
from node_replication_tpu.models import SYN_READ, SYN_WRITE, make_synthetic


class TestSynthetic:
    def test_deterministic_replay_converges(self):
        # The synthetic DS (`benches/synthetic.rs:59-110` analog) derives
        # its touched lines from op args, so replay on every replica must
        # produce identical state.
        d = make_synthetic(n=512, cold_reads=4, cold_writes=2, hot_reads=2,
                           hot_writes=1, hot_set=32)
        nr = NodeReplicated(d, n_replicas=2, log_entries=256, gc_slack=16,
                            exec_window=16)
        t0, t1 = nr.register(0), nr.register(1)
        for i in range(20):
            nr.enqueue_mut((SYN_WRITE, i * 17 + 3), t0 if i % 2 else t1)
        nr.flush()
        nr.sync()
        assert nr.replicas_equal()
        # state actually changed
        nr.verify(lambda s: None if np.any(s["lines"]) else
                  (_ for _ in ()).throw(AssertionError("no writes landed")))

    def test_read_matches_write_checksum_footprint(self):
        # A read with the same seed as a write sees the post-write lines.
        d = make_synthetic(n=64, cold_reads=2, cold_writes=1, hot_reads=1,
                           hot_writes=1, hot_set=8)
        nr = NodeReplicated(d, n_replicas=1, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        r0 = nr.execute((SYN_READ, 5), tok)
        assert r0 == 0  # zero state → zero checksum
        nr.execute_mut((SYN_WRITE, 5), tok)
        r1 = nr.execute((SYN_READ, 5), tok)
        assert r1 != 0

    def test_zero_cost_knobs(self):
        # cost knobs at zero must not crash (empty concatenate branches).
        d = make_synthetic(n=64, cold_reads=1, cold_writes=1, hot_reads=0,
                           hot_writes=0, hot_set=8)
        nr = NodeReplicated(d, n_replicas=1, log_entries=256, gc_slack=16)
        tok = nr.register(0)
        nr.execute_mut((SYN_WRITE, 1), tok)
        nr.execute((SYN_READ, 1), tok)
