"""Overload plane (ISSUE 9): adaptive admission, priority shedding,
brownout reads, backpressure watermarks, circuit breaker, per-cause
retry accounting."""

import threading
import time

import pytest

from node_replication_tpu import NodeReplicated
from node_replication_tpu.models import SR_GET, SR_SET, make_seqreg
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.serve import (
    BULK,
    CRITICAL,
    NORMAL,
    CircuitBreaker,
    CircuitOpen,
    DeadlineExceeded,
    LagSource,
    OverloadConfig,
    OverloadGovernor,
    Overloaded,
    ReplicaFailed,
    RetryPolicy,
    ServeConfig,
    ServeFrontend,
    call_with_retry,
)


def make_nr(regs=8, replicas=1):
    return NodeReplicated(
        make_seqreg(regs), n_replicas=replicas,
        log_entries=512, gc_slack=64,
    )


# ==========================================================================
# OverloadGovernor: the AIMD loop, watermarks, brownout hysteresis
# ==========================================================================


class TestGovernor:
    def test_config_validation(self):
        with pytest.raises(ValueError):
            OverloadConfig(target_delay_s=0)
        with pytest.raises(ValueError):
            OverloadConfig(decrease=1.0)
        with pytest.raises(ValueError):
            OverloadConfig(brownout_enter=1.0, brownout_exit=1.0)
        with pytest.raises(ValueError):
            OverloadConfig(min_limit=0)

    def test_congested_round_multiplicative_decrease(self):
        cfg = OverloadConfig(target_delay_s=0.01, min_limit=4,
                             decrease=0.5)
        g = OverloadGovernor(cfg, queue_depth=64)
        g.register_replica(0)
        assert g.limit(0) == 64  # cold start at full depth
        g.on_round(0, queue_delay_s=0.05, n_ops=8)
        assert g.limit(0) == 32
        g.on_round(0, queue_delay_s=0.05, n_ops=8)
        assert g.limit(0) == 16
        for _ in range(10):
            g.on_round(0, queue_delay_s=0.05, n_ops=8)
        assert g.limit(0) == 4  # clamped at min_limit

    def test_clean_round_additive_increase(self):
        cfg = OverloadConfig(target_delay_s=0.01, increase=4)
        g = OverloadGovernor(cfg, queue_depth=64)
        g.register_replica(0)
        g.on_round(0, 0.05, 8)  # 32
        g.on_round(0, 0.001, 8)
        assert g.limit(0) == 36
        for _ in range(20):
            g.on_round(0, 0.001, 8)
        assert g.limit(0) == 64  # capped at the static depth

    def test_backpressure_watermarks(self):
        cfg = OverloadConfig(target_delay_s=0.01)
        g = OverloadGovernor(cfg, queue_depth=64)
        g.register_replica(0)
        lag = [0]
        g.add_source(LagSource("x", lambda: lag[0], low=100,
                               high=200))
        # below low: no pressure, clean rounds grow
        g.on_round(0, 0.05, 8)  # decrease -> 32
        g.on_round(0, 0.001, 8)
        assert g.limit(0) == 36
        # between the watermarks: growth pauses, no decrease
        lag[0] = 150
        g.on_round(0, 0.001, 8)
        assert g.limit(0) == 36
        # at/above high: multiplicative decrease even on clean delay
        lag[0] = 250
        g.on_round(0, 0.001, 8)
        assert g.limit(0) == 18
        assert g.backpressure() >= 1.0

    def test_duplicate_source_rejected(self):
        g = OverloadGovernor(OverloadConfig(), queue_depth=8)
        g.add_source(LagSource("x", lambda: 0, 1, 2))
        with pytest.raises(ValueError):
            g.add_source(LagSource("x", lambda: 0, 1, 2))
        with pytest.raises(ValueError):
            LagSource("bad", lambda: 0, low=5, high=5)

    def test_brownout_hysteresis(self):
        cfg = OverloadConfig(target_delay_s=0.01, brownout_enter=2.0,
                             brownout_exit=0.75, ewma_alpha=1.0)
        g = OverloadGovernor(cfg, queue_depth=64)
        g.register_replica(0)
        assert not g.brownout()
        g.on_round(0, 0.03, 8)  # ewma = 3x target > enter
        assert g.brownout()
        # above exit but below enter: STAYS in brownout (hysteresis)
        g.on_round(0, 0.012, 8)
        assert g.brownout()
        g.on_round(0, 0.001, 8)  # below exit: leaves
        assert not g.brownout()

    def test_unregistered_rid_falls_back_to_depth(self):
        g = OverloadGovernor(OverloadConfig(), queue_depth=17)
        assert g.limit(5) == 17


# ==========================================================================
# Priority shedding: eviction order, inversion impossibility
# ==========================================================================


class TestPriorityShedding:
    def test_bulk_evicted_before_normal_before_critical(self):
        nr = make_nr()
        fe = ServeFrontend(
            nr, ServeConfig(queue_depth=3, batch_linger_s=0.0),
            auto_start=False,
        )
        fb = fe.submit((SR_SET, 0, 1), priority=BULK)
        fn = fe.submit((SR_SET, 0, 2), priority=NORMAL)
        fe.submit((SR_SET, 0, 3), priority=NORMAL)
        # full: a CRITICAL arrival evicts the BULK op first
        fe.submit((SR_SET, 0, 4), priority=CRITICAL)
        exc = fb.exception(1.0)
        assert isinstance(exc, Overloaded) and exc.evicted
        assert exc.priority == BULK
        # full again (no BULK left): next CRITICAL evicts a NORMAL —
        # the NEWEST queued one of that class, so the older fn stays
        fe.submit((SR_SET, 0, 5), priority=CRITICAL)
        assert not fn.done()
        st = fe.stats()
        assert st["evicted"] == 2
        assert st["shed_by_priority"] == {"critical": 0, "normal": 1,
                                          "bulk": 1}
        assert st["priority_inversions"] == 0
        fe.close(drain=False)

    def test_critical_sheds_only_into_critical_queue(self):
        nr = make_nr()
        fe = ServeFrontend(
            nr, ServeConfig(queue_depth=2, batch_linger_s=0.0),
            auto_start=False,
        )
        fe.submit((SR_SET, 0, 1), priority=CRITICAL)
        fe.submit((SR_SET, 0, 2), priority=CRITICAL)
        with pytest.raises(Overloaded) as ei:
            fe.submit((SR_SET, 0, 3), priority=CRITICAL)
        assert ei.value.priority == CRITICAL
        # the invariant counter: zero, because nothing lower sat queued
        assert fe.stats()["priority_inversions"] == 0
        fe.close(drain=False)

    def test_bulk_sheds_without_evicting(self):
        nr = make_nr()
        fe = ServeFrontend(
            nr, ServeConfig(queue_depth=1, batch_linger_s=0.0),
            auto_start=False,
        )
        fe.submit((SR_SET, 0, 1), priority=NORMAL)
        with pytest.raises(Overloaded):
            fe.submit((SR_SET, 0, 2), priority=BULK)
        assert fe.stats()["evicted"] == 0
        fe.close(drain=False)

    def test_strict_priority_drain_order(self):
        nr = make_nr()
        fe = ServeFrontend(
            nr, ServeConfig(queue_depth=8, batch_max_ops=8,
                            batch_linger_s=0.0),
            auto_start=False,
        )
        fb = fe.submit((SR_SET, 0, 10), priority=BULK)
        fc = fe.submit((SR_SET, 0, 20), priority=CRITICAL)
        fn = fe.submit((SR_SET, 0, 30), priority=NORMAL)
        fe.start()
        fe.drain(5.0)
        # seqreg fetch-and-set exposes execution order: CRITICAL saw
        # the initial 0, NORMAL the CRITICAL's write, BULK the NORMAL's
        assert fc.result(5) == 0
        assert fn.result(5) == 20
        assert fb.result(5) == 30
        fe.close()

    def test_restart_fold_keeps_priority_breakdown(self):
        # a failover restart retires the queue; its per-priority shed
        # counts must fold into the aggregates like the totals do, or
        # stats()['shed'] and sum(shed_by_priority) drift apart
        nr = make_nr()
        fe = ServeFrontend(
            nr, ServeConfig(queue_depth=1, batch_linger_s=0.0,
                            failover=True),
            auto_start=False,
        )
        fe.submit((SR_SET, 0, 1), priority=NORMAL)
        with pytest.raises(Overloaded):
            fe.submit((SR_SET, 0, 2), priority=BULK)  # 1 bulk shed
        q = fe._queues[0]
        fe._fail_replica(0, q, RuntimeError("test kill"))
        fe.restart_replica(0)
        st = fe.stats()
        assert st["shed"] == 1
        assert sum(st["shed_by_priority"].values()) == st["shed"]
        assert st["shed_by_priority"]["bulk"] == 1
        fe.close(drain=False)

    def test_bad_priority_rejected(self):
        nr = make_nr()
        with ServeFrontend(nr, ServeConfig()) as fe:
            with pytest.raises(ValueError):
                fe.submit((SR_SET, 0, 1), priority=7)


# ==========================================================================
# Eager expired sweep at admission (satellite fix)
# ==========================================================================


class TestEagerExpiredSweep:
    def test_corpses_do_not_shed_live_traffic(self):
        nr = make_nr()
        fe = ServeFrontend(
            nr, ServeConfig(queue_depth=4, batch_linger_s=0.0),
            auto_start=False,
        )
        dead = [fe.submit((SR_SET, 0, i), deadline_s=0.01)
                for i in range(4)]
        time.sleep(0.03)  # all four expire in the queue
        # the queue is "full" of corpses — pre-fix this shed; now the
        # sweep clears them and the live op is admitted
        live = fe.submit((SR_SET, 0, 99), deadline_s=10.0)
        for f in dead:
            assert isinstance(f.exception(1.0), DeadlineExceeded)
        assert not live.done()
        st = fe.stats()
        assert st["deadline_missed"] == 4
        assert st["shed"] == 0
        assert st["queued"] == 1
        fe.start()
        assert live.result(5) == 0  # no corpse touched the log
        fe.close()

    def test_sweep_only_runs_at_the_limit(self):
        nr = make_nr()
        fe = ServeFrontend(
            nr, ServeConfig(queue_depth=8, batch_linger_s=0.0),
            auto_start=False,
        )
        doomed = fe.submit((SR_SET, 0, 1), deadline_s=0.01)
        time.sleep(0.03)
        fe.submit((SR_SET, 0, 2))  # room left: no sweep happens
        assert not doomed.done()
        assert fe.stats()["queued"] == 2
        fe.close(drain=False)


# ==========================================================================
# Brownout reads
# ==========================================================================


class TestBrownoutReads:
    def test_brownout_serves_stale_path_within_bound(self):
        nr = make_nr()
        cfg = ServeConfig(
            queue_depth=64, batch_linger_s=0.0,
            overload=OverloadConfig(target_delay_s=0.01,
                                    ewma_alpha=1.0,
                                    brownout_max_lag=4096),
        )
        with ServeFrontend(nr, cfg) as fe:
            fe.call((SR_SET, 3, 42))
            # force brownout via a hot round
            fe.governor.on_round(0, 0.1, 8)
            assert fe.governor.brownout()
            v = fe.read((SR_GET, 3), rid=0)
            assert v == 42  # replica is caught up: stale == fresh
            st = fe.governor.stats()
            assert st["brownout_reads"] == 1
            assert st["max_brownout_lag"] <= 4096

    def test_explicit_min_pos_bypasses_brownout(self):
        nr = make_nr()
        cfg = ServeConfig(
            queue_depth=64, batch_linger_s=0.0,
            overload=OverloadConfig(target_delay_s=0.01,
                                    ewma_alpha=1.0),
        )
        with ServeFrontend(nr, cfg) as fe:
            fe.call((SR_SET, 1, 7))
            fe.governor.on_round(0, 0.1, 8)
            assert fe.governor.brownout()
            assert fe.read((SR_GET, 1), rid=0, min_pos=0) == 7
            # the read-your-writes path never counts as a brownout read
            assert fe.governor.stats()["brownout_reads"] == 0

    def test_over_bound_falls_back_to_synced_read(self):
        nr = make_nr()
        cfg = ServeConfig(
            queue_depth=64, batch_linger_s=0.0,
            overload=OverloadConfig(target_delay_s=0.01,
                                    ewma_alpha=1.0,
                                    brownout_max_lag=0),
        )
        with ServeFrontend(nr, cfg) as fe:
            fe.call((SR_SET, 2, 5))
            fe.governor.on_round(0, 0.1, 8)
            # bound 0: any lag forces the synced path; with the
            # replica caught up lag == 0 <= 0, so the stale path is
            # still legal — both serve the correct value
            assert fe.read((SR_GET, 2), rid=0) == 5
            assert fe.governor.stats()["max_brownout_lag"] == 0

    def test_execute_stale_reads_current_state(self):
        nr = make_nr()
        tok = nr.register(0)
        nr.execute_mut_batch([(SR_SET, 0, 9)], 0)
        assert nr.execute_stale((SR_GET, 0), tok) == 9
        assert nr.read_lag(0) == 0
        # the atomic bounded form: (value, lag) within the bound
        assert nr.execute_stale_bounded((SR_GET, 0), tok, 10) == (9, 0)

    def test_linger_at_or_above_target_rejected(self):
        # a linger >= the AIMD setpoint would read an idle frontend
        # as congested (the delay signal includes the linger)
        with pytest.raises(ValueError):
            ServeConfig(batch_linger_s=0.02,
                        overload=OverloadConfig(target_delay_s=0.01))


# ==========================================================================
# Circuit breaker + per-cause retry accounting
# ==========================================================================


class TestCircuitBreaker:
    def test_opens_after_threshold_and_half_open_probe(self):
        b = CircuitBreaker(failure_threshold=3, cooldown_s=0.05)
        for _ in range(3):
            b.before_call()
            b.record_failure()
        assert b.state == "open"
        with pytest.raises(CircuitOpen) as ei:
            b.before_call()
        assert ei.value.retry_after_s > 0
        time.sleep(0.06)
        b.before_call()  # the half-open probe is admitted
        assert b.state == "half-open"
        with pytest.raises(CircuitOpen):
            b.before_call()  # only ONE probe at a time
        b.record_success()
        assert b.state == "closed"
        b.before_call()  # closed again: calls flow

    def test_half_open_failure_reopens(self):
        b = CircuitBreaker(failure_threshold=2, cooldown_s=0.05)
        for _ in range(2):
            b.record_failure()
        time.sleep(0.06)
        b.before_call()
        b.record_failure()  # the probe failed
        assert b.state == "open"
        with pytest.raises(CircuitOpen):
            b.before_call()

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(failure_threshold=3, cooldown_s=1.0)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(failure_threshold=0)
        with pytest.raises(ValueError):
            CircuitBreaker(cooldown_s=0)

    def test_lost_probe_lease_expires(self):
        # a probe whose caller never reports back (crash, untyped
        # error outside the breaker's accounting) must not wedge the
        # circuit half-open forever: the probe holds a lease one
        # cool-down long, then the next caller takes it over
        b = CircuitBreaker(failure_threshold=1, cooldown_s=0.05)
        b.record_failure()  # open
        time.sleep(0.06)
        b.before_call()  # probe admitted; caller vanishes silently
        with pytest.raises(CircuitOpen):
            b.before_call()  # lease still held
        time.sleep(0.06)  # lease expired
        b.before_call()  # taken over
        b.record_success()
        assert b.state == "closed"


class _FlakyFrontend:
    """Stub: raises the scripted errors, then succeeds."""

    def __init__(self, errors):
        self.errors = list(errors)
        self.calls = 0

    def call(self, op, rid=0, deadline_s=None, timeout=None,
             **kwargs):
        self.calls += 1
        if self.errors:
            raise self.errors.pop(0)
        return 42

    def healthy_rids(self):
        return [0, 1]


class TestRetryByCause:
    def _counters(self):
        reg = get_registry()
        return {c: reg.counter(f"serve.retry.{c}").value
                for c in ("overloaded", "replica_failed",
                          "circuit_open")}

    def test_counters_split_by_cause(self):
        reg = get_registry()
        was = reg.enabled
        reg.enable()
        try:
            before = self._counters()
            fe = _FlakyFrontend([Overloaded(0, 4),
                                 ReplicaFailed(0, None, False)])
            policy = RetryPolicy(max_attempts=5,
                                 base_backoff_s=0.0001,
                                 max_backoff_s=0.001)
            assert call_with_retry(fe, (SR_SET, 0, 1),
                                   policy=policy) == 42
            after = self._counters()
            assert after["overloaded"] - before["overloaded"] == 1
            assert (after["replica_failed"]
                    - before["replica_failed"]) == 1
            assert after["circuit_open"] == before["circuit_open"]
        finally:
            if not was:
                reg.disable()

    def test_breaker_wired_through_retry(self):
        reg = get_registry()
        was = reg.enabled
        reg.enable()
        try:
            before = self._counters()
            # enough sheds to trip the breaker, then success: the
            # retry loop must ride out the cool-down (CircuitOpen is
            # transient) and land the op
            fe = _FlakyFrontend([Overloaded(0, 4)] * 3)
            b = CircuitBreaker(failure_threshold=2, cooldown_s=0.02)
            policy = RetryPolicy(max_attempts=10,
                                 base_backoff_s=0.0001,
                                 max_backoff_s=0.001)
            assert call_with_retry(fe, (SR_SET, 0, 1), policy=policy,
                                   breaker=b) == 42
            after = self._counters()
            assert after["overloaded"] > before["overloaded"]
            assert after["circuit_open"] > before["circuit_open"]
            assert b.state == "closed"
        finally:
            if not was:
                reg.disable()

    def test_maybe_executed_still_propagates_with_breaker(self):
        fe = _FlakyFrontend([ReplicaFailed(0, None,
                                           maybe_executed=True)])
        with pytest.raises(ReplicaFailed):
            call_with_retry(fe, (SR_SET, 0, 1),
                            breaker=CircuitBreaker())

    def test_non_transient_outcome_reported_to_breaker(self):
        # a call ending in DeadlineExceeded (outside the retry loop's
        # transient set) must still report to the breaker — a probe
        # that exits silently would strand the circuit half-open
        fe = _FlakyFrontend([DeadlineExceeded(0, 0.01)])
        b = CircuitBreaker(failure_threshold=1, cooldown_s=10.0)
        with pytest.raises(DeadlineExceeded):
            call_with_retry(fe, (SR_SET, 0, 1), breaker=b)
        assert b.state == "open"
        assert b.stats()["consecutive_failures"] == 1


# ==========================================================================
# Backpressure wiring: WAL fsync lag, shipper lag
# ==========================================================================


class TestBackpressureWiring:
    def test_wal_fsync_lag_export(self, tmp_path):
        from node_replication_tpu.durable.wal import WriteAheadLog

        wal = WriteAheadLog(str(tmp_path / "wal"), policy="batch",
                            arg_width=2)
        assert wal.fsync_lag() == 0
        wal.append(0, [(1, 0, 5), (1, 1, 6)])
        assert wal.fsync_lag() == 2
        wal.sync()
        assert wal.fsync_lag() == 0
        wal.close()

    def test_frontend_auto_registers_wal_source(self, tmp_path):
        from node_replication_tpu.durable.wal import WriteAheadLog

        nr = make_nr()
        wal = WriteAheadLog(str(tmp_path / "wal"), policy="batch",
                            arg_width=nr.spec.arg_width)
        nr.attach_wal(wal)
        cfg = ServeConfig(batch_linger_s=0.0,
                          overload=OverloadConfig())
        with ServeFrontend(nr, cfg) as fe:
            assert "wal-fsync" in fe.governor.stats()["sources"]
        nr.detach_wal().close()

    def test_wal_attached_after_construction_still_wired(self,
                                                         tmp_path):
        # the PR-5 flow: build the frontend first, attach_wal later —
        # the fsync-lag leg must resolve the WAL at poll time, not
        # snapshot None at construction
        from node_replication_tpu.durable.wal import WriteAheadLog

        nr = make_nr()
        cfg = ServeConfig(
            batch_linger_s=0.0,
            overload=OverloadConfig(),
            wal_lag_low=1, wal_lag_high=4,
        )
        with ServeFrontend(nr, cfg) as fe:
            assert "wal-fsync" in fe.governor.stats()["sources"]
            assert fe.governor.backpressure() == 0.0  # no WAL yet
            wal = WriteAheadLog(str(tmp_path / "wal"), policy="none",
                                arg_width=nr.spec.arg_width)
            nr.attach_wal(wal)
            for i in range(6):
                fe.call((SR_SET, 0, i + 1))
            # 6 journaled, none fsynced: past the high watermark
            assert fe.governor.backpressure() >= 1.0
        nr.detach_wal().close()

    def test_add_backpressure_source_requires_governor(self):
        nr = make_nr()
        with ServeFrontend(nr, ServeConfig()) as fe:
            with pytest.raises(ValueError):
                fe.add_backpressure_source("x", lambda: 0, 1, 2)

    def test_high_lag_clamps_admission(self):
        nr = make_nr()
        cfg = ServeConfig(
            queue_depth=64, batch_linger_s=0.0,
            overload=OverloadConfig(target_delay_s=10.0,
                                    min_limit=4),
        )
        with ServeFrontend(nr, cfg) as fe:
            lag = [10_000]
            fe.add_backpressure_source("ship", lambda: lag[0],
                                       low=100, high=1000)
            # clean delay, but the source is past its high watermark:
            # every round shrinks admission toward the floor
            for _ in range(10):
                fe.governor.on_round(0, 0.0, 8)
            assert fe.governor.limit(0) == 4
            lag[0] = 0  # backlog drained: admission recovers
            for _ in range(20):
                fe.governor.on_round(0, 0.0, 8)
            assert fe.governor.limit(0) == 64


# ==========================================================================
# End to end: adaptive admission under a real burst
# ==========================================================================


class TestAdaptiveEndToEnd:
    def test_no_loss_no_inversion_under_burst(self):
        nr = make_nr(regs=8)
        cfg = ServeConfig(
            queue_depth=16, batch_max_ops=8, batch_linger_s=0.0,
            overload=OverloadConfig(target_delay_s=0.002,
                                    min_limit=2),
        )
        outcomes = {"ok": 0, "shed": 0, "evicted": 0}
        with ServeFrontend(nr, cfg) as fe:
            futs = []
            for i in range(200):
                prio = (CRITICAL, NORMAL, BULK)[i % 3]
                try:
                    futs.append(fe.submit((SR_SET, i % 8, i + 1),
                                          priority=prio))
                except Overloaded:
                    outcomes["shed"] += 1
            fe.drain(10.0)
            for f in futs:
                exc = f.exception(10.0)
                if exc is None:
                    outcomes["ok"] += 1
                elif isinstance(exc, Overloaded) and exc.evicted:
                    outcomes["evicted"] += 1
                else:  # pragma: no cover - would fail the assert below
                    raise AssertionError(f"unexpected {exc!r}")
            st = fe.stats()
        assert outcomes["ok"] + outcomes["evicted"] == len(futs)
        assert st["priority_inversions"] == 0
        assert st["accepted"] == len(futs)
        assert st["completed"] == outcomes["ok"]
        # log effect matches acks exactly: tail == completed ops
        import numpy as np

        assert int(np.asarray(nr.log.tail)) == outcomes["ok"]

    def test_concurrent_clients_with_breakers(self):
        nr = make_nr(regs=4)
        cfg = ServeConfig(
            queue_depth=8, batch_max_ops=4, batch_linger_s=0.0,
            overload=OverloadConfig(target_delay_s=0.001,
                                    min_limit=2),
        )
        errs: list = []

        def client(fe, c):
            b = CircuitBreaker(failure_threshold=4, cooldown_s=0.01)
            policy = RetryPolicy(max_attempts=12,
                                 base_backoff_s=0.0005,
                                 max_backoff_s=0.01)
            prev = 0
            for i in range(50):
                try:
                    resp = call_with_retry(
                        fe, (SR_SET, c, i + 1), policy=policy,
                        breaker=b, priority=(i % 3),
                    )
                except (Overloaded, CircuitOpen):
                    continue  # budget exhausted: op provably shed
                if resp != prev:
                    errs.append((c, i, resp, prev))
                prev = i + 1

        with ServeFrontend(nr, cfg) as fe:
            ths = [threading.Thread(target=client, args=(fe, c))
                   for c in range(4)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
        assert not errs, errs[:5]
